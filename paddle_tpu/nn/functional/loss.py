"""Loss functionals.

Parity target: ``python/paddle/nn/functional/loss.py`` in the reference.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...core.tensor import Tensor
from ...ops._helpers import ensure_tensor, forward_op


def _reduce(out, reduction):
    if reduction == "mean":
        return jnp.mean(out)
    if reduction == "sum":
        return jnp.sum(out)
    return out


def cross_entropy(input, label, weight=None, ignore_index=-100, reduction="mean",  # noqa: A002
                  soft_label=False, axis=-1, use_softmax=True, label_smoothing=0.0,
                  name=None):
    """Softmax cross entropy (ref: nn.functional.cross_entropy →
    softmax_with_cross_entropy phi kernel)."""
    input, label = ensure_tensor(input), ensure_tensor(label)
    args = [input, label] + ([ensure_tensor(weight)] if weight is not None else [])

    def impl(logits, lab, *w):
        ax = axis % logits.ndim
        logp = jax.nn.log_softmax(logits, axis=ax) if use_softmax else jnp.log(
            jnp.clip(logits, 1e-30, None))
        n_classes = logits.shape[ax]
        if soft_label or (lab.ndim == logits.ndim and lab.shape == logits.shape):
            soft = lab
            if label_smoothing > 0:
                soft = soft * (1 - label_smoothing) + label_smoothing / n_classes
            loss = -jnp.sum(soft * logp, axis=ax)
            valid = None
        else:
            lab_idx = lab
            if lab_idx.ndim == logits.ndim:  # trailing 1 dim
                lab_idx = jnp.squeeze(lab_idx, axis=ax)
            lab_idx = lab_idx.astype(jnp.int32)
            valid = lab_idx != ignore_index
            safe = jnp.where(valid, lab_idx, 0)
            picked = jnp.take_along_axis(
                logp, jnp.expand_dims(safe, ax), axis=ax).squeeze(ax)
            if label_smoothing > 0:
                smooth_loss = -jnp.mean(logp, axis=ax)
                loss = -(1 - label_smoothing) * picked + label_smoothing * smooth_loss
            else:
                loss = -picked
            if w:
                loss = loss * jnp.take(w[0], safe)
            loss = jnp.where(valid, loss, 0.0)
        if reduction == "mean":
            if valid is not None:
                denom = jnp.maximum(jnp.sum(valid.astype(loss.dtype)), 1.0)
                if w:
                    denom = jnp.maximum(jnp.sum(
                        jnp.where(valid, jnp.take(w[0], jnp.where(valid, lab_idx, 0)),
                                  0.0)), 1e-12)
                return jnp.sum(loss) / denom
            return jnp.mean(loss)
        if reduction == "sum":
            return jnp.sum(loss)
        return loss

    return forward_op("cross_entropy", impl, args)


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100,
                               numeric_stable_mode=True, return_softmax=False,
                               axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none", axis=axis)
    # paddle keeps the reduced axis
    from ...ops.manipulation import unsqueeze
    loss = unsqueeze(loss, axis)
    if return_softmax:
        from .activation import softmax
        return loss, softmax(logits, axis=axis)
    return loss


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean",  # noqa: A002
             name=None):
    input, label = ensure_tensor(input), ensure_tensor(label)
    args = [input, label] + ([ensure_tensor(weight)] if weight is not None else [])

    def impl(logp, lab, *w):
        lab = lab.astype(jnp.int32)
        valid = lab != ignore_index
        safe = jnp.where(valid, lab, 0)
        picked = jnp.take_along_axis(logp, safe[:, None], axis=1).squeeze(1)
        loss = -picked
        wt = jnp.take(w[0], safe) if w else jnp.ones_like(loss)
        loss = jnp.where(valid, loss * wt, 0.0)
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(jnp.sum(jnp.where(valid, wt, 0.0)), 1e-12)
        if reduction == "sum":
            return jnp.sum(loss)
        return loss

    return forward_op("nll_loss", impl, args)


def mse_loss(input, label, reduction="mean", name=None):  # noqa: A002
    return forward_op("mse_loss",
                      lambda a, b: _reduce(jnp.square(a - b), reduction),
                      [ensure_tensor(input), ensure_tensor(label)])


def l1_loss(input, label, reduction="mean", name=None):  # noqa: A002
    return forward_op("l1_loss",
                      lambda a, b: _reduce(jnp.abs(a - b), reduction),
                      [ensure_tensor(input), ensure_tensor(label)])


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):  # noqa: A002
    def impl(a, b):
        d = a - b
        ad = jnp.abs(d)
        loss = jnp.where(ad < delta, 0.5 * d * d / delta, ad - 0.5 * delta)
        # paddle multiplies by delta (huber normalization)
        return _reduce(loss * delta, reduction)

    return forward_op("smooth_l1_loss", impl,
                      [ensure_tensor(input), ensure_tensor(label)])


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):  # noqa: A002
    args = [ensure_tensor(input), ensure_tensor(label)] + \
        ([ensure_tensor(weight)] if weight is not None else [])

    def impl(p, y, *w):
        p = jnp.clip(p, 1e-12, 1.0 - 1e-12)
        loss = -(y * jnp.log(p) + (1 - y) * jnp.log1p(-p))
        if w:
            loss = loss * w[0]
        return _reduce(loss, reduction)

    return forward_op("binary_cross_entropy", impl, args)


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean",
                                     pos_weight=None, name=None):
    args = [ensure_tensor(logit), ensure_tensor(label)]
    if weight is not None:
        args.append(ensure_tensor(weight))
    if pos_weight is not None:
        args.append(ensure_tensor(pos_weight))

    def impl(z, y, *extra):
        i = 0
        w = None
        pw = None
        if weight is not None:
            w = extra[i]
            i += 1
        if pos_weight is not None:
            pw = extra[i]
        # stable: max(z,0) - z*y + log(1+exp(-|z|)), with optional pos_weight
        log_sig_pos = -jax.nn.softplus(-z)
        log_sig_neg = -z - jax.nn.softplus(-z)
        if pw is not None:
            loss = -(pw * y * log_sig_pos + (1 - y) * log_sig_neg)
        else:
            loss = -(y * log_sig_pos + (1 - y) * log_sig_neg)
        if w is not None:
            loss = loss * w
        return _reduce(loss, reduction)

    return forward_op("bce_with_logits", impl, args)


def kl_div(input, label, reduction="mean", log_target=False, name=None):  # noqa: A002
    def impl(logp, y):
        if log_target:
            loss = jnp.exp(y) * (y - logp)
        else:
            loss = y * (jnp.log(jnp.clip(y, 1e-12, None)) - logp)
        if reduction == "batchmean":
            return jnp.sum(loss) / logp.shape[0]
        return _reduce(loss, reduction)

    return forward_op("kl_div", impl, [ensure_tensor(input), ensure_tensor(label)])


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):  # noqa: A002
    return forward_op(
        "margin_ranking_loss",
        lambda a, b, y: _reduce(jnp.maximum(0.0, -y * (a - b) + margin), reduction),
        [ensure_tensor(input), ensure_tensor(other), ensure_tensor(label)])


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):  # noqa: A002
    return forward_op(
        "hinge_embedding_loss",
        lambda x, y: _reduce(jnp.where(y == 1, x, jnp.maximum(0.0, margin - x)),
                             reduction),
        [ensure_tensor(input), ensure_tensor(label)])


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean",
                          name=None):
    def impl(a, b, y):
        cos = jnp.sum(a * b, -1) / (jnp.linalg.norm(a, axis=-1) *
                                    jnp.linalg.norm(b, axis=-1) + 1e-12)
        loss = jnp.where(y == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce(loss, reduction)

    return forward_op("cosine_embedding_loss", impl,
                      [ensure_tensor(input1), ensure_tensor(input2),
                       ensure_tensor(label)])


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0, epsilon=1e-6,  # noqa: A002
                        swap=False, reduction="mean", name=None):
    def impl(a, pos, neg):
        dp = jnp.sum(jnp.abs(a - pos) ** p, -1) ** (1 / p)
        dn = jnp.sum(jnp.abs(a - neg) ** p, -1) ** (1 / p)
        if swap:
            dn2 = jnp.sum(jnp.abs(pos - neg) ** p, -1) ** (1 / p)
            dn = jnp.minimum(dn, dn2)
        return _reduce(jnp.maximum(0.0, dp - dn + margin), reduction)

    return forward_op("triplet_margin_loss", impl,
                      [ensure_tensor(input), ensure_tensor(positive),
                       ensure_tensor(negative)])


def log_loss(input, label, epsilon=1e-4, name=None):  # noqa: A002
    return forward_op(
        "log_loss",
        lambda p, y: -y * jnp.log(p + epsilon) - (1 - y) * jnp.log(1 - p + epsilon),
        [ensure_tensor(input), ensure_tensor(label)])


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    args = [ensure_tensor(logit), ensure_tensor(label)] + \
        ([ensure_tensor(normalizer)] if normalizer is not None else [])

    def impl(z, y, *n):
        p = jax.nn.sigmoid(z)
        ce = jnp.maximum(z, 0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        loss = a_t * ((1 - p_t) ** gamma) * ce
        if n:
            loss = loss / n[0]
        return _reduce(loss, reduction)

    return forward_op("sigmoid_focal_loss", impl, args)


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC loss via the standard alpha-recursion in log space (lax.scan over time).

    Ref capability: paddle.nn.functional.ctc_loss (warpctc in the reference).
    Expects log_probs [T, B, C] (paddle layout) already log-softmaxed or logits.
    """
    log_probs = ensure_tensor(log_probs)
    labels = ensure_tensor(labels)
    input_lengths = ensure_tensor(input_lengths)
    label_lengths = ensure_tensor(label_lengths)

    def impl(lp, lab, in_len, lab_len):
        lp = jax.nn.log_softmax(lp, axis=-1)
        T, B, C = lp.shape
        S = lab.shape[1]
        ext_len = 2 * S + 1
        # extended label sequence: blank, l1, blank, l2, ... blank
        ext = jnp.full((B, ext_len), blank, dtype=lab.dtype)
        ext = ext.at[:, 1::2].set(lab)
        neg_inf = jnp.asarray(-1e30, lp.dtype)

        def get_probs(t_lp):  # [B, ext_len]
            return jnp.take_along_axis(t_lp, ext, axis=1)

        # init alpha at t=0
        alpha0 = jnp.full((B, ext_len), neg_inf)
        p0 = get_probs(lp[0])
        alpha0 = alpha0.at[:, 0].set(p0[:, 0])
        alpha0 = alpha0.at[:, 1].set(jnp.where(lab_len > 0, p0[:, 1], neg_inf))

        same_as_prev2 = jnp.concatenate(
            [jnp.ones((B, 2), bool), ext[:, 2:] == ext[:, :-2]], axis=1)

        def step(alpha, t_lp):
            p = get_probs(t_lp)
            a_prev = alpha
            a_shift1 = jnp.concatenate([jnp.full((B, 1), neg_inf), alpha[:, :-1]], 1)
            a_shift2 = jnp.concatenate([jnp.full((B, 2), neg_inf), alpha[:, :-2]], 1)
            a_shift2 = jnp.where(same_as_prev2, neg_inf, a_shift2)
            new = jnp.logaddexp(jnp.logaddexp(a_prev, a_shift1), a_shift2) + p
            return new, new

        _, alphas = jax.lax.scan(step, alpha0, lp[1:])
        alphas = jnp.concatenate([alpha0[None], alphas], 0)  # [T, B, ext_len]

        # pick alpha at t = in_len-1, positions 2*lab_len-1 and 2*lab_len
        t_idx = jnp.clip(in_len - 1, 0, T - 1).astype(jnp.int32)
        batch = jnp.arange(B)
        final = alphas[t_idx, batch]  # [B, ext_len]
        e1 = jnp.take_along_axis(final, jnp.clip(2 * lab_len - 1, 0, ext_len - 1)
                                 [:, None].astype(jnp.int32), 1)[:, 0]
        e2 = jnp.take_along_axis(final, jnp.clip(2 * lab_len, 0, ext_len - 1)
                                 [:, None].astype(jnp.int32), 1)[:, 0]
        ll = jnp.logaddexp(e1, e2)
        loss = -ll
        if norm_by_times:
            loss = loss / in_len.astype(loss.dtype)
        if reduction == "mean":
            return jnp.mean(loss / lab_len.astype(loss.dtype))
        if reduction == "sum":
            return jnp.sum(loss)
        return loss

    return forward_op("ctc_loss", impl,
                      [log_probs, labels, input_lengths, label_lengths])


def square_error_cost(input, label):  # noqa: A002
    return forward_op("square_error_cost", lambda a, b: jnp.square(a - b),
                      [ensure_tensor(input), ensure_tensor(label)])


def dice_loss(input, label, epsilon=1e-5, name=None):  # noqa: A002
    def impl(p, y):
        y1 = jax.nn.one_hot(y.squeeze(-1), p.shape[-1], dtype=p.dtype)
        reduce_dims = tuple(range(1, p.ndim))
        inter = jnp.sum(p * y1, axis=reduce_dims)
        union = jnp.sum(p, axis=reduce_dims) + jnp.sum(y1, axis=reduce_dims)
        return jnp.mean(1 - (2 * inter + epsilon) / (union + epsilon))

    return forward_op("dice_loss", impl, [ensure_tensor(input), ensure_tensor(label)])


def huber_loss(input, label, delta=1.0, reduction="mean", name=None):  # noqa: A002
    """ref: paddle.nn.functional.huber_loss (quadratic within delta)."""
    x, y = ensure_tensor(input), ensure_tensor(label)

    def f(a, b):
        d = a - b
        ad = jnp.abs(d)
        out = jnp.where(ad <= delta, 0.5 * d * d, delta * (ad - 0.5 * delta))
        return _reduce(out, reduction)
    return forward_op("huber_loss", f, [x, y])


def soft_margin_loss(input, label, reduction="mean", name=None):  # noqa: A002
    """ref: soft_margin_loss — log(1 + exp(-y * x)) with y in {-1, 1}."""
    x, y = ensure_tensor(input), ensure_tensor(label)

    def f(a, b):
        # softplus form: log1p(exp(z)) overflows for moderate margins
        return _reduce(jax.nn.softplus(-b * a), reduction)
    return forward_op("soft_margin_loss", f, [x, y])


def multi_label_soft_margin_loss(input, label, weight=None,  # noqa: A002
                                 reduction="mean", name=None):
    """ref: multi-label one-vs-all BCE-with-logits averaged over classes."""
    x, y = ensure_tensor(input), ensure_tensor(label)
    w = None if weight is None else ensure_tensor(weight)

    def f(a, b, wv=None):
        per = -(b * jax.nn.log_sigmoid(a) + (1 - b) * jax.nn.log_sigmoid(-a))
        if wv is not None:
            per = per * wv
        return _reduce(per.mean(axis=-1), reduction)
    args = [x, y] if w is None else [x, y, w]
    return forward_op("multi_label_soft_margin_loss", f, args)


def poisson_nll_loss(input, label, log_input=True, full=False,  # noqa: A002
                     epsilon=1e-8, reduction="mean", name=None):
    """ref: poisson_nll_loss (Stirling term when full=True)."""
    x, y = ensure_tensor(input), ensure_tensor(label)

    def f(a, b):
        if log_input:
            out = jnp.exp(a) - b * a
        else:
            out = a - b * jnp.log(a + epsilon)
        if full:
            stirling = b * jnp.log(b + epsilon) - b + \
                0.5 * jnp.log(2 * jnp.pi * (b + epsilon))
            out = out + jnp.where(b > 1, stirling, 0.0)
        return _reduce(out, reduction)
    return forward_op("poisson_nll_loss", f, [x, y])


def gaussian_nll_loss(input, label, variance, full=False,  # noqa: A002
                      epsilon=1e-6, reduction="mean", name=None):
    """ref: gaussian_nll_loss — 0.5*(log var + (x-y)^2/var) [+ const]."""
    x, y, v = ensure_tensor(input), ensure_tensor(label), \
        ensure_tensor(variance)

    def f(a, b, var):
        var = jnp.maximum(var, epsilon)
        out = 0.5 * (jnp.log(var) + jnp.square(a - b) / var)
        if full:
            out = out + 0.5 * jnp.log(2 * jnp.pi)
        return _reduce(out, reduction)
    return forward_op("gaussian_nll_loss", f, [x, y, v])


# ---------------------------------------------------------------------------
# r5: the remaining loss surface (SURVEY §2.3 long tail). Upstream sources:
# npair_loss/margin_cross_entropy in python/paddle/nn/functional/loss.py & 
# margin_cross_entropy_op; rank/bpr/center/teacher-student/modified-huber in
# paddle/fluid/operators/*_loss_op*; rnnt_loss (warprnnt_op) redesigned as a
# lax.scan log-semiring DP (same move as ctc_loss above).
# ---------------------------------------------------------------------------

def npair_loss(anchor, positive, labels, l2_reg: float = 0.002, name=None):
    """ref: npair_loss — softmax CE over the anchor·positiveᵀ similarity
    matrix with same-label targets, plus an L2 term on the embeddings."""
    a, p, l = ensure_tensor(anchor), ensure_tensor(positive), \
        ensure_tensor(labels)

    def f(av, pv, lv):
        sim = av @ pv.T                                     # [B, B]
        same = (lv[:, None] == lv[None, :]).astype(av.dtype)
        tgt = same / jnp.maximum(same.sum(-1, keepdims=True), 1)
        logp = jax.nn.log_softmax(sim, -1)
        ce = -(tgt * logp).sum(-1).mean()
        reg = l2_reg * (jnp.sum(av * av) + jnp.sum(pv * pv)) \
            / (2 * av.shape[0])
        return ce + reg

    return forward_op("npair_loss", f, [a, p, l])


def margin_cross_entropy(logits, label, margin1: float = 1.0,
                         margin2: float = 0.5, margin3: float = 0.0,
                         scale: float = 64.0, return_softmax: bool = False,
                         reduction="mean", name=None):
    """ref: margin_cross_entropy_op (ArcFace/CosFace family): the target
    class cosine becomes ``cos(m1*θ + m2) - m3``, everything scaled by
    ``scale`` before CE. (The hybrid-parallel sharded variant is
    ParallelCrossEntropy's margin mode territory; this is the single-chip
    op.)"""
    x, y = ensure_tensor(logits), ensure_tensor(label)

    def f(lv, yv):
        cos_t = jnp.take_along_axis(lv, yv[:, None], -1)[:, 0]
        cos_t = jnp.clip(cos_t, -1 + 1e-7, 1 - 1e-7)
        theta = jnp.arccos(cos_t)
        target = jnp.cos(margin1 * theta + margin2) - margin3
        adj = lv.at[jnp.arange(lv.shape[0]), yv].set(target)
        adj = adj * scale
        logp = jax.nn.log_softmax(adj, -1)
        ce = -jnp.take_along_axis(logp, yv[:, None], -1)[:, 0]
        out = _reduce(ce, reduction)
        if return_softmax:
            return out, jnp.exp(logp)
        return out

    return forward_op("margin_cross_entropy", f, [x, y])


def rank_loss(label, left, right, name=None):
    """ref: rank_loss_op (RankNet): -label*(l-r) + log(1 + e^(l-r))."""
    lt, a, b = ensure_tensor(label), ensure_tensor(left), \
        ensure_tensor(right)

    def f(lv, av, bv):
        o = av - bv
        return jnp.maximum(o, 0) - o * lv + jnp.log1p(jnp.exp(-jnp.abs(o)))

    return forward_op("rank_loss", f, [lt, a, b])


def multi_margin_loss(input, label, p: int = 1, margin: float = 1.0,  # noqa: A002
                      weight=None, reduction="mean", name=None):
    """ref: multi_margin_loss — mean_j max(0, margin - x_y + x_j)^p."""
    x, y = ensure_tensor(input), ensure_tensor(label)
    args = [x, y] + ([ensure_tensor(weight)] if weight is not None else [])

    def f(xv, yv, *w):
        C = xv.shape[1]
        xy = jnp.take_along_axis(xv, yv[:, None], -1)
        m = jnp.clip(margin - xy + xv, 0) ** p
        if w:
            m = m * w[0][yv][:, None]
        m = m * (jnp.arange(C)[None] != yv[:, None])
        return _reduce(m.sum(-1) / C, reduction)

    return forward_op("multi_margin_loss", f, args)


def triplet_margin_with_distance_loss(input, positive, negative,  # noqa: A002
                                      distance_function=None,
                                      margin: float = 1.0, swap: bool = False,
                                      reduction="mean", name=None):
    """ref: triplet_margin_with_distance_loss — triplet loss under a
    user distance (defaults to L2)."""
    a, p, n = ensure_tensor(input), ensure_tensor(positive), \
        ensure_tensor(negative)

    if distance_function is not None:
        dp = distance_function(a, p)
        dn = distance_function(a, n)
        if swap:
            dpn = distance_function(p, n)
            dn = forward_op("tmwd_min", jnp.minimum,
                            [ensure_tensor(dn), ensure_tensor(dpn)])
        return forward_op(
            "triplet_margin_with_distance_loss",
            lambda d1, d2: _reduce(jnp.clip(margin + d1 - d2, 0), reduction),
            [ensure_tensor(dp), ensure_tensor(dn)])

    def f(av, pv, nv):
        dp = jnp.sqrt(jnp.sum((av - pv) ** 2, -1) + 1e-12)
        dn = jnp.sqrt(jnp.sum((av - nv) ** 2, -1) + 1e-12)
        if swap:
            dn = jnp.minimum(dn, jnp.sqrt(jnp.sum((pv - nv) ** 2, -1)
                                          + 1e-12))
        return _reduce(jnp.clip(margin + dp - dn, 0), reduction)

    return forward_op("triplet_margin_with_distance_loss", f, [a, p, n])


def rnnt_loss(logits, labels, logit_lengths, label_lengths, blank: int = 0,
              fastemit_lambda: float = 0.0, reduction="mean", name=None):
    """RNN-T loss (ref: warprnnt_op). ``logits [B, T, U+1, K]`` (log-probs
    taken internally), ``labels [B, U]``. TPU formulation: the alpha
    lattice rolls forward over t via lax.scan with the whole [B, U+1] front
    updated per step; the in-row emit recursion is a second (static-U)
    scan — one compiled program, batch-vectorized, no per-sequence loops
    (upstream walks the lattice per sequence on CPU/CUDA)."""
    from jax import lax
    lg = ensure_tensor(logits)
    lb = ensure_tensor(labels)
    lt = ensure_tensor(logit_lengths)
    ut = ensure_tensor(label_lengths)

    def f(lgv, lbv, ltv, utv):
        B, T, U1, K = lgv.shape
        U = U1 - 1
        logp = jax.nn.log_softmax(lgv, -1)
        # blank[b, t, u] / emit[b, t, u] transition log-probs
        blank_lp = logp[..., blank]                          # [B, T, U+1]
        emit_lp = jnp.take_along_axis(
            logp[:, :, :U, :], lbv[:, None, :, None], -1)[..., 0]  # [B,T,U]
        if fastemit_lambda:
            emit_lp = emit_lp + jnp.log1p(fastemit_lambda)
        NEG = -1e30

        def row_fill(prev_alpha, t):
            # alpha over u at fixed t: first from below (blank from t-1),
            # then emit transitions left-to-right within the row
            from_blank = prev_alpha + blank_lp[:, t - 1]     # [B, U+1]

            def emit_step(carry, u):
                # carry = alpha[t, u]; next = logsumexp(from_blank[u+1],
                #                                       carry + emit[t, u])
                nxt = jnp.logaddexp(from_blank[:, u + 1],
                                    carry + emit_lp[:, t, u])
                return nxt, nxt

            first = from_blank[:, 0]
            _, rest = lax.scan(emit_step, first, jnp.arange(U))
            alpha_t = jnp.concatenate([first[:, None], rest.T], 1)
            # rows beyond a sequence's T keep the previous alpha
            keep = (t < ltv)[:, None]
            return jnp.where(keep, alpha_t, prev_alpha), None

        # t = 0 row: only emits along u
        def emit0(carry, u):
            nxt = carry + emit_lp[:, 0, u]
            return nxt, nxt

        a00 = jnp.zeros((B,))
        _, r0 = lax.scan(emit0, a00, jnp.arange(U))
        alpha0 = jnp.concatenate([a00[:, None], r0.T], 1)
        alphaT, _ = lax.scan(row_fill, alpha0, jnp.arange(1, T))
        # final: alpha[T-1, U] + blank at (T-1, U)
        last_t = jnp.clip(ltv - 1, 0)
        # alphaT is alpha at the LAST valid row per sequence already
        # (rows past ltv frozen); read u = label_length
        a_final = jnp.take_along_axis(alphaT, utv[:, None], 1)[:, 0]
        final_blank = blank_lp[jnp.arange(B), last_t, utv]
        ll = a_final + final_blank
        return _reduce(-ll, reduction)

    return forward_op("rnnt_loss", f, [lg, lb, lt, ut])


def adaptive_log_softmax_with_loss(input, label, head_weight, tail_weights,  # noqa: A002
                                   cutoffs, head_bias=None, name=None):
    """ref: adaptive_log_softmax_with_loss — frequency-adaptive softmax:
    head classes + shortlist cluster logits, tail clusters project down
    then out. Returns (output [B] log-probs of the target, loss scalar)."""
    x, y = ensure_tensor(input), ensure_tensor(label)
    hw = ensure_tensor(head_weight)
    tws = [ensure_tensor(w) for pair in tail_weights for w in pair]
    args = [x, y, hw] + tws + \
        ([ensure_tensor(head_bias)] if head_bias is not None else [])
    n_tail = len(tail_weights)
    shortlist = cutoffs[0]

    def f(xv, yv, hwv, *rest):
        tails = [(rest[2 * i], rest[2 * i + 1]) for i in range(n_tail)]
        hb = rest[-1] if head_bias is not None else None
        head_logits = xv @ hwv                               # [B, s + n_tail]
        if hb is not None:
            head_logits = head_logits + hb
        head_logp = jax.nn.log_softmax(head_logits, -1)
        # shortlist targets read directly
        out = jnp.take_along_axis(
            head_logp, jnp.clip(yv, 0, shortlist - 1)[:, None], -1)[:, 0]
        lo = shortlist
        for i, (w1, w2) in enumerate(tails):
            hi = cutoffs[i + 1]
            cluster_lp = head_logp[:, shortlist + i]
            tail_logp = jax.nn.log_softmax((xv @ w1) @ w2, -1)
            rel = jnp.clip(yv - lo, 0, hi - lo - 1)
            cand = cluster_lp + jnp.take_along_axis(
                tail_logp, rel[:, None], -1)[:, 0]
            out = jnp.where((yv >= lo) & (yv < hi), cand, out)
            lo = hi
        return out, -out.mean()

    return forward_op("adaptive_log_softmax_with_loss", f, args)


def class_center_sample(label, num_classes: int, num_samples: int,
                        group=None, name=None):
    """ref: class_center_sample_op (PartialFC): sample ``num_samples``
    class centers always including every positive class; remap labels into
    the sampled index space. Eager (the sample IS data-dependent — it
    feeds a subsequent gather whose shape is static num_samples)."""
    lt = ensure_tensor(label)
    lv = np.asarray(lt._value).reshape(-1)
    pos = np.unique(lv)
    rest = np.setdiff1d(np.arange(num_classes), pos)
    rng = np.random.default_rng(np.random.randint(0, 2 ** 31))
    n_extra = max(0, num_samples - pos.size)
    extra = rng.choice(rest, size=min(n_extra, rest.size), replace=False) \
        if rest.size else np.empty((0,), np.int64)
    sampled = np.concatenate([pos, extra]).astype(np.int64)
    remap = -np.ones(num_classes, np.int64)
    remap[sampled] = np.arange(sampled.size)
    from ...core.tensor import to_tensor
    return to_tensor(remap[lv]), to_tensor(sampled)


def center_loss(input, label, centers, alpha: float = 0.5,  # noqa: A002
                update_center: bool = True, name=None):
    """ref: center_loss_op — squared distance to the class center; returns
    ``(loss [B], new_centers)`` (the in-place CUDA center update made
    pure)."""
    x, y, c = ensure_tensor(input), ensure_tensor(label), \
        ensure_tensor(centers)

    def f(xv, yv, cv):
        diff = xv - cv[yv]
        loss = 0.5 * jnp.sum(diff * diff, -1)
        if not update_center:
            return loss, cv
        cnt = jnp.zeros((cv.shape[0],)).at[yv].add(1.0)
        upd = jnp.zeros_like(cv).at[yv].add(diff)
        new_c = cv + alpha * upd / (cnt[:, None] + 1.0)
        return loss, new_c

    return forward_op("center_loss", f, [x, y, c])


def teacher_student_sigmoid_loss(input, label,  # noqa: A002
                                 soft_max_up_bound: float = 15.0,
                                 soft_max_lower_bound: float = -15.0,
                                 name=None):
    """ref: teacher_student_sigmoid_loss_op (CTR distillation): hard CE
    when label <= 0/1 bounds, soft sigmoid regression otherwise."""
    x, y = ensure_tensor(input), ensure_tensor(label)

    def f(xv, yv):
        z = jnp.clip(xv, soft_max_lower_bound, soft_max_up_bound)
        log1pe = jnp.maximum(z, 0) + jnp.log1p(jnp.exp(-jnp.abs(z)))
        hard = jnp.where(yv > 0.5, log1pe - z, log1pe)
        soft = log1pe - z * yv
        return jnp.where((yv <= 0.0) | (yv >= 1.0), hard, soft)

    return forward_op("teacher_student_sigmoid_loss", f, [x, y])


def bpr_loss(input, label, name=None):  # noqa: A002
    """ref: bpr_loss_op (Bayesian Personalized Ranking): -mean over
    negatives of log sigmoid(x_pos - x_neg)."""
    x, y = ensure_tensor(input), ensure_tensor(label)

    def f(xv, yv):
        B, C = xv.shape
        pos = jnp.take_along_axis(xv, yv[:, None], -1)       # [B, 1]
        o = pos - xv
        lse = jnp.log1p(jnp.exp(-jnp.abs(o))) + jnp.maximum(-o, 0)
        mask = jnp.arange(C)[None] != yv[:, None]
        return (lse * mask).sum(-1) / jnp.maximum(C - 1, 1)

    return forward_op("bpr_loss", f, [x, y])


def cos_sim(X, Y, name=None):
    """ref: cos_sim_op — rowwise cosine similarity [B] (Y may broadcast
    from one row)."""
    a, b = ensure_tensor(X), ensure_tensor(Y)

    def f(av, bv):
        bv = jnp.broadcast_to(bv, av.shape)
        num = (av * bv).sum(-1)
        return num / jnp.maximum(
            jnp.linalg.norm(av, axis=-1) * jnp.linalg.norm(bv, axis=-1),
            1e-12)

    return forward_op("cos_sim", f, [a, b])


def squared_l2_norm(x, name=None):
    """ref: squared_l2_norm_op — sum of squares (the grad-clip kernel)."""
    return forward_op("squared_l2_norm", lambda v: jnp.sum(v * v),
                      [ensure_tensor(x)])


def squared_l2_distance(x, y, name=None):
    """ref: squared_l2_distance_op — rowwise sum of squared differences."""
    return forward_op(
        "squared_l2_distance",
        lambda a, b: jnp.sum((a - b) ** 2, axis=-1),
        [ensure_tensor(x), ensure_tensor(y)])


def modified_huber_loss(input, label, name=None):  # noqa: A002
    """ref: modified_huber_loss_op — quadratically-smoothed hinge for
    {0,1} labels: max(0, 1-yx)^2 if yx >= -1 else -4yx (y in {-1, 1})."""
    x, yt = ensure_tensor(input), ensure_tensor(label)

    def f(xv, yv):
        s = 2.0 * yv - 1.0
        z = s * xv
        return jnp.where(z >= -1.0, jnp.clip(1.0 - z, 0) ** 2, -4.0 * z)

    return forward_op("modified_huber_loss", f, [x, yt])


def identity_loss(x, reduction="none", name=None):
    """ref: identity_loss_op — marks a value as the loss with an optional
    reduction (sum/mean/none)."""
    t = ensure_tensor(x)
    red = {0: "sum", 1: "mean", 2: "none"}.get(reduction, reduction)
    return forward_op("identity_loss", lambda v: _reduce(v, red), [t])


def hsigmoid_loss(input, label, num_classes: int, weight, bias=None,  # noqa: A002
                  path_table=None, path_code=None, is_sparse: bool = False,
                  name=None):
    """ref: hsigmoid_loss (hierarchical_sigmoid_op): binary classifications
    down a complete binary Huffman tree over classes. Default tree: the
    reference's complete-binary coding (node ids from the class id's path);
    ``weight [num_classes - 1, D]``. Custom trees via
    ``path_table/path_code [B, L]``."""
    x, y = ensure_tensor(input), ensure_tensor(label)
    w = ensure_tensor(weight)
    args = [x, y, w]
    if bias is not None:
        args.append(ensure_tensor(bias))
    if path_table is not None:
        args.insert(3, ensure_tensor(path_table))
        args.insert(4, ensure_tensor(path_code))

    import math as _math
    L = max(1, int(_math.ceil(_math.log2(max(num_classes, 2)))))

    def f(xv, yv, wv, *rest):
        if path_table is not None:
            pt, pc = rest[0], rest[1]
            bv = rest[2] if bias is not None else None
            valid = pt >= 0
            nodes = jnp.clip(pt, 0, wv.shape[0] - 1)
            codes = pc.astype(xv.dtype)
        else:
            bv = rest[0] if bias is not None else None
            # complete binary tree: internal node ids along the path of
            # class c (root = 0); depth L
            c = yv + num_classes                     # leaf position
            levels = []
            code_l = []
            node = c
            for _ in range(L):
                code_l.append((node % 2).astype(xv.dtype))
                node = node // 2
                levels.append(node - 1)              # internal id (root=0)
            nodes = jnp.stack(levels[::-1], 1)       # [B, L] root->leaf
            codes = jnp.stack(code_l[::-1], 1)
            valid = nodes >= 0
            nodes = jnp.clip(nodes, 0, wv.shape[0] - 1)
        logits = jnp.einsum("bd,bld->bl", xv, wv[nodes])
        if bv is not None:
            logits = logits + bv[nodes]
        # BCE with code as target
        lse = jnp.maximum(logits, 0) - logits * codes + \
            jnp.log1p(jnp.exp(-jnp.abs(logits)))
        return (lse * valid).sum(-1)

    return forward_op("hsigmoid_loss", f, args)
