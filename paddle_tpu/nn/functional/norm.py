"""Normalization functionals.

Parity target: ``python/paddle/nn/functional/norm.py`` (batch_norm backed by phi
batch_norm kernels with running-stat mutation). Running stats are updated in-place on
the passed mean/variance tensors, mirroring Paddle's semantics; inside ``jit`` those
become functionalized state (captured as inputs/outputs of the compiled step).
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...core.tensor import Tensor
from ...ops._helpers import ensure_tensor, forward_op


def batch_norm(x, running_mean, running_var, weight=None, bias=None, training=False,
               momentum=0.9, epsilon=1e-5, data_format="NCHW", use_global_stats=None,
               name=None):
    x = ensure_tensor(x)
    ch_axis = x.ndim - 1 if data_format[-1] == "C" and len(data_format) > 2 else 1
    axes = tuple(i for i in range(x.ndim) if i != ch_axis)
    use_batch_stats = training and not use_global_stats

    stat_shape = [1] * x.ndim
    stat_shape[ch_axis] = x.shape[ch_axis]

    if use_batch_stats:
        # compute batch stats eagerly (they're needed to mutate running stats)
        args = [x] + [a for a in (weight, bias) if a is not None]

        def impl(v, *wb):
            mean = jnp.mean(v, axis=axes, keepdims=True)
            var = jnp.var(v, axis=axes, keepdims=True)
            out = (v - mean) * jax.lax.rsqrt(var + epsilon)
            i = 0
            if weight is not None:
                out = out * wb[i].reshape(stat_shape)
                i += 1
            if bias is not None:
                out = out + wb[i].reshape(stat_shape)
            return out, mean.reshape(-1), var.reshape(-1)

        out, bmean, bvar = forward_op("batch_norm", impl, args)
        if running_mean is not None:
            running_mean.set_value(momentum * running_mean._value +
                                   (1 - momentum) * bmean._value)
        if running_var is not None:
            n = int(np.prod([x.shape[a] for a in axes]))
            unbiased = bvar._value * (n / max(n - 1, 1))
            running_var.set_value(momentum * running_var._value +
                                  (1 - momentum) * unbiased)
        return out

    args = [x, ensure_tensor(running_mean), ensure_tensor(running_var)] + \
        [a for a in (weight, bias) if a is not None]

    def impl_infer(v, m, var, *wb):
        out = (v - m.reshape(stat_shape)) * jax.lax.rsqrt(var.reshape(stat_shape) + epsilon)
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(stat_shape)
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(stat_shape)
        return out

    return forward_op("batch_norm_infer", impl_infer, args)


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5, name=None):
    x = ensure_tensor(x)
    if isinstance(normalized_shape, int):
        normalized_shape = (normalized_shape,)
    n_axes = len(tuple(normalized_shape))
    axes = tuple(range(x.ndim - n_axes, x.ndim))
    args = [x] + [ensure_tensor(a) for a in (weight, bias) if a is not None]

    def impl(v, *wb):
        mean = jnp.mean(v, axis=axes, keepdims=True)
        var = jnp.var(v, axis=axes, keepdims=True)
        out = (v - mean) * jax.lax.rsqrt(var + epsilon)
        i = 0
        if weight is not None:
            out = out * wb[i]
            i += 1
        if bias is not None:
            out = out + wb[i]
        return out

    return forward_op("layer_norm", impl, args)


def rms_norm(x, weight=None, epsilon=1e-6, name=None):
    """RMSNorm (ref: paddle.incubate.nn.functional.fused_rms_norm). Dispatches to
    the fused Pallas kernel (kernels/rms_norm.py) when a weight is given and the
    feature dim is lane-aligned; jnp fallback otherwise."""
    x = ensure_tensor(x)
    args = [x] + ([ensure_tensor(weight)] if weight is not None else [])
    d = x.shape[-1]
    use_kernel = weight is not None and d % 128 == 0 and \
        int(np.prod(x.shape[:-1])) % 8 == 0

    def impl(v, *w):
        if use_kernel:
            from ...kernels.rms_norm import rms_norm as rms_kernel
            return rms_kernel(v, w[0], epsilon)
        ms = jnp.mean(jnp.square(v.astype(jnp.float32)), axis=-1, keepdims=True)
        out = (v.astype(jnp.float32) * jax.lax.rsqrt(ms + epsilon)).astype(v.dtype)
        if w:
            out = out * w[0]
        return out

    return forward_op("rms_norm", impl, args)


def instance_norm(x, running_mean=None, running_var=None, weight=None, bias=None,
                  use_input_stats=True, momentum=0.9, eps=1e-5, data_format="NCHW",
                  name=None):
    x = ensure_tensor(x)
    ch_axis = 1 if data_format.startswith("NC") else x.ndim - 1
    axes = tuple(i for i in range(2, x.ndim)) if ch_axis == 1 else \
        tuple(i for i in range(1, x.ndim - 1))
    stat_shape = [1] * x.ndim
    stat_shape[ch_axis] = x.shape[ch_axis]
    args = [x] + [ensure_tensor(a) for a in (weight, bias) if a is not None]

    def impl(v, *wb):
        mean = jnp.mean(v, axis=axes, keepdims=True)
        var = jnp.var(v, axis=axes, keepdims=True)
        out = (v - mean) * jax.lax.rsqrt(var + eps)
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(stat_shape)
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(stat_shape)
        return out

    return forward_op("instance_norm", impl, args)


def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None,
               data_format="NCHW", name=None):
    x = ensure_tensor(x)
    channels_last = data_format[-1] == "C" and len(data_format) > 2
    args = [x] + [ensure_tensor(a) for a in (weight, bias) if a is not None]

    def impl(v, *wb):
        if channels_last:
            v = jnp.moveaxis(v, -1, 1)
        n, c = v.shape[:2]
        g = v.reshape(n, num_groups, c // num_groups, *v.shape[2:])
        axes = tuple(range(2, g.ndim))
        mean = jnp.mean(g, axis=axes, keepdims=True)
        var = jnp.var(g, axis=axes, keepdims=True)
        out = ((g - mean) * jax.lax.rsqrt(var + epsilon)).reshape(v.shape)
        stat_shape = [1] * out.ndim
        stat_shape[1] = c
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(stat_shape)
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(stat_shape)
        if channels_last:
            out = jnp.moveaxis(out, 1, -1)
        return out

    return forward_op("group_norm", impl, args)


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW",
                        name=None):
    x = ensure_tensor(x)

    def impl(v):
        if data_format != "NCHW":
            v = jnp.moveaxis(v, -1, 1)
        sq = jnp.square(v)
        half = size // 2
        pads = [(0, 0), (half, size - 1 - half)] + [(0, 0)] * (v.ndim - 2)
        padded = jnp.pad(sq, pads)
        window = [1, size] + [1] * (v.ndim - 2)
        summed = jax.lax.reduce_window(padded, 0.0, jax.lax.add, window,
                                       (1,) * v.ndim, "VALID" if False else
                                       [(0, 0)] * v.ndim)
        out = v / (k + alpha * summed) ** beta
        if data_format != "NCHW":
            out = jnp.moveaxis(out, 1, -1)
        return out

    return forward_op("local_response_norm", impl, [x])


def spectral_norm(weight, u, v, dim=0, power_iters=1, eps=1e-12, name=None):
    weight, u, v = ensure_tensor(weight), ensure_tensor(u), ensure_tensor(v)

    def impl(w, u_, v_):
        wm = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
        for _ in range(power_iters):
            v_ = wm.T @ u_
            v_ = v_ / (jnp.linalg.norm(v_) + eps)
            u_ = wm @ v_
            u_ = u_ / (jnp.linalg.norm(u_) + eps)
        sigma = u_ @ wm @ v_
        return w / sigma

    return forward_op("spectral_norm", impl, [weight, u, v])


def sync_batch_norm(x, running_mean, running_var, weight=None, bias=None,
                    training: bool = False, momentum: float = 0.9,
                    epsilon: float = 1e-5, data_format="NCHW", name=None):
    """Cross-replica batch norm (ref: sync_batch_norm_op): batch statistics
    are all-reduced over the data-parallel group before normalization, so
    every replica normalizes by the GLOBAL batch. On TPU: inside pjit/GSPMD
    the mean/var reduction is already global when the batch axis is sharded
    (XLA inserts the collective); in the eager multi-process tier the
    explicit all_reduce below does it. Single-process: plain batch_norm."""
    from ...distributed import collective as C
    if not (training and C.is_initialized() and C.get_world_size() > 1):
        return batch_norm(x, running_mean, running_var, weight=weight,
                          bias=bias, training=training, momentum=momentum,
                          epsilon=epsilon, data_format=data_format)
    from ...ops._helpers import ensure_tensor as _et
    t = _et(x)
    axes = (0, 2, 3) if data_format == "NCHW" and t.ndim == 4 else (0,)
    from ...ops.math import mean as _mean
    import jax.numpy as _jnp
    from ...ops._helpers import forward_op as _f
    local_mean = _f("sbn_mean", lambda v: v.mean(axes), [t])
    local_sq = _f("sbn_sq", lambda v: (v * v).mean(axes), [t])
    g_mean = C.all_reduce(local_mean) / C.get_world_size()
    g_sq = C.all_reduce(local_sq) / C.get_world_size()

    def norm(v, m, sq, *wb):
        var = sq - m * m
        shape = (1, -1) + (1,) * (v.ndim - 2) if data_format == "NCHW" \
            else (1,) * (v.ndim - 1) + (-1,)
        out = (v - m.reshape(shape)) / _jnp.sqrt(var.reshape(shape)
                                                 + epsilon)
        if wb:
            out = out * wb[0].reshape(shape)
            if len(wb) > 1:
                out = out + wb[1].reshape(shape)
        return out

    extra = [w for w in (weight, bias) if w is not None]
    return _f("sync_batch_norm", norm,
              [t, g_mean, g_sq] + [_et(w) for w in extra])
