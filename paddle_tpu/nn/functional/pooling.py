"""Pooling functionals.

Parity target: ``python/paddle/nn/functional/pooling.py``. Lowered to
``jax.lax.reduce_window`` (XLA pools natively on TPU).
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...core.tensor import Tensor
from ...ops._helpers import ensure_tensor, forward_op
from .conv import _padding, _tuple


def _window(rank, kernel, stride, padding, channels_last, ceil_mode=False,
            in_spatial=None):
    k = _tuple(kernel, rank)
    s = _tuple(stride if stride is not None else kernel, rank)
    pad = _padding(padding, rank)
    if ceil_mode and not isinstance(pad, str):
        # extend hi padding so the last (partial) window is included — the padded
        # cells are the reducer's identity so results match paddle's ceil_mode
        pad = list(pad)
        for i in range(rank):
            lo, hi = pad[i]
            span = in_spatial[i] + lo + hi - k[i]
            out_ceil = -(-span // s[i]) + 1
            # torch/paddle clamp: drop a window that would start entirely inside
            # the right padding
            if (out_ceil - 1) * s[i] >= in_spatial[i] + lo:
                out_ceil -= 1
            extra = (out_ceil - 1) * s[i] + k[i] - (in_spatial[i] + lo + hi)
            pad[i] = (lo, hi + max(0, extra))
    if channels_last:
        dims = (1,) + k + (1,)
        strides = (1,) + s + (1,)
        pads = [(0, 0)] + (pad if isinstance(pad, list) else []) + [(0, 0)] \
            if not isinstance(pad, str) else pad
    else:
        dims = (1, 1) + k
        strides = (1, 1) + s
        pads = [(0, 0), (0, 0)] + pad if not isinstance(pad, str) else pad
    return dims, strides, pads, k, s, pad


def _pool(rank, reducer, init_val, avg=False):
    def pool(x, kernel_size, stride=None, padding=0, ceil_mode=False,
             exclusive=True, divisor_override=None, data_format=None,
             return_mask=False, name=None, count_include_pad=None):
        x = ensure_tensor(x)
        channels_last = data_format in ("NLC", "NHWC", "NDHWC")
        in_spatial = x.shape[1:-1] if channels_last else x.shape[2:]
        dims, strides, pads, k, s, pad = _window(rank, kernel_size, stride, padding,
                                                 channels_last, ceil_mode, in_spatial)
        if count_include_pad is not None:
            # paddle MaxPool uses `ceil_mode`; AvgPool's exclusive == not count_include_pad
            exclusive = not count_include_pad

        def impl(v):
            if avg:
                summed = jax.lax.reduce_window(v, 0.0, jax.lax.add, dims, strides, pads)
                if divisor_override:
                    return summed / divisor_override
                if exclusive and not isinstance(pads, str):
                    ones = jnp.ones_like(v)
                    counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, dims,
                                                   strides, pads)
                    return summed / counts
                return summed / float(np.prod(k))
            return jax.lax.reduce_window(v, init_val, reducer, dims, strides, pads)

        out = forward_op(f"{'avg' if avg else 'max'}_pool{rank}d", impl, [x])
        if return_mask:
            idx = _pool_mask(x, k, s, pads, rank, channels_last)
            return out, idx
        return out

    pool.__name__ = f"{'avg' if avg else 'max'}_pool{rank}d"
    return pool


def _pool_mask(x, k, s, pads, rank, channels_last):
    """Indices of max elements (flattened spatial index, paddle convention)."""
    from ...core.tensor import to_tensor

    v = np.asarray(x._value)
    if rank != 2 or channels_last:
        raise NotImplementedError("return_mask only for NCHW 2-D pooling")
    n, c, h, w = v.shape
    kh, kw = k
    sh, sw = s
    if isinstance(pads, str):
        ph = pw = 0
        ph_hi = pw_hi = 0
    else:
        (ph, ph_hi), (pw, pw_hi) = pads[2], pads[3]
    # use the (possibly ceil-extended) actual pads so the mask shape matches out
    oh = (h + ph + ph_hi - kh) // sh + 1
    ow = (w + pw + pw_hi - kw) // sw + 1
    out = np.zeros((n, c, oh, ow), np.int64)
    vp = np.pad(v, ((0, 0), (0, 0), (ph, ph), (pw, pw)), constant_values=-np.inf)
    for i in range(oh):
        for j in range(ow):
            win = vp[:, :, i * sh:i * sh + kh, j * sw:j * sw + kw].reshape(n, c, -1)
            am = win.argmax(-1)
            r = i * sh + am // kw - ph
            cc = j * sw + am % kw - pw
            out[:, :, i, j] = r * w + cc
    return to_tensor(out)


max_pool1d = _pool(1, jax.lax.max, -jnp.inf)
max_pool2d = _pool(2, jax.lax.max, -jnp.inf)
max_pool3d = _pool(3, jax.lax.max, -jnp.inf)
avg_pool1d = _pool(1, jax.lax.add, 0.0, avg=True)
avg_pool2d = _pool(2, jax.lax.add, 0.0, avg=True)
avg_pool3d = _pool(3, jax.lax.add, 0.0, avg=True)


def _adaptive(rank, avg):
    def pool(x, output_size, data_format=None, return_mask=False, name=None):
        x = ensure_tensor(x)
        channels_last = data_format in ("NLC", "NHWC", "NDHWC")
        out_sp = _tuple(output_size, rank)
        nd = rank + 2
        spatial = list(range(1, nd - 1)) if channels_last else list(range(2, nd))
        in_sp = [x.shape[i] for i in spatial]
        out_sp = tuple(in_sp[i] if out_sp[i] is None else out_sp[i]
                       for i in range(rank))

        def impl(v):
            # decompose into per-axis adaptive pooling via mean/max over index bins
            out = v
            for ax_i, (ax, osz) in enumerate(zip(spatial, out_sp)):
                isz = out.shape[ax]
                starts = np.floor(np.arange(osz) * isz / osz).astype(int)
                ends = np.ceil((np.arange(osz) + 1) * isz / osz).astype(int)
                pieces = []
                for st, en in zip(starts, ends):
                    sl = jax.lax.slice_in_dim(out, int(st), int(en), axis=ax)
                    red = jnp.mean(sl, axis=ax, keepdims=True) if avg else \
                        jnp.max(sl, axis=ax, keepdims=True)
                    pieces.append(red)
                out = jnp.concatenate(pieces, axis=ax)
            return out

        res = forward_op(f"adaptive_{'avg' if avg else 'max'}_pool{rank}d", impl, [x])
        if return_mask:
            raise NotImplementedError("adaptive pooling return_mask")
        return res

    pool.__name__ = f"adaptive_{'avg' if avg else 'max'}_pool{rank}d"
    return pool


adaptive_avg_pool1d = _adaptive(1, True)
adaptive_avg_pool2d = _adaptive(2, True)
adaptive_avg_pool3d = _adaptive(3, True)
adaptive_max_pool1d = _adaptive(1, False)
adaptive_max_pool2d = _adaptive(2, False)
adaptive_max_pool3d = _adaptive(3, False)


def lp_pool2d(x, norm_type, kernel_size, stride=None, padding=0, ceil_mode=False,
              data_format="NCHW", name=None):
    x = ensure_tensor(x)
    p = float(norm_type)
    channels_last = data_format == "NHWC"
    in_spatial = x.shape[1:-1] if channels_last else x.shape[2:]
    dims, strides, pads, k, s, _ = _window(2, kernel_size, stride, padding,
                                           channels_last, ceil_mode, in_spatial)

    def impl(v):
        powed = jnp.abs(v) ** p
        summed = jax.lax.reduce_window(powed, 0.0, jax.lax.add, dims, strides, pads)
        return summed ** (1.0 / p)

    return forward_op("lp_pool2d", impl, [x])


def lp_pool1d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCL", name=None):
    """Power-average pooling over 1-D windows (ref: nn.functional.lp_pool1d)."""
    x = ensure_tensor(x)
    p = float(norm_type)
    channels_last = data_format == "NLC"
    in_spatial = x.shape[1:-1] if channels_last else x.shape[2:]
    dims, strides, pads, k, s, _ = _window(1, kernel_size, stride, padding,
                                           channels_last, ceil_mode,
                                           in_spatial)

    def impl(v):
        powed = jnp.abs(v) ** p
        summed = jax.lax.reduce_window(powed, 0.0, jax.lax.add, dims,
                                       strides, pads)
        return summed ** (1.0 / p)

    return forward_op("lp_pool1d", impl, [x])


def _fractional_pool(nd):
    def op(x, output_size, kernel_size=None, random_u=None, return_mask=False,
           name=None):
        """Fractional max pooling (Graham 2014; ATen interval formula —
        start_i = floor((i+u)*alpha) - floor(u*alpha), alpha =
        (in-k)/(out-1), fixed k-window, last window right-aligned; ref:
        nn.functional.fractional_max_pool2d/3d). Deterministic given
        ``random_u``; default draws from the framework RNG."""
        x = ensure_tensor(x)
        spatial = x.shape[2:]
        if isinstance(output_size, int):
            out_sz = (output_size,) * nd
        else:
            out_sz = tuple(int(o) for o in output_size)
        if kernel_size is None:
            ks = tuple(spatial[d] // out_sz[d] for d in range(nd))
        elif isinstance(kernel_size, int):
            ks = (kernel_size,) * nd
        else:
            ks = tuple(int(k) for k in kernel_size)
        if random_u is None:
            from ...ops import random as _rnd
            import numpy as _np
            u = float(_np.asarray(
                _rnd.uniform([1], min=0.0, max=1.0)._value)[0])
        else:
            u = float(random_u)

        import math as _m

        def starts(n_in, n_out, k):
            if n_out == 1:
                return [0]
            a = (n_in - k) / (n_out - 1)
            return [(n_in - k) if i == n_out - 1 else
                    int((i + u) * a) - int(u * a) for i in range(n_out)]

        st = [starts(spatial[d], out_sz[d], ks[d]) for d in range(nd)]

        def impl(v):
            import itertools
            outs = jnp.zeros(v.shape[:2] + out_sz, v.dtype)
            for idx in itertools.product(*(range(o) for o in out_sz)):
                sl = (slice(None), slice(None)) + tuple(
                    slice(st[d][idx[d]], st[d][idx[d]] + ks[d])
                    for d in range(nd))
                red = v[sl]
                for _ in range(nd):
                    red = red.max(axis=2)
                outs = outs.at[(slice(None), slice(None)) + idx].set(red)
            return outs

        out = forward_op(f"fractional_max_pool{nd}d", impl, [x])
        if return_mask:
            def mask_impl(v):
                import itertools
                m = jnp.zeros(v.shape[:2] + out_sz, jnp.int64)
                W = spatial[-1]
                for idx in itertools.product(*(range(o) for o in out_sz)):
                    sl = (slice(None), slice(None)) + tuple(
                        slice(st[d][idx[d]], st[d][idx[d]] + ks[d])
                        for d in range(nd))
                    red = v[sl].reshape(v.shape[:2] + (-1,))
                    loc = jnp.argmax(red, axis=-1)
                    # flat index within the FULL spatial plane
                    if nd == 2:
                        r = st[0][idx[0]] + loc // ks[1]
                        c = st[1][idx[1]] + loc % ks[1]
                        flat = r * W + c
                    else:
                        k12 = ks[1] * ks[2]
                        d0 = st[0][idx[0]] + loc // k12
                        d1 = st[1][idx[1]] + (loc % k12) // ks[2]
                        d2 = st[2][idx[2]] + loc % ks[2]
                        flat = (d0 * spatial[1] + d1) * spatial[2] + d2
                    m = m.at[(slice(None), slice(None)) + idx].set(flat)
                return m
            mask = forward_op(f"fractional_max_pool{nd}d_mask", mask_impl,
                              [x], differentiable=False)
            return out, mask
        return out

    op.__name__ = f"fractional_max_pool{nd}d"
    op.__qualname__ = op.__name__
    return op


fractional_max_pool2d = _fractional_pool(2)
fractional_max_pool3d = _fractional_pool(3)
