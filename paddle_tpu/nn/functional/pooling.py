"""Pooling functionals.

Parity target: ``python/paddle/nn/functional/pooling.py``. Lowered to
``jax.lax.reduce_window`` (XLA pools natively on TPU).
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...core.tensor import Tensor
from ...ops._helpers import ensure_tensor, forward_op
from .conv import _padding, _tuple


def _window(rank, kernel, stride, padding, channels_last, ceil_mode=False,
            in_spatial=None):
    k = _tuple(kernel, rank)
    s = _tuple(stride if stride is not None else kernel, rank)
    pad = _padding(padding, rank)
    if ceil_mode and not isinstance(pad, str):
        # extend hi padding so the last (partial) window is included — the padded
        # cells are the reducer's identity so results match paddle's ceil_mode
        pad = list(pad)
        for i in range(rank):
            lo, hi = pad[i]
            span = in_spatial[i] + lo + hi - k[i]
            out_ceil = -(-span // s[i]) + 1
            # torch/paddle clamp: drop a window that would start entirely inside
            # the right padding
            if (out_ceil - 1) * s[i] >= in_spatial[i] + lo:
                out_ceil -= 1
            extra = (out_ceil - 1) * s[i] + k[i] - (in_spatial[i] + lo + hi)
            pad[i] = (lo, hi + max(0, extra))
    if channels_last:
        dims = (1,) + k + (1,)
        strides = (1,) + s + (1,)
        pads = [(0, 0)] + (pad if isinstance(pad, list) else []) + [(0, 0)] \
            if not isinstance(pad, str) else pad
    else:
        dims = (1, 1) + k
        strides = (1, 1) + s
        pads = [(0, 0), (0, 0)] + pad if not isinstance(pad, str) else pad
    return dims, strides, pads, k, s, pad


def _pool(rank, reducer, init_val, avg=False):
    def pool(x, kernel_size, stride=None, padding=0, ceil_mode=False,
             exclusive=True, divisor_override=None, data_format=None,
             return_mask=False, name=None, count_include_pad=None):
        x = ensure_tensor(x)
        channels_last = data_format in ("NLC", "NHWC", "NDHWC")
        in_spatial = x.shape[1:-1] if channels_last else x.shape[2:]
        dims, strides, pads, k, s, pad = _window(rank, kernel_size, stride, padding,
                                                 channels_last, ceil_mode, in_spatial)
        if count_include_pad is not None:
            # paddle MaxPool uses `ceil_mode`; AvgPool's exclusive == not count_include_pad
            exclusive = not count_include_pad

        def impl(v):
            if avg:
                summed = jax.lax.reduce_window(v, 0.0, jax.lax.add, dims, strides, pads)
                if divisor_override:
                    return summed / divisor_override
                if exclusive and not isinstance(pads, str):
                    ones = jnp.ones_like(v)
                    counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, dims,
                                                   strides, pads)
                    return summed / counts
                return summed / float(np.prod(k))
            return jax.lax.reduce_window(v, init_val, reducer, dims, strides, pads)

        out = forward_op(f"{'avg' if avg else 'max'}_pool{rank}d", impl, [x])
        if return_mask:
            idx = _pool_mask(x, k, s, pads, rank, channels_last)
            return out, idx
        return out

    pool.__name__ = f"{'avg' if avg else 'max'}_pool{rank}d"
    return pool


def _pool_mask(x, k, s, pads, rank, channels_last):
    """Indices of max elements (flattened spatial index, paddle convention)."""
    from ...core.tensor import to_tensor

    v = np.asarray(x._value)
    if rank != 2 or channels_last:
        raise NotImplementedError("return_mask only for NCHW 2-D pooling")
    n, c, h, w = v.shape
    kh, kw = k
    sh, sw = s
    if isinstance(pads, str):
        ph = pw = 0
        ph_hi = pw_hi = 0
    else:
        (ph, ph_hi), (pw, pw_hi) = pads[2], pads[3]
    # use the (possibly ceil-extended) actual pads so the mask shape matches out
    oh = (h + ph + ph_hi - kh) // sh + 1
    ow = (w + pw + pw_hi - kw) // sw + 1
    out = np.zeros((n, c, oh, ow), np.int64)
    vp = np.pad(v, ((0, 0), (0, 0), (ph, ph), (pw, pw)), constant_values=-np.inf)
    for i in range(oh):
        for j in range(ow):
            win = vp[:, :, i * sh:i * sh + kh, j * sw:j * sw + kw].reshape(n, c, -1)
            am = win.argmax(-1)
            r = i * sh + am // kw - ph
            cc = j * sw + am % kw - pw
            out[:, :, i, j] = r * w + cc
    return to_tensor(out)


max_pool1d = _pool(1, jax.lax.max, -jnp.inf)
max_pool2d = _pool(2, jax.lax.max, -jnp.inf)
max_pool3d = _pool(3, jax.lax.max, -jnp.inf)
avg_pool1d = _pool(1, jax.lax.add, 0.0, avg=True)
avg_pool2d = _pool(2, jax.lax.add, 0.0, avg=True)
avg_pool3d = _pool(3, jax.lax.add, 0.0, avg=True)


def _adaptive(rank, avg):
    def pool(x, output_size, data_format=None, return_mask=False, name=None):
        x = ensure_tensor(x)
        channels_last = data_format in ("NLC", "NHWC", "NDHWC")
        out_sp = _tuple(output_size, rank)
        nd = rank + 2
        spatial = list(range(1, nd - 1)) if channels_last else list(range(2, nd))
        in_sp = [x.shape[i] for i in spatial]
        out_sp = tuple(in_sp[i] if out_sp[i] is None else out_sp[i]
                       for i in range(rank))

        def impl(v):
            # decompose into per-axis adaptive pooling via mean/max over index bins
            out = v
            for ax_i, (ax, osz) in enumerate(zip(spatial, out_sp)):
                isz = out.shape[ax]
                starts = np.floor(np.arange(osz) * isz / osz).astype(int)
                ends = np.ceil((np.arange(osz) + 1) * isz / osz).astype(int)
                pieces = []
                for st, en in zip(starts, ends):
                    sl = jax.lax.slice_in_dim(out, int(st), int(en), axis=ax)
                    red = jnp.mean(sl, axis=ax, keepdims=True) if avg else \
                        jnp.max(sl, axis=ax, keepdims=True)
                    pieces.append(red)
                out = jnp.concatenate(pieces, axis=ax)
            return out

        res = forward_op(f"adaptive_{'avg' if avg else 'max'}_pool{rank}d", impl, [x])
        if return_mask:
            raise NotImplementedError("adaptive pooling return_mask")
        return res

    pool.__name__ = f"adaptive_{'avg' if avg else 'max'}_pool{rank}d"
    return pool


adaptive_avg_pool1d = _adaptive(1, True)
adaptive_avg_pool2d = _adaptive(2, True)
adaptive_avg_pool3d = _adaptive(3, True)
adaptive_max_pool1d = _adaptive(1, False)
adaptive_max_pool2d = _adaptive(2, False)
adaptive_max_pool3d = _adaptive(3, False)


def lp_pool2d(x, norm_type, kernel_size, stride=None, padding=0, ceil_mode=False,
              data_format="NCHW", name=None):
    x = ensure_tensor(x)
    p = float(norm_type)
    channels_last = data_format == "NHWC"
    in_spatial = x.shape[1:-1] if channels_last else x.shape[2:]
    dims, strides, pads, k, s, _ = _window(2, kernel_size, stride, padding,
                                           channels_last, ceil_mode, in_spatial)

    def impl(v):
        powed = jnp.abs(v) ** p
        summed = jax.lax.reduce_window(powed, 0.0, jax.lax.add, dims, strides, pads)
        return summed ** (1.0 / p)

    return forward_op("lp_pool2d", impl, [x])
