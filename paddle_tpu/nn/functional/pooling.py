"""Pooling functionals.

Parity target: ``python/paddle/nn/functional/pooling.py``. Lowered to
``jax.lax.reduce_window`` (XLA pools natively on TPU).
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...core.tensor import Tensor
from ...ops._helpers import ensure_tensor, forward_op
from .conv import _padding, _tuple


def _window(rank, kernel, stride, padding, channels_last, ceil_mode=False,
            in_spatial=None):
    k = _tuple(kernel, rank)
    s = _tuple(stride if stride is not None else kernel, rank)
    pad = _padding(padding, rank)
    if ceil_mode and not isinstance(pad, str):
        # extend hi padding so the last (partial) window is included — the padded
        # cells are the reducer's identity so results match paddle's ceil_mode
        pad = list(pad)
        for i in range(rank):
            lo, hi = pad[i]
            span = in_spatial[i] + lo + hi - k[i]
            out_ceil = -(-span // s[i]) + 1
            # torch/paddle clamp: drop a window that would start entirely inside
            # the right padding
            if (out_ceil - 1) * s[i] >= in_spatial[i] + lo:
                out_ceil -= 1
            extra = (out_ceil - 1) * s[i] + k[i] - (in_spatial[i] + lo + hi)
            pad[i] = (lo, hi + max(0, extra))
    if channels_last:
        dims = (1,) + k + (1,)
        strides = (1,) + s + (1,)
        pads = [(0, 0)] + (pad if isinstance(pad, list) else []) + [(0, 0)] \
            if not isinstance(pad, str) else pad
    else:
        dims = (1, 1) + k
        strides = (1, 1) + s
        pads = [(0, 0), (0, 0)] + pad if not isinstance(pad, str) else pad
    return dims, strides, pads, k, s, pad


def _pool(rank, reducer, init_val, avg=False):
    def pool(x, kernel_size, stride=None, padding=0, ceil_mode=False,
             exclusive=True, divisor_override=None, data_format=None,
             return_mask=False, name=None, count_include_pad=None):
        x = ensure_tensor(x)
        channels_last = data_format in ("NLC", "NHWC", "NDHWC")
        in_spatial = x.shape[1:-1] if channels_last else x.shape[2:]
        dims, strides, pads, k, s, pad = _window(rank, kernel_size, stride, padding,
                                                 channels_last, ceil_mode, in_spatial)
        if count_include_pad is not None:
            # paddle MaxPool uses `ceil_mode`; AvgPool's exclusive == not count_include_pad
            exclusive = not count_include_pad

        def impl(v):
            if avg:
                summed = jax.lax.reduce_window(v, 0.0, jax.lax.add, dims, strides, pads)
                if divisor_override:
                    return summed / divisor_override
                if exclusive and not isinstance(pads, str):
                    ones = jnp.ones_like(v)
                    counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, dims,
                                                   strides, pads)
                    return summed / counts
                return summed / float(np.prod(k))
            return jax.lax.reduce_window(v, init_val, reducer, dims, strides, pads)

        out = forward_op(f"{'avg' if avg else 'max'}_pool{rank}d", impl, [x])
        if return_mask:
            idx = _pool_mask(x, k, s, pads, rank, channels_last)
            return out, idx
        return out

    pool.__name__ = f"{'avg' if avg else 'max'}_pool{rank}d"
    return pool


def _pool_mask(x, k, s, pads, rank, channels_last):
    """Indices of max elements (flattened spatial index, paddle convention)."""
    from ...core.tensor import to_tensor

    v = np.asarray(x._value)
    if rank != 2 or channels_last:
        raise NotImplementedError("return_mask only for NCHW 2-D pooling")
    n, c, h, w = v.shape
    kh, kw = k
    sh, sw = s
    if isinstance(pads, str):
        ph = pw = 0
        ph_hi = pw_hi = 0
    else:
        (ph, ph_hi), (pw, pw_hi) = pads[2], pads[3]
    # use the (possibly ceil-extended) actual pads so the mask shape matches out
    oh = (h + ph + ph_hi - kh) // sh + 1
    ow = (w + pw + pw_hi - kw) // sw + 1
    out = np.zeros((n, c, oh, ow), np.int64)
    vp = np.pad(v, ((0, 0), (0, 0), (ph, ph), (pw, pw)), constant_values=-np.inf)
    for i in range(oh):
        for j in range(ow):
            win = vp[:, :, i * sh:i * sh + kh, j * sw:j * sw + kw].reshape(n, c, -1)
            am = win.argmax(-1)
            r = i * sh + am // kw - ph
            cc = j * sw + am % kw - pw
            out[:, :, i, j] = r * w + cc
    return to_tensor(out)


max_pool1d = _pool(1, jax.lax.max, -jnp.inf)
max_pool2d = _pool(2, jax.lax.max, -jnp.inf)
max_pool3d = _pool(3, jax.lax.max, -jnp.inf)
avg_pool1d = _pool(1, jax.lax.add, 0.0, avg=True)
avg_pool2d = _pool(2, jax.lax.add, 0.0, avg=True)
avg_pool3d = _pool(3, jax.lax.add, 0.0, avg=True)


def _adaptive(rank, avg):
    def pool(x, output_size, data_format=None, return_mask=False, name=None):
        x = ensure_tensor(x)
        channels_last = data_format in ("NLC", "NHWC", "NDHWC")
        out_sp = _tuple(output_size, rank)
        nd = rank + 2
        spatial = list(range(1, nd - 1)) if channels_last else list(range(2, nd))
        in_sp = [x.shape[i] for i in spatial]
        out_sp = tuple(in_sp[i] if out_sp[i] is None else out_sp[i]
                       for i in range(rank))

        def impl(v):
            # decompose into per-axis adaptive pooling via mean/max over index bins
            out = v
            for ax_i, (ax, osz) in enumerate(zip(spatial, out_sp)):
                isz = out.shape[ax]
                starts = np.floor(np.arange(osz) * isz / osz).astype(int)
                ends = np.ceil((np.arange(osz) + 1) * isz / osz).astype(int)
                pieces = []
                for st, en in zip(starts, ends):
                    sl = jax.lax.slice_in_dim(out, int(st), int(en), axis=ax)
                    red = jnp.mean(sl, axis=ax, keepdims=True) if avg else \
                        jnp.max(sl, axis=ax, keepdims=True)
                    pieces.append(red)
                out = jnp.concatenate(pieces, axis=ax)
            return out

        res = forward_op(f"adaptive_{'avg' if avg else 'max'}_pool{rank}d", impl, [x])
        if return_mask:
            raise NotImplementedError("adaptive pooling return_mask")
        return res

    pool.__name__ = f"adaptive_{'avg' if avg else 'max'}_pool{rank}d"
    return pool


adaptive_avg_pool1d = _adaptive(1, True)
adaptive_avg_pool2d = _adaptive(2, True)
adaptive_avg_pool3d = _adaptive(3, True)
adaptive_max_pool1d = _adaptive(1, False)
adaptive_max_pool2d = _adaptive(2, False)
adaptive_max_pool3d = _adaptive(3, False)


def lp_pool2d(x, norm_type, kernel_size, stride=None, padding=0, ceil_mode=False,
              data_format="NCHW", name=None):
    x = ensure_tensor(x)
    p = float(norm_type)
    channels_last = data_format == "NHWC"
    in_spatial = x.shape[1:-1] if channels_last else x.shape[2:]
    dims, strides, pads, k, s, _ = _window(2, kernel_size, stride, padding,
                                           channels_last, ceil_mode, in_spatial)

    def impl(v):
        powed = jnp.abs(v) ** p
        summed = jax.lax.reduce_window(powed, 0.0, jax.lax.add, dims, strides, pads)
        return summed ** (1.0 / p)

    return forward_op("lp_pool2d", impl, [x])


def lp_pool1d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCL", name=None):
    """Power-average pooling over 1-D windows (ref: nn.functional.lp_pool1d)."""
    x = ensure_tensor(x)
    p = float(norm_type)
    channels_last = data_format == "NLC"
    in_spatial = x.shape[1:-1] if channels_last else x.shape[2:]
    dims, strides, pads, k, s, _ = _window(1, kernel_size, stride, padding,
                                           channels_last, ceil_mode,
                                           in_spatial)

    def impl(v):
        powed = jnp.abs(v) ** p
        summed = jax.lax.reduce_window(powed, 0.0, jax.lax.add, dims,
                                       strides, pads)
        return summed ** (1.0 / p)

    return forward_op("lp_pool1d", impl, [x])


def _fractional_pool(nd):
    def op(x, output_size, kernel_size=None, random_u=None, return_mask=False,
           name=None):
        """Fractional max pooling (Graham 2014; ATen interval formula —
        start_i = floor((i+u)*alpha) - floor(u*alpha), alpha =
        (in-k)/(out-1), fixed k-window, last window right-aligned; ref:
        nn.functional.fractional_max_pool2d/3d). Deterministic given
        ``random_u``; default draws from the framework RNG."""
        x = ensure_tensor(x)
        spatial = x.shape[2:]
        if isinstance(output_size, int):
            out_sz = (output_size,) * nd
        else:
            out_sz = tuple(int(o) for o in output_size)
        if kernel_size is None:
            ks = tuple(spatial[d] // out_sz[d] for d in range(nd))
        elif isinstance(kernel_size, int):
            ks = (kernel_size,) * nd
        else:
            ks = tuple(int(k) for k in kernel_size)
        if random_u is None:
            from ...ops import random as _rnd
            import numpy as _np
            u = float(_np.asarray(
                _rnd.uniform([1], min=0.0, max=1.0)._value)[0])
        else:
            u = float(random_u)

        import math as _m

        def starts(n_in, n_out, k):
            if n_out == 1:
                return [0]
            a = (n_in - k) / (n_out - 1)
            return [(n_in - k) if i == n_out - 1 else
                    int((i + u) * a) - int(u * a) for i in range(n_out)]

        st = [starts(spatial[d], out_sz[d], ks[d]) for d in range(nd)]

        def impl(v):
            import itertools
            outs = jnp.zeros(v.shape[:2] + out_sz, v.dtype)
            for idx in itertools.product(*(range(o) for o in out_sz)):
                sl = (slice(None), slice(None)) + tuple(
                    slice(st[d][idx[d]], st[d][idx[d]] + ks[d])
                    for d in range(nd))
                red = v[sl]
                for _ in range(nd):
                    red = red.max(axis=2)
                outs = outs.at[(slice(None), slice(None)) + idx].set(red)
            return outs

        out = forward_op(f"fractional_max_pool{nd}d", impl, [x])
        if return_mask:
            def mask_impl(v):
                import itertools
                m = jnp.zeros(v.shape[:2] + out_sz, jnp.int64)
                W = spatial[-1]
                for idx in itertools.product(*(range(o) for o in out_sz)):
                    sl = (slice(None), slice(None)) + tuple(
                        slice(st[d][idx[d]], st[d][idx[d]] + ks[d])
                        for d in range(nd))
                    red = v[sl].reshape(v.shape[:2] + (-1,))
                    loc = jnp.argmax(red, axis=-1)
                    # flat index within the FULL spatial plane
                    if nd == 2:
                        r = st[0][idx[0]] + loc // ks[1]
                        c = st[1][idx[1]] + loc % ks[1]
                        flat = r * W + c
                    else:
                        k12 = ks[1] * ks[2]
                        d0 = st[0][idx[0]] + loc // k12
                        d1 = st[1][idx[1]] + (loc % k12) // ks[2]
                        d2 = st[2][idx[2]] + loc % ks[2]
                        flat = (d0 * spatial[1] + d1) * spatial[2] + d2
                    m = m.at[(slice(None), slice(None)) + idx].set(flat)
                return m
            mask = forward_op(f"fractional_max_pool{nd}d_mask", mask_impl,
                              [x], differentiable=False)
            return out, mask
        return out

    op.__name__ = f"fractional_max_pool{nd}d"
    op.__qualname__ = op.__name__
    return op


fractional_max_pool2d = _fractional_pool(2)
fractional_max_pool3d = _fractional_pool(3)


# ---------------------------------------------------------------------------
# r5: max_unpool family (ref: python/paddle/nn/functional/pooling.py
# max_unpool1d/2d/3d; fluid unpool_op/unpool3d_op). TPU formulation: a
# scatter of the pooled values to their argmax flat indices — one
# ``.at[].set`` over the [N*C, H*W] plane, static shapes.
# ---------------------------------------------------------------------------

def _unpool_nd(name, spatial):
    def op(x, indices, kernel_size, stride=None, padding=0,
           output_size=None, data_format=None, name=None):
        from ...ops._helpers import ensure_tensor, forward_op
        xt = ensure_tensor(x)
        it = ensure_tensor(indices)
        ks = (kernel_size,) * spatial if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        st = ks if stride is None else (
            (stride,) * spatial if isinstance(stride, int) else tuple(stride))
        pd = (padding,) * spatial if isinstance(padding, int) \
            else tuple(padding)
        in_sp = [int(s) for s in xt.shape[2:]]
        if output_size is None:
            out_sp = [(in_sp[d] - 1) * st[d] - 2 * pd[d] + ks[d]
                      for d in range(spatial)]
        else:
            out_sp = [int(s) for s in tuple(output_size)[-spatial:]]

        def impl(xv, iv):
            N, C = xv.shape[:2]
            plane = 1
            for s in out_sp:
                plane *= s
            flat = jnp.zeros((N, C, plane), xv.dtype)
            xi = xv.reshape(N, C, -1)
            ii = iv.reshape(N, C, -1).astype(jnp.int32)
            out = flat.at[
                jnp.arange(N)[:, None, None],
                jnp.arange(C)[None, :, None], ii].set(xi)
            return out.reshape([N, C] + out_sp)

        return forward_op(name, impl, [xt, it])

    op.__name__ = name
    op.__qualname__ = name
    op.__doc__ = (f"Inverse of max_pool{spatial}d with return_mask=True: "
                  f"scatters values back to their argmax positions (ref: "
                  f"paddle.nn.functional.{name} / fluid unpool op).")
    return op


max_unpool1d = _unpool_nd("max_unpool1d", 1)
max_unpool2d = _unpool_nd("max_unpool2d", 2)
max_unpool3d = _unpool_nd("max_unpool3d", 3)


# r5: index-returning pool names + SPP + legacy unpool aliases (ref:
# max_pool2d_with_index_op / max_pool3d_with_index_op / spp_op /
# unpool_op / unpool3d_op)
def max_pool2d_with_index(x, kernel_size, stride=None, padding=0,
                          global_pooling=False, adaptive=False, name=None):
    """max_pool2d that always returns (out, argmax indices) — the
    upstream kernel behind return_mask."""
    if global_pooling:
        kernel_size = [int(s) for s in ensure_tensor(x).shape[2:]]
        stride, padding = kernel_size, 0
    return max_pool2d(x, kernel_size, stride=stride, padding=padding,
                      return_mask=True)


def max_pool3d_with_index(x, kernel_size, stride=None, padding=0,
                          global_pooling=False, adaptive=False, name=None):
    """3-D twin of max_pool2d_with_index. The indices are flat positions
    in each [D*H*W] plane (upstream convention)."""
    from ...ops._helpers import forward_op as _f
    xt = ensure_tensor(x)
    ks = (kernel_size,) * 3 if isinstance(kernel_size, int) \
        else tuple(kernel_size)
    st = ks if stride is None else (
        (stride,) * 3 if isinstance(stride, int) else tuple(stride))
    pd = (padding,) * 3 if isinstance(padding, int) else tuple(padding)

    def impl(v):
        N, C, D, H, W = v.shape
        pv = jnp.pad(v, ((0, 0), (0, 0)) + tuple(
            (p, p) for p in pd), constant_values=-jnp.inf)
        OD = (D + 2 * pd[0] - ks[0]) // st[0] + 1
        OH = (H + 2 * pd[1] - ks[1]) // st[1] + 1
        OW = (W + 2 * pd[2] - ks[2]) // st[2] + 1
        # window tape: [N, C, OD, OH, OW, kd*kh*kw] via gather
        dz = jnp.arange(OD) * st[0]
        dy = jnp.arange(OH) * st[1]
        dx = jnp.arange(OW) * st[2]
        kz, ky, kx = jnp.meshgrid(jnp.arange(ks[0]), jnp.arange(ks[1]),
                                  jnp.arange(ks[2]), indexing="ij")
        zz = dz[:, None, None, None] + kz.reshape(-1)[None, None, None, :]
        yy = dy[None, :, None, None] + ky.reshape(-1)[None, None, None, :]
        xx = dx[None, None, :, None] + kx.reshape(-1)[None, None, None, :]
        win = pv[:, :, zz, yy, xx]            # [N, C, OD, OH, OW, K]
        out = win.max(-1)
        arg = win.argmax(-1)
        ki = arg
        z0 = zz[..., 0][None, None] + ki // (ks[1] * ks[2]) - pd[0]
        rem = ki % (ks[1] * ks[2])
        y0 = yy[..., 0][None, None] + rem // ks[2] - pd[1]
        x0 = xx[..., 0][None, None] + rem % ks[2] - pd[2]
        flat = (z0 * H + y0) * W + x0
        return out, flat.astype(jnp.int32)

    return _f("max_pool3d_with_index", impl, [xt])


def spp(x, pyramid_height: int = 3, pool_type: str = "max", name=None):
    """Spatial pyramid pooling (ref: spp_op): adaptive pools at 1x1, 2x2,
    ... 2^(h-1) grids, flattened and concatenated."""
    from ...ops._helpers import forward_op as _f
    xt = ensure_tensor(x)
    outs = []
    for lvl in range(pyramid_height):
        bins = 2 ** lvl
        if pool_type == "max":
            p = adaptive_max_pool2d(xt, bins)
        else:
            p = adaptive_avg_pool2d(xt, bins)
        from ...ops.manipulation import reshape
        outs.append(reshape(p, [int(p.shape[0]), -1]))
    from ...ops.manipulation import concat
    return concat(outs, axis=1)


def unpool(x, indices, kernel_size, stride=None, padding=0,
           output_size=None, name=None):
    """Legacy name for max_unpool2d (ref: unpool_op)."""
    return max_unpool2d(x, indices, kernel_size, stride, padding,
                        output_size)


def unpool3d(x, indices, kernel_size, stride=None, padding=0,
             output_size=None, name=None):
    """Legacy name for max_unpool3d (ref: unpool3d_op)."""
    return max_unpool3d(x, indices, kernel_size, stride, padding,
                        output_size)


def pool2d(x, pool_size, pool_type: str = "max", pool_stride=1,
           pool_padding=0, global_pooling: bool = False,
           ceil_mode: bool = False, exclusive: bool = True, name=None):
    """Legacy merged pooling op (ref: pool2d_op): max or avg selected by
    attribute."""
    if global_pooling:
        pool_size = [int(s) for s in ensure_tensor(x).shape[2:]]
        pool_stride, pool_padding = pool_size, 0
    if pool_type == "max":
        return max_pool2d(x, pool_size, stride=pool_stride,
                          padding=pool_padding, ceil_mode=ceil_mode)
    return avg_pool2d(x, pool_size, stride=pool_stride,
                      padding=pool_padding, ceil_mode=ceil_mode,
                      exclusive=exclusive)


def pool3d(x, pool_size, pool_type: str = "max", pool_stride=1,
           pool_padding=0, global_pooling: bool = False,
           ceil_mode: bool = False, exclusive: bool = True, name=None):
    """Legacy merged 3-D pooling op (ref: pool3d_op)."""
    if global_pooling:
        pool_size = [int(s) for s in ensure_tensor(x).shape[2:]]
        pool_stride, pool_padding = pool_size, 0
    if pool_type == "max":
        return max_pool3d(x, pool_size, stride=pool_stride,
                          padding=pool_padding, ceil_mode=ceil_mode)
    return avg_pool3d(x, pool_size, stride=pool_stride,
                      padding=pool_padding, ceil_mode=ceil_mode,
                      exclusive=exclusive)
