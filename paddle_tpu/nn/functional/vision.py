"""Vision-geometry functionals.

Parity targets: ``python/paddle/nn/functional/vision.py`` in the reference
(grid_sample, affine_grid, pixel_shuffle siblings) and
``python/paddle/nn/functional/common.py`` (fold) — NCHW layout, jnp-backed,
tape-differentiable.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...ops._helpers import ensure_tensor, forward_op

__all__ = ["grid_sample", "affine_grid", "fold", "temporal_shift",
           "bilinear", "feature_alpha_dropout"]


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    """Sample ``x [N,C,H,W]`` at normalized ``grid [N,Ho,Wo,2]`` coordinates
    in [-1, 1] (ref: F.grid_sample; bilinear/nearest, zeros/border/reflection
    padding)."""
    t, g = ensure_tensor(x), ensure_tensor(grid)
    if mode not in ("bilinear", "nearest"):
        raise ValueError(f"grid_sample mode must be bilinear/nearest, "
                         f"got {mode!r}")
    if padding_mode not in ("zeros", "border", "reflection"):
        raise ValueError(f"grid_sample padding_mode {padding_mode!r}")

    def f(v, gv):
        N, C, H, W = v.shape

        def unnormalize(coord, size):
            if align_corners:
                return (coord + 1) / 2 * (size - 1)
            return ((coord + 1) * size - 1) / 2

        ix = unnormalize(gv[..., 0], W)          # [N, Ho, Wo]
        iy = unnormalize(gv[..., 1], H)

        def reflect(c, size):
            if align_corners:
                span = 2 * (size - 1)
                c = jnp.abs(c) % jnp.maximum(span, 1)
                return jnp.where(c > size - 1, span - c, c)
            span = 2 * size
            c = (jnp.abs(c + 0.5) % jnp.maximum(span, 1))
            c = jnp.where(c > size - 0.5, span - c, c) - 0.5
            return jnp.clip(c, 0, size - 1)

        if padding_mode == "reflection":
            ix = reflect(ix, W)
            iy = reflect(iy, H)

        def gather(yc, xc):
            # integer coords [N,Ho,Wo] -> values [N,C,Ho,Wo] with padding
            inb = (yc >= 0) & (yc < H) & (xc >= 0) & (xc < W)
            ycc = jnp.clip(yc, 0, H - 1)
            xcc = jnp.clip(xc, 0, W - 1)
            n_idx = jnp.arange(N)[:, None, None]
            vals = v[n_idx, :, ycc, xcc]          # [N, Ho, Wo, C]
            vals = jnp.moveaxis(vals, -1, 1)      # [N, C, Ho, Wo]
            if padding_mode == "zeros":
                vals = vals * inb[:, None].astype(vals.dtype)
            return vals

        if mode == "nearest":
            return gather(jnp.round(iy).astype(jnp.int32),
                          jnp.round(ix).astype(jnp.int32))

        x0 = jnp.floor(ix)
        y0 = jnp.floor(iy)
        wx = (ix - x0)[:, None]
        wy = (iy - y0)[:, None]
        x0i, y0i = x0.astype(jnp.int32), y0.astype(jnp.int32)
        v00 = gather(y0i, x0i)
        v01 = gather(y0i, x0i + 1)
        v10 = gather(y0i + 1, x0i)
        v11 = gather(y0i + 1, x0i + 1)
        top = v00 * (1 - wx) + v01 * wx
        bot = v10 * (1 - wx) + v11 * wx
        return top * (1 - wy) + bot * wy
    return forward_op("grid_sample", f, [t, g])


def affine_grid(theta, out_shape, align_corners=True, name=None):
    """2D affine sampling grid [N,H,W,2] from theta [N,2,3]
    (ref: F.affine_grid)."""
    th = ensure_tensor(theta)
    N, H, W = int(out_shape[0]), int(out_shape[-2]), int(out_shape[-1])

    def f(tv):
        def axis(size):
            if align_corners:
                return jnp.linspace(-1.0, 1.0, size)
            return (jnp.arange(size) * 2 + 1) / size - 1
        ys = axis(H)
        xs = axis(W)
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")      # [H, W]
        base = jnp.stack([gx, gy, jnp.ones_like(gx)], -1)  # [H, W, 3]
        return jnp.einsum("nij,hwj->nhwi", tv, base)       # [N, H, W, 2]
    return forward_op("affine_grid", f, [th])


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1,
         name=None):
    """col2im (ref: F.fold): [N, C*kh*kw, L] -> [N, C, H, W], summing
    overlapping patches — the exact adjoint of unfold."""
    t = ensure_tensor(x)

    def pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)
    oh, ow = pair(output_sizes)
    kh, kw = pair(kernel_sizes)
    sh, sw = pair(strides)
    ph, pw = pair(paddings)
    dh, dw = pair(dilations)
    nh = (oh + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
    nw = (ow + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1

    def f(v):
        N = v.shape[0]
        C = v.shape[1] // (kh * kw)
        cols = v.reshape(N, C, kh, kw, nh, nw)
        out = jnp.zeros((N, C, oh + 2 * ph, ow + 2 * pw), v.dtype)
        for i in range(kh):       # static, small
            for j in range(kw):
                ys = i * dh + sh * jnp.arange(nh)
                xs = j * dw + sw * jnp.arange(nw)
                out = out.at[:, :, ys[:, None], xs[None, :]].add(
                    cols[:, :, i, j])
        return out[:, :, ph:ph + oh, pw:pw + ow]
    return forward_op("fold", f, [t])


def temporal_shift(x, seg_num, shift_ratio=0.25, name=None,
                   data_format="NCHW"):
    """TSM temporal shift (ref: F.temporal_shift): shift a channel fraction
    one step along the segment (time) dim in each direction."""
    t = ensure_tensor(x)
    if data_format != "NCHW":
        raise ValueError("temporal_shift supports NCHW")

    def f(v):
        NT, C, H, W = v.shape
        n = NT // seg_num
        v5 = v.reshape(n, seg_num, C, H, W)
        c1 = int(C * shift_ratio)
        c2 = int(C * 2 * shift_ratio)
        fwd = jnp.concatenate(
            [jnp.zeros_like(v5[:, :1, :c1]), v5[:, :-1, :c1]], axis=1)
        bwd = jnp.concatenate(
            [v5[:, 1:, c1:c2], jnp.zeros_like(v5[:, :1, c1:c2])], axis=1)
        return jnp.concatenate([fwd, bwd, v5[:, :, c2:]],
                               axis=2).reshape(NT, C, H, W)
    return forward_op("temporal_shift", f, [t])


def bilinear(x1, x2, weight, bias=None, name=None):
    """ref: F.bilinear — out[n, o] = x1[n]^T W[o] x2[n] (+ bias)."""
    a, b, w = ensure_tensor(x1), ensure_tensor(x2), ensure_tensor(weight)
    args = [a, b, w]
    if bias is not None:
        args.append(ensure_tensor(bias))

    def f(av, bv, wv, biasv=None):
        out = jnp.einsum("ni,oij,nj->no", av, wv, bv)
        if biasv is not None:
            out = out + biasv
        return out
    return forward_op("bilinear", f, args)


def feature_alpha_dropout(x, p=0.5, training=True, name=None):
    """Alpha dropout over whole channels (ref: feature_alpha_dropout —
    SELU-compatible noise on [N, C, ...] with per-channel masks)."""
    t = ensure_tensor(x)
    if not 0 <= p < 1:
        raise ValueError(f"feature_alpha_dropout p must be in [0,1), got {p}")
    if not training or p == 0.0:
        return t
    from ...ops.random import _next_key
    key = _next_key()
    alpha_p = -1.7580993408473766  # -scale*alpha of SELU

    def f(v):
        mask_shape = v.shape[:2] + (1,) * (v.ndim - 2)
        keep = jax.random.bernoulli(key, 1 - p, mask_shape)
        a = (1 / ((1 - p) * (1 + p * alpha_p ** 2)) ** 0.5)
        b = -a * alpha_p * p
        return (jnp.where(keep, v, alpha_p) * a + b).astype(v.dtype)
    return forward_op("feature_alpha_dropout", f, [t])
