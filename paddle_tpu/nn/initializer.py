"""Weight initializers.

Parity target: ``python/paddle/nn/initializer/`` in the reference (Constant, Normal,
TruncatedNormal, Uniform, XavierNormal/Uniform, KaimingNormal/Uniform, Assign,
Orthogonal, calculate_gain). Initializers mutate the Parameter's value via the global
splittable RNG (ops/random.py), so ``paddle.seed`` makes init deterministic.
"""

from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Parameter, Tensor
from ..ops.random import _next_key

__all__ = ["Initializer", "Constant", "Normal", "TruncatedNormal", "Uniform",
           "XavierNormal", "XavierUniform", "KaimingNormal", "KaimingUniform",
           "Assign", "Orthogonal", "Dirac", "calculate_gain", "set_global_initializer"]


def _fans(shape):
    shape = tuple(shape)
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv kernels: paddle layout [out_c, in_c, *spatial]
    receptive = int(np.prod(shape[2:]))
    return shape[1] * receptive, shape[0] * receptive


class Initializer:
    def __call__(self, param: Tensor, block=None):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value: float = 0.0):
        self.value = value

    def __call__(self, param, block=None):
        param._value = jnp.full_like(param._value, self.value)
        return param


class Normal(Initializer):
    def __init__(self, mean: float = 0.0, std: float = 1.0):
        self.mean, self.std = mean, std

    def __call__(self, param, block=None):
        z = jax.random.normal(_next_key(), param._value.shape, jnp.float32)
        param._value = (self.mean + self.std * z).astype(param._value.dtype)
        return param


class TruncatedNormal(Initializer):
    """Truncated at ±2σ (paddle default a=-2,b=2)."""

    def __init__(self, mean: float = 0.0, std: float = 1.0, a: float = -2.0,
                 b: float = 2.0):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def __call__(self, param, block=None):
        z = jax.random.truncated_normal(_next_key(), self.a, self.b,
                                        param._value.shape, jnp.float32)
        param._value = (self.mean + self.std * z).astype(param._value.dtype)
        return param


class Uniform(Initializer):
    def __init__(self, low: float = -1.0, high: float = 1.0):
        self.low, self.high = low, high

    def __call__(self, param, block=None):
        u = jax.random.uniform(_next_key(), param._value.shape, jnp.float32,
                               self.low, self.high)
        param._value = u.astype(param._value.dtype)
        return param


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain: float = 1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, param, block=None):
        fi, fo = _fans(param._value.shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        z = jax.random.normal(_next_key(), param._value.shape, jnp.float32) * std
        param._value = z.astype(param._value.dtype)
        return param


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain: float = 1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, param, block=None):
        fi, fo = _fans(param._value.shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        u = jax.random.uniform(_next_key(), param._value.shape, jnp.float32,
                               -limit, limit)
        param._value = u.astype(param._value.dtype)
        return param


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope: float = 0.0,
                 nonlinearity: str = "relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, param, block=None):
        fi, _ = _fans(param._value.shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        std = gain / math.sqrt(fi)
        z = jax.random.normal(_next_key(), param._value.shape, jnp.float32) * std
        param._value = z.astype(param._value.dtype)
        return param


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope: float = 0.0,
                 nonlinearity: str = "relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, param, block=None):
        fi, _ = _fans(param._value.shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        limit = gain * math.sqrt(3.0 / fi)
        u = jax.random.uniform(_next_key(), param._value.shape, jnp.float32,
                               -limit, limit)
        param._value = u.astype(param._value.dtype)
        return param


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, param, block=None):
        v = self.value._value if isinstance(self.value, Tensor) else jnp.asarray(self.value)
        param._value = v.astype(param._value.dtype).reshape(param._value.shape)
        return param


class Orthogonal(Initializer):
    def __init__(self, gain: float = 1.0):
        self.gain = gain

    def __call__(self, param, block=None):
        shape = param._value.shape
        rows, cols = shape[0], int(np.prod(shape[1:]))
        flat = jax.random.normal(_next_key(), (max(rows, cols), min(rows, cols)),
                                 jnp.float32)
        q, r = jnp.linalg.qr(flat)
        q = q * jnp.sign(jnp.diagonal(r))
        q = q.T if rows < cols else q
        param._value = (self.gain * q[:rows, :cols]).reshape(shape).astype(
            param._value.dtype)
        return param


class Dirac(Initializer):
    """Identity-preserving conv kernel init (ref: paddle.nn.initializer.Dirac)."""

    def __init__(self, groups: int = 1):
        self.groups = groups

    def __call__(self, param, block=None):
        shape = param._value.shape  # [out_c, in_c, *spatial]
        v = np.zeros(shape, np.float32)
        out_c, in_c = shape[0], shape[1]
        centers = tuple(s // 2 for s in shape[2:])
        per = out_c // self.groups
        for g in range(self.groups):
            for i in range(min(per, in_c)):
                v[(g * per + i, i) + centers] = 1.0
        param._value = jnp.asarray(v, param._value.dtype)
        return param


def calculate_gain(nonlinearity: str, param=None) -> float:
    recipes = {
        "sigmoid": 1.0, "linear": 1.0, "conv1d": 1.0, "conv2d": 1.0, "conv3d": 1.0,
        "conv_transpose1d": 1.0, "conv_transpose2d": 1.0, "conv_transpose3d": 1.0,
        "tanh": 5.0 / 3.0, "relu": math.sqrt(2.0),
        "selu": 3.0 / 4.0,
    }
    if nonlinearity == "leaky_relu":
        slope = 0.01 if param is None else param
        return math.sqrt(2.0 / (1 + slope ** 2))
    if nonlinearity in recipes:
        return recipes[nonlinearity]
    raise ValueError(f"unknown nonlinearity {nonlinearity}")


_global_weight_init = None
_global_bias_init = None


def set_global_initializer(weight_init, bias_init=None):
    global _global_weight_init, _global_bias_init
    _global_weight_init, _global_bias_init = weight_init, bias_init
