"""nn.Layer — the module base class.

Parity target: ``python/paddle/nn/layer/layers.py`` in the reference (class ``Layer``):
auto-registration of Parameters/sublayers via ``__setattr__``, buffers with
persistability, forward pre/post hooks, ``state_dict``/``set_state_dict``,
train/eval mode, ``apply``, named traversals. The redesign keeps the imperative
surface; under ``jit.to_static`` the layer's parameters become explicit inputs of the
compiled program (see jit/).
"""

from __future__ import annotations

import warnings
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple, Union

import numpy as np
import jax.numpy as jnp

from ..core.dtype import canonical_dtype, get_default_dtype
from ..core.tensor import Parameter, Tensor, to_tensor

__all__ = ["Layer", "ParamAttr"]


@dataclass
class ParamAttr:
    """paddle.ParamAttr parity (reference: python/paddle/base/param_attr.py)."""
    name: Optional[str] = None
    initializer: Optional[Callable] = None
    learning_rate: float = 1.0
    regularizer: Any = None
    trainable: bool = True
    do_model_average: bool = True
    need_clip: bool = True

    @staticmethod
    def _to_attr(attr):
        if attr is None:
            return ParamAttr()
        if isinstance(attr, ParamAttr):
            return attr
        if attr is False:
            return False
        if isinstance(attr, str):
            return ParamAttr(name=attr)
        if callable(attr):  # bare initializer
            return ParamAttr(initializer=attr)
        raise TypeError(f"invalid ParamAttr: {attr!r}")


class _HookHandle:
    _next_id = 0

    def __init__(self, hooks: Dict[int, Callable]):
        self._hooks = hooks
        self._id = _HookHandle._next_id
        _HookHandle._next_id += 1

    def remove(self):
        self._hooks.pop(self._id, None)


class Layer:
    def __init__(self, name_scope: Optional[str] = None, dtype=None):
        object.__setattr__(self, "_parameters", OrderedDict())
        object.__setattr__(self, "_sub_layers", OrderedDict())
        object.__setattr__(self, "_buffers", OrderedDict())
        self._non_persistable_buffer_names = set()
        self._forward_pre_hooks: Dict[int, Callable] = OrderedDict()
        self._forward_post_hooks: Dict[int, Callable] = OrderedDict()
        self.training = True
        self._dtype = canonical_dtype(dtype) or get_default_dtype()
        self._full_name = name_scope or self.__class__.__name__.lower()

    # -- registration -------------------------------------------------------
    def __setattr__(self, name: str, value: Any):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ before assigning parameters")
            params[name] = value
            self.__dict__.pop(name, None)
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call Layer.__init__ before assigning sublayers")
            layers[name] = value
            self.__dict__.pop(name, None)
        else:
            if params is not None and name in params:
                if value is None:
                    del params[name]
                elif isinstance(value, Tensor):
                    params[name] = value  # allow rebinding a plain tensor slot
                else:
                    object.__setattr__(self, name, value)
                    return
            elif buffers is not None and name in buffers:
                buffers[name] = value
            else:
                object.__setattr__(self, name, value)

    def __getattr__(self, name: str):
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(f"'{type(self).__name__}' object has no attribute {name!r}")

    def __delattr__(self, name):
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def add_parameter(self, name: str, parameter: Optional[Parameter]) -> Optional[Parameter]:
        if parameter is not None and not isinstance(parameter, Parameter):
            raise TypeError("add_parameter expects a Parameter")
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name: str, sublayer: "Layer") -> "Layer":
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def register_buffer(self, name: str, tensor: Optional[Tensor],
                        persistable: bool = True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)

    def create_parameter(self, shape, attr=None, dtype=None, is_bias: bool = False,
                         default_initializer=None) -> Union[Parameter, None]:
        """Build a Parameter per ParamAttr (ref: Layer.create_parameter +
        LayerHelper in python/paddle/base/layer_helper_base.py)."""
        from . import initializer as I

        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        dt = canonical_dtype(dtype) or self._dtype
        shape = tuple(int(s) for s in shape)
        p = Parameter(jnp.zeros(shape, dt), trainable=attr.trainable, name=attr.name)
        init = attr.initializer or default_initializer
        if init is None:
            init = I._global_bias_init if is_bias else I._global_weight_init
        if init is None:
            init = I.Constant(0.0) if is_bias else I.XavierNormal()
        init(p)
        if attr.learning_rate != 1.0:
            p.optimize_attr = {"learning_rate": attr.learning_rate}
        if attr.regularizer is not None:
            p.regularizer = attr.regularizer
        p.need_clip = attr.need_clip
        return p

    def create_tensor(self, name=None, persistable=None, dtype=None) -> Tensor:
        return to_tensor(np.zeros([0], dtype=str(canonical_dtype(dtype) or self._dtype)))

    # -- traversal ----------------------------------------------------------
    def parameters(self, include_sublayers: bool = True) -> List[Parameter]:
        return [p for _, p in self.named_parameters(
            include_sublayers=include_sublayers)]

    def named_parameters(self, prefix: str = "", include_sublayers: bool = True
                         ) -> Iterator[Tuple[str, Parameter]]:
        seen = set()
        for name, layer in self._traverse(prefix, include_sublayers):
            for pname, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                yield (f"{name}.{pname}" if name else pname), p

    def clear_gradients(self):
        """paddle.nn.Layer.clear_gradients parity."""
        for p in self.parameters():
            p.clear_grad()

    def buffers(self, include_sublayers: bool = True) -> List[Tensor]:
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix: str = "", include_sublayers: bool = True
                      ) -> Iterator[Tuple[str, Tensor]]:
        seen = set()
        for name, layer in self._traverse(prefix, include_sublayers):
            for bname, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                yield (f"{name}.{bname}" if name else bname), b

    def children(self) -> Iterator["Layer"]:
        for _, l in self.named_children():
            yield l

    def named_children(self) -> Iterator[Tuple[str, "Layer"]]:
        yield from self._sub_layers.items()

    def sublayers(self, include_self: bool = False) -> List["Layer"]:
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def named_sublayers(self, prefix: str = "", include_self: bool = False
                        ) -> Iterator[Tuple[str, "Layer"]]:
        for name, layer in self._traverse(prefix, True):
            if name == prefix and not include_self:
                continue
            yield name, layer

    def _traverse(self, prefix: str, include_sublayers: bool
                  ) -> Iterator[Tuple[str, "Layer"]]:
        yield prefix, self
        if include_sublayers:
            for name, sub in self._sub_layers.items():
                if sub is None:
                    continue
                sub_prefix = f"{prefix}.{name}" if prefix else name
                yield from sub._traverse(sub_prefix, True)

    def apply(self, fn: Callable[["Layer"], None]) -> "Layer":
        for l in self.children():
            l.apply(fn)
        fn(self)
        return self

    # -- state dict ---------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers: bool = True,
                   structured_name_prefix: str = "", use_hook: bool = True
                   ) -> Dict[str, Tensor]:
        out = destination if destination is not None else OrderedDict()
        for name, p in self.named_parameters(structured_name_prefix,
                                             include_sublayers):
            out[name] = p
        for name, layer in self._traverse(structured_name_prefix, include_sublayers):
            for bname, b in layer._buffers.items():
                if b is None or bname in layer._non_persistable_buffer_names:
                    continue
                out[(f"{name}.{bname}" if name else bname)] = b
        return out

    def set_state_dict(self, state_dict: Dict[str, Any], use_structured_name: bool = True):
        own = self.state_dict()
        missing, unexpected = [], []
        for k, v in state_dict.items():
            if k not in own:
                unexpected.append(k)
                continue
            t = own[k]
            val = v.numpy() if isinstance(v, Tensor) else np.asarray(v)
            if tuple(val.shape) != tuple(t.shape):
                raise ValueError(f"shape mismatch for {k}: {val.shape} vs {t.shape}")
            t.set_value(val.astype(t.dtype))
        for k in own:
            if k not in state_dict:
                missing.append(k)
        if missing or unexpected:
            warnings.warn(f"set_state_dict: missing={missing} unexpected={unexpected}")
        return missing, unexpected

    set_dict = set_state_dict
    load_dict = set_state_dict

    # -- modes --------------------------------------------------------------
    def train(self):
        self.training = True
        for l in self.sublayers():
            l.training = True
        return self

    def eval(self):
        self.training = False
        for l in self.sublayers():
            l.training = False
        return self

    # -- dtype / device -----------------------------------------------------
    def to(self, device=None, dtype=None, blocking=None):
        dt = canonical_dtype(dtype)
        for p in self.parameters():
            if dt is not None and p.is_floating_point():
                p._value = p._value.astype(dt)
        for b in self.buffers():
            if dt is not None and b is not None and b.is_floating_point():
                b._value = b._value.astype(dt)
        if device is not None:
            from ..core.place import get_jax_device, set_device, _current_place
            import jax
            if isinstance(device, str):
                saved = _current_place()
                place = set_device(device)
                set_device(saved)
            else:
                place = device
            dev = get_jax_device(place)
            for t in list(self.parameters()) + [b for b in self.buffers() if b is not None]:
                t._value = jax.device_put(t._value, dev)
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def half(self):
        return self.to(dtype="float16")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    # -- hooks & call -------------------------------------------------------
    def register_forward_pre_hook(self, hook) -> _HookHandle:
        h = _HookHandle(self._forward_pre_hooks)
        self._forward_pre_hooks[h._id] = hook
        return h

    def register_forward_post_hook(self, hook) -> _HookHandle:
        h = _HookHandle(self._forward_post_hooks)
        self._forward_post_hooks[h._id] = hook
        return h

    def __call__(self, *inputs, **kwargs):
        for hook in list(self._forward_pre_hooks.values()):
            res = hook(self, inputs)
            if res is not None:
                inputs = res if isinstance(res, tuple) else (res,)
        out = self.forward(*inputs, **kwargs)
        for hook in list(self._forward_post_hooks.values()):
            res = hook(self, inputs, out)
            if res is not None:
                out = res
        return out

    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def full_name(self) -> str:
        return self._full_name

    def extra_repr(self) -> str:
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, sub in self._sub_layers.items():
            sub_repr = repr(sub).split("\n")
            lines.append(f"  ({name}): " + ("\n  ".join(sub_repr)))
        main = f"{type(self).__name__}({extra}"
        if lines:
            return main + "\n" + "\n".join(lines) + "\n)"
        return main + ")"

    def __dir__(self):
        return list(super().__dir__()) + list(self._parameters) + \
            list(self._buffers) + list(self._sub_layers)
