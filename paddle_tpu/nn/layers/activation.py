"""Activation layers (parity: python/paddle/nn/layer/activation.py)."""

from __future__ import annotations

from .. import functional as F
from .. import initializer as I
from ..layer import Layer


def _wrap(fname, **fixed):
    class _Act(Layer):
        def __init__(self, *args, **kwargs):
            super().__init__()
            self._kwargs = dict(fixed)
            # positional args map onto the functional's named params in order
            fn = getattr(F, fname)
            import inspect
            params = [p for p in inspect.signature(fn).parameters if p not in ("x", "name")]
            for n, v in zip(params, args):
                self._kwargs[n] = v
            self._kwargs.update({k: v for k, v in kwargs.items() if k != "name"})

        def forward(self, x):
            return getattr(F, fname)(x, **self._kwargs)

    _Act.__name__ = fname.title().replace("_", "")
    return _Act


ReLU = _wrap("relu")
ReLU6 = _wrap("relu6")
Sigmoid = _wrap("sigmoid")
Tanh = _wrap("tanh")
GELU = _wrap("gelu")
SiLU = _wrap("silu")
Swish = _wrap("swish")
Mish = _wrap("mish")
Hardswish = _wrap("hardswish")
Hardsigmoid = _wrap("hardsigmoid")
Hardtanh = _wrap("hardtanh")
Hardshrink = _wrap("hardshrink")
Softshrink = _wrap("softshrink")
Softplus = _wrap("softplus")
Softsign = _wrap("softsign")
Tanhshrink = _wrap("tanhshrink")
LogSigmoid = _wrap("log_sigmoid")
ELU = _wrap("elu")
CELU = _wrap("celu")
SELU = _wrap("selu")
LeakyReLU = _wrap("leaky_relu")
Softmax = _wrap("softmax")
LogSoftmax = _wrap("log_softmax")
Maxout = _wrap("maxout")
ThresholdedReLU = _wrap("thresholded_relu")
GLU = _wrap("glu")


class RReLU(Layer):
    """Needs self.training forwarded (random slopes only while training)."""

    def __init__(self, lower=0.125, upper=1.0 / 3.0, name=None):
        super().__init__()
        self.lower = lower
        self.upper = upper

    def forward(self, x):
        return F.rrelu(x, self.lower, self.upper, training=self.training)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self.data_format = data_format
        self.weight = self.create_parameter(
            [num_parameters], attr=weight_attr,
            default_initializer=I.Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight, self.data_format)
