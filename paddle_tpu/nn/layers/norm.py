"""Norm layers (parity: python/paddle/nn/layer/norm.py)."""

from __future__ import annotations

import numpy as np

from ...core.tensor import Parameter, Tensor, to_tensor
from .. import functional as F
from .. import initializer as I
from ..layer import Layer


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCHW", use_global_stats=None,
                 name=None):
        super().__init__()
        self.num_features = num_features
        self.momentum = momentum
        self.epsilon = epsilon
        self.data_format = data_format
        self.use_global_stats = use_global_stats
        self.weight = self.create_parameter(
            [num_features], attr=weight_attr, default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter([num_features], attr=bias_attr,
                                          is_bias=True)
        self.register_buffer("_mean", to_tensor(np.zeros(num_features, np.float32)))
        self.register_buffer("_variance", to_tensor(np.ones(num_features, np.float32)))

    def forward(self, x):
        return F.batch_norm(x, self._mean, self._variance, self.weight, self.bias,
                            training=self.training, momentum=self.momentum,
                            epsilon=self.epsilon, data_format=self.data_format,
                            use_global_stats=self.use_global_stats)

    def extra_repr(self):
        return f"num_features={self.num_features}, momentum={self.momentum}"


class BatchNorm(_BatchNormBase):
    """Legacy paddle.nn.BatchNorm (acts like 2-D by default)."""

    def __init__(self, num_channels, act=None, momentum=0.9, epsilon=1e-5,
                 param_attr=None, bias_attr=None, data_layout="NCHW",
                 use_global_stats=None, **kwargs):
        super().__init__(num_channels, momentum, epsilon, param_attr, bias_attr,
                         data_layout, use_global_stats)
        self._act = act

    def forward(self, x):
        out = super().forward(x)
        if self._act:
            out = getattr(F, self._act)(out)
        return out


class BatchNorm1D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCL", use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr, bias_attr,
                         data_format, use_global_stats)


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCDHW", use_global_stats=None,
                 name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr, bias_attr,
                         data_format, use_global_stats)


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica batch norm. Under jit+GSPMD the batch axis is globally sharded
    and XLA computes global statistics automatically, so this is BatchNorm with a
    documented contract (ref capability: paddle.nn.SyncBatchNorm / sync_batch_norm op).
    """

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, cls):
            new = cls(layer.num_features, layer.momentum, layer.epsilon,
                      data_format=layer.data_format)
            new.weight.set_value(layer.weight.numpy())
            new.bias.set_value(layer.bias.numpy())
            new._mean.set_value(layer._mean.numpy())
            new._variance.set_value(layer._variance.numpy())
            return new
        for name, sub in list(layer._sub_layers.items()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self.normalized_shape = list(normalized_shape)
        self.epsilon = epsilon
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                self.normalized_shape, attr=weight_attr,
                default_initializer=I.Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(self.normalized_shape, attr=bias_attr,
                                              is_bias=True)

    def forward(self, x):
        return F.layer_norm(x, self.normalized_shape, self.weight, self.bias,
                            self.epsilon)

    def extra_repr(self):
        return f"normalized_shape={self.normalized_shape}"


class RMSNorm(Layer):
    """TPU-first RMSNorm layer (ref capability: paddle.incubate fused_rms_norm)."""

    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None, name=None):
        super().__init__()
        self.epsilon = epsilon
        self.weight = self.create_parameter(
            [hidden_size], attr=weight_attr, default_initializer=I.Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, self.epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self.num_groups = num_groups
        self.num_channels = num_channels
        self.epsilon = epsilon
        self.data_format = data_format
        self.weight = None if weight_attr is False else self.create_parameter(
            [num_channels], attr=weight_attr, default_initializer=I.Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            [num_channels], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self.num_groups, self.epsilon, self.weight, self.bias,
                            self.data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self.epsilon = epsilon
        self.data_format = data_format
        if weight_attr is False:
            self.weight = None
            self.bias = None
        else:
            self.weight = self.create_parameter(
                [num_features], attr=weight_attr, default_initializer=I.Constant(1.0))
            self.bias = self.create_parameter([num_features], attr=bias_attr,
                                              is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias,
                               eps=self.epsilon, data_format=self.data_format)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW",
                 name=None):
        super().__init__()
        self.size = size
        self.alpha = alpha
        self.beta = beta
        self.k = k
        self.data_format = data_format

    def forward(self, x):
        return F.local_response_norm(x, self.size, self.alpha, self.beta, self.k,
                                     self.data_format)


class SpectralNorm(Layer):
    def __init__(self, weight_shape, dim=0, power_iters=1, epsilon=1e-12, name=None):
        super().__init__()
        self.dim = dim
        self.power_iters = power_iters
        self.epsilon = epsilon
        h = weight_shape[dim]
        w = int(np.prod(weight_shape)) // h
        self.weight_u = self.create_parameter(
            [h], default_initializer=I.Normal(0.0, 1.0))
        self.weight_u.stop_gradient = True
        self.weight_v = self.create_parameter(
            [w], default_initializer=I.Normal(0.0, 1.0))
        self.weight_v.stop_gradient = True

    def forward(self, weight):
        return F.spectral_norm(weight, self.weight_u, self.weight_v, self.dim,
                               self.power_iters, self.epsilon)
