"""Pooling layers (parity: python/paddle/nn/layer/pooling.py)."""

from __future__ import annotations

from .. import functional as F
from ..layer import Layer


class _Pool(Layer):
    _fn = None
    _default_fmt = "NCHW"

    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 return_mask=False, exclusive=True, divisor_override=None,
                 data_format=None, name=None):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.ceil_mode = ceil_mode
        self.return_mask = return_mask
        self.exclusive = exclusive
        self.divisor_override = divisor_override
        self.data_format = data_format or self._default_fmt

    def forward(self, x):
        fn = getattr(F, self._fn)
        kwargs = dict(stride=self.stride, padding=self.padding,
                      ceil_mode=self.ceil_mode, data_format=self.data_format)
        if self._fn.startswith("max"):
            kwargs["return_mask"] = self.return_mask
        else:
            kwargs["exclusive"] = self.exclusive
            kwargs["divisor_override"] = self.divisor_override
        return fn(x, self.kernel_size, **kwargs)


class MaxPool1D(_Pool):
    _fn = "max_pool1d"
    _default_fmt = "NCL"


class MaxPool2D(_Pool):
    _fn = "max_pool2d"


class MaxPool3D(_Pool):
    _fn = "max_pool3d"
    _default_fmt = "NCDHW"


class AvgPool1D(_Pool):
    _fn = "avg_pool1d"
    _default_fmt = "NCL"


class AvgPool2D(_Pool):
    _fn = "avg_pool2d"


class AvgPool3D(_Pool):
    _fn = "avg_pool3d"
    _default_fmt = "NCDHW"


class _AdaptivePool(Layer):
    _fn = None

    def __init__(self, output_size, return_mask=False, data_format=None, name=None):
        super().__init__()
        self.output_size = output_size
        self.return_mask = return_mask
        self.data_format = data_format

    def forward(self, x):
        fn = getattr(F, self._fn)
        if self._fn.startswith("adaptive_max"):
            # data_format is not part of the reference AdaptiveMaxPool API,
            # but the layout pass (nn/layout.py) sets it on the layer — the
            # functional accepts it, so it must flow through here too
            return fn(x, self.output_size, return_mask=self.return_mask,
                      data_format=self.data_format)
        return fn(x, self.output_size, data_format=self.data_format)


class AdaptiveAvgPool1D(_AdaptivePool):
    _fn = "adaptive_avg_pool1d"


class AdaptiveAvgPool2D(_AdaptivePool):
    _fn = "adaptive_avg_pool2d"


class AdaptiveAvgPool3D(_AdaptivePool):
    _fn = "adaptive_avg_pool3d"


class AdaptiveMaxPool1D(_AdaptivePool):
    _fn = "adaptive_max_pool1d"


class AdaptiveMaxPool2D(_AdaptivePool):
    _fn = "adaptive_max_pool2d"


class AdaptiveMaxPool3D(_AdaptivePool):
    _fn = "adaptive_max_pool3d"
