"""Recurrent layers.

Parity target: ``python/paddle/nn/layer/rnn.py`` (SimpleRNN/LSTM/GRU + cells, RNN
wrapper, birnn). TPU redesign: the time loop is a single ``jax.lax.scan`` inside one
traced op — XLA compiles the whole recurrence (no per-step Python dispatch, which is
the part of Paddle's dygraph RNN that would be slowest on TPU).
"""

from __future__ import annotations

import math
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp

from ...core.tensor import Tensor
from ...ops._helpers import ensure_tensor, forward_op
from .. import initializer as I
from ..layer import Layer


def _cell_params(layer: Layer, input_size: int, hidden_size: int, gates: int,
                 suffix: str = ""):
    std = 1.0 / math.sqrt(hidden_size)
    u = I.Uniform(-std, std)
    wi = layer.create_parameter([gates * hidden_size, input_size],
                                default_initializer=u)
    wh = layer.create_parameter([gates * hidden_size, hidden_size],
                                default_initializer=u)
    bi = layer.create_parameter([gates * hidden_size], is_bias=True,
                                default_initializer=u)
    bh = layer.create_parameter([gates * hidden_size], is_bias=True,
                                default_initializer=u)
    layer.add_parameter(f"weight_ih{suffix}", wi)
    layer.add_parameter(f"weight_hh{suffix}", wh)
    layer.add_parameter(f"bias_ih{suffix}", bi)
    layer.add_parameter(f"bias_hh{suffix}", bh)
    return wi, wh, bi, bh


def _simple_rnn_step(activation):
    act = jnp.tanh if activation == "tanh" else jax.nn.relu

    def step(x, h, wi, wh, bi, bh):
        return act(x @ wi.T + bi + h @ wh.T + bh)

    return step


def _lstm_step(x, hc, wi, wh, bi, bh):
    h, c = hc
    gates = x @ wi.T + bi + h @ wh.T + bh
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
    g = jnp.tanh(g)
    c2 = f * c + i * g
    h2 = o * jnp.tanh(c2)
    return h2, c2


def _gru_step(x, h, wi, wh, bi, bh):
    xr = x @ wi.T + bi
    hr = h @ wh.T + bh
    xz, xr_, xn = jnp.split(xr, 3, axis=-1)
    hz, hr_, hn = jnp.split(hr, 3, axis=-1)
    z = jax.nn.sigmoid(xz + hz)
    r = jax.nn.sigmoid(xr_ + hr_)
    n = jnp.tanh(xn + r * hn)
    return (1 - z) * n + z * h


class SimpleRNNCell(Layer):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.hidden_size = hidden_size
        self.activation = activation
        _cell_params(self, input_size, hidden_size, 1)

    def forward(self, inputs, states=None):
        inputs = ensure_tensor(inputs)
        if states is None:
            from ...ops.creation import zeros
            states = zeros([inputs.shape[0], self.hidden_size], inputs.dtype)
        step = _simple_rnn_step(self.activation)
        out = forward_op("simple_rnn_cell", step,
                         [inputs, states, self.weight_ih, self.weight_hh,
                          self.bias_ih, self.bias_hh])
        return out, out


class LSTMCell(Layer):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.hidden_size = hidden_size
        _cell_params(self, input_size, hidden_size, 4)

    def forward(self, inputs, states=None):
        inputs = ensure_tensor(inputs)
        if states is None:
            from ...ops.creation import zeros
            z = zeros([inputs.shape[0], self.hidden_size], inputs.dtype)
            states = (z, z.clone())
        h, c = states

        def impl(x, hv, cv, wi, wh, bi, bh):
            return _lstm_step(x, (hv, cv), wi, wh, bi, bh)

        h2, c2 = forward_op("lstm_cell", impl,
                            [inputs, h, c, self.weight_ih, self.weight_hh,
                             self.bias_ih, self.bias_hh])
        return h2, (h2, c2)


class GRUCell(Layer):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.hidden_size = hidden_size
        _cell_params(self, input_size, hidden_size, 3)

    def forward(self, inputs, states=None):
        inputs = ensure_tensor(inputs)
        if states is None:
            from ...ops.creation import zeros
            states = zeros([inputs.shape[0], self.hidden_size], inputs.dtype)
        out = forward_op("gru_cell", _gru_step,
                         [inputs, states, self.weight_ih, self.weight_hh,
                          self.bias_ih, self.bias_hh])
        return out, out


class _RNNBase(Layer):
    """Multi-layer (bi)directional recurrence compiled as lax.scan per layer."""

    MODE = None  # "RNN_TANH" | "RNN_RELU" | "LSTM" | "GRU"

    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.activation = activation
        self.bidirect = direction in ("bidirect", "bidirectional")
        self.num_directions = 2 if self.bidirect else 1
        gates = {"LSTM": 4, "GRU": 3}.get(self.MODE, 1)
        self._param_names = []
        for layer_i in range(num_layers):
            for d in range(self.num_directions):
                in_sz = input_size if layer_i == 0 else \
                    hidden_size * self.num_directions
                sfx = f"_l{layer_i}" + ("_reverse" if d else "")
                _cell_params(self, in_sz, hidden_size, gates, sfx)
                self._param_names.append(sfx)

    def _step_fn(self):
        if self.MODE == "LSTM":
            return _lstm_step
        if self.MODE == "GRU":
            return _gru_step
        return _simple_rnn_step("relu" if self.MODE == "RNN_RELU" else "tanh")

    def forward(self, inputs, initial_states=None, sequence_length=None):
        inputs = ensure_tensor(inputs)
        is_lstm = self.MODE == "LSTM"
        L, D, H = self.num_layers, self.num_directions, self.hidden_size
        batch_axis = 1 if self.time_major else 0
        b = inputs.shape[batch_axis]

        if initial_states is None:
            from ...ops.creation import zeros
            h0 = zeros([L * D, b, H], inputs.dtype)
            initial_states = (h0, h0.clone()) if is_lstm else h0

        params = []
        for sfx in self._param_names:
            params += [getattr(self, "weight_ih" + sfx),
                       getattr(self, "weight_hh" + sfx),
                       getattr(self, "bias_ih" + sfx),
                       getattr(self, "bias_hh" + sfx)]

        step = self._step_fn()
        time_major = self.time_major
        dropout = self.dropout if self.training else 0.0
        drop_keys = None
        if dropout > 0 and L > 1:
            from ...ops.random import _next_key
            drop_keys = [_next_key() for _ in range(L - 1)]

        state_args = list(initial_states) if is_lstm else [initial_states]

        def impl(x, *flat):
            if is_lstm:
                h0v, c0v = flat[0], flat[1]
                pvals = flat[2:]
            else:
                h0v = flat[0]
                pvals = flat[1:]
            if not time_major:
                x = jnp.swapaxes(x, 0, 1)  # [T, B, I]
            layer_in = x
            last_h, last_c = [], []
            for li in range(L):
                dir_outs = []
                for d in range(D):
                    pi = (li * D + d) * 4
                    wi, wh, bi, bh = pvals[pi:pi + 4]
                    sl = li * D + d
                    if is_lstm:
                        init = (h0v[sl], c0v[sl])

                        def body(carry, xt, wi=wi, wh=wh, bi=bi, bh=bh):
                            h2, c2 = step(xt, carry, wi, wh, bi, bh)
                            return (h2, c2), h2
                    else:
                        init = h0v[sl]

                        def body(carry, xt, wi=wi, wh=wh, bi=bi, bh=bh):
                            h2 = step(xt, carry, wi, wh, bi, bh)
                            return h2, h2
                    seq = jnp.flip(layer_in, 0) if d == 1 else layer_in
                    final, outs = jax.lax.scan(body, init, seq)
                    if d == 1:
                        outs = jnp.flip(outs, 0)
                    dir_outs.append(outs)
                    if is_lstm:
                        last_h.append(final[0])
                        last_c.append(final[1])
                    else:
                        last_h.append(final)
                layer_in = jnp.concatenate(dir_outs, axis=-1) if D == 2 else dir_outs[0]
                if dropout > 0 and li < L - 1 and drop_keys is not None:
                    keep = jax.random.bernoulli(drop_keys[li], 1 - dropout,
                                                layer_in.shape)
                    layer_in = jnp.where(keep, layer_in / (1 - dropout), 0.0)
            out = layer_in if time_major else jnp.swapaxes(layer_in, 0, 1)
            hN = jnp.stack(last_h)
            if is_lstm:
                return out, hN, jnp.stack(last_c)
            return out, hN

        res = forward_op(f"rnn_{self.MODE}", impl, [inputs] + state_args + params)
        if is_lstm:
            out, h, c = res
            return out, (h, c)
        out, h = res
        return out, h


class SimpleRNN(_RNNBase):
    MODE = "RNN_TANH"

    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, activation="tanh", **kwargs):
        if activation == "relu":
            self.MODE = "RNN_RELU"
        super().__init__(input_size, hidden_size, num_layers, direction, time_major,
                         dropout, activation, **kwargs)


class LSTM(_RNNBase):
    MODE = "LSTM"


class GRU(_RNNBase):
    MODE = "GRU"


class RNN(Layer):
    """Wrapper running a cell over time (ref: paddle.nn.RNN)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None, **kwargs):
        from ...ops.manipulation import unbind, stack
        axis = 0 if self.time_major else 1
        steps = unbind(inputs, axis)
        if self.is_reverse:
            steps = steps[::-1]
        outs = []
        states = initial_states
        for xt in steps:
            out, states = self.cell(xt, states)
            outs.append(out)
        if self.is_reverse:
            outs = outs[::-1]
        return stack(outs, axis), states


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, False, time_major)
        self.rnn_bw = RNN(cell_bw, True, time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ...ops.manipulation import concat
        st_fw, st_bw = (initial_states if initial_states is not None
                        else (None, None))
        out_fw, s_fw = self.rnn_fw(inputs, st_fw)
        out_bw, s_bw = self.rnn_bw(inputs, st_bw)
        from ...ops import concat as cat
        return cat([out_fw, out_bw], axis=-1), (s_fw, s_bw)
