"""NHWC (channels-last) layout pass for conv nets.

Parity/perf target: TPU convolutions want channels-last — the channel dim
maps onto the 128-wide lane dimension of the MXU, and XLA inserts transposes
around every conv when fed NCHW (the reference keeps NCHW because cuDNN
prefers it; on TPU that default is the wrong one and costs real throughput —
the ResNet-50 bench row). The pass converts a model to run channels-last
internally while keeping the user-facing NCHW contract:

* every layout-bearing layer's ``data_format`` attribute is flipped in place
  (``NCL``→``NLC``, ``NCHW``→``NHWC``, ``NCDHW``→``NDHWC``) — conv/norm
  weights are NOT permuted: conv weights keep paddle's ``[O, I/groups, *k]``
  storage layout and the conv functional transposes per ``data_format`` at
  trace time, where XLA folds the transpose into the executable's weight
  layout assignment (zero per-step cost, and state_dicts stay
  NCHW-compatible for checkpoint round-trips);
* :class:`ChannelsLast` wraps the converted net, transposing 4-D inputs
  NCHW→NHWC once at the boundary and 4-D outputs back, so callers (and
  DataLoaders) keep feeding NCHW.

Scope: safe for nets whose cross-layout dataflow is per-channel (conv, norm,
pooling, activations, elementwise) and whose flattens happen after global
pooling (spatial 1x1 — identical element order in both layouts): the ResNet/
VGG-classifier-free/MobileNet families. Nets that reshape spatial maps
mid-network (detection heads) need their reshapes made layout-aware first.
"""

from __future__ import annotations

from typing import Any

from ..core.tensor import Tensor
from .layer import Layer

__all__ = ["ChannelsLast", "to_channels_last", "to_channels_first"]

_TO_LAST = {"NCL": "NLC", "NCHW": "NHWC", "NCDHW": "NDHWC"}
_TO_FIRST = {v: k for k, v in _TO_LAST.items()}
# adaptive pools default data_format=None (meaning channels-first); infer
# the rank from the functional they dispatch to
_RANK_LAST = {"1d": "NLC", "2d": "NHWC", "3d": "NDHWC"}


def _flip(layer: Layer, table) -> int:
    n = 0
    for sub in layer.sublayers(include_self=True):
        df = getattr(sub, "data_format", "missing")
        if df == "missing":
            continue
        if isinstance(df, str) and df in table:
            sub.data_format = table[df]
            n += 1
        elif df is None and table is _TO_LAST:
            fn = getattr(sub, "_fn", "") or ""
            for suffix, fmt in _RANK_LAST.items():
                if fn.endswith(suffix):
                    sub.data_format = fmt
                    n += 1
                    break
    return n


def to_channels_last(layer: Layer) -> Layer:
    """In-place: flip every layout-bearing sublayer to channels-last.
    Returns the same layer (conversion count is not exposed — a net with no
    layout-bearing layers converts to itself)."""
    _flip(layer, _TO_LAST)
    return layer


def to_channels_first(layer: Layer) -> Layer:
    """Inverse of :func:`to_channels_last` (undo, e.g. before jit.save of an
    NCHW inference artifact)."""
    _flip(layer, _TO_FIRST)
    return layer


def _nhwc(x):
    from ..ops.manipulation import transpose
    return transpose(x, [0, 2, 3, 1])


def _nchw(x):
    from ..ops.manipulation import transpose
    return transpose(x, [0, 3, 1, 2])


def _map_spatial(obj: Any, fn):
    if isinstance(obj, Tensor):
        return fn(obj) if obj.ndim == 4 else obj
    if isinstance(obj, dict):
        return {k: _map_spatial(v, fn) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_map_spatial(v, fn) for v in obj)
    return obj


class ChannelsLast(Layer):
    """Boundary wrapper: NCHW in, NCHW out, channels-last inside.

        net = ChannelsLast(resnet50())      # converts in place and wraps
        loss = loss_fn(net(x_nchw), y)      # convs run NHWC on the MXU

    4-D inputs are transposed to NHWC once per step; 4-D outputs (feature
    maps from ``feature_only`` backbones) are transposed back — under jit
    both boundary transposes fuse with their neighbors. Non-4-D outputs
    (logits) pass through. ``state_dict``/``set_state_dict`` delegate to the
    wrapped net so checkpoints interchange with the NCHW model.
    """

    def __init__(self, net: Layer):
        super().__init__()
        self.net = to_channels_last(net)

    def forward(self, *inputs):
        ins = [_map_spatial(x, _nhwc) for x in inputs]
        return _map_spatial(self.net(*ins), _nchw)

    # checkpoint interchange with the unwrapped NCHW model
    def state_dict(self, *args, **kwargs):
        return self.net.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self.net.set_state_dict(state_dict, *args, **kwargs)

    set_dict = set_state_dict
    load_dict = set_state_dict
