"""paddle.nn.quant parity: the weight-only quantized inference surface.

Parity target: ``python/paddle/nn/quant/quantized_linear.py``
(weight_only_linear / WeightOnlyLinear, llm_int8_linear) — the user-facing
knob that turns trained fp Linears into int8-weight inference layers.

TPU lowering: the Pallas stream-dequant kernel (``kernels/quant_matmul``)
on TPU backends — HBM traffic for weights drops 2x vs bf16 and the dequant
happens in VMEM — with an XLA dequant-matmul fallback elsewhere (identical
numerics). ``quantize_linears`` walks a model and swaps every ``nn.Linear``
in place, the one-call migration path the reference's
``paddle.nn.quant.weight_quantize`` workflow provides.
"""

from __future__ import annotations

import numpy as np

from ..core.tensor import to_tensor
from ..ops.quant import weight_only_linear, weight_quantize
from .layer import Layer

__all__ = ["WeightOnlyLinear", "quantize_linears"]


class WeightOnlyLinear(Layer):
    """Inference Linear with int8 weights + per-output-channel scales
    (ref: paddle.nn.quant.WeightOnlyLinear)."""

    def __init__(self, weight, scale, bias=None, weight_dtype="int8"):
        super().__init__()
        from ..ops._helpers import ensure_tensor
        self.weight = ensure_tensor(weight)
        self.weight_scale = ensure_tensor(scale)
        self.bias = ensure_tensor(bias) if bias is not None else None
        self.weight_dtype = weight_dtype
        self.in_features = int(self.weight.shape[0])
        self.out_features = int(self.weight.shape[1])

    @classmethod
    def from_linear(cls, linear) -> "WeightOnlyLinear":
        q, s = weight_quantize(linear.weight)
        return cls(q, s, linear.bias)

    def forward(self, x):
        return weight_only_linear(x, self.weight, self.weight_scale,
                                  bias=self.bias,
                                  weight_dtype=self.weight_dtype)

    def extra_repr(self):
        return (f"in_features={self.in_features}, "
                f"out_features={self.out_features}, int8")


def quantize_linears(model: Layer, min_features: int = 1) -> int:
    """Swap every ``nn.Linear`` sublayer for a :class:`WeightOnlyLinear`
    in place; returns the count swapped. ``min_features`` skips tiny
    projections where the int8 stream buys nothing."""
    from .layers.common import Linear
    swapped = 0

    # walk the sublayer tree via the Layer registry
    def walk(layer):
        nonlocal swapped
        for key, sub in list(getattr(layer, "_sub_layers", {}).items()):
            if isinstance(sub, Linear) and \
                    sub.in_features >= min_features:
                layer._sub_layers[key] = WeightOnlyLinear.from_linear(sub)
                setattr(layer, key, layer._sub_layers[key])
                swapped += 1
            else:
                walk(sub)
    walk(model)
    return swapped
