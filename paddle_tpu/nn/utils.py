"""paddle.nn.utils (parity: python/paddle/nn/utils/)."""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor, _wrap_value
from .clip import clip_grad_norm_  # noqa: F401

__all__ = ["clip_grad_norm_", "parameters_to_vector", "vector_to_parameters",
           "weight_norm", "remove_weight_norm", "spectral_norm"]


def parameters_to_vector(parameters, name=None) -> Tensor:
    vals = [p._value.reshape(-1) for p in parameters]
    return _wrap_value(jnp.concatenate(vals))


def vector_to_parameters(vec: Tensor, parameters, name=None):
    offset = 0
    for p in parameters:
        n = int(np.prod(p._value.shape))
        p.set_value(vec._value[offset:offset + n].reshape(p._value.shape))
        offset += n


def weight_norm(layer, name="weight", dim=0):
    """Reparametrize weight = g * v/||v|| (ref: paddle.nn.utils.weight_norm).
    Implemented as a forward-pre-hook recomputing the weight."""
    w = getattr(layer, name)
    axes = tuple(i for i in range(w.ndim) if i != dim)
    from ..core.tensor import Parameter

    g = Parameter(jnp.linalg.norm(w._value, axis=axes, keepdims=True))
    v = Parameter(w._value)
    # the original weight is replaced by the reparam: drop it from _parameters so it
    # no longer reaches parameters()/state_dict() (paddle deletes it at setup too)
    del layer._parameters[name]
    layer.add_parameter(name + "_g", g)
    layer.add_parameter(name + "_v", v)

    def compute():
        # rebuild the weight from the reparam each call so grads flow to g and v
        from ..ops import divide, multiply
        return multiply(v, divide(g, _clip_norm_tensor(v, axes)))

    def hook(l, inputs):
        object.__setattr__(l, name, compute())
        return None

    object.__setattr__(layer, name, compute())

    h = layer.register_forward_pre_hook(hook)
    layer._weight_norm_hook = h
    return layer


def _clip_norm_tensor(v, axes):
    from ..core.dispatch import forward_op
    return forward_op("wn_norm",
                      lambda x: jnp.maximum(
                          jnp.linalg.norm(x, axis=axes, keepdims=True), 1e-12), [v])


def remove_weight_norm(layer, name="weight"):
    if hasattr(layer, "_weight_norm_hook"):
        layer._weight_norm_hook.remove()
        del layer._weight_norm_hook
    g = layer._parameters.pop(name + "_g", None)
    v = layer._parameters.pop(name + "_v", None)
    if g is not None and v is not None:
        norm = jnp.linalg.norm(v._value, axis=tuple(
            i for i in range(v.ndim) if g._value.shape[i] == 1), keepdims=True)
        from ..core.tensor import Parameter
        layer.__dict__.pop(name, None)  # drop the computed-weight attribute
        layer._parameters[name] = Parameter(g._value * v._value / jnp.maximum(norm, 1e-12))
    return layer


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12, dim=None):
    """ref: paddle.nn.utils.spectral_norm — power-iteration reparam as a pre-hook."""
    w = getattr(layer, name)
    if dim is None:
        dim = 0
    h = w.shape[dim]
    wmat_cols = int(np.prod(w.shape)) // h
    import jax
    from ..ops.random import _next_key
    from ..core.tensor import Parameter

    u0 = jax.random.normal(_next_key(), (h,), jnp.float32)
    layer.register_buffer(name + "_u", _wrap_value(u0 / jnp.linalg.norm(u0)))
    v_param = Parameter(w._value)
    del layer._parameters[name]  # replaced by the reparam (see weight_norm)
    layer.add_parameter(name + "_orig", v_param)

    def hook(l, inputs):
        from ..core.dispatch import forward_op
        u = getattr(l, name + "_u")

        def impl(wv, uv):
            wm = jnp.moveaxis(wv, dim, 0).reshape(h, -1)
            for _ in range(n_power_iterations):
                vv = wm.T @ uv
                vv = vv / jnp.maximum(jnp.linalg.norm(vv), eps)
                uv = wm @ vv
                uv = uv / jnp.maximum(jnp.linalg.norm(uv), eps)
            sigma = uv @ wm @ vv
            return wv / sigma, uv

        new_w, new_u = forward_op("spectral_norm_reparam", impl, [v_param, u])
        u.set_value(new_u.numpy())
        object.__setattr__(l, name, new_w)
        return None

    object.__setattr__(layer, name, _wrap_value(w._value))
    layer.register_forward_pre_hook(hook)
    return layer
