"""The op surface.

The ops.yaml-equivalent single source of truth lives in ``core.dispatch.OP_REGISTRY``;
these modules populate it and patch methods onto Tensor (mirroring how the reference's
``python/paddle/tensor/__init__.py`` assembles the tensor namespace).
"""

import types as _types

from . import (array, creation, decode, extended, extras, legacy, linalg,
               logic, manipulation, math, quant, random, search, sequence,
               sets, special, windows)

_EXCLUDE = {"Tensor", "Parameter", "to_tensor", "ensure_tensor", "forward_op",
            "register_op", "patch_methods", "unary_factory", "binary_factory",
            "axes_arg", "canonical_dtype", "get_default_dtype", "get_jax_device",
            "Generator", "default_generator", "OP_REGISTRY"}


def _export(module):
    from ..core.dispatch import OP_REGISTRY, register_op
    names = []
    for k, v in vars(module).items():
        if k.startswith("_") or isinstance(v, _types.ModuleType) or k in _EXCLUDE:
            continue
        if getattr(v, "__module__", "") == "typing":
            continue  # leaked `from typing import ...` names are not ops
        globals()[k] = v
        names.append(k)
        # complete the ops.yaml-equivalent schema registry (single source of
        # truth for the surface: every public op is registered with its doc,
        # whether factory-generated or hand-written)
        if callable(v) and not isinstance(v, type) and k not in OP_REGISTRY:
            register_op(k, v, doc=(v.__doc__ or "").strip(), public=v)
        elif callable(v) and k in OP_REGISTRY and OP_REGISTRY[k].public is None:
            OP_REGISTRY[k].public = v
    return names


__all__ = sorted(set(
    _export(creation) + _export(math) + _export(manipulation) + _export(linalg)
    + _export(logic) + _export(search) + _export(random) + _export(extras)
    + _export(extended) + _export(sets) + _export(special)
    + _export(windows) + _export(sequence) + _export(quant)
    + _export(decode) + _export(legacy) + _export(array)))
# the inplace generator reads the assembled surface above — import it last
from . import inplace  # noqa: E402
__all__ = sorted(set(__all__ + _export(inplace)))
from .random import Generator, default_generator  # noqa: E402
from .creation import to_tensor  # noqa: E402
