"""Shared plumbing for the op surface modules.

Parity target: the argument-normalization layer of ``python/paddle/tensor/*.py`` in the
reference — each public op is a thin wrapper that canonicalizes arguments and enters the
dispatcher (see core/dispatch.py for the TPU redesign of the hot path below it).
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import numpy as np
import jax.numpy as jnp

from ..core.dispatch import forward_op, register_op
from ..core.tensor import Tensor, to_tensor

__all__ = ["ensure_tensor", "unary_factory", "binary_factory", "patch_methods",
           "forward_op", "register_op", "Tensor", "axes_arg"]


def ensure_tensor(x) -> Tensor:
    return x if isinstance(x, Tensor) else to_tensor(x)


def axes_arg(axis):
    """Canonicalize paddle-style axis arguments (int | list | tuple | None)."""
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    if isinstance(axis, Tensor):
        return tuple(int(a) for a in np.asarray(axis._value).reshape(-1))
    return int(axis)


def unary_factory(name: str, jfn: Callable, doc: str = ""):
    register_op(name, jfn, doc, category="unary")

    def op(x, name=None):
        return forward_op(op.__name__, jfn, [ensure_tensor(x)])

    op.__name__ = name
    op.__qualname__ = name
    op.__doc__ = doc or f"Elementwise {name} (jnp-backed; Paddle API parity)."
    return op


def binary_factory(name: str, jfn: Callable, doc: str = ""):
    register_op(name, jfn, doc, category="binary")

    def op(x, y, name=None):
        return forward_op(op.__name__, jfn, [ensure_tensor(x), ensure_tensor(y)])

    op.__name__ = name
    op.__qualname__ = name
    op.__doc__ = doc or f"Elementwise broadcasting {name} (jnp-backed; Paddle API parity)."
    return op


def patch_methods(pairs: Sequence[tuple]):
    """Attach (method_name, function) pairs to Tensor, mirroring Paddle's
    monkey-patching of python/paddle/tensor/* onto the C++ tensor class."""
    for mname, fn in pairs:
        setattr(Tensor, mname, fn)
