"""TensorArray ops (ref: python/paddle/tensor/array.py — create_array /
array_read / array_write / array_length, plus the
tensor_array_to_tensor fusion op).

TPU stance: the reference's LoDTensorArray is a dynamic-length list the
static-graph while_op threads through steps. Under this framework's jit
tiers, loops are ``lax.scan``/``while_loop`` with stacked carries — so the
eager TensorArray here is a plain Python list (exactly what the reference's
dygraph mode does too), and ``tensor_array_to_tensor`` is the bridge that
stacks/concats it into the static world."""

from __future__ import annotations

import numpy as np

from ._helpers import Tensor, ensure_tensor, forward_op

__all__ = ["create_array", "array_read", "array_write", "array_length",
           "tensor_array_to_tensor"]


def create_array(dtype="float32", initialized_list=None, name=None):
    """New TensorArray (a list; ref: paddle.tensor.create_array)."""
    arr = [ensure_tensor(t) for t in (initialized_list or [])]
    return arr


def array_write(x, i, array=None, name=None):
    """Write ``x`` at position ``i`` (extends the array as upstream's
    write-past-end does)."""
    if array is None:
        array = []
    idx = int(i) if not isinstance(i, Tensor) else int(np.asarray(i._value))
    t = ensure_tensor(x)
    while len(array) <= idx:
        array.append(None)
    array[idx] = t
    return array


def array_read(array, i, name=None):
    """Read position ``i``."""
    idx = int(i) if not isinstance(i, Tensor) else int(np.asarray(i._value))
    return array[idx]


def array_length(array, name=None):
    """Length of the array as a Tensor (ref: paddle.tensor.array_length)."""
    from ..core.tensor import to_tensor
    return to_tensor(np.int64(len(array)))


def tensor_array_to_tensor(array, axis: int = 0, use_stack: bool = False,
                           name=None):
    """Stack or concat the array into one Tensor + per-element sizes (ref:
    tensor_array_to_tensor_op)."""
    ts = [ensure_tensor(t) for t in array]
    from ..core.tensor import to_tensor
    if use_stack:
        from .manipulation import stack
        out = stack(ts, axis=axis)
        sizes = np.ones(len(ts), np.int64)
    else:
        from .manipulation import concat
        out = concat(ts, axis=axis)
        sizes = np.asarray([int(t.shape[axis]) for t in ts], np.int64)
    return out, to_tensor(sizes)
