"""Tensor creation ops.

Parity target: ``python/paddle/tensor/creation.py`` in the reference. Creation runs
outside the tape (constants have no grad) except ``assign``/``clone``/``diag``-style
ops over Tensor inputs.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.dtype import canonical_dtype, get_default_dtype
from ..core.place import get_jax_device
from ..core.tensor import Parameter, Tensor, _wrap_value, to_tensor
from ._helpers import ensure_tensor, forward_op, patch_methods

__all__ = [
    "to_tensor", "zeros", "ones", "full", "empty", "zeros_like", "ones_like",
    "full_like", "empty_like", "arange", "linspace", "logspace", "eye", "diag",
    "diagflat", "meshgrid", "tril", "triu", "tril_indices", "triu_indices", "assign",
    "clone", "numel", "complex", "one_hot", "create_parameter",
]


def _shape_arg(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in np.asarray(shape._value).reshape(-1))
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s) for s in shape)


def _dt(dtype, like_float=True):
    d = canonical_dtype(dtype)
    if d is None:
        return get_default_dtype() if like_float else None
    return d


def zeros(shape, dtype=None, name=None) -> Tensor:
    return Tensor(jnp.zeros(_shape_arg(shape), _dt(dtype)))


def ones(shape, dtype=None, name=None) -> Tensor:
    return Tensor(jnp.ones(_shape_arg(shape), _dt(dtype)))


def full(shape, fill_value, dtype=None, name=None) -> Tensor:
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    d = canonical_dtype(dtype)
    if d is None:
        d = get_default_dtype() if isinstance(fill_value, float) else None
    return Tensor(jnp.full(_shape_arg(shape), fill_value, d))


def empty(shape, dtype=None, name=None) -> Tensor:
    # XLA has no uninitialized memory; zeros is the TPU-native "empty".
    return zeros(shape, dtype)


def zeros_like(x, dtype=None, name=None) -> Tensor:
    x = ensure_tensor(x)
    return Tensor(jnp.zeros_like(x._value, canonical_dtype(dtype)))


def ones_like(x, dtype=None, name=None) -> Tensor:
    x = ensure_tensor(x)
    return Tensor(jnp.ones_like(x._value, canonical_dtype(dtype)))


def full_like(x, fill_value, dtype=None, name=None) -> Tensor:
    x = ensure_tensor(x)
    return Tensor(jnp.full_like(x._value, fill_value, dtype=canonical_dtype(dtype)))


def empty_like(x, dtype=None, name=None) -> Tensor:
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None) -> Tensor:
    def _v(x):
        return x.item() if isinstance(x, Tensor) else x
    start, end, step = _v(start), _v(end), _v(step)
    if end is None:
        start, end = 0, start
    return Tensor(jnp.arange(start, end, step, canonical_dtype(dtype)))


def linspace(start, stop, num, dtype=None, name=None) -> Tensor:
    def _v(x):
        return x.item() if isinstance(x, Tensor) else x
    return Tensor(jnp.linspace(_v(start), _v(stop), int(_v(num)),
                               dtype=canonical_dtype(dtype)))


def logspace(start, stop, num, base=10.0, dtype=None, name=None) -> Tensor:
    def _v(x):
        return x.item() if isinstance(x, Tensor) else x
    return Tensor(jnp.logspace(_v(start), _v(stop), int(_v(num)), base=_v(base),
                               dtype=canonical_dtype(dtype)))


def eye(num_rows, num_columns=None, dtype=None, name=None) -> Tensor:
    return Tensor(jnp.eye(int(num_rows),
                          int(num_columns) if num_columns is not None else None,
                          dtype=_dt(dtype)))


def diag(x, offset=0, padding_value=0, name=None) -> Tensor:
    x = ensure_tensor(x)

    def impl(v):
        out = jnp.diag(v, k=offset)
        if v.ndim == 1 and padding_value != 0:
            mask = jnp.eye(out.shape[0], dtype=bool)
            mask = jnp.roll(mask, offset, axis=1) if offset else mask
            out = jnp.where(mask, out, padding_value)
        return out

    return forward_op("diag", impl, [x])


def diagflat(x, offset=0, name=None) -> Tensor:
    return forward_op("diagflat", lambda v: jnp.diagflat(v, k=offset), [ensure_tensor(x)])


def diag_embed(x, offset=0, dim1=-2, dim2=-1, name=None) -> Tensor:
    x = ensure_tensor(x)

    def impl(v):
        n = v.shape[-1] + abs(offset)
        base = jnp.zeros(v.shape[:-1] + (n, n), v.dtype)
        idx = jnp.arange(v.shape[-1])
        r = idx + max(-offset, 0)
        c = idx + max(offset, 0)
        out = base.at[..., r, c].set(v)
        if (dim1, dim2) != (-2, -1):
            out = jnp.moveaxis(out, (-2, -1), (dim1, dim2))
        return out

    return forward_op("diag_embed", impl, [x])


def meshgrid(*args, **kwargs):
    ts = [ensure_tensor(a) for a in (args[0] if len(args) == 1 and
                                     isinstance(args[0], (list, tuple)) else args)]
    outs = forward_op("meshgrid", lambda *vs: tuple(jnp.meshgrid(*vs, indexing="ij")), ts)
    return list(outs) if isinstance(outs, tuple) else [outs]


def tril(x, diagonal=0, name=None) -> Tensor:
    return forward_op("tril", lambda v: jnp.tril(v, k=diagonal), [ensure_tensor(x)])


def triu(x, diagonal=0, name=None) -> Tensor:
    return forward_op("triu", lambda v: jnp.triu(v, k=diagonal), [ensure_tensor(x)])


def tril_indices(row, col, offset=0, dtype="int64", name=None) -> Tensor:
    r, c = np.tril_indices(row, offset, col)
    return Tensor(jnp.asarray(np.stack([r, c]), canonical_dtype(dtype)))


def triu_indices(row, col=None, offset=0, dtype="int64", name=None) -> Tensor:
    col = col if col is not None else row
    r, c = np.triu_indices(row, offset, col)
    return Tensor(jnp.asarray(np.stack([r, c]), canonical_dtype(dtype)))


def assign(x, output=None):
    """paddle.assign: copy into `output` (or a fresh tensor); differentiable."""
    x = x if isinstance(x, Tensor) else to_tensor(np.asarray(x))
    out = forward_op("assign", lambda v: v + 0, [x])
    if output is not None:
        output._rebind(out)
        return output
    return out


def clone(x, name=None) -> Tensor:
    return ensure_tensor(x).clone()


def numel(x, name=None) -> Tensor:
    return Tensor(jnp.asarray(ensure_tensor(x).size, jnp.int64))


def complex(real, imag, name=None) -> Tensor:  # noqa: A001
    return forward_op("complex", jax.lax.complex,
                      [ensure_tensor(real), ensure_tensor(imag)])


def polar(abs, angle, name=None) -> Tensor:  # noqa: A002
    return forward_op("polar",
                      lambda r, t: jax.lax.complex(r * jnp.cos(t), r * jnp.sin(t)),
                      [ensure_tensor(abs), ensure_tensor(angle)])


def one_hot(x, num_classes, name=None) -> Tensor:
    x = ensure_tensor(x)
    return forward_op("one_hot",
                      lambda v: jax.nn.one_hot(v, num_classes,
                                               dtype=get_default_dtype()),
                      [x], differentiable=False)


def create_parameter(shape, dtype=None, name=None, attr=None, is_bias=False,
                     default_initializer=None) -> Parameter:
    """paddle.create_parameter parity (static-graph helper, eager here)."""
    from ..nn import initializer as init

    d = _dt(dtype)
    p = Parameter(jnp.zeros(_shape_arg(shape), d), name=name)
    if default_initializer is not None:
        default_initializer(p)
    elif is_bias:
        init.Constant(0.0)(p)
    else:
        init.XavierNormal()(p)
    return p


patch_methods([
    ("tril", tril), ("triu", triu), ("diag", diag), ("diagflat", diagflat),
])
