"""Structured-prediction / decoding op family.

Parity targets: ``linear_chain_crf_op`` / ``crf_decoding_op`` (paddle
fluid CRF layers), ``ctc_align_op``, ``warpctc_op``, the seq2seq decode ops
(``beam_search_op``, ``beam_search_decode_op``, ``gather_tree_op``) and
``edit_distance_op`` in the reference.

TPU redesign: each dynamic-programming recursion (CRF forward, Viterbi,
Levenshtein, beam back-tracking) is a ``lax.scan`` over the time axis with
the whole batch vectorized per step — the upstream per-sequence CPU loops /
CUDA kernels become one compiled program with static [B, T] shapes and
length masks. Beam search keeps a static [B, W] beam; finished beams are
frozen by score masking rather than removed.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ._helpers import Tensor, ensure_tensor, forward_op

__all__ = [
    "linear_chain_crf", "crf_decoding", "ctc_align", "warpctc",
    "beam_search", "beam_search_decode", "gather_tree", "edit_distance",
]


# ---------------------------------------------------------------------------
# CRF (reference transition layout: [K+2, K]; row 0 start, row 1 stop)
# ---------------------------------------------------------------------------

def linear_chain_crf(emission, transition, label, length=None, name=None):
    """Negative log-likelihood of a linear-chain CRF (ref:
    linear_chain_crf_op). ``emission [B, T, K]``, ``transition [K+2, K]``
    (row 0 = start, row 1 = stop, rows 2.. = pairwise), ``label [B, T]``.
    Returns ``log_likelihood [B]`` (logZ - path score, the reference's
    sign). Forward algorithm = one lax.scan, batch-vectorized."""
    et = ensure_tensor(emission)
    tt = ensure_tensor(transition)
    lt = ensure_tensor(label)
    args = [et, tt, lt]
    if length is not None:
        args.append(ensure_tensor(length))

    def impl(ev, tv, lv, *ln):
        B, T, K = ev.shape
        start, stop, trans = tv[0], tv[1], tv[2:]
        lens = ln[0] if ln else jnp.full((B,), T)
        valid = jnp.arange(T)[None, :] < lens[:, None]

        # --- partition function: alpha recursion over t
        def step(alpha, t):
            # alpha [B, K]; scores [B, K_prev, K_next]
            s = alpha[:, :, None] + trans[None] + ev[:, t][:, None, :]
            nxt = jax.scipy.special.logsumexp(s, axis=1)
            keep = valid[:, t][:, None]
            return jnp.where(keep, nxt, alpha), None

        alpha0 = start[None] + ev[:, 0]
        alphaT, _ = lax.scan(step, alpha0, jnp.arange(1, T))
        logZ = jax.scipy.special.logsumexp(alphaT + stop[None], axis=1)

        # --- gold path score
        b = jnp.arange(B)
        em_sc = jnp.where(valid,
                          jnp.take_along_axis(ev, lv[..., None],
                                              -1)[..., 0], 0.0).sum(1)
        prev = lv[:, :-1]
        nxt = lv[:, 1:]
        tr_sc = jnp.where(valid[:, 1:], trans[prev, nxt], 0.0).sum(1)
        first = lv[:, 0]
        last = jnp.take_along_axis(lv, jnp.clip(lens - 1, 0)[:, None],
                                   1)[:, 0]
        score = em_sc + tr_sc + start[first] + stop[last]
        return logZ - score

    return forward_op("linear_chain_crf", impl, args)


def crf_decoding(emission, transition, length=None, name=None):
    """Viterbi decode with the CRF's [K+2, K] transition layout (ref:
    crf_decoding_op). Returns the argmax path ``[B, T]`` (padding tail 0).
    Max-product scan forward + back-pointer scan backward."""
    et = ensure_tensor(emission)
    tt = ensure_tensor(transition)
    args = [et, tt]
    if length is not None:
        args.append(ensure_tensor(length))

    def impl(ev, tv, *ln):
        B, T, K = ev.shape
        start, stop, trans = tv[0], tv[1], tv[2:]
        lens = ln[0] if ln else jnp.full((B,), T)
        valid = jnp.arange(T)[None, :] < lens[:, None]

        def fwd(carry, t):
            alpha = carry
            s = alpha[:, :, None] + trans[None] + ev[:, t][:, None, :]
            best = s.max(1)
            ptr = s.argmax(1)
            keep = valid[:, t][:, None]
            return jnp.where(keep, best, alpha), \
                jnp.where(keep, ptr, jnp.arange(K)[None])

        alpha0 = start[None] + ev[:, 0]
        alphaT, ptrs = lax.scan(fwd, alpha0, jnp.arange(1, T))
        # ptrs [T-1, B, K]
        last = (alphaT + stop[None]).argmax(1)                # [B]

        def bwd(carry, ptr_t):
            lab = carry
            prev = jnp.take_along_axis(ptr_t, lab[:, None], 1)[:, 0]
            return prev, lab

        first_lab, labs = lax.scan(bwd, last, ptrs, reverse=True)
        path = jnp.concatenate([first_lab[None], labs], 0).T  # [B, T]
        return jnp.where(valid, path, 0)

    return forward_op("crf_decoding", impl, args, differentiable=False)


# ---------------------------------------------------------------------------
# CTC
# ---------------------------------------------------------------------------

def ctc_align(input, input_length=None, blank: int = 0, padding_value: int = 0,
              name=None):
    """Collapse CTC raw predictions: merge repeats then drop blanks (ref:
    ctc_align_op). Static compaction: keep-mask + stable sort, fixed [B, T]
    out + lengths."""
    it = ensure_tensor(input)
    args = [it]
    if input_length is not None:
        args.append(ensure_tensor(input_length))

    def impl(v, *ln):
        B, T = v.shape
        lens = ln[0] if ln else jnp.full((B,), T)
        j = jnp.arange(T)[None, :]
        valid = j < lens[:, None]
        prev = jnp.concatenate([jnp.full((B, 1), -1, v.dtype), v[:, :-1]], 1)
        keep = valid & (v != blank) & (v != prev)
        order = jnp.argsort(jnp.where(keep, j, T), axis=1, stable=True)
        g = jnp.take_along_axis(v, order, 1)
        new_lens = keep.sum(1)
        out = jnp.where(j < new_lens[:, None], g, padding_value)
        return out, new_lens

    return forward_op("ctc_align", impl, args, differentiable=False)


def warpctc(logits, label, logits_length, labels_length, blank: int = 0,
            norm_by_times: bool = False, name=None):
    """CTC loss under the reference's warpctc entry point (ref:
    warpctc_op) — routes to the in-graph alpha-recursion CTC
    (``nn.functional.ctc_loss`` scan formulation). ``logits [T, B, K]``
    (time-major — both entry points share the warpctc convention)."""
    from ..nn import functional as F
    lg = ensure_tensor(logits)
    loss = F.ctc_loss(lg, label, logits_length, labels_length,
                      blank=blank, reduction="none")
    if norm_by_times:
        from ._helpers import forward_op as _f
        lt = ensure_tensor(logits_length)
        return _f("warpctc_norm",
                  lambda l, n: l / jnp.maximum(n.astype(l.dtype), 1),
                  [loss, lt])
    return loss


# ---------------------------------------------------------------------------
# beam search
# ---------------------------------------------------------------------------

def beam_search(pre_ids, pre_scores, ids, scores, beam_size: int,
                end_id: int, level: int = 0, is_accumulated: bool = True,
                name=None):
    """One beam-search expansion step (ref: beam_search_op). ``pre_scores
    [B, W]`` current beam scores, ``scores [B, W, V]`` next-token
    (log-prob) scores; picks the global top-W of W*V candidates per batch.
    Finished beams (last id == end_id) are frozen: they emit only end_id
    with unchanged score. Returns ``(selected_ids [B, W],
    selected_scores [B, W], parent_idx [B, W])`` — static shapes."""
    pit = ensure_tensor(pre_ids)
    pst = ensure_tensor(pre_scores)
    st = ensure_tensor(scores)

    def impl(pi, ps, sc):
        B, W, V = sc.shape
        fin = pi == end_id                                   # [B, W]
        total = jnp.where(fin[..., None],
                          -jnp.inf, ps[..., None] + sc)
        # frozen beams re-emit end_id at their own score
        total = total.at[:, :, end_id].set(
            jnp.where(fin, ps, total[:, :, end_id]))
        flat = total.reshape(B, W * V)
        top, idx = lax.top_k(flat, W)
        parent = idx // V
        tok = idx % V
        return tok, top, parent

    return forward_op("beam_search", impl, [pit, pst, st],
                      differentiable=False)


def gather_tree(ids, parents, name=None):
    """Reconstruct full beams from per-step tokens + parent pointers (ref:
    gather_tree_op; paddle.nn.functional.gather_tree). ``ids/parents
    [T, B, W]``; a reverse lax.scan walks the pointer chain."""
    it = ensure_tensor(ids)
    pt = ensure_tensor(parents)

    def impl(iv, pv):
        T, B, W = iv.shape
        b = jnp.arange(B)[:, None]

        def step(beam, t):
            # beam [B, W]: which slot at step t+1 each final beam occupies
            tok = iv[t][b, beam]
            par = pv[t][b, beam]
            return par, tok

        last = jnp.broadcast_to(jnp.arange(W)[None], (B, W))
        _, toks = lax.scan(step, last, jnp.arange(T), reverse=True)
        return toks                                          # [T, B, W]

    return forward_op("gather_tree", impl, [it, pt], differentiable=False)


def beam_search_decode(ids, parents, beam_size=None, end_id: int = -1,
                       name=None):
    """Full-beam decode (ref: beam_search_decode_op): gather_tree then
    truncate each beam at its first ``end_id``. Returns ``(sequences
    [T, B, W], lengths [B, W])``."""
    full = gather_tree(ids, parents)

    def impl(fv):
        T = fv.shape[0]
        hit = fv == end_id
        any_end = hit.any(0)
        first = jnp.where(any_end, hit.argmax(0) + 1, T)     # keep end token
        return fv, first

    return forward_op("beam_search_decode", impl, [full],
                      differentiable=False)


# ---------------------------------------------------------------------------
# edit distance
# ---------------------------------------------------------------------------

def edit_distance(input, label, input_length=None, label_length=None,
                  normalized: bool = True, name=None):
    """Levenshtein distance per batch row (ref: edit_distance_op).
    ``input [B, T1]``, ``label [B, T2]`` id sequences with optional
    lengths. The DP table rolls forward one column per scan step (static
    [B, T1+1] carry). Returns ``(distance [B], sequence_num [B])``."""
    it = ensure_tensor(input)
    lt = ensure_tensor(label)
    args = [it, lt]
    if input_length is not None:
        args.append(ensure_tensor(input_length))
        args.append(ensure_tensor(label_length))

    def impl(iv, lv, *ln):
        B, T1 = iv.shape
        T2 = lv.shape[1]
        ilen = ln[0] if ln else jnp.full((B,), T1)
        llen = ln[1] if ln else jnp.full((B,), T2)

        # dp[i] = distance(input[:i], label[:j]) rolled over j
        row0 = jnp.broadcast_to(jnp.arange(T1 + 1)[None].astype(jnp.float32),
                                (B, T1 + 1))

        def col(dp, j):
            # moving to column j+1 (label token j)
            sub = dp[:, :-1] + (iv != lv[:, j][:, None]).astype(jnp.float32)
            base = jnp.concatenate(
                [jnp.full((B, 1), j + 1, jnp.float32),
                 jnp.full((B, T1), jnp.inf)], 1)
            ins = dp + 1.0                                   # from left col

            def inner(prev, i):
                cur = jnp.minimum(jnp.minimum(ins[:, i + 1], sub[:, i]),
                                  prev + 1.0)
                return cur, cur

            first = base[:, 0]
            _, rest = lax.scan(inner, first, jnp.arange(T1))
            newdp = jnp.concatenate([first[:, None], rest.T], 1)
            # columns beyond this row's label length keep the old dp
            keep = (j < llen)[:, None]
            return jnp.where(keep, newdp, dp), None

        dpT, _ = lax.scan(col, row0, jnp.arange(T2))
        dist = jnp.take_along_axis(dpT, ilen[:, None], 1)[:, 0]
        if normalized:
            dist = dist / jnp.maximum(llen.astype(jnp.float32), 1)
        return dist, jnp.ones((B,), jnp.int32)

    return forward_op("edit_distance", impl, args, differentiable=False)


def ctc_greedy_decoder(input, blank: int = 0, input_length=None, name=None):  # noqa: A002
    """Greedy CTC decode: argmax per step then collapse (ref:
    ctc_greedy_decoder_op) — argmax + the ctc_align compaction, one
    program. ``input [B, T, K]`` probabilities/logits. Returns
    ``(out [B, T], out_lens [B])``."""
    it = ensure_tensor(input)
    args = [it]
    if input_length is not None:
        args.append(ensure_tensor(input_length))

    def impl(v, *ln):
        ids = jnp.argmax(v, -1)
        B, T = ids.shape
        lens = ln[0] if ln else jnp.full((B,), T)
        j = jnp.arange(T)[None, :]
        valid = j < lens[:, None]
        prev = jnp.concatenate(
            [jnp.full((B, 1), -1, ids.dtype), ids[:, :-1]], 1)
        keep = valid & (ids != blank) & (ids != prev)
        order = jnp.argsort(jnp.where(keep, j, T), axis=1, stable=True)
        g = jnp.take_along_axis(ids, order, 1)
        new_lens = keep.sum(1)
        return jnp.where(j < new_lens[:, None], g, 0), new_lens

    return forward_op("ctc_greedy_decoder", impl, args,
                      differentiable=False)


__all__ += ["ctc_greedy_decoder"]
