"""Extended tensor-op surface (round-3 breadth push toward the reference's
~2,000-function tensor API — SURVEY §2.3).

Parity targets: ``python/paddle/tensor/{math,manipulation,search,stat}.py``
in the reference. All jnp/XLA-backed, registered in OP_REGISTRY so the
schema sweep (tests/test_op_sweep.py) and docs/OPS.md cover them.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ._helpers import (Tensor, axes_arg, binary_factory, ensure_tensor,
                       forward_op, register_op, unary_factory)

__all__ = [
    "slice_scatter", "polygamma", "logaddexp2", "frexp", "sgn",
    "nanquantile", "as_strided", "unfold_axis", "atleast_1d", "atleast_2d",
    "atleast_3d", "fix", "fmod", "msort", "rank", "reverse", "binomial",
    "standard_gamma", "cummin", "logcumsumexp", "isposinf", "isneginf",
    "isreal", "iscomplex", "index_sample", "strided_slice", "increment",
    "gammainc", "gammaincc", "hfft2", "ihfft2", "hfftn", "ihfftn",
    "segment_sum", "segment_mean", "segment_max", "segment_min",
]


# ---------------------------------------------------------------------------
# elementwise additions (factory-registered: picked up by the schema sweep)
# ---------------------------------------------------------------------------

fix = unary_factory("fix", jnp.trunc, "Round toward zero (alias of trunc).")
sgn = unary_factory(
    "sgn", lambda x: (jnp.sign(x) if not jnp.iscomplexobj(x)
                      else jnp.where(x == 0, 0, x / jnp.abs(x))),
    "Sign; for complex inputs x/|x| (ref: paddle.sgn).")
isposinf = unary_factory("isposinf", jnp.isposinf, "x == +inf elementwise.")
isneginf = unary_factory("isneginf", jnp.isneginf, "x == -inf elementwise.")
isreal = unary_factory("isreal", jnp.isreal, "True where imaginary part 0.")
iscomplex = unary_factory(
    "iscomplex", lambda x: jnp.full(x.shape, jnp.iscomplexobj(x)),
    "True where the dtype is complex (ref: paddle.is_complex semantics).")
logaddexp2 = binary_factory("logaddexp2", jnp.logaddexp2,
                            "log2(2**x + 2**y), overflow-safe.")
fmod = binary_factory("fmod", jnp.fmod,
                      "C-style remainder (sign follows the dividend).")
gammainc = binary_factory(
    "gammainc", lambda a, x: jax.scipy.special.gammainc(a, x),
    "Regularized lower incomplete gamma P(a, x).")
gammaincc = binary_factory(
    "gammaincc", lambda a, x: jax.scipy.special.gammaincc(a, x),
    "Regularized upper incomplete gamma Q(a, x).")


def polygamma(x, n: int = 1, name=None):
    """n-th derivative of digamma (ref: paddle.polygamma)."""
    t = ensure_tensor(x)
    return forward_op("polygamma",
                      lambda v: jax.scipy.special.polygamma(n, v), [t])


register_op("polygamma", lambda v: jax.scipy.special.polygamma(1, v),
            "n-th polygamma function (n static).")


def frexp(x, name=None):
    """(mantissa, exponent) with x = m * 2**e, 0.5 <= |m| < 1."""
    t = ensure_tensor(x)
    return forward_op("frexp", lambda v: tuple(jnp.frexp(v)), [t])


register_op("frexp", lambda v: tuple(jnp.frexp(v)),
            "Decompose into mantissa and exponent.", n_outputs=2,
            differentiable=False)


# ---------------------------------------------------------------------------
# manipulation
# ---------------------------------------------------------------------------

def slice_scatter(x, value, axes, starts, ends, strides=None, name=None):
    """Write ``value`` into the slice of ``x`` selected by axes/starts/ends
    (ref: paddle.slice_scatter)."""
    t = ensure_tensor(x)
    v = ensure_tensor(value)
    axes = [int(a) for a in axes]
    strides = [1] * len(axes) if strides is None else [int(s) for s in strides]

    def impl(xv, vv):
        idx = [slice(None)] * xv.ndim
        for a, s, e, st in zip(axes, starts, ends, strides):
            idx[a] = slice(int(s), int(e), st)
        return xv.at[tuple(idx)].set(vv.astype(xv.dtype))

    return forward_op("slice_scatter", impl, [t, v])


register_op("slice_scatter", lambda x, v: x,
            "Scatter a value tensor into a strided slice.")


def as_strided(x, shape, stride, offset: int = 0, name=None):
    """Strided view as a gather (ref: paddle.as_strided; on TPU a copy —
    XLA has no aliasing views across programs)."""
    t = ensure_tensor(x)
    shape = [int(s) for s in shape]
    stride = [int(s) for s in stride]

    def impl(v):
        flat = v.reshape(-1)
        if not shape:
            return flat[offset]
        grids = np.ix_(*[np.arange(n) for n in shape])
        lin = np.broadcast_to(
            offset + sum(g * s for g, s in zip(grids, stride)), tuple(shape))
        return flat[jnp.asarray(lin, jnp.int32)]

    return forward_op("as_strided", impl, [t])


register_op("as_strided", lambda x: x,
            "Strided re-indexing of the underlying buffer (gather copy).")


def unfold_axis(x, axis: int, size: int, step: int, name=None):
    """Sliding windows over one axis: shape[axis] -> (n_windows, size) as
    the LAST dim. This is ``Tensor.unfold``'s semantics — the TOP-LEVEL
    ``paddle.nn.functional.unfold`` is the unrelated im2col op and keeps its
    name (nn/functional/common.py)."""
    t = ensure_tensor(x)
    axis = int(axis)

    def impl(v):
        ax = axis % v.ndim
        n = (v.shape[ax] - size) // step + 1
        starts = jnp.arange(n) * step
        win = jnp.arange(size)
        idx = starts[:, None] + win[None, :]          # [n, size]
        out = jnp.take(v, idx.reshape(-1), axis=ax)
        out = out.reshape(v.shape[:ax] + (n, size) + v.shape[ax + 1:])
        return jnp.moveaxis(out, ax + 1, -1)

    return forward_op("unfold_axis", impl, [t])


register_op("unfold_axis", lambda x: x,
            "Sliding-window view over one axis (Tensor.unfold).")

# method surface: x.unfold(axis, size, step) — the Tensor METHOD is the
# sliding window; the module-level `unfold` name stays with im2col
from ._helpers import patch_methods  # noqa: E402

patch_methods([("unfold", unfold_axis)])


def atleast_1d(*xs, name=None):
    outs = [forward_op("atleast_1d", jnp.atleast_1d, [ensure_tensor(x)])
            for x in xs]
    return outs[0] if len(outs) == 1 else outs


def atleast_2d(*xs, name=None):
    outs = [forward_op("atleast_2d", jnp.atleast_2d, [ensure_tensor(x)])
            for x in xs]
    return outs[0] if len(outs) == 1 else outs


def atleast_3d(*xs, name=None):
    outs = [forward_op("atleast_3d", jnp.atleast_3d, [ensure_tensor(x)])
            for x in xs]
    return outs[0] if len(outs) == 1 else outs


register_op("atleast_1d", jnp.atleast_1d, "Promote to >= 1-D.")
register_op("atleast_2d", jnp.atleast_2d, "Promote to >= 2-D.")
register_op("atleast_3d", jnp.atleast_3d, "Promote to >= 3-D.")


def msort(x, name=None):
    """Sort along the FIRST axis (ref: paddle.msort)."""
    return forward_op("msort", lambda v: jnp.sort(v, axis=0),
                      [ensure_tensor(x)])


register_op("msort", lambda v: jnp.sort(v, axis=0), "Sort along axis 0.")


def rank(x, name=None):
    """Number of dimensions, as a 0-D int32 tensor (ref: paddle.rank)."""
    t = ensure_tensor(x)
    return forward_op("rank", lambda v: jnp.asarray(v.ndim, jnp.int32), [t],
                      differentiable=False)


register_op("rank", lambda v: jnp.asarray(v.ndim, jnp.int32),
            "ndim as a tensor.", differentiable=False)


def reverse(x, axis, name=None):
    """Alias of flip (legacy paddle.reverse)."""
    ax = axes_arg(axis)
    ax = (ax,) if isinstance(ax, int) else ax
    return forward_op("reverse", lambda v: jnp.flip(v, axis=ax),
                      [ensure_tensor(x)])


register_op("reverse", lambda v: jnp.flip(v, axis=0), "Flip along axes.")


def strided_slice(x, axes, starts, ends, strides, name=None):
    """ref: paddle.strided_slice."""
    t = ensure_tensor(x)
    axes = [int(a) for a in axes]

    def impl(v):
        idx = [slice(None)] * v.ndim
        for a, s, e, st in zip(axes, starts, ends, strides):
            idx[a] = slice(int(s), int(e), int(st))
        return v[tuple(idx)]

    return forward_op("strided_slice", impl, [t])


register_op("strided_slice", lambda v: v, "Multi-axis strided slice.")


def index_sample(x, index):
    """Per-row gather: out[i, j] = x[i, index[i, j]] (ref:
    paddle.index_sample)."""
    t = ensure_tensor(x)
    i = ensure_tensor(index)
    return forward_op(
        "index_sample",
        lambda v, ix: jnp.take_along_axis(v, ix.astype(jnp.int32), axis=1),
        [t, i])


register_op("index_sample",
            lambda v, ix: jnp.take_along_axis(v, ix.astype(jnp.int32), 1),
            "Batched per-row gather.")


def increment(x, value: float = 1.0, name=None):
    """In-place add of a scalar (ref: paddle.increment)."""
    t = ensure_tensor(x)
    out = forward_op("increment", lambda v: v + np.asarray(value, v.dtype),
                     [t])
    t._rebind(out)
    return t


register_op("increment", lambda v: v + 1, "x += value (in place).")


# ---------------------------------------------------------------------------
# reductions / scans / stats
# ---------------------------------------------------------------------------

def cummin(x, axis: Optional[int] = None, dtype="int64", name=None):
    """(values, indices) running minimum (ref: paddle.cummin)."""
    t = ensure_tensor(x)

    def impl(v):
        vv = v.reshape(-1) if axis is None else v
        ax = 0 if axis is None else int(axis) % vv.ndim
        n = vv.shape[ax]
        ar = jnp.broadcast_to(
            jnp.arange(n).reshape([-1 if i == ax else 1
                                   for i in range(vv.ndim)]), vv.shape)

        def comb(a, b):  # the argmin monoid (ties keep the earlier index)
            av, ai = a
            bv, bi = b
            bwins = (bv < av) | ((bv == av) & (bi < ai))
            return jnp.where(bwins, bv, av), jnp.where(bwins, bi, ai)

        vals, idx = lax.associative_scan(comb, (vv, ar), axis=ax)
        return vals, idx.astype(jnp.int64 if dtype == "int64" else jnp.int32)

    return forward_op("cummin", impl, [t])


register_op("cummin", lambda v: lax.associative_scan(jnp.minimum, v, axis=0),
            "Running minimum with indices.", n_outputs=2)


def logcumsumexp(x, axis: Optional[int] = None, name=None):
    t = ensure_tensor(x)

    def impl(v):
        vv = v.reshape(-1) if axis is None else v
        ax = 0 if axis is None else int(axis)
        return lax.cumlogsumexp(vv, axis=ax)

    return forward_op("logcumsumexp", impl, [t])


register_op("logcumsumexp", lambda v: lax.cumlogsumexp(v, axis=0),
            "Numerically stable log(cumsum(exp(x))).")


def nanquantile(x, q, axis=None, keepdim: bool = False, name=None):
    t = ensure_tensor(x)
    ax = axes_arg(axis)
    return forward_op(
        "nanquantile",
        lambda v: jnp.nanquantile(v, jnp.asarray(q), axis=ax,
                                  keepdims=keepdim),
        [t])


register_op("nanquantile", lambda v: jnp.nanquantile(v, 0.5),
            "Quantile ignoring NaNs.")


# ---------------------------------------------------------------------------
# random
# ---------------------------------------------------------------------------

def _next_key():
    from .random import _next_key as nk
    return nk()


def binomial(count, prob, name=None):
    """Sample Binomial(count, prob) elementwise (ref: paddle.binomial)."""
    c = ensure_tensor(count)
    p = ensure_tensor(prob)
    key = _next_key()

    def impl(cv, pv):
        shape = jnp.broadcast_shapes(cv.shape, pv.shape)
        return jax.random.binomial(
            key, cv.astype(jnp.float32), pv.astype(jnp.float32),
            shape=shape).astype(jnp.int64)

    return forward_op("binomial", impl, [c, p], differentiable=False)


register_op("binomial", lambda c, p: c * 0,
            "Binomial sampling.", differentiable=False)


def standard_gamma(alpha, name=None):
    """Sample Gamma(alpha, 1) (ref: paddle.standard_gamma)."""
    a = ensure_tensor(alpha)
    key = _next_key()
    return forward_op(
        "standard_gamma",
        lambda av: jax.random.gamma(key, av.astype(jnp.float32)),
        [a], differentiable=False)


register_op("standard_gamma", lambda a: a,
            "Gamma(alpha, 1) sampling.", differentiable=False)


# ---------------------------------------------------------------------------
# fft completions (hermitian 2-D/N-D)
# ---------------------------------------------------------------------------

# factorization (torch.fft semantics): the input is one-sided Hermitian in
# the LAST transform dim only — full C->C transforms over the other dims,
# then the Hermitian C->R transform last (mirror of irfftn's structure)

def _hfft_nd(v, s, axes, norm, inverse: bool):
    axes = tuple(range(-len(s), 0)) if (axes is None and s is not None) \
        else (axes if axes is not None else tuple(range(v.ndim)))
    axes = tuple(a % v.ndim for a in axes)
    other, last = axes[:-1], axes[-1]
    s_other = None if s is None else tuple(s[:-1])
    n_last = None if s is None else s[-1]
    if inverse:
        u = jnp.fft.ihfft(v, n=n_last, axis=last, norm=norm)
        return jnp.fft.ifftn(u, s=s_other, axes=other, norm=norm) \
            if other else u
    u = jnp.fft.fftn(v, s=s_other, axes=other, norm=norm) if other else v
    return jnp.fft.hfft(u, n=n_last, axis=last, norm=norm)


def _fft_member(name, default_axes, inverse):
    def op(x, s=None, axes=None, norm=None, name=None):
        ax = axes if axes is not None else default_axes
        return forward_op(
            name, lambda v: _hfft_nd(v, s, ax, norm, inverse),
            [ensure_tensor(x)])
    op.__name__ = name
    op.__doc__ = (f"{name}: Hermitian FFT family (torch.fft semantics); "
                  f"honors s/axes/norm.")
    register_op(name, lambda v: _hfft_nd(v, None, default_axes, None,
                                         inverse),
                f"{name} (hermitian FFT family).")
    return op


hfft2 = _fft_member("hfft2", (-2, -1), inverse=False)
ihfft2 = _fft_member("ihfft2", (-2, -1), inverse=True)
hfftn = _fft_member("hfftn", None, inverse=False)
ihfftn = _fft_member("ihfftn", None, inverse=True)


# ---------------------------------------------------------------------------
# geometric segment ops (ref: paddle.geometric.segment_*)
# ---------------------------------------------------------------------------

# the shared reduction table — geometric's send/recv ops reuse these exact
# lambdas (single definition for the empty-segment guard etc.)
_SEGMENT_POOLS = {
    "sum": lambda d, s, n: jax.ops.segment_sum(d, s, num_segments=n),
    "mean": lambda d, s, n: jax.ops.segment_sum(d, s, num_segments=n) /
    jnp.maximum(jax.ops.segment_sum(jnp.ones(s.shape, jnp.float32), s,
                                    num_segments=n), 1.0).reshape(
        (-1,) + (1,) * (d.ndim - 1)),
    "max": lambda d, s, n: jax.ops.segment_max(d, s, num_segments=n),
    "min": lambda d, s, n: jax.ops.segment_min(d, s, num_segments=n),
}


def _segment(name, pool):
    jfn = _SEGMENT_POOLS[pool]

    def op(data, segment_ids, name=None):
        d = ensure_tensor(data)
        s = ensure_tensor(segment_ids)

        def impl(dv, sv):
            num = int(np.asarray(jax.device_get(sv)).max()) + 1 \
                if sv.size else 0
            return jfn(dv, sv.astype(jnp.int32), num)

        return forward_op(name, impl, [d, s])
    op.__name__ = name
    register_op(name, lambda d, s: d, f"{name}: per-segment reduction.")
    return op


segment_sum = _segment("segment_sum", "sum")
segment_mean = _segment("segment_mean", "mean")
segment_max = _segment("segment_max", "max")
segment_min = _segment("segment_min", "min")
