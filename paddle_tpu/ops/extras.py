"""Secondary op surface — stacking/splitting variants, distance/statistics,
scatter-style updates, complex views, misc math.

Parity targets: scattered across ``python/paddle/tensor/{manipulation,math,
linalg,stat}.py`` in the reference. All jnp-backed through the dispatcher
(tape-differentiable, jit-traceable).
"""

from __future__ import annotations

import math as _math

import numpy as np
import jax
import jax.numpy as jnp

from ._helpers import (axes_arg, binary_factory, ensure_tensor, forward_op,
                       patch_methods, unary_factory)

__all__ = [
    "hstack", "vstack", "dstack", "column_stack", "row_stack", "tensor_split",
    "hsplit", "vsplit", "dsplit", "unflatten", "block_diag", "rot90",
    "diagonal_scatter", "select_scatter", "positive", "signbit", "sinc",
    "vander", "trapezoid", "cumulative_trapezoid", "renorm", "cdist", "pdist",
    "cartesian_prod", "combinations", "view_as_complex", "view_as_real",
    "is_complex", "is_floating_point", "aminmax", "baddbmm", "isin",
    "histogramdd", "as_complex", "as_real", "polar",
]


# -- stacking / splitting ----------------------------------------------------

def _tensors(xs):
    return [ensure_tensor(x) for x in xs]


def hstack(x, name=None):
    return forward_op("hstack", lambda *vs: jnp.hstack(vs), _tensors(x))


def vstack(x, name=None):
    return forward_op("vstack", lambda *vs: jnp.vstack(vs), _tensors(x))


def dstack(x, name=None):
    return forward_op("dstack", lambda *vs: jnp.dstack(vs), _tensors(x))


def column_stack(x, name=None):
    return forward_op("column_stack", lambda *vs: jnp.column_stack(vs),
                      _tensors(x))


row_stack = vstack


def tensor_split(x, num_or_indices, axis=0, name=None):
    t = ensure_tensor(x)
    if isinstance(num_or_indices, int):
        parts = jnp.array_split(np.arange(t.shape[axis]), num_or_indices)
        bounds = np.cumsum([len(p) for p in parts])[:-1].tolist()
    else:
        bounds = list(num_or_indices)
    outs = forward_op(
        "tensor_split",
        lambda v: tuple(jnp.split(v, bounds, axis=axis)), [t])
    return list(outs)


def hsplit(x, num_or_indices, name=None):
    t = ensure_tensor(x)
    return tensor_split(t, num_or_indices, axis=0 if t.ndim == 1 else 1)


def vsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=0)


def dsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=2)


def unflatten(x, axis, shape, name=None):
    t = ensure_tensor(x)
    axis = axis % t.ndim
    shape = [int(s) for s in shape]
    full = list(t.shape)
    new = full[:axis] + shape + full[axis + 1:]
    if -1 in shape:
        i = shape.index(-1)
        known = int(np.prod([s for s in shape if s != -1]))
        shape[i] = full[axis] // known
        new = full[:axis] + shape + full[axis + 1:]
    return forward_op("unflatten", lambda v: v.reshape(new), [t])


def block_diag(inputs, name=None):
    ts = _tensors(inputs)

    def f(*vs):
        vs = [v[None, None] if v.ndim == 0 else
              (v[None] if v.ndim == 1 else v) for v in vs]
        rows = sum(v.shape[0] for v in vs)
        cols = sum(v.shape[1] for v in vs)
        out = jnp.zeros((rows, cols), vs[0].dtype)
        r = c = 0
        for v in vs:
            out = out.at[r:r + v.shape[0], c:c + v.shape[1]].set(v)
            r += v.shape[0]
            c += v.shape[1]
        return out
    return forward_op("block_diag", f, ts)


def rot90(x, k=1, axes=(0, 1), name=None):
    return forward_op("rot90", lambda v: jnp.rot90(v, k, axes),
                      [ensure_tensor(x)])


# -- scatter-style functional updates ---------------------------------------

def diagonal_scatter(x, y, offset=0, axis1=0, axis2=1, name=None):
    t, s = ensure_tensor(x), ensure_tensor(y)

    def f(v, src):
        n1, n2 = v.shape[axis1], v.shape[axis2]
        idx = jnp.arange(max(n1, n2))
        i = idx if offset >= 0 else idx - offset
        j = idx + offset if offset >= 0 else idx
        keep = (i < n1) & (j < n2)
        i, j = i[keep[: len(i)]], j[keep[: len(j)]]
        ix = [slice(None)] * v.ndim
        ix[axis1], ix[axis2] = i, j
        return v.at[tuple(ix)].set(src)
    return forward_op("diagonal_scatter", f, [t, s])


def select_scatter(x, values, axis, index, name=None):
    t, s = ensure_tensor(x), ensure_tensor(values)

    def f(v, src):
        ix = [slice(None)] * v.ndim
        ix[axis % v.ndim] = index
        return v.at[tuple(ix)].set(src)
    return forward_op("select_scatter", f, [t, s])


# -- elementwise / math ------------------------------------------------------

positive = unary_factory("positive", lambda v: +v)
signbit = unary_factory("signbit", jnp.signbit)
sinc = unary_factory("sinc", jnp.sinc)


def vander(x, n=None, increasing=False, name=None):
    return forward_op(
        "vander", lambda v: jnp.vander(v, n, increasing=increasing),
        [ensure_tensor(x)])


def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    t = ensure_tensor(y)
    if x is not None:
        return forward_op("trapezoid",
                          lambda v, xv: jnp.trapezoid(v, xv, axis=axis),
                          [t, ensure_tensor(x)])
    d = 1.0 if dx is None else dx
    return forward_op("trapezoid",
                      lambda v: jnp.trapezoid(v, dx=d, axis=axis), [t])


def cumulative_trapezoid(y, x=None, dx=None, axis=-1, name=None):
    t = ensure_tensor(y)

    def f(v, xv=None):
        sl1 = [slice(None)] * v.ndim
        sl2 = [slice(None)] * v.ndim
        sl1[axis] = slice(1, None)
        sl2[axis] = slice(None, -1)
        avg = (v[tuple(sl1)] + v[tuple(sl2)]) / 2.0
        if xv is not None:
            d = xv[tuple(sl1)] - xv[tuple(sl2)]
        else:
            d = 1.0 if dx is None else dx
        return jnp.cumsum(avg * d, axis=axis)
    if x is not None:
        return forward_op("cumulative_trapezoid", f, [t, ensure_tensor(x)])
    return forward_op("cumulative_trapezoid", f, [t])


def renorm(x, p, axis, max_norm, name=None):
    t = ensure_tensor(x)

    def f(v):
        dims = tuple(d for d in range(v.ndim) if d != axis % v.ndim)
        norms = jnp.sum(jnp.abs(v) ** p, axis=dims, keepdims=True) ** (1.0 / p)
        factor = jnp.where(norms > max_norm, max_norm / (norms + 1e-7), 1.0)
        return v * factor
    return forward_op("renorm", f, [t])


def baddbmm(input, x, y, beta=1.0, alpha=1.0, name=None):  # noqa: A002
    return forward_op(
        "baddbmm",
        lambda b, a, c: beta * b + alpha * jnp.einsum("bij,bjk->bik", a, c),
        [ensure_tensor(input), ensure_tensor(x), ensure_tensor(y)])


def aminmax(x, axis=None, keepdim=False, name=None):
    t = ensure_tensor(x)
    ax = axes_arg(axis)
    return forward_op(
        "aminmax",
        lambda v: (jnp.min(v, axis=ax, keepdims=keepdim),
                   jnp.max(v, axis=ax, keepdims=keepdim)), [t])


def isin(x, test_x, assume_unique=False, invert=False, name=None):
    return forward_op(
        "isin", lambda v, tv: jnp.isin(v, tv, invert=invert),
        [ensure_tensor(x), ensure_tensor(test_x)], differentiable=False)


# -- distances / statistics --------------------------------------------------

def cdist(x, y, p=2.0, compute_mode=None, name=None):
    """Pairwise p-norm distance [..., M, N] (ref: paddle.cdist)."""
    t1, t2 = ensure_tensor(x), ensure_tensor(y)

    def f(a, b):
        diff = a[..., :, None, :] - b[..., None, :, :]
        if p == 2.0:
            return jnp.sqrt(jnp.sum(diff * diff, axis=-1) + 1e-30)
        return jnp.sum(jnp.abs(diff) ** p, axis=-1) ** (1.0 / p)
    return forward_op("cdist", f, [t1, t2])


def pdist(x, p=2.0, name=None):
    """Condensed pairwise distances of [N, D] -> [N*(N-1)/2]."""
    t = ensure_tensor(x)
    n = t.shape[0]
    iu = np.triu_indices(n, k=1)

    def f(v):
        diff = v[:, None, :] - v[None, :, :]
        if p == 2.0:
            d = jnp.sqrt(jnp.sum(diff * diff, axis=-1) + 1e-30)
        else:
            d = jnp.sum(jnp.abs(diff) ** p, axis=-1) ** (1.0 / p)
        return d[iu]
    return forward_op("pdist", f, [t])


def histogramdd(x, bins=10, ranges=None, density=False, weights=None,
                name=None):
    """Host-side (non-differentiable counting op — one numpy pass yields
    both histogram and edges)."""
    t = ensure_tensor(x)
    w = None if weights is None else np.asarray(ensure_tensor(weights)._value)
    hist_np, edges_np = np.histogramdd(np.asarray(t._value), bins=bins,
                                       range=ranges, density=density,
                                       weights=w)
    from ..core.tensor import Tensor
    return (Tensor(jnp.asarray(hist_np.astype(np.float32))),
            [Tensor(jnp.asarray(e.astype(np.float32))) for e in edges_np])


def cartesian_prod(x, name=None):
    ts = _tensors(x)

    def f(*vs):
        grids = jnp.meshgrid(*vs, indexing="ij")
        return jnp.stack([g.reshape(-1) for g in grids], axis=-1)
    return forward_op("cartesian_prod", f, ts)


def combinations(x, r=2, with_replacement=False, name=None):
    t = ensure_tensor(x)
    import itertools
    n = t.shape[0]
    src = (itertools.combinations_with_replacement(range(n), r)
           if with_replacement else itertools.combinations(range(n), r))
    idx = np.asarray(list(src), np.int32).reshape(-1, r)

    def f(v):
        return v[jnp.asarray(idx)]
    return forward_op("combinations", f, [t])


# -- complex views (single source of truth in ops/manipulation.py) -----------

from .manipulation import as_complex, as_real  # noqa: E402

view_as_complex = as_complex
view_as_real = as_real


def polar(abs, angle, name=None):  # noqa: A002
    return forward_op(
        "polar",
        lambda r, t: jax.lax.complex(r * jnp.cos(t), r * jnp.sin(t)),
        [ensure_tensor(abs), ensure_tensor(angle)])


def is_complex(x) -> bool:
    return bool(jnp.issubdtype(ensure_tensor(x).dtype, jnp.complexfloating))


def is_floating_point(x) -> bool:
    return bool(jnp.issubdtype(ensure_tensor(x).dtype, jnp.floating))


patch_methods([
    ("unflatten", unflatten), ("rot90", rot90),
    ("diagonal_scatter", diagonal_scatter),
    ("select_scatter", select_scatter), ("signbit", signbit),
    ("sinc", sinc), ("trapezoid", trapezoid), ("renorm", renorm),
    ("cdist", cdist), ("pdist", pdist), ("aminmax", aminmax),
    ("isin", isin), ("baddbmm", baddbmm),
    ("is_complex", is_complex), ("is_floating_point", is_floating_point),
])
