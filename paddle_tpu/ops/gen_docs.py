"""Generate the op-surface reference from the schema registry.

The reference drives codegen (C++ API, grad nodes, bindings, docs) from
``paddle/phi/api/yaml/ops.yaml``; here the registry IS the runtime op table
(``core.dispatch.OP_REGISTRY``) and this generator derives the docs from it
— one source of truth, no drift.

    python -m paddle_tpu.ops.gen_docs [out_path]
"""

from __future__ import annotations

import inspect
import sys


def generate(out_path: str = "docs/OPS.md") -> str:
    import os

    # populate the registry: the tensor surface plus every domain that
    # registers kernels (upstream: one ops.yaml covers them all)
    import paddle_tpu.ops  # noqa: F401
    import paddle_tpu.nn.functional  # noqa: F401
    import paddle_tpu.sparse  # noqa: F401
    import paddle_tpu.signal  # noqa: F401
    import paddle_tpu.geometric  # noqa: F401
    import paddle_tpu.vision.ops  # noqa: F401
    import paddle_tpu.fft  # noqa: F401
    import paddle_tpu.audio  # noqa: F401
    import paddle_tpu.incubate.nn.functional  # noqa: F401
    import paddle_tpu.distributed.moe_utils  # noqa: F401
    import paddle_tpu.optimizer  # noqa: F401
    import paddle_tpu.distributed.ps  # noqa: F401
    import paddle_tpu.vision.transforms  # noqa: F401
    import paddle_tpu.text  # noqa: F401
    import paddle_tpu.metric  # noqa: F401
    from paddle_tpu.core.dispatch import OP_REGISTRY
    from paddle_tpu.ops.sweep_specs import attach_specs, sweep_coverage
    attach_specs()

    lines = ["# Op surface reference",
             "",
             "Generated from `core.dispatch.OP_REGISTRY` (the ops.yaml-"
             "equivalent single source of truth) by "
             "`python -m paddle_tpu.ops.gen_docs`. Do not edit by hand.",
             "",
             f"{len(OP_REGISTRY)} registered ops.",
             "",
             "Sweep coverage (tests/test_op_sweep.py: numpy/scipy oracle + "
             "finite-difference grad + bf16 legs, from the schema's "
             "category tags and OpDef.sweep specs): "
             f"**{sweep_coverage()[0]} of {sweep_coverage()[1]} ops "
             f"({100 * sweep_coverage()[0] // sweep_coverage()[1]}%)**; "
             "the rest are covered by hand-written domain tests "
             "(tests/test_*.py) or are stateful/random/IO ops outside the "
             "oracle pattern.",
             ""]
    # serving ops surface (ISSUE 6): the health_snapshot() payload an ops
    # endpoint serves, generated from the engine's field registry (the
    # snapshot test pins the live payload to the same registry)
    from paddle_tpu.inference.serving.engine import HEALTH_SNAPSHOT_FIELDS
    lines += ["## Serving health surface",
              "",
              "`inference.serving.ServingEngine.health_snapshot()` "
              "(docs/SERVING.md \"Overload & multi-tenancy\") returns one "
              "JSON-serializable record per call — the payload a "
              "`/healthz` or metrics endpoint should serve:",
              "",
              "| field | meaning |",
              "|---|---|"]
    lines += [f"| `{k}` | {v} |" for k, v in HEALTH_SNAPSHOT_FIELDS.items()]
    lines += ["",
              "## Op table",
              "",
              "| op | signature | doc |",
              "|---|---|---|"]
    for name in sorted(OP_REGISTRY):
        d = OP_REGISTRY[name]
        try:
            sig = str(inspect.signature(d.fn))
        except (TypeError, ValueError):
            sig = "(...)"
        doc = (d.doc or "").split("\n")[0].replace("|", "\\|")
        lines.append(f"| `{name}` | `{sig}` | {doc} |")
    text = "\n".join(lines) + "\n"
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    with open(out_path, "w") as f:
        f.write(text)
    return out_path


if __name__ == "__main__":
    path = generate(sys.argv[1] if len(sys.argv) > 1 else "docs/OPS.md")
    print(f"wrote {path}")
