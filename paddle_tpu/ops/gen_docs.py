"""Generate the op-surface reference from the schema registry.

The reference drives codegen (C++ API, grad nodes, bindings, docs) from
``paddle/phi/api/yaml/ops.yaml``; here the registry IS the runtime op table
(``core.dispatch.OP_REGISTRY``) and this generator derives the docs from it
— one source of truth, no drift.

    python -m paddle_tpu.ops.gen_docs [out_path]
"""

from __future__ import annotations

import inspect
import sys


def generate(out_path: str = "docs/OPS.md") -> str:
    import os

    # populate the registry: the tensor surface plus every domain that
    # registers kernels (upstream: one ops.yaml covers them all)
    import paddle_tpu.ops  # noqa: F401
    import paddle_tpu.nn.functional  # noqa: F401
    import paddle_tpu.sparse  # noqa: F401
    import paddle_tpu.signal  # noqa: F401
    import paddle_tpu.geometric  # noqa: F401
    import paddle_tpu.vision.ops  # noqa: F401
    from paddle_tpu.core.dispatch import OP_REGISTRY

    lines = ["# Op surface reference",
             "",
             "Generated from `core.dispatch.OP_REGISTRY` (the ops.yaml-"
             "equivalent single source of truth) by "
             "`python -m paddle_tpu.ops.gen_docs`. Do not edit by hand.",
             "",
             f"{len(OP_REGISTRY)} registered ops.",
             "",
             "| op | signature | doc |",
             "|---|---|---|"]
    for name in sorted(OP_REGISTRY):
        d = OP_REGISTRY[name]
        try:
            sig = str(inspect.signature(d.fn))
        except (TypeError, ValueError):
            sig = "(...)"
        doc = (d.doc or "").split("\n")[0].replace("|", "\\|")
        lines.append(f"| `{name}` | `{sig}` | {doc} |")
    text = "\n".join(lines) + "\n"
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    with open(out_path, "w") as f:
        f.write(text)
    return out_path


if __name__ == "__main__":
    path = generate(sys.argv[1] if len(sys.argv) > 1 else "docs/OPS.md")
    print(f"wrote {path}")
