"""Generate the op-surface reference from the schema registry.

The reference drives codegen (C++ API, grad nodes, bindings, docs) from
``paddle/phi/api/yaml/ops.yaml``; here the registry IS the runtime op table
(``core.dispatch.OP_REGISTRY``) and this generator derives the docs from it
— one source of truth, no drift.

    python -m paddle_tpu.ops.gen_docs [out_path]
"""

from __future__ import annotations

import inspect
import sys


def generate(out_path: str = "docs/OPS.md") -> str:
    import os

    # populate the registry: the tensor surface plus every domain that
    # registers kernels (upstream: one ops.yaml covers them all)
    import paddle_tpu.ops  # noqa: F401
    import paddle_tpu.nn.functional  # noqa: F401
    import paddle_tpu.sparse  # noqa: F401
    import paddle_tpu.signal  # noqa: F401
    import paddle_tpu.geometric  # noqa: F401
    import paddle_tpu.vision.ops  # noqa: F401
    import paddle_tpu.fft  # noqa: F401
    import paddle_tpu.audio  # noqa: F401
    import paddle_tpu.incubate.nn.functional  # noqa: F401
    import paddle_tpu.distributed.moe_utils  # noqa: F401
    import paddle_tpu.optimizer  # noqa: F401
    import paddle_tpu.distributed.ps  # noqa: F401
    import paddle_tpu.vision.transforms  # noqa: F401
    import paddle_tpu.text  # noqa: F401
    import paddle_tpu.metric  # noqa: F401
    from paddle_tpu.core.dispatch import OP_REGISTRY
    from paddle_tpu.ops.sweep_specs import attach_specs, sweep_coverage
    attach_specs()

    lines = ["# Op surface reference",
             "",
             "Generated from `core.dispatch.OP_REGISTRY` (the ops.yaml-"
             "equivalent single source of truth) by "
             "`python -m paddle_tpu.ops.gen_docs`. Do not edit by hand.",
             "",
             f"{len(OP_REGISTRY)} registered ops.",
             "",
             "Sweep coverage (tests/test_op_sweep.py: numpy/scipy oracle + "
             "finite-difference grad + bf16 legs, from the schema's "
             "category tags and OpDef.sweep specs): "
             f"**{sweep_coverage()[0]} of {sweep_coverage()[1]} ops "
             f"({100 * sweep_coverage()[0] // sweep_coverage()[1]}%)**; "
             "the rest are covered by hand-written domain tests "
             "(tests/test_*.py) or are stateful/random/IO ops outside the "
             "oracle pattern.",
             ""]
    # serving ops surface (ISSUE 6): the health_snapshot() payload an ops
    # endpoint serves, generated from the engine's field registry (the
    # snapshot test pins the live payload to the same registry)
    from paddle_tpu.inference.serving.engine import HEALTH_SNAPSHOT_FIELDS
    lines += ["## Serving health surface",
              "",
              "`inference.serving.ServingEngine.health_snapshot()` "
              "(docs/SERVING.md \"Overload & multi-tenancy\") returns one "
              "JSON-serializable record per call — the payload the "
              "serving endpoints below serve. "
              "`EngineSupervisor.health_snapshot()` adds the "
              "supervisor-level fields on top:",
              "",
              "| field | meaning |",
              "|---|---|"]
    lines += [f"| `{k}` | {v} |" for k, v in HEALTH_SNAPSHOT_FIELDS.items()]
    # serving front line (ISSUE 7): endpoints + drain/restart runbook +
    # the server flag table, all generated from the live registries so
    # the runbook cannot drift from the code
    from paddle_tpu.flags import flags_table, get_flags
    lines += [
        "",
        "## Serving front line (`inference.serving.server`)",
        "",
        "`ServingServer` multiplexes any number of streaming clients "
        "onto ONE supervised engine thread: submissions cross a "
        "thread-safe command queue, token/finish events come back on "
        "bounded per-client asyncio queues (SSE frames over the TCP "
        "transport; dict events over the in-process transport the tier-1 "
        "tests use). A consumer that falls `FLAGS_serving_client_queue` "
        "events behind is disconnected and its request cancelled — KV "
        "freed, nothing pinned.",
        "",
        "### Endpoints",
        "",
        "| endpoint | verb | serves | status |",
        "|---|---|---|---|",
        "| `/healthz` | GET | liveness: pump thread alive and the hang "
        "watchdog quiet | 200 / 503 |",
        "| `/readyz` | GET | readiness: accepting (not draining/closed) "
        "AND engine restart budget intact AND queue below its bound | "
        "200 / 503 |",
        "| `/metrics` | GET | the full supervisor `health_snapshot()` "
        "(fields above), incl. per-tenant TTFT/TPOT p50/p99 and the "
        "`autoscale` recommendation | 200 |",
        "| `/generate` | POST | SSE token stream for `{\"prompt\": "
        "[ids], ...submit kwargs}`; 503 + `retry_after_s` while "
        "draining/broken, 429 + `retry_after_s` when the bounded queue "
        "sheds | 200 / 429 / 503 / 400 |",
        "",
        "### Restart runbook (engine supervision)",
        "",
        "The engine step loop runs under `EngineSupervisor`'s crash "
        "barrier: an unexpected exception — or a hang-watchdog trip "
        "naming a `serving.*` section — tears the engine down, rebuilds "
        "it from the same params/config (reusing the compiled "
        "`EnginePrograms`: recovery never recompiles), and re-submits "
        "every non-terminal request (queued verbatim; running from "
        "`prompt + tokens so far` on the preemption-recompute path — "
        "greedy outputs stay bit-identical, no delivered token "
        "repeats). Each recovery consumes one unit of the "
        "`FLAGS_serving_max_restarts` budget; when it runs out the "
        "replica flips BROKEN: `/readyz` 503, submits refused, in-flight "
        "requests failed with partials readable. Page on: `restarts` "
        "climbing (crash loop brewing), `broken: true` (replace the "
        "replica), `watchdog.fired` (a dispatch hung).",
        "",
        "### Drain runbook (deploys / preemption)",
        "",
        "SIGTERM — forwarded by the elastic launcher on preemption "
        "(`--preempt_grace`, exported as `PADDLE_PREEMPT_GRACE`) — or "
        "`close()` starts a graceful drain: (1) admissions stop, new "
        "submits get the structured 503 + `retry_after_s`; (2) in-flight "
        "requests finish within the deadline "
        "(`PADDLE_PREEMPT_GRACE - 2s` when the launcher set it, else "
        "`FLAGS_serving_drain_deadline_s`); (3) the remainder is "
        "cancelled, every KV block returns to the pool (the drain "
        "report's `leaked_blocks` must read 0).",
        "",
        "### Autoscale hook",
        "",
        "`EngineSupervisor.autoscale_signal()` turns queue-depth / "
        "shed-rate / slot-utilization telemetry into `scale_up` / "
        "`scale_in` / `hold`, and can write the elastic launcher's "
        "`--elastic_rejoin_file` format "
        "(`distributed.launch.main.write_rejoin_file`: empty file = "
        "take what you need, integer = offered worker count) so a "
        "watching launcher scales the job out.",
        "",
        "### Server / serving flags",
        ""]
    lines += flags_table(sorted(n for n in get_flags()
                                if n.startswith("FLAGS_serving_")))
    lines += ["",
              "## Op table",
              "",
              "| op | signature | doc |",
              "|---|---|---|"]
    for name in sorted(OP_REGISTRY):
        d = OP_REGISTRY[name]
        try:
            sig = str(inspect.signature(d.fn))
        except (TypeError, ValueError):
            sig = "(...)"
        doc = (d.doc or "").split("\n")[0].replace("|", "\\|")
        lines.append(f"| `{name}` | `{sig}` | {doc} |")
    text = "\n".join(lines) + "\n"
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    with open(out_path, "w") as f:
        f.write(text)
    return out_path


if __name__ == "__main__":
    path = generate(sys.argv[1] if len(sys.argv) > 1 else "docs/OPS.md")
    print(f"wrote {path}")
