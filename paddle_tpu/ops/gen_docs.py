"""Generate the op-surface reference from the schema registry.

The reference drives codegen (C++ API, grad nodes, bindings, docs) from
``paddle/phi/api/yaml/ops.yaml``; here the registry IS the runtime op table
(``core.dispatch.OP_REGISTRY``) and this generator derives the docs from it
— one source of truth, no drift.

    python -m paddle_tpu.ops.gen_docs [out_path]
"""

from __future__ import annotations

import inspect
import sys


def generate(out_path: str = "docs/OPS.md") -> str:
    import os

    # populate the registry: the tensor surface plus every domain that
    # registers kernels (upstream: one ops.yaml covers them all)
    import paddle_tpu.ops  # noqa: F401
    import paddle_tpu.nn.functional  # noqa: F401
    import paddle_tpu.sparse  # noqa: F401
    import paddle_tpu.signal  # noqa: F401
    import paddle_tpu.geometric  # noqa: F401
    import paddle_tpu.vision.ops  # noqa: F401
    import paddle_tpu.fft  # noqa: F401
    import paddle_tpu.audio  # noqa: F401
    import paddle_tpu.incubate.nn.functional  # noqa: F401
    import paddle_tpu.distributed.moe_utils  # noqa: F401
    import paddle_tpu.optimizer  # noqa: F401
    import paddle_tpu.distributed.ps  # noqa: F401
    import paddle_tpu.vision.transforms  # noqa: F401
    import paddle_tpu.text  # noqa: F401
    import paddle_tpu.metric  # noqa: F401
    from paddle_tpu.core.dispatch import OP_REGISTRY
    from paddle_tpu.ops.sweep_specs import attach_specs, sweep_coverage
    attach_specs()

    lines = ["# Op surface reference",
             "",
             "Generated from `core.dispatch.OP_REGISTRY` (the ops.yaml-"
             "equivalent single source of truth) by "
             "`python -m paddle_tpu.ops.gen_docs`. Do not edit by hand.",
             "",
             f"{len(OP_REGISTRY)} registered ops.",
             "",
             "Sweep coverage (tests/test_op_sweep.py: numpy/scipy oracle + "
             "finite-difference grad + bf16 legs, from the schema's "
             "category tags and OpDef.sweep specs): "
             f"**{sweep_coverage()[0]} of {sweep_coverage()[1]} ops "
             f"({100 * sweep_coverage()[0] // sweep_coverage()[1]}%)**; "
             "the rest are covered by hand-written domain tests "
             "(tests/test_*.py) or are stateful/random/IO ops outside the "
             "oracle pattern.",
             ""]
    # serving ops surface (ISSUE 6): the health_snapshot() payload an ops
    # endpoint serves, generated from the engine's field registry (the
    # snapshot test pins the live payload to the same registry)
    from paddle_tpu.inference.serving.engine import HEALTH_SNAPSHOT_FIELDS
    lines += ["## Serving health surface",
              "",
              "`inference.serving.ServingEngine.health_snapshot()` "
              "(docs/SERVING.md \"Overload & multi-tenancy\") returns one "
              "JSON-serializable record per call — the payload the "
              "serving endpoints below serve. "
              "`EngineSupervisor.health_snapshot()` adds the "
              "supervisor-level fields on top:",
              "",
              "| field | meaning |",
              "|---|---|"]
    lines += [f"| `{k}` | {v} |" for k, v in HEALTH_SNAPSHOT_FIELDS.items()]
    # serving front line (ISSUE 7): endpoints + drain/restart runbook +
    # the server flag table, all generated from the live registries so
    # the runbook cannot drift from the code
    from paddle_tpu.flags import flags_table, get_flags
    lines += [
        "",
        "## Serving front line (`inference.serving.server`)",
        "",
        "`ServingServer` multiplexes any number of streaming clients "
        "onto ONE supervised engine thread: submissions cross a "
        "thread-safe command queue, token/finish events come back on "
        "bounded per-client asyncio queues (SSE frames over the TCP "
        "transport; dict events over the in-process transport the tier-1 "
        "tests use). A consumer that falls `FLAGS_serving_client_queue` "
        "events behind is disconnected and its request cancelled — KV "
        "freed, nothing pinned.",
        "",
        "### Endpoints",
        "",
        "| endpoint | verb | serves | status |",
        "|---|---|---|---|",
        "| `/healthz` | GET | liveness: pump thread alive and the hang "
        "watchdog quiet | 200 / 503 |",
        "| `/readyz` | GET | readiness: accepting (not draining/closed) "
        "AND engine restart budget intact AND queue below its bound | "
        "200 / 503 |",
        "| `/metrics` | GET | the full supervisor `health_snapshot()` "
        "(fields above), incl. per-tenant TTFT/TPOT p50/p99 and the "
        "`autoscale` recommendation | 200 |",
        "| `/generate` | POST | SSE token stream for `{\"prompt\": "
        "[ids], ...submit kwargs}`; 503 + `retry_after_s` while "
        "draining/broken, 429 + `retry_after_s` when the bounded queue "
        "sheds | 200 / 429 / 503 / 400 |",
        "",
        "### Restart runbook (engine supervision)",
        "",
        "The engine step loop runs under `EngineSupervisor`'s crash "
        "barrier: an unexpected exception — or a hang-watchdog trip "
        "naming a `serving.*` section — tears the engine down, rebuilds "
        "it from the same params/config (reusing the compiled "
        "`EnginePrograms`: recovery never recompiles), and re-submits "
        "every non-terminal request (queued verbatim; running from "
        "`prompt + tokens so far` on the preemption-recompute path — "
        "greedy outputs stay bit-identical, no delivered token "
        "repeats). Each recovery consumes one unit of the "
        "`FLAGS_serving_max_restarts` budget; when it runs out the "
        "replica flips BROKEN: `/readyz` 503, submits refused, in-flight "
        "requests failed with partials readable. Page on: `restarts` "
        "climbing (crash loop brewing), `broken: true` (replace the "
        "replica), `watchdog.fired` (a dispatch hung).",
        "",
        "### Drain runbook (deploys / preemption)",
        "",
        "SIGTERM — forwarded by the elastic launcher on preemption "
        "(`--preempt_grace`, exported as `PADDLE_PREEMPT_GRACE`) — or "
        "`close()` starts a graceful drain: (1) admissions stop, new "
        "submits get the structured 503 + `retry_after_s`; (2) in-flight "
        "requests finish within the deadline "
        "(`PADDLE_PREEMPT_GRACE - 2s` when the launcher set it, else "
        "`FLAGS_serving_drain_deadline_s`); (3) the remainder is "
        "cancelled, every KV block returns to the pool (the drain "
        "report's `leaked_blocks` must read 0).",
        "",
        "### Cold-restart runbook (durable serving)",
        "",
        "With `FLAGS_serving_journal_dir` set, every replica logs its "
        "request lifecycle to ONE shared `RequestJournal` "
        "(`inference.serving.journal`): an append-only WAL of "
        "crc-framed submit / token-cursor / ownership-rebase / terminal "
        "events, fsynced once per engine step "
        "(`FLAGS_serving_journal_sync`; admissions fsync at submit so "
        "an ACKED request is never lost), plus a serving-state snapshot "
        "every `FLAGS_serving_snapshot_every` flushes (tmp + fsync + "
        "rename, newest two generations kept) that bounds replay "
        "length. KV is NEVER persisted — recovery recomputes it through "
        "the resubmit path. After a `kill -9` (or host loss with the "
        "journal on durable storage): "
        "`EngineSupervisor.recover(journal_dir, params, cfg, ...)` for "
        "one replica, `ServingRouter.cold_start(journal_dir, ...)` for "
        "a fleet. Recovery loads the newest snapshot that verifies "
        "(corrupt generations are skipped — `snapshot_fallbacks` "
        "counts them), replays the WAL suffix (a torn tail is "
        "truncated to the last whole frame — `torn_tail_bytes`), "
        "closes records whose delivered tokens already complete them, "
        "and resubmits everything else bit-exactly from `prompt + "
        "delivered-so-far` under its original journal id — zero lost "
        "requests, zero re-delivered tokens, greedy and seeded streams "
        "bit-identical (the `durable_exactly_once` auditor check and "
        "`bench --serve`'s `serving_recovery_ms` row hold the line; "
        "journal overhead is asserted < 5% there). A graceful SIGTERM "
        "drain writes a final snapshot, so the next cold start replays "
        "nothing. Watch: `torn_tail_bytes` > 0 (the crash cut a "
        "write), `snapshot_fallbacks` climbing (snapshot corruption — "
        "check the disk), `resubmitted`/`recovered_tokens` (work "
        "re-entering the fleet after recovery).",
        "",
        "### Autoscale hook",
        "",
        "`EngineSupervisor.autoscale_signal()` turns queue-depth / "
        "shed-rate / slot-utilization telemetry into `scale_up` / "
        "`scale_in` / `hold`, and can write the elastic launcher's "
        "`--elastic_rejoin_file` format "
        "(`distributed.launch.main.write_rejoin_file`: empty file = "
        "take what you need, integer = offered worker count) so a "
        "watching launcher scales the job out.",
        "",
        "### Server / serving flags",
        ""]
    lines += flags_table(sorted(
        n for n in get_flags()
        if n.startswith("FLAGS_serving_")
        and not n.startswith("FLAGS_serving_router_")))
    # serving fleet (ISSUE 9): the multi-replica router tier — breaker
    # states, failover/rolling-restart runbooks, the router snapshot
    # registry and the router flag table, all from the live registries
    from paddle_tpu.inference.serving.router import ROUTER_HEALTH_FIELDS
    lines += [
        "",
        "## Serving fleet (`inference.serving.router`)",
        "",
        "`ServingRouter` fronts N in-process replicas — each a full "
        "supervisor/server stack — sharing ONE set of params and ONE "
        "compiled `EnginePrograms` (spawning or rebuilding a replica "
        "never recompiles). Every submit probes the candidates "
        "(`/readyz` predicate + `health_snapshot()`; a raising probe is "
        "a breaker failure) and picks by power-of-two-choices on queue "
        "depth, with tenant/prefix-affinity stickiness keeping "
        "shared-prefix traffic on the replica that holds its cached KV "
        "blocks. `ServingServer` front-lines a router exactly as it "
        "front-lines one supervisor — same endpoints, same SSE streams.",
        "",
        "### Circuit breaker states",
        "",
        "| state | traffic | transition |",
        "|---|---|---|",
        "| `closed` | flows; consecutive failures counted | "
        "`FLAGS_serving_router_breaker_threshold` failures in a row "
        "(probe raises, submit unavailability, supervisor restarts) "
        "-> `open`; a replica going BROKEN trips it immediately |",
        "| `open` | none — the router routes around the replica and "
        "EVACUATES its in-flight requests (failover from delivered "
        "tokens, bit-exact) | after "
        "`FLAGS_serving_router_breaker_cooldown_s` the next routing "
        "decision runs a half-open probe |",
        "| `half_open` | one health probe, no user traffic at risk | "
        "probe success -> `closed` (the replica rejoins); failure -> "
        "`open` with a fresh cooldown |",
        "",
        "### Failover runbook",
        "",
        "A replica that exhausts its restart budget (`broken`) or opens "
        "its breaker loses its traffic: every non-terminal request is "
        "resubmitted to a healthy replica from `prompt + tokens "
        "delivered so far` (`EngineSupervisor.resubmit`, the "
        "preemption-recompute path) — greedy outputs stay bit-identical "
        "and no delivered token repeats. With NO routable replica left "
        "the request goes state `failed` (partial readable) and "
        "`counters.failed` increments — page on it. Watch: "
        "`counters.failovers` climbing (a replica is flapping), "
        "`fleet.routable` vs `fleet.size` (capacity lost), "
        "`replicas.<rid>.breaker.state` (who is walled off).",
        "",
        "### Rolling-restart runbook (deploys)",
        "",
        "`start_rolling_restart()` (or the blocking `rolling_restart()`)"
        " drains ONE replica at a time — admissions shift to the rest of "
        "the fleet, in-flight work finishes (or fails over at the drain "
        "deadline), the replica rebuilds from the shared programs "
        "(generation bumps, breaker resets), and the roll moves on. A "
        "live trace served across the roll completes with ZERO failed "
        "requests — `counters.failed` staying 0 is the acceptance "
        "invariant. A `broken` replica is healed by the roll: its "
        "rebuild gets a fresh restart budget.",
        "",
        "### Drain-with-migration runbook (live KV migration)",
        "",
        "With `FLAGS_serving_migrate` on (or `RouterConfig(migrate="
        "True)`), every router-initiated drain — `drain_replica()` for "
        "scale-in, each per-replica drain of a rolling restart, and the "
        "deadline sweep before evacuation — first LIVE-MIGRATES the "
        "draining replica's in-flight requests instead of waiting them "
        "out: `EngineSupervisor.export_request` serializes the request's "
        "resolved decode state plus its KV block chain "
        "(`ServingEngine.serialize_request`), a healthy candidate "
        "adopts it (`adopt` — shape-key-checked, all-or-nothing: any "
        "refusal frees everything it touched and raises `AdoptError`), "
        "and only after the adoptive route is installed is the origin "
        "copy released (`release_migrated` — exactly-once by "
        "construction: the route moves before the origin cancel, so the "
        "drain-cancel sweep can never double-failover the request). "
        "Decoding continues on the survivor with ZERO recomputed "
        "tokens and a bit-identical stream; PRNG continuity for sampled "
        "requests rides the serialized state. When NO candidate can "
        "take the blocks (pool full, no slot, mismatched shape key) the "
        "request falls back to the PR 9 resubmit path at the drain "
        "deadline — `counters.migration_fallbacks` counts these; "
        "correctness is unchanged, only the recompute cost returns. "
        "Watch: `counters.migrations` / `migration_tokens` (work "
        "preserved), `migration_fallbacks` climbing (targets too full "
        "to adopt — add capacity before rolling), and the auditor's "
        "`migration_exactly_once` check, which fails the fleet if a "
        "migrated stream ever diverges from its router-side mirror.",
        "",
        "### Autoscale actuation",
        "",
        "`router.autoscale()` acts on the fleet-aggregated "
        "`autoscale_signal()`: scale-up SPAWNS a replica (up to "
        "`FLAGS_serving_router_max_replicas`) and optionally writes the "
        "elastic launcher's `--elastic_rejoin_file`; scale-in DRAINS the "
        "least-loaded replica (never below one). `router.poll_rejoin()` "
        "consumes the same file format back "
        "(`distributed.launch.main.consume_rejoin_file`), so an external "
        "autoscaler can drive fleet size through one file.",
        "",
        "### Router health surface",
        "",
        "`ServingRouter.health_snapshot()` — keys pinned to "
        "`ROUTER_HEALTH_FIELDS` by the snapshot test:",
        "",
        "| field | meaning |",
        "|---|---|"]
    lines += [f"| `{k}` | {v} |" for k, v in ROUTER_HEALTH_FIELDS.items()]
    lines += [
        "",
        "### Router flags",
        ""]
    lines += flags_table(sorted(
        n for n in get_flags()
        if n.startswith("FLAGS_serving_router_")))
    # fleet-scale replay + invariant audit (ISSUE 13): the auditor check
    # table renders straight from the AUDIT_CHECKS registry and the
    # replay runbook documents the manifest contract, so neither can
    # drift from audit.py/workload.py
    from paddle_tpu.inference.serving.audit import AUDIT_CHECKS
    lines += [
        "",
        "## Workload replay & capacity planning "
        "(`inference.serving.workload` / `.audit`)",
        "",
        "The fleet-scale proof layer: a DETERMINISTIC workload generator "
        "(`WorkloadSpec`/`generate_trace` — diurnal/bursty arrivals, "
        "Zipf tenants, shared-prefix prompt families, mixed greedy/"
        "sampled knobs, priorities/deadlines, client cancels/disconnects/"
        "abandons, and 429/503 retries that back off by the returned "
        "`retry_after_s`), replayed through a multi-replica router by "
        "`run_replay` under a seeded step-indexed chaos timeline "
        "(`testing.chaos.chaos_timeline`) while the autoscaler actuates, "
        "with the `InvariantAuditor` sampling throughout and running "
        "exhaustively at quiesce.",
        "",
        "### Invariant auditor",
        "",
        "`InvariantAuditor` evaluates the registry below against a live "
        "engine / supervisor / router; a failure raises a structured "
        "`InvariantViolation` naming the CHECK, the REPLICA and the "
        "replay MANIFEST that reproduces it. Three deployment modes: "
        "per-step in tests (the one definition of each invariant the "
        "test suite's fuzzes call), sampled in long replays "
        "(`WorkloadSpec.audit_every`), and in production — "
        "`router.audit()`, folded into `health_snapshot()` behind "
        "`FLAGS_serving_audit` (off by default: the checks walk every "
        "block map).",
        "",
        "| check | proves |",
        "|---|---|"]
    lines += [f"| `{k}` | {v} |" for k, v in AUDIT_CHECKS.items()]
    lines += [
        "",
        "### Replay runbook",
        "",
        "1. Every `run_replay` emits a `ReplayManifest` (seed + spec + "
        "chaos schedule + the resolved `ServingConfig` and "
        "`RouterConfig` scalars + the starting replica count, plus the "
        "`FLAGS_serving_*` values recorded for the operator's "
        "reference — both configs resolve from them eagerly, so the "
        "shape fields already carry the values that mattered; "
        "`manifest_json` in the report) and stamps it into every "
        "violation. To reproduce a fleet-scale failure bit-exactly: "
        "`run_replay(params, cfg, "
        "manifest=ReplayManifest.from_json(s))` — the captured engine "
        "+ fleet shape is re-applied (pass `serving_config=` / "
        "`router_config=` / `replicas=` to override), same per-request "
        "token streams, same chaos firing order, same audit trail "
        "(`retry_policy=\"fixed\"`; the `\"hint\"` policy honors the "
        "measured wall-clock `retry_after_s`, so shed counts then track "
        "host load).",
        "2. Chaos timelines are STEP-indexed, never wall-clock: an event "
        "fires at the identical point in the request stream on every "
        "replay. `replica_kill` is skipped (and logged) when fewer than "
        "two adoption-capable replicas remain — killing the sole "
        "survivor proves nothing about failover.",
        "3. The driver's clients are part of the workload: a shed submit "
        "retries after the backoff its policy dictates, misbehaving "
        "clients cancel/disconnect/abandon at scripted token counts, "
        "and client-side step deadlines cancel overdue work.",
        "4. The report's acceptance surface: `violations == []`, "
        "`failed == 0` (no request stranded without a replica), "
        "`leaked_blocks == 0` on every replica at quiesce, autoscale "
        "`spawns`/`drains` >= 1 each with the measured arrival-TTFT "
        "p99 effect vs the fixed-fleet counterfactual "
        "(`bench --serve`'s replay row asserts all of it).",
        "",
        "### Capacity report",
        "",
        "`capacity_report` (emitted with every replay, standalone "
        "callable) combines the `paged_pool_block_bytes` arithmetic — "
        "per-chip block cost and concurrent sequences across fp/int8 x "
        "TP degree at an HBM budget — with the replay's measured "
        "curves: req/s, TTFT/TPOT p50/p99, `goodput_tok_s_per_chip` "
        "(SLO-met tokens per second per chip — the "
        "`serving_replay_goodput` bench metric), and the sizing line "
        "(\"X replicas of config Y serve Z req/s within SLO\") plus "
        "`replicas_for_<N>_req_s` projections.",
    ]
    lines += ["",
              "## Op table",
              "",
              "| op | signature | doc |",
              "|---|---|---|"]
    for name in sorted(OP_REGISTRY):
        d = OP_REGISTRY[name]
        try:
            sig = str(inspect.signature(d.fn))
        except (TypeError, ValueError):
            sig = "(...)"
        doc = (d.doc or "").split("\n")[0].replace("|", "\\|")
        lines.append(f"| `{name}` | `{sig}` | {doc} |")
    text = "\n".join(lines) + "\n"
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    with open(out_path, "w") as f:
        f.write(text)
    return out_path


if __name__ == "__main__":
    path = generate(sys.argv[1] if len(sys.argv) > 1 else "docs/OPS.md")
    print(f"wrote {path}")
