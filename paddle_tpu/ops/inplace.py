"""In-place op variants (``add_``, ``tanh_``, ...).

Parity target: the ``*_`` inplace API family of ``python/paddle/tensor/*``
(generated upstream by the inplace pass over ops.yaml). TPU redesign: jax
arrays are immutable, so "in place" means compute-out-of-place then REBIND
the Tensor's buffer (``Tensor._rebind`` — bumps the inplace version counter
and keeps the autograd graph flowing through the new value; the same
semantics the reference's inplace grad nodes provide, minus the buffer
aliasing XLA would not allow across programs anyway).

Every variant is registered in OP_REGISTRY (docs/OPS.md) pointing at the
base op's kernel fn.
"""

from __future__ import annotations

from ..core.dispatch import OP_REGISTRY, register_op

__all__ = []  # populated below


def _make(base_name: str, base_fn):
    def op(x, *args, **kwargs):
        out = base_fn(x, *args, **kwargs)
        x._rebind(out)
        return x

    op.__name__ = base_name + "_"
    op.__qualname__ = op.__name__
    op.__doc__ = (f"In-place variant of ``{base_name}`` (rebinds the "
                  f"tensor's buffer; ref: paddle.Tensor.{base_name}_).")
    base = OP_REGISTRY.get(base_name)
    register_op(base_name + "_",
                base.fn if base else (lambda v: v),
                f"In-place variant of {base_name}.",
                differentiable=base.differentiable if base else True)
    return op


# base-op names whose paddle API includes an inplace twin; only generated
# when the base exists here (asserted below so drift is loud)
_INPLACE_BASES = [
    "acos", "acosh", "asin", "asinh", "atan", "atanh", "atan2",
    "cos", "cosh", "sin", "sinh", "tan", "tanh",
    "erf", "erfinv", "exp", "expm1", "log", "log10", "log1p", "log2",
    "logit", "sigmoid", "square", "trunc", "frac", "digamma", "lgamma",
    "gammaln", "i0", "nan_to_num", "copysign", "hypot", "ldexp", "lerp",
    "bitwise_and", "bitwise_or", "bitwise_xor", "bitwise_not",
    "logical_and", "logical_or", "logical_xor", "logical_not",
    "greater_equal", "greater_than", "less_equal", "less_than", "not_equal",
    "remainder", "mod", "floor_divide",
    "tril", "triu", "masked_fill", "index_fill", "index_put", "index_add",
    "put_along_axis", "renorm",
    # r4 breadth: arithmetic/rounding/shape in-place twins (paddle's
    # generated inplace pass covers these upstream)
    "add", "subtract", "multiply", "divide", "pow", "clip", "ceil", "floor",
    "round", "rsqrt", "sqrt", "reciprocal", "neg", "scale", "flatten",
    "reshape", "squeeze", "unsqueeze", "flip", "cumsum", "cumprod",
    "exp2", "expit", "erfc", "maximum", "minimum", "fmax", "fmin",
    "heaviside", "deg2rad", "rad2deg", "sinc", "xlogy",
    "sort", "sgn", "igamma", "igammac", "polygamma", "index_copy",
    "scatter_add", "scatter_reduce", "true_divide", "trunc_divide",
    "divide_no_nan", "bitwise_invert", "masked_scatter",
    "take_along_dim", "narrow", "clip_by_norm",
    # r5: remaining genuine upstream inplace twins
    "fill_diagonal_tensor",
]


def _populate():
    import paddle_tpu.ops as _ops

    made = {}
    missing = []
    for base in _INPLACE_BASES:
        fn = getattr(_ops, base, None)
        if fn is None:
            missing.append(base)
            continue
        made[base + "_"] = _make(base, fn)
    if missing:
        raise ImportError(
            f"inplace generation: base ops missing from the surface: "
            f"{missing} (add them or drop from _INPLACE_BASES)")
    return made


_generated = _populate()
globals().update(_generated)
__all__ = sorted(_generated)

# upstream exposes every inplace twin as a Tensor METHOD (x.tanh_(),
# x.scatter_(...)); mirror that for the generated family (math.py patches
# its own hand-written subset first — don't shadow those)
from ..core.tensor import Tensor as _T  # noqa: E402

for _mname, _mfn in _generated.items():
    if not hasattr(_T, _mname):
        setattr(_T, _mname, _mfn)


def _fill(x, value):
    import jax.numpy as jnp
    from ._helpers import ensure_tensor, forward_op
    t = ensure_tensor(x)
    out = forward_op("fill", lambda v: jnp.full_like(v, value), [t])
    t._rebind(out)
    return t


def fill_(x, value, name=None):
    """Set every element to ``value`` (ref: paddle.Tensor.fill_)."""
    return _fill(x, value)


def zero_(x, name=None):
    """Set every element to 0 (ref: paddle.Tensor.zero_)."""
    return _fill(x, 0)


def fill_diagonal_(x, value, offset: int = 0, wrap: bool = False, name=None):
    """Write ``value`` onto the (offset) diagonal; ``wrap`` repeats the
    diagonal down tall matrices, numpy-style (ref: Tensor.fill_diagonal_)."""
    import numpy as np

    import jax.numpy as jnp
    from ._helpers import ensure_tensor, forward_op
    t = ensure_tensor(x)

    def impl(v):
        H, W = v.shape[-2], v.shape[-1]
        r0, c0 = (0, offset) if offset >= 0 else (-offset, 0)
        n = max(0, min(H - r0, W - c0))
        rows = np.arange(n) + r0
        cols = np.arange(n) + c0
        if wrap and offset == 0 and H > W:
            # numpy wrap semantics: restart the diagonal every W+1 rows
            rows, cols = [], []
            start = 0
            while start < H:
                m = min(W, H - start)
                rows.append(np.arange(m) + start)
                cols.append(np.arange(m))
                start += W + 1
            rows = np.concatenate(rows)
            cols = np.concatenate(cols)
        return v.at[..., jnp.asarray(rows), jnp.asarray(cols)].set(value)

    out = forward_op("fill_diagonal", impl, [t])
    t._rebind(out)
    return t


register_op("fill", lambda v: v * 0, "Fill with a scalar (in place).")
register_op("zero_", lambda v: v * 0, "Zero the tensor (in place).")
register_op("fill_diagonal", lambda v: v, "Write the diagonal (in place).")
__all__ += ["fill_", "zero_", "fill_diagonal_"]
