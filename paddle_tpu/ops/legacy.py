"""Legacy / long-tail op cluster.

Parity targets: scattered singles from ``paddle/fluid/operators`` and
``python/paddle/tensor`` that predate the phi reorganization — batch-size-
like creation ops, CTR ops (cvm, data_norm, shuffle_batch), per-slot
batch_fc, partial concat/sum, layout shuffles (space_to_depth), plus newer
tensor API entries (nonzero_static, fill_diagonal_tensor, pca_lowrank).

TPU notes: everything stays static-shape (nonzero_static exists upstream
precisely because nonzero's dynamic shape breaks compiled graphs — the op
IS the TPU formulation); random ops draw from the framework generator
eagerly; the rest are jnp one-liners or einsums.
"""

from __future__ import annotations

import math as _math

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ._helpers import Tensor, axes_arg, ensure_tensor, forward_op

__all__ = [
    "exprel", "multigammaln", "reduce_as", "addbmm", "pca_lowrank",
    "im2col", "is_integer", "contiguous", "log_normal", "space_to_depth",
    "depth_to_space", "affine_channel", "data_norm", "fill_any",
    "fill_any_like", "unique_with_counts", "partial_concat", "partial_sum",
    "shuffle_batch", "batch_fc", "cvm", "sampling_id",
    "uniform_random_batch_size_like", "gaussian_random_batch_size_like",
    "fill_constant_batch_size_like", "dropout_nd",
    "fused_embedding_seq_pool", "nonzero_static", "fill_diagonal_tensor",
]


def exprel(x, name=None):
    """(e^x - 1) / x, -> 1 at 0 (scipy.special.exprel parity)."""
    def impl(v):
        small = jnp.abs(v) < 1e-8
        safe = jnp.where(small, 1.0, v)
        return jnp.where(small, 1.0 + v / 2, jnp.expm1(safe) / safe)
    return forward_op("exprel", impl, [ensure_tensor(x)])


def multigammaln(x, p: int, name=None):
    """Log multivariate gamma (scipy.special.multigammaln parity)."""
    def impl(v):
        c = 0.25 * p * (p - 1) * _math.log(_math.pi)
        return c + sum(jax.scipy.special.gammaln(v - 0.5 * j)
                       for j in range(p))
    return forward_op("multigammaln", impl, [ensure_tensor(x)])


def reduce_as(x, target, name=None):
    """Sum-reduce ``x`` down to ``target``'s shape (ref: paddle.reduce_as)."""
    xt = ensure_tensor(x)
    tt = ensure_tensor(target)

    def impl(v, t):
        extra = v.ndim - t.ndim
        if extra:
            v = v.sum(tuple(range(extra)))
        axes = tuple(i for i in range(v.ndim)
                     if t.shape[i] == 1 and v.shape[i] != 1)
        return v.sum(axes, keepdims=True) if axes else v

    return forward_op("reduce_as", impl, [xt, tt])


def addbmm(input, x, y, beta: float = 1.0, alpha: float = 1.0, name=None):
    """beta*input + alpha*sum_b(x[b] @ y[b]) (torch.addbmm parity)."""
    return forward_op(
        "addbmm",
        lambda i, a, b: beta * i + alpha * jnp.einsum("bik,bkj->ij", a, b),
        [ensure_tensor(input), ensure_tensor(x), ensure_tensor(y)])


def pca_lowrank(x, q=None, center: bool = True, niter: int = 2, name=None):
    """Randomized low-rank PCA -> (U, S, V) (torch.pca_lowrank parity;
    power-iterated randomized range finder, all dense matmuls)."""
    xt = ensure_tensor(x)
    m, n = int(xt.shape[-2]), int(xt.shape[-1])
    q = q if q is not None else min(6, m, n)

    def impl(v):
        a = v - v.mean(-2, keepdims=True) if center else v
        key = jax.random.PRNGKey(0)
        omega = jax.random.normal(key, (n, q), a.dtype)
        y = a @ omega
        for _ in range(niter):
            y = a @ (a.T @ y)
        Q, _ = jnp.linalg.qr(y)
        b = Q.T @ a
        u, s, vt = jnp.linalg.svd(b, full_matrices=False)
        return Q @ u, s, vt.T

    return forward_op("pca_lowrank", impl, [xt])


def im2col(x, kernel_size, stride=1, padding=0, dilation=1, name=None):
    """Patch extraction [B, C, H, W] -> [B, C*kh*kw, L] (ref: im2col — the
    unfold kernel; one conv_general_dilated_patches call)."""
    xt = ensure_tensor(x)
    kh, kw = ((kernel_size, kernel_size) if isinstance(kernel_size, int)
              else tuple(kernel_size))
    sh, sw = (stride, stride) if isinstance(stride, int) else tuple(stride)
    ph, pw = (padding, padding) if isinstance(padding, int) else tuple(padding)
    dh, dw = (dilation, dilation) if isinstance(dilation, int) \
        else tuple(dilation)

    def impl(v):
        p = lax.conv_general_dilated_patches(
            v, (kh, kw), (sh, sw), [(ph, ph), (pw, pw)],
            rhs_dilation=(dh, dw))
        B, F = p.shape[:2]
        return p.reshape(B, F, -1)

    return forward_op("im2col", impl, [xt])


def is_integer(x, name=None):
    """dtype predicate (ref: paddle.is_integer)."""
    t = ensure_tensor(x)
    return jnp.issubdtype(t._value.dtype, jnp.integer)


def contiguous(x, name=None):
    """Identity on XLA (arrays are always dense row-major; ref:
    paddle.Tensor.contiguous)."""
    return forward_op("contiguous", lambda v: v, [ensure_tensor(x)])


def log_normal(mean=1.0, std=2.0, shape=None, name=None):
    """Log-normal sample (ref: paddle.log_normal)."""
    from .random import standard_normal
    out = standard_normal(shape if shape is not None else [1])
    return forward_op(
        "log_normal", lambda v: jnp.exp(v * std + mean), [out])


def space_to_depth(x, blocksize: int, name=None):
    """[B, C, H, W] -> [B, C*bs*bs, H/bs, W/bs] (ref: space_to_depth_op)."""
    bs = blocksize

    def impl(v):
        B, C, H, W = v.shape
        v = v.reshape(B, C, H // bs, bs, W // bs, bs)
        return v.transpose(0, 3, 5, 1, 2, 4).reshape(
            B, C * bs * bs, H // bs, W // bs)

    return forward_op("space_to_depth", impl, [ensure_tensor(x)])


def depth_to_space(x, blocksize: int, name=None):
    """Inverse of space_to_depth (ref: pixel_shuffle's NCHW kernel)."""
    bs = blocksize

    def impl(v):
        B, C, H, W = v.shape
        v = v.reshape(B, bs, bs, C // (bs * bs), H, W)
        return v.transpose(0, 3, 4, 1, 5, 2).reshape(
            B, C // (bs * bs), H * bs, W * bs)

    return forward_op("depth_to_space", impl, [ensure_tensor(x)])


def affine_channel(x, scale, bias, data_layout: str = "NCHW", name=None):
    """Per-channel scale + bias (ref: affine_channel_op — the frozen-BN
    kernel)."""
    def impl(v, s, b):
        if data_layout == "NCHW":
            shape = (1, -1) + (1,) * (v.ndim - 2)
        else:
            shape = (1,) * (v.ndim - 1) + (-1,)
        return v * s.reshape(shape) + b.reshape(shape)

    return forward_op("affine_channel", impl,
                      [ensure_tensor(x), ensure_tensor(scale),
                       ensure_tensor(bias)])


def data_norm(x, batch_size, batch_sum, batch_square_sum,
              epsilon: float = 1e-4, name=None):
    """CTR data normalization (ref: data_norm_op): normalize by
    accumulated batch statistics; pure form returns
    ``(out, new_size, new_sum, new_square_sum)``."""
    def impl(v, n, s, ss):
        mean = s / n
        scale = jnp.sqrt(n / jnp.maximum(ss - n * mean * mean / n, epsilon))
        # upstream: scale = sqrt(n / sum((x - mean)^2)) per feature
        var = ss / n - mean * mean
        out = (v - mean) / jnp.sqrt(jnp.maximum(var, epsilon))
        B = v.shape[0]
        return (out, n + B, s + v.sum(0), ss + (v * v).sum(0))

    return forward_op("data_norm", impl,
                      [ensure_tensor(x), ensure_tensor(batch_size),
                       ensure_tensor(batch_sum),
                       ensure_tensor(batch_square_sum)])


def fill_any(x, value, name=None):
    """Fill with a runtime scalar (ref: fill_any_op)."""
    vt = ensure_tensor(value)
    return forward_op(
        "fill_any",
        lambda v, val: jnp.full_like(v, val.astype(v.dtype)),
        [ensure_tensor(x), vt], differentiable=False)


def fill_any_like(x, value, dtype=None, name=None):
    """full_like under the legacy name (ref: fill_any_like_op)."""
    def impl(v):
        out = jnp.full_like(v, value)
        if dtype is not None:
            from .creation import canonical_dtype
            out = out.astype(canonical_dtype(dtype))
        return out
    return forward_op("fill_any_like", impl, [ensure_tensor(x)],
                      differentiable=False)


def unique_with_counts(x, dtype="int32", name=None):
    """(unique values, inverse index, counts) — eager (data-dependent
    output shape; ref: unique_with_counts_op)."""
    t = ensure_tensor(x)
    v, inv, cnt = np.unique(np.asarray(t._value), return_inverse=True,
                            return_counts=True)
    from ..core.tensor import to_tensor
    return to_tensor(v), to_tensor(inv.astype(np.int64)), \
        to_tensor(cnt.astype(np.int64))


def partial_concat(xs, start_index: int = 0, length: int = -1, name=None):
    """Concat x[:, start:start+length] of each input (ref:
    partial_concat_op)."""
    ts = [ensure_tensor(x) for x in xs]

    def impl(*vs):
        sl = [v[:, start_index:(None if length < 0
                                else start_index + length)] for v in vs]
        return jnp.concatenate(sl, -1)

    return forward_op("partial_concat", impl, ts)


def partial_sum(xs, start_index: int = 0, length: int = -1, name=None):
    """Sum of x[:, start:start+length] across inputs (ref:
    partial_sum_op)."""
    ts = [ensure_tensor(x) for x in xs]

    def impl(*vs):
        sl = [v[:, start_index:(None if length < 0
                                else start_index + length)] for v in vs]
        return sum(sl[1:], sl[0])

    return forward_op("partial_sum", impl, ts)


def shuffle_batch(x, seed=None, name=None):
    """Random row permutation (ref: shuffle_batch_op). Eager random;
    returns (shuffled, permutation)."""
    t = ensure_tensor(x)
    rng = np.random.default_rng(seed)
    perm = rng.permutation(int(t.shape[0]))
    from ..core.tensor import to_tensor
    pt = to_tensor(perm.astype(np.int64))
    out = forward_op("shuffle_batch", lambda v, p: v[p], [t, pt])
    return out, pt


def batch_fc(x, w, bias=None, name=None):
    """Per-slot FC: x [S, B, I] @ w [S, I, O] + b [S, O] (ref:
    batch_fc_op — the CTR multi-slot projection, one einsum)."""
    args = [ensure_tensor(x), ensure_tensor(w)]
    if bias is not None:
        args.append(ensure_tensor(bias))

    def impl(xv, wv, *b):
        out = jnp.einsum("sbi,sio->sbo", xv, wv)
        return out + b[0][:, None, :] if b else out

    return forward_op("batch_fc", impl, args)


def cvm(x, cvm_input, use_cvm: bool = True, name=None):
    """Continuous-value-model feature transform (ref: cvm_op): the first
    two columns are (show, click); use_cvm keeps them log-transformed,
    otherwise they are dropped."""
    def impl(v, c):
        show = jnp.log(c[:, 0] + 1)
        click = jnp.log(c[:, 1] + 1) - show
        if use_cvm:
            return jnp.concatenate([show[:, None], click[:, None],
                                    v[:, 2:]], -1)
        return v[:, 2:]

    return forward_op("cvm", impl,
                      [ensure_tensor(x), ensure_tensor(cvm_input)])


def sampling_id(x, min=0.0, max=1.0, seed=0, dtype="int64", name=None):  # noqa: A002
    """Sample one category id per row from probability rows (ref:
    sampling_id_op). Eager random."""
    t = ensure_tensor(x)
    p = np.asarray(t._value, np.float64)
    p = p / p.sum(-1, keepdims=True)
    rng = np.random.default_rng(seed or None)
    ids = np.array([rng.choice(p.shape[1], p=row) for row in p])
    from ..core.tensor import to_tensor
    return to_tensor(ids.astype(np.int64))


def uniform_random_batch_size_like(input, shape, min=-1.0, max=1.0,  # noqa: A002
                                   input_dim_idx: int = 0,
                                   output_dim_idx: int = 0, dtype="float32",
                                   name=None):
    """Uniform sample whose dim ``output_dim_idx`` copies the input's batch
    (ref: uniform_random_batch_size_like_op)."""
    t = ensure_tensor(input)
    shape = list(shape)
    shape[output_dim_idx] = int(t.shape[input_dim_idx])
    from .random import uniform
    return uniform(shape, min=min, max=max, dtype=dtype)


def gaussian_random_batch_size_like(input, shape, mean=0.0, std=1.0,  # noqa: A002
                                    input_dim_idx: int = 0,
                                    output_dim_idx: int = 0,
                                    dtype="float32", name=None):
    """Gaussian twin of uniform_random_batch_size_like."""
    t = ensure_tensor(input)
    shape = list(shape)
    shape[output_dim_idx] = int(t.shape[input_dim_idx])
    from .random import normal
    return normal(mean=mean, std=std, shape=shape)


def fill_constant_batch_size_like(input, shape, dtype, value,  # noqa: A002
                                  input_dim_idx: int = 0,
                                  output_dim_idx: int = 0, name=None):
    """Constant tensor with the input's batch size (ref:
    fill_constant_batch_size_like_op)."""
    t = ensure_tensor(input)
    shape = list(shape)
    shape[output_dim_idx] = int(t.shape[input_dim_idx])
    from .creation import full
    return full(shape, value, dtype=dtype)


def dropout_nd(x, p: float = 0.5, axis=None, training: bool = True,
               mode: str = "upscale_in_train", name=None):
    """Dropout with the mask shared over the non-listed axes (ref:
    incubate dropout_nd)."""
    from ..nn import functional as F
    if axis is None:
        return F.dropout(x, p, training=training, mode=mode)
    t = ensure_tensor(x)
    if not training or p == 0.0:
        return forward_op("dropout_nd", lambda v: v, [t])
    axes = axes_arg(axis)
    axes = (axes,) if isinstance(axes, int) else axes
    from .random import _next_key
    key = _next_key()

    def impl(v):
        shape = tuple(v.shape[d] if d in axes else 1 for d in range(v.ndim))
        keep = jax.random.bernoulli(key, 1 - p, shape)
        if mode == "upscale_in_train":
            return jnp.where(keep, v / (1 - p), 0)
        return jnp.where(keep, v, 0)

    return forward_op("dropout_nd", impl, [t])


def fused_embedding_seq_pool(table, ids, pool_type: str = "sum",
                             padding_idx=None, name=None):
    """Embedding lookup + sequence pool in one op (ref:
    fused_embedding_seq_pool_op): ids [B, T] -> pooled [B, D]."""
    tt = ensure_tensor(table)
    it = ensure_tensor(ids)

    def impl(tv, iv):
        emb = tv[jnp.clip(iv, 0, tv.shape[0] - 1)]            # [B, T, D]
        if padding_idx is not None:
            emb = emb * (iv != padding_idx)[..., None]
        if pool_type == "sum":
            return emb.sum(1)
        if pool_type in ("mean", "average"):
            n = ((iv != padding_idx).sum(1, keepdims=True)
                 if padding_idx is not None
                 else jnp.full((iv.shape[0], 1), iv.shape[1]))
            return emb.sum(1) / jnp.maximum(n, 1)
        raise ValueError(f"pool_type {pool_type!r}")

    return forward_op("fused_embedding_seq_pool", impl, [tt, it])


def nonzero_static(x, size: int, fill_value: int = -1, name=None):
    """Static-shape nonzero (ref: paddle.nonzero_static — added upstream
    exactly because dynamic nonzero can't live in a compiled graph):
    returns the first ``size`` nonzero coordinates [size, ndim], padded
    with ``fill_value``."""
    t = ensure_tensor(x)

    def impl(v):
        flat = (v != 0).reshape(-1)
        idx = jnp.argsort(~flat, stable=True)[:size]          # nonzeros first
        n = flat.sum()
        coords = jnp.stack(jnp.unravel_index(idx, v.shape), -1)
        ok = jnp.arange(size) < n
        return jnp.where(ok[:, None], coords, fill_value)

    return forward_op("nonzero_static", impl, [t], differentiable=False)


def fill_diagonal_tensor(x, y, offset: int = 0, dim1: int = 0,
                         dim2: int = 1, name=None):
    """Write ``y`` along the (dim1, dim2) diagonal (ref:
    paddle.Tensor.fill_diagonal_tensor)."""
    xt = ensure_tensor(x)
    yt = ensure_tensor(y)

    def impl(v, w):
        vm = jnp.moveaxis(v, (dim1, dim2), (-2, -1))
        n = min(vm.shape[-2], vm.shape[-1] - offset) if offset >= 0 \
            else min(vm.shape[-2] + offset, vm.shape[-1])
        r = jnp.arange(max(n, 0))
        rows = r - min(offset, 0)
        cols = r + max(offset, 0)
        vm = vm.at[..., rows, cols].set(w)
        return jnp.moveaxis(vm, (-2, -1), (dim1, dim2))

    return forward_op("fill_diagonal_tensor", impl, [xt, yt])


# -- r5 second batch: static-graph-era singles + CTR text matching ----------

def fc(x, size: int, num_flatten_dims: int = 1, weight=None, bias=None,
       activation=None, name=None):
    """Static-graph fc layer op (ref: fc_op): flatten trailing dims, one
    matmul + bias + optional relu."""
    xt = ensure_tensor(x)
    if weight is None:
        raise ValueError("fc: pass `weight` explicitly (the layer tier "
                         "owns parameter creation)")
    args = [xt, ensure_tensor(weight)]
    if bias is not None:
        args.append(ensure_tensor(bias))

    def impl(v, w, *b):
        # shapes read from the runtime operand (shape-polymorphic across
        # re-traces: the static Executor replays with real batch sizes)
        lead = v.shape[:num_flatten_dims]
        flat_in = 1
        for s in v.shape[num_flatten_dims:]:
            flat_in *= int(s)
        out = v.reshape(tuple(lead) + (flat_in,)) @ w
        if b:
            out = out + b[0]
        if activation == "relu":
            out = jnp.maximum(out, 0)
        return out

    return forward_op("fc", impl, args)


def assign_value(shape, dtype, values, name=None):
    """Materialize a host constant (ref: assign_value_op)."""
    from .creation import to_tensor as _tt, canonical_dtype
    arr = np.asarray(values, dtype=canonical_dtype(dtype)).reshape(shape)
    return _tt(arr)


def soft_relu(x, threshold: float = 40.0, name=None):
    """log(1 + e^x) with clipping (ref: soft_relu_op)."""
    return forward_op(
        "soft_relu",
        lambda v: jnp.log1p(jnp.exp(jnp.clip(v, -threshold, threshold))),
        [ensure_tensor(x)])


def brelu(x, t_min: float = 0.0, t_max: float = 24.0, name=None):
    """Bounded relu (ref: brelu_op)."""
    return forward_op("brelu", lambda v: jnp.clip(v, t_min, t_max),
                      [ensure_tensor(x)])


def match_matrix_tensor(x, y, w, x_lens=None, y_lens=None, dim_t=None,
                        name=None):
    """Bilinear text-match tensor (ref: match_matrix_tensor_op): for each
    channel t, score[b, t, i, j] = x[b, i] @ w[t] @ y[b, j] — one einsum
    (the CTR text-matching kernel on dense padded batches)."""
    xt = ensure_tensor(x)      # [B, Lx, D1]
    yt = ensure_tensor(y)      # [B, Ly, D2]
    wt = ensure_tensor(w)      # [D1, T, D2]

    def impl(xv, yv, wv):
        return jnp.einsum("bid,dte,bje->btij", xv, wv, yv)

    return forward_op("match_matrix_tensor", impl, [xt, yt, wt])


def sequence_topk_avg_pooling(x, topks, channel_num: int = 1, name=None):
    """Top-k average pooling over the last axis per channel/row (ref:
    sequence_topk_avg_pooling_op): for each k in ``topks``, the mean of
    the k largest values."""
    xt = ensure_tensor(x)

    def impl(v):
        outs = []
        srt = jnp.sort(v, axis=-1)[..., ::-1]
        for k in topks:
            kk = min(k, v.shape[-1])
            outs.append(srt[..., :kk].mean(-1))
        return jnp.stack(outs, -1)

    return forward_op("sequence_topk_avg_pooling", impl, [xt])


def rank_attention(x, rank_offset, rank_param, max_rank: int = 3,
                   name=None):
    """Rank-aware attention projection (ref: rank_attention_op, the CTR
    position-bias kernel): each row picks the parameter block of its
    (rank_i, rank_j) pair; one gather + batched matmul."""
    xt = ensure_tensor(x)              # [B, D]
    ot = ensure_tensor(rank_offset)    # [B, 1 + 2*max_rank] ins rank + pairs
    pt = ensure_tensor(rank_param)     # [max_rank*max_rank*D, out]

    def impl(xv, ov, pv):
        B, D = xv.shape
        out_dim = pv.shape[1]
        blocks = pv.reshape(max_rank * max_rank, D, out_dim)
        ins_rank = jnp.clip(ov[:, 0], 0, max_rank - 1)
        acc = jnp.zeros((B, out_dim), xv.dtype)
        cnt = jnp.zeros((B, 1), xv.dtype)
        for k in range(max_rank):
            other = ov[:, 1 + 2 * k]
            valid = other >= 0
            idx = jnp.clip(ins_rank * max_rank +
                           jnp.clip(other, 0, max_rank - 1), 0,
                           max_rank * max_rank - 1).astype(jnp.int32)
            proj = jnp.einsum("bd,bdo->bo", xv, blocks[idx])
            acc = acc + jnp.where(valid[:, None], proj, 0)
            cnt = cnt + valid[:, None].astype(xv.dtype)
        return acc / jnp.maximum(cnt, 1)

    return forward_op("rank_attention", impl, [xt, ot, pt])


def tree_conv(nodes_vector, edge_set, filter, max_depth: int = 2,
              name=None):
    """Tree-based convolution (ref: tree_conv_op, TBCNN): continuous
    binary-tree position weights over each node's children window. Dense
    formulation: adjacency as a [N, N] mask, one einsum per weight role
    (top/left/right)."""
    nt = ensure_tensor(nodes_vector)   # [B, N, D]
    et = ensure_tensor(edge_set)       # [B, E, 2] (parent, child)
    ft = ensure_tensor(filter)         # [D, 3, out]  (top/left/right roles)

    def impl(nv, ev, fv):
        B, N, D = nv.shape
        out_dim = fv.shape[-1]
        par = jnp.clip(ev[..., 0], 0, N - 1)
        chl = jnp.clip(ev[..., 1], 0, N - 1)
        valid = (ev[..., 0] >= 0) & (ev[..., 1] >= 0)
        adj = jnp.zeros((B, N, N), nv.dtype)
        b = jnp.broadcast_to(jnp.arange(B)[:, None], par.shape)
        adj = adj.at[b, par, chl].max(jnp.where(valid, 1.0, 0.0))
        deg = adj.sum(-1, keepdims=True)                    # children count
        # eta weights: top for self, left/right by child position
        pos = jnp.cumsum(adj, -1) * adj                     # 1-based pos
        denom = jnp.maximum(deg - 1, 1)
        eta_r = (pos - 1) / denom * adj
        eta_l = (1 - (pos - 1) / denom) * adj
        self_top = jnp.eye(N, dtype=nv.dtype)[None]
        h = (jnp.einsum("bnm,bmd,do->bno", self_top,
                        nv, fv[:, 0]) +
             jnp.einsum("bnm,bmd,do->bno", eta_l, nv, fv[:, 1]) +
             jnp.einsum("bnm,bmd,do->bno", eta_r, nv, fv[:, 2]))
        return jnp.tanh(h)

    return forward_op("tree_conv", impl, [nt, et, ft])


def var_conv_2d(x, row_lens, col_lens, w, input_channel: int = 1,
                output_channel: int = 1, filter_size: int = 3,
                stride: int = 1, name=None):
    """Variable-size 2-D conv over per-sample [row, col] shapes (ref:
    var_conv_2d_op). Dense formulation: conv at full capacity + validity
    mask from the per-sample sizes."""
    from jax import lax as _lax
    xt = ensure_tensor(x)              # [B, C, H, W] padded capacity
    rt = ensure_tensor(row_lens)
    ct = ensure_tensor(col_lens)
    wt = ensure_tensor(w)              # [out, in, k, k]

    def impl(xv, rv, cv, wv):
        pad = filter_size // 2
        out = _lax.conv_general_dilated(
            xv, wv, (stride, stride), [(pad, pad), (pad, pad)])
        H, W = out.shape[2], out.shape[3]
        rm = jnp.arange(H)[None, :] < rv[:, None]
        cm = jnp.arange(W)[None, :] < cv[:, None]
        return out * (rm[:, None, :, None] & cm[:, None, None, :])

    return forward_op("var_conv_2d", impl, [xt, rt, ct, wt])


__all__ += ["fc", "assign_value", "soft_relu", "brelu",
            "match_matrix_tensor", "sequence_topk_avg_pooling",
            "rank_attention", "tree_conv", "var_conv_2d"]


# -- r5 third batch: remaining genuine singles ------------------------------

def l1_norm(x, name=None):
    """Sum of absolute values (ref: l1_norm_op)."""
    return forward_op("l1_norm", lambda v: jnp.sum(jnp.abs(v)),
                      [ensure_tensor(x)])


def share_data(x, name=None):
    """Alias view of a tensor (ref: share_data_op — buffer sharing is a
    no-op under XLA's immutable arrays)."""
    return forward_op("share_data", lambda v: v, [ensure_tensor(x)])


def lod_array_length(array, name=None):
    """Length of a TensorArray (ref: lod_array_length_op)."""
    from .array import array_length
    return array_length(array)


def set_value(x, value, name=None):
    """Overwrite a tensor's buffer in place with host data (ref:
    set_value_op / Tensor.set_value)."""
    t = ensure_tensor(x)
    v = value._value if isinstance(value, Tensor) else jnp.asarray(
        np.asarray(value, dtype=np.asarray(t._value).dtype))
    out = forward_op("set_value", lambda a, b: b.reshape(a.shape),
                     [t, ensure_tensor(v)])
    t._rebind(out)
    return t


def bilinear_tensor_product(x, y, weight, bias=None, name=None):
    """out[b, k] = x[b] @ W[k] @ y[b] (ref: bilinear_tensor_product_op)."""
    args = [ensure_tensor(x), ensure_tensor(y), ensure_tensor(weight)]
    if bias is not None:
        args.append(ensure_tensor(bias))

    def impl(xv, yv, wv, *b):
        out = jnp.einsum("bi,kij,bj->bk", xv, wv, yv)
        return out + b[0] if b else out

    return forward_op("bilinear_tensor_product", impl, args)


def chunk_eval(input, label, chunk_scheme: str = "IOB",  # noqa: A002
               num_chunk_types: int = 1, excluded_chunk_types=None,
               seq_lens=None, name=None):
    """Chunk-level precision/recall/F1 for sequence labeling (ref:
    chunk_eval_op, IOB scheme). Eager host metric: returns (precision,
    recall, f1, num_infer, num_label, num_correct)."""
    from ..core.tensor import to_tensor

    def extract(seq):
        chunks = []
        start = None
        ctype = None
        for i, t in enumerate(list(seq) + [-1]):
            t = int(t)
            if t < 0 or t % 2 == 0:  # B-* tag (even) or end
                if start is not None:
                    chunks.append((start, i, ctype))
                    start, ctype = None, None
                if t >= 0 and t % 2 == 0 and t // 2 < num_chunk_types:
                    start, ctype = i, t // 2
            # odd tags continue the current chunk (I-*); mismatched I ends
        return set(chunks)

    iv = np.asarray(ensure_tensor(input)._value)
    lv = np.asarray(ensure_tensor(label)._value)
    if iv.ndim == 1:
        iv, lv = iv[None], lv[None]
    lens = (np.asarray(ensure_tensor(seq_lens)._value)
            if seq_lens is not None else
            np.full(iv.shape[0], iv.shape[1]))
    ni = nl = nc = 0
    for b in range(iv.shape[0]):
        ic = extract(iv[b, :lens[b]])
        lc = extract(lv[b, :lens[b]])
        ni += len(ic)
        nl += len(lc)
        nc += len(ic & lc)
    p = nc / max(ni, 1)
    r = nc / max(nl, 1)
    f1 = 2 * p * r / max(p + r, 1e-12)
    return (to_tensor(np.float32(p)), to_tensor(np.float32(r)),
            to_tensor(np.float32(f1)), to_tensor(np.int64(ni)),
            to_tensor(np.int64(nl)), to_tensor(np.int64(nc)))


__all__ += ["l1_norm", "share_data", "lod_array_length", "set_value",
            "bilinear_tensor_product", "chunk_eval"]
