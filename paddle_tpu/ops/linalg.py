"""Linear algebra ops.

Parity target: ``python/paddle/tensor/linalg.py`` (+ ``paddle.linalg`` namespace) in
the reference, backed there by cuBLAS/cuSOLVER phi kernels. Matmuls here go straight
to jnp → XLA dot_general, which is the MXU path on TPU; decompositions lower to XLA's
linalg suite.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ._helpers import (axes_arg, ensure_tensor, forward_op,
                       patch_methods, register_op)


def matmul(x, y, transpose_x=False, transpose_y=False, name=None) -> Tensor:
    x, y = ensure_tensor(x), ensure_tensor(y)

    def impl(a, b):
        if transpose_x and a.ndim >= 2:
            a = jnp.swapaxes(a, -1, -2)
        if transpose_y and b.ndim >= 2:
            b = jnp.swapaxes(b, -1, -2)
        return jnp.matmul(a, b)

    return forward_op("matmul", impl, [x, y])


def mm(input, mat2, name=None) -> Tensor:  # noqa: A002
    return matmul(input, mat2)


def bmm(x, y, name=None) -> Tensor:
    return matmul(x, y)


def mv(x, vec, name=None) -> Tensor:
    return matmul(x, vec)


def t(input, name=None) -> Tensor:  # noqa: A002
    input = ensure_tensor(input)
    if input.ndim > 2:
        raise ValueError("paddle.t only supports ndim<=2")
    return forward_op("t", lambda v: v.T, [input])


def norm(x, p=None, axis=None, keepdim=False, name=None) -> Tensor:
    x = ensure_tensor(x)
    ax = axes_arg(axis)
    if p is None:
        p = "fro" if (ax is None or isinstance(ax, tuple)) else 2

    def impl(v):
        if ax is None:
            flat = v.reshape(-1)
            if p == "fro" or p == 2:
                return jnp.linalg.norm(flat)
            if p == float("inf"):
                return jnp.max(jnp.abs(flat))
            if p == float("-inf"):
                return jnp.min(jnp.abs(flat))
            return jnp.sum(jnp.abs(flat) ** p) ** (1.0 / p)
        return jnp.linalg.norm(v, ord=None if p == "fro" else p, axis=ax,
                               keepdims=keepdim)

    return forward_op("norm", impl, [x])


def vector_norm(x, p=2, axis=None, keepdim=False, name=None) -> Tensor:
    return forward_op("vector_norm",
                      lambda v: jnp.linalg.vector_norm(v, ord=p, axis=axes_arg(axis),
                                                       keepdims=keepdim),
                      [ensure_tensor(x)])


def matrix_norm(x, p="fro", axis=(-2, -1), keepdim=False, name=None) -> Tensor:
    return forward_op("matrix_norm",
                      lambda v: jnp.linalg.matrix_norm(v, ord=p, keepdims=keepdim),
                      [ensure_tensor(x)])


def dist(x, y, p=2, name=None) -> Tensor:
    return norm(ensure_tensor(x) - ensure_tensor(y), p=p)


def cholesky(x, upper=False, name=None) -> Tensor:
    return forward_op("cholesky",
                      lambda v: jnp.linalg.cholesky(v).swapaxes(-1, -2) if upper
                      else jnp.linalg.cholesky(v), [ensure_tensor(x)])


def cholesky_solve(x, y, upper=False, name=None) -> Tensor:
    x, y = ensure_tensor(x), ensure_tensor(y)

    def impl(b, L):
        if upper:
            L = jnp.swapaxes(L, -1, -2)
        z = jax.scipy.linalg.solve_triangular(L, b, lower=True)
        return jax.scipy.linalg.solve_triangular(jnp.swapaxes(L, -1, -2), z, lower=False)

    return forward_op("cholesky_solve", impl, [x, y])


def qr(x, mode="reduced", name=None):
    outs = forward_op("qr", lambda v: tuple(jnp.linalg.qr(v, mode=mode)),
                      [ensure_tensor(x)])
    return outs


def svd(x, full_matrices=False, name=None):
    return forward_op("svd",
                      lambda v: tuple(jnp.linalg.svd(v, full_matrices=full_matrices)),
                      [ensure_tensor(x)])


def svdvals(x, name=None) -> Tensor:
    return forward_op("svdvals",
                      lambda v: jnp.linalg.svd(v, compute_uv=False), [ensure_tensor(x)])


def eig(x, name=None):
    """General eig — XLA supports it on CPU only; eager-mode host fallback, matching
    the reference's cuSOLVER-on-host behavior class."""
    x = ensure_tensor(x)
    w, v = np.linalg.eig(np.asarray(x._value))
    from ..core.tensor import to_tensor
    return to_tensor(w), to_tensor(v)


def eigh(x, UPLO="L", name=None):
    return forward_op("eigh", lambda v: tuple(jnp.linalg.eigh(v, UPLO=UPLO)),
                      [ensure_tensor(x)])


def eigvals(x, name=None) -> Tensor:
    x = ensure_tensor(x)
    from ..core.tensor import to_tensor
    return to_tensor(np.linalg.eigvals(np.asarray(x._value)))


def eigvalsh(x, UPLO="L", name=None) -> Tensor:
    return forward_op("eigvalsh", lambda v: jnp.linalg.eigvalsh(v, UPLO=UPLO),
                      [ensure_tensor(x)])


def inv(x, name=None) -> Tensor:
    return forward_op("inv", jnp.linalg.inv, [ensure_tensor(x)])


def pinv(x, rcond=1e-15, hermitian=False, name=None) -> Tensor:
    return forward_op("pinv",
                      lambda v: jnp.linalg.pinv(v, rtol=rcond, hermitian=hermitian),
                      [ensure_tensor(x)])


def solve(x, y, name=None) -> Tensor:
    return forward_op("solve", jnp.linalg.solve, [ensure_tensor(x), ensure_tensor(y)])


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False,
                     name=None) -> Tensor:
    x, y = ensure_tensor(x), ensure_tensor(y)

    def impl(a, b):
        return jax.scipy.linalg.solve_triangular(
            a, b, lower=not upper, trans=1 if transpose else 0,
            unit_diagonal=unitriangular)

    return forward_op("triangular_solve", impl, [x, y])


def lstsq(x, y, rcond=None, driver=None, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)
    sol, res, rank, sv = (jnp.linalg.lstsq(x._value, y._value, rcond=rcond))
    from ..core.tensor import to_tensor
    return to_tensor(sol), to_tensor(res), to_tensor(rank), to_tensor(sv)


def det(x, name=None) -> Tensor:
    return forward_op("det", jnp.linalg.det, [ensure_tensor(x)])


def slogdet(x, name=None):
    return forward_op("slogdet", lambda v: tuple(jnp.linalg.slogdet(v)),
                      [ensure_tensor(x)])


def matrix_power(x, n, name=None) -> Tensor:
    return forward_op("matrix_power", lambda v: jnp.linalg.matrix_power(v, int(n)),
                      [ensure_tensor(x)])


def matrix_rank(x, tol=None, hermitian=False, name=None) -> Tensor:
    return forward_op("matrix_rank",
                      lambda v: jnp.linalg.matrix_rank(v, rtol=tol),
                      [ensure_tensor(x)], differentiable=False)


def cond(x, p=None, name=None) -> Tensor:
    return forward_op("cond_number", lambda v: jnp.linalg.cond(v, p=p),
                      [ensure_tensor(x)])


def multi_dot(tensors, name=None) -> Tensor:
    ts = [ensure_tensor(t) for t in tensors]
    return forward_op("multi_dot", lambda *vs: jnp.linalg.multi_dot(vs), ts)


def einsum(equation, *operands) -> Tensor:
    ts = [ensure_tensor(o) for o in operands]
    return forward_op("einsum", lambda *vs: jnp.einsum(equation, *vs), ts)


def householder_product(x, tau, name=None) -> Tensor:
    x, tau = ensure_tensor(x), ensure_tensor(tau)

    def impl(a, t):
        m, n = a.shape[-2], a.shape[-1]
        q = jnp.eye(m, dtype=a.dtype)
        q = jnp.broadcast_to(q, a.shape[:-2] + (m, m)).copy() if a.ndim > 2 else q

        def body(i, q):
            v = jnp.where(jnp.arange(m) < i, 0.0, a[..., :, i])
            v = v.at[..., i].set(1.0)
            h = jnp.eye(m, dtype=a.dtype) - t[..., i, None, None] * \
                (v[..., :, None] @ v[..., None, :])
            return q @ h

        for i in range(n):
            q = body(i, q)
        return q[..., :, :n]

    return forward_op("householder_product", impl, [x, tau])


def corrcoef(x, rowvar=True, name=None) -> Tensor:
    return forward_op("corrcoef",
                      lambda v: jnp.corrcoef(v, rowvar=rowvar), [ensure_tensor(x)])


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None) -> Tensor:
    return forward_op("cov",
                      lambda v: jnp.cov(v, rowvar=rowvar, ddof=1 if ddof else 0),
                      [ensure_tensor(x)])


patch_methods([
    ("matmul", matmul), ("mm", mm), ("bmm", bmm), ("mv", mv), ("norm", norm),
    ("cholesky", cholesky), ("inv", inv), ("pinv", pinv), ("det", det),
    ("matrix_power", matrix_power),
])


def lu(x, pivot=True, get_infos=False, name=None):
    """LU factorization (ref: paddle.linalg.lu — packed LU + pivots[+infos])."""
    t = ensure_tensor(x)

    def f(v):
        lu_mat, piv, _ = jax.lax.linalg.lu(v)
        # jax returns 0-based row-permutation indices; reference returns
        # 1-based pivots (LAPACK convention)
        return lu_mat, (piv + 1).astype(jnp.int32)
    lu_mat, pivots = forward_op("lu", f, [t])
    if get_infos:
        from .creation import zeros
        infos = zeros(list(t.shape[:-2]) or [1], "int32")
        return lu_mat, pivots, infos
    return lu_mat, pivots


def lu_unpack(x, y, unpack_ludata=True, unpack_pivots=True, name=None):
    """Unpack packed LU + pivots into (P, L, U) (ref: paddle.linalg.lu_unpack)."""
    lu_t, piv_t = ensure_tensor(x), ensure_tensor(y)
    m, n = lu_t.shape[-2], lu_t.shape[-1]
    k = min(m, n)

    def f(lu_mat, piv):
        eye_m = jnp.eye(m, dtype=lu_mat.dtype)
        l = jnp.tril(lu_mat[..., :, :k], -1) + eye_m[..., :, :k]  # noqa: E741
        u = jnp.triu(lu_mat[..., :k, :])
        piv0 = piv.astype(jnp.int32) - 1  # back to 0-based

        def perm_one(p):
            perm = jnp.arange(m)
            def body(i, perm):
                a = perm[i]
                b = perm[p[i]]
                return perm.at[i].set(b).at[p[i]].set(a)
            return jax.lax.fori_loop(0, p.shape[0], body, perm)
        batch_shape = piv0.shape[:-1]
        if batch_shape:
            perm = jnp.reshape(
                jax.vmap(perm_one)(piv0.reshape(-1, piv0.shape[-1])),
                batch_shape + (m,))
        else:
            perm = perm_one(piv0)
        p_mat = jax.nn.one_hot(perm, m, dtype=lu_mat.dtype)
        p_mat = jnp.swapaxes(p_mat, -1, -2)
        return p_mat, l, u
    return forward_op("lu_unpack", f, [lu_t, piv_t])


def matrix_exp(x, name=None):
    """Matrix exponential (ref: paddle.linalg.matrix_exp; jax.scipy expm)."""
    from jax.scipy.linalg import expm
    return forward_op("matrix_exp", expm, [ensure_tensor(x)])


def ormqr(x, tau, other, left=True, transpose=False, name=None):
    """Multiply by the FULL m x m Q of a Householder QR factorization
    (ref: paddle.linalg.ormqr / LAPACK ormqr): the k reflectors stored in
    ``x``'s lower trapezoid are applied to ``other`` directly — the thin Q
    from householder_product cannot represent full-Q products."""
    a, tt, c = ensure_tensor(x), ensure_tensor(tau), ensure_tensor(other)
    if a.ndim != 2 or c.ndim != 2:
        raise ValueError("ormqr: batched inputs are not supported; got "
                         f"ndim {a.ndim}/{c.ndim}")

    def f(av, tv, cv):
        m = av.shape[0]
        k = tv.shape[0]
        rows = jnp.arange(m)

        def reflect(i, mat):
            col = jnp.take(av, i, axis=1)
            v = jnp.where(rows > i, col, jnp.where(rows == i, 1.0, 0.0))
            w = v @ mat                      # [n]
            return mat - jnp.take(tv, i) * jnp.outer(v, w)

        def apply_q(mat, trans):
            # Q = H_0 H_1 ... H_{k-1}; Q @ C applies reflectors right-to-left
            def body(j, mat):
                i = j if trans else k - 1 - j
                return reflect(i, mat)
            return jax.lax.fori_loop(0, k, body, mat)

        if left:
            return apply_q(cv, transpose)
        # C @ Q = (Q^T C^T)^T ; C @ Q^T = (Q C^T)^T
        return apply_q(cv.T, not transpose).T
    return forward_op("ormqr", f, [a, tt, c])


def svd_lowrank(x, q=6, niter=2, M=None, name=None):
    """Randomized low-rank SVD (ref: paddle.linalg.svd_lowrank; Halko et al.
    subspace iteration). Returns (U [m,q], S [q], V [n,q])."""
    t = ensure_tensor(x)
    m, n = t.shape[-2], t.shape[-1]
    q = min(q, m, n)
    from .random import _next_key
    key = _next_key()

    def f(v, mv=None):
        a = v if mv is None else v - mv
        omega = jax.random.normal(key, a.shape[:-2] + (n, q), a.dtype)
        y = a @ omega
        for _ in range(niter):
            y = a @ (jnp.swapaxes(a, -1, -2) @ y)
        qmat, _ = jnp.linalg.qr(y)
        b = jnp.swapaxes(qmat, -1, -2) @ a
        ub, s, vt = jnp.linalg.svd(b, full_matrices=False)
        return qmat @ ub, s, jnp.swapaxes(vt, -1, -2)
    args = [t] if M is None else [t, ensure_tensor(M)]
    return forward_op("svd_lowrank", f, args)


# -- r4 breadth: solve/inverse completions (VERDICT #6) ----------------------

def cholesky_inverse(x, upper: bool = False, name=None) -> Tensor:
    """Inverse of a matrix given its Cholesky factor (torch.cholesky_inverse
    parity): A^-1 from u with A = u u^T (lower) / u^T u (upper)."""
    def impl(u):
        from jax.scipy.linalg import cho_solve
        eye = jnp.eye(u.shape[-1], dtype=u.dtype)
        return cho_solve((u, not upper), eye)
    return forward_op("cholesky_inverse", impl, [ensure_tensor(x)])


def lu_solve(b, lu_data, pivots, trans: int = 0, name=None) -> Tensor:
    """Solve A x = b from the packed LU factorization (scipy convention;
    ref: paddle.linalg.lu_solve). ``pivots`` are 1-based (paddle/LAPACK)."""
    def impl(bv, luv, pv):
        from jax.scipy.linalg import lu_solve as _ls
        return _ls((luv, pv.astype(jnp.int32) - 1), bv, trans=trans)
    return forward_op("lu_solve", impl,
                      [ensure_tensor(b), ensure_tensor(lu_data),
                       ensure_tensor(pivots)])


def tensorinv(x, ind: int = 2, name=None) -> Tensor:
    """Inverse of a tensor viewed as a linear map (numpy.linalg.tensorinv)."""
    return forward_op("tensorinv", lambda v: jnp.linalg.tensorinv(v, ind),
                      [ensure_tensor(x)])


def tensorsolve(x, y, axes=None, name=None) -> Tensor:
    """Solve the tensor equation a x = b (numpy.linalg.tensorsolve)."""
    return forward_op("tensorsolve",
                      lambda a, b: jnp.linalg.tensorsolve(a, b, axes=axes),
                      [ensure_tensor(x), ensure_tensor(y)])


def geqrf(x, name=None):
    """Raw QR factorization (LAPACK geqrf: packed householder + tau)."""
    def impl(v):
        from jax._src.lax import linalg as _ll
        return tuple(_ll.geqrf(v))
    return forward_op("geqrf", impl, [ensure_tensor(x)])


orgqr = householder_product  # LAPACK name alias (torch.orgqr parity)

for _n, _f in (("cholesky_inverse", cholesky_inverse), ("lu_solve", lu_solve),
               ("tensorinv", tensorinv), ("tensorsolve", tensorsolve),
               ("geqrf", geqrf), ("orgqr", orgqr)):
    register_op(_n, _f, (_f.__doc__ or "").strip().split("\n")[0], public=_f)
