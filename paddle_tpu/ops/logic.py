"""Comparison, logical and bitwise ops.

Parity target: ``python/paddle/tensor/logic.py`` in the reference.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor
from ._helpers import binary_factory, ensure_tensor, forward_op, patch_methods, unary_factory

equal = binary_factory("equal", jnp.equal)
not_equal = binary_factory("not_equal", jnp.not_equal)
less_than = binary_factory("less_than", jnp.less)
less_equal = binary_factory("less_equal", jnp.less_equal)
greater_than = binary_factory("greater_than", jnp.greater)
greater_equal = binary_factory("greater_equal", jnp.greater_equal)
logical_and = binary_factory("logical_and", jnp.logical_and)
logical_or = binary_factory("logical_or", jnp.logical_or)
logical_xor = binary_factory("logical_xor", jnp.logical_xor)
logical_not = unary_factory("logical_not", jnp.logical_not)
bitwise_and = binary_factory("bitwise_and", jnp.bitwise_and)
bitwise_or = binary_factory("bitwise_or", jnp.bitwise_or)
bitwise_xor = binary_factory("bitwise_xor", jnp.bitwise_xor)
bitwise_not = unary_factory("bitwise_not", jnp.bitwise_not)
bitwise_left_shift = binary_factory("bitwise_left_shift", jnp.left_shift)
bitwise_right_shift = binary_factory("bitwise_right_shift", jnp.right_shift)


def equal_all(x, y, name=None) -> Tensor:
    return forward_op("equal_all", lambda a, b: jnp.array_equal(a, b),
                      [ensure_tensor(x), ensure_tensor(y)], differentiable=False)


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None) -> Tensor:
    return forward_op("allclose",
                      lambda a, b: jnp.allclose(a, b, rtol=rtol, atol=atol,
                                                equal_nan=equal_nan),
                      [ensure_tensor(x), ensure_tensor(y)], differentiable=False)


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None) -> Tensor:
    return forward_op("isclose",
                      lambda a, b: jnp.isclose(a, b, rtol=rtol, atol=atol,
                                               equal_nan=equal_nan),
                      [ensure_tensor(x), ensure_tensor(y)], differentiable=False)


def is_empty(x, name=None) -> Tensor:
    return Tensor(jnp.asarray(ensure_tensor(x).size == 0))


def is_tensor(x) -> bool:
    return isinstance(x, Tensor)


patch_methods([
    ("__eq__", lambda s, o: equal(s, o)), ("__ne__", lambda s, o: not_equal(s, o)),
    ("__lt__", lambda s, o: less_than(s, o)), ("__le__", lambda s, o: less_equal(s, o)),
    ("__gt__", lambda s, o: greater_than(s, o)),
    ("__ge__", lambda s, o: greater_equal(s, o)),
    ("__and__", lambda s, o: bitwise_and(s, o)),
    ("__or__", lambda s, o: bitwise_or(s, o)),
    ("__xor__", lambda s, o: bitwise_xor(s, o)),
    ("__invert__", lambda s: bitwise_not(s)),
    ("equal", equal), ("not_equal", not_equal), ("less_than", less_than),
    ("less_equal", less_equal), ("greater_than", greater_than),
    ("greater_equal", greater_equal), ("logical_and", logical_and),
    ("logical_or", logical_or), ("logical_xor", logical_xor),
    ("logical_not", logical_not), ("bitwise_and", bitwise_and),
    ("bitwise_or", bitwise_or), ("bitwise_xor", bitwise_xor),
    ("bitwise_not", bitwise_not), ("equal_all", equal_all), ("allclose", allclose),
    ("isclose", isclose),
])
