"""Shape / layout manipulation ops.

Parity target: ``python/paddle/tensor/manipulation.py`` in the reference. All ops are
functional on immutable arrays; Paddle's view semantics (reshape returning a view)
degrade gracefully to copies under XLA, which is the TPU-correct behavior.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.dtype import canonical_dtype
from ..core.tensor import Tensor, to_tensor
from ._helpers import axes_arg, ensure_tensor, forward_op, patch_methods


def _shape_arg(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in np.asarray(shape._value).reshape(-1))
    out = []
    for s in (shape if isinstance(shape, (list, tuple)) else [shape]):
        out.append(int(s.item()) if isinstance(s, Tensor) else int(s))
    return tuple(out)


def reshape(x, shape, name=None) -> Tensor:
    x = ensure_tensor(x)
    shp = _shape_arg(shape)
    # paddle semantics: 0 means "copy this dim from input"
    shp = tuple(x._value.shape[i] if s == 0 else s for i, s in enumerate(shp))
    return forward_op("reshape", lambda v: v.reshape(shp), [x])


def reshape_(x, shape, name=None) -> Tensor:
    x._rebind(reshape(x, shape))
    return x


view = reshape


def view_as(x, other, name=None) -> Tensor:
    return reshape(x, ensure_tensor(other).shape)


def transpose(x, perm, name=None) -> Tensor:
    x = ensure_tensor(x)
    perm = tuple(int(p) for p in perm)
    return forward_op("transpose", lambda v: jnp.transpose(v, perm), [x])


def moveaxis(x, source, destination, name=None) -> Tensor:
    return forward_op("moveaxis",
                      lambda v: jnp.moveaxis(v, axes_arg(source), axes_arg(destination)),
                      [ensure_tensor(x)])


def swapaxes(x, axis0, axis1, name=None) -> Tensor:
    return forward_op("swapaxes", lambda v: jnp.swapaxes(v, int(axis0), int(axis1)),
                      [ensure_tensor(x)])


def concat(x, axis=0, name=None) -> Tensor:
    ts = [ensure_tensor(t) for t in x]
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    return forward_op("concat", lambda *vs: jnp.concatenate(vs, axis=int(axis)), ts)


def stack(x, axis=0, name=None) -> Tensor:
    ts = [ensure_tensor(t) for t in x]
    return forward_op("stack", lambda *vs: jnp.stack(vs, axis=int(axis)), ts)


def split(x, num_or_sections, axis=0, name=None):
    x = ensure_tensor(x)
    ax = int(axis.item()) if isinstance(axis, Tensor) else int(axis)
    dim = x._value.shape[ax]
    if isinstance(num_or_sections, int):
        if dim % num_or_sections != 0:
            raise ValueError(
                f"split: dim {dim} along axis {ax} is not divisible by "
                f"{num_or_sections}")
        sizes = [dim // num_or_sections] * num_or_sections
    else:
        sizes = [int(s) for s in num_or_sections]
        if any(s == -1 for s in sizes):
            rest = dim - sum(s for s in sizes if s != -1)
            sizes = [rest if s == -1 else s for s in sizes]
    offsets = np.cumsum([0] + sizes[:-1])

    def impl(v):
        return tuple(jax.lax.slice_in_dim(v, int(o), int(o + s), axis=ax)
                     for o, s in zip(offsets, sizes))

    outs = forward_op("split", impl, [x])
    return list(outs) if isinstance(outs, tuple) else [outs]


def chunk(x, chunks, axis=0, name=None):
    return split(x, int(chunks), axis)


def unbind(x, axis=0):
    x = ensure_tensor(x)
    n = x._value.shape[int(axis)]

    def impl(v):
        return tuple(jnp.take(v, i, axis=int(axis)) for i in range(n))

    outs = forward_op("unbind", impl, [x])
    return list(outs) if isinstance(outs, tuple) else [outs]


unstack = unbind


def squeeze(x, axis=None, name=None) -> Tensor:
    x = ensure_tensor(x)
    ax = axes_arg(axis)
    if isinstance(ax, int):
        ax = (ax,)

    def impl(v):
        if ax is None:
            return jnp.squeeze(v)
        keep = tuple(a for a in ax if v.shape[a] == 1)  # paddle ignores non-1 dims
        return jnp.squeeze(v, axis=keep) if keep else v

    return forward_op("squeeze", impl, [x])


def squeeze_(x, axis=None, name=None) -> Tensor:
    x._rebind(squeeze(x, axis))
    return x


def unsqueeze(x, axis, name=None) -> Tensor:
    x = ensure_tensor(x)
    ax = axes_arg(axis)
    if isinstance(ax, int):
        ax = (ax,)
    return forward_op("unsqueeze", lambda v: jnp.expand_dims(v, ax), [x])


def unsqueeze_(x, axis, name=None) -> Tensor:
    x._rebind(unsqueeze(x, axis))
    return x


def flatten(x, start_axis=0, stop_axis=-1, name=None) -> Tensor:
    x = ensure_tensor(x)
    nd = x.ndim
    s = start_axis % nd if nd else 0
    e = stop_axis % nd if nd else 0

    def impl(v):
        shp = v.shape[:s] + (-1,) + v.shape[e + 1:]
        return v.reshape(shp)

    return forward_op("flatten", impl, [x])


def flatten_(x, start_axis=0, stop_axis=-1, name=None) -> Tensor:
    x._rebind(flatten(x, start_axis, stop_axis))
    return x


def tile(x, repeat_times, name=None) -> Tensor:
    x = ensure_tensor(x)
    reps = _shape_arg(repeat_times)
    return forward_op("tile", lambda v: jnp.tile(v, reps), [x])


def expand(x, shape, name=None) -> Tensor:
    x = ensure_tensor(x)
    shp = _shape_arg(shape)
    offset = len(shp) - x.ndim  # new leading dims prepended by broadcast
    resolved = []
    for i, s in enumerate(shp):
        if s == -1:
            if i < offset:
                raise ValueError(
                    f"expand: -1 is not allowed for a newly added leading dim "
                    f"(dim {i} of target shape {tuple(shp)} for input shape "
                    f"{tuple(x._value.shape)})")
            s = x._value.shape[i - offset]
        resolved.append(s)
    shp = tuple(resolved)
    return forward_op("expand", lambda v: jnp.broadcast_to(v, shp), [x])


def expand_as(x, y, name=None) -> Tensor:
    return expand(x, ensure_tensor(y).shape)


def broadcast_to(x, shape, name=None) -> Tensor:
    return forward_op("broadcast_to",
                      lambda v: jnp.broadcast_to(v, _shape_arg(shape)),
                      [ensure_tensor(x)])


def broadcast_tensors(inputs, name=None):
    ts = [ensure_tensor(t) for t in inputs]
    outs = forward_op("broadcast_tensors", lambda *vs: tuple(jnp.broadcast_arrays(*vs)), ts)
    return list(outs)


def broadcast_shape(x_shape, y_shape):
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def gather(x, index, axis=0, name=None) -> Tensor:
    """paddle.gather: select rows of `axis` by a 1-D index."""
    x, index = ensure_tensor(x), ensure_tensor(index)
    ax = int(axis.item()) if isinstance(axis, Tensor) else int(axis)
    return forward_op("gather", lambda v, i: jnp.take(v, i.reshape(-1), axis=ax),
                      [x, index])


def gather_nd(x, index, name=None) -> Tensor:
    x, index = ensure_tensor(x), ensure_tensor(index)

    def impl(v, idx):
        return v[tuple(jnp.moveaxis(idx, -1, 0))]

    return forward_op("gather_nd", impl, [x, index])


def scatter(x, index, updates, overwrite=True, name=None) -> Tensor:
    """paddle.scatter: write `updates` rows into `x` at `index` along axis 0."""
    x, index, updates = ensure_tensor(x), ensure_tensor(index), ensure_tensor(updates)

    def impl(v, i, u):
        i = i.reshape(-1)
        if overwrite:
            return v.at[i].set(u.astype(v.dtype))
        zeroed = v.at[i].set(jnp.zeros_like(u, v.dtype))
        return zeroed.at[i].add(u.astype(v.dtype))

    return forward_op("scatter", impl, [x, index, updates])


def scatter_(x, index, updates, overwrite=True, name=None) -> Tensor:
    x._rebind(scatter(x, index, updates, overwrite))
    return x


def scatter_nd_add(x, index, updates, name=None) -> Tensor:
    x, index, updates = ensure_tensor(x), ensure_tensor(index), ensure_tensor(updates)

    def impl(v, i, u):
        return v.at[tuple(jnp.moveaxis(i, -1, 0))].add(u.astype(v.dtype))

    return forward_op("scatter_nd_add", impl, [x, index, updates])


def scatter_nd(index, updates, shape, name=None) -> Tensor:
    index, updates = ensure_tensor(index), ensure_tensor(updates)
    shp = _shape_arg(shape)

    def impl(i, u):
        base = jnp.zeros(shp, u.dtype)
        return base.at[tuple(jnp.moveaxis(i, -1, 0))].add(u)

    return forward_op("scatter_nd", impl, [index, updates])


def index_select(x, index, axis=0, name=None) -> Tensor:
    x, index = ensure_tensor(x), ensure_tensor(index)
    return forward_op("index_select",
                      lambda v, i: jnp.take(v, i.reshape(-1), axis=int(axis)),
                      [x, index])


def index_sample(x, index) -> Tensor:
    x, index = ensure_tensor(x), ensure_tensor(index)
    return forward_op("index_sample",
                      lambda v, i: jnp.take_along_axis(v, i, axis=1), [x, index])


def index_add(x, index, axis, value, name=None) -> Tensor:
    x, index, value = ensure_tensor(x), ensure_tensor(index), ensure_tensor(value)
    ax = int(axis)

    def impl(v, i, u):
        vm = jnp.moveaxis(v, ax, 0)
        um = jnp.moveaxis(u, ax, 0)
        out = vm.at[i.reshape(-1)].add(um.astype(v.dtype))
        return jnp.moveaxis(out, 0, ax)

    return forward_op("index_add", impl, [x, index, value])


def index_put(x, indices, value, accumulate=False, name=None) -> Tensor:
    x, value = ensure_tensor(x), ensure_tensor(value)
    idx = tuple(i._value if isinstance(i, Tensor) else i for i in indices)

    def impl(v, u):
        return v.at[idx].add(u) if accumulate else v.at[idx].set(u.astype(v.dtype))

    return forward_op("index_put", impl, [x, value])


def take_along_axis(arr, indices, axis, broadcast=True) -> Tensor:
    arr, indices = ensure_tensor(arr), ensure_tensor(indices)
    return forward_op("take_along_axis",
                      lambda v, i: jnp.take_along_axis(v, i, axis=int(axis)),
                      [arr, indices])


def put_along_axis(arr, indices, values, axis, reduce="assign", include_self=True,
                   broadcast=True) -> Tensor:
    arr, indices = ensure_tensor(arr), ensure_tensor(indices)
    values = ensure_tensor(values)

    def impl(v, i, u):
        u = jnp.broadcast_to(u.astype(v.dtype), i.shape)
        if reduce == "assign":
            return jnp.put_along_axis(v, i, u, axis=int(axis), inplace=False)
        mode = {"add": "add", "multiply": "multiply", "mul": "multiply",
                "amin": "min", "amax": "max"}[reduce]
        dim_idx = [jnp.arange(s).reshape([-1 if d == k else 1 for d in range(v.ndim)])
                   for k, s in enumerate(v.shape)]
        dim_idx[int(axis)] = i
        at = v.at[tuple(dim_idx)]
        return {"add": at.add, "multiply": at.multiply, "min": at.min,
                "max": at.max}[mode](u)

    return forward_op("put_along_axis", impl, [arr, indices, values])


def take(x, index, mode="raise", name=None) -> Tensor:
    x, index = ensure_tensor(x), ensure_tensor(index)
    jmode = {"raise": "clip", "clip": "clip", "wrap": "wrap"}[mode]
    return forward_op("take",
                      lambda v, i: jnp.take(v.reshape(-1), i, mode=jmode), [x, index])


def flip(x, axis, name=None) -> Tensor:
    ax = axes_arg(axis)
    return forward_op("flip", lambda v: jnp.flip(v, axis=ax), [ensure_tensor(x)])


def roll(x, shifts, axis=None, name=None) -> Tensor:
    sh = axes_arg(shifts)
    ax = axes_arg(axis)
    return forward_op("roll", lambda v: jnp.roll(v, sh, axis=ax), [ensure_tensor(x)])


def rot90(x, k=1, axes=(0, 1), name=None) -> Tensor:
    return forward_op("rot90", lambda v: jnp.rot90(v, k=k, axes=tuple(axes)),
                      [ensure_tensor(x)])


def repeat_interleave(x, repeats, axis=None, name=None) -> Tensor:
    x = ensure_tensor(x)
    r = repeats._value if isinstance(repeats, Tensor) else repeats
    return forward_op("repeat_interleave",
                      lambda v: jnp.repeat(v, r, axis=axes_arg(axis)), [x])


def masked_select(x, mask, name=None) -> Tensor:
    """Dynamic output shape: eager-only (not traceable under jit) — same caveat class
    as Paddle's dynamic-shape ops under to_static."""
    x, mask = ensure_tensor(x), ensure_tensor(mask)
    return forward_op("masked_select", lambda v, m: v[m.astype(bool)], [x, mask])


def masked_fill(x, mask, value, name=None) -> Tensor:
    x, mask = ensure_tensor(x), ensure_tensor(mask)
    if isinstance(value, Tensor):
        return forward_op("masked_fill",
                          lambda v, m, u: jnp.where(m.astype(bool), u.astype(v.dtype), v),
                          [x, mask, value])
    return forward_op("masked_fill",
                      lambda v, m: jnp.where(m.astype(bool), value, v), [x, mask])


def masked_scatter(x, mask, value, name=None) -> Tensor:
    x, mask, value = ensure_tensor(x), ensure_tensor(mask), ensure_tensor(value)

    def impl(v, m, u):
        m = m.astype(bool)
        flat_idx = jnp.cumsum(m.reshape(-1)) - 1
        picked = u.reshape(-1)[jnp.clip(flat_idx, 0, u.size - 1)]
        return jnp.where(m, picked.reshape(v.shape).astype(v.dtype), v)

    return forward_op("masked_scatter", impl, [x, mask, value])


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    """Eager-only (dynamic output shape)."""
    x = ensure_tensor(x)
    res = np.unique(np.asarray(x._value), return_index=return_index,
                    return_inverse=return_inverse, return_counts=return_counts,
                    axis=axes_arg(axis))
    if not isinstance(res, tuple):
        return to_tensor(res)
    return tuple(to_tensor(r) for r in res)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None,
                       dtype="int64", name=None):
    x = np.asarray(ensure_tensor(x)._value)
    if axis is None:
        x = x.reshape(-1)
        keep = np.concatenate([[True], x[1:] != x[:-1]])
    else:
        raise NotImplementedError("unique_consecutive with axis")
    out = to_tensor(x[keep])
    extras = []
    if return_inverse:
        extras.append(to_tensor(np.cumsum(keep) - 1))
    if return_counts:
        idx = np.flatnonzero(keep)
        extras.append(to_tensor(np.diff(np.append(idx, x.size))))
    return (out, *extras) if extras else out


def as_complex(x, name=None) -> Tensor:
    return forward_op("as_complex", lambda v: jax.lax.complex(v[..., 0], v[..., 1]),
                      [ensure_tensor(x)])


def as_real(x, name=None) -> Tensor:
    return forward_op("as_real",
                      lambda v: jnp.stack([jnp.real(v), jnp.imag(v)], axis=-1),
                      [ensure_tensor(x)])


def tensordot(x, y, axes=2, name=None) -> Tensor:
    ax = axes
    if isinstance(ax, Tensor):
        ax = np.asarray(ax._value).tolist()
    return forward_op("tensordot", lambda a, b: jnp.tensordot(a, b, axes=ax),
                      [ensure_tensor(x), ensure_tensor(y)])


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):  # noqa: A002
    input = ensure_tensor(input)
    size = index_num // nshards

    def impl(v):
        shard = v // size
        local = v % size
        return jnp.where(shard == shard_id, local, ignore_value)

    return forward_op("shard_index", impl, [input], differentiable=False)


def crop(x, shape=None, offsets=None, name=None) -> Tensor:
    x = ensure_tensor(x)
    shp = _shape_arg(shape)
    offs = _shape_arg(offsets) if offsets is not None else (0,) * len(shp)
    shp = tuple(x._value.shape[i] - offs[i] if s == -1 else s for i, s in enumerate(shp))

    def impl(v):
        return jax.lax.dynamic_slice(v, offs, shp)

    return forward_op("crop", impl, [x])


patch_methods([
    ("reshape", reshape), ("reshape_", reshape_), ("view", view), ("view_as", view_as),
    ("transpose", transpose), ("moveaxis", moveaxis), ("swapaxes", swapaxes),
    ("split", split), ("chunk", chunk), ("squeeze", squeeze), ("squeeze_", squeeze_),
    ("unsqueeze", unsqueeze), ("unsqueeze_", unsqueeze_), ("flatten", flatten),
    ("flatten_", flatten_), ("tile", tile), ("expand", expand), ("expand_as", expand_as),
    ("broadcast_to", broadcast_to), ("gather", gather), ("gather_nd", gather_nd),
    ("scatter", scatter), ("scatter_", scatter_), ("scatter_nd_add", scatter_nd_add),
    ("index_select", index_select), ("index_sample", index_sample),
    ("index_add", index_add), ("index_put", index_put),
    ("take_along_axis", take_along_axis), ("put_along_axis", put_along_axis),
    ("take", take), ("flip", flip), ("roll", roll), ("rot90", rot90),
    ("repeat_interleave", repeat_interleave), ("masked_select", masked_select),
    ("masked_fill", masked_fill), ("unique", unique), ("unbind", unbind),
    ("tensordot", tensordot), ("as_complex", as_complex), ("as_real", as_real),
])
