"""Elementwise and reduction math ops.

Parity target: ``python/paddle/tensor/math.py`` + ``python/paddle/tensor/stat.py`` in
the reference (backed there by phi kernels, ``paddle/phi/kernels/``). Here every op is
one pure-jnp function entering the dispatcher; XLA fuses elementwise chains into
surrounding matmuls on TPU, so there is no hand-written fusion tier
(``paddle/phi/kernels/fusion/``) for these.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.dtype import canonical_dtype
from ..core.tensor import Tensor, to_tensor
from ._helpers import (axes_arg, binary_factory, ensure_tensor, forward_op,
                       patch_methods, unary_factory)

# -- elementwise binary -----------------------------------------------------
add = binary_factory("add", jnp.add)
subtract = binary_factory("subtract", jnp.subtract)
multiply = binary_factory("multiply", jnp.multiply)
divide = binary_factory("divide", jnp.true_divide)
floor_divide = binary_factory("floor_divide", jnp.floor_divide)
remainder = binary_factory("remainder", jnp.remainder)
mod = remainder
floor_mod = remainder
pow_ = binary_factory("elementwise_pow", jnp.power)
maximum = binary_factory("maximum", jnp.maximum)
minimum = binary_factory("minimum", jnp.minimum)
fmax = binary_factory("fmax", jnp.fmax)
fmin = binary_factory("fmin", jnp.fmin)
atan2 = binary_factory("atan2", jnp.arctan2)
hypot = binary_factory("hypot", jnp.hypot)
logaddexp = binary_factory("logaddexp", jnp.logaddexp)
nextafter = binary_factory("nextafter", jnp.nextafter)
copysign = binary_factory("copysign", jnp.copysign)
heaviside = binary_factory("heaviside", lambda x, y: jnp.heaviside(x, y))
gcd = binary_factory("gcd", jnp.gcd)
lcm = binary_factory("lcm", jnp.lcm)
ldexp = binary_factory("ldexp", jnp.ldexp)
inner = binary_factory("inner", jnp.inner)
outer = binary_factory("outer", lambda x, y: jnp.outer(x, y))


def pow(x, y, name=None):  # noqa: A001 — Paddle public name
    return pow_(x, y)


# -- elementwise unary ------------------------------------------------------
exp = unary_factory("exp", jnp.exp)
expm1 = unary_factory("expm1", jnp.expm1)
log = unary_factory("log", jnp.log)
log2 = unary_factory("log2", jnp.log2)
log10 = unary_factory("log10", jnp.log10)
log1p = unary_factory("log1p", jnp.log1p)
sqrt = unary_factory("sqrt", jnp.sqrt)
rsqrt = unary_factory("rsqrt", jax.lax.rsqrt)
square = unary_factory("square", jnp.square)
abs = unary_factory("abs", jnp.abs)  # noqa: A001
sign = unary_factory("sign", jnp.sign)
neg = unary_factory("neg", jnp.negative)
negative = neg
reciprocal = unary_factory("reciprocal", jnp.reciprocal)
sin = unary_factory("sin", jnp.sin)
cos = unary_factory("cos", jnp.cos)
tan = unary_factory("tan", jnp.tan)
asin = unary_factory("asin", jnp.arcsin)
acos = unary_factory("acos", jnp.arccos)
atan = unary_factory("atan", jnp.arctan)
sinh = unary_factory("sinh", jnp.sinh)
cosh = unary_factory("cosh", jnp.cosh)
tanh = unary_factory("tanh", jnp.tanh)
asinh = unary_factory("asinh", jnp.arcsinh)
acosh = unary_factory("acosh", jnp.arccosh)
atanh = unary_factory("atanh", jnp.arctanh)
erf = unary_factory("erf", jax.scipy.special.erf)
erfinv = unary_factory("erfinv", jax.scipy.special.erfinv)
floor = unary_factory("floor", jnp.floor)
ceil = unary_factory("ceil", jnp.ceil)
round = unary_factory("round", jnp.round)  # noqa: A001
trunc = unary_factory("trunc", jnp.trunc)
frac = unary_factory("frac", lambda x: x - jnp.trunc(x))
sigmoid = unary_factory("sigmoid", jax.nn.sigmoid)
digamma = unary_factory("digamma", jax.scipy.special.digamma)
lgamma = unary_factory("lgamma", jax.scipy.special.gammaln)
gammaln = lgamma
i0 = unary_factory("i0", jax.scipy.special.i0)
i0e = unary_factory("i0e", jax.scipy.special.i0e)
i1 = unary_factory("i1", jax.scipy.special.i1)
i1e = unary_factory("i1e", jax.scipy.special.i1e)
deg2rad = unary_factory("deg2rad", jnp.deg2rad)
rad2deg = unary_factory("rad2deg", jnp.rad2deg)
conj = unary_factory("conj", jnp.conj)
real = unary_factory("real", jnp.real)
imag = unary_factory("imag", jnp.imag)
angle = unary_factory("angle", jnp.angle)
isfinite = unary_factory("isfinite", jnp.isfinite)
isinf = unary_factory("isinf", jnp.isinf)
isnan = unary_factory("isnan", jnp.isnan)
isneginf = unary_factory("isneginf", jnp.isneginf)
isposinf = unary_factory("isposinf", jnp.isposinf)
isreal = unary_factory("isreal", jnp.isreal)


def logit(x, eps=None, name=None):
    x = ensure_tensor(x)
    return forward_op("logit", _logit_impl, [x], {"eps": eps})


def _logit_impl(x, eps):
    if eps is not None:
        x = jnp.clip(x, eps, 1.0 - eps)
    return jnp.log(x / (1.0 - x))


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    x = ensure_tensor(x)
    return forward_op("nan_to_num", jnp.nan_to_num, [x],
                      {"nan": nan, "posinf": posinf, "neginf": neginf})


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    """paddle.scale: out = scale*x + bias (or scale*(x+bias))."""
    x = ensure_tensor(x)
    s = scale._value if isinstance(scale, Tensor) else scale

    def impl(x):
        out = x * s + bias if bias_after_scale else (x + bias) * s
        return out.astype(x.dtype)

    out = forward_op("scale", impl, [x])
    if act is not None:
        from ..nn import functional as F
        out = getattr(F, act)(out)
    return out


def clip(x, min=None, max=None, name=None):  # noqa: A002
    x = ensure_tensor(x)
    lo = min._value if isinstance(min, Tensor) else min
    hi = max._value if isinstance(max, Tensor) else max
    return forward_op("clip", lambda v: jnp.clip(v, lo, hi), [x])


def lerp(x, y, weight, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)
    if isinstance(weight, Tensor):
        return forward_op("lerp", lambda a, b, w: a + w * (b - a), [x, y, weight])
    return forward_op("lerp", lambda a, b: a + weight * (b - a), [x, y])


def cast(x, dtype):
    """Differentiable dtype cast (ref: paddle.cast / phi cast kernel)."""
    x = ensure_tensor(x)
    dt = canonical_dtype(dtype)
    return forward_op("cast", lambda v: v.astype(dt), [x])


def increment(x, value=1.0, name=None):
    x = ensure_tensor(x)
    new = forward_op("increment", lambda v: v + value, [x])
    x._rebind(new)
    return x


def add_n(inputs, name=None):
    """Sum a list of tensors (ref: paddle.add_n / sum_op)."""
    ts = [ensure_tensor(t) for t in inputs]

    def impl(*vs):
        out = vs[0]
        for v in vs[1:]:
            out = out + v
        return out

    return forward_op("add_n", impl, ts)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):  # noqa: A002
    return forward_op("addmm", lambda i, a, b: beta * i + alpha * (a @ b),
                      [ensure_tensor(input), ensure_tensor(x), ensure_tensor(y)])


def cross(x, y, axis=None, name=None):
    ax = -1 if axis is None else int(axis)
    return forward_op("cross", lambda a, b: jnp.cross(a, b, axis=ax),
                      [ensure_tensor(x), ensure_tensor(y)])


def dot(x, y, name=None):
    # paddle.dot: 1-D/2-D batched inner product over last dim
    return forward_op("dot", lambda a, b: jnp.sum(a * b, axis=-1),
                      [ensure_tensor(x), ensure_tensor(y)])


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return forward_op("trace",
                      lambda v: jnp.trace(v, offset=offset, axis1=axis1, axis2=axis2),
                      [ensure_tensor(x)])


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return forward_op("diagonal",
                      lambda v: jnp.diagonal(v, offset=offset, axis1=axis1, axis2=axis2),
                      [ensure_tensor(x)])


def kron(x, y, name=None):
    return forward_op("kron", jnp.kron, [ensure_tensor(x), ensure_tensor(y)])


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    args = [ensure_tensor(x)]
    pre = prepend._value if isinstance(prepend, Tensor) else prepend
    app = append._value if isinstance(append, Tensor) else append
    return forward_op("diff",
                      lambda v: jnp.diff(v, n=n, axis=axis, prepend=pre, append=app),
                      args)


# -- reductions -------------------------------------------------------------
def _reduction(name: str, jfn, allow_dtype=False):
    def op(x, axis=None, keepdim=False, dtype=None, name=None):
        x = ensure_tensor(x)
        ax = axes_arg(axis)
        kw = {"axis": ax, "keepdims": keepdim}
        if allow_dtype and dtype is not None:
            kw["dtype"] = canonical_dtype(dtype)
        return forward_op(name, lambda v: jfn(v, **kw), [x])

    op.__name__ = name
    op.__qualname__ = name
    op.__doc__ = f"Reduction {name} over `axis` (Paddle API parity)."
    return op


sum = _reduction("sum", jnp.sum, allow_dtype=True)  # noqa: A001
mean = _reduction("mean", jnp.mean)
prod = _reduction("prod", jnp.prod, allow_dtype=True)
max = _reduction("max", jnp.max)  # noqa: A001
min = _reduction("min", jnp.min)  # noqa: A001
amax = _reduction("amax", jnp.max)
amin = _reduction("amin", jnp.min)
nansum = _reduction("nansum", jnp.nansum, allow_dtype=True)
nanmean = _reduction("nanmean", jnp.nanmean)


def all(x, axis=None, keepdim=False, name=None):  # noqa: A001
    return forward_op("all", lambda v: jnp.all(v, axis=axes_arg(axis), keepdims=keepdim),
                      [ensure_tensor(x)])


def any(x, axis=None, keepdim=False, name=None):  # noqa: A001
    return forward_op("any", lambda v: jnp.any(v, axis=axes_arg(axis), keepdims=keepdim),
                      [ensure_tensor(x)])


def count_nonzero(x, axis=None, keepdim=False, name=None):
    return forward_op("count_nonzero",
                      lambda v: jnp.count_nonzero(v, axis=axes_arg(axis), keepdims=keepdim),
                      [ensure_tensor(x)])


def logsumexp(x, axis=None, keepdim=False, name=None):
    return forward_op("logsumexp",
                      lambda v: jax.scipy.special.logsumexp(v, axis=axes_arg(axis),
                                                            keepdims=keepdim),
                      [ensure_tensor(x)])


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    ddof = 1 if unbiased else 0
    return forward_op("std",
                      lambda v: jnp.std(v, axis=axes_arg(axis), ddof=ddof, keepdims=keepdim),
                      [ensure_tensor(x)])


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    ddof = 1 if unbiased else 0
    return forward_op("var",
                      lambda v: jnp.var(v, axis=axes_arg(axis), ddof=ddof, keepdims=keepdim),
                      [ensure_tensor(x)])


def median(x, axis=None, keepdim=False, mode="avg", name=None):
    return forward_op("median",
                      lambda v: jnp.median(v, axis=axes_arg(axis), keepdims=keepdim),
                      [ensure_tensor(x)])


def nanmedian(x, axis=None, keepdim=False, name=None):
    return forward_op("nanmedian",
                      lambda v: jnp.nanmedian(v, axis=axes_arg(axis), keepdims=keepdim),
                      [ensure_tensor(x)])


def quantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    qv = q._value if isinstance(q, Tensor) else jnp.asarray(q)
    return forward_op("quantile",
                      lambda v: jnp.quantile(v, qv, axis=axes_arg(axis), keepdims=keepdim,
                                             method=interpolation),
                      [ensure_tensor(x)])


def cumsum(x, axis=None, dtype=None, name=None):
    x = ensure_tensor(x)
    dt = canonical_dtype(dtype)

    def impl(v):
        if axis is None:
            v = v.reshape(-1)
            return jnp.cumsum(v, dtype=dt)
        return jnp.cumsum(v, axis=int(axis), dtype=dt)

    return forward_op("cumsum", impl, [x])


def cumprod(x, dim=None, dtype=None, name=None):
    x = ensure_tensor(x)
    dt = canonical_dtype(dtype)

    def impl(v):
        if dim is None:
            v = v.reshape(-1)
            return jnp.cumprod(v, dtype=dt)
        return jnp.cumprod(v, axis=int(dim), dtype=dt)

    return forward_op("cumprod", impl, [x])


def _cum_extreme(name, cmp):
    def op(x, axis=None, dtype="int64", name_=None):
        x = ensure_tensor(x)
        ax = 0 if axis is None else int(axis)
        idx_dt = canonical_dtype(dtype)

        def impl(v):
            if axis is None:
                v = v.reshape(-1)
            iota = jax.lax.broadcasted_iota(idx_dt, v.shape, ax)

            def comb(a, b):
                av, ai = a
                bv, bi = b
                take_b = cmp(bv, av)  # strict: earliest index wins ties (Paddle)
                return jnp.where(take_b, bv, av), jnp.where(take_b, bi, ai)

            return jax.lax.associative_scan(comb, (v, iota), axis=ax)

        return forward_op(name, impl, [x])

    op.__name__ = name
    return op


cummax = _cum_extreme("cummax", lambda b, a: b > a)
cummin = _cum_extreme("cummin", lambda b, a: b < a)


def logcumsumexp(x, axis=None, name=None):
    x = ensure_tensor(x)

    def impl(v):
        if axis is None:
            return jax.lax.cumlogsumexp(v.reshape(-1), axis=0)
        return jax.lax.cumlogsumexp(v, axis=int(axis))

    return forward_op("logcumsumexp", impl, [x])


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return forward_op("stanh", lambda v: scale_b * jnp.tanh(scale_a * v),
                      [ensure_tensor(x)])


def multiplex(inputs, index, name=None):
    ts = [ensure_tensor(t) for t in inputs]
    idx = ensure_tensor(index)

    def impl(i, *vs):
        stacked = jnp.stack(vs)  # [n, batch, ...]
        rows = jnp.arange(vs[0].shape[0])
        return stacked[i.reshape(-1), rows]

    return forward_op("multiplex", impl, [idx] + ts)


# -- in-place variants ------------------------------------------------------
def _inplace(x: Tensor, fn, *args, **kwargs):
    new = fn(x, *args, **kwargs)
    x._rebind(new)
    return x


def _make_inplace(fn):
    def op(x, *args, **kwargs):
        return _inplace(x, fn, *args, **kwargs)
    op.__name__ = fn.__name__ + "_"
    return op


add_ = _make_inplace(add)
subtract_ = _make_inplace(subtract)
multiply_ = _make_inplace(multiply)
divide_ = _make_inplace(divide)
clip_ = _make_inplace(clip)
scale_ = _make_inplace(scale)
exp_ = _make_inplace(exp)
sqrt_ = _make_inplace(sqrt)
rsqrt_ = _make_inplace(rsqrt)
reciprocal_ = _make_inplace(reciprocal)
round_ = _make_inplace(round)
floor_ = _make_inplace(floor)
ceil_ = _make_inplace(ceil)
tanh_ = _make_inplace(tanh)
abs_ = _make_inplace(abs)
sin_ = _make_inplace(sin)
cos_ = _make_inplace(cos)
neg_ = _make_inplace(neg)


# -- dunders + method patching ---------------------------------------------
def _rsub(x, y):
    return subtract(y, x)


def _rdiv(x, y):
    return divide(y, x)


def _rpow(x, y):
    return pow_(y, x)


def _rfloordiv(x, y):
    return floor_divide(y, x)


def _rmod(x, y):
    return remainder(y, x)


def _matmul_method(x, y):
    from . import linalg
    return linalg.matmul(x, y)


def _rmatmul_method(x, y):
    from . import linalg
    return linalg.matmul(y, x)


patch_methods([
    ("__add__", lambda s, o: add(s, o)), ("__radd__", lambda s, o: add(s, o)),
    ("__sub__", lambda s, o: subtract(s, o)), ("__rsub__", _rsub),
    ("__mul__", lambda s, o: multiply(s, o)), ("__rmul__", lambda s, o: multiply(s, o)),
    ("__truediv__", lambda s, o: divide(s, o)), ("__rtruediv__", _rdiv),
    ("__floordiv__", lambda s, o: floor_divide(s, o)), ("__rfloordiv__", _rfloordiv),
    ("__mod__", lambda s, o: remainder(s, o)), ("__rmod__", _rmod),
    ("__pow__", lambda s, o: pow_(s, o)), ("__rpow__", _rpow),
    ("__neg__", lambda s: neg(s)), ("__abs__", lambda s: abs(s)),
    ("__matmul__", _matmul_method), ("__rmatmul__", _rmatmul_method),
    ("__pos__", lambda s: s),
    ("add", add), ("subtract", subtract), ("multiply", multiply), ("divide", divide),
    ("floor_divide", floor_divide), ("remainder", remainder), ("mod", remainder),
    ("pow", pow), ("maximum", maximum), ("minimum", minimum), ("fmax", fmax),
    ("fmin", fmin), ("atan2", atan2),
    ("exp", exp), ("log", log), ("log2", log2), ("log10", log10), ("log1p", log1p),
    ("sqrt", sqrt), ("rsqrt", rsqrt), ("square", square), ("abs", abs), ("sign", sign),
    ("reciprocal", reciprocal), ("sin", sin), ("cos", cos), ("tan", tan),
    ("tanh", tanh), ("sigmoid", sigmoid), ("erf", erf), ("erfinv", erfinv),
    ("floor", floor), ("ceil", ceil), ("round", round), ("trunc", trunc),
    ("frac", frac), ("digamma", digamma), ("lgamma", lgamma),
    ("isfinite", isfinite), ("isinf", isinf), ("isnan", isnan),
    ("scale", scale), ("clip", clip), ("lerp", lerp), ("cast", cast),
    ("astype", cast), ("nan_to_num", nan_to_num), ("logit", logit),
    ("sum", sum), ("mean", mean), ("prod", prod), ("max", max), ("min", min),
    ("amax", amax), ("amin", amin), ("all", all), ("any", any),
    ("logsumexp", logsumexp), ("std", std), ("var", var), ("median", median),
    ("quantile", quantile), ("cumsum", cumsum), ("cumprod", cumprod),
    ("logcumsumexp", logcumsumexp), ("count_nonzero", count_nonzero),
    ("nansum", nansum), ("nanmean", nanmean),
    ("dot", dot), ("cross", cross), ("trace", trace), ("diagonal", diagonal),
    ("kron", kron), ("inner", inner), ("outer", outer), ("addmm", addmm),
    ("diff", diff), ("neg", neg),
    ("add_", add_), ("subtract_", subtract_), ("multiply_", multiply_),
    ("divide_", divide_), ("clip_", clip_), ("scale_", scale_), ("exp_", exp_),
    ("sqrt_", sqrt_), ("rsqrt_", rsqrt_), ("reciprocal_", reciprocal_),
    ("round_", round_), ("floor_", floor_), ("ceil_", ceil_), ("tanh_", tanh_),
    ("abs_", abs_), ("sin_", sin_), ("cos_", cos_), ("neg_", neg_),
])
