"""Quantization op family.

Parity targets: the fake-quant training ops
(``paddle/fluid/operators/fake_quantize_op.*`` — QAT observers), the
quantize/dequantize_linear pair (``paddle/fluid/operators/quantize_linear_op``),
and the weight-only inference surface
(``paddle/incubate/nn/functional/weight_only_linear``, ``weight_quantize`` /
``weight_dequantize``, ``llm_int8_linear``).

TPU redesign: the reference implements each observer as a stateful CUDA
kernel mutating scale buffers in place; here every op is a pure function —
state (moving scales, accumulators) goes in and comes out explicitly, which
is what makes them jit/scan-compatible under XLA. The weight-only path
routes through the Pallas int8 stream kernel (``kernels/quant_matmul.py``)
on TPU backends and an XLA dequant-matmul elsewhere.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ._helpers import Tensor, ensure_tensor, forward_op

__all__ = [
    "fake_quantize_abs_max", "fake_quantize_dequantize_abs_max",
    "fake_channel_wise_quantize_abs_max",
    "fake_channel_wise_quantize_dequantize_abs_max",
    "fake_quantize_range_abs_max", "fake_quantize_moving_average_abs_max",
    "fake_quantize_dequantize_moving_average_abs_max",
    "moving_average_abs_max_scale", "quantize_linear", "dequantize_linear",
    "weight_quantize", "weight_dequantize", "weight_only_linear",
    "llm_int8_linear",
]


def _qmax(bit_length: int) -> float:
    return float((1 << (bit_length - 1)) - 1)


# ---------------------------------------------------------------------------
# fake-quant observers (QAT). Pure: (x, state...) -> (out, new_state...)
# ---------------------------------------------------------------------------

def fake_quantize_abs_max(x, bit_length: int = 8, name=None):
    """Per-tensor abs-max quantization: returns ``(q, scale)`` with
    ``q = round(x / scale * qmax)`` as int round kept in float storage (the
    reference's fake-quant contract)."""
    xt = ensure_tensor(x)
    qmax = _qmax(bit_length)

    def impl(xv):
        scale = jnp.max(jnp.abs(xv))
        s = jnp.maximum(scale, 1e-8)
        return jnp.clip(jnp.round(xv / s * qmax), -qmax, qmax), scale

    return forward_op("fake_quantize_abs_max", impl, [xt],
                      differentiable=False)


def fake_quantize_dequantize_abs_max(x, bit_length: int = 8, name=None):
    """Quantize-then-dequantize (the straight-through QAT forward); returns
    ``(out, scale)``. Differentiable via the STE: gradient flows as
    identity within the clip range (jnp formulation uses the same rounding
    but the tape records the smooth surrogate)."""
    xt = ensure_tensor(x)
    qmax = _qmax(bit_length)

    def impl(xv):
        scale = jnp.max(jnp.abs(xv))
        s = jnp.maximum(scale, 1e-8)
        q = jnp.clip(jnp.round(xv / s * qmax), -qmax, qmax)
        # straight-through estimator: identity gradient through the rounding
        deq = xv + jax.lax.stop_gradient(q * s / qmax - xv)
        return deq, scale

    return forward_op("fake_quantize_dequantize_abs_max", impl, [xt])


def fake_channel_wise_quantize_abs_max(x, bit_length: int = 8,
                                       quant_axis: int = 0, name=None):
    """Per-channel abs-max quantization along ``quant_axis``; returns
    ``(q, scales)``."""
    xt = ensure_tensor(x)
    qmax = _qmax(bit_length)

    def impl(xv):
        axes = tuple(d for d in range(xv.ndim) if d != quant_axis)
        scale = jnp.max(jnp.abs(xv), axis=axes)
        shape = [1] * xv.ndim
        shape[quant_axis] = -1
        s = jnp.maximum(scale, 1e-8).reshape(shape)
        return jnp.clip(jnp.round(xv / s * qmax), -qmax, qmax), scale

    return forward_op("fake_channel_wise_quantize_abs_max", impl, [xt],
                      differentiable=False)


def fake_channel_wise_quantize_dequantize_abs_max(x, bit_length: int = 8,
                                                  quant_axis: int = 0,
                                                  name=None):
    """Per-channel quantize-dequantize with STE gradient; returns
    ``(out, scales)``."""
    xt = ensure_tensor(x)
    qmax = _qmax(bit_length)

    def impl(xv):
        axes = tuple(d for d in range(xv.ndim) if d != quant_axis)
        scale = jnp.max(jnp.abs(xv), axis=axes)
        shape = [1] * xv.ndim
        shape[quant_axis] = -1
        s = jnp.maximum(scale, 1e-8).reshape(shape)
        q = jnp.clip(jnp.round(xv / s * qmax), -qmax, qmax)
        deq = xv + jax.lax.stop_gradient(q * s / qmax - xv)
        return deq, scale

    return forward_op("fake_channel_wise_quantize_dequantize_abs_max",
                      impl, [xt])


def fake_quantize_range_abs_max(x, in_scale, window_size: int = 10000,
                                bit_length: int = 8, is_test: bool = False,
                                name=None):
    """Range-tracked abs-max: scale = max(current batch max, tracked scale)
    (the reference's windowed observer made pure: the tracked scale is an
    explicit input/output). Returns ``(q, out_scale)``."""
    xt = ensure_tensor(x)
    st = ensure_tensor(in_scale)
    qmax = _qmax(bit_length)

    def impl(xv, sv):
        cur = jnp.max(jnp.abs(xv))
        scale = sv if is_test else jnp.maximum(cur, sv)
        s = jnp.maximum(scale, 1e-8)
        return jnp.clip(jnp.round(xv / s * qmax), -qmax, qmax), scale

    return forward_op("fake_quantize_range_abs_max", impl, [xt, st],
                      differentiable=False)


def moving_average_abs_max_scale(x, accum, state, rate: float = 0.9,
                                 name=None):
    """EMA abs-max observer: returns ``(scale, new_accum, new_state)`` with
    ``accum = rate*accum + |x|_max``, ``state = rate*state + 1``,
    ``scale = accum/state`` (pure form of the reference's in-place
    moving_average_abs_max_scale_op)."""
    xt = ensure_tensor(x)
    at = ensure_tensor(accum)
    st = ensure_tensor(state)

    def impl(xv, av, sv):
        cur = jnp.max(jnp.abs(xv))
        na = rate * av + cur
        ns = rate * sv + 1.0
        return na / ns, na, ns

    return forward_op("moving_average_abs_max_scale", impl, [xt, at, st],
                      differentiable=False)


def fake_quantize_moving_average_abs_max(x, accum, state, rate: float = 0.9,
                                         bit_length: int = 8,
                                         is_test: bool = False, name=None):
    """EMA-scaled fake quantization; returns ``(q, scale, accum, state)``."""
    xt = ensure_tensor(x)
    at = ensure_tensor(accum)
    st = ensure_tensor(state)
    qmax = _qmax(bit_length)

    def impl(xv, av, sv):
        if is_test:
            scale, na, ns = av / jnp.maximum(sv, 1e-8), av, sv
        else:
            cur = jnp.max(jnp.abs(xv))
            na = rate * av + cur
            ns = rate * sv + 1.0
            scale = na / ns
        s = jnp.maximum(scale, 1e-8)
        return (jnp.clip(jnp.round(xv / s * qmax), -qmax, qmax),
                scale, na, ns)

    return forward_op("fake_quantize_moving_average_abs_max", impl,
                      [xt, at, st], differentiable=False)


def fake_quantize_dequantize_moving_average_abs_max(
        x, accum, state, rate: float = 0.9, bit_length: int = 8,
        is_test: bool = False, name=None):
    """EMA-scaled quantize-dequantize with STE gradient; returns
    ``(out, scale, accum, state)``."""
    xt = ensure_tensor(x)
    at = ensure_tensor(accum)
    st = ensure_tensor(state)
    qmax = _qmax(bit_length)

    def impl(xv, av, sv):
        if is_test:
            scale, na, ns = av / jnp.maximum(sv, 1e-8), av, sv
        else:
            cur = jax.lax.stop_gradient(jnp.max(jnp.abs(xv)))
            na = rate * av + cur
            ns = rate * sv + 1.0
            scale = na / ns
        s = jnp.maximum(scale, 1e-8)
        q = jnp.clip(jnp.round(xv / s * qmax), -qmax, qmax)
        deq = xv + jax.lax.stop_gradient(q * s / qmax - xv)
        return deq, scale, na, ns

    return forward_op("fake_quantize_dequantize_moving_average_abs_max",
                      impl, [xt, at, st])


# ---------------------------------------------------------------------------
# quantize/dequantize_linear (ONNX-style affine pair)
# ---------------------------------------------------------------------------

def quantize_linear(x, scale, zero_point=None, quant_axis: int = -1,
                    bit_length: int = 8, name=None):
    """Affine quantization ``q = clip(round(x/scale) + zp)`` (ref:
    quantize_linear_op). ``quant_axis=-1`` is per-tensor; otherwise
    per-channel along that axis. Returns int8-ranged values (int32
    storage, matching the reference's out dtype pre-cast)."""
    xt = ensure_tensor(x)
    st = ensure_tensor(scale)
    qmax = _qmax(bit_length)
    args = [xt, st]
    if zero_point is not None:
        args.append(ensure_tensor(zero_point))

    def impl(xv, sv, *zp):
        z = zp[0] if zp else 0
        if quant_axis >= 0 and sv.ndim:
            shape = [1] * xv.ndim
            shape[quant_axis] = -1
            sv = sv.reshape(shape)
            z = z.reshape(shape) if zp else 0
        q = jnp.round(xv / jnp.maximum(sv, 1e-8)) + z
        return jnp.clip(q, -qmax - 1, qmax).astype(jnp.int32)

    return forward_op("quantize_linear", impl, args, differentiable=False)


def dequantize_linear(x, scale, zero_point=None, quant_axis: int = -1,
                      name=None):
    """Affine dequantization ``(q - zp) * scale`` (ref:
    dequantize_linear_op)."""
    xt = ensure_tensor(x)
    st = ensure_tensor(scale)
    args = [xt, st]
    if zero_point is not None:
        args.append(ensure_tensor(zero_point))

    def impl(xv, sv, *zp):
        z = zp[0] if zp else 0
        if quant_axis >= 0 and sv.ndim:
            shape = [1] * xv.ndim
            shape[quant_axis] = -1
            sv = sv.reshape(shape)
            z = z.reshape(shape) if zp else 0
        return (xv.astype(jnp.float32) - z) * sv

    return forward_op("dequantize_linear", impl, args, differentiable=False)


# ---------------------------------------------------------------------------
# weight-only inference surface (paddle.incubate parity)
# ---------------------------------------------------------------------------

def weight_quantize(w, algo: str = "weight_only_int8", name=None):
    """Per-output-channel symmetric int8 weight quantization; returns
    ``(int8_weight [K, N], scales [N])`` (ref:
    paddle.incubate.nn.functional.weight_quantize; the reference also
    repacks for its CUDA tile layout — XLA/Pallas needs no repack, the
    kernel reads the natural [K, N] layout)."""
    if algo not in ("weight_only_int8", "llm.int8"):
        raise ValueError(f"unsupported algo {algo!r} (int4 packing has no "
                         "TPU kernel here)")
    wt = ensure_tensor(w)

    def impl(wv):
        scale = jnp.maximum(jnp.max(jnp.abs(wv), axis=0), 1e-8)  # [N]
        q = jnp.clip(jnp.round(wv / scale[None, :] * 127.0), -127, 127)
        return q.astype(jnp.int8), (scale / 127.0).astype(jnp.float32)

    return forward_op("weight_quantize", impl, [wt], differentiable=False)


def weight_dequantize(w, scale, name=None):
    """Inverse of :func:`weight_quantize`: ``w_int8 * scale`` -> float."""
    wt = ensure_tensor(w)
    st = ensure_tensor(scale)
    return forward_op(
        "weight_dequantize",
        lambda wv, sv: wv.astype(jnp.float32) * sv[None, :],
        [wt, st], differentiable=False)


def weight_only_linear(x, weight, scale, bias=None, weight_dtype="int8",
                       name=None):
    """``x @ dequant(weight)`` with int8 weights streamed from HBM (ref:
    paddle.incubate.nn.functional.weight_only_linear). On TPU backends this
    routes to the Pallas stream-dequant kernel
    (``kernels.quant_matmul.quant_matmul``); elsewhere an XLA
    dequant-matmul with identical numerics."""
    if weight_dtype != "int8":
        raise ValueError("only int8 weights are supported")
    xt = ensure_tensor(x)
    wt = ensure_tensor(weight)
    st = ensure_tensor(scale)
    args = [xt, wt, st]
    if bias is not None:
        args.append(ensure_tensor(bias))

    def impl(xv, wv, sv, *b):
        lead = xv.shape[:-1]
        x2 = xv.reshape(-1, xv.shape[-1])
        from ..kernels.dispatch import on_tpu
        if on_tpu():
            from ..kernels.quant_matmul import weight_only_matmul
            out = weight_only_matmul(x2, wv, sv,
                                     out_dtype=x2.dtype).astype(x2.dtype)
        else:
            out = x2 @ (wv.astype(x2.dtype) * sv[None, :].astype(x2.dtype))
        out = out.reshape(lead + (wv.shape[1],))
        return out + b[0] if b else out

    return forward_op("weight_only_linear", impl, args)


def llm_int8_linear(x, weight, scale, threshold: float = 6.0, name=None):
    """LLM.int8: columns of ``x`` with amax above ``threshold`` run in
    fp16/bf16, the rest through the int8 path (ref:
    paddle.incubate.nn.functional.llm_int8_linear). TPU formulation: the
    split is a mask, both paths are dense matmuls, XLA fuses the merge —
    no dynamic shapes."""
    xt = ensure_tensor(x)
    wt = ensure_tensor(weight)
    st = ensure_tensor(scale)

    def impl(xv, wv, sv):
        lead = xv.shape[:-1]
        x2 = xv.reshape(-1, xv.shape[-1])
        outlier = jnp.max(jnp.abs(x2), axis=0) > threshold       # [K]
        # inlier path: dynamic per-row int8 activation quant, int8xint8
        # matmul accumulated in int32 (MXU native), double dequant
        x_in = jnp.where(outlier[None, :], 0, x2)
        xs = jnp.maximum(jnp.max(jnp.abs(x_in), axis=1), 1e-8)   # [M]
        xq = jnp.clip(jnp.round(x_in / xs[:, None] * 127.0),
                      -127, 127).astype(jnp.int8)
        acc = jax.lax.dot(xq, wv, preferred_element_type=jnp.int32)
        inl = acc.astype(jnp.float32) * (xs[:, None] / 127.0) * sv[None, :]
        # outlier columns stay in floating point
        x_out = jnp.where(outlier[None, :], x2, 0)
        wf = wv.astype(x2.dtype) * sv[None, :].astype(x2.dtype)
        out = inl.astype(x2.dtype) + x_out @ wf
        return out.reshape(lead + (wv.shape[1],))

    return forward_op("llm_int8_linear", impl, [xt, wt, st])


def fake_dequantize_max_abs(x, scale, max_range: float = 127.0, name=None):
    """Dequantize by the recorded abs-max scale: ``x * scale / max_range``
    (ref: fake_dequantize_max_abs_op)."""
    return forward_op(
        "fake_dequantize_max_abs",
        lambda v, s: v.astype(jnp.float32) * s / max_range,
        [ensure_tensor(x), ensure_tensor(scale)], differentiable=False)


def fake_channel_wise_dequantize_max_abs(x, scales, quant_bits=(8,),
                                         quant_axis: int = 0, name=None):
    """Per-channel dequantize (ref:
    fake_channel_wise_dequantize_max_abs_op)."""
    st = [ensure_tensor(s) for s in
          (scales if isinstance(scales, (list, tuple)) else [scales])]
    qmax = float((1 << (quant_bits[0] - 1)) - 1)

    def impl(v, s, *more):
        shape = [1] * v.ndim
        shape[quant_axis] = -1
        out = v.astype(jnp.float32) * s.reshape(shape) / qmax
        for extra in more:   # second-level (whole-tensor) scale
            out = out * extra / qmax
        return out

    return forward_op("fake_channel_wise_dequantize_max_abs", impl,
                      [ensure_tensor(x)] + st, differentiable=False)


__all__ += ["fake_dequantize_max_abs", "fake_channel_wise_dequantize_max_abs"]
