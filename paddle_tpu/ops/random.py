"""Random ops and global RNG state.

Parity target: ``python/paddle/tensor/random.py`` + ``paddle.seed`` / generator state
(reference: ``paddle/phi/core/generator.h``). TPU redesign: the global generator is a
splittable ``jax.random`` key held in a module-level state object. Each op splits the
key functionally; ``jit.to_static`` captures the state as an implicit input/output of
the compiled program, so compiled steps draw fresh randomness per call (unlike naive
tracing which would bake the key in as a constant).
"""

from __future__ import annotations

import threading
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..core.dtype import canonical_dtype, get_default_dtype
from ..core.tensor import Tensor, to_tensor
from ._helpers import ensure_tensor, forward_op, patch_methods


class Generator:
    """Splittable-key RNG generator (``paddle.Generator`` parity).

    The key materializes on first use, NOT at construction: the module-level
    default generator must not initialize the XLA backend at import time
    (jax.distributed.initialize must run before any backend touch)."""

    def __init__(self, seed: int = 0):
        self.key = None
        self._seed = seed

    def _ensure(self):
        if self.key is None:
            self.key = jax.random.PRNGKey(self._seed)

    def manual_seed(self, seed: int):
        self.key = jax.random.PRNGKey(seed)
        self._seed = seed
        return self

    def initial_seed(self) -> int:
        return self._seed

    def next_key(self):
        self._ensure()
        self.key, sub = jax.random.split(self.key)
        return sub

    def get_state(self):
        self._ensure()
        return to_tensor(self.key)

    def set_state(self, state):
        self.key = state._value if isinstance(state, Tensor) else jnp.asarray(state)


_default_generator = Generator(0)


def default_generator() -> Generator:
    return _default_generator


def seed(s: int) -> Generator:
    """paddle.seed parity: reseed the global generator."""
    _default_generator.manual_seed(int(s))
    return _default_generator


def get_rng_state():
    return [_default_generator.get_state()]


def set_rng_state(state_list):
    _default_generator.set_state(state_list[0])


def _next_key():
    # under a to_static trace the key is an implicit program input (fresh
    # randomness per compiled call instead of a baked trace-time constant)
    from ..core.tensor import _trace_hook
    ctx = _trace_hook.ctx
    if ctx is not None:
        return ctx.rng_key()
    return _default_generator.next_key()


def _float_dt(dtype):
    d = canonical_dtype(dtype)
    return d if d is not None else get_default_dtype()


def rand(shape, dtype=None, name=None) -> Tensor:
    return uniform(shape, dtype, min=0.0, max=1.0)


def randn(shape, dtype=None, name=None) -> Tensor:
    return standard_normal(shape, dtype)


def standard_normal(shape, dtype=None, name=None) -> Tensor:
    from .creation import _shape_arg
    return Tensor(jax.random.normal(_next_key(), _shape_arg(shape), _float_dt(dtype)))


def normal(mean=0.0, std=1.0, shape=None, name=None) -> Tensor:
    from .creation import _shape_arg
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = mean._value if isinstance(mean, Tensor) else jnp.asarray(mean)
        s = std._value if isinstance(std, Tensor) else jnp.asarray(std)
        shp = jnp.broadcast_shapes(m.shape, s.shape)
        z = jax.random.normal(_next_key(), shp, get_default_dtype())
        return Tensor(m + s * z)
    shp = _shape_arg(shape) if shape is not None else ()
    z = jax.random.normal(_next_key(), shp, get_default_dtype())
    return Tensor(mean + std * z)


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None) -> Tensor:  # noqa: A002
    from .creation import _shape_arg
    key = jax.random.PRNGKey(seed) if seed else _next_key()
    lo = min.item() if isinstance(min, Tensor) else min
    hi = max.item() if isinstance(max, Tensor) else max
    return Tensor(jax.random.uniform(key, _shape_arg(shape), _float_dt(dtype),
                                     minval=lo, maxval=hi))


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None) -> Tensor:
    from .creation import _shape_arg
    if high is None:
        low, high = 0, low
    return Tensor(jax.random.randint(_next_key(), _shape_arg(shape), int(low), int(high),
                                     canonical_dtype(dtype)))


def randint_like(x, low=0, high=None, dtype=None, name=None) -> Tensor:
    x = ensure_tensor(x)
    dt = canonical_dtype(dtype) or x.dtype
    if high is None:
        low, high = 0, low
    return Tensor(jax.random.randint(_next_key(), tuple(x.shape), int(low), int(high),
                                     dt if jnp.issubdtype(dt, jnp.integer) else jnp.int64
                                     ).astype(dt))


def randperm(n, dtype="int64", name=None) -> Tensor:
    return Tensor(jax.random.permutation(_next_key(), int(n)).astype(canonical_dtype(dtype)))


def bernoulli(x, name=None) -> Tensor:
    x = ensure_tensor(x)
    return forward_op(
        "bernoulli",
        lambda v, key: jax.random.bernoulli(key, v).astype(v.dtype),
        [x, Tensor(_next_key())], differentiable=False)


def bernoulli_(x, p=0.5, name=None) -> Tensor:
    x.set_value(jax.random.bernoulli(_next_key(), p, tuple(x.shape)).astype(x.dtype))
    return x


def poisson(x, name=None) -> Tensor:
    x = ensure_tensor(x)
    return Tensor(jax.random.poisson(_next_key(), x._value).astype(x.dtype))


def multinomial(x, num_samples=1, replacement=False, name=None) -> Tensor:
    x = ensure_tensor(x)
    logits = jnp.log(jnp.clip(x._value, 1e-30, None))
    if replacement:
        out = jax.random.categorical(_next_key(), logits, axis=-1,
                                     shape=(num_samples,) + logits.shape[:-1])
        out = jnp.moveaxis(out, 0, -1)
    else:
        # Gumbel top-k trick: without-replacement sampling
        g = jax.random.gumbel(_next_key(), logits.shape, logits.dtype)
        _, out = jax.lax.top_k(logits + g, num_samples)
    return Tensor(out.astype(jnp.int64))


def exponential_(x, lam=1.0, name=None) -> Tensor:
    x.set_value(jax.random.exponential(_next_key(), tuple(x.shape)).astype(x.dtype) / lam)
    return x


def uniform_(x, min=-1.0, max=1.0, seed=0, name=None) -> Tensor:  # noqa: A002
    x.set_value(jax.random.uniform(_next_key(), tuple(x.shape),
                                   x.dtype if jnp.issubdtype(x.dtype, jnp.floating)
                                   else jnp.float32, minval=min, maxval=max))
    return x


def normal_(x, mean=0.0, std=1.0, name=None) -> Tensor:
    x.set_value(mean + std * jax.random.normal(_next_key(), tuple(x.shape), x.dtype))
    return x


def rand_like(x, dtype=None, name=None) -> Tensor:
    x = ensure_tensor(x)
    dt = canonical_dtype(dtype) or x.dtype
    return Tensor(jax.random.uniform(_next_key(), tuple(x.shape), dt))


def randn_like(x, dtype=None, name=None) -> Tensor:
    x = ensure_tensor(x)
    dt = canonical_dtype(dtype) or x.dtype
    return Tensor(jax.random.normal(_next_key(), tuple(x.shape), dt))


patch_methods([
    ("bernoulli_", bernoulli_), ("exponential_", exponential_),
    ("uniform_", uniform_), ("normal_", normal_), ("multinomial", multinomial),
])
