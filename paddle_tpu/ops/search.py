"""Search / sort / selection ops.

Parity target: ``python/paddle/tensor/search.py`` in the reference. Ops with
data-dependent output shapes (``nonzero``, ``masked_select``) are eager-only, the same
restriction class Paddle documents for them under ``to_static``.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.dtype import canonical_dtype
from ..core.tensor import Tensor, to_tensor
from ._helpers import ensure_tensor, forward_op, patch_methods


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None) -> Tensor:
    x = ensure_tensor(x)
    dt = canonical_dtype(dtype)

    def impl(v):
        out = jnp.argmax(v, axis=axis if axis is None else int(axis), keepdims=keepdim)
        return out.astype(dt)

    return forward_op("argmax", impl, [x], differentiable=False)


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None) -> Tensor:
    x = ensure_tensor(x)
    dt = canonical_dtype(dtype)

    def impl(v):
        out = jnp.argmin(v, axis=axis if axis is None else int(axis), keepdims=keepdim)
        return out.astype(dt)

    return forward_op("argmin", impl, [x], differentiable=False)


def argsort(x, axis=-1, descending=False, stable=False, name=None) -> Tensor:
    x = ensure_tensor(x)

    def impl(v):
        idx = jnp.argsort(v, axis=int(axis), stable=stable, descending=descending)
        return idx.astype(jnp.int64)

    return forward_op("argsort", impl, [x], differentiable=False)


def sort(x, axis=-1, descending=False, stable=False, name=None) -> Tensor:
    x = ensure_tensor(x)

    def impl(v):
        return jnp.sort(v, axis=int(axis), stable=stable, descending=descending)

    return forward_op("sort", impl, [x])


def topk(x, k, axis=None, largest=True, sorted=True, name=None):  # noqa: A002
    x = ensure_tensor(x)
    kk = int(k.item()) if isinstance(k, Tensor) else int(k)
    ax = -1 if axis is None else int(axis)

    def impl(v):
        vm = jnp.moveaxis(v, ax, -1)
        if largest:
            vals, idx = jax.lax.top_k(vm, kk)
        else:
            vals, idx = jax.lax.top_k(-vm, kk)
            vals = -vals
        return jnp.moveaxis(vals, -1, ax), jnp.moveaxis(idx.astype(jnp.int64), -1, ax)

    return forward_op("topk", impl, [x])


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    x = ensure_tensor(x)
    kk = int(k)

    def impl(v):
        sv = jnp.sort(v, axis=int(axis))
        si = jnp.argsort(v, axis=int(axis)).astype(jnp.int64)
        vals = jnp.take(sv, kk - 1, axis=int(axis))
        idx = jnp.take(si, kk - 1, axis=int(axis))
        if keepdim:
            vals = jnp.expand_dims(vals, int(axis))
            idx = jnp.expand_dims(idx, int(axis))
        return vals, idx

    return forward_op("kthvalue", impl, [x])


def mode(x, axis=-1, keepdim=False, name=None):
    """Most frequent value along axis (host fallback; uncommon op)."""
    x = ensure_tensor(x)
    arr = np.asarray(x._value)
    ax = int(axis) % arr.ndim
    moved = np.moveaxis(arr, ax, -1)
    flat = moved.reshape(-1, moved.shape[-1])
    out = np.empty(flat.shape[0], arr.dtype)
    idx = np.empty(flat.shape[0], np.int64)
    for i, row in enumerate(flat):
        uniq, inv, counts = np.unique(row, return_inverse=True, return_counts=True)
        winner = counts.argmax()
        out[i] = uniq[winner]
        idx[i] = np.where(inv == winner)[0][-1]  # paddle returns the last occurrence
    out = out.reshape(moved.shape[:-1])
    idx = idx.reshape(moved.shape[:-1])
    if keepdim:
        out = np.expand_dims(out, ax)
        idx = np.expand_dims(idx, ax)
    return to_tensor(out), to_tensor(idx)


def where(condition, x=None, y=None, name=None):
    condition = ensure_tensor(condition)
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    return forward_op("where",
                      lambda c, a, b: jnp.where(c.astype(bool), a, b),
                      [condition, ensure_tensor(x), ensure_tensor(y)])


def where_(condition, x, y, name=None):
    out = where(condition, x, y)
    x._rebind(out)
    return x


def nonzero(x, as_tuple=False):
    """Eager-only (dynamic output shape)."""
    x = ensure_tensor(x)
    nz = np.nonzero(np.asarray(x._value))
    if as_tuple:
        return tuple(to_tensor(i.astype(np.int64)) for i in nz)
    return to_tensor(np.stack(nz, axis=1).astype(np.int64))


def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None) -> Tensor:
    dt = jnp.int32 if out_int32 else jnp.int64
    return forward_op("searchsorted",
                      lambda s, v: jnp.searchsorted(
                          s, v, side="right" if right else "left").astype(dt),
                      [ensure_tensor(sorted_sequence), ensure_tensor(values)],
                      differentiable=False)


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None) -> Tensor:
    return searchsorted(sorted_sequence, x, out_int32=out_int32, right=right)


def index_fill(x, index, axis, value, name=None) -> Tensor:
    x, index = ensure_tensor(x), ensure_tensor(index)

    def impl(v, i):
        vm = jnp.moveaxis(v, int(axis), 0)
        vm = vm.at[i.reshape(-1)].set(value)
        return jnp.moveaxis(vm, 0, int(axis))

    return forward_op("index_fill", impl, [x, index])


def histogram(input, bins=100, min=0, max=0, name=None) -> Tensor:  # noqa: A002
    input = ensure_tensor(input)
    lo, hi = (None, None) if (min == 0 and max == 0) else (min, max)

    def impl(v):
        r = None if lo is None else (lo, hi)
        h, _ = jnp.histogram(v.reshape(-1), bins=bins, range=r)
        return h

    return forward_op("histogram", impl, [input], differentiable=False)


def bincount(x, weights=None, minlength=0, name=None) -> Tensor:
    x = ensure_tensor(x)
    arr = np.asarray(x._value)
    w = np.asarray(weights._value) if isinstance(weights, Tensor) else weights
    return to_tensor(np.bincount(arr, weights=w, minlength=minlength))


patch_methods([
    ("argmax", argmax), ("argmin", argmin), ("argsort", argsort), ("sort", sort),
    ("topk", topk), ("kthvalue", kthvalue), ("mode", mode), ("where", where),
    ("nonzero", nonzero), ("bucketize", bucketize), ("histogram", histogram),
    ("bincount", bincount), ("index_fill", index_fill),
])
