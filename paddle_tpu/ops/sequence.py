"""Sequence (LoD) op family.

Parity target: ``paddle/fluid/operators/sequence_ops/*`` + the
``paddle.static.nn.sequence_*`` surface in the reference.

TPU redesign (not a translation): the reference represents variable-length
batches as LoD ragged tensors (a flat ``[sum(L_i), D]`` buffer plus host-side
offset tables) and each sequence op walks the offsets with per-sequence CPU
loops or custom CUDA kernels. Ragged layouts defeat XLA's static-shape
compilation model, so here the canonical representation is **dense padded**
``[B, T, ...]`` data plus a ``seq_lens [B]`` vector, and every op is a pure,
mask-driven jnp program (jit-traceable, tape-differentiable, MXU/VPU
friendly). Ops whose upstream output is ragged return the dense buffer at
static capacity plus the new lengths — the same information, XLA-compilable.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ._helpers import Tensor, axes_arg, ensure_tensor, forward_op

__all__ = [
    "sequence_pad", "sequence_unpad", "sequence_expand", "sequence_expand_as",
    "sequence_reverse", "sequence_softmax", "sequence_pool",
    "sequence_first_step", "sequence_last_step", "sequence_conv",
    "sequence_slice", "sequence_concat", "sequence_enumerate",
    "sequence_erase", "sequence_reshape", "sequence_scatter", "lod_reset",
    "im2sequence", "row_conv",
]


def _lens(seq_lens):
    return ensure_tensor(seq_lens)


def _valid(lens_v, T):
    """[B] lengths -> [B, T] bool validity mask."""
    return jnp.arange(T)[None, :] < lens_v[:, None]


# ---------------------------------------------------------------------------
# pad / unpad / reshape — representation shuttles
# ---------------------------------------------------------------------------

def sequence_pad(x, pad_value, maxlen, seq_lens, name=None):
    """Pack a flat ``[N, ...]`` buffer of concatenated sequences into a dense
    padded ``[B, maxlen, ...]`` batch (ref: sequence_pad_op). ``maxlen`` is
    static (the TPU capacity contract); rows beyond each length hold
    ``pad_value``. Returns ``(padded, seq_lens)`` like the reference's
    (Out, Length) pair."""
    xt = ensure_tensor(x)
    lt = _lens(seq_lens)

    def impl(xv, lv):
        B = lv.shape[0]
        starts = jnp.cumsum(lv) - lv                       # [B] row offsets
        j = jnp.arange(maxlen)
        gather = starts[:, None] + j[None, :]              # [B, T]
        valid = j[None, :] < lv[:, None]
        safe = jnp.clip(gather, 0, xv.shape[0] - 1)
        out = xv[safe]                                     # [B, T, ...]
        mask = valid.reshape(valid.shape + (1,) * (out.ndim - 2))
        return jnp.where(mask, out, jnp.asarray(pad_value, xv.dtype)), lv

    return forward_op("sequence_pad", impl, [xt, lt])


def sequence_unpad(x, length, name=None):
    """Dense padded ``[B, T, ...]`` -> flat ``[sum(L_i), ...]`` (ref:
    sequence_unpad_op). The output length is data-dependent, so this is an
    EAGER-ONLY op (documented contract, same as ``nms``): under a trace use
    the mask form directly."""
    xt = ensure_tensor(x)
    lv = np.asarray(_lens(length)._value)
    xv = xt._value
    rows = [np.asarray(xv[b, : int(lv[b])]) for b in range(xv.shape[0])]
    flat = np.concatenate(rows, 0) if rows else np.zeros((0,) + xv.shape[2:])
    from ..core.tensor import to_tensor
    return to_tensor(flat.astype(np.asarray(xv).dtype))


def sequence_reshape(x, new_dim: int, seq_lens, name=None):
    """Refold the trailing dim: each length-L row of width D becomes length
    ``L*D//new_dim`` of width ``new_dim`` (ref: sequence_reshape_op).
    Returns ``(out, new_lens)``."""
    xt = ensure_tensor(x)
    lt = _lens(seq_lens)

    def impl(xv, lv):
        B, T, D = xv.shape
        out = xv.reshape(B, T * D // new_dim, new_dim)
        return out, lv * D // new_dim

    return forward_op("sequence_reshape", impl, [xt, lt])


def lod_reset(x, seq_lens, name=None):
    """Reassign the length metadata of a dense batch (ref: lod_reset_op —
    which rewrites the LoD table without touching data). Dense form: the
    data IS unchanged; returns ``(x, seq_lens)``."""
    xt = ensure_tensor(x)
    lt = _lens(seq_lens)
    return forward_op("lod_reset", lambda xv, lv: (xv, lv), [xt, lt])


# ---------------------------------------------------------------------------
# expand / reverse / erase / slice / concat / scatter — index machinery
# ---------------------------------------------------------------------------

def sequence_expand(x, y_lens, ref_level: int = 0, name=None):
    """Repeat each row ``i`` of ``x [B, ...]`` ``y_lens[i]`` times into a
    dense ``[B, max(y_lens), ...]`` batch (ref: sequence_expand_op, dense
    reformulation: the ragged repeat becomes a broadcast + validity mask).
    Returns ``(out, y_lens)``."""
    xt = ensure_tensor(x)
    lt = _lens(y_lens)
    # static capacity = max repeat count; read eagerly (capacity is a shape,
    # so it must be static on TPU — the caller's lens tensor is concrete)
    cap = int(np.max(np.asarray(lt._value))) if lt._value.size else 0

    def impl2(xv, lv):
        out = jnp.broadcast_to(xv[:, None], (xv.shape[0], cap) + xv.shape[1:])
        mask = _valid(lv, cap).reshape(
            (xv.shape[0], cap) + (1,) * (xv.ndim - 1))
        return out * mask.astype(xv.dtype) if jnp.issubdtype(
            xv.dtype, jnp.inexact) else jnp.where(mask, out, 0), lv

    return forward_op("sequence_expand", impl2, [xt, lt])


def sequence_expand_as(x, y, name=None):
    """Expand each row of ``x [B, ...]`` across ``y``'s time axis
    (ref: sequence_expand_as_op): out[b, t] = x[b]."""
    xt = ensure_tensor(x)
    yt = ensure_tensor(y)

    def impl(xv, yv):
        T = yv.shape[1]
        return jnp.broadcast_to(xv[:, None], (xv.shape[0], T) + xv.shape[1:])

    return forward_op("sequence_expand_as", impl, [xt, yt])


def sequence_reverse(x, seq_lens=None, name=None):
    """Reverse the valid prefix of each row, padding stays in place (ref:
    sequence_reverse_op). Pure index remap — one gather, no host loop."""
    xt = ensure_tensor(x)
    if seq_lens is None:
        def impl0(xv):
            return jnp.flip(xv, axis=1)
        return forward_op("sequence_reverse", impl0, [xt])
    lt = _lens(seq_lens)

    def impl(xv, lv):
        T = xv.shape[1]
        j = jnp.arange(T)[None, :]
        src = jnp.where(j < lv[:, None], lv[:, None] - 1 - j, j)
        return jnp.take_along_axis(
            xv, src.reshape(src.shape + (1,) * (xv.ndim - 2)).astype(jnp.int32),
            axis=1)

    return forward_op("sequence_reverse", impl, [xt, lt])


def sequence_erase(x, tokens, seq_lens, name=None):
    """Remove every occurrence of ``tokens`` from each sequence, left-align
    the survivors, pad the tail with 0 (ref: sequence_erase_op). TPU
    formulation: a stable mask compaction — argsort of (kept ? position :
    capacity) is a single XLA sort, no data-dependent shapes. Returns
    ``(out, new_lens)``."""
    xt = ensure_tensor(x)
    lt = _lens(seq_lens)
    toks = jnp.asarray(list(tokens))

    def impl(xv, lv):
        B, T = xv.shape
        j = jnp.arange(T)[None, :]
        valid = j < lv[:, None]
        keep = valid & ~jnp.isin(xv, toks)
        order = jnp.argsort(jnp.where(keep, j, T), axis=1, stable=True)
        gathered = jnp.take_along_axis(xv, order, axis=1)
        new_lens = keep.sum(1)
        out = jnp.where(j < new_lens[:, None], gathered, 0)
        return out, new_lens

    return forward_op("sequence_erase", impl, [xt, lt],
                      differentiable=False)


def sequence_slice(x, offset, length, seq_lens=None, name=None):
    """Per-row slice ``x[b, offset[b] : offset[b]+length[b]]`` left-aligned
    into the same static capacity (ref: sequence_slice_op). Returns
    ``(out, length)``."""
    xt = ensure_tensor(x)
    ot = ensure_tensor(offset)
    nt = ensure_tensor(length)

    def impl(xv, ov, nv):
        T = xv.shape[1]
        j = jnp.arange(T)[None, :]
        src = jnp.clip(ov[:, None] + j, 0, T - 1)
        out = jnp.take_along_axis(
            xv, src.reshape(src.shape + (1,) * (xv.ndim - 2)).astype(jnp.int32),
            axis=1)
        mask = (j < nv[:, None]).reshape(
            (xv.shape[0], T) + (1,) * (xv.ndim - 2))
        return jnp.where(mask, out, 0 if not jnp.issubdtype(
            xv.dtype, jnp.inexact) else jnp.asarray(0, xv.dtype)), nv

    return forward_op("sequence_slice", impl, [xt, ot, nt])


def sequence_concat(xs, lens_list, name=None):
    """Concatenate k dense batches along time per batch element, packing the
    valid prefixes back to back (ref: sequence_concat_op). Static capacity =
    sum of input capacities; one scatter per input. Returns
    ``(out, new_lens)``."""
    ts = [ensure_tensor(x) for x in xs]
    ls = [_lens(l) for l in lens_list]
    caps = [int(t.shape[1]) for t in ts]
    total = sum(caps)

    def impl(*vals):
        k = len(ts)
        xvs, lvs = vals[:k], vals[k:]
        B = xvs[0].shape[0]
        trail = xvs[0].shape[2:]
        out = jnp.zeros((B, total) + trail, xvs[0].dtype)
        start = jnp.zeros((B,), jnp.int32)
        for xv, lv, cap in zip(xvs, lvs, caps):
            j = jnp.arange(cap)[None, :]
            dest = start[:, None] + j                      # [B, cap]
            valid = j < lv[:, None]
            dest = jnp.where(valid, dest, total)           # OOB rows dropped
            b = jnp.broadcast_to(jnp.arange(B)[:, None], dest.shape)
            out = out.at[b.reshape(-1), dest.reshape(-1)].set(
                xv.reshape((B * cap,) + trail), mode="drop")
            start = start + lv.astype(jnp.int32)
        return out, sum(lv for lv in lvs)

    return forward_op("sequence_concat", impl, ts + ls)


def sequence_scatter(x, index, updates, name=None):
    """Per-row scatter-add: ``out[b, index[b, k]] += updates[b, k]`` (ref:
    sequence_scatter_op reformulated dense: the sequence offsets become the
    batch dim)."""
    xt = ensure_tensor(x)
    it = ensure_tensor(index)
    ut = ensure_tensor(updates)

    def impl(xv, iv, uv):
        B = xv.shape[0]
        b = jnp.broadcast_to(jnp.arange(B)[:, None], iv.shape)
        return xv.at[b.reshape(-1), iv.reshape(-1)].add(uv.reshape(-1))

    return forward_op("sequence_scatter", impl, [xt, it, ut])


def sequence_enumerate(x, win_size: int, pad_value: int = 0, name=None):
    """Sliding id windows: out[b, t] = x[b, t : t+win] with tail padding
    (ref: sequence_enumerate_op)."""
    xt = ensure_tensor(x)

    def impl(xv):
        B, T = xv.shape
        j = jnp.arange(T)[:, None] + jnp.arange(win_size)[None, :]  # [T, W]
        safe = jnp.clip(j, 0, T - 1)
        out = xv[:, safe]                                  # [B, T, W]
        return jnp.where(j[None] < T, out, pad_value)

    return forward_op("sequence_enumerate", impl, [xt],
                      differentiable=False)


# ---------------------------------------------------------------------------
# softmax / pool / conv — masked compute
# ---------------------------------------------------------------------------

def sequence_softmax(x, seq_lens, name=None):
    """Masked softmax over the valid prefix of each row; padding gets 0
    (ref: sequence_softmax_op)."""
    xt = ensure_tensor(x)
    lt = _lens(seq_lens)

    def impl(xv, lv):
        valid = _valid(lv, xv.shape[1])
        s = jnp.where(valid, xv, -jnp.inf)
        p = jax.nn.softmax(s, axis=1)
        return jnp.where(valid, p, 0.0)

    return forward_op("sequence_softmax", impl, [xt, lt])


def sequence_pool(x, pool_type: str, seq_lens, pad_value: float = 0.0,
                  name=None):
    """Pool the valid prefix per row: average/sum/sqrt/max/min/last/first
    (ref: sequence_pool_op). Empty sequences yield ``pad_value``."""
    xt = ensure_tensor(x)
    lt = _lens(seq_lens)
    pt = pool_type.lower()
    if pt not in ("average", "mean", "sum", "sqrt", "max", "min", "last",
                  "first"):
        raise ValueError(f"unknown pool_type {pool_type!r}")

    def impl(xv, lv):
        B, T = xv.shape[:2]
        valid = _valid(lv, T).reshape((B, T) + (1,) * (xv.ndim - 2))
        lf = jnp.maximum(lv.astype(xv.dtype), 1).reshape(
            (B,) + (1,) * (xv.ndim - 2))
        if pt in ("average", "mean"):
            out = jnp.where(valid, xv, 0).sum(1) / lf
        elif pt == "sum":
            out = jnp.where(valid, xv, 0).sum(1)
        elif pt == "sqrt":
            out = jnp.where(valid, xv, 0).sum(1) / jnp.sqrt(lf)
        elif pt == "max":
            out = jnp.where(valid, xv, -jnp.inf).max(1)
        elif pt == "min":
            out = jnp.where(valid, xv, jnp.inf).min(1)
        elif pt == "first":
            out = xv[:, 0]
        else:  # last
            idx = jnp.clip(lv - 1, 0).astype(jnp.int32)
            out = jnp.take_along_axis(
                xv, idx.reshape((B, 1) + (1,) * (xv.ndim - 2)), axis=1
            )[:, 0]
        empty = (lv == 0).reshape((B,) + (1,) * (out.ndim - 1))
        return jnp.where(empty, jnp.asarray(pad_value, xv.dtype), out)

    return forward_op("sequence_pool", impl, [xt, lt])


def sequence_first_step(x, seq_lens, name=None):
    """First valid timestep per row (ref: sequence_ops first_step)."""
    return sequence_pool(x, "first", seq_lens)


def sequence_last_step(x, seq_lens, name=None):
    """Last valid timestep per row (ref: sequence_ops last_step)."""
    return sequence_pool(x, "last", seq_lens)


def sequence_conv(x, weight, context_length: int, context_start=None,
                  seq_lens=None, bias=None, name=None):
    """Context-window projection: each timestep sees the concatenation of
    ``context_length`` neighbors starting at ``context_start`` and is
    projected by ``weight [context_length*D, M]`` (ref: sequence_conv_op).
    TPU formulation: gather the window tape then ONE [B*T, C*D]x[C*D, M]
    matmul — MXU shaped, no per-sequence loops. Out-of-sequence context rows
    are zero (the reference's zero-padding semantics)."""
    xt = ensure_tensor(x)
    wt = ensure_tensor(weight)
    if context_start is None:
        context_start = -((context_length - 1) // 2)
    args = [xt, wt]
    lt = None
    if seq_lens is not None:
        lt = _lens(seq_lens)
        args.append(lt)
    if bias is not None:
        args.append(ensure_tensor(bias))

    def impl(xv, wv, *rest):
        lv = rest[0] if seq_lens is not None else None
        bv = rest[-1] if bias is not None else None
        B, T, D = xv.shape
        offs = jnp.arange(context_length) + context_start
        j = jnp.arange(T)[:, None] + offs[None, :]          # [T, C]
        inside = (j >= 0) & (j < T)
        safe = jnp.clip(j, 0, T - 1)
        win = xv[:, safe]                                   # [B, T, C, D]
        mask = inside[None, :, :, None]
        if lv is not None:
            mask = mask & (j[None] < lv[:, None, None])[..., None]
        win = jnp.where(mask, win, 0)
        out = win.reshape(B, T, context_length * D) @ wv    # [B, T, M]
        if bv is not None:
            out = out + bv
        if lv is not None:
            out = jnp.where(_valid(lv, T)[..., None], out, 0)
        return out

    return forward_op("sequence_conv", impl, args)


def row_conv(x, weight, seq_lens=None, name=None):
    """Lookahead (row) convolution: out[b,t] = sum_k x[b,t+k] * w[k]
    elementwise over channels, k in [0, future_context] (ref: row_conv_op,
    the DeepSpeech2 streaming op). Same gather-tape formulation as
    sequence_conv but depthwise."""
    xt = ensure_tensor(x)
    wt = ensure_tensor(weight)
    args = [xt, wt]
    if seq_lens is not None:
        args.append(_lens(seq_lens))

    def impl(xv, wv, *rest):
        lv = rest[0] if rest else None
        B, T, D = xv.shape
        K = wv.shape[0]
        j = jnp.arange(T)[:, None] + jnp.arange(K)[None, :]  # [T, K]
        inside = j < T
        safe = jnp.clip(j, 0, T - 1)
        win = xv[:, safe]                                    # [B, T, K, D]
        mask = inside[None, :, :, None]
        if lv is not None:
            mask = mask & (j[None] < lv[:, None, None])[..., None]
        win = jnp.where(mask, win, 0)
        out = jnp.einsum("btkd,kd->btd", win, wv)
        if lv is not None:
            out = jnp.where(_valid(lv, T)[..., None], out, 0)
        return out

    return forward_op("row_conv", impl, args)


def im2sequence(x, filter_size, stride=1, padding=0, name=None):
    """Image -> patch sequence: ``[B, C, H, W]`` to ``[B, OH*OW, C*kh*kw]``
    (ref: im2sequence_op). One ``conv_general_dilated_patches`` call — the
    XLA-native patch extraction (no host loops)."""
    xt = ensure_tensor(x)
    kh, kw = ((filter_size, filter_size) if isinstance(filter_size, int)
              else tuple(filter_size))
    sh, sw = (stride, stride) if isinstance(stride, int) else tuple(stride)
    ph, pw = (padding, padding) if isinstance(padding, int) else tuple(padding)

    def impl(xv):
        patches = lax.conv_general_dilated_patches(
            xv, (kh, kw), (sh, sw), [(ph, ph), (pw, pw)])  # [B, C*kh*kw, OH, OW]
        B, F = patches.shape[:2]
        return patches.reshape(B, F, -1).transpose(0, 2, 1)

    return forward_op("im2sequence", impl, [xt])
