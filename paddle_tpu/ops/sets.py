"""Set operations on 1-D tensors.

Parity targets: ``python/paddle/tensor/math.py`` set ops in the reference
(``intersect``/upstream proposals) and the numpy set-routine surface the
ecosystem expects (``intersect1d``/``setdiff1d``/``union1d``/``setxor1d``/
``in1d``). TPU note: true set ops are dynamically shaped; following the
registry-wide static-shape policy these return (values, validity_count)
style results where noted, or run as host-assisted creation ops (no
gradient surface, like ``unique``'s documented contract).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ._helpers import ensure_tensor, forward_op, register_op

__all__ = ["intersect1d", "setdiff1d", "union1d", "setxor1d", "in1d",
           "isin_1d"]


def _flat_val(x):
    return ensure_tensor(x)._value.reshape(-1)


def _host_set_op(name, np_fn, x, y, assume_unique=False):
    # set results are data-dependent in SHAPE — computed on host like the
    # reference's CPU fallback for dynamic-shape ops; inputs are synced
    # (documented: not jit-traceable, use the mask-style ops inside jit)
    a = np.asarray(_flat_val(x))
    b = np.asarray(_flat_val(y))
    out = np_fn(a, b, assume_unique=assume_unique) if assume_unique is not None \
        else np_fn(a, b)
    return forward_op(name, lambda: jnp.asarray(out), [],
                      differentiable=False)


def intersect1d(x, y, assume_unique: bool = False, name=None):
    """Sorted unique values present in both tensors."""
    return _host_set_op("intersect1d", np.intersect1d, x, y, assume_unique)


def setdiff1d(x, y, assume_unique: bool = False, name=None):
    """Sorted unique values in ``x`` that are not in ``y``."""
    return _host_set_op("setdiff1d", np.setdiff1d, x, y, assume_unique)


def union1d(x, y, name=None):
    """Sorted union of unique values."""
    a = np.asarray(_flat_val(x))
    b = np.asarray(_flat_val(y))
    out = np.union1d(a, b)
    return forward_op("union1d", lambda: jnp.asarray(out), [],
                      differentiable=False)


def setxor1d(x, y, assume_unique: bool = False, name=None):
    """Sorted values in exactly one of the tensors."""
    return _host_set_op("setxor1d", np.setxor1d, x, y, assume_unique)


def in1d(x, test, assume_unique: bool = False, invert: bool = False,
         name=None):
    """Boolean mask over ``x.ravel()``: element present in ``test``.
    Static-shaped (mask, not values) — safe inside jit."""
    xv = _flat_val(x)
    tv = _flat_val(test)

    def impl(xv, tv):
        m = (xv[:, None] == tv[None, :]).any(axis=1)
        return ~m if invert else m
    return forward_op("in1d", impl, [ensure_tensor(xv), ensure_tensor(tv)],
                      differentiable=False)


isin_1d = in1d

for _n, _f in (("intersect1d", intersect1d), ("setdiff1d", setdiff1d),
               ("union1d", union1d), ("setxor1d", setxor1d), ("in1d", in1d)):
    register_op(_n, _f, _f.__doc__ or "", differentiable=False,
                category="set", public=_f)
