"""Special functions and remaining math/manipulation surface (round-4
breadth: r3 VERDICT #6 — scatter/window/set/special completion).

Parity targets: ``python/paddle/tensor/math.py`` + ``paddle.incubate``
special functions in the reference; numpy/scipy names are the oracles
(tests/test_op_sweep.py reaches these through the OpDef.sweep specs in
``ops/sweep_specs.py``).
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.scipy import special as jsp

from ._helpers import (axes_arg, binary_factory, ensure_tensor, forward_op,
                       register_op, unary_factory)

__all__ = [
    "xlogy", "xlog1py", "exp2", "expit", "erfc", "erfcx", "igamma",
    "igammac", "logdet", "vdot", "addmv", "addr", "chain_matmul",
    "float_power", "std_mean", "var_mean", "gradient",
    "histogram_bin_edges", "fliplr", "flipud", "rollaxis", "swapdims",
    "narrow", "narrow_copy", "split_with_sizes", "concatenate", "arctan2",
    "entr", "rel_entr", "kl_div", "zeta", "betaln", "betainc", "sinc_pi",
    "log_ndtr", "ndtr", "ndtri", "spherical_bessel_j0", "cbrt",
    "nanargmax", "nanargmin", "nanstd", "nanvar",
]


# -- elementwise special fns (factories: auto-swept unary/binary) -----------

xlogy = binary_factory("xlogy", jsp.xlogy, "x*log(y), 0 at x==0.")
xlog1py = binary_factory("xlog1py", jsp.xlog1py, "x*log1p(y), 0 at x==0.")
exp2 = unary_factory("exp2", jnp.exp2, "2**x elementwise.")
expit = unary_factory("expit", jsp.expit, "Logistic sigmoid (scipy name).")
erfc = unary_factory("erfc", jsp.erfc, "1 - erf(x).")
erfcx = unary_factory("erfcx", lambda x: jnp.exp(x * x) * jsp.erfc(x),
                      "Scaled complementary error function exp(x^2)*erfc(x).")
igamma = binary_factory("igamma", jsp.gammainc,
                        "Regularized lower incomplete gamma P(a, x).")
igammac = binary_factory("igammac", jsp.gammaincc,
                         "Regularized upper incomplete gamma Q(a, x).")
entr = unary_factory("entr", jsp.entr, "-x*log(x) for x>0; 0 at 0.")
rel_entr = binary_factory("rel_entr", jsp.rel_entr,
                          "x*log(x/y) (KL integrand).")
kl_div = binary_factory("kl_div", jsp.kl_div, "x*log(x/y) - x + y.")
zeta = binary_factory("zeta", jsp.zeta, "Hurwitz zeta(x, q).")
betaln = binary_factory("betaln", jsp.betaln, "log|B(a, b)|.")
ndtr = unary_factory("ndtr", jsp.ndtr, "Standard normal CDF.")
log_ndtr = unary_factory("log_ndtr", jsp.log_ndtr, "log of the normal CDF.")
ndtri = unary_factory("ndtri", jsp.ndtri, "Inverse of the normal CDF.")
cbrt = unary_factory("cbrt", jnp.cbrt, "Cube root, sign-preserving.")
sinc_pi = unary_factory("sinc_pi", jnp.sinc, "Normalized sinc sin(pi x)/(pi x).")
spherical_bessel_j0 = unary_factory(
    "spherical_bessel_j0",
    lambda x: jnp.where(jnp.abs(x) < 1e-6, 1.0 - x * x / 6.0,
                        jnp.sin(x) / jnp.where(x == 0, 1.0, x)),
    "Spherical Bessel function j0(x) = sin(x)/x.")


def betainc(a, b, x, name=None):
    """Regularized incomplete beta I_x(a, b)."""
    return forward_op("betainc", jsp.betainc,
                      [ensure_tensor(a), ensure_tensor(b), ensure_tensor(x)])


register_op("betainc", jsp.betainc, betainc.__doc__, public=betainc)


# -- linalg-ish --------------------------------------------------------------

def logdet(x, name=None):
    """log|det(x)| for positive-determinant batches (torch.logdet parity)."""
    def impl(v):
        sign, ld = jnp.linalg.slogdet(v)
        return jnp.where(sign > 0, ld, jnp.nan)
    return forward_op("logdet", impl, [ensure_tensor(x)])


def vdot(x, y, name=None):
    """Flattened dot product (conjugating for complex inputs)."""
    return forward_op("vdot", jnp.vdot,
                      [ensure_tensor(x), ensure_tensor(y)])


def addmv(input, mat, vec, beta: float = 1.0, alpha: float = 1.0, name=None):
    """beta*input + alpha*(mat @ vec)."""
    return forward_op(
        "addmv", lambda i, m, v: beta * i + alpha * (m @ v),
        [ensure_tensor(input), ensure_tensor(mat), ensure_tensor(vec)])


def addr(input, vec1, vec2, beta: float = 1.0, alpha: float = 1.0, name=None):
    """beta*input + alpha*outer(vec1, vec2)."""
    return forward_op(
        "addr", lambda i, a, b: beta * i + alpha * jnp.outer(a, b),
        [ensure_tensor(input), ensure_tensor(vec1), ensure_tensor(vec2)])


def chain_matmul(*mats, name=None):
    """Product of a chain of matrices (optimal association via jnp.linalg
    multi_dot)."""
    ts = [ensure_tensor(m) for m in (mats[0] if len(mats) == 1 and
                                     isinstance(mats[0], (list, tuple))
                                     else mats)]
    return forward_op("chain_matmul",
                      lambda *vs: jnp.linalg.multi_dot(vs), ts)


def float_power(x, y, name=None):
    """x**y computed in float64-free fashion: promote to the widest float
    available (fp32 here; x64 is disabled on TPU stacks)."""
    def impl(a, b):
        return jnp.power(a.astype(jnp.float32), b.astype(jnp.float32))
    return forward_op("float_power", impl,
                      [ensure_tensor(x), ensure_tensor(y)])


# -- statistics --------------------------------------------------------------

def std_mean(x, axis=None, unbiased: bool = True, keepdim: bool = False,
             name=None):
    """(std, mean) in one pass (torch.std_mean parity)."""
    ax = axes_arg(axis)

    def impl(v):
        dd = 1 if unbiased else 0
        return (jnp.std(v, axis=ax, ddof=dd, keepdims=keepdim),
                jnp.mean(v, axis=ax, keepdims=keepdim))
    return forward_op("std_mean", impl, [ensure_tensor(x)])


def var_mean(x, axis=None, unbiased: bool = True, keepdim: bool = False,
             name=None):
    """(var, mean) in one pass (torch.var_mean parity)."""
    ax = axes_arg(axis)

    def impl(v):
        dd = 1 if unbiased else 0
        return (jnp.var(v, axis=ax, ddof=dd, keepdims=keepdim),
                jnp.mean(v, axis=ax, keepdims=keepdim))
    return forward_op("var_mean", impl, [ensure_tensor(x)])


def nanargmax(x, axis=None, keepdim: bool = False, name=None):
    return forward_op("nanargmax",
                      lambda v: jnp.nanargmax(v, axis=axes_arg(axis),
                                              keepdims=keepdim),
                      [ensure_tensor(x)], differentiable=False)


def nanargmin(x, axis=None, keepdim: bool = False, name=None):
    return forward_op("nanargmin",
                      lambda v: jnp.nanargmin(v, axis=axes_arg(axis),
                                              keepdims=keepdim),
                      [ensure_tensor(x)], differentiable=False)


def nanstd(x, axis=None, unbiased: bool = True, keepdim: bool = False,
           name=None):
    return forward_op(
        "nanstd",
        lambda v: jnp.nanstd(v, axis=axes_arg(axis),
                             ddof=1 if unbiased else 0, keepdims=keepdim),
        [ensure_tensor(x)])


def nanvar(x, axis=None, unbiased: bool = True, keepdim: bool = False,
           name=None):
    return forward_op(
        "nanvar",
        lambda v: jnp.nanvar(v, axis=axes_arg(axis),
                             ddof=1 if unbiased else 0, keepdims=keepdim),
        [ensure_tensor(x)])


def gradient(x, spacing: float = 1.0, axis=None, name=None):
    """Central-difference gradient (numpy.gradient parity; unit spacing or a
    scalar step)."""
    ax = axes_arg(axis)

    def impl(v):
        axes = range(v.ndim) if ax is None else \
            ([ax] if isinstance(ax, int) else ax)
        outs = [jnp.gradient(v, spacing, axis=a) for a in axes]
        return tuple(outs) if len(outs) > 1 else outs[0]
    return forward_op("gradient", impl, [ensure_tensor(x)])


def histogram_bin_edges(x, bins: int = 100, min=0, max=0, name=None):
    """Bin edges the way paddle.histogram computes them (min==max==0 ->
    data range)."""
    def impl(v):
        lo, hi = (jnp.min(v), jnp.max(v)) if (min == 0 and max == 0) \
            else (jnp.asarray(min, v.dtype), jnp.asarray(max, v.dtype))
        hi = jnp.where(hi > lo, hi, lo + 1)
        return jnp.linspace(lo, hi, bins + 1)
    return forward_op("histogram_bin_edges", impl, [ensure_tensor(x)],
                      differentiable=False)


# -- manipulation aliases/completions ---------------------------------------

def fliplr(x, name=None):
    return forward_op("fliplr", jnp.fliplr, [ensure_tensor(x)])


def flipud(x, name=None):
    return forward_op("flipud", jnp.flipud, [ensure_tensor(x)])


def rollaxis(x, axis: int, start: int = 0, name=None):
    return forward_op("rollaxis",
                      lambda v: jnp.rollaxis(v, axis, start),
                      [ensure_tensor(x)])


def swapdims(x, dim0: int, dim1: int, name=None):
    return forward_op("swapdims",
                      lambda v: jnp.swapaxes(v, dim0, dim1),
                      [ensure_tensor(x)])


def narrow(x, axis: int, start: int, length: int, name=None):
    """Contiguous slice of ``length`` along ``axis`` (torch.narrow parity)."""
    return forward_op(
        "narrow",
        lambda v: lax.slice_in_dim(v, start, start + length, axis=axis),
        [ensure_tensor(x)])


narrow_copy = narrow


def split_with_sizes(x, sizes, axis: int = 0, name=None):
    """Split into chunks of the given sizes along ``axis``."""
    offs = np.cumsum([0] + list(sizes))
    if offs[-1] != ensure_tensor(x).shape[axis]:
        raise ValueError(f"sizes {list(sizes)} do not sum to dim "
                         f"{ensure_tensor(x).shape[axis]}")

    def impl(v):
        return tuple(lax.slice_in_dim(v, int(a), int(b), axis=axis)
                     for a, b in zip(offs[:-1], offs[1:]))
    return forward_op("split_with_sizes", impl, [ensure_tensor(x)])


def concatenate(x, axis: int = 0, name=None):
    """numpy-name alias of concat."""
    ts = [ensure_tensor(t) for t in x]
    return forward_op("concatenate",
                      lambda *vs: jnp.concatenate(vs, axis=axis), ts)


def arctan2(x, y, name=None):
    return forward_op("arctan2", jnp.arctan2,
                      [ensure_tensor(x), ensure_tensor(y)])


for _n, _f in (
        ("logdet", logdet), ("vdot", vdot), ("addmv", addmv), ("addr", addr),
        ("chain_matmul", chain_matmul), ("float_power", float_power),
        ("std_mean", std_mean), ("var_mean", var_mean),
        ("gradient", gradient), ("histogram_bin_edges", histogram_bin_edges),
        ("fliplr", fliplr), ("flipud", flipud), ("rollaxis", rollaxis),
        ("swapdims", swapdims), ("narrow", narrow),
        ("narrow_copy", narrow_copy), ("split_with_sizes", split_with_sizes),
        ("concatenate", concatenate), ("arctan2", arctan2),
        ("nanargmax", nanargmax), ("nanargmin", nanargmin),
        ("nanstd", nanstd), ("nanvar", nanvar)):
    register_op(_n, _f, (_f.__doc__ or "").strip().split("\n")[0],
                public=_f)


# -- r4 breadth, second batch: index/scatter/shift completions ---------------

def index_copy(x, index, source, axis: int = 0, name=None):
    """Copy rows of ``source`` into ``x`` at ``index`` along ``axis``
    (torch.index_copy parity)."""
    def impl(v, idx, src):
        mv = jnp.moveaxis(v, axis, 0)
        ms = jnp.moveaxis(src, axis, 0)
        return jnp.moveaxis(mv.at[idx].set(ms), 0, axis)
    return forward_op("index_copy", impl,
                      [ensure_tensor(x), ensure_tensor(index),
                       ensure_tensor(source)])


def scatter_add(x, index, updates, axis: int = 0, name=None):
    """Accumulating scatter along ``axis`` (torch.scatter_add semantics:
    per-element indices of the same rank as updates)."""
    def impl(v, idx, upd):
        oidx = jnp.indices(upd.shape)
        gather = tuple(idx if d == axis else oidx[d]
                       for d in range(v.ndim))
        return v.at[gather].add(upd)
    return forward_op("scatter_add", impl,
                      [ensure_tensor(x), ensure_tensor(index),
                       ensure_tensor(updates)])


def scatter_reduce(x, index, updates, reduce: str = "sum", axis: int = 0,
                   include_self: bool = True, name=None):
    """Reduce-scatter along ``axis`` with sum/prod/amax/amin/mean modes
    (torch.scatter_reduce parity; paddle: put_along_axis(reduce=...))."""
    modes = {"sum": "add", "add": "add", "prod": "multiply",
             "multiply": "multiply", "amax": "max", "amin": "min",
             "mean": "add"}
    if reduce not in modes:
        raise ValueError(f"unknown reduce {reduce!r}; options "
                         f"{sorted(modes)}")

    identities = {"add": 0, "multiply": 1, "max": None, "min": None}

    def impl(v, idx, upd):
        oidx = jnp.indices(upd.shape)
        gather = tuple(idx if d == axis else oidx[d]
                       for d in range(v.ndim))
        base = v
        if not include_self:
            # Destination values must not participate: overwrite every
            # scattered position with the reduce identity first (for
            # amax/amin, the dtype's -inf/+inf extremum).
            mode = modes[reduce]
            if identities[mode] is None:
                if jnp.issubdtype(v.dtype, jnp.inexact):
                    ident = jnp.array(
                        -jnp.inf if mode == "max" else jnp.inf, v.dtype)
                else:
                    info = jnp.iinfo(v.dtype)
                    ident = jnp.array(
                        info.min if mode == "max" else info.max, v.dtype)
            else:
                ident = jnp.array(identities[mode], v.dtype)
            base = v.at[gather].set(jnp.broadcast_to(ident, upd.shape))
        at = base.at[gather]
        out = getattr(at, modes[reduce])(upd)
        if reduce == "mean":
            cnt = jnp.zeros_like(v).at[gather].add(jnp.ones_like(upd))
            self_cnt = jnp.ones_like(cnt) * (1.0 if include_self else 0.0)
            out = out / jnp.maximum(cnt + self_cnt, 1)
        return out
    return forward_op("scatter_reduce", impl,
                      [ensure_tensor(x), ensure_tensor(index),
                       ensure_tensor(updates)])


def diag_indices(n: int, ndim: int = 2, name=None):
    """Indices of the main diagonal of an ``ndim``-d array of side n."""
    def impl():
        r = jnp.arange(n)
        return tuple(r for _ in range(ndim))
    return forward_op("diag_indices", impl, [], differentiable=False)


def unravel_index(indices, shape, name=None):
    """Flat index -> coordinate tuple (numpy.unravel_index parity)."""
    return forward_op("unravel_index",
                      lambda i: jnp.unravel_index(i, tuple(shape)),
                      [ensure_tensor(indices)], differentiable=False)


def ravel_multi_index(multi_index, shape, mode="raise", name=None):
    """Coordinate arrays -> flat indices."""
    ts = [ensure_tensor(m) for m in multi_index]
    return forward_op(
        "ravel_multi_index",
        lambda *ms: jnp.ravel_multi_index(ms, tuple(shape), mode="clip"),
        ts, differentiable=False)


def true_divide(x, y, name=None):
    return forward_op("true_divide", jnp.true_divide,
                      [ensure_tensor(x), ensure_tensor(y)])


def trunc_divide(x, y, name=None):
    """Division rounded toward zero (paddle.trunc_divide)."""
    return forward_op("trunc_divide",
                      lambda a, b: jnp.trunc(a / b),
                      [ensure_tensor(x), ensure_tensor(y)])


def divide_no_nan(x, y, name=None):
    """x/y with 0 where y == 0 (tf-style safe divide; reference uses it in
    metric kernels)."""
    def impl(a, b):
        safe = jnp.where(b == 0, 1, b)
        return jnp.where(b == 0, 0, a / safe)
    return forward_op("divide_no_nan", impl,
                      [ensure_tensor(x), ensure_tensor(y)])


def bitwise_invert(x, name=None):
    return forward_op("bitwise_invert", jnp.invert, [ensure_tensor(x)],
                      differentiable=False)


def cumulative_sum(x, axis=None, name=None):
    return forward_op("cumulative_sum",
                      lambda v: jnp.cumsum(v, axis=axes_arg(axis)),
                      [ensure_tensor(x)])


def cumulative_prod(x, axis=None, name=None):
    return forward_op("cumulative_prod",
                      lambda v: jnp.cumprod(v, axis=axes_arg(axis)),
                      [ensure_tensor(x)])


def clip_by_norm(x, max_norm: float, name=None):
    """Scale ``x`` so its L2 norm is at most ``max_norm`` (ref:
    paddle.nn.clip_by_norm / ClipGradByNorm kernel)."""
    def impl(v):
        n = jnp.sqrt(jnp.sum(v.astype(jnp.float32) ** 2))
        scale = jnp.minimum(1.0, max_norm / jnp.maximum(n, 1e-12))
        return (v.astype(jnp.float32) * scale).astype(v.dtype)
    return forward_op("clip_by_norm", impl, [ensure_tensor(x)])


def clip_by_global_norm(t_list, clip_norm: float, name=None):
    """Scale a LIST of tensors by the global-norm clip factor (ref:
    ClipGradByGlobalNorm)."""
    ts = [ensure_tensor(t) for t in t_list]

    def impl(*vs):
        g2 = sum(jnp.sum(v.astype(jnp.float32) ** 2) for v in vs)
        gn = jnp.sqrt(g2)
        scale = clip_norm / jnp.maximum(gn, clip_norm)
        return tuple((v.astype(jnp.float32) * scale).astype(v.dtype)
                     for v in vs)
    return forward_op("clip_by_global_norm", impl, ts)


for _n, _f in (("index_copy", index_copy), ("scatter_add", scatter_add),
               ("scatter_reduce", scatter_reduce),
               ("diag_indices", diag_indices),
               ("unravel_index", unravel_index),
               ("ravel_multi_index", ravel_multi_index),
               ("true_divide", true_divide), ("trunc_divide", trunc_divide),
               ("divide_no_nan", divide_no_nan),
               ("bitwise_invert", bitwise_invert),
               ("cumulative_sum", cumulative_sum),
               ("cumulative_prod", cumulative_prod),
               ("clip_by_norm", clip_by_norm),
               ("clip_by_global_norm", clip_by_global_norm)):
    register_op(_n, _f, (_f.__doc__ or "").strip().split("\n")[0], public=_f)
__all__ += ["index_copy", "scatter_add", "scatter_reduce", "diag_indices",
            "unravel_index", "ravel_multi_index", "true_divide",
            "trunc_divide", "divide_no_nan", "bitwise_invert",
            "cumulative_sum", "cumulative_prod", "clip_by_norm",
            "clip_by_global_norm"]


# -- r4 breadth, third batch: aliases + inplace random fills ----------------

def take_along_dim(x, indices, dim: int = 0, name=None):
    """torch-name alias of take_along_axis."""
    return forward_op("take_along_dim",
                      lambda v, i: jnp.take_along_axis(v, i, axis=dim),
                      [ensure_tensor(x), ensure_tensor(indices)])


def permute_dims(x, axes, name=None):
    """Array-API name for transpose-with-permutation."""
    return forward_op("permute_dims",
                      lambda v: jnp.transpose(v, tuple(axes)),
                      [ensure_tensor(x)])


def relu_(x, name=None):
    """In-place ReLU (ref: paddle.nn.functional.relu_)."""
    t = ensure_tensor(x)
    out = forward_op("relu_", lambda v: jnp.maximum(v, 0), [t])
    t._rebind(out)
    return t


def _random_fill(name, sampler_doc, dist):
    def op(x, *args, name_=None, **kw):
        t = ensure_tensor(x)
        from .random import _next_key
        import jax.random as jr

        def impl(v):
            key = _next_key()
            shp = v.shape
            if dist == "cauchy":
                loc = args[0] if args else kw.get("loc", 0.0)
                scale = args[1] if len(args) > 1 else kw.get("scale", 1.0)
                u = jr.uniform(key, shp, jnp.float32, 1e-6, 1 - 1e-6)
                s = loc + scale * jnp.tan(jnp.pi * (u - 0.5))
            elif dist == "geometric":
                p = args[0] if args else kw.get("probs", 0.5)
                u = jr.uniform(key, shp, jnp.float32, 1e-9, 1.0)
                s = jnp.floor(jnp.log(u) / jnp.log1p(-p)) + 1
            else:  # log_normal
                mean = args[0] if args else kw.get("mean", 1.0)
                std = args[1] if len(args) > 1 else kw.get("std", 2.0)
                s = jnp.exp(mean + std * jr.normal(key, shp, jnp.float32))
            return s.astype(v.dtype)
        out = forward_op(name, impl, [t], differentiable=False)
        t._rebind(out)
        return t

    op.__name__ = name
    op.__qualname__ = name
    op.__doc__ = sampler_doc
    register_op(name, op, sampler_doc, differentiable=False,
                category="random", public=op)
    return op


cauchy_ = _random_fill(
    "cauchy_", "Fill in place with Cauchy(loc, scale) samples "
    "(ref: Tensor.cauchy_).", "cauchy")
geometric_ = _random_fill(
    "geometric_", "Fill in place with Geometric(p) samples "
    "(ref: Tensor.geometric_).", "geometric")
log_normal_ = _random_fill(
    "log_normal_", "Fill in place with LogNormal(mean, std) samples "
    "(ref: Tensor.log_normal_).", "log_normal")


for _n, _f in (("take_along_dim", take_along_dim),
               ("permute_dims", permute_dims), ("relu_", relu_)):
    register_op(_n, _f, (_f.__doc__ or "").strip().split("\n")[0], public=_f)
__all__ += ["take_along_dim", "permute_dims", "relu_", "cauchy_",
            "geometric_", "log_normal_"]


from ._helpers import patch_methods as _patch
_patch([("cauchy_", cauchy_), ("geometric_", geometric_),
        ("log_normal_", log_normal_), ("take_along_dim", take_along_dim),
        ("relu_", relu_), ("xlogy", xlogy), ("vdot", vdot),
        ("float_power", float_power), ("narrow", narrow),
        ("fliplr", fliplr), ("flipud", flipud), ("swapdims", swapdims),
        ("scatter_add", scatter_add), ("index_copy", index_copy),
        ("scatter_reduce", scatter_reduce), ("exp2", exp2),
        ("erfc", erfc), ("igamma", igamma), ("igammac", igammac)])


# creation/conversion aliases (torch/numpy-style entry points the ecosystem
# expects; all route to to_tensor / histogram)

def asarray(data, dtype=None, name=None):
    """numpy-style alias of to_tensor."""
    from ..core.tensor import to_tensor
    return to_tensor(data, dtype=dtype)


def as_tensor(data, dtype=None, name=None):
    """torch-style alias of to_tensor (no-copy when already a Tensor of the
    right dtype)."""
    from ..core.tensor import Tensor, to_tensor
    if isinstance(data, Tensor) and (dtype is None or
                                     str(data.dtype) == str(dtype)):
        return data
    return to_tensor(data, dtype=dtype)


def from_numpy(array, name=None):
    """torch-style alias of to_tensor for numpy arrays."""
    from ..core.tensor import to_tensor
    return to_tensor(array)


def histc(x, bins: int = 100, min=0, max=0, name=None):
    """torch-name alias of histogram (counts only)."""
    def impl(v):
        lo, hi = (jnp.min(v), jnp.max(v)) if (min == 0 and max == 0) \
            else (jnp.asarray(min, v.dtype), jnp.asarray(max, v.dtype))
        hi = jnp.where(hi > lo, hi, lo + 1)
        return jnp.histogram(v, bins=bins, range=(lo, hi))[0]
    return forward_op("histc", impl, [ensure_tensor(x)],
                      differentiable=False)


for _n, _f in (("asarray", asarray), ("as_tensor", as_tensor),
               ("from_numpy", from_numpy), ("histc", histc)):
    register_op(_n, _f, (_f.__doc__ or "").strip().split("\n")[0],
                differentiable=False, public=_f)
__all__ += ["asarray", "as_tensor", "from_numpy", "histc"]
