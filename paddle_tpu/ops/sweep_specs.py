"""Sweep specs: example calls + numpy oracles for COMPOSITE ops.

The unary/binary factory ops are swept automatically from their category
tag (tests/test_op_sweep.py); everything else needs an example-call spec —
this module attaches them to the ``OpDef`` entries post-import (r3 VERDICT
#6: "extend the schema with an oracle field so the sweep reaches composite
ops"). A spec is ``(rng) -> [(args, kwargs, oracle), ...]`` where ``args``
may contain numpy arrays (converted to Tensors by the sweep) and ``oracle``
is a numpy callable or None (run-only leg).

Two tiers:
* EXPLICIT specs below for ops whose call shape needs thought (windows vs
  scipy, fft vs numpy.fft, sets, scatter family, reductions with axes).
* AUTO specs for simple one-tensor ops (public signature ``(x, name=None)``)
  — forward run + numpy oracle when ``numpy.<name>`` exists, gradient
  finite-difference when differentiable.

Ops with neither (stateful/random/IO/shape-polymorphic) are counted and
reported as unswept in docs/OPS.md.
"""

from __future__ import annotations

import inspect

import numpy as np

__all__ = ["attach_specs", "sweep_coverage"]


def _x(rng, shape=(3, 4)):
    return rng.standard_normal(shape).astype(np.float32)


def _pos(rng, shape=(3, 4)):
    return (rng.random(shape) * 2 + 0.3).astype(np.float32)


# ---------------------------------------------------------------------------
# explicit spec tables
# ---------------------------------------------------------------------------

def _window_specs():
    """Windows vs scipy.signal oracles (sym and periodic legs)."""
    try:
        import scipy.signal as ss
    except ImportError:          # pragma: no cover
        ss = None
    table = {
        "blackman_window": ("blackman", ()),
        "hamming_window": ("hamming", ()),
        "hann_window": ("hann", ()),
        "bartlett_window": ("bartlett", ()),
        "kaiser_window": (("kaiser", 12.0), ()),
        "nuttall_window": ("nuttall", ()),
        "blackman_harris_window": ("blackmanharris", ()),
        "bohman_window": ("bohman", ()),
        "cosine_window": ("cosine", ()),
        "tukey_window": (("tukey", 0.5), ()),
        "gaussian_window": (("gaussian", 7.0), ()),
        "exponential_window": (("exponential", None, 1.0), ()),
        "triang_window": ("triang", ()),
    }
    specs = {}
    for op, (sci_name, extra) in table.items():
        def mk(sci_name=sci_name, extra=extra):
            def spec(rng):
                legs = []
                for M, sym in ((8, True), (9, False)):
                    orc = (None if ss is None else
                           (lambda M=M, sym=sym:
                            ss.get_window(sci_name, M, fftbins=not sym)))
                    legs.append(((M,) + tuple(extra),
                                 {"sym": sym, "dtype": "float32"},
                                 (lambda *_a, _o=orc, **_k: _o())
                                 if orc else None))
                return legs
            return spec
        specs[op] = mk()
    def _gw_oracle(*_a, **_k):
        import scipy.signal as _ss
        return _ss.get_window("hann", 16)
    specs["get_window"] = lambda rng: [
        (("hann", 16), {"dtype": "float32"}, _gw_oracle)]
    specs["general_cosine_window"] = lambda rng: [
        ((8, [0.5, 0.5]), {"dtype": "float32"}, None)]
    specs["general_hamming_window"] = lambda rng: [
        ((8, 0.6), {"dtype": "float32"}, None)]
    specs["taylor_window"] = lambda rng: [((16,), {"dtype": "float32"},
                                           None)]
    return specs


def _fft_specs():
    def o(name):
        return getattr(np.fft, name)
    simple = {}
    for n in ("fft", "ifft", "fftn", "ifftn", "fft2", "ifft2", "rfft",
              "rfft2", "rfftn", "ihfft"):
        simple[n] = (lambda n=n: (lambda rng: [
            ((_x(rng, (4, 8)),), {},
             lambda a, **k: o(n)(a))]))()
    for n in ("irfft", "irfft2", "irfftn", "hfft"):
        simple[n] = (lambda n=n: (lambda rng: [
            ((_x(rng, (4, 8)) + 1j * _x(rng, (4, 8)),), {},
             lambda a, **k: o(n)(a))]))()
    simple["fftshift"] = lambda rng: [((_x(rng, (4, 8)),), {},
                                       lambda a, **k: np.fft.fftshift(a))]
    simple["ifftshift"] = lambda rng: [((_x(rng, (4, 8)),), {},
                                        lambda a, **k: np.fft.ifftshift(a))]
    simple["fftfreq"] = lambda rng: [
        ((8,), {}, lambda *a, **k: np.fft.fftfreq(8).astype(np.float32))]
    simple["rfftfreq"] = lambda rng: [
        ((8,), {}, lambda *a, **k: np.fft.rfftfreq(8).astype(np.float32))]
    return simple


def _set_specs():
    a = np.asarray([3, 1, 2, 3, 5], np.int32)
    b = np.asarray([2, 3, 9], np.int32)
    return {
        "intersect1d": lambda rng: [((a, b), {},
                                     lambda x, y, **k: np.intersect1d(x, y))],
        "setdiff1d": lambda rng: [((a, b), {},
                                   lambda x, y, **k: np.setdiff1d(x, y))],
        "union1d": lambda rng: [((a, b), {},
                                 lambda x, y, **k: np.union1d(x, y))],
        "setxor1d": lambda rng: [((a, b), {},
                                  lambda x, y, **k: np.setxor1d(x, y))],
        "in1d": lambda rng: [((a, b), {},
                              lambda x, y, **k: np.in1d(x, y))],
    }


def _composite_specs():
    """Hand specs for multi-arg / axis ops (numpy oracle where one exists)."""
    sp = {}

    def add(name, spec):
        sp[name] = spec

    add("logdet", lambda rng: [
        (((_x(rng, (3, 3)) @ _x(rng, (3, 3)).T + 3 * np.eye(3, dtype=np.float32)),),
         {}, lambda a, **k: np.log(np.linalg.det(a)))])
    add("vdot", lambda rng: [((_x(rng), _x(rng)), {},
                              lambda a, b, **k: np.vdot(a, b))])
    add("addmv", lambda rng: [
        ((_x(rng, (3,)), _x(rng, (3, 4)), _x(rng, (4,))), {},
         lambda i, m, v, **k: i + m @ v)])
    add("addr", lambda rng: [
        ((_x(rng, (3, 4)), _x(rng, (3,)), _x(rng, (4,))), {},
         lambda i, a, b, **k: i + np.outer(a, b))])
    add("chain_matmul", lambda rng: [
        ((_x(rng, (2, 3)), _x(rng, (3, 4)), _x(rng, (4, 2))), {},
         lambda a, b, c, **k: a @ b @ c)])
    add("float_power", lambda rng: [
        ((_pos(rng), _pos(rng)), {},
         lambda a, b, **k: np.float_power(a, b).astype(np.float32))])
    add("std_mean", lambda rng: [
        ((_x(rng),), {}, lambda a, **k: (np.std(a, ddof=1), np.mean(a)))])
    add("var_mean", lambda rng: [
        ((_x(rng),), {}, lambda a, **k: (np.var(a, ddof=1), np.mean(a)))])
    add("gradient", lambda rng: [
        ((_x(rng, (8,)),), {}, lambda a, **k: np.gradient(a))])
    add("fliplr", lambda rng: [((_x(rng),), {},
                                lambda a, **k: np.fliplr(a))])
    add("flipud", lambda rng: [((_x(rng),), {},
                                lambda a, **k: np.flipud(a))])
    add("rollaxis", lambda rng: [((_x(rng, (2, 3, 4)), 2), {},
                                  lambda a, *r, **k: np.rollaxis(a, 2))])
    add("swapdims", lambda rng: [((_x(rng, (2, 3, 4)), 0, 2), {},
                                  lambda a, *r, **k: np.swapaxes(a, 0, 2))])
    add("narrow", lambda rng: [((_x(rng, (5, 4)), 0, 1, 3), {},
                                lambda a, *r, **k: a[1:4])])
    add("narrow_copy", lambda rng: [((_x(rng, (5, 4)), 0, 1, 3), {},
                                     lambda a, *r, **k: a[1:4])])
    add("split_with_sizes", lambda rng: [
        ((_x(rng, (6, 4)), [2, 4]), {},
         lambda a, *r, **k: (a[:2], a[2:]))])
    add("arctan2", lambda rng: [((_x(rng), _pos(rng)), {},
                                 lambda a, b, **k: np.arctan2(a, b))])
    add("nanargmax", lambda rng: [((_x(rng),), {},
                                   lambda a, **k: np.nanargmax(a))])
    add("nanargmin", lambda rng: [((_x(rng),), {},
                                   lambda a, **k: np.nanargmin(a))])
    add("nanstd", lambda rng: [((_x(rng),), {},
                                lambda a, **k: np.nanstd(a, ddof=1))])
    add("nanvar", lambda rng: [((_x(rng),), {},
                                lambda a, **k: np.nanvar(a, ddof=1))])
    add("histogram_bin_edges", lambda rng: [
        ((_x(rng, (16,)), 4), {},
         lambda a, *r, **k: np.histogram_bin_edges(a, 4,
                                                   (a.min(), a.max())))])
    add("histc", lambda rng: [
        ((_pos(rng, (16,)), 4), {},
         lambda a, *r, **k: np.histogram(a, 4, (a.min(), a.max()))[0])])
    add("betainc", lambda rng: [
        ((_pos(rng), _pos(rng),
          (0.1 + 0.8 * np.random.default_rng(0).random((3, 4))
           ).astype(np.float32)), {}, None)])
    add("true_divide", lambda rng: [((_x(rng), _pos(rng)), {},
                                     lambda a, b, **k: a / b)])
    add("trunc_divide", lambda rng: [((_x(rng), _pos(rng)), {},
                                      lambda a, b, **k: np.trunc(a / b))])
    add("divide_no_nan", lambda rng: [
        ((_x(rng), np.asarray([[1, 0, 2, 0]] * 3, np.float32)), {},
         lambda a, b, **k: np.where(b == 0, 0, a / np.where(b == 0, 1, b)))])
    add("bitwise_invert", lambda rng: [
        ((np.asarray([1, 2, 3], np.int32),), {},
         lambda a, **k: np.invert(a))])
    add("cumulative_sum", lambda rng: [
        ((_x(rng, (8,)),), {}, lambda a, **k: np.cumsum(a))])
    add("cumulative_prod", lambda rng: [
        ((_pos(rng, (6,)),), {}, lambda a, **k: np.cumprod(a))])
    add("clip_by_norm", lambda rng: [
        ((_x(rng), 1.0), {},
         lambda a, *r, **k: a * min(1.0, 1.0 / np.linalg.norm(a)))])
    add("take_along_dim", lambda rng: [
        ((_x(rng, (3, 4)), np.asarray([[0], [1], [2]], np.int32)),
         {"dim": 1},
         lambda a, i, **k: np.take_along_axis(a, i, axis=1))])
    add("permute_dims", lambda rng: [
        ((_x(rng, (2, 3, 4)), (2, 0, 1)), {},
         lambda a, *r, **k: np.transpose(a, (2, 0, 1)))])
    add("index_copy", lambda rng: [
        ((_x(rng, (4, 3)), np.asarray([0, 2], np.int32), _x(rng, (2, 3))),
         {}, lambda a, i, s, **k: _np_index_copy(a, i, s))])
    add("scatter_add", lambda rng: [
        ((np.zeros((3, 3), np.float32),
          np.asarray([[0, 1, 2], [0, 1, 2]], np.int32),
          np.ones((2, 3), np.float32)), {}, None)])
    add("scatter_reduce", lambda rng: [
        ((np.zeros((3, 3), np.float32),
          np.asarray([[0, 1, 2], [0, 1, 2]], np.int32),
          np.ones((2, 3), np.float32)), {"reduce": "amax"}, None)])
    add("unravel_index", lambda rng: [
        ((np.asarray([5, 7], np.int32), (3, 4)), {},
         lambda i, *r, **k: np.unravel_index(i, (3, 4)))])
    add("diag_indices", lambda rng: [((3,), {}, None)])
    add("cholesky_inverse", lambda rng: [
        ((np.linalg.cholesky(
            _x(rng, (3, 3)) @ _x(rng, (3, 3)).T +
            3 * np.eye(3, dtype=np.float32)).astype(np.float32),), {},
         None)])
    add("tensorinv", lambda rng: [
        ((_x(rng, (6, 2, 3)).reshape(6, 2, 3),), {"ind": 1},
         lambda a, **k: np.linalg.tensorinv(a, 1))])
    add("tensorsolve", lambda rng: [
        ((_x(rng, (2, 3, 6)), _x(rng, (2, 3))), {},
         lambda a, b, **k: np.linalg.tensorsolve(a, b))])
    add("geqrf", lambda rng: [((_x(rng, (4, 3)),), {}, None)])
    add("pairwise_distance", lambda rng: [
        ((_x(rng), _x(rng)), {}, None)])
    add("softmax2d", lambda rng: [((_x(rng, (2, 3, 4, 4)),), {}, None)])
    add("lp_pool1d", lambda rng: [
        ((_x(rng, (1, 2, 8)), 2.0, 4, 4), {}, None)])
    add("fractional_max_pool2d", lambda rng: [
        ((_x(rng, (1, 2, 9, 9)), 4), {"kernel_size": 2, "random_u": 0.3},
         None)])
    add("fractional_max_pool3d", lambda rng: [
        ((_x(rng, (1, 1, 9, 9, 9)), 4), {"kernel_size": 2, "random_u": 0.5},
         None)])
    def spd(rng):
        m = _x(rng, (3, 3))
        return (m @ m.T + 3 * np.eye(3, dtype=np.float32))
    add("cholesky", lambda rng: [((spd(rng),), {},
                                  lambda a, **k: np.linalg.cholesky(a))])
    add("det", lambda rng: [((spd(rng),), {},
                             lambda a, **k: np.linalg.det(a))])
    add("inv", lambda rng: [((spd(rng),), {},
                             lambda a, **k: np.linalg.inv(a))])
    add("slogdet", lambda rng: [((spd(rng),), {}, None)])
    add("eigvalsh", lambda rng: [((spd(rng),), {}, None)])
    add("eigh", lambda rng: [((spd(rng),), {}, None)])
    add("eig", lambda rng: [((spd(rng),), {}, None)])
    add("eigvals", lambda rng: [((spd(rng),), {}, None)])
    add("matrix_exp", lambda rng: [((0.1 * _x(rng, (3, 3)),), {}, None)])
    add("std", lambda rng: [((_x(rng),), {},
                             lambda a, **k: np.std(a, ddof=1))])
    add("var", lambda rng: [((_x(rng),), {},
                             lambda a, **k: np.var(a, ddof=1))])
    add("clip", lambda rng: [((_x(rng),), {"min": -0.5, "max": 0.5},
                              lambda a, **k: np.clip(a, -0.5, 0.5))])
    add("logit", lambda rng: [
        (((0.1 + 0.8 * np.random.default_rng(7).random((3, 4))
           ).astype(np.float32),), {},
         lambda a, **k: np.log(a / (1 - a)))])
    add("bincount", lambda rng: [
        ((np.asarray([0, 1, 1, 3], np.int32),), {},
         lambda a, **k: np.bincount(a))])
    add("histogram", lambda rng: [
        ((_pos(rng, (16,)), 4), {"min": 0.0, "max": 3.0},
         lambda a, *r, **k: np.histogram(a, 4, (0.0, 3.0))[0])])
    add("vander", lambda rng: [
        ((_x(rng, (4,)),), {"n": 3},
         lambda a, **k: np.vander(a, 3))])
    add("concatenate", lambda rng: [
        (([_x(rng), _x(rng)],), {},
         lambda xs, **k: np.concatenate(xs))])
    add("ravel_multi_index", lambda rng: [
        (([np.asarray([1, 2], np.int32), np.asarray([0, 3], np.int32)],
          (3, 4)), {},
         lambda mi, shape, **k: np.ravel_multi_index(tuple(mi), shape,
                                                     mode="clip"))])
    add("lu_solve", lambda rng: [
        ((np.asarray([1.0, 2.0], np.float32),
          np.asarray([[4.0, 2.0], [0.5, 2.0]], np.float32),
          np.asarray([1, 2], np.int32)), {}, None)])
    return sp


def _np_index_copy(a, i, s):
    out = a.copy()
    out[i] = s
    return out


# auto-specced one-tensor ops that need a positive/bounded domain
_AUTO_DOMAIN = {
    "cbrt": _x, "exp2": _x, "expit": _x, "erfc": _x,
}

# never auto-spec: random/stateful/inplace/shape-polymorphic/IO, plus ops
# whose single positional arg is a SHAPE or needs structured input (they
# get explicit specs or stay unswept)
_AUTO_EXCLUDE_PREFIX = ("fused_", "sparse_")
_AUTO_EXCLUDE_SUFFIX = ("_",)
_AUTO_EXCLUDE = {
    "zeros", "ones", "empty", "eye", "rand", "randn", "randperm", "uniform",
    "standard_normal", "standard_gamma", "seed", "create_parameter", "crop",
    "empty_like", "vander", "nonzero", "einsum", "multi_dot",
    "triu_indices", "tril_indices", "bincount", "histogram", "histogramdd",
    "clip", "logit", "cholesky", "det", "inv", "eig", "eigh", "eigvals",
    "eigvalsh", "slogdet", "matrix_exp", "std", "var", "concatenate",
    "ravel_multi_index", "interpolate", "upsample",
    "read_file", "decode_jpeg", "sampling_id",
    "merge_selected_rows", "get_tensor_from_selected_rows",
    "fill", "fill_diagonal",
}


def _auto_spec(name, public):
    """Generic spec for ``(x, name=None)``-shaped publics: forward + numpy
    oracle when numpy has the name; gradient handled by the sweep."""
    try:
        sig = inspect.signature(public)
    except (TypeError, ValueError):
        return None
    params = list(sig.parameters.values())
    required = [p for p in params
                if p.default is inspect.Parameter.empty and
                p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)]
    if len(required) != 1:
        return None
    np_fn = getattr(np, name, None)
    oracle = (lambda a, **k: np_fn(a)) if callable(np_fn) else None
    maker = _AUTO_DOMAIN.get(name, _x)

    def spec(rng):
        return [((maker(rng),), {}, oracle)]
    return spec


def attach_specs():
    """Attach sweep/oracle specs to the live registry; returns coverage."""
    from ..core.dispatch import OP_REGISTRY

    explicit = {}
    explicit.update(_window_specs())
    explicit.update(_fft_specs())
    explicit.update(_set_specs())
    explicit.update(_composite_specs())
    explicit.update(_bulk_specs())

    attached = 0
    explicit.update(_r5_specs())
    explicit.update(_r5b_specs())
    # the sparse_* registrations store the raw VALUES kernel as public;
    # rebind to the user-facing sparse API so the sweep drives the real
    # entry points
    import paddle_tpu.sparse as _S
    _overrides = {
        "sparse_add": _S.add, "sparse_subtract": _S.subtract,
        "sparse_multiply": _S.multiply, "sparse_divide": _S.divide,
        "sparse_matmul": _S.matmul, "sparse_masked_matmul":
        _S.masked_matmul, "sparse_mv": _S.mv, "sparse_addmm": _S.addmm,
        "sparse_sum": _S.sum, "sparse_transpose": _S.transpose,
        "sparse_reshape": _S.reshape, "sparse_cast": _S.cast,
        "sparse_pow": _S.pow,
        "sparse_coalesce": lambda t, name=None: t.coalesce(),
        "sparse_relu": _S.relu, "sparse_relu6": _S.nn.functional.relu6,
        "sparse_leaky_relu": _S.nn.functional.leaky_relu,
        "sparse_softmax": _S.nn.functional.softmax,
        "sparse_attention": _S.nn.functional.attention,
    }
    import paddle_tpu.vision.ops as _V
    _overrides.update({
        "box_iou": _V.box_iou, "nms": _V.nms, "box_coder": _V.box_coder,
        "roi_align": _V.roi_align, "roi_pool": _V.roi_pool,
    })
    for _n in ("abs", "asin", "asinh", "atan", "atanh", "deg2rad",
               "expm1", "log1p", "neg", "rad2deg", "sin", "sinh", "sqrt",
               "square", "tan", "tanh"):
        _overrides["sparse_" + _n] = getattr(_S, _n)
    for _n, _f in _overrides.items():
        d = OP_REGISTRY.get(_n)
        if d is not None:
            d.public = _f
    for name, spec in explicit.items():
        d = OP_REGISTRY.get(name)
        if d is not None:
            d.sweep = spec
            if d.public is None:   # older registrations stored the public
                d.public = d.fn    # wrapper as fn (signal/geometric style)
            attached += 1
    for name, d in OP_REGISTRY.items():
        if d.sweep is not None or d.category in ("unary", "binary"):
            continue
        if name.endswith(_AUTO_EXCLUDE_SUFFIX) or \
                name.startswith(_AUTO_EXCLUDE_PREFIX) or \
                name in _AUTO_EXCLUDE:
            continue
        if d.public is None:
            continue
        spec = _auto_spec(name, d.public)
        if spec is not None:
            d.sweep = spec
            attached += 1
    # r5: the in-place `_` family is swept for ALIASING semantics (the
    # result must be rebound onto the caller's tensor and match the base
    # op's value) by tests/test_op_sweep.py::test_inplace_aliasing_sweep.
    # Mark each twin whose base is itself swept: the marker tuple keeps
    # them out of the composite (callable-spec) sweep.
    for name, d in OP_REGISTRY.items():
        if not name.endswith("_") or d.sweep is not None:
            continue
        if name in ("fill_", "fill_diagonal_"):
            # hand-written twins whose base registrations are placeholder
            # lambdas (inplace.py) — covered by hand tests
            continue
        base = OP_REGISTRY.get(name[:-1])
        if base is not None and (base.category in ("unary", "binary")
                                 or callable(base.sweep)):
            d.sweep = ("inplace", name[:-1])
            attached += 1
    return attached


def sweep_coverage():
    """(covered, total): ops exercised by the sweep (factory categories or
    an attached spec) over all registered ops."""
    from ..core.dispatch import OP_REGISTRY
    total = len(OP_REGISTRY)
    covered = sum(1 for d in OP_REGISTRY.values()
                  if d.category in ("unary", "binary") or d.sweep is not None)
    return covered, total


def _bulk_specs():
    """r4 second batch: matmul/manipulation/indexing/creation/search/loss/
    pool/segment groups. Oracle = numpy where a clean counterpart exists,
    else run-only (finiteness; the op keeps its hand-written domain test)."""
    sp = {}

    def add(name, spec):
        sp[name] = spec

    i32 = np.int32

    # ---- matmul family ----
    add("matmul", lambda rng: [((_x(rng, (3, 4)), _x(rng, (4, 2))), {},
                                lambda a, b, **k: a @ b)])
    add("mm", lambda rng: [((_x(rng, (3, 4)), _x(rng, (4, 2))), {},
                            lambda a, b, **k: a @ b)])
    add("bmm", lambda rng: [((_x(rng, (2, 3, 4)), _x(rng, (2, 4, 2))), {},
                             lambda a, b, **k: a @ b)])
    add("mv", lambda rng: [((_x(rng, (3, 4)), _x(rng, (4,))), {},
                            lambda a, b, **k: a @ b)])
    add("dot", lambda rng: [((_x(rng, (5,)), _x(rng, (5,))), {},
                             lambda a, b, **k: np.dot(a, b))])
    add("cross", lambda rng: [((_x(rng, (4, 3)), _x(rng, (4, 3))), {},
                               lambda a, b, **k: np.cross(a, b))])
    add("kron", lambda rng: [((_x(rng, (2, 2)), _x(rng, (3, 2))), {},
                              lambda a, b, **k: np.kron(a, b))])
    add("tensordot", lambda rng: [((_x(rng, (3, 4)), _x(rng, (4, 5))),
                                   {"axes": 1},
                                   lambda a, b, **k: np.tensordot(a, b, 1))])
    add("addmm", lambda rng: [
        ((_x(rng, (3, 2)), _x(rng, (3, 4)), _x(rng, (4, 2))), {},
         lambda i, a, b, **k: i + a @ b)])
    add("baddbmm", lambda rng: [
        ((_x(rng, (2, 3, 2)), _x(rng, (2, 3, 4)), _x(rng, (2, 4, 2))), {},
         lambda i, a, b, **k: i + a @ b)])
    add("multi_dot", lambda rng: [
        (([_x(rng, (2, 3)), _x(rng, (3, 4)), _x(rng, (4, 2))],), {},
         lambda ms, **k: np.linalg.multi_dot(ms))])
    add("einsum", lambda rng: [
        (("ij,jk->ik", _x(rng, (3, 4)), _x(rng, (4, 2))), {},
         lambda eq, a, b, **k: np.einsum(eq, a, b))])
    add("outer", lambda rng: [((_x(rng, (3,)), _x(rng, (4,))), {},
                               lambda a, b, **k: np.outer(a, b))])
    add("inner", lambda rng: [((_x(rng, (3, 4)), _x(rng, (2, 4))), {},
                               lambda a, b, **k: np.inner(a, b))])

    # ---- manipulation ----
    add("reshape", lambda rng: [((_x(rng, (3, 4)), [2, 6]), {},
                                 lambda a, *r, **k: a.reshape(2, 6))])
    add("transpose", lambda rng: [((_x(rng, (2, 3, 4)), [2, 0, 1]), {},
                                   lambda a, *r, **k: a.transpose(2, 0, 1))])
    add("unsqueeze", lambda rng: [((_x(rng, (3, 4)), 1), {},
                                   lambda a, *r, **k: a[:, None])])
    add("tile", lambda rng: [((_x(rng, (2, 3)), [2, 2]), {},
                              lambda a, *r, **k: np.tile(a, (2, 2)))])
    add("broadcast_to", lambda rng: [((_x(rng, (1, 4)), [3, 4]), {},
                                      lambda a, *r, **k:
                                      np.broadcast_to(a, (3, 4)))])
    add("expand", lambda rng: [((_x(rng, (1, 4)), [3, 4]), {},
                                lambda a, *r, **k:
                                np.broadcast_to(a, (3, 4)))])
    add("expand_as", lambda rng: [((_x(rng, (1, 4)), _x(rng, (3, 4))), {},
                                   lambda a, b, **k:
                                   np.broadcast_to(a, b.shape))])
    add("moveaxis", lambda rng: [((_x(rng, (2, 3, 4)), 0, 2), {},
                                  lambda a, *r, **k: np.moveaxis(a, 0, 2))])
    add("swapaxes", lambda rng: [((_x(rng, (2, 3, 4)), 0, 2), {},
                                  lambda a, *r, **k: np.swapaxes(a, 0, 2))])
    add("roll", lambda rng: [((_x(rng, (3, 4)), 2), {},
                              lambda a, *r, **k: np.roll(a, 2))])
    add("flip", lambda rng: [((_x(rng, (3, 4)), 0), {},
                              lambda a, *r, **k: np.flip(a, 0))])
    add("chunk", lambda rng: [((_x(rng, (6, 4)), 3), {},
                               lambda a, *r, **k:
                               tuple(np.split(a, 3, 0)))])
    add("split", lambda rng: [((_x(rng, (6, 4)), 3), {},
                               lambda a, *r, **k:
                               tuple(np.split(a, 3, 0)))])
    add("hsplit", lambda rng: [((_x(rng, (4, 6)), 3), {},
                                lambda a, *r, **k:
                                tuple(np.hsplit(a, 3)))])
    add("vsplit", lambda rng: [((_x(rng, (6, 4)), 3), {},
                                lambda a, *r, **k:
                                tuple(np.vsplit(a, 3)))])
    add("dsplit", lambda rng: [((_x(rng, (2, 3, 6)), 3), {},
                                lambda a, *r, **k:
                                tuple(np.dsplit(a, 3)))])
    add("tensor_split", lambda rng: [((_x(rng, (7, 4)), 3), {},
                                      lambda a, *r, **k:
                                      tuple(np.array_split(a, 3, 0)))])
    add("repeat_interleave", lambda rng: [((_x(rng, (3, 2)), 2), {},
                                           lambda a, *r, **k:
                                           np.repeat(a, 2, axis=None))])
    add("unflatten", lambda rng: [((_x(rng, (2, 6)), 1, [2, 3]), {},
                                   lambda a, *r, **k:
                                   a.reshape(2, 2, 3))])
    add("cast", lambda rng: [((_x(rng), "float32"), {}, None)])
    add("reverse", lambda rng: [((_x(rng, (3, 4)), 0), {},
                                 lambda a, *r, **k: np.flip(a, 0))])
    add("crop", lambda rng: [((_x(rng, (4, 5)), [2, 3], [1, 1]), {},
                              lambda a, *r, **k: a[1:3, 1:4])])
    add("strided_slice", lambda rng: [
        ((_x(rng, (6, 5)), [0], [1], [5], [2]), {},
         lambda a, *r, **k: a[1:5:2])])
    add("pad", lambda rng: [((_x(rng, (3, 4)), [1, 1, 0, 0]), {},
                             None)])
    add("meshgrid", lambda rng: [
        (([np.arange(3, dtype=np.float32),
           np.arange(4, dtype=np.float32)],), {}, None)])
    add("atleast_1d", lambda rng: [((_x(rng, (3,)),), {},
                                    lambda a, **k: np.atleast_1d(a))])
    add("atleast_2d", lambda rng: [((_x(rng, (3,)),), {},
                                    lambda a, **k: np.atleast_2d(a))])
    add("atleast_3d", lambda rng: [((_x(rng, (3,)),), {},
                                    lambda a, **k: np.atleast_3d(a))])

    # ---- indexing / scatter ----
    idx2 = np.asarray([0, 2], i32)
    add("take", lambda rng: [((_x(rng, (3, 4)), np.asarray([1, 5], i32)),
                              {}, lambda a, i, **k: a.ravel()[i])])
    add("take_along_axis", lambda rng: [
        ((_x(rng, (3, 4)), np.asarray([[0], [1], [2]], i32), 1), {},
         lambda a, i, ax, **k: np.take_along_axis(a, i, 1))])
    add("index_select", lambda rng: [
        ((_x(rng, (4, 3)), idx2), {},
         lambda a, i, **k: a[i])])
    add("gather", lambda rng: [
        ((_x(rng, (4, 3)), idx2), {},
         lambda a, i, **k: a[i])])
    add("gather_nd", lambda rng: [
        ((_x(rng, (4, 3)), np.asarray([[0, 1], [2, 2]], i32)), {},
         lambda a, i, **k: a[i[:, 0], i[:, 1]])])
    add("index_sample", lambda rng: [
        ((_x(rng, (3, 4)), np.asarray([[0, 1], [1, 2], [2, 3]], i32)), {},
         lambda a, i, **k: np.take_along_axis(a, i, 1))])
    add("masked_fill", lambda rng: [
        ((_x(rng, (3, 4)), _x(rng, (3, 4)) > 0, 9.0), {},
         lambda a, m, v, **k: np.where(m, v, a))])
    add("masked_scatter", lambda rng: [
        ((_x(rng, (2, 3)), np.asarray([[1, 0, 1], [0, 1, 0]], bool),
          _x(rng, (6,))), {}, None)])
    add("index_fill", lambda rng: [
        ((_x(rng, (4, 3)), idx2, 0, 7.0), {},
         lambda a, i, ax, v, **k: _np_index_fill(a, i, v))])
    add("index_add", lambda rng: [
        ((_x(rng, (4, 3)), idx2, 0, _x(rng, (2, 3))), {}, None)])
    add("index_put", lambda rng: [
        ((_x(rng, (4, 3)), (idx2, np.asarray([0, 1], i32)),
          np.asarray([5.0, 6.0], np.float32)), {}, None)])
    add("put_along_axis", lambda rng: [
        ((_x(rng, (3, 4)), np.asarray([[0], [1], [2]], i32),
          9.0, 1), {}, None)])
    add("scatter", lambda rng: [
        ((_x(rng, (4, 3)), idx2, _x(rng, (2, 3))), {}, None)])
    add("scatter_nd", lambda rng: [
        ((np.asarray([[1], [3]], i32), _x(rng, (2, 3)), [5, 3]), {},
         None)])
    add("scatter_nd_add", lambda rng: [
        ((_x(rng, (5, 3)), np.asarray([[1], [3]], i32),
          _x(rng, (2, 3))), {}, None)])
    add("select_scatter", lambda rng: [
        ((_x(rng, (3, 4)), _x(rng, (4,)), 0, 1), {}, None)])
    add("slice_scatter", lambda rng: [
        ((_x(rng, (6, 3)), _x(rng, (2, 3)), [0], [1], [5], [2]), {},
         None)])
    add("diagonal_scatter", lambda rng: [
        ((_x(rng, (3, 3)), _x(rng, (3,))), {}, None)])

    add("multiplex", lambda rng: [
        (([_x(rng, (3, 4)), _x(rng, (3, 4))],
          np.asarray([0, 1, 0], i32)), {}, None)])
    add("shard_index", lambda rng: [
        ((np.asarray([[1], [6]], np.int64), 8, 2, -1), {}, None)])

    # ---- creation ----
    add("arange", lambda rng: [((0, 10, 2), {},
                                lambda *a, **k: np.arange(0, 10, 2))])
    add("linspace", lambda rng: [((0.0, 1.0, 5), {},
                                  lambda *a, **k:
                                  np.linspace(0, 1, 5,
                                              dtype=np.float32))])
    add("logspace", lambda rng: [((0.0, 2.0, 3), {},
                                  lambda *a, **k:
                                  np.logspace(0, 2, 3,
                                              dtype=np.float32))])
    add("eye", lambda rng: [((3, 4), {},
                             lambda *a, **k: np.eye(3, 4,
                                                    dtype=np.float32))])
    add("ones", lambda rng: [(([2, 3],), {},
                              lambda *a, **k: np.ones((2, 3),
                                                      np.float32))])
    add("zeros", lambda rng: [(([2, 3],), {},
                               lambda *a, **k: np.zeros((2, 3),
                                                        np.float32))])
    add("full", lambda rng: [(([2, 3], 7.0), {},
                              lambda *a, **k: np.full((2, 3), 7.0,
                                                      np.float32))])
    add("full_like", lambda rng: [((_x(rng), 7.0), {},
                                   lambda a, v, **k:
                                   np.full_like(a, 7.0))])
    add("empty", lambda rng: [(([2, 3],), {}, None)])
    add("empty_like", lambda rng: [((_x(rng),), {}, None)])
    add("complex", lambda rng: [((_x(rng), _x(rng)), {},
                                 lambda a, b, **k: a + 1j * b)])
    add("broadcast_shape", lambda rng: [(([2, 1, 3], [4, 3]), {}, None)])

    # ---- search / compare ----
    add("searchsorted", lambda rng: [
        ((np.sort(_x(rng, (6,))), _x(rng, (4,))), {},
         lambda s, v, **k: np.searchsorted(s, v))])
    add("bucketize", lambda rng: [
        ((_x(rng, (4,)), np.sort(_x(rng, (5,)))), {},
         lambda v, s, **k: np.searchsorted(s, v))])
    add("topk", lambda rng: [((_x(rng, (3, 6)), 2), {},
                              lambda a, kk, **k:
                              (np.sort(a, -1)[:, ::-1][:, :2],
                               np.argsort(-a, -1, kind="stable")[:, :2]))])
    add("kthvalue", lambda rng: [((_x(rng, (3, 6)), 2), {}, None)])
    add("isclose", lambda rng: [
        ((_x(rng), _x(rng)), {},
         lambda a, b, **k: np.isclose(a, b, 1e-5, 1e-8))])
    add("allclose", lambda rng: [
        ((_x(rng), _x(rng)), {},
         lambda a, b, **k: np.allclose(a, b, 1e-5, 1e-8))])
    add("equal_all", lambda rng: [
        ((_x(rng), _x(rng)), {},
         lambda a, b, **k: np.array_equal(a, b))])
    add("isin", lambda rng: [
        ((np.asarray([1, 2, 3, 4], i32), np.asarray([2, 4], i32)), {},
         lambda a, t, **k: np.isin(a, t))])

    # ---- elementwise leftovers ----
    add("lerp", lambda rng: [
        ((_x(rng), _x(rng), 0.3), {},
         lambda a, b, w, **k: a + 0.3 * (b - a))])
    add("floor_mod", lambda rng: [
        ((_pos(rng), _pos(rng)), {},
         lambda a, b, **k: np.mod(a, b))])
    add("mod", lambda rng: [
        ((_pos(rng), _pos(rng)), {},
         lambda a, b, **k: np.mod(a, b))])
    add("pow", lambda rng: [
        ((_pos(rng), 2.0), {}, lambda a, b, **k: a ** 2.0)])
    add("quantile", lambda rng: [
        ((_x(rng, (16,)), 0.5), {},
         lambda a, q, **k: np.quantile(a, 0.5).astype(np.float32))])
    add("nanquantile", lambda rng: [
        ((_x(rng, (16,)), 0.5), {},
         lambda a, q, **k: np.nanquantile(a, 0.5).astype(np.float32))])
    add("renorm", lambda rng: [((_x(rng, (3, 4)), 2.0, 0, 1.0), {}, None)])
    add("dist", lambda rng: [((_x(rng), _x(rng)), {},
                              lambda a, b, **k:
                              np.linalg.norm((a - b).ravel()))])

    # ---- linalg solves ----
    def spd3(rng):
        m = _x(rng, (3, 3))
        return m @ m.T + 3 * np.eye(3, dtype=np.float32)
    add("solve", lambda rng: [((spd3(rng), _x(rng, (3,))), {},
                               lambda a, b, **k: np.linalg.solve(a, b))])
    add("cholesky_solve", lambda rng: [
        ((_x(rng, (3,)), np.linalg.cholesky(spd3(rng)).astype(np.float32)),
         {}, None)])
    add("triangular_solve", lambda rng: [
        ((np.tril(spd3(rng)).astype(np.float32), _x(rng, (3, 1))),
         {"upper": False}, None)])
    add("lstsq", lambda rng: [((_x(rng, (5, 3)), _x(rng, (5, 1))), {},
                               None)])
    add("matrix_power", lambda rng: [
        ((spd3(rng), 3), {},
         lambda a, n, **k: np.linalg.matrix_power(a, 3))])
    add("lu_unpack", lambda rng: [
        ((np.asarray([[4.0, 2.0], [0.5, 2.0]], np.float32),
          np.asarray([2, 2], i32)), {}, None)])

    # ---- losses / nn functional ----
    t32 = (0.1 + 0.8 * np.random.default_rng(3).random((4, 3))
           ).astype(np.float32)
    add("l1_loss", lambda rng: [
        ((_x(rng), _x(rng)), {},
         lambda a, b, **k: np.abs(a - b).mean())])
    add("mse_loss", lambda rng: [
        ((_x(rng), _x(rng)), {},
         lambda a, b, **k: ((a - b) ** 2).mean())])
    add("smooth_l1_loss", lambda rng: [((_x(rng), _x(rng)), {}, None)])
    add("huber_loss", lambda rng: [((_x(rng), _x(rng)), {}, None)])
    add("log_loss", lambda rng: [((t32, (t32 > 0.5).astype(np.float32)),
                                  {}, None)])
    add("binary_cross_entropy", lambda rng: [
        ((t32, (t32 > 0.5).astype(np.float32)), {}, None)])
    add("binary_cross_entropy_with_logits", lambda rng: [
        ((_x(rng), ( _x(rng) > 0).astype(np.float32)), {}, None)])
    add("nll_loss", lambda rng: [
        ((np.log(t32 / t32.sum(-1, keepdims=True)),
          np.asarray([0, 1, 2, 0], np.int64)), {}, None)])
    add("cross_entropy", lambda rng: [
        ((_x(rng, (4, 3)), np.asarray([0, 1, 2, 0], np.int64)), {}, None)])
    add("softmax_with_cross_entropy", lambda rng: [
        ((_x(rng, (4, 3)), np.asarray([[0], [1], [2], [0]], np.int64)),
         {}, None)])
    add("cosine_similarity", lambda rng: [
        ((_x(rng), _x(rng)), {},
         lambda a, b, **k: (a * b).sum(-1) /
         (np.linalg.norm(a, axis=-1) * np.linalg.norm(b, axis=-1)))])
    add("one_hot", lambda rng: [
        ((np.asarray([0, 2, 1], i32), 4), {},
         lambda a, n, **k: np.eye(4, dtype=np.float32)[a])])
    add("embedding", lambda rng: [
        ((np.asarray([0, 2], i32), _x(rng, (5, 4))), {},
         lambda i, w, **k: w[i])])
    add("linear", lambda rng: [
        ((_x(rng, (2, 4)), _x(rng, (4, 3)), _x(rng, (3,))), {},
         lambda x, w, b, **k: x @ w + b)])

    # ---- pools / convs: run-only legs (hand-tested with oracles elsewhere)
    for n, shape, extra in (
            ("avg_pool1d", (1, 2, 8), (2,)), ("avg_pool2d", (1, 2, 8, 8),
                                              (2,)),
            ("avg_pool3d", (1, 1, 4, 4, 4), (2,)),
            ("max_pool1d", (1, 2, 8), (2,)), ("max_pool2d", (1, 2, 8, 8),
                                              (2,)),
            ("max_pool3d", (1, 1, 4, 4, 4), (2,)),
            ("adaptive_avg_pool1d", (1, 2, 8), (2,)),
            ("adaptive_avg_pool2d", (1, 2, 8, 8), (2,)),
            ("adaptive_avg_pool3d", (1, 1, 4, 4, 4), (2,)),
            ("adaptive_max_pool1d", (1, 2, 8), (2,)),
            ("adaptive_max_pool2d", (1, 2, 8, 8), (2,)),
            ("adaptive_max_pool3d", (1, 1, 4, 4, 4), (2,))):
        add(n, (lambda shape=shape, extra=extra:
                (lambda rng: [((_x(rng, shape),) + extra, {}, None)]))())
    for n, xs, ws in (("conv1d", (1, 2, 8), (3, 2, 3)),
                      ("conv2d", (1, 2, 8, 8), (3, 2, 3, 3)),
                      ("conv3d", (1, 1, 6, 6, 6), (2, 1, 3, 3, 3))):
        add(n, (lambda xs=xs, ws=ws:
                (lambda rng: [((_x(rng, xs), _x(rng, ws)), {}, None)]))())

    # ---- segments (numpy oracle) ----
    seg = np.asarray([0, 0, 1, 2, 2], i32)

    def seg_oracle(red):
        def o(x, s, **k):
            return np.stack([red(x[s == g]) for g in range(int(s.max()) + 1)])
        return o
    add("segment_sum", lambda rng: [
        ((_x(rng, (5, 3)), seg), {},
         seg_oracle(lambda v: v.sum(0)))])
    add("segment_mean", lambda rng: [
        ((_x(rng, (5, 3)), seg), {},
         seg_oracle(lambda v: v.mean(0)))])
    add("segment_max", lambda rng: [
        ((_x(rng, (5, 3)), seg), {},
         seg_oracle(lambda v: v.max(0)))])
    add("segment_min", lambda rng: [
        ((_x(rng, (5, 3)), seg), {},
         seg_oracle(lambda v: v.min(0)))])

    # ---- random / signal: run-only (statistical tests live elsewhere) ----
    for n, args in (("rand", ([2, 3],)), ("randn", ([2, 3],)),
                    ("randint", (0, 5, [2, 3])), ("randperm", (6,)),
                    ("uniform", ([2, 3],)), ("normal", (0.0, 1.0, [2, 3])),
                    ("standard_normal", ([2, 3],)),
                    ("standard_gamma", (2.0, [2, 3]))):
        add(n, (lambda args=args:
                (lambda rng: [(args, {}, None)]))())
    add("stft", lambda rng: [
        ((_x(rng, (1, 256)), 64), {"hop_length": 32,
                                   "window": np.hanning(64).astype(
                                       np.float32)}, None)])
    add("frame", lambda rng: [
        ((_x(rng, (1, 64)), 16, 8), {}, None)])
    return sp


def _np_index_fill(a, i, v):
    out = a.copy()
    out[i] = v
    return out


def _np_fill_diag(a, v):
    out = a.copy()
    np.fill_diagonal(out, v)
    return out


def _r5_specs():
    """r5: specs for the round-5 op families (sequence/quant/detection/
    decode/fused/optimizer/transforms/moe-infra) plus older unswept nn
    composites. Oracle = numpy where the math is a one-liner; run-only
    (finiteness + shape) where the op has its own hand-written domain test
    in tests/ (every family here does)."""
    sp = {}

    def add(name, spec):
        sp[name] = spec

    i64 = np.int64

    def _lens(*v):
        return np.asarray(v, i64)

    # ---- sequence family ----
    add("sequence_pad", lambda rng: [((
        _x(rng, (6, 2)), 0.0, 4, _lens(2, 4)), {}, None)])
    add("sequence_reverse", lambda rng: [((
        _x(rng, (2, 4, 2)), _lens(3, 4)), {}, None)])
    add("sequence_softmax", lambda rng: [((
        _x(rng, (2, 4)), _lens(2, 4)), {}, None)])
    add("sequence_pool", lambda rng: [((
        _x(rng, (2, 4)), "mean", _lens(2, 3)), {}, None)])
    add("sequence_first_step", lambda rng: [((
        _x(rng, (2, 4)), _lens(2, 3)), {},
        lambda x, l, **k: x[:, 0])])
    add("sequence_last_step", lambda rng: [((
        _x(rng, (2, 4)), _lens(2, 3)), {}, None)])
    add("sequence_expand", lambda rng: [((
        _x(rng, (2, 3)), _lens(1, 2)), {}, None)])
    add("sequence_expand_as", lambda rng: [((
        _x(rng, (2, 3)), _x(rng, (2, 4, 3))), {}, None)])
    add("sequence_conv", lambda rng: [((
        _x(rng, (1, 5, 3)), _x(rng, (9, 4)), 3), {}, None)])
    add("sequence_slice", lambda rng: [((
        _x(rng, (2, 6)), _lens(1, 2), _lens(2, 3)), {}, None)])
    add("sequence_concat", lambda rng: [((
        [_x(rng, (2, 2)), _x(rng, (2, 3))],
        [_lens(1, 2), _lens(2, 1)]), {}, None)])
    add("sequence_enumerate", lambda rng: [((
        rng.integers(0, 9, (2, 5)).astype(i64), 2), {}, None)])
    add("sequence_erase", lambda rng: [((
        rng.integers(0, 4, (2, 5)).astype(i64), [1], _lens(5, 4)),
        {}, None)])
    add("sequence_reshape", lambda rng: [((
        _x(rng, (1, 2, 4)), 2, _lens(2)), {}, None)])
    add("sequence_scatter", lambda rng: [((
        np.zeros((2, 5), np.float32),
        rng.integers(0, 5, (2, 2)).astype(i64), _x(rng, (2, 2))),
        {}, None)])
    add("lod_reset", lambda rng: [((
        _x(rng, (2, 3)), _lens(1, 3)), {}, None)])
    add("im2sequence", lambda rng: [((
        _x(rng, (1, 2, 4, 4)), 2), {"stride": 2}, None)])
    add("row_conv", lambda rng: [((
        _x(rng, (1, 4, 3)), _x(rng, (2, 3))), {}, None)])

    # ---- quant family ----
    add("fake_quantize_abs_max", lambda rng: [((_x(rng),), {}, None)])
    add("fake_quantize_dequantize_abs_max",
        lambda rng: [((_x(rng),), {}, None)])
    add("fake_channel_wise_quantize_abs_max",
        lambda rng: [((_x(rng, (4, 3)),), {"quant_axis": 1}, None)])
    add("fake_channel_wise_quantize_dequantize_abs_max",
        lambda rng: [((_x(rng, (4, 3)),), {"quant_axis": 1}, None)])
    add("fake_quantize_range_abs_max", lambda rng: [((
        _x(rng), np.float32(0.5)), {}, None)])
    add("fake_quantize_moving_average_abs_max", lambda rng: [((
        _x(rng), np.float32(0.0), np.float32(0.0)), {}, None)])
    add("fake_quantize_dequantize_moving_average_abs_max", lambda rng: [((
        _x(rng), np.float32(0.0), np.float32(0.0)), {}, None)])
    add("moving_average_abs_max_scale", lambda rng: [((
        _x(rng), np.float32(0.0), np.float32(0.0)), {}, None)])
    add("quantize_linear", lambda rng: [((
        _x(rng), np.float32(0.05)), {}, None)])
    add("dequantize_linear", lambda rng: [((
        rng.integers(-127, 127, (3, 4)).astype(np.int32),
        np.float32(0.05)), {},
        lambda q, s, **k: q.astype(np.float32) * s)])
    add("fake_dequantize_max_abs", lambda rng: [((
        _x(rng), np.float32(127.0)), {},
        lambda x, s, **k: x * s / 127.0)])
    add("fake_channel_wise_dequantize_max_abs", lambda rng: [((
        _x(rng, (3, 4)), _pos(rng, (3,))), {}, None)])
    add("weight_quantize", lambda rng: [((_x(rng, (8, 4)),), {}, None)])
    add("weight_dequantize", lambda rng: [((
        rng.integers(-127, 127, (8, 4)).astype(np.int8),
        _pos(rng, (4,))), {},
        lambda w, s, **k: w.astype(np.float32) * s[None, :])])
    add("weight_only_linear", lambda rng: [((
        _x(rng, (3, 8)), rng.integers(-127, 127, (8, 4)).astype(np.int8),
        _pos(rng, (4,))), {}, None)])
    add("llm_int8_linear", lambda rng: [((
        _x(rng, (3, 8)), rng.integers(-127, 127, (8, 4)).astype(np.int8),
        _pos(rng, (4,))), {}, None)])

    # ---- detection family (static in-graph ops; run-only, domain tests
    # in tests/test_legacy_ops.py carry the semantics) ----
    def boxes4(rng, n=6):
        lo = rng.random((n, 2)).astype(np.float32) * 10
        wh = rng.random((n, 2)).astype(np.float32) * 10 + 1
        return np.concatenate([lo, lo + wh], -1)

    add("deform_conv2d", lambda rng: [((
        _x(rng, (1, 2, 4, 4)), np.zeros((1, 18, 4, 4), np.float32),
        _x(rng, (3, 2, 3, 3))), {"padding": 1}, None)])
    add("psroi_pool", lambda rng: [((
        _x(rng, (1, 8, 4, 4)), np.array([[0, 0, 4, 4]], np.float32)),
        {"output_size": 2}, None)])
    add("prroi_pool", lambda rng: [((
        _x(rng, (1, 2, 4, 4)), np.array([[0, 0, 4, 4]], np.float32)),
        {"output_size": 2}, None)])
    add("prior_box", lambda rng: [((
        np.zeros((1, 2, 2, 2), np.float32),
        np.zeros((1, 3, 16, 16), np.float32), [4.0]), {}, None)])
    add("density_prior_box", lambda rng: [((
        np.zeros((1, 2, 2, 2), np.float32),
        np.zeros((1, 3, 16, 16), np.float32), [2], [4.0], [1.0]),
        {}, None)])
    add("anchor_generator", lambda rng: [((
        np.zeros((1, 2, 2, 2), np.float32), [8.0], [1.0]), {}, None)])
    add("yolo_box", lambda rng: [((
        _x(rng, (1, 21, 2, 2)), np.array([[32, 32]], i64),
        [4, 4, 8, 8, 16, 16], 2), {}, None)])
    add("yolo_loss", lambda rng: [((
        _x(rng, (1, 21, 2, 2)),
        np.abs(_x(rng, (1, 2, 4))) % 0.8 + 0.1,
        rng.integers(0, 2, (1, 2)).astype(i64),
        [4, 4, 8, 8, 16, 16], [0, 1, 2], 2), {}, None)])
    add("matrix_nms", lambda rng: [((
        boxes4(rng)[None], rng.random((1, 2, 6)).astype(np.float32)),
        {}, None)])
    add("multiclass_nms", lambda rng: [((
        boxes4(rng)[None], rng.random((1, 2, 6)).astype(np.float32)),
        {}, None)])
    add("generate_proposals", lambda rng: [((
        rng.random((1, 2, 2, 2)).astype(np.float32),
        _x(rng, (1, 8, 2, 2)), np.array([[16.0, 16.0]], np.float32),
        rng.random((2, 2, 2, 4)).astype(np.float32) * 8),
        {"pre_nms_top_n": 6, "post_nms_top_n": 3}, None)])
    add("collect_fpn_proposals", lambda rng: [((
        [boxes4(rng, 3), boxes4(rng, 3)],
        [rng.random(3).astype(np.float32),
         rng.random(3).astype(np.float32)], 4), {}, None)])
    add("box_clip", lambda rng: [((
        boxes4(rng), np.array([[16.0, 16.0, 1.0]], np.float32)),
        {}, None)])
    add("iou_similarity", lambda rng: [((
        boxes4(rng, 3), boxes4(rng, 4)), {}, None)])
    add("target_assign", lambda rng: [((
        _x(rng, (3, 2)), np.array([0, -1, 2, 1], i64)), {}, None)])
    add("mine_hard_examples", lambda rng: [((
        rng.random(8).astype(np.float32),
        np.array([0, -1, -1, 1, -1, -1, -1, -1], i64)), {}, None)])
    add("ssd_loss", lambda rng: [((
        _x(rng, (6, 4)) * 0.1, _x(rng, (6, 3)),
        np.array([[0, 0, 0.4, 0.4], [0.5, 0.5, 0.9, 0.9]], np.float32),
        np.array([1, 2], i64), rng.random((6, 4)).astype(np.float32)),
        {}, None)])
    add("detection_output", lambda rng: [((
        _x(rng, (1, 6, 4)) * 0.1, rng.random((1, 6, 3)).astype(np.float32),
        rng.random((6, 4)).astype(np.float32)), {}, None)])
    add("polygon_box_transform", lambda rng: [((
        np.ones((1, 8, 2, 2), np.float32),), {}, None)])
    add("rpn_target_assign", lambda rng: [((
        boxes4(rng), boxes4(rng, 2)), {}, None)])
    add("retinanet_target_assign", lambda rng: [((
        boxes4(rng), boxes4(rng, 2), np.array([1, 3], i64)), {}, None)])
    add("generate_proposal_labels", lambda rng: [((
        boxes4(rng), boxes4(rng, 2), np.array([1, 2], i64)), {}, None)])
    add("box_decoder_and_assign", lambda rng: [((
        boxes4(rng, 4), np.tile(np.asarray(
            [[0.1, 0.1, 0.2, 0.2]], np.float32), (4, 1)),
        _x(rng, (4, 8)) * 0.1, rng.random((4, 2)).astype(np.float32)),
        {}, None)])
    add("roi_perspective_transform", lambda rng: [((
        _x(rng, (1, 2, 8, 8)),
        np.array([[1, 1, 6, 1, 6, 6, 1, 6]], np.float32), 4, 4),
        {}, None)])
    add("correlation", lambda rng: [((
        _x(rng, (1, 2, 5, 5)), _x(rng, (1, 2, 5, 5))),
        {"max_displacement": 1}, None)])
    add("bilateral_slice", lambda rng: [((
        _x(rng, (1, 3, 4, 4)), rng.random((1, 4, 4)).astype(np.float32),
        _x(rng, (1, 12, 2, 2, 2))), {"has_offset": True}, None)])
    add("retinanet_detection_output", lambda rng: [((
        [_x(rng, (1, 4, 4)) * 0.1],
        [rng.random((1, 4, 3)).astype(np.float32)],
        [boxes4(rng, 4)], None), {}, None)])

    # ---- decode family ----
    add("linear_chain_crf", lambda rng: [((
        _x(rng, (2, 4, 3)), _x(rng, (5, 3)),
        rng.integers(0, 3, (2, 4)).astype(i64)), {}, None)])
    add("crf_decoding", lambda rng: [((
        _x(rng, (2, 4, 3)), _x(rng, (5, 3))), {}, None)])
    add("ctc_align", lambda rng: [((
        rng.integers(0, 3, (2, 6)).astype(i64),), {}, None)])
    add("ctc_greedy_decoder", lambda rng: [((
        _x(rng, (2, 5, 4)),), {}, None)])
    add("warpctc", lambda rng: [((
        _x(rng, (6, 2, 5)), rng.integers(1, 5, (2, 2)).astype(i64),
        _lens(6, 6), _lens(2, 2)), {}, None)])  # [T, B, K] time-major
    add("beam_search", lambda rng: [((
        rng.integers(0, 3, (1, 2)).astype(i64),
        _x(rng, (1, 2)), None,
        np.log(rng.random((1, 2, 4)).astype(np.float32) + 0.1), 2, 3),
        {}, None)])
    add("gather_tree", lambda rng: [((
        rng.integers(0, 5, (3, 1, 2)).astype(i64),
        rng.integers(0, 2, (3, 1, 2)).astype(i64)), {}, None)])
    add("beam_search_decode", lambda rng: [((
        rng.integers(0, 5, (3, 1, 2)).astype(i64),
        rng.integers(0, 2, (3, 1, 2)).astype(i64)), {}, None)])
    add("edit_distance", lambda rng: [((
        rng.integers(0, 5, (2, 4)).astype(i64),
        rng.integers(0, 5, (2, 3)).astype(i64)), {}, None)])
    add("rnnt_loss", lambda rng: [((
        _x(rng, (1, 3, 2, 4)), np.array([[1]], i64),
        _lens(3), _lens(1)), {}, None)])
    add("viterbi_decode", lambda rng: [((
        _x(rng, (1, 4, 3)), _x(rng, (3, 3)), _lens(4)), {}, None)])

    # ---- MoE infra ----
    add("number_count", lambda rng: [((
        rng.integers(0, 4, 8).astype(i64), 4), {},
        lambda v, n, **k: np.bincount(v, minlength=n))])
    add("expert_count", lambda rng: [((
        rng.integers(0, 3, 8).astype(i64), 3), {},
        lambda v, n, **k: np.bincount(v, minlength=n))])
    add("assign_pos", lambda rng: [((
        rng.integers(0, 3, 6).astype(i64), _lens(2, 4, 6)), {}, None)])
    add("limit_by_capacity", lambda rng: [((
        np.array([5, 1, 3], i64), np.array([2, 2, 2], i64)), {},
        lambda e, c, **k: np.minimum(e, c))])
    add("prune_gate_by_capacity", lambda rng: [((
        rng.integers(0, 2, 6).astype(i64), np.array([3, 3], i64), 2),
        {}, None)])
    add("random_routing", lambda rng: [((
        rng.integers(0, 4, (4, 2)).astype(i64),
        rng.random((4, 2)).astype(np.float32),
        rng.random(4).astype(np.float32)), {}, None)])

    # ---- fused surface ----
    add("fused_rms_norm", lambda rng: [((
        _x(rng, (2, 4, 8)), _pos(rng, (8,))), {}, None)])
    add("fused_layer_norm", lambda rng: [((
        _x(rng, (2, 4, 8)), _pos(rng, (8,)), _x(rng, (8,))), {}, None)])
    add("fused_linear", lambda rng: [((
        _x(rng, (3, 4)), _x(rng, (4, 5))), {}, None)])
    add("fused_matmul_bias", lambda rng: [((
        _x(rng, (3, 4)), _x(rng, (4, 5)), _x(rng, (5,))), {},
        lambda a, b, c, **k: a @ b + c)])
    add("fused_gemm_epilogue", lambda rng: [((
        _x(rng, (3, 4)), _x(rng, (4, 5)), _x(rng, (5,))),
        {"activation": "relu"},
        lambda a, b, c, **k: np.maximum(a @ b + c, 0))])
    add("fused_linear_activation", lambda rng: [((
        _x(rng, (3, 4)), _x(rng, (4, 5)), _x(rng, (5,))),
        {"activation": "relu"}, None)])
    add("fused_bias_act", lambda rng: [((
        _x(rng, (3, 4)), _x(rng, (4,))), {"act_method": "relu"},
        lambda x, b, **k: np.maximum(x + b, 0))])
    add("fused_dropout_add", lambda rng: [((
        _x(rng, (3, 4)), _x(rng, (3, 4))), {"p": 0.0},
        lambda x, y, **k: x + y)])
    add("fused_feedforward", lambda rng: [((
        _x(rng, (2, 3, 8)), _x(rng, (8, 16)), _x(rng, (16, 8))),
        {"dropout1_rate": 0.0, "dropout2_rate": 0.0}, None)])
    add("fused_attention", lambda rng: [((
        _x(rng, (2, 3, 8)), _x(rng, (3, 2, 4, 8)), _x(rng, (8, 8))),
        {"dropout_rate": 0.0, "attn_dropout_rate": 0.0,
         "pre_layer_norm": True}, None)])
    add("fused_gate_attention", lambda rng: [((
        _x(rng, (2, 3, 8)),),
        {"qkv_weight": _x(rng, (3, 2, 4, 8)), "merge_qkv": True,
         "has_gating": False}, None)])
    add("fused_bias_dropout_residual_layer_norm", lambda rng: [((
        _x(rng, (2, 3, 8)), _x(rng, (2, 3, 8))),
        {"dropout_rate": 0.0}, None)])
    add("fused_bn_add_act", lambda rng: [((
        _x(rng, (1, 3, 4, 4)), _x(rng, (1, 3, 4, 4)),
        np.zeros(3, np.float32), np.ones(3, np.float32),
        np.ones(3, np.float32), np.zeros(3, np.float32)), {}, None)])
    add("resnet_unit", lambda rng: [((
        _x(rng, (1, 2, 4, 4)), _x(rng, (3, 2, 3, 3)),
        np.ones(3, np.float32), np.zeros(3, np.float32),
        np.zeros(3, np.float32), np.ones(3, np.float32)), {}, None)])
    add("masked_multihead_attention", lambda rng: [((
        _x(rng, (2, 24)), np.zeros((2, 2, 2, 4, 4), np.float32)),
        {"seq_lens": np.zeros(2, i64)}, None)])
    add("variable_length_memory_efficient_attention", lambda rng: [((
        _x(rng, (1, 2, 4, 4)), _x(rng, (1, 2, 4, 4)),
        _x(rng, (1, 2, 4, 4))), {"seq_lens": _lens(3)}, None)])
    add("fused_moe", lambda rng: [((
        _x(rng, (2, 3, 8)), _x(rng, (8, 4)), _x(rng, (4, 8, 16)),
        _x(rng, (4, 16, 8))), {}, None)])
    add("fused_ec_moe", lambda rng: [((
        _x(rng, (2, 3, 8)), _x(rng, (8, 4)), _x(rng, (4, 8, 16)),
        _x(rng, (4, 16, 8))), {}, None)])
    add("softmax_mask_fuse", lambda rng: [((
        _x(rng, (1, 2, 3, 4)), np.zeros((1, 1, 3, 4), np.float32)),
        {}, None)])
    add("softmax_mask_fuse_upper_triangle", lambda rng: [((
        _x(rng, (1, 2, 4, 4)),), {}, None)])
    add("fused_multi_head_attention", lambda rng: [((
        _x(rng, (2, 3, 8)), _x(rng, (8, 24))), {"num_heads": 2}, None)])
    add("fused_rotary_position_embedding", lambda rng: [((
        _x(rng, (1, 4, 2, 8)),), {}, None)])
    add("fusion_gru", lambda rng: [((
        _x(rng, (2, 4, 3)), _x(rng, (3, 12)), _x(rng, (4, 12))),
        {}, None)])
    add("fusion_lstm", lambda rng: [((
        _x(rng, (2, 4, 3)), _x(rng, (3, 16)), _x(rng, (4, 16))),
        {}, None)])
    add("multi_gru", lambda rng: [((
        _x(rng, (2, 4, 3)),
        [_x(rng, (3, 12)), _x(rng, (3, 12))],
        [_x(rng, (4, 12)), _x(rng, (4, 12))]), {}, None)])
    add("gru_unit", lambda rng: [((
        _x(rng, (2, 12)), _x(rng, (2, 4)), _x(rng, (4, 12))), {}, None)])
    add("lstm_unit", lambda rng: [((
        _x(rng, (2, 16)), _x(rng, (2, 4))), {}, None)])

    # ---- optimizer update kernels ----
    z4 = lambda: np.zeros(4, np.float32)
    g4 = lambda rng: (_x(rng, (4,)) * 0.1).astype(np.float32)
    p4 = lambda rng: _pos(rng, (4,))
    add("sgd_update", lambda rng: [((p4(rng), g4(rng)), {}, None)])
    add("momentum_update", lambda rng: [((p4(rng), g4(rng), z4()),
                                         {}, None)])
    add("adagrad_update", lambda rng: [((p4(rng), g4(rng), z4()),
                                        {}, None)])
    add("decayed_adagrad_update", lambda rng: [((p4(rng), g4(rng), z4()),
                                                {}, None)])
    add("proximal_adagrad_update", lambda rng: [((
        p4(rng), g4(rng), p4(rng)), {}, None)])
    add("proximal_gd_update", lambda rng: [((p4(rng), g4(rng)), {}, None)])
    add("adadelta_update", lambda rng: [((p4(rng), g4(rng), z4(), z4()),
                                         {}, None)])
    add("rmsprop_update", lambda rng: [((p4(rng), g4(rng), z4(), z4()),
                                        {}, None)])
    add("adamax_update", lambda rng: [((
        p4(rng), g4(rng), z4(), z4(), np.float32(0.9)), {}, None)])
    add("ftrl_update", lambda rng: [((
        p4(rng), g4(rng), p4(rng), z4()), {}, None)])
    add("adam_update", lambda rng: [((
        p4(rng), g4(rng), z4(), z4(), np.float32(0.9),
        np.float32(0.999)), {}, None)])
    add("adamw_update", lambda rng: [((
        p4(rng), g4(rng), z4(), z4(), np.float32(0.9),
        np.float32(0.999)), {}, None)])
    add("nadam_update", lambda rng: [((
        p4(rng), g4(rng), z4(), z4(), np.float32(0.9),
        np.float32(0.999)), {}, None)])
    add("radam_update", lambda rng: [((
        p4(rng), g4(rng), z4(), z4(), np.float32(0.9),
        np.float32(0.999), np.float32(1.0)), {}, None)])
    add("lamb_update", lambda rng: [((
        p4(rng), g4(rng), z4(), z4(), np.float32(0.9),
        np.float32(0.999)), {}, None)])
    add("lars_momentum_update", lambda rng: [((p4(rng), g4(rng), z4()),
                                              {}, None)])
    add("sparse_momentum_update", lambda rng: [((
        _pos(rng, (5, 3)), _x(rng, (2, 3)), np.zeros((5, 3), np.float32),
        np.array([1, 3], i64)), {}, None)])
    add("dgc_momentum_update", lambda rng: [((
        p4(rng), g4(rng), z4(), z4()), {}, None)])

    # ---- image transforms (host numpy kernels) ----
    def img(rng):
        return (rng.random((6, 6, 3)) * 255).astype(np.uint8)

    add("adjust_brightness", lambda rng: [((img(rng), 1.2), {}, None)])
    add("adjust_contrast", lambda rng: [((img(rng), 0.8), {}, None)])
    add("adjust_saturation", lambda rng: [((img(rng), 1.5), {}, None)])
    add("adjust_hue", lambda rng: [((img(rng), 0.1), {}, None)])
    add("to_grayscale", lambda rng: [((img(rng),), {}, None)])
    add("rotate", lambda rng: [((img(rng), 30.0), {}, None)])
    add("perspective", lambda rng: [((
        img(rng), [[0, 0], [5, 0], [5, 5], [0, 5]],
        [[0, 0], [5, 1], [5, 5], [0, 4]]), {}, None)])
    add("erase", lambda rng: [((img(rng), 1, 1, 2, 2, 0), {}, None)])
    add("solarize", lambda rng: [((img(rng),), {}, None)])
    add("posterize", lambda rng: [((img(rng), 4), {}, None)])
    add("equalize", lambda rng: [((img(rng),), {}, None)])
    add("autocontrast", lambda rng: [((img(rng),), {}, None)])
    add("gaussian_blur", lambda rng: [((img(rng), 3), {}, None)])
    add("img_crop", lambda rng: [((img(rng), 1, 1, 3, 3), {}, None)])
    add("img_normalize", lambda rng: [((
        img(rng).astype(np.float32).tolist(), [0.5] * 3, [0.5] * 3),
        {"data_format": "HWC"}, None)])  # nested list: host transform
    add("img_pad", lambda rng: [((img(rng), 2), {}, None)])
    add("center_crop", lambda rng: [((img(rng), 4), {}, None)])
    add("resize", lambda rng: [((img(rng), 4), {}, None)])

    # ---- legacy singles ----
    add("addbmm", lambda rng: [((
        np.zeros((3, 2), np.float32), _x(rng, (2, 3, 4)),
        _x(rng, (2, 4, 2))), {},
        lambda i, a, b, **k: i + np.einsum("bik,bkj->ij", a, b))])
    add("reduce_as", lambda rng: [((
        _x(rng, (2, 3, 4)), np.zeros((3, 1), np.float32)), {},
        lambda x, t, **k: x.sum(0).sum(-1, keepdims=True))])
    add("pca_lowrank", lambda rng: [((_x(rng, (8, 5)),), {"q": 3}, None)])
    add("im2col", lambda rng: [((
        _x(rng, (1, 2, 4, 4)), 2), {"stride": 2}, None)])
    add("space_to_depth", lambda rng: [((
        _x(rng, (1, 2, 4, 4)), 2), {}, None)])
    add("depth_to_space", lambda rng: [((
        _x(rng, (1, 8, 2, 2)), 2), {}, None)])
    add("affine_channel", lambda rng: [((
        _x(rng, (1, 3, 2, 2)), _pos(rng, (3,)), _x(rng, (3,))),
        {}, None)])
    add("data_norm", lambda rng: [((
        _x(rng, (4, 3)), np.full(3, 10.0, np.float32),
        np.zeros(3, np.float32), np.full(3, 20.0, np.float32)),
        {}, None)])
    add("fill_any", lambda rng: [((
        np.zeros((2, 2), np.float32), np.float32(7.0)), {},
        lambda x, v, **k: np.full_like(x, 7.0))])
    add("fill_any_like", lambda rng: [((
        np.zeros((2, 2), np.float32), 3.0), {},
        lambda x, v, **k: np.full_like(x, 3.0))])
    add("partial_concat", lambda rng: [((
        [_x(rng, (2, 4)), _x(rng, (2, 4))], 1, 2), {}, None)])
    add("partial_sum", lambda rng: [((
        [_x(rng, (2, 4)), _x(rng, (2, 4))], 0, 2), {}, None)])
    add("batch_fc", lambda rng: [((
        _x(rng, (2, 3, 4)), _x(rng, (2, 4, 5))), {},
        lambda x, w, **k: np.einsum("sbi,sio->sbo", x, w))])
    add("cvm", lambda rng: [((
        _x(rng, (3, 5)), np.abs(_x(rng, (3, 2)))), {}, None)])
    add("sampling_id", lambda rng: [((
        rng.random((3, 4)).astype(np.float32) + 0.1,), {}, None)])
    add("uniform_random_batch_size_like", lambda rng: [((
        np.zeros((5, 2), np.float32), [1, 3]), {}, None)])
    add("gaussian_random_batch_size_like", lambda rng: [((
        np.zeros((5, 2), np.float32), [1, 3]), {}, None)])
    add("fill_constant_batch_size_like", lambda rng: [((
        np.zeros((5, 2), np.float32), [1, 3], "float32", 2.0), {},
        lambda x, s, d, v, **k: np.full((5, 3), 2.0, np.float32))])
    add("dropout_nd", lambda rng: [((
        np.ones((2, 3), np.float32), 0.0), {"axis": 0},
        lambda x, p, **k: x)])
    add("fused_embedding_seq_pool", lambda rng: [((
        np.eye(4, dtype=np.float32),
        rng.integers(0, 4, (2, 3)).astype(i64)), {}, None)])
    add("nonzero_static", lambda rng: [((
        (np.abs(_x(rng, (3, 3))) > 0.5).astype(np.float32), 4),
        {}, None)])
    add("fill_diagonal_tensor", lambda rng: [((
        np.zeros((3, 3), np.float32), _x(rng, (3,))), {}, None)])
    add("l1_norm", lambda rng: [((_x(rng),), {},
                                 lambda x, **k: np.abs(x).sum())])
    add("share_data", lambda rng: [((_x(rng),), {}, lambda x, **k: x)])
    add("bilinear_tensor_product", lambda rng: [((
        _x(rng, (2, 3)), _x(rng, (2, 4)), _x(rng, (5, 3, 4))), {},
        lambda x, y, w, **k: np.einsum("bi,kij,bj->bk", x, w, y))])
    add("fc", lambda rng: [((
        _x(rng, (2, 4)), 3), {"weight": _x(rng, (4, 3))}, None)])
    add("match_matrix_tensor", lambda rng: [((
        _x(rng, (2, 3, 4)), _x(rng, (2, 5, 6)), _x(rng, (4, 2, 6))),
        {}, None)])
    add("sequence_topk_avg_pooling", lambda rng: [((
        _x(rng, (2, 6)), [1, 3]), {}, None)])
    add("rank_attention", lambda rng: [((
        _x(rng, (3, 4)),
        np.array([[0, 1, -1, 0, -1], [1, 0, -1, -1, -1],
                  [2, 2, 0, 1, 0]], i64),
        _x(rng, (36, 5))), {"max_rank": 3}, None)])
    add("tree_conv", lambda rng: [((
        _x(rng, (1, 4, 3)), np.array([[[0, 1], [0, 2], [1, 3]]], i64),
        _x(rng, (3, 3, 6))), {}, None)])
    add("var_conv_2d", lambda rng: [((
        _x(rng, (2, 1, 4, 4)), _lens(3, 4), _lens(4, 2),
        _x(rng, (2, 1, 3, 3))), {}, None)])
    add("exprel", lambda rng: [((_x(rng),), {}, None)])
    add("multigammaln", lambda rng: [((_pos(rng) + 1.0, 2), {}, None)])
    add("contiguous", lambda rng: [((_x(rng),), {}, lambda x, **k: x)])
    add("soft_relu", lambda rng: [((_x(rng),), {},
                                   lambda x, **k: np.log1p(np.exp(x)))])
    add("brelu", lambda rng: [((_x(rng) * 10,), {},
                               lambda x, **k: np.clip(x, 0, 24))])

    # ---- metric functionals ----
    add("accuracy", lambda rng: [((
        rng.random((6, 3)).astype(np.float32),
        rng.integers(0, 3, (6, 1)).astype(i64)), {}, None)])
    add("auc", lambda rng: [((
        rng.random(8).astype(np.float32),
        rng.integers(0, 2, 8).astype(i64)), {}, None)])
    add("precision_recall", lambda rng: [((
        rng.random((6, 3)).astype(np.float32),
        rng.integers(0, 3, 6).astype(i64)), {}, None)])
    add("positive_negative_pair", lambda rng: [((
        rng.random(6).astype(np.float32),
        rng.integers(0, 2, 6).astype(i64),
        np.zeros(6, i64)), {}, None)])

    # ---- older unswept nn composites ----
    add("conv2d_transpose", lambda rng: [((
        _x(rng, (1, 3, 4, 4)), _x(rng, (3, 2, 3, 3))), {}, None)])
    add("conv1d_transpose", lambda rng: [((
        _x(rng, (1, 3, 6)), _x(rng, (3, 2, 3))), {}, None)])
    add("conv3d_transpose", lambda rng: [((
        _x(rng, (1, 2, 3, 3, 3)), _x(rng, (2, 2, 2, 2, 2))), {}, None)])
    add("depthwise_conv2d", lambda rng: [((
        _x(rng, (1, 3, 4, 4)), _x(rng, (3, 1, 3, 3))),
        {"padding": 1}, None)])
    add("depthwise_conv2d_transpose", lambda rng: [((
        _x(rng, (1, 3, 4, 4)), _x(rng, (3, 1, 3, 3))), {}, None)])
    add("conv2d_fusion", lambda rng: [((
        _x(rng, (1, 3, 4, 4)), _x(rng, (4, 3, 3, 3))),
        {"padding": 1}, None)])
    add("batch_norm", lambda rng: [((
        _x(rng, (2, 3, 4, 4)), np.zeros(3, np.float32),
        np.ones(3, np.float32)), {}, None)])
    add("sync_batch_norm", lambda rng: [((
        _x(rng, (2, 3, 4, 4)), np.zeros(3, np.float32),
        np.ones(3, np.float32)), {}, None)])
    add("layer_norm", lambda rng: [((
        _x(rng, (2, 6)), [6]), {}, None)])
    add("group_norm", lambda rng: [((
        _x(rng, (2, 4, 3, 3)), 2), {}, None)])
    add("local_response_norm", lambda rng: [((
        _x(rng, (1, 4, 4, 4)), 3), {}, None)])
    add("pool2d", lambda rng: [((
        _x(rng, (1, 2, 4, 4)), 2, "avg"), {}, None)])
    add("pool3d", lambda rng: [((
        _x(rng, (1, 2, 4, 4, 4)), 2, "max"), {}, None)])
    add("lp_pool2d", lambda rng: [((
        _x(rng, (1, 2, 4, 4)), 2.0, 2), {}, None)])
    add("max_pool2d_with_index", lambda rng: [((
        _x(rng, (1, 2, 4, 4)), 2), {}, None)])
    add("max_pool3d_with_index", lambda rng: [((
        _x(rng, (1, 2, 4, 4, 4)), 2), {}, None)])
    add("maxout", lambda rng: [((
        _x(rng, (1, 4, 3, 3)), 2), {}, None)])
    add("prelu", lambda rng: [((
        _x(rng, (2, 4)), np.float32(0.2)), {},
        lambda x, a, **k: np.where(x >= 0, x, a * x))])
    add("pad2d", lambda rng: [((
        _x(rng, (1, 2, 3, 3)), [1, 1, 1, 1]), {}, None)])
    add("pad3d", lambda rng: [((
        _x(rng, (1, 2, 3, 3, 3)), [1, 1, 1, 1, 1, 1]), {}, None)])
    add("pixel_shuffle", lambda rng: [((
        _x(rng, (1, 8, 2, 2)), 2), {}, None)])
    add("pixel_unshuffle", lambda rng: [((
        _x(rng, (1, 2, 4, 4)), 2), {}, None)])
    add("channel_shuffle", lambda rng: [((
        _x(rng, (1, 4, 3, 3)), 2), {}, None)])
    add("grid_sample", lambda rng: [((
        _x(rng, (1, 2, 4, 4)),
        (rng.random((1, 3, 3, 2)).astype(np.float32) * 2 - 1)),
        {}, None)])
    add("affine_grid", lambda rng: [((
        _x(rng, (1, 2, 3)), [1, 2, 4, 4]), {}, None)])
    add("fold", lambda rng: [((
        _x(rng, (1, 8, 4)), [4, 4], [2, 2]), {"strides": 2}, None)])
    add("bilinear", lambda rng: [((
        _x(rng, (2, 3)), _x(rng, (2, 4)), _x(rng, (5, 3, 4))),
        {}, None)])
    add("flash_attention", lambda rng: [((
        _x(rng, (1, 4, 2, 8)), _x(rng, (1, 4, 2, 8)),
        _x(rng, (1, 4, 2, 8))), {}, None)])
    add("attention_probs", lambda rng: [((
        _x(rng, (1, 2, 3, 4)), _x(rng, (1, 2, 3, 4))), {}, None)])
    add("ctc_loss", lambda rng: [((
        _x(rng, (6, 2, 5)), rng.integers(1, 5, (2, 2)).astype(i64),
        _lens(6, 6), _lens(2, 2)), {}, None)])  # [T, B, C] paddle layout
    add("dice_loss", lambda rng: [((
        rng.random((2, 4, 1)).astype(np.float32),
        rng.integers(0, 2, (2, 4, 1)).astype(i64)), {}, None)])
    add("gaussian_nll_loss", lambda rng: [((
        _x(rng, (4,)), _x(rng, (4,)), _pos(rng, (4,))), {}, None)])
    add("hinge_embedding_loss", lambda rng: [((
        _x(rng, (4,)),
        (rng.integers(0, 2, 4) * 2 - 1).astype(np.float32)), {}, None)])
    add("cosine_embedding_loss", lambda rng: [((
        _x(rng, (2, 4)), _x(rng, (2, 4)),
        (rng.integers(0, 2, 2) * 2 - 1).astype(np.float32)), {}, None)])
    add("margin_ranking_loss", lambda rng: [((
        _x(rng, (4,)), _x(rng, (4,)),
        (rng.integers(0, 2, 4) * 2 - 1).astype(np.float32)), {}, None)])
    add("multi_label_soft_margin_loss", lambda rng: [((
        _x(rng, (2, 4)), rng.integers(0, 2, (2, 4)).astype(np.float32)),
        {}, None)])
    add("poisson_nll_loss", lambda rng: [((
        _x(rng, (4,)), _pos(rng, (4,))), {}, None)])
    add("max_unpool1d", lambda rng: [((
        _x(rng, (1, 2, 2)), np.array([[[0, 2]]], i64) *
        np.ones((1, 2, 2), i64), 2), {}, None)])
    add("max_unpool2d", lambda rng: [((
        _x(rng, (1, 2, 2, 2)),
        rng.integers(0, 4, (1, 2, 2, 2)).astype(i64), 2), {}, None)])
    add("max_unpool3d", lambda rng: [((
        _x(rng, (1, 1, 2, 2, 2)),
        rng.integers(0, 8, (1, 1, 2, 2, 2)).astype(i64), 2), {}, None)])
    add("npair_loss", lambda rng: [((
        _x(rng, (4, 8)), _x(rng, (4, 8)),
        rng.integers(0, 2, 4).astype(i64)), {}, None)])
    add("margin_cross_entropy", lambda rng: [((
        (rng.random((4, 6)).astype(np.float32) * 2 - 1) * 0.9,
        rng.integers(0, 6, 4).astype(i64)), {}, None)])
    add("rank_loss", lambda rng: [((
        rng.integers(0, 2, 4).astype(np.float32), _x(rng, (4,)),
        _x(rng, (4,))), {}, None)])
    add("multi_margin_loss", lambda rng: [((
        _x(rng, (4, 5)), rng.integers(0, 5, 4).astype(i64)), {}, None)])
    add("triplet_margin_with_distance_loss", lambda rng: [((
        _x(rng, (4, 8)), _x(rng, (4, 8)), _x(rng, (4, 8))), {}, None)])
    add("adaptive_log_softmax_with_loss", lambda rng: [((
        _x(rng, (4, 8)), rng.integers(0, 5, 4).astype(i64),
        _x(rng, (8, 3)),
        [(_x(rng, (8, 4)), _x(rng, (4, 3)))], [2, 5]), {}, None)])
    add("center_loss", lambda rng: [((
        _x(rng, (4, 8)), rng.integers(0, 3, 4).astype(i64),
        np.zeros((3, 8), np.float32)), {}, None)])
    add("teacher_student_sigmoid_loss", lambda rng: [((
        _x(rng, (4,)), rng.random(4).astype(np.float32)), {}, None)])
    add("bpr_loss", lambda rng: [((
        _x(rng, (3, 5)), rng.integers(0, 5, 3).astype(i64)), {}, None)])
    add("cos_sim", lambda rng: [((
        _x(rng, (3, 4)), _x(rng, (3, 4))), {}, None)])
    add("squared_l2_norm", lambda rng: [((_x(rng),), {},
                                         lambda x, **k: (x * x).sum())])
    add("squared_l2_distance", lambda rng: [((
        _x(rng, (3, 4)), _x(rng, (3, 4))), {},
        lambda x, y, **k: ((x - y) ** 2).sum(-1))])
    add("modified_huber_loss", lambda rng: [((
        _x(rng, (4,)), rng.integers(0, 2, 4).astype(np.float32)),
        {}, None)])
    add("identity_loss", lambda rng: [((_x(rng),), {"reduction": "sum"},
                                       lambda x, **k: x.sum())])
    add("hsigmoid_loss", lambda rng: [((
        _x(rng, (3, 6)), rng.integers(0, 4, 3).astype(i64), 4,
        _x(rng, (3, 6))), {}, None)])
    add("chunk_eval", lambda rng: [((
        rng.integers(0, 2, (1, 6)).astype(i64),
        rng.integers(0, 2, (1, 6)).astype(i64)), {}, None)])
    add("cdist", lambda rng: [((
        _x(rng, (3, 4)), _x(rng, (5, 4))), {}, None)])
    add("histogramdd", lambda rng: [((_x(rng, (8, 2)),), {"bins": 3},
                                     None)])
    add("householder_product", lambda rng: [((
        _x(rng, (4, 3)), _x(rng, (3,))), {}, None)])
    add("ormqr", lambda rng: [((
        _x(rng, (4, 3)), _x(rng, (3,)), _x(rng, (4, 4))), {}, None)])
    add("orgqr", lambda rng: [((
        _x(rng, (4, 3)), _x(rng, (3,))), {}, None)])
    add("polar", lambda rng: [((
        _pos(rng), _x(rng)), {},
        lambda a, t, **k: a * np.exp(1j * t))])
    add("as_strided", lambda rng: [((
        _x(rng, (8,)), [2, 3], [3, 1]), {}, None)])
    add("masked_select", lambda rng: [((
        _x(rng, (3, 3)), _x(rng, (3, 3)) > 0), {}, None)])
    add("clip_by_global_norm", lambda rng: [((
        [_x(rng, (3,)), _x(rng, (2, 2))], 1.0), {}, None)])
    add("create_dct", lambda rng: [((4, 8), {}, None)])
    add("fft_frequencies", lambda rng: [((16, 8), {}, None)])
    add("mel_frequencies", lambda rng: [((8,), {}, None)])
    add("compute_fbank_matrix", lambda rng: [((16, 8), {}, None)])
    add("send_uv", lambda rng: [((
        _x(rng, (4, 3)), _x(rng, (4, 3)),
        np.array([0, 1], i64), np.array([1, 2], i64)), {}, None)])
    add("interpolate", lambda rng: [((
        _x(rng, (1, 2, 4, 4)),), {"size": [2, 2], "mode": "bilinear"},
        None)])
    add("upsample", lambda rng: [((
        _x(rng, (1, 2, 2, 2)),), {"scale_factor": 2}, None)])
    add("linear_interp", lambda rng: [((
        _x(rng, (1, 2, 6)),), {"size": [3]}, None)])
    add("bilinear_interp", lambda rng: [((
        _x(rng, (1, 2, 4, 4)),), {"size": [2, 2]}, None)])
    add("nearest_interp", lambda rng: [((
        _x(rng, (1, 2, 4, 4)),), {"scale_factor": 2}, None)])
    add("bicubic_interp", lambda rng: [((
        _x(rng, (1, 2, 4, 4)),), {"size": [2, 2]}, None)])
    add("trilinear_interp", lambda rng: [((
        _x(rng, (1, 2, 4, 4, 4)),), {"size": [2, 2, 2]}, None)])
    add("spp", lambda rng: [((_x(rng, (1, 2, 4, 4)),), {}, None)])
    add("unpool", lambda rng: [((
        _x(rng, (1, 2, 2, 2)),
        rng.integers(0, 4, (1, 2, 2, 2)).astype(i64), 2), {}, None)])
    add("unpool3d", lambda rng: [((
        _x(rng, (1, 1, 2, 2, 2)),
        rng.integers(0, 8, (1, 1, 2, 2, 2)).astype(i64), 2), {}, None)])
    add("log_mel_spectrogram", lambda rng: [((
        _x(rng, (1, 512)),), {"n_fft": 128, "n_mels": 8}, None)])
    add("c_embedding", lambda rng: [((
        _x(rng, (4, 3)), rng.integers(0, 6, (2, 3)).astype(i64)),
        {}, None)])
    add("c_softmax_with_cross_entropy", lambda rng: [((
        _x(rng, (3, 5)), rng.integers(0, 5, 3).astype(i64)), {}, None)])
    return sp


def _r5b_specs():
    """r5 second batch: the sparse surface (COO/CSR operands pass through
    the sweep untouched; outputs unwrap to their values), the remaining
    vision/nn composites, and eager singles. Run-only where the hand tests
    own the semantics."""
    sp = {}

    def add(name, spec):
        sp[name] = spec

    i64 = np.int64

    def coo(rng, shape=(3, 3), nnz=4, chan=None):
        from .. import sparse as S
        idx = np.stack([rng.integers(0, s, nnz) for s in shape])
        # dedupe coordinates (coalesced inputs keep oracles simple)
        keys = set()
        cols = []
        for j in range(nnz):
            k = tuple(int(idx[d, j]) for d in range(len(shape)))
            if k in keys:
                continue
            keys.add(k)
            cols.append(j)
        idx = idx[:, cols]
        vshape = (idx.shape[1],) if chan is None else (idx.shape[1], chan)
        vals = rng.standard_normal(vshape).astype(np.float32)
        return S.sparse_coo_tensor(idx, vals, list(shape))

    # sparse unary/value ops: run-only (values-map semantics)
    for n in ["sparse_abs", "sparse_asin", "sparse_asinh", "sparse_atan",
              "sparse_atanh", "sparse_deg2rad", "sparse_expm1",
              "sparse_log1p", "sparse_neg", "sparse_rad2deg", "sparse_relu",
              "sparse_relu6", "sparse_leaky_relu", "sparse_sin",
              "sparse_sinh", "sparse_sqrt", "sparse_square", "sparse_tan",
              "sparse_tanh", "sparse_softmax", "sparse_coalesce"]:

        def mk():
            def spec(rng):
                t = coo(rng)
                # domain-safe for EVERY member (sqrt/log1p/asin/atanh...):
                # squash into (0.05, 0.95) — seed-proof, not
                # luck-of-the-draw
                vals = np.tanh(np.abs(np.asarray(
                    t.values()._value))) * 0.9 + 0.05
                t.values_._value = __import__("jax").numpy.asarray(
                    vals.astype(np.float32))
                return [((t,), {}, None)]
            return spec
        add(n, mk())

    def _coo_pair(rng):
        from .. import sparse as S
        idx = np.array([[0, 1, 2], [1, 0, 2]])
        a = S.sparse_coo_tensor(idx, rng.standard_normal(3).astype(
            np.float32), [3, 3])
        b = S.sparse_coo_tensor(idx, rng.standard_normal(3).astype(
            np.float32), [3, 3])
        return a, b

    add("sparse_add", lambda rng: [((*_coo_pair(rng),), {}, None)])
    add("sparse_subtract", lambda rng: [((*_coo_pair(rng),), {}, None)])
    add("sparse_multiply", lambda rng: [((*_coo_pair(rng),), {}, None)])
    add("sparse_divide", lambda rng: [((*_coo_pair(rng),), {}, None)])
    add("sparse_matmul", lambda rng: [((
        coo(rng), _x(rng, (3, 2))), {}, None)])
    add("sparse_masked_matmul", lambda rng: [((
        _x(rng, (3, 3)), _x(rng, (3, 3)), coo(rng)), {}, None)])
    add("sparse_mv", lambda rng: [((coo(rng), _x(rng, (3,))), {}, None)])
    add("sparse_addmm", lambda rng: [((
        _x(rng, (3, 2)), coo(rng), _x(rng, (3, 2))), {}, None)])
    add("sparse_sum", lambda rng: [((coo(rng),), {}, None)])
    add("sparse_transpose", lambda rng: [((coo(rng), [1, 0]), {}, None)])
    add("sparse_reshape", lambda rng: [((coo(rng), [9]), {}, None)])
    add("sparse_cast", lambda rng: [((coo(rng), "float32"), {}, None)])
    add("sparse_pow", lambda rng: [((coo(rng), 2.0), {}, None)])

    def voxels(rng):
        from .. import sparse as S
        idx = np.array([[0, 0, 0], [0, 1, 2], [1, 2, 0], [2, 0, 1]])
        return S.sparse_coo_tensor(
            idx, rng.standard_normal((3, 2)).astype(np.float32),
            [1, 4, 4, 4, 2])

    add("sparse_conv3d", lambda rng: [((
        voxels(rng), _x(rng, (3, 3, 3, 2, 3))), {"padding": 1}, None)])
    add("sparse_subm_conv3d", lambda rng: [((
        voxels(rng), _x(rng, (3, 3, 3, 2, 3))), {}, None)])
    add("sparse_max_pool3d", lambda rng: [((voxels(rng), 2), {}, None)])
    add("sparse_batch_norm", lambda rng: [((
        voxels(rng), np.zeros(2, np.float32), np.ones(2, np.float32)),
        {}, None)])
    add("sparse_attention", lambda rng: [((
        _x(rng, (1, 1, 4, 4)), _x(rng, (1, 1, 4, 4)),
        _x(rng, (1, 1, 4, 4)),
        __import__("paddle_tpu").sparse.sparse_csr_tensor(
            np.array([0, 2, 4, 6, 8]), np.array([0, 1, 1, 2, 2, 3, 3, 0]),
            np.ones(8, np.float32), [4, 4])), {}, None)])

    # vision leftovers
    def boxes(rng, n=4):
        lo = rng.random((n, 2)).astype(np.float32) * 8
        wh = rng.random((n, 2)).astype(np.float32) * 8 + 1
        return np.concatenate([lo, lo + wh], -1)

    add("box_iou", lambda rng: [((boxes(rng), boxes(rng, 3)), {}, None)])
    add("nms", lambda rng: [((boxes(rng),), {}, None)])
    add("box_coder", lambda rng: [((
        boxes(rng), np.tile(np.asarray([[0.1, 0.1, 0.2, 0.2]],
                                       np.float32), (4, 1)),
        boxes(rng)), {}, None)])
    add("roi_align", lambda rng: [((
        _x(rng, (1, 2, 8, 8)), np.array([[0, 0, 6, 6]], np.float32),
        np.array([1], i64), 2), {}, None)])
    add("roi_pool", lambda rng: [((
        _x(rng, (1, 2, 8, 8)), np.array([[0, 0, 6, 6]], np.float32),
        np.array([1], i64), 2), {}, None)])
    add("distribute_fpn_proposals", lambda rng: [((
        np.array([[0, 0, 10, 10], [0, 0, 200, 200]], np.float32),
        2, 5, 4, 224), {}, None)])
    add("temporal_shift", lambda rng: [((
        _x(rng, (4, 4, 2, 2)), 2, 0.25), {}, None)])

    # nn leftovers
    add("conv_transpose1d", lambda rng: [((
        _x(rng, (1, 3, 6)), _x(rng, (3, 2, 3))), {}, None)])
    add("conv_transpose2d", lambda rng: [((
        _x(rng, (1, 3, 4, 4)), _x(rng, (3, 2, 3, 3))), {}, None)])
    add("conv_transpose3d", lambda rng: [((
        _x(rng, (1, 2, 3, 3, 3)), _x(rng, (2, 2, 2, 2, 2))), {}, None)])
    add("scaled_dot_product_attention", lambda rng: [((
        _x(rng, (1, 4, 2, 8)), _x(rng, (1, 4, 2, 8)),
        _x(rng, (1, 4, 2, 8))), {}, None)])
    add("sigmoid_focal_loss", lambda rng: [((
        _x(rng, (4, 3)), rng.integers(0, 2, (4, 3)).astype(np.float32)),
        {}, None)])
    add("soft_margin_loss", lambda rng: [((
        _x(rng, (4,)), (rng.integers(0, 2, 4) * 2 - 1).astype(np.float32)),
        {}, None)])
    add("square_error_cost", lambda rng: [((
        _x(rng, (4,)), _x(rng, (4,))), {},
        lambda a, b, **k: (a - b) ** 2)])
    add("triplet_margin_loss", lambda rng: [((
        _x(rng, (4, 8)), _x(rng, (4, 8)), _x(rng, (4, 8))), {}, None)])
    add("spectral_norm", lambda rng: [((
        _x(rng, (4, 5)), _x(rng, (4,)), _x(rng, (5,))), {}, None)])
    add("zeropad2d", lambda rng: [((
        _x(rng, (1, 2, 3, 3)), [1, 1, 1, 1]), {}, None)])
    add("unfold", lambda rng: [((
        _x(rng, (1, 2, 4, 4)), 2), {}, None)])
    add("unfold_axis", lambda rng: [((
        _x(rng, (8,)), 0, 4, 2), {}, None)])
    add("istft", lambda rng: [((
        __import__("paddle_tpu").signal.stft(
            __import__("paddle_tpu").to_tensor(
                rng.standard_normal((1, 256)).astype(np.float32)),
            64, 32), 64, 32), {}, None)])

    # eager/graph singles
    add("sequence_unpad", lambda rng: [((
        _x(rng, (2, 4, 2)), np.array([2, 3], i64)), {}, None)])
    add("lookup_table", lambda rng: [((
        _x(rng, (6, 3)), rng.integers(0, 6, (2, 2)).astype(i64)),
        {}, None)])
    add("lookup_table_v2", lambda rng: [((
        _x(rng, (6, 3)), rng.integers(0, 6, (2, 2)).astype(i64)),
        {}, None)])
    add("send_u_recv", lambda rng: [((
        _x(rng, (4, 3)), np.array([0, 1, 2], i64),
        np.array([1, 2, 0], i64)), {}, None)])
    add("send_ue_recv", lambda rng: [((
        _x(rng, (4, 3)), _x(rng, (3, 3)), np.array([0, 1, 2], i64),
        np.array([1, 2, 0], i64)), {}, None)])
    add("assign_value", lambda rng: [((
        [2, 2], "float32", [1.0, 2.0, 3.0, 4.0]), {}, None)])
    add("tril_indices", lambda rng: [((3, 3), {},
                                      lambda r, c, **k: np.stack(
                                          np.tril_indices(r, 0, c)))])
    add("triu_indices", lambda rng: [((3, 3), {},
                                      lambda r, c, **k: np.stack(
                                          np.triu_indices(r, 0, c)))])
    add("nonzero", lambda rng: [((
        (np.abs(_x(rng, (3, 3))) > 0.7).astype(np.float32),), {}, None)])
    add("isin_1d", lambda rng: [((
        rng.integers(0, 5, 6).astype(i64),
        rng.integers(0, 5, 3).astype(i64)), {}, None)])
    add("sample_neighbors", lambda rng: [((
        np.array([1, 2, 0], i64), np.array([0, 2, 3, 3], i64),
        np.array([0, 1], i64), 2), {}, None)])
    add("graph_sample_neighbors", sp["sample_neighbors"])
    add("weighted_sample_neighbors", lambda rng: [((
        np.array([1, 2, 0], i64), np.array([0, 2, 3, 3], i64),
        np.ones(3, np.float32), np.array([0], i64), 1), {}, None)])
    add("reindex_graph", lambda rng: [((
        np.array([5, 9], i64), np.array([[1, -1], [0, 1]], i64),
        np.array([1, 2], i64)), {}, None)])
    add("graph_reindex", sp["reindex_graph"])
    add("khop_sampler", lambda rng: [((
        np.array([1, 2, 0], i64), np.array([0, 2, 3, 3], i64),
        np.array([0], i64), [1]), {}, None)])
    add("graph_khop_sampler", sp["khop_sampler"])
    add("fused_multi_transformer", lambda rng: [((
        _x(rng, (1, 3, 8)), [ _pos(rng, (8,)) ], [ _x(rng, (8,)) ],
        [ _x(rng, (3, 2, 4, 8)) ], None, [ _x(rng, (8, 8)) ], None,
        [ _pos(rng, (8,)) ], [ _x(rng, (8,)) ], [ _x(rng, (8, 16)) ],
        None, [ _x(rng, (16, 8)) ], None), {"num_heads": 2}, None)])
    return sp
