"""Sweep specs: example calls + numpy oracles for COMPOSITE ops.

The unary/binary factory ops are swept automatically from their category
tag (tests/test_op_sweep.py); everything else needs an example-call spec —
this module attaches them to the ``OpDef`` entries post-import (r3 VERDICT
#6: "extend the schema with an oracle field so the sweep reaches composite
ops"). A spec is ``(rng) -> [(args, kwargs, oracle), ...]`` where ``args``
may contain numpy arrays (converted to Tensors by the sweep) and ``oracle``
is a numpy callable or None (run-only leg).

Two tiers:
* EXPLICIT specs below for ops whose call shape needs thought (windows vs
  scipy, fft vs numpy.fft, sets, scatter family, reductions with axes).
* AUTO specs for simple one-tensor ops (public signature ``(x, name=None)``)
  — forward run + numpy oracle when ``numpy.<name>`` exists, gradient
  finite-difference when differentiable.

Ops with neither (stateful/random/IO/shape-polymorphic) are counted and
reported as unswept in docs/OPS.md.
"""

from __future__ import annotations

import inspect

import numpy as np

__all__ = ["attach_specs", "sweep_coverage"]


def _x(rng, shape=(3, 4)):
    return rng.standard_normal(shape).astype(np.float32)


def _pos(rng, shape=(3, 4)):
    return (rng.random(shape) * 2 + 0.3).astype(np.float32)


# ---------------------------------------------------------------------------
# explicit spec tables
# ---------------------------------------------------------------------------

def _window_specs():
    """Windows vs scipy.signal oracles (sym and periodic legs)."""
    try:
        import scipy.signal as ss
    except ImportError:          # pragma: no cover
        ss = None
    table = {
        "blackman_window": ("blackman", ()),
        "hamming_window": ("hamming", ()),
        "hann_window": ("hann", ()),
        "bartlett_window": ("bartlett", ()),
        "kaiser_window": (("kaiser", 12.0), ()),
        "nuttall_window": ("nuttall", ()),
        "blackman_harris_window": ("blackmanharris", ()),
        "bohman_window": ("bohman", ()),
        "cosine_window": ("cosine", ()),
        "tukey_window": (("tukey", 0.5), ()),
        "gaussian_window": (("gaussian", 7.0), ()),
        "exponential_window": (("exponential", None, 1.0), ()),
        "triang_window": ("triang", ()),
    }
    specs = {}
    for op, (sci_name, extra) in table.items():
        def mk(sci_name=sci_name, extra=extra):
            def spec(rng):
                legs = []
                for M, sym in ((8, True), (9, False)):
                    orc = (None if ss is None else
                           (lambda M=M, sym=sym:
                            ss.get_window(sci_name, M, fftbins=not sym)))
                    legs.append(((M,) + tuple(extra),
                                 {"sym": sym, "dtype": "float32"},
                                 (lambda *_a, _o=orc, **_k: _o())
                                 if orc else None))
                return legs
            return spec
        specs[op] = mk()
    def _gw_oracle(*_a, **_k):
        import scipy.signal as _ss
        return _ss.get_window("hann", 16)
    specs["get_window"] = lambda rng: [
        (("hann", 16), {"dtype": "float32"}, _gw_oracle)]
    specs["general_cosine_window"] = lambda rng: [
        ((8, [0.5, 0.5]), {"dtype": "float32"}, None)]
    specs["general_hamming_window"] = lambda rng: [
        ((8, 0.6), {"dtype": "float32"}, None)]
    specs["taylor_window"] = lambda rng: [((16,), {"dtype": "float32"},
                                           None)]
    return specs


def _fft_specs():
    def o(name):
        return getattr(np.fft, name)
    simple = {}
    for n in ("fft", "ifft", "fftn", "ifftn", "fft2", "ifft2", "rfft",
              "rfft2", "rfftn", "ihfft"):
        simple[n] = (lambda n=n: (lambda rng: [
            ((_x(rng, (4, 8)),), {},
             lambda a, **k: o(n)(a))]))()
    for n in ("irfft", "irfft2", "irfftn", "hfft"):
        simple[n] = (lambda n=n: (lambda rng: [
            ((_x(rng, (4, 8)) + 1j * _x(rng, (4, 8)),), {},
             lambda a, **k: o(n)(a))]))()
    simple["fftshift"] = lambda rng: [((_x(rng, (4, 8)),), {},
                                       lambda a, **k: np.fft.fftshift(a))]
    simple["ifftshift"] = lambda rng: [((_x(rng, (4, 8)),), {},
                                        lambda a, **k: np.fft.ifftshift(a))]
    simple["fftfreq"] = lambda rng: [
        ((8,), {}, lambda *a, **k: np.fft.fftfreq(8).astype(np.float32))]
    simple["rfftfreq"] = lambda rng: [
        ((8,), {}, lambda *a, **k: np.fft.rfftfreq(8).astype(np.float32))]
    return simple


def _set_specs():
    a = np.asarray([3, 1, 2, 3, 5], np.int32)
    b = np.asarray([2, 3, 9], np.int32)
    return {
        "intersect1d": lambda rng: [((a, b), {},
                                     lambda x, y, **k: np.intersect1d(x, y))],
        "setdiff1d": lambda rng: [((a, b), {},
                                   lambda x, y, **k: np.setdiff1d(x, y))],
        "union1d": lambda rng: [((a, b), {},
                                 lambda x, y, **k: np.union1d(x, y))],
        "setxor1d": lambda rng: [((a, b), {},
                                  lambda x, y, **k: np.setxor1d(x, y))],
        "in1d": lambda rng: [((a, b), {},
                              lambda x, y, **k: np.in1d(x, y))],
    }


def _composite_specs():
    """Hand specs for multi-arg / axis ops (numpy oracle where one exists)."""
    sp = {}

    def add(name, spec):
        sp[name] = spec

    add("logdet", lambda rng: [
        (((_x(rng, (3, 3)) @ _x(rng, (3, 3)).T + 3 * np.eye(3, dtype=np.float32)),),
         {}, lambda a, **k: np.log(np.linalg.det(a)))])
    add("vdot", lambda rng: [((_x(rng), _x(rng)), {},
                              lambda a, b, **k: np.vdot(a, b))])
    add("addmv", lambda rng: [
        ((_x(rng, (3,)), _x(rng, (3, 4)), _x(rng, (4,))), {},
         lambda i, m, v, **k: i + m @ v)])
    add("addr", lambda rng: [
        ((_x(rng, (3, 4)), _x(rng, (3,)), _x(rng, (4,))), {},
         lambda i, a, b, **k: i + np.outer(a, b))])
    add("chain_matmul", lambda rng: [
        ((_x(rng, (2, 3)), _x(rng, (3, 4)), _x(rng, (4, 2))), {},
         lambda a, b, c, **k: a @ b @ c)])
    add("float_power", lambda rng: [
        ((_pos(rng), _pos(rng)), {},
         lambda a, b, **k: np.float_power(a, b).astype(np.float32))])
    add("std_mean", lambda rng: [
        ((_x(rng),), {}, lambda a, **k: (np.std(a, ddof=1), np.mean(a)))])
    add("var_mean", lambda rng: [
        ((_x(rng),), {}, lambda a, **k: (np.var(a, ddof=1), np.mean(a)))])
    add("gradient", lambda rng: [
        ((_x(rng, (8,)),), {}, lambda a, **k: np.gradient(a))])
    add("fliplr", lambda rng: [((_x(rng),), {},
                                lambda a, **k: np.fliplr(a))])
    add("flipud", lambda rng: [((_x(rng),), {},
                                lambda a, **k: np.flipud(a))])
    add("rollaxis", lambda rng: [((_x(rng, (2, 3, 4)), 2), {},
                                  lambda a, *r, **k: np.rollaxis(a, 2))])
    add("swapdims", lambda rng: [((_x(rng, (2, 3, 4)), 0, 2), {},
                                  lambda a, *r, **k: np.swapaxes(a, 0, 2))])
    add("narrow", lambda rng: [((_x(rng, (5, 4)), 0, 1, 3), {},
                                lambda a, *r, **k: a[1:4])])
    add("narrow_copy", lambda rng: [((_x(rng, (5, 4)), 0, 1, 3), {},
                                     lambda a, *r, **k: a[1:4])])
    add("split_with_sizes", lambda rng: [
        ((_x(rng, (6, 4)), [2, 4]), {},
         lambda a, *r, **k: (a[:2], a[2:]))])
    add("arctan2", lambda rng: [((_x(rng), _pos(rng)), {},
                                 lambda a, b, **k: np.arctan2(a, b))])
    add("nanargmax", lambda rng: [((_x(rng),), {},
                                   lambda a, **k: np.nanargmax(a))])
    add("nanargmin", lambda rng: [((_x(rng),), {},
                                   lambda a, **k: np.nanargmin(a))])
    add("nanstd", lambda rng: [((_x(rng),), {},
                                lambda a, **k: np.nanstd(a, ddof=1))])
    add("nanvar", lambda rng: [((_x(rng),), {},
                                lambda a, **k: np.nanvar(a, ddof=1))])
    add("histogram_bin_edges", lambda rng: [
        ((_x(rng, (16,)), 4), {},
         lambda a, *r, **k: np.histogram_bin_edges(a, 4,
                                                   (a.min(), a.max())))])
    add("histc", lambda rng: [
        ((_pos(rng, (16,)), 4), {},
         lambda a, *r, **k: np.histogram(a, 4, (a.min(), a.max()))[0])])
    add("betainc", lambda rng: [
        ((_pos(rng), _pos(rng),
          (0.1 + 0.8 * np.random.default_rng(0).random((3, 4))
           ).astype(np.float32)), {}, None)])
    add("true_divide", lambda rng: [((_x(rng), _pos(rng)), {},
                                     lambda a, b, **k: a / b)])
    add("trunc_divide", lambda rng: [((_x(rng), _pos(rng)), {},
                                      lambda a, b, **k: np.trunc(a / b))])
    add("divide_no_nan", lambda rng: [
        ((_x(rng), np.asarray([[1, 0, 2, 0]] * 3, np.float32)), {},
         lambda a, b, **k: np.where(b == 0, 0, a / np.where(b == 0, 1, b)))])
    add("bitwise_invert", lambda rng: [
        ((np.asarray([1, 2, 3], np.int32),), {},
         lambda a, **k: np.invert(a))])
    add("cumulative_sum", lambda rng: [
        ((_x(rng, (8,)),), {}, lambda a, **k: np.cumsum(a))])
    add("cumulative_prod", lambda rng: [
        ((_pos(rng, (6,)),), {}, lambda a, **k: np.cumprod(a))])
    add("clip_by_norm", lambda rng: [
        ((_x(rng), 1.0), {},
         lambda a, *r, **k: a * min(1.0, 1.0 / np.linalg.norm(a)))])
    add("take_along_dim", lambda rng: [
        ((_x(rng, (3, 4)), np.asarray([[0], [1], [2]], np.int32)),
         {"dim": 1},
         lambda a, i, **k: np.take_along_axis(a, i, axis=1))])
    add("permute_dims", lambda rng: [
        ((_x(rng, (2, 3, 4)), (2, 0, 1)), {},
         lambda a, *r, **k: np.transpose(a, (2, 0, 1)))])
    add("index_copy", lambda rng: [
        ((_x(rng, (4, 3)), np.asarray([0, 2], np.int32), _x(rng, (2, 3))),
         {}, lambda a, i, s, **k: _np_index_copy(a, i, s))])
    add("scatter_add", lambda rng: [
        ((np.zeros((3, 3), np.float32),
          np.asarray([[0, 1, 2], [0, 1, 2]], np.int32),
          np.ones((2, 3), np.float32)), {}, None)])
    add("scatter_reduce", lambda rng: [
        ((np.zeros((3, 3), np.float32),
          np.asarray([[0, 1, 2], [0, 1, 2]], np.int32),
          np.ones((2, 3), np.float32)), {"reduce": "amax"}, None)])
    add("unravel_index", lambda rng: [
        ((np.asarray([5, 7], np.int32), (3, 4)), {},
         lambda i, *r, **k: np.unravel_index(i, (3, 4)))])
    add("diag_indices", lambda rng: [((3,), {}, None)])
    add("cholesky_inverse", lambda rng: [
        ((np.linalg.cholesky(
            _x(rng, (3, 3)) @ _x(rng, (3, 3)).T +
            3 * np.eye(3, dtype=np.float32)).astype(np.float32),), {},
         None)])
    add("tensorinv", lambda rng: [
        ((_x(rng, (6, 2, 3)).reshape(6, 2, 3),), {"ind": 1},
         lambda a, **k: np.linalg.tensorinv(a, 1))])
    add("tensorsolve", lambda rng: [
        ((_x(rng, (2, 3, 6)), _x(rng, (2, 3))), {},
         lambda a, b, **k: np.linalg.tensorsolve(a, b))])
    add("geqrf", lambda rng: [((_x(rng, (4, 3)),), {}, None)])
    add("pairwise_distance", lambda rng: [
        ((_x(rng), _x(rng)), {}, None)])
    add("softmax2d", lambda rng: [((_x(rng, (2, 3, 4, 4)),), {}, None)])
    add("lp_pool1d", lambda rng: [
        ((_x(rng, (1, 2, 8)), 2.0, 4, 4), {}, None)])
    add("fractional_max_pool2d", lambda rng: [
        ((_x(rng, (1, 2, 9, 9)), 4), {"kernel_size": 2, "random_u": 0.3},
         None)])
    add("fractional_max_pool3d", lambda rng: [
        ((_x(rng, (1, 1, 9, 9, 9)), 4), {"kernel_size": 2, "random_u": 0.5},
         None)])
    def spd(rng):
        m = _x(rng, (3, 3))
        return (m @ m.T + 3 * np.eye(3, dtype=np.float32))
    add("cholesky", lambda rng: [((spd(rng),), {},
                                  lambda a, **k: np.linalg.cholesky(a))])
    add("det", lambda rng: [((spd(rng),), {},
                             lambda a, **k: np.linalg.det(a))])
    add("inv", lambda rng: [((spd(rng),), {},
                             lambda a, **k: np.linalg.inv(a))])
    add("slogdet", lambda rng: [((spd(rng),), {}, None)])
    add("eigvalsh", lambda rng: [((spd(rng),), {}, None)])
    add("eigh", lambda rng: [((spd(rng),), {}, None)])
    add("eig", lambda rng: [((spd(rng),), {}, None)])
    add("eigvals", lambda rng: [((spd(rng),), {}, None)])
    add("matrix_exp", lambda rng: [((0.1 * _x(rng, (3, 3)),), {}, None)])
    add("std", lambda rng: [((_x(rng),), {},
                             lambda a, **k: np.std(a, ddof=1))])
    add("var", lambda rng: [((_x(rng),), {},
                             lambda a, **k: np.var(a, ddof=1))])
    add("clip", lambda rng: [((_x(rng),), {"min": -0.5, "max": 0.5},
                              lambda a, **k: np.clip(a, -0.5, 0.5))])
    add("logit", lambda rng: [
        (((0.1 + 0.8 * np.random.default_rng(7).random((3, 4))
           ).astype(np.float32),), {},
         lambda a, **k: np.log(a / (1 - a)))])
    add("bincount", lambda rng: [
        ((np.asarray([0, 1, 1, 3], np.int32),), {},
         lambda a, **k: np.bincount(a))])
    add("histogram", lambda rng: [
        ((_pos(rng, (16,)), 4), {"min": 0.0, "max": 3.0},
         lambda a, *r, **k: np.histogram(a, 4, (0.0, 3.0))[0])])
    add("vander", lambda rng: [
        ((_x(rng, (4,)),), {"n": 3},
         lambda a, **k: np.vander(a, 3))])
    add("concatenate", lambda rng: [
        (([_x(rng), _x(rng)],), {},
         lambda xs, **k: np.concatenate(xs))])
    add("ravel_multi_index", lambda rng: [
        (([np.asarray([1, 2], np.int32), np.asarray([0, 3], np.int32)],
          (3, 4)), {},
         lambda mi, shape, **k: np.ravel_multi_index(tuple(mi), shape,
                                                     mode="clip"))])
    add("lu_solve", lambda rng: [
        ((np.asarray([1.0, 2.0], np.float32),
          np.asarray([[4.0, 2.0], [0.5, 2.0]], np.float32),
          np.asarray([1, 2], np.int32)), {}, None)])
    return sp


def _np_index_copy(a, i, s):
    out = a.copy()
    out[i] = s
    return out


# auto-specced one-tensor ops that need a positive/bounded domain
_AUTO_DOMAIN = {
    "cbrt": _x, "exp2": _x, "expit": _x, "erfc": _x,
}

# never auto-spec: random/stateful/inplace/shape-polymorphic/IO, plus ops
# whose single positional arg is a SHAPE or needs structured input (they
# get explicit specs or stay unswept)
_AUTO_EXCLUDE_PREFIX = ("fused_", "sparse_")
_AUTO_EXCLUDE_SUFFIX = ("_",)
_AUTO_EXCLUDE = {
    "zeros", "ones", "empty", "eye", "rand", "randn", "randperm", "uniform",
    "standard_normal", "standard_gamma", "seed", "create_parameter", "crop",
    "empty_like", "vander", "nonzero", "einsum", "multi_dot",
    "triu_indices", "tril_indices", "bincount", "histogram", "histogramdd",
    "clip", "logit", "cholesky", "det", "inv", "eig", "eigh", "eigvals",
    "eigvalsh", "slogdet", "matrix_exp", "std", "var", "concatenate",
    "ravel_multi_index", "interpolate", "upsample",
}


def _auto_spec(name, public):
    """Generic spec for ``(x, name=None)``-shaped publics: forward + numpy
    oracle when numpy has the name; gradient handled by the sweep."""
    try:
        sig = inspect.signature(public)
    except (TypeError, ValueError):
        return None
    params = list(sig.parameters.values())
    required = [p for p in params
                if p.default is inspect.Parameter.empty and
                p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)]
    if len(required) != 1:
        return None
    np_fn = getattr(np, name, None)
    oracle = (lambda a, **k: np_fn(a)) if callable(np_fn) else None
    maker = _AUTO_DOMAIN.get(name, _x)

    def spec(rng):
        return [((maker(rng),), {}, oracle)]
    return spec


def attach_specs():
    """Attach sweep/oracle specs to the live registry; returns coverage."""
    from ..core.dispatch import OP_REGISTRY

    explicit = {}
    explicit.update(_window_specs())
    explicit.update(_fft_specs())
    explicit.update(_set_specs())
    explicit.update(_composite_specs())
    explicit.update(_bulk_specs())

    attached = 0
    for name, spec in explicit.items():
        d = OP_REGISTRY.get(name)
        if d is not None:
            d.sweep = spec
            if d.public is None:   # older registrations stored the public
                d.public = d.fn    # wrapper as fn (signal/geometric style)
            attached += 1
    for name, d in OP_REGISTRY.items():
        if d.sweep is not None or d.category in ("unary", "binary"):
            continue
        if name.endswith(_AUTO_EXCLUDE_SUFFIX) or \
                name.startswith(_AUTO_EXCLUDE_PREFIX) or \
                name in _AUTO_EXCLUDE:
            continue
        if d.public is None:
            continue
        spec = _auto_spec(name, d.public)
        if spec is not None:
            d.sweep = spec
            attached += 1
    return attached


def sweep_coverage():
    """(covered, total): ops exercised by the sweep (factory categories or
    an attached spec) over all registered ops."""
    from ..core.dispatch import OP_REGISTRY
    total = len(OP_REGISTRY)
    covered = sum(1 for d in OP_REGISTRY.values()
                  if d.category in ("unary", "binary") or d.sweep is not None)
    return covered, total


def _bulk_specs():
    """r4 second batch: matmul/manipulation/indexing/creation/search/loss/
    pool/segment groups. Oracle = numpy where a clean counterpart exists,
    else run-only (finiteness; the op keeps its hand-written domain test)."""
    sp = {}

    def add(name, spec):
        sp[name] = spec

    i32 = np.int32

    # ---- matmul family ----
    add("matmul", lambda rng: [((_x(rng, (3, 4)), _x(rng, (4, 2))), {},
                                lambda a, b, **k: a @ b)])
    add("mm", lambda rng: [((_x(rng, (3, 4)), _x(rng, (4, 2))), {},
                            lambda a, b, **k: a @ b)])
    add("bmm", lambda rng: [((_x(rng, (2, 3, 4)), _x(rng, (2, 4, 2))), {},
                             lambda a, b, **k: a @ b)])
    add("mv", lambda rng: [((_x(rng, (3, 4)), _x(rng, (4,))), {},
                            lambda a, b, **k: a @ b)])
    add("dot", lambda rng: [((_x(rng, (5,)), _x(rng, (5,))), {},
                             lambda a, b, **k: np.dot(a, b))])
    add("cross", lambda rng: [((_x(rng, (4, 3)), _x(rng, (4, 3))), {},
                               lambda a, b, **k: np.cross(a, b))])
    add("kron", lambda rng: [((_x(rng, (2, 2)), _x(rng, (3, 2))), {},
                              lambda a, b, **k: np.kron(a, b))])
    add("tensordot", lambda rng: [((_x(rng, (3, 4)), _x(rng, (4, 5))),
                                   {"axes": 1},
                                   lambda a, b, **k: np.tensordot(a, b, 1))])
    add("addmm", lambda rng: [
        ((_x(rng, (3, 2)), _x(rng, (3, 4)), _x(rng, (4, 2))), {},
         lambda i, a, b, **k: i + a @ b)])
    add("baddbmm", lambda rng: [
        ((_x(rng, (2, 3, 2)), _x(rng, (2, 3, 4)), _x(rng, (2, 4, 2))), {},
         lambda i, a, b, **k: i + a @ b)])
    add("multi_dot", lambda rng: [
        (([_x(rng, (2, 3)), _x(rng, (3, 4)), _x(rng, (4, 2))],), {},
         lambda ms, **k: np.linalg.multi_dot(ms))])
    add("einsum", lambda rng: [
        (("ij,jk->ik", _x(rng, (3, 4)), _x(rng, (4, 2))), {},
         lambda eq, a, b, **k: np.einsum(eq, a, b))])
    add("outer", lambda rng: [((_x(rng, (3,)), _x(rng, (4,))), {},
                               lambda a, b, **k: np.outer(a, b))])
    add("inner", lambda rng: [((_x(rng, (3, 4)), _x(rng, (2, 4))), {},
                               lambda a, b, **k: np.inner(a, b))])

    # ---- manipulation ----
    add("reshape", lambda rng: [((_x(rng, (3, 4)), [2, 6]), {},
                                 lambda a, *r, **k: a.reshape(2, 6))])
    add("transpose", lambda rng: [((_x(rng, (2, 3, 4)), [2, 0, 1]), {},
                                   lambda a, *r, **k: a.transpose(2, 0, 1))])
    add("unsqueeze", lambda rng: [((_x(rng, (3, 4)), 1), {},
                                   lambda a, *r, **k: a[:, None])])
    add("tile", lambda rng: [((_x(rng, (2, 3)), [2, 2]), {},
                              lambda a, *r, **k: np.tile(a, (2, 2)))])
    add("broadcast_to", lambda rng: [((_x(rng, (1, 4)), [3, 4]), {},
                                      lambda a, *r, **k:
                                      np.broadcast_to(a, (3, 4)))])
    add("expand", lambda rng: [((_x(rng, (1, 4)), [3, 4]), {},
                                lambda a, *r, **k:
                                np.broadcast_to(a, (3, 4)))])
    add("expand_as", lambda rng: [((_x(rng, (1, 4)), _x(rng, (3, 4))), {},
                                   lambda a, b, **k:
                                   np.broadcast_to(a, b.shape))])
    add("moveaxis", lambda rng: [((_x(rng, (2, 3, 4)), 0, 2), {},
                                  lambda a, *r, **k: np.moveaxis(a, 0, 2))])
    add("swapaxes", lambda rng: [((_x(rng, (2, 3, 4)), 0, 2), {},
                                  lambda a, *r, **k: np.swapaxes(a, 0, 2))])
    add("roll", lambda rng: [((_x(rng, (3, 4)), 2), {},
                              lambda a, *r, **k: np.roll(a, 2))])
    add("flip", lambda rng: [((_x(rng, (3, 4)), 0), {},
                              lambda a, *r, **k: np.flip(a, 0))])
    add("chunk", lambda rng: [((_x(rng, (6, 4)), 3), {},
                               lambda a, *r, **k:
                               tuple(np.split(a, 3, 0)))])
    add("split", lambda rng: [((_x(rng, (6, 4)), 3), {},
                               lambda a, *r, **k:
                               tuple(np.split(a, 3, 0)))])
    add("hsplit", lambda rng: [((_x(rng, (4, 6)), 3), {},
                                lambda a, *r, **k:
                                tuple(np.hsplit(a, 3)))])
    add("vsplit", lambda rng: [((_x(rng, (6, 4)), 3), {},
                                lambda a, *r, **k:
                                tuple(np.vsplit(a, 3)))])
    add("dsplit", lambda rng: [((_x(rng, (2, 3, 6)), 3), {},
                                lambda a, *r, **k:
                                tuple(np.dsplit(a, 3)))])
    add("tensor_split", lambda rng: [((_x(rng, (7, 4)), 3), {},
                                      lambda a, *r, **k:
                                      tuple(np.array_split(a, 3, 0)))])
    add("repeat_interleave", lambda rng: [((_x(rng, (3, 2)), 2), {},
                                           lambda a, *r, **k:
                                           np.repeat(a, 2, axis=None))])
    add("unflatten", lambda rng: [((_x(rng, (2, 6)), 1, [2, 3]), {},
                                   lambda a, *r, **k:
                                   a.reshape(2, 2, 3))])
    add("cast", lambda rng: [((_x(rng), "float32"), {}, None)])
    add("reverse", lambda rng: [((_x(rng, (3, 4)), 0), {},
                                 lambda a, *r, **k: np.flip(a, 0))])
    add("crop", lambda rng: [((_x(rng, (4, 5)), [2, 3], [1, 1]), {},
                              lambda a, *r, **k: a[1:3, 1:4])])
    add("strided_slice", lambda rng: [
        ((_x(rng, (6, 5)), [0], [1], [5], [2]), {},
         lambda a, *r, **k: a[1:5:2])])
    add("pad", lambda rng: [((_x(rng, (3, 4)), [1, 1, 0, 0]), {},
                             None)])
    add("meshgrid", lambda rng: [
        (([np.arange(3, dtype=np.float32),
           np.arange(4, dtype=np.float32)],), {}, None)])
    add("atleast_1d", lambda rng: [((_x(rng, (3,)),), {},
                                    lambda a, **k: np.atleast_1d(a))])
    add("atleast_2d", lambda rng: [((_x(rng, (3,)),), {},
                                    lambda a, **k: np.atleast_2d(a))])
    add("atleast_3d", lambda rng: [((_x(rng, (3,)),), {},
                                    lambda a, **k: np.atleast_3d(a))])

    # ---- indexing / scatter ----
    idx2 = np.asarray([0, 2], i32)
    add("take", lambda rng: [((_x(rng, (3, 4)), np.asarray([1, 5], i32)),
                              {}, lambda a, i, **k: a.ravel()[i])])
    add("take_along_axis", lambda rng: [
        ((_x(rng, (3, 4)), np.asarray([[0], [1], [2]], i32), 1), {},
         lambda a, i, ax, **k: np.take_along_axis(a, i, 1))])
    add("index_select", lambda rng: [
        ((_x(rng, (4, 3)), idx2), {},
         lambda a, i, **k: a[i])])
    add("gather", lambda rng: [
        ((_x(rng, (4, 3)), idx2), {},
         lambda a, i, **k: a[i])])
    add("gather_nd", lambda rng: [
        ((_x(rng, (4, 3)), np.asarray([[0, 1], [2, 2]], i32)), {},
         lambda a, i, **k: a[i[:, 0], i[:, 1]])])
    add("index_sample", lambda rng: [
        ((_x(rng, (3, 4)), np.asarray([[0, 1], [1, 2], [2, 3]], i32)), {},
         lambda a, i, **k: np.take_along_axis(a, i, 1))])
    add("masked_fill", lambda rng: [
        ((_x(rng, (3, 4)), _x(rng, (3, 4)) > 0, 9.0), {},
         lambda a, m, v, **k: np.where(m, v, a))])
    add("masked_scatter", lambda rng: [
        ((_x(rng, (2, 3)), np.asarray([[1, 0, 1], [0, 1, 0]], bool),
          _x(rng, (6,))), {}, None)])
    add("index_fill", lambda rng: [
        ((_x(rng, (4, 3)), idx2, 0, 7.0), {},
         lambda a, i, ax, v, **k: _np_index_fill(a, i, v))])
    add("index_add", lambda rng: [
        ((_x(rng, (4, 3)), idx2, 0, _x(rng, (2, 3))), {}, None)])
    add("index_put", lambda rng: [
        ((_x(rng, (4, 3)), (idx2, np.asarray([0, 1], i32)),
          np.asarray([5.0, 6.0], np.float32)), {}, None)])
    add("put_along_axis", lambda rng: [
        ((_x(rng, (3, 4)), np.asarray([[0], [1], [2]], i32),
          9.0, 1), {}, None)])
    add("scatter", lambda rng: [
        ((_x(rng, (4, 3)), idx2, _x(rng, (2, 3))), {}, None)])
    add("scatter_nd", lambda rng: [
        ((np.asarray([[1], [3]], i32), _x(rng, (2, 3)), [5, 3]), {},
         None)])
    add("scatter_nd_add", lambda rng: [
        ((_x(rng, (5, 3)), np.asarray([[1], [3]], i32),
          _x(rng, (2, 3))), {}, None)])
    add("select_scatter", lambda rng: [
        ((_x(rng, (3, 4)), _x(rng, (4,)), 0, 1), {}, None)])
    add("slice_scatter", lambda rng: [
        ((_x(rng, (6, 3)), _x(rng, (2, 3)), [0], [1], [5], [2]), {},
         None)])
    add("diagonal_scatter", lambda rng: [
        ((_x(rng, (3, 3)), _x(rng, (3,))), {}, None)])

    add("multiplex", lambda rng: [
        (([_x(rng, (3, 4)), _x(rng, (3, 4))],
          np.asarray([0, 1, 0], i32)), {}, None)])
    add("shard_index", lambda rng: [
        ((np.asarray([[1], [6]], np.int64), 8, 2, -1), {}, None)])

    # ---- creation ----
    add("arange", lambda rng: [((0, 10, 2), {},
                                lambda *a, **k: np.arange(0, 10, 2))])
    add("linspace", lambda rng: [((0.0, 1.0, 5), {},
                                  lambda *a, **k:
                                  np.linspace(0, 1, 5,
                                              dtype=np.float32))])
    add("logspace", lambda rng: [((0.0, 2.0, 3), {},
                                  lambda *a, **k:
                                  np.logspace(0, 2, 3,
                                              dtype=np.float32))])
    add("eye", lambda rng: [((3, 4), {},
                             lambda *a, **k: np.eye(3, 4,
                                                    dtype=np.float32))])
    add("ones", lambda rng: [(([2, 3],), {},
                              lambda *a, **k: np.ones((2, 3),
                                                      np.float32))])
    add("zeros", lambda rng: [(([2, 3],), {},
                               lambda *a, **k: np.zeros((2, 3),
                                                        np.float32))])
    add("full", lambda rng: [(([2, 3], 7.0), {},
                              lambda *a, **k: np.full((2, 3), 7.0,
                                                      np.float32))])
    add("full_like", lambda rng: [((_x(rng), 7.0), {},
                                   lambda a, v, **k:
                                   np.full_like(a, 7.0))])
    add("empty", lambda rng: [(([2, 3],), {}, None)])
    add("empty_like", lambda rng: [((_x(rng),), {}, None)])
    add("complex", lambda rng: [((_x(rng), _x(rng)), {},
                                 lambda a, b, **k: a + 1j * b)])
    add("broadcast_shape", lambda rng: [(([2, 1, 3], [4, 3]), {}, None)])

    # ---- search / compare ----
    add("searchsorted", lambda rng: [
        ((np.sort(_x(rng, (6,))), _x(rng, (4,))), {},
         lambda s, v, **k: np.searchsorted(s, v))])
    add("bucketize", lambda rng: [
        ((_x(rng, (4,)), np.sort(_x(rng, (5,)))), {},
         lambda v, s, **k: np.searchsorted(s, v))])
    add("topk", lambda rng: [((_x(rng, (3, 6)), 2), {},
                              lambda a, kk, **k:
                              (np.sort(a, -1)[:, ::-1][:, :2],
                               np.argsort(-a, -1, kind="stable")[:, :2]))])
    add("kthvalue", lambda rng: [((_x(rng, (3, 6)), 2), {}, None)])
    add("isclose", lambda rng: [
        ((_x(rng), _x(rng)), {},
         lambda a, b, **k: np.isclose(a, b, 1e-5, 1e-8))])
    add("allclose", lambda rng: [
        ((_x(rng), _x(rng)), {},
         lambda a, b, **k: np.allclose(a, b, 1e-5, 1e-8))])
    add("equal_all", lambda rng: [
        ((_x(rng), _x(rng)), {},
         lambda a, b, **k: np.array_equal(a, b))])
    add("isin", lambda rng: [
        ((np.asarray([1, 2, 3, 4], i32), np.asarray([2, 4], i32)), {},
         lambda a, t, **k: np.isin(a, t))])

    # ---- elementwise leftovers ----
    add("lerp", lambda rng: [
        ((_x(rng), _x(rng), 0.3), {},
         lambda a, b, w, **k: a + 0.3 * (b - a))])
    add("floor_mod", lambda rng: [
        ((_pos(rng), _pos(rng)), {},
         lambda a, b, **k: np.mod(a, b))])
    add("mod", lambda rng: [
        ((_pos(rng), _pos(rng)), {},
         lambda a, b, **k: np.mod(a, b))])
    add("pow", lambda rng: [
        ((_pos(rng), 2.0), {}, lambda a, b, **k: a ** 2.0)])
    add("quantile", lambda rng: [
        ((_x(rng, (16,)), 0.5), {},
         lambda a, q, **k: np.quantile(a, 0.5).astype(np.float32))])
    add("nanquantile", lambda rng: [
        ((_x(rng, (16,)), 0.5), {},
         lambda a, q, **k: np.nanquantile(a, 0.5).astype(np.float32))])
    add("renorm", lambda rng: [((_x(rng, (3, 4)), 2.0, 0, 1.0), {}, None)])
    add("dist", lambda rng: [((_x(rng), _x(rng)), {},
                              lambda a, b, **k:
                              np.linalg.norm((a - b).ravel()))])

    # ---- linalg solves ----
    def spd3(rng):
        m = _x(rng, (3, 3))
        return m @ m.T + 3 * np.eye(3, dtype=np.float32)
    add("solve", lambda rng: [((spd3(rng), _x(rng, (3,))), {},
                               lambda a, b, **k: np.linalg.solve(a, b))])
    add("cholesky_solve", lambda rng: [
        ((_x(rng, (3,)), np.linalg.cholesky(spd3(rng)).astype(np.float32)),
         {}, None)])
    add("triangular_solve", lambda rng: [
        ((np.tril(spd3(rng)).astype(np.float32), _x(rng, (3, 1))),
         {"upper": False}, None)])
    add("lstsq", lambda rng: [((_x(rng, (5, 3)), _x(rng, (5, 1))), {},
                               None)])
    add("matrix_power", lambda rng: [
        ((spd3(rng), 3), {},
         lambda a, n, **k: np.linalg.matrix_power(a, 3))])
    add("lu_unpack", lambda rng: [
        ((np.asarray([[4.0, 2.0], [0.5, 2.0]], np.float32),
          np.asarray([2, 2], i32)), {}, None)])

    # ---- losses / nn functional ----
    t32 = (0.1 + 0.8 * np.random.default_rng(3).random((4, 3))
           ).astype(np.float32)
    add("l1_loss", lambda rng: [
        ((_x(rng), _x(rng)), {},
         lambda a, b, **k: np.abs(a - b).mean())])
    add("mse_loss", lambda rng: [
        ((_x(rng), _x(rng)), {},
         lambda a, b, **k: ((a - b) ** 2).mean())])
    add("smooth_l1_loss", lambda rng: [((_x(rng), _x(rng)), {}, None)])
    add("huber_loss", lambda rng: [((_x(rng), _x(rng)), {}, None)])
    add("log_loss", lambda rng: [((t32, (t32 > 0.5).astype(np.float32)),
                                  {}, None)])
    add("binary_cross_entropy", lambda rng: [
        ((t32, (t32 > 0.5).astype(np.float32)), {}, None)])
    add("binary_cross_entropy_with_logits", lambda rng: [
        ((_x(rng), ( _x(rng) > 0).astype(np.float32)), {}, None)])
    add("nll_loss", lambda rng: [
        ((np.log(t32 / t32.sum(-1, keepdims=True)),
          np.asarray([0, 1, 2, 0], np.int64)), {}, None)])
    add("cross_entropy", lambda rng: [
        ((_x(rng, (4, 3)), np.asarray([0, 1, 2, 0], np.int64)), {}, None)])
    add("softmax_with_cross_entropy", lambda rng: [
        ((_x(rng, (4, 3)), np.asarray([[0], [1], [2], [0]], np.int64)),
         {}, None)])
    add("cosine_similarity", lambda rng: [
        ((_x(rng), _x(rng)), {},
         lambda a, b, **k: (a * b).sum(-1) /
         (np.linalg.norm(a, axis=-1) * np.linalg.norm(b, axis=-1)))])
    add("one_hot", lambda rng: [
        ((np.asarray([0, 2, 1], i32), 4), {},
         lambda a, n, **k: np.eye(4, dtype=np.float32)[a])])
    add("embedding", lambda rng: [
        ((np.asarray([0, 2], i32), _x(rng, (5, 4))), {},
         lambda i, w, **k: w[i])])
    add("linear", lambda rng: [
        ((_x(rng, (2, 4)), _x(rng, (4, 3)), _x(rng, (3,))), {},
         lambda x, w, b, **k: x @ w + b)])

    # ---- pools / convs: run-only legs (hand-tested with oracles elsewhere)
    for n, shape, extra in (
            ("avg_pool1d", (1, 2, 8), (2,)), ("avg_pool2d", (1, 2, 8, 8),
                                              (2,)),
            ("avg_pool3d", (1, 1, 4, 4, 4), (2,)),
            ("max_pool1d", (1, 2, 8), (2,)), ("max_pool2d", (1, 2, 8, 8),
                                              (2,)),
            ("max_pool3d", (1, 1, 4, 4, 4), (2,)),
            ("adaptive_avg_pool1d", (1, 2, 8), (2,)),
            ("adaptive_avg_pool2d", (1, 2, 8, 8), (2,)),
            ("adaptive_avg_pool3d", (1, 1, 4, 4, 4), (2,)),
            ("adaptive_max_pool1d", (1, 2, 8), (2,)),
            ("adaptive_max_pool2d", (1, 2, 8, 8), (2,)),
            ("adaptive_max_pool3d", (1, 1, 4, 4, 4), (2,))):
        add(n, (lambda shape=shape, extra=extra:
                (lambda rng: [((_x(rng, shape),) + extra, {}, None)]))())
    for n, xs, ws in (("conv1d", (1, 2, 8), (3, 2, 3)),
                      ("conv2d", (1, 2, 8, 8), (3, 2, 3, 3)),
                      ("conv3d", (1, 1, 6, 6, 6), (2, 1, 3, 3, 3))):
        add(n, (lambda xs=xs, ws=ws:
                (lambda rng: [((_x(rng, xs), _x(rng, ws)), {}, None)]))())

    # ---- segments (numpy oracle) ----
    seg = np.asarray([0, 0, 1, 2, 2], i32)

    def seg_oracle(red):
        def o(x, s, **k):
            return np.stack([red(x[s == g]) for g in range(int(s.max()) + 1)])
        return o
    add("segment_sum", lambda rng: [
        ((_x(rng, (5, 3)), seg), {},
         seg_oracle(lambda v: v.sum(0)))])
    add("segment_mean", lambda rng: [
        ((_x(rng, (5, 3)), seg), {},
         seg_oracle(lambda v: v.mean(0)))])
    add("segment_max", lambda rng: [
        ((_x(rng, (5, 3)), seg), {},
         seg_oracle(lambda v: v.max(0)))])
    add("segment_min", lambda rng: [
        ((_x(rng, (5, 3)), seg), {},
         seg_oracle(lambda v: v.min(0)))])

    # ---- random / signal: run-only (statistical tests live elsewhere) ----
    for n, args in (("rand", ([2, 3],)), ("randn", ([2, 3],)),
                    ("randint", (0, 5, [2, 3])), ("randperm", (6,)),
                    ("uniform", ([2, 3],)), ("normal", (0.0, 1.0, [2, 3])),
                    ("standard_normal", ([2, 3],)),
                    ("standard_gamma", (2.0, [2, 3]))):
        add(n, (lambda args=args:
                (lambda rng: [(args, {}, None)]))())
    add("stft", lambda rng: [
        ((_x(rng, (1, 256)), 64), {"hop_length": 32,
                                   "window": np.hanning(64).astype(
                                       np.float32)}, None)])
    add("frame", lambda rng: [
        ((_x(rng, (1, 64)), 16, 8), {}, None)])
    return sp


def _np_index_fill(a, i, v):
    out = a.copy()
    out[i] = v
    return out


def _np_fill_diag(a, v):
    out = a.copy()
    np.fill_diagonal(out, v)
    return out
