"""Sweep specs: example calls + numpy oracles for COMPOSITE ops.

The unary/binary factory ops are swept automatically from their category
tag (tests/test_op_sweep.py); everything else needs an example-call spec —
this module attaches them to the ``OpDef`` entries post-import (r3 VERDICT
#6: "extend the schema with an oracle field so the sweep reaches composite
ops"). A spec is ``(rng) -> [(args, kwargs, oracle), ...]`` where ``args``
may contain numpy arrays (converted to Tensors by the sweep) and ``oracle``
is a numpy callable or None (run-only leg).

Two tiers:
* EXPLICIT specs below for ops whose call shape needs thought (windows vs
  scipy, fft vs numpy.fft, sets, scatter family, reductions with axes).
* AUTO specs for simple one-tensor ops (public signature ``(x, name=None)``)
  — forward run + numpy oracle when ``numpy.<name>`` exists, gradient
  finite-difference when differentiable.

Ops with neither (stateful/random/IO/shape-polymorphic) are counted and
reported as unswept in docs/OPS.md.
"""

from __future__ import annotations

import inspect

import numpy as np

__all__ = ["attach_specs", "sweep_coverage"]


def _x(rng, shape=(3, 4)):
    return rng.standard_normal(shape).astype(np.float32)


def _pos(rng, shape=(3, 4)):
    return (rng.random(shape) * 2 + 0.3).astype(np.float32)


# ---------------------------------------------------------------------------
# explicit spec tables
# ---------------------------------------------------------------------------

def _window_specs():
    """Windows vs scipy.signal oracles (sym and periodic legs)."""
    try:
        import scipy.signal as ss
    except ImportError:          # pragma: no cover
        ss = None
    table = {
        "blackman_window": ("blackman", ()),
        "hamming_window": ("hamming", ()),
        "hann_window": ("hann", ()),
        "bartlett_window": ("bartlett", ()),
        "kaiser_window": (("kaiser", 12.0), ()),
        "nuttall_window": ("nuttall", ()),
        "blackman_harris_window": ("blackmanharris", ()),
        "bohman_window": ("bohman", ()),
        "cosine_window": ("cosine", ()),
        "tukey_window": (("tukey", 0.5), ()),
        "gaussian_window": (("gaussian", 7.0), ()),
        "exponential_window": (("exponential", None, 1.0), ()),
        "triang_window": ("triang", ()),
    }
    specs = {}
    for op, (sci_name, extra) in table.items():
        def mk(sci_name=sci_name, extra=extra):
            def spec(rng):
                legs = []
                for M, sym in ((8, True), (9, False)):
                    orc = (None if ss is None else
                           (lambda M=M, sym=sym:
                            ss.get_window(sci_name, M, fftbins=not sym)))
                    legs.append(((M,) + tuple(extra),
                                 {"sym": sym, "dtype": "float32"},
                                 (lambda *_a, _o=orc, **_k: _o())
                                 if orc else None))
                return legs
            return spec
        specs[op] = mk()
    def _gw_oracle(*_a, **_k):
        import scipy.signal as _ss
        return _ss.get_window("hann", 16)
    specs["get_window"] = lambda rng: [
        (("hann", 16), {"dtype": "float32"}, _gw_oracle)]
    specs["general_cosine_window"] = lambda rng: [
        ((8, [0.5, 0.5]), {"dtype": "float32"}, None)]
    specs["general_hamming_window"] = lambda rng: [
        ((8, 0.6), {"dtype": "float32"}, None)]
    specs["taylor_window"] = lambda rng: [((16,), {"dtype": "float32"},
                                           None)]
    return specs


def _fft_specs():
    def o(name):
        return getattr(np.fft, name)
    simple = {}
    for n in ("fft", "ifft", "fftn", "ifftn", "fft2", "ifft2", "rfft",
              "rfft2", "rfftn", "ihfft"):
        simple[n] = (lambda n=n: (lambda rng: [
            ((_x(rng, (4, 8)),), {},
             lambda a, **k: o(n)(a))]))()
    for n in ("irfft", "irfft2", "irfftn", "hfft"):
        simple[n] = (lambda n=n: (lambda rng: [
            ((_x(rng, (4, 8)) + 1j * _x(rng, (4, 8)),), {},
             lambda a, **k: o(n)(a))]))()
    simple["fftshift"] = lambda rng: [((_x(rng, (4, 8)),), {},
                                       lambda a, **k: np.fft.fftshift(a))]
    simple["ifftshift"] = lambda rng: [((_x(rng, (4, 8)),), {},
                                        lambda a, **k: np.fft.ifftshift(a))]
    simple["fftfreq"] = lambda rng: [
        ((8,), {}, lambda *a, **k: np.fft.fftfreq(8).astype(np.float32))]
    simple["rfftfreq"] = lambda rng: [
        ((8,), {}, lambda *a, **k: np.fft.rfftfreq(8).astype(np.float32))]
    return simple


def _set_specs():
    a = np.asarray([3, 1, 2, 3, 5], np.int32)
    b = np.asarray([2, 3, 9], np.int32)
    return {
        "intersect1d": lambda rng: [((a, b), {},
                                     lambda x, y, **k: np.intersect1d(x, y))],
        "setdiff1d": lambda rng: [((a, b), {},
                                   lambda x, y, **k: np.setdiff1d(x, y))],
        "union1d": lambda rng: [((a, b), {},
                                 lambda x, y, **k: np.union1d(x, y))],
        "setxor1d": lambda rng: [((a, b), {},
                                  lambda x, y, **k: np.setxor1d(x, y))],
        "in1d": lambda rng: [((a, b), {},
                              lambda x, y, **k: np.in1d(x, y))],
    }


def _composite_specs():
    """Hand specs for multi-arg / axis ops (numpy oracle where one exists)."""
    sp = {}

    def add(name, spec):
        sp[name] = spec

    add("logdet", lambda rng: [
        (((_x(rng, (3, 3)) @ _x(rng, (3, 3)).T + 3 * np.eye(3, dtype=np.float32)),),
         {}, lambda a, **k: np.log(np.linalg.det(a)))])
    add("vdot", lambda rng: [((_x(rng), _x(rng)), {},
                              lambda a, b, **k: np.vdot(a, b))])
    add("addmv", lambda rng: [
        ((_x(rng, (3,)), _x(rng, (3, 4)), _x(rng, (4,))), {},
         lambda i, m, v, **k: i + m @ v)])
    add("addr", lambda rng: [
        ((_x(rng, (3, 4)), _x(rng, (3,)), _x(rng, (4,))), {},
         lambda i, a, b, **k: i + np.outer(a, b))])
    add("chain_matmul", lambda rng: [
        ((_x(rng, (2, 3)), _x(rng, (3, 4)), _x(rng, (4, 2))), {},
         lambda a, b, c, **k: a @ b @ c)])
    add("float_power", lambda rng: [
        ((_pos(rng), _pos(rng)), {},
         lambda a, b, **k: np.float_power(a, b).astype(np.float32))])
    add("std_mean", lambda rng: [
        ((_x(rng),), {}, lambda a, **k: (np.std(a, ddof=1), np.mean(a)))])
    add("var_mean", lambda rng: [
        ((_x(rng),), {}, lambda a, **k: (np.var(a, ddof=1), np.mean(a)))])
    add("gradient", lambda rng: [
        ((_x(rng, (8,)),), {}, lambda a, **k: np.gradient(a))])
    add("fliplr", lambda rng: [((_x(rng),), {},
                                lambda a, **k: np.fliplr(a))])
    add("flipud", lambda rng: [((_x(rng),), {},
                                lambda a, **k: np.flipud(a))])
    add("rollaxis", lambda rng: [((_x(rng, (2, 3, 4)), 2), {},
                                  lambda a, *r, **k: np.rollaxis(a, 2))])
    add("swapdims", lambda rng: [((_x(rng, (2, 3, 4)), 0, 2), {},
                                  lambda a, *r, **k: np.swapaxes(a, 0, 2))])
    add("narrow", lambda rng: [((_x(rng, (5, 4)), 0, 1, 3), {},
                                lambda a, *r, **k: a[1:4])])
    add("narrow_copy", lambda rng: [((_x(rng, (5, 4)), 0, 1, 3), {},
                                     lambda a, *r, **k: a[1:4])])
    add("split_with_sizes", lambda rng: [
        ((_x(rng, (6, 4)), [2, 4]), {},
         lambda a, *r, **k: (a[:2], a[2:]))])
    add("arctan2", lambda rng: [((_x(rng), _pos(rng)), {},
                                 lambda a, b, **k: np.arctan2(a, b))])
    add("nanargmax", lambda rng: [((_x(rng),), {},
                                   lambda a, **k: np.nanargmax(a))])
    add("nanargmin", lambda rng: [((_x(rng),), {},
                                   lambda a, **k: np.nanargmin(a))])
    add("nanstd", lambda rng: [((_x(rng),), {},
                                lambda a, **k: np.nanstd(a, ddof=1))])
    add("nanvar", lambda rng: [((_x(rng),), {},
                                lambda a, **k: np.nanvar(a, ddof=1))])
    add("histogram_bin_edges", lambda rng: [
        ((_x(rng, (16,)), 4), {},
         lambda a, *r, **k: np.histogram_bin_edges(a, 4,
                                                   (a.min(), a.max())))])
    add("histc", lambda rng: [
        ((_pos(rng, (16,)), 4), {},
         lambda a, *r, **k: np.histogram(a, 4, (a.min(), a.max()))[0])])
    add("betainc", lambda rng: [
        ((_pos(rng), _pos(rng),
          (0.1 + 0.8 * np.random.default_rng(0).random((3, 4))
           ).astype(np.float32)), {}, None)])
    add("true_divide", lambda rng: [((_x(rng), _pos(rng)), {},
                                     lambda a, b, **k: a / b)])
    add("trunc_divide", lambda rng: [((_x(rng), _pos(rng)), {},
                                      lambda a, b, **k: np.trunc(a / b))])
    add("divide_no_nan", lambda rng: [
        ((_x(rng), np.asarray([[1, 0, 2, 0]] * 3, np.float32)), {},
         lambda a, b, **k: np.where(b == 0, 0, a / np.where(b == 0, 1, b)))])
    add("bitwise_invert", lambda rng: [
        ((np.asarray([1, 2, 3], np.int32),), {},
         lambda a, **k: np.invert(a))])
    add("cumulative_sum", lambda rng: [
        ((_x(rng, (8,)),), {}, lambda a, **k: np.cumsum(a))])
    add("cumulative_prod", lambda rng: [
        ((_pos(rng, (6,)),), {}, lambda a, **k: np.cumprod(a))])
    add("clip_by_norm", lambda rng: [
        ((_x(rng), 1.0), {},
         lambda a, *r, **k: a * min(1.0, 1.0 / np.linalg.norm(a)))])
    add("take_along_dim", lambda rng: [
        ((_x(rng, (3, 4)), np.asarray([[0], [1], [2]], np.int32)),
         {"dim": 1},
         lambda a, i, **k: np.take_along_axis(a, i, axis=1))])
    add("permute_dims", lambda rng: [
        ((_x(rng, (2, 3, 4)), (2, 0, 1)), {},
         lambda a, *r, **k: np.transpose(a, (2, 0, 1)))])
    add("index_copy", lambda rng: [
        ((_x(rng, (4, 3)), np.asarray([0, 2], np.int32), _x(rng, (2, 3))),
         {}, lambda a, i, s, **k: _np_index_copy(a, i, s))])
    add("scatter_add", lambda rng: [
        ((np.zeros((3, 3), np.float32),
          np.asarray([[0, 1, 2], [0, 1, 2]], np.int32),
          np.ones((2, 3), np.float32)), {}, None)])
    add("scatter_reduce", lambda rng: [
        ((np.zeros((3, 3), np.float32),
          np.asarray([[0, 1, 2], [0, 1, 2]], np.int32),
          np.ones((2, 3), np.float32)), {"reduce": "amax"}, None)])
    add("unravel_index", lambda rng: [
        ((np.asarray([5, 7], np.int32), (3, 4)), {},
         lambda i, *r, **k: np.unravel_index(i, (3, 4)))])
    add("diag_indices", lambda rng: [((3,), {}, None)])
    add("cholesky_inverse", lambda rng: [
        ((np.linalg.cholesky(
            _x(rng, (3, 3)) @ _x(rng, (3, 3)).T +
            3 * np.eye(3, dtype=np.float32)).astype(np.float32),), {},
         None)])
    add("tensorinv", lambda rng: [
        ((_x(rng, (6, 2, 3)).reshape(6, 2, 3),), {"ind": 1},
         lambda a, **k: np.linalg.tensorinv(a, 1))])
    add("tensorsolve", lambda rng: [
        ((_x(rng, (2, 3, 6)), _x(rng, (2, 3))), {},
         lambda a, b, **k: np.linalg.tensorsolve(a, b))])
    add("geqrf", lambda rng: [((_x(rng, (4, 3)),), {}, None)])
    add("pairwise_distance", lambda rng: [
        ((_x(rng), _x(rng)), {}, None)])
    add("softmax2d", lambda rng: [((_x(rng, (2, 3, 4, 4)),), {}, None)])
    add("lp_pool1d", lambda rng: [
        ((_x(rng, (1, 2, 8)), 2.0, 4, 4), {}, None)])
    add("fractional_max_pool2d", lambda rng: [
        ((_x(rng, (1, 2, 9, 9)), 4), {"kernel_size": 2, "random_u": 0.3},
         None)])
    add("fractional_max_pool3d", lambda rng: [
        ((_x(rng, (1, 1, 9, 9, 9)), 4), {"kernel_size": 2, "random_u": 0.5},
         None)])
    def spd(rng):
        m = _x(rng, (3, 3))
        return (m @ m.T + 3 * np.eye(3, dtype=np.float32))
    add("cholesky", lambda rng: [((spd(rng),), {},
                                  lambda a, **k: np.linalg.cholesky(a))])
    add("det", lambda rng: [((spd(rng),), {},
                             lambda a, **k: np.linalg.det(a))])
    add("inv", lambda rng: [((spd(rng),), {},
                             lambda a, **k: np.linalg.inv(a))])
    add("slogdet", lambda rng: [((spd(rng),), {}, None)])
    add("eigvalsh", lambda rng: [((spd(rng),), {}, None)])
    add("eigh", lambda rng: [((spd(rng),), {}, None)])
    add("eig", lambda rng: [((spd(rng),), {}, None)])
    add("eigvals", lambda rng: [((spd(rng),), {}, None)])
    add("matrix_exp", lambda rng: [((0.1 * _x(rng, (3, 3)),), {}, None)])
    add("std", lambda rng: [((_x(rng),), {},
                             lambda a, **k: np.std(a, ddof=1))])
    add("var", lambda rng: [((_x(rng),), {},
                             lambda a, **k: np.var(a, ddof=1))])
    add("clip", lambda rng: [((_x(rng),), {"min": -0.5, "max": 0.5},
                              lambda a, **k: np.clip(a, -0.5, 0.5))])
    add("logit", lambda rng: [
        (((0.1 + 0.8 * np.random.default_rng(7).random((3, 4))
           ).astype(np.float32),), {},
         lambda a, **k: np.log(a / (1 - a)))])
    add("bincount", lambda rng: [
        ((np.asarray([0, 1, 1, 3], np.int32),), {},
         lambda a, **k: np.bincount(a))])
    add("histogram", lambda rng: [
        ((_pos(rng, (16,)), 4), {"min": 0.0, "max": 3.0},
         lambda a, *r, **k: np.histogram(a, 4, (0.0, 3.0))[0])])
    add("vander", lambda rng: [
        ((_x(rng, (4,)),), {"n": 3},
         lambda a, **k: np.vander(a, 3))])
    add("concatenate", lambda rng: [
        (([_x(rng), _x(rng)],), {},
         lambda xs, **k: np.concatenate(xs))])
    add("ravel_multi_index", lambda rng: [
        (([np.asarray([1, 2], np.int32), np.asarray([0, 3], np.int32)],
          (3, 4)), {},
         lambda mi, shape, **k: np.ravel_multi_index(tuple(mi), shape,
                                                     mode="clip"))])
    add("lu_solve", lambda rng: [
        ((np.asarray([1.0, 2.0], np.float32),
          np.asarray([[4.0, 2.0], [0.5, 2.0]], np.float32),
          np.asarray([1, 2], np.int32)), {}, None)])
    return sp


def _np_index_copy(a, i, s):
    out = a.copy()
    out[i] = s
    return out


# auto-specced one-tensor ops that need a positive/bounded domain
_AUTO_DOMAIN = {
    "cbrt": _x, "exp2": _x, "expit": _x, "erfc": _x,
}

# never auto-spec: random/stateful/inplace/shape-polymorphic/IO, plus ops
# whose single positional arg is a SHAPE or needs structured input (they
# get explicit specs or stay unswept)
_AUTO_EXCLUDE_PREFIX = ("fused_", "sparse_")
_AUTO_EXCLUDE_SUFFIX = ("_",)
_AUTO_EXCLUDE = {
    "zeros", "ones", "empty", "eye", "rand", "randn", "randperm", "uniform",
    "standard_normal", "standard_gamma", "seed", "create_parameter", "crop",
    "empty_like", "vander", "nonzero", "einsum", "multi_dot",
    "triu_indices", "tril_indices", "bincount", "histogram", "histogramdd",
    "clip", "logit", "cholesky", "det", "inv", "eig", "eigh", "eigvals",
    "eigvalsh", "slogdet", "matrix_exp", "std", "var", "concatenate",
    "ravel_multi_index", "interpolate", "upsample",
}


def _auto_spec(name, public):
    """Generic spec for ``(x, name=None)``-shaped publics: forward + numpy
    oracle when numpy has the name; gradient handled by the sweep."""
    try:
        sig = inspect.signature(public)
    except (TypeError, ValueError):
        return None
    params = list(sig.parameters.values())
    required = [p for p in params
                if p.default is inspect.Parameter.empty and
                p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)]
    if len(required) != 1:
        return None
    np_fn = getattr(np, name, None)
    oracle = (lambda a, **k: np_fn(a)) if callable(np_fn) else None
    maker = _AUTO_DOMAIN.get(name, _x)

    def spec(rng):
        return [((maker(rng),), {}, oracle)]
    return spec


def attach_specs():
    """Attach sweep/oracle specs to the live registry; returns coverage."""
    from ..core.dispatch import OP_REGISTRY

    explicit = {}
    explicit.update(_window_specs())
    explicit.update(_fft_specs())
    explicit.update(_set_specs())
    explicit.update(_composite_specs())

    attached = 0
    for name, spec in explicit.items():
        d = OP_REGISTRY.get(name)
        if d is not None:
            d.sweep = spec
            attached += 1
    for name, d in OP_REGISTRY.items():
        if d.sweep is not None or d.category in ("unary", "binary"):
            continue
        if name.endswith(_AUTO_EXCLUDE_SUFFIX) or \
                name.startswith(_AUTO_EXCLUDE_PREFIX) or \
                name in _AUTO_EXCLUDE:
            continue
        if d.public is None:
            continue
        spec = _auto_spec(name, d.public)
        if spec is not None:
            d.sweep = spec
            attached += 1
    return attached


def sweep_coverage():
    """(covered, total): ops exercised by the sweep (factory categories or
    an attached spec) over all registered ops."""
    from ..core.dispatch import OP_REGISTRY
    total = len(OP_REGISTRY)
    covered = sum(1 for d in OP_REGISTRY.values()
                  if d.category in ("unary", "binary") or d.sweep is not None)
    return covered, total
