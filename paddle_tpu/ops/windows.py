"""Window functions (signal/audio windowing surface).

Parity targets: ``paddle.audio.functional.window.get_window`` and the
window set scipy exposes through it (reference routes windows through
``paddle/audio/functional/window.py``); the ``*_window`` creation-op names
mirror the torch-style aliases the ecosystem expects.

All windows are pure jnp expressions of ``arange(M)`` — creation ops (no
gradient surface), periodic/symmetric conventions supported the way
scipy.signal does (``sym=False`` computes the M+1 window and drops the last
sample).
"""

from __future__ import annotations

import math as _math

import jax.numpy as jnp

from ._helpers import forward_op, register_op
from ..core.dtype import canonical_dtype

__all__ = [
    "blackman_window", "hamming_window", "hann_window", "bartlett_window",
    "kaiser_window", "nuttall_window", "blackman_harris_window",
    "bohman_window", "cosine_window", "tukey_window", "gaussian_window",
    "exponential_window", "general_cosine_window", "general_hamming_window",
    "triang_window", "taylor_window", "get_window",
]


def _ext(M: int, sym: bool):
    """scipy's extend/truncate trick for periodic windows."""
    return (M + 1, True) if (not sym and M > 1) else (M, False)


def _general_cosine(M, a, sym):
    Mx, trunc = _ext(M, sym)
    if Mx == 1:
        w = jnp.ones(1)
    else:
        fac = jnp.linspace(-_math.pi, _math.pi, Mx)
        w = sum(ai * jnp.cos(i * fac) for i, ai in enumerate(a))
    return w[:-1] if trunc else w


def _creation(name, fn, doc=""):
    """Register a window creation op returning a float Tensor."""
    def op(window_length, *args, sym=True, dtype="float32", name_=None,
           **kw):
        dt = canonical_dtype(dtype)
        M = int(window_length)

        def impl():
            return fn(M, *args, sym=sym, **kw).astype(dt)
        return forward_op(name, impl, [], differentiable=False)

    op.__name__ = name
    op.__qualname__ = name
    op.__doc__ = doc or f"{name} of the given length (sym=False -> periodic)."
    register_op(name, fn, op.__doc__, differentiable=False,
                category="window", public=op)
    return op


def _blackman(M, sym=True):
    return _general_cosine(M, [0.42, 0.50, 0.08], sym)


def _hamming(M, sym=True):
    return _general_cosine(M, [0.54, 0.46], sym)


def _general_hamming(M, alpha, sym=True):
    return _general_cosine(M, [alpha, 1.0 - alpha], sym)


def _hann(M, sym=True):
    return _general_cosine(M, [0.5, 0.5], sym)


def _nuttall(M, sym=True):
    return _general_cosine(M, [0.3635819, 0.4891775, 0.1365995, 0.0106411],
                           sym)


def _blackman_harris(M, sym=True):
    return _general_cosine(M, [0.35875, 0.48829, 0.14128, 0.01168], sym)


def _bartlett(M, sym=True):
    Mx, trunc = _ext(M, sym)
    if Mx == 1:
        return jnp.ones(1)
    n = jnp.arange(Mx)
    w = jnp.where(n <= (Mx - 1) / 2.0, 2.0 * n / (Mx - 1),
                  2.0 - 2.0 * n / (Mx - 1))
    return w[:-1] if trunc else w


def _triang(M, sym=True):
    Mx, trunc = _ext(M, sym)
    n = jnp.arange(1, (Mx + 1) // 2 + 1)
    if Mx % 2 == 0:
        w = (2 * n - 1.0) / Mx
        w = jnp.concatenate([w, w[::-1]])
    else:
        w = 2 * n / (Mx + 1.0)
        w = jnp.concatenate([w, w[-2::-1]])
    return w[:-1] if trunc else w


def _kaiser(M, beta=12.0, sym=True):
    Mx, trunc = _ext(M, sym)
    if Mx == 1:
        return jnp.ones(1)
    n = jnp.arange(Mx)
    alpha = (Mx - 1) / 2.0
    from jax.scipy.special import i0 as _i0
    w = _i0(beta * jnp.sqrt(jnp.clip(
        1.0 - ((n - alpha) / alpha) ** 2, 0.0, 1.0))) / _i0(jnp.float32(beta))
    return w[:-1] if trunc else w


def _bohman(M, sym=True):
    Mx, trunc = _ext(M, sym)
    if Mx == 1:
        return jnp.ones(1)
    fac = jnp.abs(jnp.linspace(-1, 1, Mx))
    w = (1 - fac) * jnp.cos(_math.pi * fac) + \
        1.0 / _math.pi * jnp.sin(_math.pi * fac)
    # endpoints are exactly zero in scipy
    w = w.at[0].set(0.0).at[-1].set(0.0)
    return w[:-1] if trunc else w


def _cosine(M, sym=True):
    Mx, trunc = _ext(M, sym)
    w = jnp.sin(_math.pi / Mx * (jnp.arange(Mx) + 0.5))
    return w[:-1] if trunc else w


def _tukey(M, alpha=0.5, sym=True):
    Mx, trunc = _ext(M, sym)
    if Mx == 1:
        return jnp.ones(1)
    if alpha <= 0:
        w = jnp.ones(Mx)
    elif alpha >= 1:
        w = _hann(Mx, sym=True)
    else:
        n = jnp.arange(Mx)
        width = alpha * (Mx - 1) / 2.0
        w = jnp.where(
            n < width,
            0.5 * (1 + jnp.cos(_math.pi * (-1 + 2.0 * n / alpha / (Mx - 1)))),
            jnp.where(
                n > (Mx - 1) * (1 - alpha / 2.0),
                0.5 * (1 + jnp.cos(_math.pi * (-2.0 / alpha + 1 +
                                               2.0 * n / alpha / (Mx - 1)))),
                1.0))
    return w[:-1] if trunc else w


def _gaussian(M, std=7.0, sym=True):
    Mx, trunc = _ext(M, sym)
    n = jnp.arange(Mx) - (Mx - 1.0) / 2.0
    w = jnp.exp(-(n ** 2) / (2.0 * std * std))
    return w[:-1] if trunc else w


def _exponential(M, center=None, tau=1.0, sym=True):
    Mx, trunc = _ext(M, sym)
    c = (Mx - 1) / 2.0 if center is None else center
    n = jnp.arange(Mx)
    w = jnp.exp(-jnp.abs(n - c) / tau)
    return w[:-1] if trunc else w


def _taylor(M, nbar=4, sll=30, norm=True, sym=True):
    Mx, trunc = _ext(M, sym)
    B = 10 ** (sll / 20.0)
    A = _math.acosh(B) / _math.pi
    s2 = nbar ** 2 / (A ** 2 + (nbar - 0.5) ** 2)
    ma = jnp.arange(1, nbar, dtype=jnp.float32)

    Fm = []
    import numpy as _np
    man = _np.arange(1, nbar)
    for mi in man:
        numer = (-1) ** (mi + 1) * _np.prod(
            1 - mi ** 2 / s2 / (A ** 2 + (man - 0.5) ** 2))
        denom = 2 * _np.prod([1 - mi ** 2 / j ** 2
                              for j in man if j != mi])
        Fm.append(numer / denom)
    Fm = jnp.asarray(_np.asarray(Fm, _np.float32))
    n = jnp.arange(Mx)
    w = 1 + 2 * jnp.sum(
        Fm[:, None] * jnp.cos(2 * _math.pi * ma[:, None] *
                              (n[None] - Mx / 2.0 + 0.5) / Mx), axis=0)
    if norm:
        scale = 1 + 2 * jnp.sum(
            Fm * jnp.cos(2 * _math.pi * ma * (-0.5 + 0.5)), axis=0)
        w = w / scale
    return w[:-1] if trunc else w


def _general_cosine_pub(M, a, sym=True):
    return _general_cosine(M, list(a), sym)


blackman_window = _creation("blackman_window", _blackman)
hamming_window = _creation("hamming_window", _hamming)
hann_window = _creation("hann_window", _hann)
bartlett_window = _creation("bartlett_window", _bartlett)
kaiser_window = _creation("kaiser_window", _kaiser)
nuttall_window = _creation("nuttall_window", _nuttall)
blackman_harris_window = _creation("blackman_harris_window", _blackman_harris)
bohman_window = _creation("bohman_window", _bohman)
cosine_window = _creation("cosine_window", _cosine)
tukey_window = _creation("tukey_window", _tukey)
gaussian_window = _creation("gaussian_window", _gaussian)
exponential_window = _creation("exponential_window", _exponential)
general_cosine_window = _creation("general_cosine_window",
                                  _general_cosine_pub)
general_hamming_window = _creation("general_hamming_window", _general_hamming)
triang_window = _creation("triang_window", _triang)
taylor_window = _creation("taylor_window", _taylor)

_BY_NAME = {
    "blackman": _blackman, "hamming": _hamming, "hann": _hann,
    "bartlett": _bartlett, "kaiser": _kaiser, "nuttall": _nuttall,
    "blackmanharris": _blackman_harris, "bohman": _bohman,
    "cosine": _cosine, "tukey": _tukey, "gaussian": _gaussian,
    "exponential": _exponential, "general_cosine": _general_cosine_pub,
    "general_hamming": _general_hamming, "triang": _triang,
    "taylor": _taylor,
}


def get_window(window, win_length: int, fftbins: bool = True,
               dtype: str = "float64"):
    """``paddle.audio.functional.get_window`` parity: window by name (or
    ``(name, param)`` tuple), periodic by default (``fftbins=True``)."""
    args = ()
    if isinstance(window, (tuple, list)):
        window, *args = window
    if not isinstance(window, str):
        raise TypeError(f"window must be a str or (str, param), got "
                        f"{window!r}")
    try:
        fn = _BY_NAME[window]
    except KeyError:
        raise ValueError(
            f"unknown window {window!r}; options: {sorted(_BY_NAME)}") \
            from None
    dt = canonical_dtype(dtype)
    return forward_op("get_window",
                      lambda: fn(int(win_length), *args,
                                 sym=not fftbins).astype(dt),
                      [], differentiable=False)


register_op("get_window", get_window, get_window.__doc__,
            differentiable=False, category="window", public=get_window)
