"""paddle.optimizer namespace (parity: python/paddle/optimizer/__init__.py)."""

from . import lr
from .optimizer import (SGD, Adagrad, Adam, Adamax, AdamW, Lamb, Momentum,
                        Optimizer, RMSProp)

__all__ = ["lr", "SGD", "Momentum", "Adam", "AdamW", "Adamax", "Adagrad", "RMSProp",
           "Lamb", "Optimizer"]
