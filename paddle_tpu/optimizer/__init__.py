"""paddle.optimizer namespace (parity: python/paddle/optimizer/__init__.py)."""

from . import lr
from . import ops as optimizer_ops
from .optimizer import (ASGD, LBFGS, SGD, Adadelta, Adagrad, Adam, Adamax,
                        AdamW, Lamb, Momentum, NAdam, Optimizer, RAdam,
                        RMSProp, Rprop)

__all__ = ["lr", "SGD", "Momentum", "Adam", "AdamW", "Adamax", "Adagrad",
           "RMSProp", "Lamb", "Optimizer", "Adadelta", "Rprop", "NAdam",
           "RAdam", "ASGD", "LBFGS"]
