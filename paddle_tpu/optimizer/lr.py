"""LR schedulers.

Parity target: ``python/paddle/optimizer/lr.py`` in the reference (LRScheduler base
with get_lr/step/state_dict and the full scheduler family).
"""

from __future__ import annotations

import math
from typing import List, Optional

__all__ = ["LRScheduler", "NoamDecay", "ExponentialDecay", "NaturalExpDecay",
           "InverseTimeDecay", "PolynomialDecay", "LinearWarmup", "PiecewiseDecay",
           "CosineAnnealingDecay", "MultiStepDecay", "StepDecay", "LambdaDecay",
           "ReduceOnPlateau", "MultiplicativeDecay", "OneCycleLR",
           "CyclicLR", "CosineAnnealingWarmRestarts"]


class LRScheduler:
    def __init__(self, learning_rate=0.1, last_epoch=-1, verbose=False):
        self.base_lr = float(learning_rate)
        self.last_epoch = last_epoch
        self.verbose = verbose
        self.last_lr = self.base_lr
        self.step()

    def __call__(self):
        return self.last_lr

    def step(self, epoch=None):
        if epoch is None:
            self.last_epoch += 1
        else:
            self.last_epoch = epoch
        self.last_lr = self.get_lr()
        if self.verbose:
            print(f"Epoch {self.last_epoch}: lr set to {self.last_lr}")

    def get_lr(self):
        raise NotImplementedError

    def state_dict(self):
        return {k: v for k, v in self.__dict__.items()
                if isinstance(v, (int, float, bool, str, list))}

    def set_state_dict(self, state):
        self.__dict__.update(state)

    set_dict = set_state_dict


class NoamDecay(LRScheduler):
    def __init__(self, d_model, warmup_steps, learning_rate=1.0, last_epoch=-1,
                 verbose=False):
        self.d_model = d_model
        self.warmup_steps = warmup_steps
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        step = max(self.last_epoch, 1)
        return self.base_lr * (self.d_model ** -0.5) * min(
            step ** -0.5, step * self.warmup_steps ** -1.5)


class ExponentialDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * self.gamma ** self.last_epoch


class NaturalExpDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * math.exp(-self.gamma * self.last_epoch)


class InverseTimeDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr / (1 + self.gamma * self.last_epoch)


class PolynomialDecay(LRScheduler):
    def __init__(self, learning_rate, decay_steps, end_lr=0.0001, power=1.0,
                 cycle=False, last_epoch=-1, verbose=False):
        self.decay_steps = decay_steps
        self.end_lr = end_lr
        self.power = power
        self.cycle = cycle
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        step = self.last_epoch
        decay_steps = self.decay_steps
        if self.cycle:
            div = math.ceil(step / decay_steps) if step > 0 else 1
            decay_steps = decay_steps * max(div, 1)
        else:
            step = min(step, decay_steps)
        return (self.base_lr - self.end_lr) * \
            (1 - step / decay_steps) ** self.power + self.end_lr


class LinearWarmup(LRScheduler):
    def __init__(self, learning_rate, warmup_steps, start_lr, end_lr, last_epoch=-1,
                 verbose=False):
        self.lr_after = learning_rate  # float or LRScheduler
        self.warmup_steps = warmup_steps
        self.start_lr = start_lr
        self.end_lr = end_lr
        super().__init__(start_lr, last_epoch, verbose)

    def get_lr(self):
        if self.last_epoch < self.warmup_steps:
            return (self.end_lr - self.start_lr) * \
                self.last_epoch / self.warmup_steps + self.start_lr
        if isinstance(self.lr_after, ReduceOnPlateau):
            # metric-driven, not epoch-indexed: the user drives its .step(metrics);
            # here just read its current lr
            return self.lr_after()
        if isinstance(self.lr_after, LRScheduler):
            self.lr_after.last_epoch = self.last_epoch - self.warmup_steps
            self.lr_after.last_lr = self.lr_after.get_lr()
            return self.lr_after()
        return float(self.lr_after)

    def state_dict(self):
        d = super().state_dict()
        if isinstance(self.lr_after, LRScheduler):
            d["lr_after"] = self.lr_after.state_dict()
        return d

    def set_state_dict(self, state):
        nested = state.pop("lr_after", None) if isinstance(state, dict) else None
        super().set_state_dict(state)
        if nested is not None and isinstance(self.lr_after, LRScheduler):
            self.lr_after.set_state_dict(nested)


class PiecewiseDecay(LRScheduler):
    def __init__(self, boundaries, values, last_epoch=-1, verbose=False):
        self.boundaries = list(boundaries)
        self.values = list(values)
        super().__init__(values[0], last_epoch, verbose)

    def get_lr(self):
        for b, v in zip(self.boundaries, self.values):
            if self.last_epoch < b:
                return v
        return self.values[len(self.boundaries)]


class CosineAnnealingDecay(LRScheduler):
    def __init__(self, learning_rate, T_max, eta_min=0, last_epoch=-1, verbose=False):
        self.T_max = T_max
        self.eta_min = eta_min
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.eta_min + (self.base_lr - self.eta_min) * \
            (1 + math.cos(math.pi * self.last_epoch / self.T_max)) / 2


class CosineAnnealingWarmRestarts(LRScheduler):
    def __init__(self, learning_rate, T_0, T_mult=1, eta_min=0, last_epoch=-1,
                 verbose=False):
        self.T_0 = T_0
        self.T_mult = T_mult
        self.eta_min = eta_min
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        t = self.last_epoch
        t_i = self.T_0
        while t >= t_i:
            t -= t_i
            t_i *= self.T_mult
        return self.eta_min + (self.base_lr - self.eta_min) * \
            (1 + math.cos(math.pi * t / t_i)) / 2


class MultiStepDecay(LRScheduler):
    def __init__(self, learning_rate, milestones, gamma=0.1, last_epoch=-1,
                 verbose=False):
        self.milestones = list(milestones)
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        n = sum(1 for m in self.milestones if self.last_epoch >= m)
        return self.base_lr * self.gamma ** n


class StepDecay(LRScheduler):
    def __init__(self, learning_rate, step_size, gamma=0.1, last_epoch=-1,
                 verbose=False):
        self.step_size = step_size
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * self.gamma ** (self.last_epoch // self.step_size)


class LambdaDecay(LRScheduler):
    def __init__(self, learning_rate, lr_lambda, last_epoch=-1, verbose=False):
        self.lr_lambda = lr_lambda
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * self.lr_lambda(self.last_epoch)

    def state_dict(self):
        d = super().state_dict()
        d.pop("lr_lambda", None)
        return d


class MultiplicativeDecay(LRScheduler):
    def __init__(self, learning_rate, lr_lambda, last_epoch=-1, verbose=False):
        self.lr_lambda = lr_lambda
        self._cur = float(learning_rate)
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        if self.last_epoch > 0:
            self._cur = self._cur * self.lr_lambda(self.last_epoch)
        return self._cur


class ReduceOnPlateau(LRScheduler):
    def __init__(self, learning_rate, mode="min", factor=0.1, patience=10,
                 threshold=1e-4, threshold_mode="rel", cooldown=0, min_lr=0,
                 epsilon=1e-8, verbose=False):
        self.mode = mode
        self.factor = factor
        self.patience = patience
        self.threshold = threshold
        self.threshold_mode = threshold_mode
        self.cooldown = cooldown
        self.min_lr = min_lr
        self.epsilon = epsilon
        self.best = None
        self.num_bad = 0
        self.cooldown_counter = 0
        self.base_lr = float(learning_rate)
        self.last_lr = self.base_lr
        self.last_epoch = 0
        self.verbose = verbose

    def get_lr(self):
        return self.last_lr

    def step(self, metrics=None, epoch=None):
        if metrics is None:
            return
        m = float(metrics.item() if hasattr(metrics, "item") else metrics)
        better = False
        if self.best is None:
            better = True
        elif self.mode == "min":
            thr = self.best * (1 - self.threshold) if self.threshold_mode == "rel" \
                else self.best - self.threshold
            better = m < thr
        else:
            thr = self.best * (1 + self.threshold) if self.threshold_mode == "rel" \
                else self.best + self.threshold
            better = m > thr
        if better:
            self.best = m
            self.num_bad = 0
        else:
            self.num_bad += 1
        if self.cooldown_counter > 0:
            self.cooldown_counter -= 1
            self.num_bad = 0
        if self.num_bad > self.patience:
            new_lr = max(self.last_lr * self.factor, self.min_lr)
            if self.last_lr - new_lr > self.epsilon:
                self.last_lr = new_lr
            self.cooldown_counter = self.cooldown
            self.num_bad = 0


class OneCycleLR(LRScheduler):
    def __init__(self, max_learning_rate, total_steps, divide_factor=25.0,
                 end_learning_rate=0.0001, phase_pct=0.3, anneal_strategy="cos",
                 three_phase=False, last_epoch=-1, verbose=False):
        self.max_lr = max_learning_rate
        self.total_steps = total_steps
        self.initial_lr = max_learning_rate / divide_factor
        self.end_lr = end_learning_rate
        self.phase_pct = phase_pct
        self.anneal = anneal_strategy
        self.three_phase = three_phase
        super().__init__(self.initial_lr, last_epoch, verbose)

    def _interp(self, start, end, pct):
        if self.anneal == "cos":
            return end + (start - end) * (1 + math.cos(math.pi * pct)) / 2
        return start + (end - start) * pct

    def get_lr(self):
        step = min(self.last_epoch, self.total_steps)
        up = int(self.phase_pct * self.total_steps)
        if step <= up and up > 0:
            return self._interp(self.initial_lr, self.max_lr, step / up)
        if self.three_phase:
            # up -> symmetric down to initial_lr -> annihilate to end_lr
            down_end = min(2 * up, self.total_steps)
            if step <= down_end:
                return self._interp(self.max_lr, self.initial_lr,
                                    (step - up) / max(down_end - up, 1))
            return self._interp(self.initial_lr, self.end_lr,
                                (step - down_end) / max(self.total_steps - down_end, 1))
        down = self.total_steps - up
        return self._interp(self.max_lr, self.end_lr,
                            (step - up) / max(down, 1))


class CyclicLR(LRScheduler):
    def __init__(self, base_learning_rate, max_learning_rate, step_size_up,
                 step_size_down=None, mode="triangular", exp_gamma=1.0,
                 scale_fn=None, scale_mode="cycle", last_epoch=-1, verbose=False):
        self.max_lr = max_learning_rate
        self.up = step_size_up
        self.down = step_size_down if step_size_down is not None else step_size_up
        self.mode = mode
        self.exp_gamma = exp_gamma
        self.scale_fn = scale_fn
        self.scale_mode = scale_mode
        super().__init__(base_learning_rate, last_epoch, verbose)

    def get_lr(self):
        total = self.up + self.down
        cycle = math.floor(1 + self.last_epoch / total)
        x = self.last_epoch - (cycle - 1) * total
        scale = x / self.up if x <= self.up else 1 - (x - self.up) / self.down
        amp = (self.max_lr - self.base_lr) * scale
        if self.scale_fn is not None:
            arg = cycle if self.scale_mode == "cycle" else self.last_epoch
            amp *= self.scale_fn(arg)
        elif self.mode == "triangular2":
            amp /= 2 ** (cycle - 1)
        elif self.mode == "exp_range":
            amp *= self.exp_gamma ** self.last_epoch
        return self.base_lr + amp


# ---------------------------------------------------------------------------
# r5: the legacy functional decay ops (ref: the *_decay ops the reference
# keeps in fluid/layers/learning_rate_scheduler + ops.yaml: each computes
# lr(step) as a graph op). Pure closed forms over a step count — usable
# inside a compiled train step (the scheduler classes above are the
# stateful eager tier).
# ---------------------------------------------------------------------------

def exponential_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    """lr * decay_rate^(step/decay_steps) (ref: exponential_decay op)."""
    def at(step):
        e = step / decay_steps
        if staircase:
            e = e // 1
        return learning_rate * decay_rate ** e
    return at


def natural_exp_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    """lr * e^(-decay_rate * step/decay_steps)."""
    import math
    def at(step):
        e = step / decay_steps
        if staircase:
            e = e // 1
        return learning_rate * math.e ** (-decay_rate * e)
    return at


def inverse_time_decay(learning_rate, decay_steps, decay_rate,
                       staircase=False):
    """lr / (1 + decay_rate * step/decay_steps)."""
    def at(step):
        e = step / decay_steps
        if staircase:
            e = e // 1
        return learning_rate / (1 + decay_rate * e)
    return at


def polynomial_decay(learning_rate, decay_steps, end_learning_rate=1e-4,
                     power=1.0, cycle=False):
    """Polynomial ramp to end_learning_rate (ref: polynomial_decay op)."""
    def at(step):
        s = min(step, decay_steps) if not cycle else step % decay_steps
        frac = (1 - s / decay_steps) ** power
        return (learning_rate - end_learning_rate) * frac + end_learning_rate
    return at


def piecewise_decay(boundaries, values):
    """Step function over boundaries (ref: piecewise_decay op)."""
    def at(step):
        for b, v in zip(boundaries, values):
            if step < b:
                return v
        return values[len(boundaries)]
    return at


def cosine_decay(learning_rate, step_each_epoch, epochs):
    """Half-cosine anneal (ref: cosine_decay op)."""
    import math
    def at(step):
        ep = step // step_each_epoch
        return learning_rate * 0.5 * (math.cos(ep * math.pi / epochs) + 1)
    return at


def noam_decay(d_model, warmup_steps, learning_rate=1.0):
    """Transformer Noam schedule (ref: noam_decay op)."""
    def at(step):
        step = max(step, 1)
        return learning_rate * d_model ** -0.5 * min(
            step ** -0.5, step * warmup_steps ** -1.5)
    return at


def linear_lr_warmup(learning_rate, warmup_steps, start_lr, end_lr):
    """Linear warmup wrapper (ref: linear_lr_warmup op); learning_rate may
    be a constant or another decay callable."""
    def at(step):
        if step < warmup_steps:
            return start_lr + (end_lr - start_lr) * step / warmup_steps
        return learning_rate(step) if callable(learning_rate) \
            else learning_rate
    return at


__all__ = [n for n in dir() if not n.startswith("_")]


def _register_decay_ops():
    from ..core.dispatch import OP_REGISTRY, register_op
    for _n in ["exponential_decay", "natural_exp_decay", "inverse_time_decay",
               "polynomial_decay", "piecewise_decay", "cosine_decay",
               "noam_decay", "linear_lr_warmup"]:
        if _n not in OP_REGISTRY:
            _f = globals()[_n]
            register_op(_n, _f, (_f.__doc__ or "").strip().split("\n")[0],
                        differentiable=False, category="lr", public=_f)


_register_decay_ops()
