"""Optimizer update-rule kernels.

Parity target: the optimizer ops in the reference's ops.yaml (adadelta_,
adamax_, ftrl, lamb_, lars_momentum_, proximal_adagrad, proximal_gd,
decayed_adagrad, sparse_momentum, dgc_momentum) — upstream each is a CUDA
kernel mutating param/state in place; here each is a PURE function
``(param, grad, *state) -> (new_param, *new_state)`` so the whole optimizer
step fuses into the training XLA program (the optimizer classes in this
package are built the same way; these ops expose the raw rules under the
reference's names)."""

from __future__ import annotations

import jax.numpy as jnp

from ..core.dispatch import register_op
from ..ops._helpers import ensure_tensor, forward_op

__all__ = [
    "adadelta_update", "adamax_update", "ftrl_update", "lamb_update",
    "lars_momentum_update", "proximal_adagrad_update", "proximal_gd_update",
    "decayed_adagrad_update", "sparse_momentum_update", "dgc_momentum_update",
]


def _op(name, impl, tensors):
    return forward_op(name, impl, [ensure_tensor(t) for t in tensors],
                      differentiable=False)


def adadelta_update(param, grad, avg_squared_grad, avg_squared_update,
                    rho: float = 0.95, epsilon: float = 1e-6,
                    learning_rate: float = 1.0, name=None):
    """Adadelta rule (ref: adadelta_ op): accumulate E[g^2], scale by
    RMS(Δx)/RMS(g)."""
    def impl(p, g, eg, ex):
        eg2 = rho * eg + (1 - rho) * g * g
        upd = jnp.sqrt(ex + epsilon) / jnp.sqrt(eg2 + epsilon) * g
        ex2 = rho * ex + (1 - rho) * upd * upd
        return p - learning_rate * upd, eg2, ex2
    return _op("adadelta_update", impl,
               [param, grad, avg_squared_grad, avg_squared_update])


def adamax_update(param, grad, moment, inf_norm, beta1_pow,
                  learning_rate: float = 0.001, beta1: float = 0.9,
                  beta2: float = 0.999, epsilon: float = 1e-8, name=None):
    """Adamax rule (ref: adamax_ op): infinity-norm second moment."""
    def impl(p, g, m, u, b1p):
        m2 = beta1 * m + (1 - beta1) * g
        u2 = jnp.maximum(beta2 * u, jnp.abs(g))
        step = learning_rate / (1 - b1p)
        return p - step * m2 / (u2 + epsilon), m2, u2, b1p * beta1
    return _op("adamax_update", impl,
               [param, grad, moment, inf_norm, beta1_pow])


def ftrl_update(param, grad, squared_accum, linear_accum,
                learning_rate: float = 0.01, l1: float = 0.0,
                l2: float = 0.0, lr_power: float = -0.5, name=None):
    """FTRL-proximal rule (ref: ftrl op, the CTR workhorse)."""
    def impl(p, g, sq, lin):
        new_sq = sq + g * g
        sigma = (new_sq ** (-lr_power) - sq ** (-lr_power)) / learning_rate
        new_lin = lin + g - sigma * p
        pre = jnp.clip(new_lin, -l1, l1) - new_lin
        denom = new_sq ** (-lr_power) / learning_rate + 2 * l2
        return pre / denom, new_sq, new_lin
    return _op("ftrl_update", impl, [param, grad, squared_accum,
                                     linear_accum])


def lamb_update(param, grad, moment1, moment2, beta1_pow, beta2_pow,
                learning_rate: float = 0.001, beta1: float = 0.9,
                beta2: float = 0.999, epsilon: float = 1e-6,
                weight_decay: float = 0.01, name=None):
    """LAMB rule (ref: lamb_ op): Adam direction with layerwise trust
    ratio."""
    def impl(p, g, m, v, b1p, b2p):
        m2 = beta1 * m + (1 - beta1) * g
        v2 = beta2 * v + (1 - beta2) * g * g
        mh = m2 / (1 - b1p)
        vh = v2 / (1 - b2p)
        r = mh / (jnp.sqrt(vh) + epsilon) + weight_decay * p
        w_norm = jnp.linalg.norm(p)
        r_norm = jnp.linalg.norm(r)
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        return (p - learning_rate * trust * r, m2, v2,
                b1p * beta1, b2p * beta2)
    return _op("lamb_update", impl, [param, grad, moment1, moment2,
                                     beta1_pow, beta2_pow])


def lars_momentum_update(param, grad, velocity, learning_rate: float = 0.001,
                         mu: float = 0.9, lars_coeff: float = 0.001,
                         lars_weight_decay: float = 0.0005,
                         epsilon: float = 0.0, name=None):
    """LARS rule (ref: lars_momentum_ op): local LR scaled by
    ||w||/||g||."""
    def impl(p, g, v):
        w_norm = jnp.linalg.norm(p)
        g_norm = jnp.linalg.norm(g)
        local = jnp.where(
            (w_norm > 0) & (g_norm > 0),
            lars_coeff * w_norm /
            (g_norm + lars_weight_decay * w_norm + epsilon), 1.0)
        v2 = mu * v + local * learning_rate * (g + lars_weight_decay * p)
        return p - v2, v2
    return _op("lars_momentum_update", impl, [param, grad, velocity])


def proximal_adagrad_update(param, grad, moment,
                            learning_rate: float = 0.01, l1: float = 0.0,
                            l2: float = 0.0, name=None):
    """Proximal Adagrad rule (ref: proximal_adagrad op): adagrad step then
    soft-threshold."""
    def impl(p, g, m):
        m2 = m + g * g
        lr = learning_rate / jnp.sqrt(m2)
        pro = p - lr * g
        out = jnp.sign(pro) * jnp.clip(jnp.abs(pro) - lr * l1, 0) / \
            (1.0 + lr * l2)
        return out, m2
    return _op("proximal_adagrad_update", impl, [param, grad, moment])


def proximal_gd_update(param, grad, learning_rate: float = 0.01,
                       l1: float = 0.0, l2: float = 0.0, name=None):
    """Proximal gradient-descent rule (ref: proximal_gd op)."""
    def impl(p, g):
        pro = p - learning_rate * g
        return jnp.sign(pro) * jnp.clip(
            jnp.abs(pro) - learning_rate * l1, 0) / \
            (1.0 + learning_rate * l2)
    return _op("proximal_gd_update", impl, [param, grad])


def decayed_adagrad_update(param, grad, moment, learning_rate: float = 0.01,
                           decay: float = 0.95, epsilon: float = 1e-6,
                           name=None):
    """Decayed Adagrad rule (ref: decayed_adagrad op)."""
    def impl(p, g, m):
        m2 = decay * m + (1 - decay) * g * g
        return p - learning_rate * g / (jnp.sqrt(m2) + epsilon), m2
    return _op("decayed_adagrad_update", impl, [param, grad, moment])


def sparse_momentum_update(param, grad, velocity, index, axis: int = 0,
                           learning_rate: float = 0.001, mu: float = 0.9,
                           name=None):
    """Momentum touching only the rows in ``index`` (ref: sparse_momentum
    op — the SelectedRows update; the parameter-server embedding path
    uses exactly this shape of update)."""
    def impl(p, g, v, idx):
        v_rows = mu * jnp.take(v, idx, axis) + g
        new_v = v.at[idx].set(v_rows) if axis == 0 else \
            jnp.moveaxis(jnp.moveaxis(v, axis, 0).at[idx].set(
                jnp.moveaxis(v_rows, axis, 0)), 0, axis)
        p_rows = jnp.take(p, idx, axis) - learning_rate * v_rows
        new_p = p.at[idx].set(p_rows) if axis == 0 else \
            jnp.moveaxis(jnp.moveaxis(p, axis, 0).at[idx].set(
                jnp.moveaxis(p_rows, axis, 0)), 0, axis)
        return new_p, new_v
    return _op("sparse_momentum_update", impl,
               [param, grad, velocity, index])


def dgc_momentum_update(param, grad, velocity, accum_grad,
                        learning_rate: float = 0.001, mu: float = 0.9,
                        sparsity: float = 0.75, name=None):
    """Deep-gradient-compression momentum (ref: dgc_momentum_op): momentum
    correction on the locally-accumulated gradient, top-|sparsity| values
    sent (here: applied), the rest re-accumulated."""
    def impl(p, g, v, acc):
        v2 = mu * v + g
        u = acc + v2
        flat = jnp.abs(u).reshape(-1)
        k = max(1, int(flat.shape[0] * (1 - sparsity)))
        thresh = jnp.sort(flat)[-k]
        mask = jnp.abs(u) >= thresh
        applied = jnp.where(mask, u, 0)
        return p - learning_rate * applied, v2 * 0.0, jnp.where(mask, 0, u)
    return _op("dgc_momentum_update", impl,
               [param, grad, velocity, accum_grad])


for _n in __all__:
    _f = globals()[_n]
    register_op(_n, _f, (_f.__doc__ or "").strip().split("\n")[0],
                differentiable=False, category="optimizer", public=_f)


# -- r5 batch 2: the mainline update rules as ops too (ref ops.yaml: sgd_,
# momentum_, adam_, adamw_, rmsprop_, adagrad_, nadam_, radam_) — the
# optimizer classes implement the same math; these are the raw kernels.

def sgd_update(param, grad, learning_rate: float = 0.01, name=None):
    """Plain SGD rule (ref: sgd_ op)."""
    return _op("sgd_update",
               lambda p, g: p - learning_rate * g, [param, grad])


def momentum_update(param, grad, velocity, learning_rate: float = 0.01,
                    mu: float = 0.9, use_nesterov: bool = False, name=None):
    """(Nesterov) momentum rule (ref: momentum_ op)."""
    def impl(p, g, v):
        v2 = mu * v + g
        step = g + mu * v2 if use_nesterov else v2
        return p - learning_rate * step, v2
    return _op("momentum_update", impl, [param, grad, velocity])


def adagrad_update(param, grad, moment, learning_rate: float = 0.01,
                   epsilon: float = 1e-6, name=None):
    """Adagrad rule (ref: adagrad_ op)."""
    def impl(p, g, m):
        m2 = m + g * g
        return p - learning_rate * g / (jnp.sqrt(m2) + epsilon), m2
    return _op("adagrad_update", impl, [param, grad, moment])


def rmsprop_update(param, grad, moment, mean_square,
                   learning_rate: float = 0.01, rho: float = 0.95,
                   epsilon: float = 1e-6, momentum: float = 0.0, name=None):
    """RMSProp rule (ref: rmsprop_ op)."""
    def impl(p, g, m, ms):
        ms2 = rho * ms + (1 - rho) * g * g
        m2 = momentum * m + learning_rate * g / jnp.sqrt(ms2 + epsilon)
        return p - m2, m2, ms2
    return _op("rmsprop_update", impl, [param, grad, moment, mean_square])


def adam_update(param, grad, moment1, moment2, beta1_pow, beta2_pow,
                learning_rate: float = 0.001, beta1: float = 0.9,
                beta2: float = 0.999, epsilon: float = 1e-8, name=None):
    """Adam rule (ref: adam_ op)."""
    def impl(p, g, m, v, b1p, b2p):
        m2 = beta1 * m + (1 - beta1) * g
        v2 = beta2 * v + (1 - beta2) * g * g
        mh = m2 / (1 - b1p)
        vh = v2 / (1 - b2p)
        return (p - learning_rate * mh / (jnp.sqrt(vh) + epsilon),
                m2, v2, b1p * beta1, b2p * beta2)
    return _op("adam_update", impl, [param, grad, moment1, moment2,
                                     beta1_pow, beta2_pow])


def adamw_update(param, grad, moment1, moment2, beta1_pow, beta2_pow,
                 learning_rate: float = 0.001, beta1: float = 0.9,
                 beta2: float = 0.999, epsilon: float = 1e-8,
                 weight_decay: float = 0.01, name=None):
    """AdamW rule (decoupled decay; ref: adamw_ op)."""
    def impl(p, g, m, v, b1p, b2p):
        p = p * (1 - learning_rate * weight_decay)
        m2 = beta1 * m + (1 - beta1) * g
        v2 = beta2 * v + (1 - beta2) * g * g
        mh = m2 / (1 - b1p)
        vh = v2 / (1 - b2p)
        return (p - learning_rate * mh / (jnp.sqrt(vh) + epsilon),
                m2, v2, b1p * beta1, b2p * beta2)
    return _op("adamw_update", impl, [param, grad, moment1, moment2,
                                      beta1_pow, beta2_pow])


def nadam_update(param, grad, moment1, moment2, beta1_pow, beta2_pow,
                 learning_rate: float = 0.001, beta1: float = 0.9,
                 beta2: float = 0.999, epsilon: float = 1e-8, name=None):
    """NAdam rule (Nesterov Adam; ref: nadam_ op)."""
    def impl(p, g, m, v, b1p, b2p):
        m2 = beta1 * m + (1 - beta1) * g
        v2 = beta2 * v + (1 - beta2) * g * g
        mh = (beta1 * m2 + (1 - beta1) * g) / (1 - b1p * beta1)
        vh = v2 / (1 - b2p)
        return (p - learning_rate * mh / (jnp.sqrt(vh) + epsilon),
                m2, v2, b1p * beta1, b2p * beta2)
    return _op("nadam_update", impl, [param, grad, moment1, moment2,
                                      beta1_pow, beta2_pow])


def radam_update(param, grad, moment1, moment2, beta1_pow, beta2_pow,
                 step, learning_rate: float = 0.001, beta1: float = 0.9,
                 beta2: float = 0.999, epsilon: float = 1e-8, name=None):
    """RAdam rule (rectified Adam; ref: radam_ op)."""
    def impl(p, g, m, v, b1p, b2p, t):
        m2 = beta1 * m + (1 - beta1) * g
        v2 = beta2 * v + (1 - beta2) * g * g
        mh = m2 / (1 - b1p)
        rho_inf = 2 / (1 - beta2) - 1
        rho_t = rho_inf - 2 * t * b2p * beta2 / (1 - b2p * beta2)
        vh = jnp.sqrt(v2 / (1 - b2p * beta2))
        r = jnp.sqrt(((rho_t - 4) * (rho_t - 2) * rho_inf) /
                     jnp.maximum((rho_inf - 4) * (rho_inf - 2) * rho_t,
                                 1e-12))
        upd = jnp.where(rho_t > 5.0,
                        r * mh / (vh + epsilon), mh)
        return (p - learning_rate * upd, m2, v2,
                b1p * beta1, b2p * beta2)
    return _op("radam_update", impl, [param, grad, moment1, moment2,
                                      beta1_pow, beta2_pow, step])


__all__ += ["sgd_update", "momentum_update", "adagrad_update",
            "rmsprop_update", "adam_update", "adamw_update",
            "nadam_update", "radam_update"]
for _n in ["sgd_update", "momentum_update", "adagrad_update",
           "rmsprop_update", "adam_update", "adamw_update",
           "nadam_update", "radam_update"]:
    _f = globals()[_n]
    register_op(_n, _f, (_f.__doc__ or "").strip().split("\n")[0],
                differentiable=False, category="optimizer", public=_f)
