"""Optimizer base + concrete optimizers.

Parity target: ``python/paddle/optimizer/`` in the reference (Optimizer base with
accumulators, `step`/`clear_grad`/`minimize`, grad clip, regularization, LR
scheduler integration, multi_precision master weights). TPU redesign: each optimizer
update is one pure-jnp function over (param, grad, accumulators); under
``jit.to_static`` the whole step fuses into the compiled program. Accumulators are
plain Tensors keyed by parameter name (Paddle's accumulator convention).
"""

from __future__ import annotations

from collections import defaultdict
from typing import Any, Dict, List, Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Parameter, Tensor, _wrap_value, to_tensor
from ..core import autograd
from .lr import LRScheduler


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, multi_precision=False):
        self._lr = learning_rate
        self._parameter_list = list(parameters) if parameters is not None else None
        self._grad_clip = grad_clip
        self._weight_decay = weight_decay
        self._multi_precision = multi_precision
        self._accumulators: Dict[str, Dict[str, Tensor]] = defaultdict(dict)
        self._master_weights: Dict[str, Tensor] = {}
        self._step_count = 0

    # -- lr ------------------------------------------------------------------
    def _lr_value(self) -> float:
        if isinstance(self._lr, LRScheduler):
            return float(self._lr())
        return float(self._lr)

    def get_lr(self):
        # under a to_static trace the lr is a host-scalar program input, so a
        # scheduler stepping between compiled calls takes effect without retracing
        from ..core.tensor import _trace_hook
        ctx = _trace_hook.ctx
        if ctx is not None:
            return ctx.host_scalar(("opt_lr", id(self)), self._lr_value)
        return self._lr_value()

    def set_lr(self, value: float):
        if isinstance(self._lr, LRScheduler):
            raise RuntimeError("cannot set_lr when using an LRScheduler")
        self._lr = float(value)

    def set_lr_scheduler(self, scheduler: LRScheduler):
        self._lr = scheduler

    # -- accumulators --------------------------------------------------------
    def _add_accumulator(self, name: str, param: Tensor, fill_value=0.0, dtype=None,
                         shape=None):
        store = self._accumulators[name]
        if param.name not in store:
            pending = getattr(self, "_pending_state", None)
            key = f"{param.name}_{name}"
            if pending and key in pending:
                v = pending.pop(key)
                store[param.name] = v if isinstance(v, Tensor) else to_tensor(v)
            else:
                shp = shape if shape is not None else param._value.shape
                dt = dtype or (jnp.float32 if self._multi_precision
                               else param._value.dtype)
                t = _wrap_value(jnp.full(shp, fill_value, dt))
                # the health sentinel's unborn-state rollback: an
                # accumulator CREATED during a bad step rolls back to this
                # creation fill (velocity 0, beta pows 1.0, ...) — as if
                # the step never ran (health.sentinel.Sentinel.gate)
                t._acc_init = float(fill_value)
                store[param.name] = t
        return store[param.name]

    def _get_accumulator(self, name: str, param: Tensor) -> Tensor:
        return self._accumulators[name][param.name]

    def _master(self, p: Parameter):
        """fp32 master weight for low-precision params (ref: multi_precision /
        master_weights in paddle optimizers + amp O2)."""
        if not self._multi_precision or p._value.dtype == jnp.float32:
            return None
        if p.name not in self._master_weights:
            mw = _wrap_value(p._value.astype(jnp.float32))
            # sentinel unborn-state rollback: a master created during a bad
            # step re-derives from its (rolled-back) source param
            mw._master_of = p
            self._master_weights[p.name] = mw
        return self._master_weights[p.name]

    # -- the step ------------------------------------------------------------
    def _params(self) -> List[Parameter]:
        if self._parameter_list is None:
            raise ValueError("optimizer constructed without parameters")
        flat = []
        for p in self._parameter_list:
            if isinstance(p, dict):  # param group
                flat.extend(p["params"])
            else:
                flat.append(p)
        return flat

    @autograd.no_grad()
    def step(self):
        params_grads = [(p, p.grad) for p in self._params()
                        if not p.stop_gradient and p.grad is not None]
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        lr = self.get_lr()
        self._step_count += 1
        for p, g in params_grads:
            param_lr = lr * getattr(p, "optimize_attr", {}).get("learning_rate", 1.0)
            g_val = g._value
            wd, l1 = self._decay_value(p)
            if wd:  # fold into gradient (paddle regularizer semantics)
                pv = p._value.astype(g_val.dtype)
                g_val = g_val + wd * (jnp.sign(pv) if l1 else pv)
            master = self._master(p)
            base = master._value if master is not None else p._value
            new_base = self._apply_one(p, base, g_val.astype(base.dtype), param_lr)
            if master is not None:
                master._value = new_base
                p._value = new_base.astype(p._value.dtype)
            else:
                p._value = new_base.astype(p._value.dtype)
            p._version += 1

    def _decay_value(self, p):
        """Returns (coeff, is_l1). Per-param regularizer wins over the optimizer's
        weight_decay (paddle semantics)."""
        from ..regularizer import L1Decay

        reg = getattr(p, "regularizer", None)
        if reg is not None:
            return float(getattr(reg, "coeff", 0.0)), isinstance(reg, L1Decay)
        wd = self._weight_decay
        if wd is None:
            return 0.0, False
        if hasattr(wd, "coeff"):
            return float(wd.coeff), isinstance(wd, L1Decay)
        return float(wd), False

    def _apply_one(self, p, value, grad, lr):
        raise NotImplementedError

    def clear_grad(self, set_to_zero: bool = False):
        for p in self._params():
            p.clear_grad()

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        from ..static import in_static_mode
        if in_static_mode():
            # static workflow: record (optimizer, loss) on the program —
            # Executor.run replays forward, then backward + this
            # optimizer's update (the append_backward contract)
            from ..static.program import default_main_program
            default_main_program().train_spec = (self, loss)
            return None, []
        loss.backward()
        self.step()
        return None, [(p, p.grad) for p in self._params()]

    def fuse(self, model, loss_fn, **kwargs):
        """Optimizer-side spelling of the fused donation-aware train step
        (jit.train_step.make_train_step): forward + loss + backward + THIS
        optimizer's update compile into one XLA program with the state
        (params + accumulators) donated. Returns a callable
        ``step(inputs, labels) -> loss``."""
        from ..jit.train_step import TrainStep
        return TrainStep(model, self, loss_fn, **kwargs)

    # -- state dict ----------------------------------------------------------
    def state_dict(self) -> Dict:
        out = {}
        for acc_name, store in self._accumulators.items():
            for pname, t in store.items():
                out[f"{pname}_{acc_name}"] = t
        if isinstance(self._lr, LRScheduler):
            out["LR_Scheduler"] = self._lr.state_dict()
        out["@step"] = to_tensor(float(self._step_count))
        if self._master_weights:
            out["master_weights"] = dict(self._master_weights)
        return out

    def set_state_dict(self, state: Dict):
        def _restore(cur: Optional[Tensor], v: Any) -> Tensor:
            """Restore IN PLACE when the existing tensor matches: compiled
            programs (jit fused steps) hold accumulator/master tensor
            IDENTITIES as state slots — rebinding the dict entry to a new
            Tensor would silently desync the live program from the dict
            (e.g. a health rollback that never reaches the compiled
            step). Shape mismatch / no current tensor falls back to the
            old rebind behavior."""
            val = v if isinstance(v, Tensor) else to_tensor(v)
            if cur is not None and tuple(cur.shape) == tuple(val.shape):
                cur._value = val._value.astype(cur._value.dtype)
                cur._version += 1
                return cur
            return val

        if "LR_Scheduler" in state and isinstance(self._lr, LRScheduler):
            self._lr.set_state_dict(state["LR_Scheduler"])
        if "@step" in state:
            v = state["@step"]
            self._step_count = int(v.item() if isinstance(v, Tensor) else v)
        mw = state.get("master_weights", {})
        for k, v in mw.items():
            self._master_weights[k] = _restore(self._master_weights.get(k), v)
        for acc_name, store in list(self._accumulators.items()):
            for pname in list(store):
                key = f"{pname}_{acc_name}"
                if key in state:
                    store[pname] = _restore(store[pname], state[key])
        # keys for accumulators not yet created are applied lazily
        self._pending_state = {k: v for k, v in state.items()
                               if k not in ("LR_Scheduler", "@step", "master_weights")}

class SGD(Optimizer):
    """ref: python/paddle/optimizer/sgd.py"""

    def _apply_one(self, p, value, grad, lr):
        return value - lr * grad


class Momentum(Optimizer):
    """ref: python/paddle/optimizer/momentum.py (use_nesterov supported)."""

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None, name=None,
                 multi_precision=False):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name,
                         multi_precision)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def _apply_one(self, p, value, grad, lr):
        vel = self._add_accumulator("velocity", p, dtype=value.dtype)
        new_v = self._momentum * vel._value + grad
        vel._value = new_v
        if self._nesterov:
            return value - lr * (grad + self._momentum * new_v)
        return value - lr * new_v


class Adam(Optimizer):
    """ref: python/paddle/optimizer/adam.py"""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None, lazy_mode=False,
                 multi_precision=False, use_multi_tensor=False, name=None,
                 amsgrad=False):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name,
                         multi_precision)
        self._beta1 = beta1
        self._beta2 = beta2
        self._eps = epsilon
        self._amsgrad = amsgrad

    def _beta(self, b):
        return float(b.item()) if isinstance(b, Tensor) else float(b)

    def _apply_one(self, p, value, grad, lr):
        b1, b2 = self._beta(self._beta1), self._beta(self._beta2)
        m = self._add_accumulator("moment1", p, dtype=value.dtype)
        v = self._add_accumulator("moment2", p, dtype=value.dtype)
        b1p = self._add_accumulator("beta1_pow_acc", p, fill_value=1.0,
                                    dtype=jnp.float32, shape=())
        b2p = self._add_accumulator("beta2_pow_acc", p, fill_value=1.0,
                                    dtype=jnp.float32, shape=())
        b1p._value = b1p._value * b1
        b2p._value = b2p._value * b2
        m._value = b1 * m._value + (1 - b1) * grad
        v._value = b2 * v._value + (1 - b2) * jnp.square(grad)
        mhat = m._value / (1 - b1p._value)
        if self._amsgrad:
            vmax = self._add_accumulator("moment2_max", p, dtype=value.dtype)
            vmax._value = jnp.maximum(vmax._value, v._value)
            vhat = vmax._value / (1 - b2p._value)
        else:
            vhat = v._value / (1 - b2p._value)
        return value - lr * mhat / (jnp.sqrt(vhat) + self._eps)


class AdamW(Adam):
    """Decoupled weight decay (ref: python/paddle/optimizer/adamw.py)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=0.01, lr_ratio=None,
                 apply_decay_param_fun=None, grad_clip=None, lazy_mode=False,
                 multi_precision=False, name=None, amsgrad=False):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip, lazy_mode, multi_precision,
                         name=name, amsgrad=amsgrad)
        self._wd = weight_decay
        self._apply_decay_fun = apply_decay_param_fun
        self._lr_ratio = lr_ratio

    def _decay_value(self, p):
        return 0.0, False  # decay handled decoupled in _apply_one

    def _apply_one(self, p, value, grad, lr):
        if self._lr_ratio is not None:
            lr = lr * self._lr_ratio(p)
        wd = self._wd if not hasattr(self._wd, "coeff") else self._wd.coeff
        if self._apply_decay_fun is None or self._apply_decay_fun(p.name):
            value = value * (1.0 - lr * float(wd))
        return super()._apply_one(p, value, grad, lr)


class Adamax(Optimizer):
    """ref: python/paddle/optimizer/adamax.py"""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon

    def _apply_one(self, p, value, grad, lr):
        m = self._add_accumulator("moment", p, dtype=value.dtype)
        u = self._add_accumulator("inf_norm", p, dtype=value.dtype)
        b1p = self._add_accumulator("beta1_pow_acc", p, fill_value=1.0,
                                    dtype=jnp.float32, shape=())
        b1p._value = b1p._value * self._beta1
        m._value = self._beta1 * m._value + (1 - self._beta1) * grad
        u._value = jnp.maximum(self._beta2 * u._value, jnp.abs(grad))
        return value - lr / (1 - b1p._value) * m._value / (u._value + self._eps)


class Adagrad(Optimizer):
    """ref: python/paddle/optimizer/adagrad.py"""

    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, name=None,
                 initial_accumulator_value=0.0):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._eps = epsilon
        self._init_acc = initial_accumulator_value

    def _apply_one(self, p, value, grad, lr):
        acc = self._add_accumulator("moment", p, fill_value=self._init_acc,
                                    dtype=value.dtype)
        acc._value = acc._value + jnp.square(grad)
        return value - lr * grad / (jnp.sqrt(acc._value) + self._eps)


class RMSProp(Optimizer):
    """ref: python/paddle/optimizer/rmsprop.py"""

    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._rho, self._eps = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _apply_one(self, p, value, grad, lr):
        ms = self._add_accumulator("mean_square", p, dtype=value.dtype)
        mom = self._add_accumulator("momentum", p, dtype=value.dtype)
        ms._value = self._rho * ms._value + (1 - self._rho) * jnp.square(grad)
        denom = ms._value
        if self._centered:
            mg = self._add_accumulator("mean_grad", p, dtype=value.dtype)
            mg._value = self._rho * mg._value + (1 - self._rho) * grad
            denom = denom - jnp.square(mg._value)
        mom._value = self._momentum * mom._value + \
            lr * grad / jnp.sqrt(denom + self._eps)
        return value - mom._value


class Lamb(Optimizer):
    """ref: python/paddle/optimizer/lamb.py"""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name,
                         multi_precision)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon
        self._lamb_wd = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def _apply_one(self, p, value, grad, lr):
        m = self._add_accumulator("moment1", p, dtype=value.dtype)
        v = self._add_accumulator("moment2", p, dtype=value.dtype)
        b1p = self._add_accumulator("beta1_pow_acc", p, fill_value=1.0,
                                    dtype=jnp.float32, shape=())
        b2p = self._add_accumulator("beta2_pow_acc", p, fill_value=1.0,
                                    dtype=jnp.float32, shape=())
        b1p._value = b1p._value * self._beta1
        b2p._value = b2p._value * self._beta2
        m._value = self._beta1 * m._value + (1 - self._beta1) * grad
        v._value = self._beta2 * v._value + (1 - self._beta2) * jnp.square(grad)
        mhat = m._value / (1 - b1p._value)
        vhat = v._value / (1 - b2p._value)
        r = mhat / (jnp.sqrt(vhat) + self._eps)
        wd = 0.0 if (self._exclude_fn is not None and self._exclude_fn(p)) \
            else self._lamb_wd
        update = r + wd * value
        w_norm = jnp.linalg.norm(value)
        u_norm = jnp.linalg.norm(update)
        trust = jnp.where((w_norm > 0) & (u_norm > 0), w_norm / u_norm, 1.0)
        return value - lr * trust * update


class Adadelta(Optimizer):
    """ref: python/paddle/optimizer/adadelta.py (accumulated squared grads +
    squared updates, rho-averaged)."""

    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None, name=None,
                 multi_precision=False):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._eps = epsilon
        self._rho = rho

    def _apply_one(self, p, value, grad, lr):
        avg_sq_g = self._add_accumulator("avg_squared_grad", p,
                                         dtype=value.dtype)
        avg_sq_u = self._add_accumulator("avg_squared_update", p,
                                         dtype=value.dtype)
        g2 = self._rho * avg_sq_g._value + (1 - self._rho) * jnp.square(grad)
        update = -jnp.sqrt((avg_sq_u._value + self._eps)
                           / (g2 + self._eps)) * grad
        u2 = self._rho * avg_sq_u._value + (1 - self._rho) * jnp.square(update)
        avg_sq_g._value = g2
        avg_sq_u._value = u2
        return value + lr * update


class Rprop(Optimizer):
    """ref: python/paddle/optimizer/rprop.py (sign-based resilient prop)."""

    def __init__(self, learning_rate=0.001, learning_rate_range=(1e-5, 50.0),
                 parameters=None, etas=(0.5, 1.2), grad_clip=None,
                 name=None, multi_precision=False):
        super().__init__(learning_rate, parameters, None, grad_clip, name,
                         multi_precision)
        self._lr_min, self._lr_max = learning_rate_range
        self._eta_neg, self._eta_pos = etas

    def _apply_one(self, p, value, grad, lr):
        prev = self._add_accumulator("prev_grad", p, dtype=value.dtype)
        step_sz = self._add_accumulator("learning_rate_step", p,
                                        fill_value=float(self._lr_value()),
                                        dtype=value.dtype)
        sign = jnp.sign(grad * prev._value)
        factor = jnp.where(sign > 0, self._eta_pos,
                           jnp.where(sign < 0, self._eta_neg, 1.0))
        new_step = jnp.clip(step_sz._value * factor, self._lr_min, self._lr_max)
        # on sign change the pending gradient is zeroed (classic Rprop-)
        g_eff = jnp.where(sign < 0, 0.0, grad)
        step_sz._value = new_step
        prev._value = g_eff
        return value - jnp.sign(g_eff) * new_step


class NAdam(Optimizer):
    """ref: python/paddle/optimizer/nadam.py (Adam + Nesterov momentum
    schedule mu_t = b1*(1 - 0.5*0.96^(t*psi)))."""

    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, momentum_decay=0.004, parameters=None,
                 weight_decay=None, grad_clip=None, name=None,
                 multi_precision=False):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._b1, self._b2, self._eps = beta1, beta2, epsilon
        self._psi = momentum_decay

    def _apply_one(self, p, value, grad, lr):
        # every time-dependent factor lives in a ()-shaped accumulator tensor
        # so the step stays correct when traced ONCE under jit.to_static
        # (reading self._step_count would bake a trace-time constant)
        m = self._add_accumulator("momentum", p, dtype=value.dtype)
        v = self._add_accumulator("moment2", p, dtype=value.dtype)
        mu_prod = self._add_accumulator("mu_product", p, fill_value=1.0,
                                        dtype=jnp.float32, shape=())
        t_acc = self._add_accumulator("t", p, fill_value=0.0,
                                      dtype=jnp.float32, shape=())
        t_acc._value = t_acc._value + 1.0
        t = t_acc._value
        mu_t = self._b1 * (1 - 0.5 * 0.96 ** (t * self._psi))
        mu_t1 = self._b1 * (1 - 0.5 * 0.96 ** ((t + 1) * self._psi))
        mu_prod._value = mu_prod._value * mu_t
        m._value = self._b1 * m._value + (1 - self._b1) * grad
        v._value = self._b2 * v._value + (1 - self._b2) * jnp.square(grad)
        mhat = (mu_t1 * m._value / (1 - mu_prod._value * mu_t1)
                + (1 - mu_t) * grad / (1 - mu_prod._value))
        vhat = v._value / (1 - self._b2 ** t)
        return value - lr * mhat / (jnp.sqrt(vhat) + self._eps)


class RAdam(Optimizer):
    """ref: python/paddle/optimizer/radam.py (rectified Adam: SGD-with-
    momentum warmup until the variance rectification term is defined)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, multi_precision=False):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._b1, self._b2, self._eps = beta1, beta2, epsilon

    def _apply_one(self, p, value, grad, lr):
        # step counter as a traced accumulator (see NAdam note): the
        # rectification branch is a jnp.where so a to_static-compiled step
        # transitions from SGDM-warmup to rectified-Adam at the right time
        m = self._add_accumulator("moment1", p, dtype=value.dtype)
        v = self._add_accumulator("moment2", p, dtype=value.dtype)
        t_acc = self._add_accumulator("t", p, fill_value=0.0,
                                      dtype=jnp.float32, shape=())
        t_acc._value = t_acc._value + 1.0
        t = t_acc._value
        m._value = self._b1 * m._value + (1 - self._b1) * grad
        v._value = self._b2 * v._value + (1 - self._b2) * jnp.square(grad)
        mhat = m._value / (1 - self._b1 ** t)
        rho_inf = 2 / (1 - self._b2) - 1
        rho_t = rho_inf - 2 * t * self._b2 ** t / (1 - self._b2 ** t)
        safe_rho = jnp.maximum(rho_t, 4.0 + 1e-3)
        r = jnp.sqrt(((safe_rho - 4) * (safe_rho - 2) * rho_inf)
                     / ((rho_inf - 4) * (rho_inf - 2) * safe_rho))
        vhat = jnp.sqrt(v._value / (1 - self._b2 ** t))
        rect = value - lr * r * mhat / (vhat + self._eps)
        warm = value - lr * mhat
        return jnp.where(rho_t > 5.0, rect, warm)


class ASGD(Optimizer):
    """ref: python/paddle/optimizer/asgd.py (averaged SGD: the d/y/ys
    recursion over a window of n steps)."""

    def __init__(self, learning_rate=0.001, batch_num=1, parameters=None,
                 weight_decay=None, grad_clip=None, name=None,
                 multi_precision=False):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._n = max(1, int(batch_num))

    def _apply_one(self, p, value, grad, lr):
        d = self._add_accumulator("d", p, dtype=value.dtype)
        ys = self._add_accumulator("ys", p,
                                   shape=(self._n,) + tuple(value.shape),
                                   dtype=value.dtype)
        t_acc = self._add_accumulator("t", p, fill_value=0.0,
                                      dtype=jnp.float32, shape=())
        t_acc._value = t_acc._value + 1.0
        # traced index: correct under a once-traced to_static step
        idx = (t_acc._value.astype(jnp.int32) - 1) % self._n
        y_old = jnp.take(ys._value, idx, axis=0)
        d._value = d._value - y_old + grad
        ys._value = jax.lax.dynamic_update_index_in_dim(
            ys._value, grad.astype(ys._value.dtype), idx, axis=0)
        m = jnp.minimum(t_acc._value, float(self._n))
        return value - lr * d._value / m


class LBFGS(Optimizer):
    """ref: python/paddle/optimizer/lbfgs.py — limited-memory BFGS with the
    closure API: ``step(closure)`` re-evaluates the loss (closure must call
    ``backward()``). Two-loop recursion over a curvature history; step length
    by backtracking Armijo line search (the reference's strong_wolfe is
    approximated by backtracking — documented divergence)."""

    def __init__(self, learning_rate=1.0, max_iter=20, max_eval=None,
                 tolerance_grad=1e-7, tolerance_change=1e-9, history_size=100,
                 line_search_fn=None, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._max_iter = int(max_iter)
        self._tol_grad = float(tolerance_grad)
        self._tol_change = float(tolerance_change)
        self._history = int(history_size)
        self._line_search = line_search_fn  # None or "strong_wolfe"
        self._s, self._y = [], []
        self._prev_flat_g = None

    def _flat(self, vals):
        return jnp.concatenate([v.reshape(-1) for v in vals])

    def _assign_flat(self, flat):
        ofs = 0
        for p in self._params():
            n = int(np.prod(p._value.shape)) if p._value.shape else 1
            p._value = flat[ofs:ofs + n].reshape(p._value.shape).astype(
                p._value.dtype)
            p._version += 1
            ofs += n

    def _gather(self):
        ps = self._params()
        flat_w = self._flat([p._value.astype(jnp.float32) for p in ps])
        flat_g = self._flat([
            (p.grad._value if p.grad is not None
             else jnp.zeros_like(p._value)).astype(jnp.float32) for p in ps])
        return flat_w, flat_g

    def _direction(self, g):
        # two-loop recursion
        q = g
        alphas = []
        for s, y in zip(reversed(self._s), reversed(self._y)):
            rho = 1.0 / jnp.maximum(jnp.vdot(y, s), 1e-10)
            a = rho * jnp.vdot(s, q)
            q = q - a * y
            alphas.append((a, rho, s, y))
        if self._y:
            y_last, s_last = self._y[-1], self._s[-1]
            gamma = jnp.vdot(s_last, y_last) / jnp.maximum(
                jnp.vdot(y_last, y_last), 1e-10)
            q = q * gamma
        for a, rho, s, y in reversed(alphas):
            b = rho * jnp.vdot(y, q)
            q = q + s * (a - b)
        return -q

    def step(self, closure):
        if closure is None:
            raise ValueError("LBFGS.step requires a closure that recomputes "
                             "the loss and calls backward()")
        loss = closure()
        self._step_count += 1
        w, g = self._gather()
        if float(jnp.abs(g).max()) <= self._tol_grad:
            return loss
        for _ in range(self._max_iter):
            d = self._direction(g)
            lr = self._lr_value()
            # backtracking Armijo
            f0 = float(loss)
            gtd = float(jnp.vdot(g, d))
            t = lr
            for _ls in range(10):
                self._assign_flat(w + t * d)
                self.clear_grad()
                loss = closure()
                if float(loss) <= f0 + 1e-4 * t * gtd:
                    break
                t *= 0.5
            w_new, g_new = self._gather()
            s, yv = w_new - w, g_new - g
            if float(jnp.vdot(s, yv)) > 1e-10:
                self._s.append(s)
                self._y.append(yv)
                if len(self._s) > self._history:
                    self._s.pop(0)
                    self._y.pop(0)
            if float(jnp.abs(g_new).max()) <= self._tol_grad or \
                    float(jnp.abs(s).max()) <= self._tol_change:
                w, g = w_new, g_new
                break
            w, g = w_new, g_new
        return loss
