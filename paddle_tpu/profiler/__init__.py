"""``paddle.profiler`` parity over the PJRT/XPlane tracer.

Parity target: ``python/paddle/profiler/profiler.py`` in the reference
(Profiler with targets, ``make_scheduler`` step states, RecordEvent host
spans, chrome-trace export; CUPTI device tracer). TPU redesign (SURVEY §5):
the device side is the PJRT profiler — ``jax.profiler`` captures an XPlane
trace viewable in TensorBoard/Perfetto; the host side keeps the reference's
RecordEvent UX via ``jax.profiler.TraceAnnotation`` spans plus a lightweight
wall-clock aggregator for ``summary()`` without TensorBoard.
"""

from __future__ import annotations

import contextlib
import enum
import os
import time
from collections import defaultdict
from typing import Callable, Iterable, Optional

__all__ = ["Profiler", "ProfilerTarget", "ProfilerState", "RecordEvent",
           "make_scheduler", "export_chrome_tracing", "load_profiler_result",
           "SummaryView", "annotate"]


def annotate(name: str):
    """Zero-overhead-when-off profiling span, gated by
    ``FLAGS_profile_annotations``.

    The perf layer (fused train step, prefetch_to_device, async checkpoint)
    wraps its stages in ``annotate("step")`` / ``annotate("data")`` /
    ``annotate("h2d")`` / ``annotate("ckpt")`` so an XPlane capture shows
    where host time goes without any code changes — flip the flag on and
    trace. Off (the default) this returns a nullcontext and never imports
    jax.profiler."""
    from ..flags import flag
    try:
        if not flag("FLAGS_profile_annotations"):
            return contextlib.nullcontext()
    except KeyError:
        return contextlib.nullcontext()
    import jax.profiler
    return jax.profiler.TraceAnnotation(name)


class ProfilerTarget(enum.Enum):
    CPU = 0
    GPU = 1
    XPU = 2
    CUSTOM_DEVICE = 3
    TPU = 4


class ProfilerState(enum.Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class SummaryView(enum.Enum):
    DeviceView = 0
    OverView = 1
    ModelView = 2
    DistributedView = 3
    KernelView = 4
    OperatorView = 5
    MemoryView = 6
    MemoryManipulationView = 7
    UDFView = 8


def make_scheduler(*, closed: int, ready: int, record: int, repeat: int = 0,
                   skip_first: int = 0) -> Callable[[int], ProfilerState]:
    """Step-state schedule (reference semantics): skip_first, then cycles of
    closed/ready/record with RECORD_AND_RETURN closing each cycle."""
    cycle = closed + ready + record

    def schedule(step: int) -> ProfilerState:
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat and s >= repeat * cycle:
            return ProfilerState.CLOSED
        pos = s % cycle
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == cycle - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD
    return schedule


def _default_schedule(step: int) -> ProfilerState:
    return ProfilerState.RECORD


def export_chrome_tracing(dir_name: str, worker_name: Optional[str] = None):
    """on_trace_ready callback: point the XPlane dump at ``dir_name`` (open
    with TensorBoard's profile plugin or Perfetto)."""
    def handler(prof: "Profiler"):
        prof._last_export_dir = dir_name
    return handler


def export_protobuf(dir_name: str, worker_name: Optional[str] = None):
    return export_chrome_tracing(dir_name, worker_name)


def load_profiler_result(path: str):
    raise NotImplementedError(
        "load_profiler_result: open the XPlane dump directory with "
        "TensorBoard's profile plugin (tensorboard --logdir <dir>)")


# -- host-side spans ---------------------------------------------------------

_host_stats = defaultdict(lambda: [0, 0.0])  # name -> [count, total_s]
_collecting = False


class RecordEvent:
    """Host span (ref: paddle.profiler.RecordEvent): shows up in the XPlane
    timeline via TraceAnnotation and in Profiler.summary() aggregates."""

    def __init__(self, name: str, event_type=None):
        self.name = name
        self._ann = None
        self._t0 = None

    def begin(self):
        import jax.profiler
        self._ann = jax.profiler.TraceAnnotation(self.name)
        self._ann.__enter__()
        self._t0 = time.perf_counter()

    def end(self):
        if self._ann is not None:
            self._ann.__exit__(None, None, None)
            if _collecting and self._t0 is not None:
                st = _host_stats[self.name]
                st[0] += 1
                st[1] += time.perf_counter() - self._t0
            self._ann = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


class Profiler:
    """ref: paddle.profiler.Profiler(targets, scheduler, on_trace_ready).

    ``step()`` drives the scheduler; RECORD states run under an active
    ``jax.profiler`` trace capturing device + host activity to ``trace_dir``.
    """

    def __init__(self, *, targets: Optional[Iterable] = None, scheduler=None,
                 on_trace_ready: Optional[Callable] = None,
                 timer_only: bool = False, record_shapes: bool = False,
                 profile_memory: bool = False, with_flops: bool = False,
                 trace_dir: str = "./profiler_log"):
        self.targets = list(targets or [ProfilerTarget.CPU])
        if scheduler is None:
            self.scheduler = _default_schedule
        elif callable(scheduler):
            self.scheduler = scheduler
        elif isinstance(scheduler, (tuple, list)) and len(scheduler) == 2:
            lo, hi = scheduler
            self.scheduler = make_scheduler(closed=max(0, lo), ready=0,
                                            record=hi - lo, repeat=1)
        else:
            raise ValueError(f"unsupported scheduler: {scheduler!r}")
        self.on_trace_ready = on_trace_ready
        self.timer_only = timer_only
        self.trace_dir = trace_dir
        self._last_export_dir = None
        self.step_num = 0
        self.current_state = ProfilerState.CLOSED
        self._tracing = False
        self._step_t0 = None
        self._step_times = []

    # -- lifecycle -----------------------------------------------------------
    def start(self):
        global _collecting
        _collecting = True
        self.current_state = self.scheduler(self.step_num)
        self._maybe_toggle_trace()
        self._step_t0 = time.perf_counter()

    def stop(self):
        global _collecting
        if self._tracing:
            self._stop_trace()
        _collecting = False
        if self.on_trace_ready is not None:
            self.on_trace_ready(self)

    def step(self, num_samples: Optional[int] = None):
        if self._step_t0 is not None:
            self._step_times.append(time.perf_counter() - self._step_t0)
        self.step_num += 1
        prev = self.current_state
        self.current_state = self.scheduler(self.step_num)
        if prev != self.current_state:
            self._maybe_toggle_trace()
            if prev == ProfilerState.RECORD_AND_RETURN and \
                    self.on_trace_ready is not None:
                self.on_trace_ready(self)
        self._step_t0 = time.perf_counter()

    def _maybe_toggle_trace(self):
        want = self.current_state in (ProfilerState.RECORD,
                                      ProfilerState.RECORD_AND_RETURN)
        if want and not self._tracing and not self.timer_only:
            self._start_trace()
        elif not want and self._tracing:
            self._stop_trace()

    def _start_trace(self):
        import jax.profiler
        os.makedirs(self.trace_dir, exist_ok=True)
        try:
            jax.profiler.start_trace(self.trace_dir)
            self._tracing = True
        except Exception:  # second concurrent trace etc. — keep timers alive
            self._tracing = False

    def _stop_trace(self):
        import jax.profiler
        try:
            jax.profiler.stop_trace()
        finally:
            self._tracing = False

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- reporting -----------------------------------------------------------
    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms", views=None):
        lines = ["-" * 64,
                 f"paddle_tpu profiler summary ({self.step_num} steps)"]
        if self._step_times:
            import numpy as np
            ts = np.asarray(self._step_times) * 1e3
            lines.append(f"step time ms: avg {ts.mean():.2f}  min {ts.min():.2f}"
                         f"  max {ts.max():.2f}")
        if _host_stats:
            lines.append(f"{'host span':<40}{'calls':>8}{'total ms':>12}")
            for name, (cnt, tot) in sorted(_host_stats.items(),
                                           key=lambda kv: -kv[1][1]):
                lines.append(f"{name:<40}{cnt:>8}{tot * 1e3:>12.2f}")
        if self._tracing or self._last_export_dir or not self.timer_only:
            lines.append(f"device trace (XPlane): {self.trace_dir} — open "
                         f"with TensorBoard's profile plugin")
        lines.append("-" * 64)
        out = "\n".join(lines)
        print(out)
        return out
