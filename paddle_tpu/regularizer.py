"""Regularizers (parity: python/paddle/regularizer.py — L1Decay/L2Decay objects
carried on ParamAttr/optimizer and folded into the gradient)."""

from __future__ import annotations


class L2Decay:
    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)

    def __repr__(self):
        return f"L2Decay({self.coeff})"


class L1Decay:
    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)

    def __repr__(self):
        return f"L1Decay({self.coeff})"
