"""``paddle.signal`` parity: STFT / inverse STFT.

Parity target: ``python/paddle/signal.py`` in the reference (stft/istft over
the frame + fft ops). TPU lowering: framing is a static gather, the FFT is
XLA's native rfft/fft — one fused program, no Python loop over frames.
"""

from __future__ import annotations

from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp

from .core.dispatch import register_op
from .ops._helpers import Tensor, ensure_tensor, forward_op

__all__ = ["stft", "istft"]


def _prep_window(window, win_length: int, n_fft: int):
    if window is None:
        w = jnp.ones((win_length,), jnp.float32)
    else:
        w = ensure_tensor(window)._value.astype(jnp.float32)
        if w.shape[0] != win_length:
            raise ValueError(f"window length {w.shape[0]} != win_length "
                             f"{win_length}")
    pad = n_fft - win_length
    if pad:
        w = jnp.pad(w, (pad // 2, pad - pad // 2))
    return w


def stft(x, n_fft: int, hop_length: Optional[int] = None,
         win_length: Optional[int] = None, window=None, center: bool = True,
         pad_mode: str = "reflect", normalized: bool = False,
         onesided: bool = True, name=None):
    """Short-time Fourier transform -> ``[..., n_freq, n_frames]`` complex
    (ref: paddle.signal.stft)."""
    t = ensure_tensor(x)
    hop = int(hop_length) if hop_length else n_fft // 4
    wl = int(win_length) if win_length else n_fft
    w = _prep_window(window, wl, n_fft)

    def impl(v):
        one_d = v.ndim == 1
        vv = v[None] if one_d else v.reshape(-1, v.shape[-1])
        if center:
            vv = jnp.pad(vv, ((0, 0), (n_fft // 2, n_fft // 2)),
                         mode=pad_mode)
        T = vv.shape[-1]
        n_frames = 1 + (T - n_fft) // hop
        idx = (jnp.arange(n_frames)[:, None] * hop +
               jnp.arange(n_fft)[None, :])
        frames = vv[:, idx] * w[None, None, :]        # [B, F, n_fft]
        sp = jnp.fft.rfft(frames, axis=-1) if onesided \
            else jnp.fft.fft(frames, axis=-1)
        if normalized:
            sp = sp / jnp.sqrt(jnp.asarray(n_fft, sp.real.dtype))
        sp = jnp.swapaxes(sp, -1, -2)                  # [B, freq, frames]
        if one_d:
            return sp[0]
        return sp.reshape(v.shape[:-1] + sp.shape[-2:])

    return forward_op("stft", impl, [t])


def istft(x, n_fft: int, hop_length: Optional[int] = None,
          win_length: Optional[int] = None, window=None, center: bool = True,
          normalized: bool = False, onesided: bool = True,
          length: Optional[int] = None, return_complex: bool = False,
          name=None):
    """Inverse STFT by windowed overlap-add with window-square
    normalization (ref: paddle.signal.istft)."""
    t = ensure_tensor(x)
    hop = int(hop_length) if hop_length else n_fft // 4
    wl = int(win_length) if win_length else n_fft
    w = _prep_window(window, wl, n_fft)

    def impl(sp):
        one_batch = sp.ndim == 2
        s = sp[None] if one_batch else sp.reshape((-1,) + sp.shape[-2:])
        s = jnp.swapaxes(s, -1, -2)                    # [B, frames, freq]
        if normalized:
            s = s * jnp.sqrt(jnp.asarray(n_fft, jnp.float32))
        frames = jnp.fft.irfft(s, n=n_fft, axis=-1) if onesided \
            else jnp.fft.ifft(s, axis=-1)
        if not return_complex:
            frames = frames.real if jnp.iscomplexobj(frames) else frames
        frames = frames * w[None, None, :]
        B, F = frames.shape[0], frames.shape[1]
        T = n_fft + hop * (F - 1)
        starts = jnp.arange(F) * hop
        idx = (starts[:, None] + jnp.arange(n_fft)[None, :]).reshape(-1)
        out = jnp.zeros((B, T), frames.dtype)
        out = out.at[:, idx].add(frames.reshape(B, -1))
        # window-square envelope for COLA normalization
        env = jnp.zeros((T,), jnp.float32).at[idx].add(
            jnp.tile(w * w, (F,)))
        out = out / jnp.maximum(env, 1e-11)[None, :]
        if center:
            out = out[:, n_fft // 2: T - n_fft // 2]
        if length is not None:
            out = out[:, :length]
        if one_batch:
            return out[0]
        return out.reshape(sp.shape[:-2] + out.shape[-1:])

    return forward_op("istft", impl, [t])


register_op("stft", stft, "Short-time Fourier transform.", public=stft)
register_op("istft", istft, "Inverse STFT (windowed overlap-add).",
            public=istft)
