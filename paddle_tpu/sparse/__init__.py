"""``paddle.sparse`` parity: COO/CSR sparse tensors.

Reference surface: ``python/paddle/sparse/`` (sparse_coo_tensor,
sparse_csr_tensor, to_dense/to_sparse_coo, elementwise + matmul + nn ops on
sparse operands). TPU redesign stance: XLA has no native sparse kernels and
TPUs are dense-matmul machines — sparse storage here is a real COO/CSR
container with conversion, indexing and the core math surface, computed by
scatter/gather + dense contraction (the honest TPU lowering; the reference's
cuSPARSE paths have no MXU analogue). Suitable for preprocessing and
moderate sparsity, documented as such.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor, _wrap_value, to_tensor
from ..ops._helpers import ensure_tensor, forward_op

__all__ = ["SparseCooTensor", "SparseCsrTensor", "sparse_coo_tensor",
           "sparse_csr_tensor", "is_same_shape", "add", "subtract",
           "multiply", "divide", "matmul", "masked_matmul", "mv", "addmm",
           "relu", "coalesce", "sin", "tan", "asin", "atan", "sinh", "tanh",
           "asinh", "atanh", "sqrt", "square", "log1p", "abs", "expm1",
           "deg2rad", "rad2deg", "neg", "pow", "cast", "sum", "transpose",
           "reshape", "nn"]


class SparseCooTensor:
    """COO sparse tensor: ``indices [ndim, nnz]`` + ``values [nnz, ...]``."""

    def __init__(self, indices: Tensor, values: Tensor, shape: Sequence[int],
                 coalesced: bool = False):
        self.indices_ = ensure_tensor(indices).astype("int32")
        self.values_ = ensure_tensor(values)
        self.shape = list(int(s) for s in shape)
        self._coalesced = coalesced

    # -- reference accessors -------------------------------------------------
    def indices(self) -> Tensor:
        return self.indices_

    def values(self) -> Tensor:
        return self.values_

    def nnz(self) -> int:
        return int(self.values_.shape[0])

    @property
    def dtype(self):
        return self.values_.dtype

    @property
    def ndim(self):
        return len(self.shape)

    def is_sparse(self):
        return True

    def is_sparse_coo(self):
        return True

    def is_sparse_csr(self):
        return False

    def to_dense(self) -> Tensor:
        shape = tuple(self.shape)

        def f(idx, vals):
            dense = jnp.zeros(shape, vals.dtype)
            return dense.at[tuple(idx[d] for d in range(len(shape)))].add(vals)
        return forward_op("sparse_to_dense", f, [self.indices_, self.values_])

    def to_sparse_csr(self) -> "SparseCsrTensor":
        if self.ndim != 2:
            raise ValueError("to_sparse_csr requires a 2-D COO tensor")
        idx = np.asarray(self.indices_.numpy())
        vals = self.values_
        order = np.lexsort((idx[1], idx[0]))
        rows, cols = idx[0][order], idx[1][order]
        crows = np.zeros(self.shape[0] + 1, np.int32)
        np.add.at(crows, rows + 1, 1)
        crows = np.cumsum(crows).astype(np.int32)
        vals_sorted = forward_op("csr_sort", lambda v: v[jnp.asarray(order)],
                                 [vals])
        return SparseCsrTensor(to_tensor(crows), to_tensor(cols.astype(np.int32)),
                               vals_sorted, self.shape)

    def coalesce(self) -> "SparseCooTensor":
        """Merge duplicate coordinates (sums values) and sort."""
        idx = np.asarray(self.indices_.numpy())
        keys = np.ravel_multi_index(tuple(idx), tuple(self.shape))
        uniq, inv = np.unique(keys, return_inverse=True)
        new_idx = np.stack(np.unravel_index(uniq, tuple(self.shape))).astype(
            np.int32)

        def f(vals):
            return jnp.zeros((len(uniq),) + vals.shape[1:], vals.dtype).at[
                jnp.asarray(inv)].add(vals)
        new_vals = forward_op("sparse_coalesce", f, [self.values_])
        return SparseCooTensor(to_tensor(new_idx), new_vals, self.shape,
                               coalesced=True)

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz()}, "
                f"dtype={self.dtype})")


class SparseCsrTensor:
    """CSR sparse matrix: crows [rows+1], cols [nnz], values [nnz]."""

    def __init__(self, crows: Tensor, cols: Tensor, values: Tensor,
                 shape: Sequence[int]):
        self.crows_ = ensure_tensor(crows).astype("int32")
        self.cols_ = ensure_tensor(cols).astype("int32")
        self.values_ = ensure_tensor(values)
        self.shape = list(int(s) for s in shape)

    def crows(self):
        return self.crows_

    def cols(self):
        return self.cols_

    def values(self):
        return self.values_

    def nnz(self):
        return int(self.values_.shape[0])

    @property
    def dtype(self):
        return self.values_.dtype

    def is_sparse_csr(self):
        return True

    def is_sparse_coo(self):
        return False

    def to_dense(self) -> Tensor:
        crows = np.asarray(self.crows_.numpy())
        rows = np.repeat(np.arange(len(crows) - 1), np.diff(crows)).astype(
            np.int32)
        shape = tuple(self.shape)

        def f(cols, vals):
            dense = jnp.zeros(shape, vals.dtype)
            return dense.at[jnp.asarray(rows), cols].add(vals)
        return forward_op("csr_to_dense", f, [self.cols_, self.values_])

    def to_sparse_coo(self, sparse_dim: Optional[int] = None) -> SparseCooTensor:
        crows = np.asarray(self.crows_.numpy())
        rows = np.repeat(np.arange(len(crows) - 1), np.diff(crows)).astype(
            np.int32)
        idx = np.stack([rows, np.asarray(self.cols_.numpy())])
        return SparseCooTensor(to_tensor(idx), self.values_, self.shape)

    def __repr__(self):
        return (f"SparseCsrTensor(shape={self.shape}, nnz={self.nnz()}, "
                f"dtype={self.dtype})")


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None,
                      stop_gradient=True) -> SparseCooTensor:
    idx = ensure_tensor(indices)
    vals = ensure_tensor(values)
    if dtype is not None:
        vals = vals.astype(dtype)
    if shape is None:
        mx = np.asarray(idx.numpy()).max(axis=1) + 1
        shape = mx.tolist()
    if not stop_gradient:
        vals.stop_gradient = False
    return SparseCooTensor(idx, vals, shape)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None,
                      stop_gradient=True) -> SparseCsrTensor:
    vals = ensure_tensor(values)
    if dtype is not None:
        vals = vals.astype(dtype)
    if not stop_gradient:
        vals.stop_gradient = False
    return SparseCsrTensor(crows, cols, vals, shape)


def is_same_shape(x, y) -> bool:
    return list(x.shape) == list(y.shape)


def _binary(name, x: SparseCooTensor, y: SparseCooTensor, op):
    if not isinstance(x, SparseCooTensor) or not isinstance(y, SparseCooTensor):
        raise TypeError(f"sparse.{name} expects SparseCooTensor operands")
    if x.shape != y.shape:
        raise ValueError(f"sparse.{name}: shape mismatch {x.shape} vs {y.shape}")
    # dense lowering (documented TPU stance)
    d = op(x.to_dense(), y.to_dense())
    return _dense_to_coo(d)


def _dense_to_coo(d: Tensor) -> SparseCooTensor:
    arr = d.numpy()
    idx = np.stack(np.nonzero(arr)).astype(np.int32)
    def f(v):
        return v[tuple(jnp.asarray(idx[i]) for i in range(idx.shape[0]))]
    vals = forward_op("dense_to_coo_values", f, [d])
    return SparseCooTensor(to_tensor(idx), vals, list(arr.shape))


def add(x, y, name=None):
    return _binary("add", x, y, lambda a, b: a + b)


def subtract(x, y, name=None):
    return _binary("subtract", x, y, lambda a, b: a - b)


def multiply(x, y, name=None):
    return _binary("multiply", x, y, lambda a, b: a * b)


def matmul(x, y, name=None):
    """sparse @ dense -> dense (the TPU-relevant case: dense contraction on
    the MXU after scatter materialization)."""
    if isinstance(x, (SparseCooTensor, SparseCsrTensor)):
        xd = x.to_dense()
    else:
        xd = ensure_tensor(x)
    if isinstance(y, (SparseCooTensor, SparseCsrTensor)):
        y = y.to_dense()
    from ..ops.linalg import matmul as dense_matmul
    return dense_matmul(xd, ensure_tensor(y))


def masked_matmul(x, y, mask: SparseCooTensor, name=None):
    """(x @ y) sampled at mask's sparsity pattern (ref: sddmm)."""
    from ..ops.linalg import matmul as dense_matmul
    prod = dense_matmul(ensure_tensor(x), ensure_tensor(y))
    idx = mask.indices_

    def f(p, i):
        return p[tuple(i[d] for d in range(i.shape[0]))]
    vals = forward_op("masked_matmul_sample", f, [prod, idx])
    return SparseCooTensor(idx, vals, mask.shape)


def relu(x: SparseCooTensor, name=None) -> SparseCooTensor:
    from ..nn import functional as F
    return SparseCooTensor(x.indices_, F.relu(x.values_), x.shape)


# ---------------------------------------------------------------------------
# zero-preserving unary surface (ref: python/paddle/sparse/unary.py — the
# reference restricts sparse elementwise ops to exactly the f(0)=0 family,
# so applying the kernel to the VALUES array is exact, [nnz]-sized work)
# ---------------------------------------------------------------------------

def _values_map(x, name, jfn):
    if isinstance(x, SparseCsrTensor):
        vals = forward_op(name, jfn, [x.values_])
        return SparseCsrTensor(x.crows_, x.cols_, vals, x.shape)
    if not isinstance(x, SparseCooTensor):
        raise TypeError(f"sparse.{name.split('_', 1)[1]} expects a sparse "
                        f"tensor")
    vals = forward_op(name, jfn, [x.values_])
    return SparseCooTensor(x.indices_, vals, x.shape, x._coalesced)


def _sparse_unary(name, jfn, doc=""):
    from ..core.dispatch import register_op as _reg
    opname = f"sparse_{name}"
    _reg(opname, jfn, doc or f"sparse.{name}: zero-preserving elementwise "
         f"{name} on the values array.")

    def op(x, name=None):
        return _values_map(x, opname, jfn)
    op.__name__ = f"sparse_{name}"
    return op


import jax as _jax  # noqa: E402

sin = _sparse_unary("sin", jnp.sin)
tan = _sparse_unary("tan", jnp.tan)
asin = _sparse_unary("asin", jnp.arcsin)
atan = _sparse_unary("atan", jnp.arctan)
sinh = _sparse_unary("sinh", jnp.sinh)
tanh = _sparse_unary("tanh", jnp.tanh)
asinh = _sparse_unary("asinh", jnp.arcsinh)
atanh = _sparse_unary("atanh", jnp.arctanh)
sqrt = _sparse_unary("sqrt", jnp.sqrt)
square = _sparse_unary("square", jnp.square)
log1p = _sparse_unary("log1p", jnp.log1p)
abs = _sparse_unary("abs", jnp.abs)  # noqa: A001
expm1 = _sparse_unary("expm1", jnp.expm1)
deg2rad = _sparse_unary("deg2rad", jnp.deg2rad)
rad2deg = _sparse_unary("rad2deg", jnp.rad2deg)
neg = _sparse_unary("neg", jnp.negative)


def pow(x, factor, name=None):  # noqa: A001
    """Elementwise power on the values (factor > 0 keeps zeros at zero)."""
    return _values_map(x, "sparse_pow",
                       lambda v: jnp.power(v, factor))


def cast(x, index_dtype=None, value_dtype=None, name=None):
    """ref: paddle.sparse.cast — retype indices and/or values."""
    from ..core.dtype import canonical_dtype
    vals = x.values_ if value_dtype is None else \
        x.values_.astype(canonical_dtype(value_dtype))
    if isinstance(x, SparseCsrTensor):
        crows = x.crows_ if index_dtype is None else \
            x.crows_.astype(canonical_dtype(index_dtype))
        cols = x.cols_ if index_dtype is None else \
            x.cols_.astype(canonical_dtype(index_dtype))
        return SparseCsrTensor(crows, cols, vals, x.shape)
    idx = x.indices_ if index_dtype is None else \
        x.indices_.astype(canonical_dtype(index_dtype))
    return SparseCooTensor(idx, vals, x.shape, x._coalesced)


def divide(x, y, name=None):
    """Elementwise divide — requires IDENTICAL sparsity patterns (upstream
    restriction: outside the intersection the result would be 0/0)."""
    if not isinstance(x, SparseCooTensor) or not isinstance(y, SparseCooTensor):
        raise TypeError("sparse.divide expects SparseCooTensor operands")
    xc, yc = x.coalesce(), y.coalesce()
    if xc.shape != yc.shape or not np.array_equal(
            np.asarray(xc.indices_.numpy()), np.asarray(yc.indices_.numpy())):
        raise ValueError(
            "sparse.divide requires operands with the same sparsity "
            "pattern (0/0 is undefined outside the intersection)")
    vals = forward_op("sparse_divide", lambda a, b: a / b,
                      [xc.values_, yc.values_])
    return SparseCooTensor(xc.indices_, vals, xc.shape, coalesced=True)


def mv(x, vec, name=None):
    """2-D sparse @ 1-D dense -> dense [m] (ref: paddle.sparse.mv): gather
    the vector at the column indices, scale by values, segment-sum by row —
    [nnz]-sized work, no densification."""
    if isinstance(x, SparseCsrTensor):
        x = x.to_sparse_coo()
    if not isinstance(x, SparseCooTensor) or x.ndim != 2:
        raise TypeError("sparse.mv expects a 2-D sparse tensor")
    v = ensure_tensor(vec)
    m = x.shape[0]

    def f(idx, vals, vv):
        contrib = vals * vv[idx[1]]
        return _jax.ops.segment_sum(contrib, idx[0], num_segments=m)

    return forward_op("sparse_mv", f, [x.indices_, x.values_, v])


def addmm(input, x, y, beta: float = 1.0, alpha: float = 1.0, name=None):
    """beta * input + alpha * (x @ y) (ref: paddle.sparse.addmm)."""
    prod = matmul(x, y)
    return ensure_tensor(input) * beta + prod * alpha


def sum(x, axis=None, dtype=None, keepdim: bool = False, name=None):  # noqa: A001
    """ref: paddle.sparse.sum. Full reduction works on values only
    ([nnz]-sized); axis reductions lower through dense (documented)."""
    if axis is None:
        out = forward_op("sparse_sum", lambda v: jnp.sum(v), [x.values_])
        return out.astype(dtype) if dtype else out
    d = x.to_dense()
    from ..ops import math as _m
    return _m.sum(d, axis=axis, keepdim=keepdim, dtype=dtype)


def transpose(x, perm, name=None):
    """Permute a COO tensor's dims: an index-row permutation, O(nnz)."""
    if isinstance(x, SparseCsrTensor):
        x = x.to_sparse_coo()
    perm = [int(p) for p in perm]

    def f(idx):
        return jnp.stack([idx[p] for p in perm])

    idx = forward_op("sparse_transpose", f, [x.indices_],
                     differentiable=False)
    return SparseCooTensor(idx, x.values_, [x.shape[p] for p in perm])


def reshape(x, shape, name=None):
    """COO reshape via linear-index recomputation, O(nnz). The index math
    runs on the HOST in int64 — logical element counts routinely exceed
    2**31 for sparse shapes, which would overflow the device's int32."""
    if isinstance(x, SparseCsrTensor):
        x = x.to_sparse_coo()
    old = x.shape
    total = int(np.prod(old))
    shape = [int(s) if s != -1 else -1 for s in shape]
    if -1 in shape:
        rest = int(np.prod([s for s in shape if s != -1]))
        shape = [s if s != -1 else total // rest for s in shape]

    idx_np = np.asarray(x.indices_.numpy()).astype(np.int64)
    lin = np.ravel_multi_index(tuple(idx_np), tuple(old))
    new_idx = np.stack(np.unravel_index(lin, tuple(shape))).astype(np.int32)
    return SparseCooTensor(to_tensor(new_idx), x.values_, shape)


# registry entries for the structural ops (the unary family registers in
# _sparse_unary)
from ..core.dispatch import register_op as _register_op  # noqa: E402
for _n, _f, _d in [
    ("sparse_pow", lambda v: v, "values power (zero-preserving)"),
    ("sparse_cast", lambda v: v, "retype indices/values"),
    ("sparse_divide", lambda a, b: a / b, "elementwise divide"),
    ("sparse_mv", lambda i, v, x: v, "sparse matrix-vector product"),
    ("sparse_addmm", lambda a, b: a, "beta*input + alpha*(x@y)"),
    ("sparse_sum", lambda v: jnp.sum(v), "sum of values"),
    ("sparse_transpose", lambda i: i, "dim permutation on indices"),
    ("sparse_reshape", lambda i: i, "linear-index reshape"),
    ("sparse_matmul", lambda a, b: a @ b, "sparse @ dense on the MXU"),
    ("sparse_masked_matmul", lambda a, b: a @ b, "sddmm sampling"),
    ("sparse_add", lambda a, b: a + b, "elementwise add"),
    ("sparse_subtract", lambda a, b: a - b, "elementwise subtract"),
    ("sparse_multiply", lambda a, b: a * b, "elementwise multiply"),
    ("sparse_relu", lambda v: jnp.maximum(v, 0), "relu on values"),
    ("sparse_coalesce", lambda v: v, "merge duplicate coordinates"),
]:
    _register_op(_n, _f, f"sparse.{_n.split('_', 1)[1]}: {_d}")


def _sparse_softmax(x, axis: int = -1, name=None):
    """Row-wise softmax over the stored values (ref:
    paddle.sparse.nn.functional.softmax; only the last axis of a 2-D
    pattern is supported, matching the reference's CSR kernel)."""
    if isinstance(x, SparseCsrTensor):
        coo = x.to_sparse_coo()
        back = "csr"
    else:
        coo, back = x, "coo"
    if coo.ndim != 2 or axis not in (-1, 1):
        raise ValueError("sparse softmax supports 2-D patterns over the "
                         "last axis")
    m = coo.shape[0]

    def f(idx, vals):
        row = idx[0]
        vmax = _jax.ops.segment_max(vals, row, num_segments=m)
        e = jnp.exp(vals - vmax[row])
        den = _jax.ops.segment_sum(e, row, num_segments=m)
        return e / den[row]

    vals = forward_op("sparse_softmax", f, [coo.indices_, coo.values_])
    out = SparseCooTensor(coo.indices_, vals, coo.shape, coo._coalesced)
    return out.to_sparse_csr() if back == "csr" else out


_register_op("sparse_softmax", lambda i, v: v,
             "sparse.nn.functional.softmax: row-wise over stored values")


class nn:  # namespace parity: paddle.sparse.nn.ReLU / functional
    class ReLU:
        def __call__(self, x):
            return relu(x)

    class Softmax:
        def __init__(self, axis: int = -1):
            self.axis = axis

        def __call__(self, x):
            return _sparse_softmax(x, self.axis)

    class functional:
        relu = staticmethod(lambda x, name=None: relu(x))
        softmax = staticmethod(_sparse_softmax)

        @staticmethod
        def relu6(x, name=None):
            return _values_map(x, "sparse_relu6",
                               lambda v: jnp.clip(v, 0, 6))

        @staticmethod
        def leaky_relu(x, negative_slope: float = 0.01, name=None):
            return _values_map(
                x, "sparse_leaky_relu",
                lambda v: jnp.where(v >= 0, v, v * negative_slope))


_register_op("sparse_relu6", lambda v: jnp.clip(v, 0, 6),
             "sparse.nn.functional.relu6 on values")
_register_op("sparse_leaky_relu", lambda v: jnp.where(v >= 0, v, v * 0.01),
             "sparse.nn.functional.leaky_relu on values")


# ---------------------------------------------------------------------------
# sparse.nn.functional (r4: VERDICT #6 — attention-mask utilities)
# ---------------------------------------------------------------------------

def _csr_to_dense_mask(sp, rows: int, cols: int):
    """CSR pattern -> dense bool [rows, cols] (True where an entry exists)."""
    import numpy as _np
    crows = _np.asarray(sp.crows().numpy())
    col = _np.asarray(sp.cols().numpy())
    m = _np.zeros((rows, cols), bool)
    for r in range(rows):
        m[r, col[crows[r]:crows[r + 1]]] = True
    return m


def attention(query, key, value, sparse_mask, key_padding_mask=None,
              attn_mask=None, name=None):
    """Sparse-masked scaled-dot-product attention (ref:
    paddle.sparse.nn.functional.attention — only QK^T entries present in
    the CSR ``sparse_mask`` pattern participate in the softmax).

    TPU formulation (documented honestly): the CSR PATTERN becomes a dense
    boolean mask applied inside a fused dense attention — on the MXU that
    is strictly faster than gather-based sparse arithmetic for the
    practical mask densities; block-SPARSE execution (whole tiles skipped)
    is the `kernels.flash_attention` segment-ids path.

    Eager-only contract (same as the sparse set ops): the CSR pattern is
    materialized on host, so calling this under ``to_static``/``jit``
    graph-breaks. The dense mask is cached on the ``sparse_mask`` object —
    repeated calls with the same pattern skip the host decode.

    Shapes: query/key/value ``[B, H, S, D]``; sparse_mask a
    :class:`SparseCsrTensor` with shape ``[B*H, S, S]`` or ``[S, S]``
    (the reference's layout). Returns ``[B, H, S, D]``.
    """
    import math as _math
    import numpy as _np
    q = ensure_tensor(query)
    k = ensure_tensor(key)
    v = ensure_tensor(value)
    B, H, S, D = q.shape

    if isinstance(sparse_mask, SparseCsrTensor):
        cached = getattr(sparse_mask, "_dense_mask_cache", None)
        if cached is not None and cached.shape[-2:] == (S, S):
            mask = cached
        elif len(sparse_mask.shape) == 3:
            # [B*H, S, S]: per-head patterns — build the stacked dense mask
            crows = _np.asarray(sparse_mask.crows().numpy())
            cols = _np.asarray(sparse_mask.cols().numpy())
            n = sparse_mask.shape[0]
            per = S + 1
            m = _np.zeros((n, S, S), bool)
            for i in range(n):
                cr = crows[i * per:(i + 1) * per]
                base = cr[0]
                for r in range(S):
                    m[i, r, cols[cr[r]:cr[r + 1]]] = True
            mask = m.reshape(B, H, S, S)
            sparse_mask._dense_mask_cache = mask
        else:
            mask = _csr_to_dense_mask(sparse_mask, S, S)[None, None]
            sparse_mask._dense_mask_cache = mask
    else:
        mask = _np.asarray(ensure_tensor(sparse_mask).numpy()) != 0
        if mask.ndim == 2:
            mask = mask[None, None]

    import jax

    def impl(qv, kv, vv):
        s = jnp.einsum("bhqd,bhkd->bhqk", qv.astype(jnp.float32),
                       kv.astype(jnp.float32)) / _math.sqrt(D)
        mm = jnp.asarray(mask)
        if key_padding_mask is not None:
            kp = ensure_tensor(key_padding_mask)._value != 0  # [B, S] keep
            mm = mm & kp[:, None, None, :]
        if attn_mask is not None:
            am = ensure_tensor(attn_mask)._value != 0
            mm = mm & am
        s = jnp.where(mm, s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        p = jnp.where(mm.any(-1, keepdims=True), p, 0.0)  # all-masked rows
        return jnp.einsum("bhqk,bhkd->bhqd", p.astype(vv.dtype), vv)

    return forward_op("sparse_attention", impl, [q, k, v])


# extend the existing sparse.nn namespace (defined above) rather than
# shadowing it
nn.functional.attention = staticmethod(attention)
__all__ += ["attention"]


# r5: the sparse conv family (VERDICT r4 next #5) extends sparse.nn
from . import nn_conv as _nn_conv
nn.Conv3D = _nn_conv.Conv3D
nn.SubmConv3D = _nn_conv.SubmConv3D
nn.BatchNorm = _nn_conv.BatchNorm
nn.MaxPool3D = _nn_conv.MaxPool3D
nn.functional.conv3d = staticmethod(_nn_conv.conv3d)
nn.functional.subm_conv3d = staticmethod(_nn_conv.subm_conv3d)
nn.functional.max_pool3d = staticmethod(_nn_conv.max_pool3d)
nn.functional.batch_norm = staticmethod(_nn_conv.batch_norm)
__all__ += ["nn_conv"]
