"""``paddle.sparse`` parity: COO/CSR sparse tensors.

Reference surface: ``python/paddle/sparse/`` (sparse_coo_tensor,
sparse_csr_tensor, to_dense/to_sparse_coo, elementwise + matmul + nn ops on
sparse operands). TPU redesign stance: XLA has no native sparse kernels and
TPUs are dense-matmul machines — sparse storage here is a real COO/CSR
container with conversion, indexing and the core math surface, computed by
scatter/gather + dense contraction (the honest TPU lowering; the reference's
cuSPARSE paths have no MXU analogue). Suitable for preprocessing and
moderate sparsity, documented as such.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor, _wrap_value, to_tensor
from ..ops._helpers import ensure_tensor, forward_op

__all__ = ["SparseCooTensor", "SparseCsrTensor", "sparse_coo_tensor",
           "sparse_csr_tensor", "is_same_shape", "add", "subtract",
           "multiply", "matmul", "masked_matmul", "relu", "coalesce"]


class SparseCooTensor:
    """COO sparse tensor: ``indices [ndim, nnz]`` + ``values [nnz, ...]``."""

    def __init__(self, indices: Tensor, values: Tensor, shape: Sequence[int],
                 coalesced: bool = False):
        self.indices_ = ensure_tensor(indices).astype("int32")
        self.values_ = ensure_tensor(values)
        self.shape = list(int(s) for s in shape)
        self._coalesced = coalesced

    # -- reference accessors -------------------------------------------------
    def indices(self) -> Tensor:
        return self.indices_

    def values(self) -> Tensor:
        return self.values_

    def nnz(self) -> int:
        return int(self.values_.shape[0])

    @property
    def dtype(self):
        return self.values_.dtype

    @property
    def ndim(self):
        return len(self.shape)

    def is_sparse(self):
        return True

    def is_sparse_coo(self):
        return True

    def is_sparse_csr(self):
        return False

    def to_dense(self) -> Tensor:
        shape = tuple(self.shape)

        def f(idx, vals):
            dense = jnp.zeros(shape, vals.dtype)
            return dense.at[tuple(idx[d] for d in range(len(shape)))].add(vals)
        return forward_op("sparse_to_dense", f, [self.indices_, self.values_])

    def to_sparse_csr(self) -> "SparseCsrTensor":
        if self.ndim != 2:
            raise ValueError("to_sparse_csr requires a 2-D COO tensor")
        idx = np.asarray(self.indices_.numpy())
        vals = self.values_
        order = np.lexsort((idx[1], idx[0]))
        rows, cols = idx[0][order], idx[1][order]
        crows = np.zeros(self.shape[0] + 1, np.int32)
        np.add.at(crows, rows + 1, 1)
        crows = np.cumsum(crows).astype(np.int32)
        vals_sorted = forward_op("csr_sort", lambda v: v[jnp.asarray(order)],
                                 [vals])
        return SparseCsrTensor(to_tensor(crows), to_tensor(cols.astype(np.int32)),
                               vals_sorted, self.shape)

    def coalesce(self) -> "SparseCooTensor":
        """Merge duplicate coordinates (sums values) and sort."""
        idx = np.asarray(self.indices_.numpy())
        keys = np.ravel_multi_index(tuple(idx), tuple(self.shape))
        uniq, inv = np.unique(keys, return_inverse=True)
        new_idx = np.stack(np.unravel_index(uniq, tuple(self.shape))).astype(
            np.int32)

        def f(vals):
            return jnp.zeros((len(uniq),) + vals.shape[1:], vals.dtype).at[
                jnp.asarray(inv)].add(vals)
        new_vals = forward_op("sparse_coalesce", f, [self.values_])
        return SparseCooTensor(to_tensor(new_idx), new_vals, self.shape,
                               coalesced=True)

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz()}, "
                f"dtype={self.dtype})")


class SparseCsrTensor:
    """CSR sparse matrix: crows [rows+1], cols [nnz], values [nnz]."""

    def __init__(self, crows: Tensor, cols: Tensor, values: Tensor,
                 shape: Sequence[int]):
        self.crows_ = ensure_tensor(crows).astype("int32")
        self.cols_ = ensure_tensor(cols).astype("int32")
        self.values_ = ensure_tensor(values)
        self.shape = list(int(s) for s in shape)

    def crows(self):
        return self.crows_

    def cols(self):
        return self.cols_

    def values(self):
        return self.values_

    def nnz(self):
        return int(self.values_.shape[0])

    @property
    def dtype(self):
        return self.values_.dtype

    def is_sparse_csr(self):
        return True

    def is_sparse_coo(self):
        return False

    def to_dense(self) -> Tensor:
        crows = np.asarray(self.crows_.numpy())
        rows = np.repeat(np.arange(len(crows) - 1), np.diff(crows)).astype(
            np.int32)
        shape = tuple(self.shape)

        def f(cols, vals):
            dense = jnp.zeros(shape, vals.dtype)
            return dense.at[jnp.asarray(rows), cols].add(vals)
        return forward_op("csr_to_dense", f, [self.cols_, self.values_])

    def to_sparse_coo(self, sparse_dim: Optional[int] = None) -> SparseCooTensor:
        crows = np.asarray(self.crows_.numpy())
        rows = np.repeat(np.arange(len(crows) - 1), np.diff(crows)).astype(
            np.int32)
        idx = np.stack([rows, np.asarray(self.cols_.numpy())])
        return SparseCooTensor(to_tensor(idx), self.values_, self.shape)

    def __repr__(self):
        return (f"SparseCsrTensor(shape={self.shape}, nnz={self.nnz()}, "
                f"dtype={self.dtype})")


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None,
                      stop_gradient=True) -> SparseCooTensor:
    idx = ensure_tensor(indices)
    vals = ensure_tensor(values)
    if dtype is not None:
        vals = vals.astype(dtype)
    if shape is None:
        mx = np.asarray(idx.numpy()).max(axis=1) + 1
        shape = mx.tolist()
    if not stop_gradient:
        vals.stop_gradient = False
    return SparseCooTensor(idx, vals, shape)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None,
                      stop_gradient=True) -> SparseCsrTensor:
    vals = ensure_tensor(values)
    if dtype is not None:
        vals = vals.astype(dtype)
    if not stop_gradient:
        vals.stop_gradient = False
    return SparseCsrTensor(crows, cols, vals, shape)


def is_same_shape(x, y) -> bool:
    return list(x.shape) == list(y.shape)


def _binary(name, x: SparseCooTensor, y: SparseCooTensor, op):
    if not isinstance(x, SparseCooTensor) or not isinstance(y, SparseCooTensor):
        raise TypeError(f"sparse.{name} expects SparseCooTensor operands")
    if x.shape != y.shape:
        raise ValueError(f"sparse.{name}: shape mismatch {x.shape} vs {y.shape}")
    # dense lowering (documented TPU stance)
    d = op(x.to_dense(), y.to_dense())
    return _dense_to_coo(d)


def _dense_to_coo(d: Tensor) -> SparseCooTensor:
    arr = d.numpy()
    idx = np.stack(np.nonzero(arr)).astype(np.int32)
    def f(v):
        return v[tuple(jnp.asarray(idx[i]) for i in range(idx.shape[0]))]
    vals = forward_op("dense_to_coo_values", f, [d])
    return SparseCooTensor(to_tensor(idx), vals, list(arr.shape))


def add(x, y, name=None):
    return _binary("add", x, y, lambda a, b: a + b)


def subtract(x, y, name=None):
    return _binary("subtract", x, y, lambda a, b: a - b)


def multiply(x, y, name=None):
    return _binary("multiply", x, y, lambda a, b: a * b)


def matmul(x, y, name=None):
    """sparse @ dense -> dense (the TPU-relevant case: dense contraction on
    the MXU after scatter materialization)."""
    if isinstance(x, (SparseCooTensor, SparseCsrTensor)):
        xd = x.to_dense()
    else:
        xd = ensure_tensor(x)
    if isinstance(y, (SparseCooTensor, SparseCsrTensor)):
        y = y.to_dense()
    from ..ops.linalg import matmul as dense_matmul
    return dense_matmul(xd, ensure_tensor(y))


def masked_matmul(x, y, mask: SparseCooTensor, name=None):
    """(x @ y) sampled at mask's sparsity pattern (ref: sddmm)."""
    from ..ops.linalg import matmul as dense_matmul
    prod = dense_matmul(ensure_tensor(x), ensure_tensor(y))
    idx = mask.indices_

    def f(p, i):
        return p[tuple(i[d] for d in range(i.shape[0]))]
    vals = forward_op("masked_matmul_sample", f, [prod, idx])
    return SparseCooTensor(idx, vals, mask.shape)


def relu(x: SparseCooTensor, name=None) -> SparseCooTensor:
    from ..nn import functional as F
    return SparseCooTensor(x.indices_, F.relu(x.values_), x.shape)


class nn:  # namespace parity: paddle.sparse.nn.ReLU
    class ReLU:
        def __call__(self, x):
            return relu(x)
