"""Sparse (point-cloud) convolution family.

Parity target: ``python/paddle/sparse/nn/layer/conv.py`` +
``paddle/phi/kernels/sparse/gpu/conv*`` in the reference — Conv3D,
SubmConv3D, BatchNorm, MaxPool3D over COO voxel grids (the SECOND/
sparse-CNN workload the paddle.sparse package exists for).

TPU redesign (VERDICT r4 next #5): the reference builds a GPU "rulebook"
(per-kernel-offset gather/scatter pair lists) with hash tables and atomic
counters, then runs one implicit-gemm per offset. The structure survives
the port; the substrate changes:

* rulebook construction is HOST-side (eager, like the sparse set ops —
  the active-site set is data-dependent by definition; this matches the
  framework's documented eager contract for COO structure changes);
* per-offset compute on device is a dense ``[n_pairs_k, Cin] @
  [Cin, Cout]`` matmul + one scatter-add — MXU-shaped, no atomics
  (duplicate outputs accumulate via ``.at[].add``);
* every offset's pair list is padded to the max pair count across
  offsets, so the whole kernel loop is ONE stacked
  ``[K, P, Cin] x [K, Cin, Cout]`` einsum with validity masks — static
  shapes once the rulebook is built (a traced/jit step can reuse it for
  a fixed voxelization).

Gradients flow through values (the gather/matmul/scatter chain is
tape-differentiable); structure (indices) carries none, as upstream.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from ..core.dispatch import register_op
from ..core.tensor import Tensor, to_tensor
from ..ops._helpers import ensure_tensor, forward_op

__all__ = ["conv3d", "subm_conv3d", "max_pool3d", "batch_norm",
           "Conv3D", "SubmConv3D", "BatchNorm", "MaxPool3D"]


def _triple(v):
    return (v, v, v) if isinstance(v, int) else tuple(v)


def _build_rulebook(coords: np.ndarray, shape, kernel, stride, padding,
                    subm: bool):
    """Host rulebook: for each kernel offset, the (input_row, output_row)
    pairs. Returns (out_coords [M, 4], pairs_in [K, P], pairs_out [K, P],
    valid [K, P]) with P = max pairs per offset (padding contract)."""
    kd, kh, kw = kernel
    sd, sh, sw = stride
    pd, ph, pw = padding
    D, H, W = shape

    key = {}
    if subm:
        out_coords = coords.copy()
        for r, c in enumerate(out_coords):
            key[tuple(c)] = r
    else:
        out_set = {}
        for b, z, y, x in coords:
            for dz in range(kd):
                for dy in range(kh):
                    for dx in range(kw):
                        oz, r1 = divmod(z + pd - dz, sd)
                        oy, r2 = divmod(y + ph - dy, sh)
                        ox, r3 = divmod(x + pw - dx, sw)
                        if r1 or r2 or r3:
                            continue
                        if 0 <= oz < (D + 2 * pd - kd) // sd + 1 and \
                                0 <= oy < (H + 2 * ph - kh) // sh + 1 and \
                                0 <= ox < (W + 2 * pw - kw) // sw + 1:
                            out_set.setdefault((b, oz, oy, ox),
                                               len(out_set))
        out_coords = np.asarray(sorted(out_set, key=out_set.get),
                                np.int32).reshape(-1, 4)
        key = {tuple(c): r for r, c in enumerate(out_coords)}

    K = kd * kh * kw
    pairs = [[] for _ in range(K)]
    in_key = {tuple(c): r for r, c in enumerate(coords)}
    for oc, orow in key.items():
        b, oz, oy, ox = oc
        for dz in range(kd):
            for dy in range(kh):
                for dx in range(kw):
                    iz = oz * sd - pd + dz
                    iy = oy * sh - ph + dy
                    ix = ox * sw - pw + dx
                    irow = in_key.get((b, iz, iy, ix))
                    if irow is not None:
                        kidx = (dz * kh + dy) * kw + dx
                        pairs[kidx].append((irow, orow))

    P = max(1, max(len(p) for p in pairs))
    pin = np.zeros((K, P), np.int32)
    pout = np.zeros((K, P), np.int32)
    valid = np.zeros((K, P), bool)
    for kidx, p in enumerate(pairs):
        for j, (i, o) in enumerate(p):
            pin[kidx, j] = i
            pout[kidx, j] = o
            valid[kidx, j] = True
    return out_coords, pin, pout, valid


def _sparse_conv(x, weight, bias, kernel, stride, padding, subm):
    from . import SparseCooTensor, sparse_coo_tensor
    if not isinstance(x, SparseCooTensor):
        raise TypeError("sparse conv expects a SparseCooTensor")
    coords = np.asarray(x.indices().numpy()).T.astype(np.int64)  # [nnz, 4]
    B, D, H, W, Cin = x.shape
    wt = ensure_tensor(weight)          # [kd, kh, kw, Cin, Cout]
    Cout = int(wt.shape[-1])
    out_coords, pin, pout, valid = _build_rulebook(
        coords, (D, H, W), kernel, stride, padding, subm)
    M = out_coords.shape[0]
    vals = x.values()
    args = [vals, wt] + ([ensure_tensor(bias)] if bias is not None else [])
    pin_j = jnp.asarray(pin)
    pout_j = jnp.asarray(pout)
    valid_j = jnp.asarray(valid)

    def impl(v, w, *b):
        K = pin_j.shape[0]
        wk = w.reshape(K, v.shape[-1], -1)               # [K, Cin, Cout]
        gathered = v[pin_j] * valid_j[..., None]          # [K, P, Cin]
        contrib = jnp.einsum("kpc,kco->kpo", gathered, wk)
        out = jnp.zeros((M, contrib.shape[-1]), v.dtype)
        out = out.at[pout_j.reshape(-1)].add(
            (contrib * valid_j[..., None]).reshape(-1, contrib.shape[-1]))
        if b:
            out = out + b[0]
        return out

    out_vals = forward_op("sparse_conv3d" if not subm else
                          "sparse_subm_conv3d", impl, args)
    if subm:
        od, oh, ow = D, H, W
    else:
        kd, kh, kw = kernel
        sd, sh, sw = stride
        pd, ph, pw = padding
        od = (D + 2 * pd - kd) // sd + 1
        oh = (H + 2 * ph - kh) // sh + 1
        ow = (W + 2 * pw - kw) // sw + 1
    return sparse_coo_tensor(to_tensor(out_coords.T.astype(np.int64)),
                             out_vals, [B, od, oh, ow, Cout])


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
           groups: int = 1, data_format="NDHWC", name=None):
    """Sparse 3-D convolution over a COO voxel grid (ref:
    paddle.sparse.nn.functional.conv3d). The active-output set is every
    site any kernel tap reaches."""
    if dilation not in (1, (1, 1, 1)) or groups != 1:
        raise NotImplementedError("sparse conv3d: dilation/groups TBD")
    return _sparse_conv(x, weight, bias, _triple(
        tuple(int(s) for s in ensure_tensor(weight).shape[:3])),
        _triple(stride), _triple(padding), subm=False)


def subm_conv3d(x, weight, bias=None, stride=1, padding=None, dilation=1,
                groups: int = 1, data_format="NDHWC", name=None):
    """Submanifold sparse conv (ref: paddle.sparse.nn.functional.
    subm_conv3d): output sites == input sites, which stops the dilation
    of the active set — the point-cloud workhorse. ``padding`` defaults
    to same-center (k//2)."""
    k = tuple(int(s) for s in ensure_tensor(weight).shape[:3])
    if padding is None:
        padding = tuple(s // 2 for s in k)
    if _triple(stride) != (1, 1, 1):
        raise ValueError("subm_conv3d requires stride 1 (the submanifold "
                         "contract)")
    return _sparse_conv(x, weight, bias, k, (1, 1, 1), _triple(padding),
                        subm=True)


def max_pool3d(x, kernel_size, stride=None, padding=0,
               data_format="NDHWC", name=None):
    """Sparse max pooling: max over each output site's populated taps
    (ref: paddle.sparse.nn.functional.max_pool3d)."""
    from . import SparseCooTensor, sparse_coo_tensor
    k = _triple(kernel_size)
    s = _triple(stride) if stride is not None else k
    p = _triple(padding)
    coords = np.asarray(x.indices().numpy()).T.astype(np.int64)
    B, D, H, W, C = x.shape
    out_coords, pin, pout, valid = _build_rulebook(
        coords, (D, H, W), k, s, p, subm=False)
    M = out_coords.shape[0]
    pin_j = jnp.asarray(pin)
    pout_j = jnp.asarray(pout)
    valid_j = jnp.asarray(valid)

    def impl(v):
        NEG = jnp.asarray(-jnp.inf, v.dtype)
        gathered = jnp.where(valid_j[..., None], v[pin_j], NEG)
        out = jnp.full((M, v.shape[-1]), NEG, v.dtype)
        out = out.at[pout_j.reshape(-1)].max(
            gathered.reshape(-1, v.shape[-1]))
        return jnp.where(jnp.isfinite(out), out, 0)

    vals = forward_op("sparse_max_pool3d", impl, [x.values()])
    kd, kh, kw = k
    sd, sh, sw = s
    pd, ph, pw = p
    od = (D + 2 * pd - kd) // sd + 1
    oh = (H + 2 * ph - kh) // sh + 1
    ow = (W + 2 * pw - kw) // sw + 1
    return sparse_coo_tensor(to_tensor(out_coords.T.astype(np.int64)),
                             vals, [B, od, oh, ow, C])


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training: bool = False, momentum: float = 0.9,
               epsilon: float = 1e-5, name=None):
    """BatchNorm over the VALUES of a COO tensor (ref:
    paddle.sparse.nn.BatchNorm — statistics over active sites only, the
    sparse-CNN convention). Pure in-graph compute; running stats are
    returned updated when training."""
    from . import SparseCooTensor, sparse_coo_tensor
    vals = x.values()
    args = [vals, ensure_tensor(running_mean), ensure_tensor(running_var)]
    if weight is not None:
        args.append(ensure_tensor(weight))
    if bias is not None:
        args.append(ensure_tensor(bias))

    def impl(v, rm, rv, *wb):
        if training:
            mean = v.mean(0)
            var = v.var(0)
            new_rm = momentum * rm + (1 - momentum) * mean
            new_rv = momentum * rv + (1 - momentum) * var
        else:
            mean, var = rm, rv
            new_rm, new_rv = rm, rv
        out = (v - mean) / jnp.sqrt(var + epsilon)
        i = 0
        if weight is not None:
            out = out * wb[i]
            i += 1
        if bias is not None:
            out = out + wb[i]
        return out, new_rm, new_rv

    out_vals, nrm, nrv = forward_op("sparse_batch_norm", impl, args)
    out = sparse_coo_tensor(x.indices(), out_vals, x.shape)
    return out, nrm, nrv


# ---------------------------------------------------------------------------
# layer tier (paddle.sparse.nn classes)
# ---------------------------------------------------------------------------

class _SparseConvBase:
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, bias_attr=True, seed: int = 0):
        k = _triple(kernel_size)
        rng = np.random.default_rng(seed)
        fan_in = in_channels * k[0] * k[1] * k[2]
        bound = 1.0 / np.sqrt(fan_in)
        self.weight = to_tensor((rng.uniform(
            -bound, bound, k + (in_channels, out_channels))
        ).astype(np.float32))
        self.weight.stop_gradient = False
        self.bias = None
        if bias_attr:
            self.bias = to_tensor(np.zeros(out_channels, np.float32))
            self.bias.stop_gradient = False
        self.stride = stride
        self.padding = padding

    def parameters(self):
        return [self.weight] + ([self.bias] if self.bias is not None
                                else [])


class Conv3D(_SparseConvBase):
    """ref: paddle.sparse.nn.Conv3D."""

    def __call__(self, x):
        return conv3d(x, self.weight, self.bias, self.stride, self.padding)


class SubmConv3D(_SparseConvBase):
    """ref: paddle.sparse.nn.SubmConv3D."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=None, bias_attr=True, seed: int = 0):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, bias_attr, seed)

    def __call__(self, x):
        return subm_conv3d(x, self.weight, self.bias, self.stride,
                           self.padding)


class BatchNorm:
    """ref: paddle.sparse.nn.BatchNorm (stateful running stats)."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5):
        self.weight = to_tensor(np.ones(num_features, np.float32))
        self.bias = to_tensor(np.zeros(num_features, np.float32))
        self.weight.stop_gradient = False
        self.bias.stop_gradient = False
        self._mean = np.zeros(num_features, np.float32)
        self._var = np.ones(num_features, np.float32)
        self.momentum = momentum
        self.epsilon = epsilon
        self.training = True

    def parameters(self):
        return [self.weight, self.bias]

    def eval(self):
        self.training = False
        return self

    def __call__(self, x):
        out, nrm, nrv = batch_norm(
            x, self._mean, self._var, self.weight, self.bias,
            training=self.training, momentum=self.momentum,
            epsilon=self.epsilon)
        if self.training:
            self._mean = np.asarray(nrm._value)
            self._var = np.asarray(nrv._value)
        return out


class MaxPool3D:
    """ref: paddle.sparse.nn.MaxPool3D."""

    def __init__(self, kernel_size, stride=None, padding=0):
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding

    def __call__(self, x):
        return max_pool3d(x, self.kernel_size, self.stride, self.padding)


for _n, _f in (("sparse_conv3d", conv3d),
               ("sparse_subm_conv3d", subm_conv3d),
               ("sparse_max_pool3d", max_pool3d),
               ("sparse_batch_norm", batch_norm)):
    register_op(_n, _f, (_f.__doc__ or "").strip().split("\n")[0],
                category="sparse", public=_f)
