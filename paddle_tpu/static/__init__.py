"""``paddle.static`` shim.

Parity stance (SURVEY §7, recorded scope): the reference's static graph mode
(Program/Executor/append_backward) is replaced wholesale by the jit stack —
``to_static`` traces imperative code into ONE compiled XLA program, which IS
the static graph. This module keeps the load-bearing names working:

* ``InputSpec`` — real (shared with jit).
* ``save_inference_model`` / ``load_inference_model`` — map onto
  ``jit.save`` / ``jit.load`` (StableHLO artifact).
* ``enable_static`` — warns and keeps eager+jit semantics (imperative code
  under this framework is already compiled via to_static).
* Program/Executor-class APIs raise with a pointer to the jit equivalent
  rather than silently half-working.
"""

from __future__ import annotations

import warnings

from ..jit.api import InputSpec

__all__ = ["InputSpec", "enable_static", "disable_static", "Program",
           "Executor", "default_main_program", "default_startup_program",
           "program_guard", "save_inference_model", "load_inference_model",
           "name_scope", "device_guard"]

_static_mode = False


def enable_static():
    global _static_mode
    if not _static_mode:
        warnings.warn(
            "paddle.static: static graph mode maps onto the jit stack on "
            "this framework — code keeps eager semantics and is compiled "
            "via paddle.jit.to_static; Program/Executor APIs are not "
            "available", stacklevel=2)
    _static_mode = True


def disable_static():
    global _static_mode
    _static_mode = False


def in_static_mode() -> bool:
    return _static_mode


def _unsupported(name: str):
    raise NotImplementedError(
        f"paddle.static.{name}: the ProgramDesc/Executor machinery is "
        f"replaced by XLA compilation — use @paddle.jit.to_static for "
        f"compiled training steps and paddle.jit.save/load for artifacts "
        f"(SURVEY §7 design stance)")


class Program:
    def __init__(self, *a, **k):
        _unsupported("Program")


class Executor:
    def __init__(self, *a, **k):
        _unsupported("Executor")


def default_main_program():
    _unsupported("default_main_program")


def default_startup_program():
    _unsupported("default_startup_program")


def program_guard(*a, **k):
    _unsupported("program_guard")


def name_scope(prefix=None):
    import contextlib

    @contextlib.contextmanager
    def _scope():
        yield
    return _scope()


def device_guard(device=None):
    import contextlib

    @contextlib.contextmanager
    def _scope():
        yield
    return _scope()


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         **kwargs):
    """Maps onto jit.save: ``fetch_vars`` must be the traced layer/function
    (the reference signature's executor is meaningless here)."""
    from ..jit import api as jit_api
    program = kwargs.get("program")
    layer = program if program is not None else fetch_vars
    specs = feed_vars if feed_vars else None
    return jit_api.save(layer, path_prefix, input_spec=specs)


def load_inference_model(path_prefix, executor=None, **kwargs):
    from ..jit import api as jit_api
    return jit_api.load(path_prefix)
