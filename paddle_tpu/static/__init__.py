"""``paddle.static`` shim.

Parity stance (SURVEY §7, recorded scope): the reference's static graph mode
(Program/Executor/append_backward) is replaced wholesale by the jit stack —
``to_static`` traces imperative code into ONE compiled XLA program, which IS
the static graph. This module keeps the load-bearing names working:

* ``InputSpec`` — real (shared with jit).
* ``save_inference_model`` / ``load_inference_model`` — map onto
  ``jit.save`` / ``jit.load`` (StableHLO artifact).
* ``enable_static`` — enters static mode: a Program records every
  dispatched op (r5, static/program.py — the single-dispatcher funnel IS
  the ProgramDesc builder) and ``Executor.run(feed, fetch_list)`` replays
  the tape as a pure function of the feeds. The classic
  data/program_guard/Executor workflow WORKS, landing on the same
  compiled-XLA substrate as to_static.
"""

from __future__ import annotations

import warnings

from ..jit.api import InputSpec
from .program import (Executor, MissingFeedError, Program, append_backward,
                      data, default_main_program, default_startup_program,
                      program_guard)

__all__ = ["InputSpec", "enable_static", "disable_static", "Program",
           "Executor", "MissingFeedError", "data",
           "append_backward", "default_main_program",
           "default_startup_program", "program_guard",
           "save_inference_model", "load_inference_model",
           "name_scope", "device_guard", "nn"]

_static_mode = False


def enable_static():
    """Enter static mode: the default main Program starts recording every
    dispatched op (construction still executes eagerly on placeholder
    data — that is the shape-inference pass)."""
    global _static_mode
    from ..core import dispatch as _d
    _static_mode = True
    _d._static_recorder = default_main_program()


def disable_static():
    global _static_mode
    from ..core import dispatch as _d
    _static_mode = False
    _d._static_recorder = None


def in_static_mode() -> bool:
    return _static_mode


class nn:
    """paddle.static.nn namespace: the layer-op surface the static
    workflow uses (fc + the functional layers; everything records into
    the active Program through the dispatcher)."""
    from ..ops.legacy import fc  # noqa: F401
    fc = staticmethod(fc)

    @staticmethod
    def batch_norm(x, *a, **k):
        from ..nn import functional as F
        return F.batch_norm(x, *a, **k)

    @staticmethod
    def conv2d(x, *a, **k):
        from ..nn import functional as F
        return F.conv2d(x, *a, **k)

    @staticmethod
    def sequence_pool(x, pool_type, lens):
        from ..ops.sequence import sequence_pool
        return sequence_pool(x, pool_type, lens)


def name_scope(prefix=None):
    import contextlib

    @contextlib.contextmanager
    def _scope():
        yield
    return _scope()


def device_guard(device=None):
    import contextlib

    @contextlib.contextmanager
    def _scope():
        yield
    return _scope()


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         **kwargs):
    """Maps onto jit.save: ``fetch_vars`` must be the traced layer/function
    (the reference signature's executor is meaningless here)."""
    from ..jit import api as jit_api
    program = kwargs.get("program")
    layer = program if program is not None else fetch_vars
    specs = feed_vars if feed_vars else None
    return jit_api.save(layer, path_prefix, input_spec=specs)


def load_inference_model(path_prefix, executor=None, **kwargs):
    from ..jit import api as jit_api
    return jit_api.load(path_prefix)
