"""Working Program/Executor tier for ``paddle.static``.

Parity target: the reference's static-graph workflow
(``paddle/fluid/framework.py`` Program + ``executor.py`` Executor):

    paddle.enable_static()
    x = paddle.static.data("x", [None, 4], "float32")
    out = my_layers(x)
    loss = paddle.mean(out)
    opt.minimize(loss)
    exe = paddle.static.Executor()
    exe.run(paddle.static.default_startup_program())
    exe.run(feed={"x": arr}, fetch_list=[loss])

TPU redesign: the reference's Program is a ProgramDesc protobuf built by
every layer call appending OpDescs. Here the SAME single-dispatcher funnel
the SOT tier uses (``core.dispatch.forward_op``) gives the recording for
free: while a Program is being constructed (static mode, program_guard),
every dispatched op appends ``(kernel_fn, input-refs, kwargs, output-refs)``
to the active Program's tape — the tape IS the ProgramDesc, with Python
object identity as SSA names (outputs pinned per record so ids stay
unique). Construction still executes eagerly on the placeholder batch
(shape inference comes out as real shapes, exactly what InferMeta provides
upstream). ``Executor.run`` replays the tape as a pure function of the
feeds + live Parameters.

``Optimizer.minimize(loss)`` marks the program as a TRAINING program: the
replay runs under the autograd tape, appends backward, and applies the
optimizer — the ``append_backward`` + optimizer-op-append contract without
a second graph IR.

v1 scope (documented limits): replay re-dispatches the tape eagerly (each
op is a jit-compiled XLA kernel; the whole-program fusion tier remains
``to_static``, which this module intentionally shares its substrate with);
ops that close over construction-time state (dropout keys, BN running
stats) replay that state — a warning fires at record time and the
stochastic-training path is ``to_static``.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

__all__ = ["Program", "Executor", "MissingFeedError", "data",
           "default_main_program", "default_startup_program",
           "program_guard", "append_backward"]


class MissingFeedError(KeyError):
    """Executor.run was asked to fetch something that depends on a feed
    placeholder with no entry in ``feed`` (ADVICE r5: the replay used to
    silently substitute the construction-time placeholder — zeros, with
    dynamic dims materialized as 1 — so a typo'd feed name produced wrong
    numerics instead of the reference Executor's missing-feed error).
    ``missing`` carries the placeholder names the fetch needs."""

    def __init__(self, missing):
        self.missing = sorted(missing)
        super().__init__(
            f"feed is missing placeholder(s) {self.missing} that the "
            f"fetched subgraph depends on; pass them in `feed` "
            f"(check for typo'd feed names)")

    def __str__(self):           # KeyError quotes repr(args[0]) by default
        return self.args[0]


_main_program: Optional["Program"] = None
_startup_program: Optional["Program"] = None


class _OpRecord:
    __slots__ = ("name", "fn", "arg_ids", "raw_args", "kwargs", "out_ids",
                 "raw_outs", "differentiable")

    def __init__(self, name, fn, arg_ids, raw_args, kwargs, out_ids,
                 raw_outs, differentiable):
        self.name = name
        self.fn = fn
        self.arg_ids = arg_ids      # per-arg: ("var", id) | ("const", val)
        self.raw_args = raw_args
        self.kwargs = kwargs
        self.out_ids = out_ids
        # outputs are PINNED for the program's lifetime: env keys are
        # id()s, so a GC'd output would let an unrelated later tensor
        # reuse its id and alias its env slot during replay
        self.raw_outs = raw_outs
        self.differentiable = differentiable


class Program:
    """The op-tape program (ProgramDesc equivalent)."""

    def __init__(self):
        self.ops: List[_OpRecord] = []
        self.feeds: Dict[str, Any] = {}       # name -> placeholder Tensor
        self.train_spec = None                 # (optimizer, loss_tensor)
        self._warned_stateful = False

    _STATEFUL_MARKERS = ("dropout", "bernoulli", "uniform", "normal",
                         "rand", "batch_norm", "rrelu", "multinomial",
                         "gumbel", "alpha_dropout")

    # -- recording hook (called from core.dispatch.forward_op) -------------
    def record(self, name, fn, args, kwargs, outs, differentiable):
        from ..core.tensor import Tensor
        if not self._warned_stateful and any(
                m in (name or "") for m in self._STATEFUL_MARKERS):
            self._warned_stateful = True
            import warnings
            warnings.warn(
                f"paddle.static: op '{name}' closes over construction-time "
                "state (an RNG key / running statistics); Executor.run "
                "replays the SAME state every call — random masks freeze "
                "and BN running stats do not advance. Use "
                "paddle.jit.to_static for stochastic/stateful training "
                "steps (the jit tier re-keys per call).", stacklevel=4)
        arg_ids = []
        for a in args:
            if isinstance(a, Tensor):
                arg_ids.append(("var", id(a)))
            else:
                arg_ids.append(("const", a))
        out_list = outs if isinstance(outs, (tuple, list)) else (outs,)
        raw_outs = [o for o in out_list if isinstance(o, Tensor)]
        self.ops.append(_OpRecord(
            name, fn, arg_ids, list(args), dict(kwargs),
            [id(o) for o in raw_outs], raw_outs, differentiable))

    def global_block(self):
        return self

    @property
    def var_names(self):
        return list(self.feeds)

    def __repr__(self):
        kind = "train" if self.train_spec else "inference"
        return (f"Program(ops={len(self.ops)}, feeds={list(self.feeds)}, "
                f"{kind})")


def default_main_program() -> Program:
    global _main_program
    if _main_program is None:
        _main_program = Program()
    return _main_program


def default_startup_program() -> Program:
    """Parameter initialization happens eagerly at layer construction on
    this framework (the reference's startup program runs initializer ops);
    an empty Program keeps the exe.run(startup) idiom working."""
    global _startup_program
    if _startup_program is None:
        _startup_program = Program()
    return _startup_program


class _ProgramGuard:
    def __init__(self, main: Program, startup: Optional[Program]):
        self.main = main
        self.startup = startup

    def __enter__(self):
        global _main_program
        from ..core import dispatch as _d
        self._prev = _main_program
        self._prev_rec = _d._static_recorder
        _main_program = self.main
        _d._static_recorder = self.main
        return self.main

    def __exit__(self, *exc):
        global _main_program
        from ..core import dispatch as _d
        _d._static_recorder = self._prev_rec
        _main_program = self._prev
        return False


def program_guard(main_program: Program, startup_program: Optional[Program]
                  = None):
    return _ProgramGuard(main_program, startup_program)


def data(name: str, shape: Sequence[int], dtype="float32", lod_level=0):
    """Feed placeholder (ref: paddle.static.data). Dynamic dims (None/-1)
    materialize as 1 for construction-time shape inference; Executor.run
    re-traces per concrete feed shape (symbolic batch the jit way)."""
    from ..core.tensor import to_tensor
    from ..ops.creation import canonical_dtype
    concrete = tuple(1 if (s is None or int(s) < 0) else int(s)
                     for s in shape)
    ph = to_tensor(np.zeros(concrete, canonical_dtype(dtype)))
    ph.stop_gradient = True
    prog = default_main_program()
    prog.feeds[name] = ph
    return ph


def append_backward(loss, parameter_list=None):
    """Mark the program for backward+update replay (ref: append_backward).
    Returns the (param, grad-slot) pairs lazily — grads exist after an
    Executor.run of the training program."""
    prog = default_main_program()
    prog.train_spec = (prog.train_spec[0] if prog.train_spec else None,
                       loss)
    return []


class Executor:
    """Replays a Program as a pure function of its feeds (ref:
    paddle.static.Executor). ``place`` is accepted and ignored — device
    placement is XLA's."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program: Optional[Program] = None, feed=None,
            fetch_list=None, return_numpy: bool = True):
        prog = program or default_main_program()
        feed = feed or {}
        fetch_list = fetch_list or []
        if prog is _startup_program:
            return []          # startup: initialization already happened

        from ..core.tensor import Tensor, to_tensor

        # a placeholder the FETCHED subgraph needs must be fed — silently
        # replaying the construction-time placeholder (zeros, dynamic dims
        # materialized as 1) turns a typo'd feed name into wrong numerics
        needed = self._needed_placeholders(prog, fetch_list)
        missing = [name for name, ph in prog.feeds.items()
                   if id(ph) in needed and name not in feed]
        if missing:
            raise MissingFeedError(missing)

        # map feed names -> placeholder ids -> fed values
        env: Dict[int, Any] = {}
        for name, ph in prog.feeds.items():
            if name in feed:
                v = feed[name]
                env[id(ph)] = v if isinstance(v, Tensor) else to_tensor(
                    np.asarray(v))
            else:
                env[id(ph)] = ph

        from ..core import dispatch as _d
        saved_rec, _d._static_recorder = _d._static_recorder, None
        try:
            outs = self._replay(prog, env)
        finally:
            _d._static_recorder = saved_rec

        if prog.train_spec and prog.train_spec[0] is not None:
            opt, loss = prog.train_spec
            lt = outs.get(id(loss), loss)
            lt.backward()
            opt.step()
            opt.clear_grad()

        results = []
        for f in fetch_list:
            t = outs.get(id(f), f)
            results.append(np.asarray(t.numpy()) if return_numpy else t)
        return results

    @staticmethod
    def _needed_placeholders(prog: Program, fetch_list) -> set:
        """Ids of every variable the fetches (and, for a training program,
        the loss) transitively depend on: walk the tape backward, growing
        the needed set through each record whose outputs intersect it. A
        fetched placeholder itself counts (the passthrough-fetch case)."""
        needed = {id(f) for f in fetch_list}
        if prog.train_spec and prog.train_spec[1] is not None:
            needed.add(id(prog.train_spec[1]))       # loss drives backward
        for rec in reversed(prog.ops):
            if any(oid in needed for oid in rec.out_ids):
                needed.update(ref for kind, ref in rec.arg_ids
                              if kind == "var")
        return needed

    def _replay(self, prog: Program, env: Dict[int, Any]) -> Dict[int, Any]:
        """Walk the tape; every op re-dispatches through forward_op with
        feeds/intermediates substituted (Parameters read their LIVE values,
        so optimizer updates persist across run() calls — the reference's
        scope semantics)."""
        from ..core.dispatch import forward_op
        from ..core.tensor import Tensor
        for rec in prog.ops:
            args = []
            for (kind, ref), raw in zip(rec.arg_ids, rec.raw_args):
                if kind == "var" and ref in env:
                    args.append(env[ref])
                else:
                    args.append(raw)
            out = forward_op(rec.name, rec.fn, args, rec.kwargs,
                             differentiable=rec.differentiable)
            out_list = out if isinstance(out, (tuple, list)) else (out,)
            for oid, o in zip(rec.out_ids,
                              [o for o in out_list
                               if isinstance(o, Tensor)]):
                env[oid] = o
        return env

    def close(self):
        pass


def reset_programs():
    """Test hook: drop the module-level default programs."""
    global _main_program, _startup_program
    _main_program = None
    _startup_program = None
