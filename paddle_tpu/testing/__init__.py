"""Testing utilities — the fault-injection (chaos) harness lives in
``paddle_tpu.testing.chaos``."""

from . import chaos  # noqa: F401

__all__ = ["chaos"]
