"""Chaos / fault-injection harness (docs/FAULT_TOLERANCE.md).

Composable injectors that drive the checkpoint + launch + elastic stack
through the failure modes a production TPU job actually sees, so the
robustness machinery is EXERCISED, not just written:

=====================  ====================================================
injector               fault it models
=====================  ====================================================
``truncate_file``      a shard cut short by a crash / full disk
``flip_bits``          silent data corruption (bad DMA, bit rot)
``fail_nth``           the Nth ``os.rename``/``os.replace``/``write`` in a
                       region raising (quota, I/O error) — syscall shim
``async_writer_fault`` an exception inside the background checkpoint
                       writer thread
``stall_heartbeat``    an alive-but-frozen worker (stops stamping past the
                       launcher's TTL without exiting)
``kill_self``          a rank dying mid-step (preemption without grace,
                       OOM kill)
``nan_payload``        NaN/Inf landing in a batch/activation buffer (the
                       sentinel's bad-step fault)
``bad_sample``         a corrupt record: Dataset.__getitem__ raising,
                       transiently (retry path) or forever (quarantine)
``dead_worker``        a DataLoader worker segfaulting mid-epoch (fires
                       once; the resurrected replacement survives)
``stalled_consumer``   a serving client that reads a few stream tokens
                       then vanishes without draining (closed SSE
                       connection) — the abandoned-stream block leak
``poison_prompt``      a malformed serving request: out-of-vocab token
                       ids / empty / garbage-length prompts that must not
                       corrupt neighbouring requests' outputs
``flood_tenant``       one tenant burst-submitting until the bounded
                       queue sheds — the noisy-neighbour overload fault
``engine_crash``       the serving engine's step loop raising mid-trace
                       (device error, host-side bug) — the supervisor
                       must rebuild and resubmit bit-exactly
``disconnect_mid_stream``  an asyncio front-line client that consumes a
                       few SSE events then closes the connection — the
                       server-side abandoned-stream cancel path
``slow_client``        a front-line client reading slower than the
                       engine produces: the bounded per-client buffer
                       overflows and the server must disconnect it
                       through engine.cancel (KV freed, not pinned)
``replica_kill``       a fleet replica dying for good mid-trace (host
                       loss, restart budget gone) — the router must fail
                       its requests over to a healthy replica bit-exactly
``slow_replica``       a replica alive but making no progress (wedged
                       accelerator, swap storm): TTFT stalls, hedged
                       retries fire, the breaker eventually opens
``flaky_probe``        a replica whose health/ops surface raises while
                       the engine may be fine — the router's probe path
                       must route around it and charge its breaker
``host_pressure``      host RAM pressure shrinking the KV offload tier
                       live (OOM-killer headroom, a co-tenant ballooning)
                       — displaced blocks must fall back to recompute,
                       never crash or corrupt
``corrupt_offload_block``  a bit-flip inside a host-offloaded KV block
                       (ECC miss, bit rot): the write-time checksum must
                       degrade the entry to a cache MISS so the request
                       recomputes bit-exactly — wrong KV is never served
``kill_prefill_replica``  a disaggregated-prefill replica dying for good
                       mid-handoff: staged requests must land on a
                       decode replica via fallback recompute with zero
                       failed requests, and long prompts collapse to the
                       unified path while the pool is empty
``stale_directory``    a poisoned fleet-cache-directory entry: the next
                       cross-replica chain pull through the armed holder
                       fails checksum verification at the graft end and
                       degrades to recompute — wrong KV is never pulled
``process_kill``       the whole serving process dying without grace
                       (kill -9 between steps): only the journal's
                       fsynced state survives; a cold restart must
                       resubmit every non-terminal request bit-exactly,
                       re-emitting no delivered token
``torn_journal_tail``  a crash mid-append cutting the journal's last
                       WAL record short: recovery must truncate the
                       torn tail and come up at the last durable record
``corrupt_snapshot``   bit rot inside the newest serving-state
                       snapshot: recovery must reject the generation
                       and fall back to the previous one or a full WAL
                       replay — the last good state, never wrong output
``adapter_churn``      hostile LoRA-adapter locality: seeded rounds of
                       cold-adapter acquires force the device pool's
                       LRU to evict warm adapters mid-traffic — pinned
                       (running) adapters must survive in place and
                       reloads must stay bit-exact
=====================  ====================================================

File injectors are plain functions; process/region injectors are context
managers and compose by nesting. The chaos test suite
(``tests/test_chaos.py``) asserts that under every one of these the job
resumes from a committed checkpoint and converges to the unfaulted loss —
and, for the serving trio, that the engine ends with BlockManager
accounting balanced and keeps accepting (and bit-exactly serving) new
requests.
"""

from __future__ import annotations

import contextlib
import os
import random
import signal
from typing import Optional

__all__ = ["truncate_file", "flip_bits", "fail_nth", "async_writer_fault",
           "stall_heartbeat", "kill_self", "nan_payload", "bad_sample",
           "dead_worker", "stalled_consumer", "poison_prompt",
           "flood_tenant", "engine_crash", "disconnect_mid_stream",
           "slow_client", "replica_kill", "slow_replica", "flaky_probe",
           "host_pressure", "corrupt_offload_block",
           "kill_prefill_replica", "stale_directory",
           "process_kill", "torn_journal_tail", "corrupt_snapshot",
           "adapter_churn",
           "ChaosEvent", "ChaosTimeline", "chaos_timeline",
           "TIMELINE_INJECTORS", "TIER_INJECTORS", "DISAGG_INJECTORS",
           "DURABLE_INJECTORS", "LORA_INJECTORS", "INJECTORS"]


def truncate_file(path: str, frac: float = 0.5,
                  keep_bytes: Optional[int] = None) -> int:
    """Cut ``path`` short (a crash mid-write / disk-full artifact).
    Keeps ``keep_bytes`` bytes when given, else ``frac`` of the file.
    Returns the new size."""
    size = os.path.getsize(path)
    keep = keep_bytes if keep_bytes is not None else int(size * frac)
    keep = max(0, min(size, keep))
    with open(path, "rb+") as f:
        f.truncate(keep)
    return keep


def flip_bits(path: str, offset: Optional[int] = None, nbits: int = 8,
              seed: int = 0) -> int:
    """XOR-corrupt ``nbits`` bits at ``offset`` (random position when None)
    — silent corruption a checksum must catch. Returns the offset hit."""
    size = os.path.getsize(path)
    if size == 0:
        raise ValueError(f"cannot bit-flip empty file {path!r}")
    rng = random.Random(seed)
    if offset is None:
        offset = rng.randrange(size)
    offset = min(offset, size - 1)
    with open(path, "rb+") as f:
        f.seek(offset)
        b = f.read(1)[0]
        mask = 0
        for _ in range(max(1, nbits)):
            mask |= 1 << rng.randrange(8)
        f.seek(offset)
        f.write(bytes([b ^ (mask or 0x01)]))
    return offset


@contextlib.contextmanager
def fail_nth(module, name: str, n: int = 1, exc: Optional[BaseException] = None):
    """Monkeypatched syscall shim: the Nth call (1-based) of
    ``module.name`` inside the region raises (default ``OSError``). Models
    quota/EIO failures at exact protocol positions, e.g.::

        with chaos.fail_nth(os, "replace", n=2):
            save_state_dict(state, path)   # 2nd atomic rename dies
    """
    real = getattr(module, name)
    err = exc if exc is not None else OSError(
        f"chaos: injected failure on call #{n} of {module.__name__}.{name}")
    state = {"calls": 0}

    def shim(*args, **kwargs):
        state["calls"] += 1
        if state["calls"] == n:
            raise err
        return real(*args, **kwargs)

    setattr(module, name, shim)
    try:
        yield state
    finally:
        setattr(module, name, real)


@contextlib.contextmanager
def async_writer_fault(exc: Optional[BaseException] = None):
    """Every job the background checkpoint writer picks up inside the
    region fails with ``exc`` (stored on the job, surfaced by
    ``wait()``/the next save — the error-propagation contract under
    test)."""
    from ..framework import async_writer
    err = exc if exc is not None else RuntimeError(
        "chaos: injected async-writer fault")
    async_writer.set_fault(err)
    try:
        yield err
    finally:
        async_writer.set_fault(None)


class stall_heartbeat:
    """Freeze the worker's liveness stamping (the thread keeps running but
    stops SETting) — to the launcher's monitor this process is
    alive-but-hung, and past ``--elastic_timeout`` it gets killed and the
    round restarts. Models a native deadlock / swap storm.

    A plain class (NOT a generator contextmanager) on purpose: a stall is
    often armed fire-and-forget right before the process freezes, and a
    GC'd generator CM would run its ``finally`` and silently un-pause."""

    def __enter__(self):
        from ..distributed import elastic
        self._ev = elastic._pause_event()
        if self._ev is not None:
            self._ev.set()
        return self

    def __exit__(self, *exc):
        if self._ev is not None:
            self._ev.clear()
        return False


def kill_self(sig: int = signal.SIGKILL) -> None:
    """Die mid-step with no cleanup (default SIGKILL: no atexit, no flush
    — exactly what a preemption without grace or an OOM kill looks like)."""
    os.kill(os.getpid(), sig)


# ---------------------------------------------------------------------------
# runtime-anomaly injectors (paddle_tpu.health; ISSUE 3)
# ---------------------------------------------------------------------------

def nan_payload(x, frac: float = 1.0, value: float = float("nan"),
                seed: int = 0):
    """Poison a numpy array (or a nested batch of them) with NaN/Inf —
    models an overflowed reduction, a bf16 numerics edge, or corrupt DMA
    landing in an activations/input buffer: the fault the on-device
    sentinel must catch as a bad step. ``frac`` of the elements (chosen by
    ``seed``) are replaced; returns a poisoned COPY."""
    import numpy as np
    if isinstance(x, dict):
        return {k: nan_payload(v, frac, value, seed) for k, v in x.items()}
    if isinstance(x, (tuple, list)):
        return type(x)(nan_payload(v, frac, value, seed) for v in x)
    arr = np.array(x, copy=True)
    if not np.issubdtype(arr.dtype, np.floating):
        return arr          # int payloads can't carry NaN — pass through
    flat = arr.reshape(-1)
    n = max(1, int(flat.size * min(1.0, max(0.0, frac))))
    idx = random.Random(seed).sample(range(flat.size), n) \
        if n < flat.size else slice(None)
    flat[idx] = value
    return arr


class bad_sample:
    """Dataset wrapper: ``__getitem__`` raises for the chosen indices —
    models a corrupt record / undecodable image. ``fails_each=None`` makes
    the fault DETERMINISTIC (every access raises: the quarantine path);
    ``fails_each=n`` makes it TRANSIENT (the first n accesses per index
    raise, then heal: the retry/backoff path)."""

    def __init__(self, dataset, indices, fails_each: Optional[int] = None,
                 exc_type=ValueError):
        self.dataset = dataset
        self.bad = set(int(i) for i in indices)
        self.fails_each = fails_each
        self.exc_type = exc_type
        self._counts = {}

    def __len__(self):
        return len(self.dataset)

    def __getitem__(self, i):
        if int(i) in self.bad:
            n = self._counts.get(int(i), 0)
            if self.fails_each is None or n < self.fails_each:
                self._counts[int(i)] = n + 1
                raise self.exc_type(
                    f"chaos: injected bad sample at index {i} "
                    f"(attempt {n + 1})")
        return self.dataset[i]


class dead_worker:
    """Dataset wrapper: the DataLoader worker that fetches ``at_index``
    SIGKILLs itself — a segfault/OOM in dataset code mid-epoch. The death
    fires ONCE per ``marker`` file (fork-shared), so the resurrected
    replacement worker survives the re-queued batch and the epoch heals."""

    def __init__(self, dataset, at_index: int, marker: str,
                 sig: int = signal.SIGKILL):
        self.dataset = dataset
        self.at_index = int(at_index)
        self.marker = marker
        self.sig = sig

    def __len__(self):
        return len(self.dataset)

    def __getitem__(self, i):
        if int(i) == self.at_index:
            try:
                fd = os.open(self.marker, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            except FileExistsError:
                pass            # already died once — the replacement lives
            else:
                os.close(fd)
                os.kill(os.getpid(), self.sig)
        return self.dataset[i]


# ---------------------------------------------------------------------------
# serving-overload injectors (paddle_tpu.inference.serving; ISSUE 6)
# ---------------------------------------------------------------------------

def stalled_consumer(engine, events: int = 2, close: bool = True) -> dict:
    """A streaming client that reads ``events`` tokens from
    ``engine.stream()`` and then VANISHES — the closed-SSE-connection /
    crashed-downstream fault. Before the lifecycle work this leaked the
    in-flight requests' KV blocks until someone else happened to drain
    the engine; now closing the abandoned generator must CANCEL the
    remaining work and return every block to the pool.

    ``close=True`` closes the generator explicitly (what CPython's GC
    does to an abandoned generator, made deterministic for the test).
    Returns ``{"events": tokens consumed, "cancelled": requests
    cancelled by the close}``."""
    gen = engine.stream()
    got = 0
    try:
        for _ in range(max(0, int(events))):
            next(gen)
            got += 1
    except StopIteration:
        pass
    before = engine.stats()["cancelled"]
    if close:
        gen.close()               # the consumer is gone; nobody resumes it
    return {"events": got,
            "cancelled": engine.stats()["cancelled"] - before}


def poison_prompt(prompt, vocab_size: int, mode: str = "oov",
                  seed: int = 0):
    """Corrupt a serving prompt the way a broken tokenizer / malicious
    client would: ``"oov"`` replaces every id with one >= ``vocab_size``
    (an out-of-range embedding lookup — XLA clamps the gather, producing
    garbage logits that must stay CONTAINED to this request), ``"neg"``
    flips ids negative, ``"empty"`` returns a zero-length prompt (must be
    rejected or served, never wedge the engine). Returns the poisoned
    COPY; the recovery proof is that co-scheduled clean requests still
    match the dense oracle bit-for-bit and pool accounting balances."""
    import numpy as np
    p = np.array(prompt, np.int32, copy=True)
    if mode == "empty":
        return p[:0]
    rng = random.Random(seed)
    if mode == "oov":
        return np.asarray([vocab_size + rng.randrange(2 ** 16)
                           for _ in p], np.int32)
    if mode == "neg":
        return -np.abs(p) - 1
    raise ValueError(f"unknown poison_prompt mode {mode!r}")


def flood_tenant(engine, tenant: str, n: int, prompt_len: int = 8,
                 max_new_tokens: int = 4, vocab_size: int = 97,
                 seed: int = 0, **submit_kwargs) -> dict:
    """One tenant burst-submits ``n`` requests — the noisy-neighbour /
    abusive-client overload fault. Submits ride the normal ``submit()``
    path, so the bounded queue SHEDS the overflow (``ServingQueueFull``
    with a retry-after hint) instead of queueing unboundedly; under the
    fair-share policy the flood's ADMITTED share stays proportional to
    its weight, and with a tenant cache quota its churn cannot evict
    other tenants' prefix blocks. Returns ``{"rids": accepted ids,
    "shed": refused submits, "retry_after_s": last hint}``."""
    import numpy as np
    from paddle_tpu.inference.serving import ServingQueueFull
    rng = np.random.default_rng(seed)
    rids, shed, hint = [], 0, None
    for _ in range(int(n)):
        p = rng.integers(0, vocab_size, (int(prompt_len),)).astype(np.int32)
        try:
            rids.append(engine.submit(p, max_new_tokens=max_new_tokens,
                                      tenant=tenant, **submit_kwargs))
        except ServingQueueFull as e:
            shed += 1
            hint = e.retry_after_s
    return {"rids": rids, "shed": shed, "retry_after_s": hint}


# ---------------------------------------------------------------------------
# serving front-line injectors (inference.serving.server/supervisor; ISSUE 7)
# ---------------------------------------------------------------------------

def engine_crash(target, at_step: int = 1,
                 exc: Optional[BaseException] = None) -> BaseException:
    """Arm the LIVE serving engine to raise from its step loop after
    ``at_step`` more iterations — a device error or host-side bug landing
    mid-trace. ``target`` is an :class:`EngineSupervisor` (or a bare
    engine). The patch rides the engine instance, so it dies with the
    crashed engine: the supervisor's rebuilt replacement runs clean, and
    the recovery proof is that every request still finishes bit-identical
    to an uninterrupted dense run with BlockManager accounting balanced.
    Returns the armed exception (for matching in asserts)."""
    eng = getattr(target, "engine", target)
    err = exc if exc is not None else RuntimeError(
        f"chaos: injected engine crash at step +{at_step}")
    real = eng._step
    state = {"calls": 0}

    def crashing(max_iters=None):
        state["calls"] += 1
        if state["calls"] >= max(1, int(at_step)):
            raise err
        return real(max_iters)

    eng._step = crashing
    return err


async def disconnect_mid_stream(server, prompt, events: int = 2,
                                **submit_kwargs) -> dict:
    """An asyncio front-line client that consumes ``events`` stream
    events then CLOSES the stream (the SSE tab closed / TCP reset fault,
    made deterministic). Closing must cancel the request through
    ``engine.cancel`` so its KV blocks free immediately. Async — run
    inside the loop the server is bound to. Returns ``{"events": n,
    "rid": srid}``."""
    gen = server.agenerate(prompt, **submit_kwargs)
    got, rid = 0, None
    try:
        async for ev in gen:
            if ev["type"] == "start":
                rid = ev["rid"]
                continue
            got += 1
            if got >= max(0, int(events)):
                break
    finally:
        await gen.aclose()            # the consumer is gone
    return {"events": got, "rid": rid}


async def slow_client(server, prompt, read_events: int = 1,
                      timeout_s: float = 20.0, **submit_kwargs) -> dict:
    """A front-line client that reads ``read_events`` events and then
    STOPS consuming while the engine keeps producing — the slow-consumer
    fault. The per-client buffer (``FLAGS_serving_client_queue`` /
    ``ServingServer(client_queue=)``) overflows, the server marks the
    stream dropped and cancels the request, and the client's eventual
    reads end in a terminal ``disconnect`` event. Returns ``{"events",
    "dropped", "disconnected", "rid"}``."""
    import asyncio
    import time as _time
    srid, client = await server.open_stream(prompt, **submit_kwargs)
    got = 0
    it = client.events()
    while got < max(0, int(read_events)):
        try:
            await it.__anext__()
            got += 1
        except StopAsyncIteration:
            break
    # stall: consume nothing until the server drops us (or the request
    # finishes first — the sentinel overflowing the full buffer also
    # marks the stream dropped, so this is bounded either way)
    t0 = _time.time()
    while not (client.dropped or client.done) \
            and _time.time() - t0 < timeout_s:
        await asyncio.sleep(0.01)
    disconnected = False
    try:
        async for ev in it:
            if ev.get("type") == "disconnect":
                disconnected = True
    finally:
        client.closed = True
        await it.aclose()
    return {"events": got, "dropped": client.dropped,
            "disconnected": disconnected, "rid": srid}


# ---------------------------------------------------------------------------
# serving-fleet injectors (inference.serving.router/replica; ISSUE 9)
# ---------------------------------------------------------------------------

def _fleet_sup(target, rid=None):
    """Resolve (supervisor, rid) from a ServingRouter (+ optional replica
    rid), a Replica, or a bare EngineSupervisor."""
    if hasattr(target, "_replicas"):          # ServingRouter
        reps = target._replicas
        rid = next(iter(reps)) if rid is None else rid
        return reps[rid].sup, rid
    if hasattr(target, "sup"):                # Replica
        return target.sup, getattr(target, "rid", None)
    return target, rid                        # EngineSupervisor


def replica_kill(target, rid=None,
                 exc: Optional[BaseException] = None) -> Optional[int]:
    """Kill one fleet replica FOR GOOD mid-trace — host loss / a crash
    loop that exhausts the restart budget. Arms the replica's engine to
    crash on its next step with the supervisor's remaining restart budget
    zeroed, so that crash flips the replica ``broken`` (in-flight
    requests FAILED, partials readable) exactly like a real budget
    exhaustion. The recovery proof: the router fails every non-terminal
    request over to a healthy replica and final outputs stay bit-identical
    to a single-replica oracle with no delivered-token repeats. ``target``
    is a :class:`ServingRouter` (``rid`` picks the victim; default the
    first replica), a :class:`Replica`, or a bare supervisor. Returns the
    killed replica's rid."""
    sup, rid = _fleet_sup(target, rid)
    sup.max_restarts = sup.restarts           # budget: already spent
    engine_crash(sup, at_step=1, exc=exc)
    if not sup.pending:
        # an idle replica's step loop never runs through the router, so
        # the armed crash would never fire: detonate now (the supervised
        # step hits the barrier, budget is spent -> broken immediately)
        sup.step()
    return rid


def slow_replica(target, rid=None, stall_steps: int = 3,
                 delay_s: float = 0.02) -> dict:
    """A replica that is alive but making NO progress (wedged
    accelerator, swap storm): its next ``stall_steps`` engine iterations
    sleep ``delay_s`` and return nothing, then the replica heals. TTFT
    on its requests stalls, so the router's hedged retry fires (and a
    long enough stall opens the breaker). The patch rides the ENGINE
    instance — a supervisor rebuild sheds it. Returns the shared state
    dict (``calls`` counts stalled iterations)."""
    import time as _time
    sup, rid = _fleet_sup(target, rid)
    eng = sup.engine
    real = eng._step
    state = {"calls": 0, "rid": rid}

    def stalled(max_iters=None):
        if state["calls"] < max(0, int(stall_steps)):
            state["calls"] += 1
            _time.sleep(max(0.0, float(delay_s)))
            return {}
        return real(max_iters)

    eng._step = stalled
    return state


def flaky_probe(target, rid=None, fails: int = 3,
                exc: Optional[BaseException] = None) -> dict:
    """A replica whose health/ops surface is wedged while the engine may
    be fine: the next ``fails`` ``health_snapshot()`` calls raise, then
    the surface heals. The router's probe path must route traffic around
    it, charge its circuit breaker per failure, and — once the breaker
    opens — re-probe half-open after the cooldown so the healed replica
    REJOINS. Patches the supervisor instance (a rolling-restart rebuild
    sheds it). Returns the shared state dict (``calls`` counts raised
    probes)."""
    sup, rid = _fleet_sup(target, rid)
    err = exc if exc is not None else RuntimeError(
        "chaos: injected flaky health probe")
    real = sup.health_snapshot
    state = {"calls": 0, "rid": rid}

    def shim():
        if state["calls"] < max(0, int(fails)):
            state["calls"] += 1
            raise err
        return real()

    sup.health_snapshot = shim
    return state


# ---------------------------------------------------------------------------
# KV-tier injectors (inference.serving.offload; ISSUE 16)
# ---------------------------------------------------------------------------

def _tier(target, rid=None):
    """Resolve (HostOffloadTier or None, rid) from a router / replica /
    supervisor / bare engine."""
    sup, rid = _fleet_sup(target, rid)
    eng = getattr(sup, "engine", sup)
    return getattr(eng.cache, "offload", None), rid


def host_pressure(target, rid=None, blocks: int = 0) -> dict:
    """Host RAM pressure: shrink one replica's KV offload tier to
    ``blocks`` live (default 0 — the tier drops everything it holds).
    Models the OOM killer reclaiming headroom or a co-tenant ballooning.
    Displaced entries silently fall back to the recompute path — the
    recovery proof is bit-identical outputs with pool accounting and the
    ``tier_partition`` invariant intact. Returns ``{"rid", "enabled",
    "before", "after", "capacity"}`` (``enabled=False`` with the tier
    off — the fault is then vacuous, like killing a replica that holds
    nothing)."""
    tier, rid = _tier(target, rid)
    if tier is None:
        return {"rid": rid, "enabled": False, "before": 0, "after": 0,
                "capacity": 0}
    before = tier.blocks
    tier.resize(blocks)
    return {"rid": rid, "enabled": True, "before": before,
            "after": tier.blocks, "capacity": tier.capacity}


def corrupt_offload_block(target, rid=None, seed: int = 0) -> dict:
    """Flip one byte inside one host-offloaded KV block WITHOUT updating
    its write-time checksum — silent host-memory corruption (ECC miss,
    bit rot). The next swap-in attempt must detect the mismatch and
    degrade to a cache MISS (``corrupt_drops`` increments, the chain
    recomputes bit-exactly); wrong KV must never reach a request.
    Returns ``{"rid", "enabled", "key"}`` — ``key=None`` when the tier
    holds nothing to corrupt (the fault is vacuous)."""
    tier, rid = _tier(target, rid)
    if tier is None:
        return {"rid": rid, "enabled": False, "key": None}
    return {"rid": rid, "enabled": True,
            "key": tier.corrupt_one(int(seed))}


# ---------------------------------------------------------------------------
# disaggregated-prefill / fleet-cache injectors (ISSUE 17)
# ---------------------------------------------------------------------------

def kill_prefill_replica(target, rid=None,
                         exc: Optional[BaseException] = None) -> dict:
    """Kill a PREFILL-pool replica for good — possibly mid-handoff, with
    prompts mid-chunked-prefill and first tokens not yet adopted by a
    decode replica. Same mechanics as :func:`replica_kill` (restart
    budget zeroed + armed crash, detonated immediately when idle), aimed
    at the first replica with ``role == "prefill"`` (or ``rid``). The
    recovery proof: every staged request lands on a decode replica
    through the failover/recompute fallback — ZERO failed requests,
    outputs bit-identical to a single-replica oracle — and subsequent
    long prompts collapse to the unified path while the pool is empty.
    Returns ``{"rid", "enabled"}``; ``enabled=False`` when the fleet has
    no prefill replica (the fault is vacuous — nothing to kill)."""
    victim = rid
    if victim is None and hasattr(target, "_replicas"):
        victim = next((r for r, rep in target._replicas.items()
                       if getattr(rep, "role", "decode") == "prefill"),
                      None)
    if victim is None:
        return {"rid": None, "enabled": False}
    replica_kill(target, rid=victim, exc=exc)
    return {"rid": victim, "enabled": True}


def stale_directory(target, seed: int = 0) -> dict:
    """Poison the fleet cache directory: arm one holder replica so its
    NEXT chain export flips a byte AFTER stamping the per-leaf checksums
    (``ServingEngine._corrupt_next_export``) — the moral equivalent of a
    directory entry pointing at a replica whose cached bytes are no
    longer what the chain key promises (torn update, host corruption in
    flight). The next cross-replica pull through that holder must fail
    checksum verification at the graft end and degrade to recompute
    (``pull_fallbacks``/partial graft) — wrong KV is never served, and
    outputs stay bit-identical. The holder is picked deterministically
    by ``seed`` from the directory's current entries. Returns ``{"rid",
    "enabled", "key"}``; ``enabled=False`` when the directory is off or
    empty (the fault is vacuous)."""
    directory = getattr(target, "_directory", None)
    if directory is None:
        return {"rid": None, "enabled": False, "key": None}
    items = directory.items()
    if not items:
        return {"rid": None, "enabled": False, "key": None}
    key, holders = items[int(seed) % len(items)]
    rid = holders[int(seed) % len(holders)]
    rep = target._replicas.get(rid)
    if rep is None:
        return {"rid": rid, "enabled": False, "key": key}
    rep.sup.engine._corrupt_next_export = True
    return {"rid": rid, "enabled": True, "key": key}


# ---------------------------------------------------------------------------
# durable-serving injectors (ISSUE 18)
# ---------------------------------------------------------------------------

def _journal_of(target):
    """Resolve the shared RequestJournal from a router / supervisor /
    engine / bare journal (or None)."""
    for attr in ("_journal", "journal"):
        j = getattr(target, attr, None)
        if j is not None:
            return j
    return target if hasattr(target, "records") \
        and hasattr(target, "abandon") else None


def _journal_dir_of(target):
    """Resolve a journal directory from a path string or anything
    :func:`_journal_of` understands."""
    if isinstance(target, (str, os.PathLike)):
        return os.fspath(target)
    j = _journal_of(target)
    return None if j is None else j.dir


def process_kill(target) -> dict:
    """Whole-process death without grace (kill -9 between engine steps:
    preemption with the grace window gone, OOM kill, host loss): every
    userspace buffer dies, no drain, no final snapshot — the only state
    that survives is what the journal's per-step fsync already made
    durable. In-process spelling: the shared journal is ABANDONED
    (buffered WAL tail discarded, handle dropped) and the live fleet
    must be thrown away untouched. The recovery proof is a NEW fleet
    built via ``EngineSupervisor.recover(journal_dir)`` /
    ``ServingRouter.cold_start(journal_dir)`` finishing every
    non-terminal request bit-identically to an unkilled oracle, with
    zero lost requests and no delivered token re-emitted
    (tests/test_journal.py; the real SIGKILL-a-subprocess spelling is
    the ``durable``-marked test). Returns ``{"enabled", "journal_dir",
    "wal_bytes", "live"}`` — ``enabled=False`` without a journal (a
    kill -9 then loses everything by design: the fault is vacuous for
    durability)."""
    j = _journal_of(target)
    if j is None:
        return {"enabled": False, "journal_dir": None,
                "wal_bytes": 0, "live": 0}
    live = len(j.live())
    size = j.abandon()
    return {"enabled": True, "journal_dir": j.dir,
            "wal_bytes": size, "live": live}


def torn_journal_tail(target, frac: float = 0.5) -> dict:
    """A crash mid-append: the WAL's last record is cut short (power
    loss between ``write`` and ``fsync``, a full disk). Truncates the
    final frame to ``frac`` of its payload so the length/crc framing
    CANNOT validate it. Recovery must truncate the torn tail in place
    and come up at the last durable record — degrade to the last good
    state, never parse garbage, never emit a wrong token. ``target`` is
    a journal directory path or anything holding a journal (apply AFTER
    :func:`process_kill` / ``abandon`` — the file must not have a live
    writer). Returns ``{"enabled", "wal", "before", "after"}`` —
    ``enabled=False`` with no WAL or an empty one (the fault is
    vacuous)."""
    d = _journal_dir_of(target)
    from paddle_tpu.inference.serving import journal as _jm
    wal = None if d is None else os.path.join(d, _jm.WAL_NAME)
    if wal is None or not os.path.exists(wal):
        return {"enabled": False, "wal": wal, "before": 0, "after": 0}
    with open(wal, "rb") as fh:
        raw = fh.read()
    # walk the framing to the last complete frame's start
    pos, last = 0, None
    while pos + _jm._FRAME.size <= len(raw):
        length, _ = _jm._FRAME.unpack_from(raw, pos)
        end = pos + _jm._FRAME.size + length
        if end > len(raw):
            break
        last = (pos, length)
        pos = end
    if last is None:
        return {"enabled": False, "wal": wal,
                "before": len(raw), "after": len(raw)}
    start, length = last
    keep = start + _jm._FRAME.size + max(0, min(length - 1,
                                                int(length * frac)))
    with open(wal, "r+b") as fh:
        fh.truncate(keep)
        fh.flush()
        os.fsync(fh.fileno())
    return {"enabled": True, "wal": wal,
            "before": len(raw), "after": keep}


def corrupt_snapshot(target, seed: int = 0, nbits: int = 8) -> dict:
    """Silent corruption inside the NEWEST serving-state snapshot (bit
    rot, torn block-device write under the atomic-rename window's
    fsync): flips ``nbits`` bits without touching its crc frame.
    Recovery must reject the generation at load (``snapshot_fallbacks``
    increments) and fall back to the previous snapshot or a full WAL
    replay — the last good state, never wrong output. ``target`` as in
    :func:`torn_journal_tail`. Returns ``{"enabled", "path"}`` —
    ``enabled=False`` with no snapshot on disk (recovery then replays
    the WAL from byte 0 anyway: the fault is vacuous)."""
    d = _journal_dir_of(target)
    if d is None:
        return {"enabled": False, "path": None}
    try:
        names = sorted((n for n in os.listdir(d)
                        if n.startswith("snapshot-")
                        and n.endswith(".snap")), reverse=True)
    except OSError:
        names = []
    if not names:
        return {"enabled": False, "path": None}
    path = os.path.join(d, names[0])
    flip_bits(path, nbits=nbits, seed=seed)
    return {"enabled": True, "path": path}


# ---------------------------------------------------------------------------
# multi-adapter LoRA injectors (ISSUE 19)
# ---------------------------------------------------------------------------

def adapter_churn(target, rid=None, rounds: int = 4, seed: int = 0) -> dict:
    """Hostile adapter locality: ``rounds`` seeded acquire/release cycles
    aimed at COLD (registered-but-evicted) adapters, forcing the device
    pool's LRU to evict warm ones and fault the cold ones back in while
    traffic is live — the worst-case adapter mix a multi-tenant LoRA
    fleet sees. Pinned (running) adapters must survive in place
    (``_free_slot`` never evicts a pinned slot), reloads must be
    bit-exact, and the ``adapter_pool_partition`` invariant must hold
    throughout. When every registered adapter is already resident the
    cycles only reshuffle LRU order — the fault is then mild, not
    vacuous: eviction order for the NEXT overflow still changes.
    ``target`` is a router (``rid`` picks the replica), replica, or bare
    supervisor/engine. Returns ``{"rid", "enabled", "touched", "loads",
    "evictions"}`` (deltas) — ``enabled=False`` with multi-adapter
    serving off or nothing registered (the fault is vacuous)."""
    sup, rid = _fleet_sup(target, rid)
    eng = getattr(sup, "engine", sup)
    pool = getattr(eng, "_lora", None)
    if pool is None or not pool.registered():
        return {"rid": rid, "enabled": False, "touched": [],
                "loads": 0, "evictions": 0}
    rng = random.Random(int(seed))
    loads0, evictions0 = pool.loads, pool.evictions
    touched = []
    for _ in range(max(1, int(rounds))):
        cold = sorted(pool.evicted())
        name = rng.choice(cold) if cold \
            else rng.choice(sorted(pool.registered()))
        slot = pool.acquire(name)
        if slot is not None:       # every slot pinned -> skip, like admit
            pool.release(name)
            touched.append(name)
    return {"rid": rid, "enabled": True, "touched": touched,
            "loads": pool.loads - loads0,
            "evictions": pool.evictions - evictions0}


# ---------------------------------------------------------------------------
# chaos timeline (fleet-scale replay; ISSUE 13)
# ---------------------------------------------------------------------------

class ChaosEvent:
    """One scheduled injector firing: ``step`` (the replay driver's
    engine-step index — NOT wall-clock, so two replays of one seed fire
    in the identical order), the injector ``name`` (a serving entry of
    :data:`INJECTORS`, or ``"disconnect_mid_stream"`` which the replay
    driver applies at the client layer), and its ``kwargs``."""

    __slots__ = ("step", "name", "kwargs")

    def __init__(self, step: int, name: str, **kwargs):
        self.step = int(step)
        self.name = str(name)
        self.kwargs = kwargs

    def __repr__(self):
        kw = "".join(f", {k}={v!r}" for k, v in sorted(self.kwargs.items()))
        return f"ChaosEvent({self.step}, {self.name!r}{kw})"


class ChaosTimeline:
    """A seeded, step-indexed schedule of serving-injector firings — the
    chaos half of a replay manifest (docs/FAULT_TOLERANCE.md "Chaos
    timelines"). Events are plain ``(step, injector, kwargs)`` triples
    sorted by step; :meth:`due` pops the ones whose step has arrived and
    the DRIVER (:func:`inference.serving.workload.run_replay`) interprets
    them against the live fleet, logging each firing into the replay's
    deterministic chaos log. Because steps (not timestamps) key the
    schedule, two replays of one manifest fire every event at the
    identical point in the request stream."""

    def __init__(self, events):
        self.events = sorted(events, key=lambda e: (e.step, e.name))
        self._cursor = 0
        self.fired: list = []     # (step, name, detail) — the chaos log

    def due(self, step: int) -> list:
        """Events scheduled at or before ``step`` that have not fired."""
        out = []
        while self._cursor < len(self.events) and \
                self.events[self._cursor].step <= step:
            out.append(self.events[self._cursor])
            self._cursor += 1
        return out

    @property
    def remaining(self) -> int:
        return len(self.events) - self._cursor

    def log(self, step: int, name: str, detail) -> None:
        self.fired.append((int(step), str(name), detail))

    def spec(self) -> list:
        """JSON-serializable schedule (the manifest's ``chaos`` field):
        ``[[step, name, kwargs], ...]``."""
        return [[e.step, e.name, dict(e.kwargs)] for e in self.events]

    @classmethod
    def from_spec(cls, spec) -> "ChaosTimeline":
        return cls([ChaosEvent(s, n, **kw) for s, n, kw in spec])


# serving injectors a timeline may schedule (the replay driver knows how
# to aim each one at a live router/fleet; disconnect_mid_stream is applied
# at the client layer — cancel a live stream mid-flight)
TIMELINE_INJECTORS = ("replica_kill", "slow_replica", "flood_tenant",
                      "poison_prompt", "disconnect_mid_stream",
                      "flaky_probe")

# the KV-tier faults (ISSUE 16) — NOT in the default timeline mix, which
# would silently reshuffle every previously generated seed's schedule;
# tier-exercising replays pass ``kinds=TIMELINE_INJECTORS +
# TIER_INJECTORS`` (or any mix) explicitly
TIER_INJECTORS = ("host_pressure", "corrupt_offload_block")

# the disaggregated-prefill / fleet-cache faults (ISSUE 17) — same
# out-of-default-mix rule as TIER_INJECTORS, for the same reason:
# previously generated seeds must keep their schedules byte-identical
DISAGG_INJECTORS = ("kill_prefill_replica", "stale_directory")

# the durable-serving faults (ISSUE 18) — out of the default mix too;
# process_kill additionally ends the replay's fleet object outright, so
# a timeline scheduling it must drive recovery itself (the journal
# kill-point fuzz in tests/test_journal.py is exactly that driver)
DURABLE_INJECTORS = ("process_kill", "torn_journal_tail",
                     "corrupt_snapshot")

# the multi-adapter LoRA fault (ISSUE 19) — out of the default mix for
# the same seed-stability reason; adapter-exercising replays pass
# ``kinds=TIMELINE_INJECTORS + LORA_INJECTORS`` explicitly
LORA_INJECTORS = ("adapter_churn",)


def chaos_timeline(seed: int, horizon_steps: int,
                   kinds=TIMELINE_INJECTORS, events: int = 6,
                   start_frac: float = 0.1,
                   end_frac: float = 0.75) -> ChaosTimeline:
    """Build a seeded chaos schedule for a replay: ``events`` firings
    drawn round-robin over ``kinds`` (every kind fires at least once when
    ``events >= len(kinds)``), at seeded steps inside ``[start_frac,
    end_frac)`` of the horizon — early enough that recovery happens under
    traffic, late enough that the fleet has work in flight. Pure function
    of its arguments: the schedule IS replayable."""
    rng = random.Random(int(seed))
    lo = max(1, int(horizon_steps * start_frac))
    hi = max(lo + 1, int(horizon_steps * end_frac))
    out = []
    for i in range(int(events)):
        name = kinds[i % len(kinds)]
        step = rng.randrange(lo, hi)
        kw = {}
        if name == "slow_replica":
            kw = {"stall_steps": rng.randrange(2, 5), "delay_s": 0.001}
        elif name == "flood_tenant":
            kw = {"n": rng.randrange(8, 17), "seed": rng.randrange(1000)}
        elif name == "poison_prompt":
            kw = {"mode": rng.choice(["oov", "neg"]),
                  "seed": rng.randrange(1000)}
        elif name == "flaky_probe":
            kw = {"fails": rng.randrange(2, 5)}
        elif name == "host_pressure":
            kw = {"blocks": rng.randrange(0, 4)}
        elif name == "corrupt_offload_block":
            kw = {"seed": rng.randrange(1000)}
        elif name == "stale_directory":
            kw = {"seed": rng.randrange(1000)}
        elif name == "adapter_churn":
            kw = {"rounds": rng.randrange(2, 6),
                  "seed": rng.randrange(1000)}
        out.append(ChaosEvent(step, name, **kw))
    return ChaosTimeline(out)


# name -> injector; docs/FAULT_TOLERANCE.md's generated injector count
# (tools/refresh_docs.py) reads this registry
INJECTORS = {
    "truncate_file": truncate_file,
    "flip_bits": flip_bits,
    "fail_nth": fail_nth,
    "async_writer_fault": async_writer_fault,
    "stall_heartbeat": stall_heartbeat,
    "kill_self": kill_self,
    "nan_payload": nan_payload,
    "bad_sample": bad_sample,
    "dead_worker": dead_worker,
    "stalled_consumer": stalled_consumer,
    "poison_prompt": poison_prompt,
    "flood_tenant": flood_tenant,
    "engine_crash": engine_crash,
    "disconnect_mid_stream": disconnect_mid_stream,
    "slow_client": slow_client,
    "replica_kill": replica_kill,
    "slow_replica": slow_replica,
    "flaky_probe": flaky_probe,
    "host_pressure": host_pressure,
    "corrupt_offload_block": corrupt_offload_block,
    "kill_prefill_replica": kill_prefill_replica,
    "stale_directory": stale_directory,
    "process_kill": process_kill,
    "torn_journal_tail": torn_journal_tail,
    "corrupt_snapshot": corrupt_snapshot,
    "adapter_churn": adapter_churn,
}
