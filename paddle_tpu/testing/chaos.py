"""Chaos / fault-injection harness (docs/FAULT_TOLERANCE.md).

Composable injectors that drive the checkpoint + launch + elastic stack
through the failure modes a production TPU job actually sees, so the
robustness machinery is EXERCISED, not just written:

=====================  ====================================================
injector               fault it models
=====================  ====================================================
``truncate_file``      a shard cut short by a crash / full disk
``flip_bits``          silent data corruption (bad DMA, bit rot)
``fail_nth``           the Nth ``os.rename``/``os.replace``/``write`` in a
                       region raising (quota, I/O error) — syscall shim
``async_writer_fault`` an exception inside the background checkpoint
                       writer thread
``stall_heartbeat``    an alive-but-frozen worker (stops stamping past the
                       launcher's TTL without exiting)
``kill_self``          a rank dying mid-step (preemption without grace,
                       OOM kill)
=====================  ====================================================

File injectors are plain functions; process/region injectors are context
managers and compose by nesting. The chaos test suite
(``tests/test_chaos.py``) asserts that under every one of these the job
resumes from a committed checkpoint and converges to the unfaulted loss.
"""

from __future__ import annotations

import contextlib
import os
import random
import signal
from typing import Optional

__all__ = ["truncate_file", "flip_bits", "fail_nth", "async_writer_fault",
           "stall_heartbeat", "kill_self", "INJECTORS"]


def truncate_file(path: str, frac: float = 0.5,
                  keep_bytes: Optional[int] = None) -> int:
    """Cut ``path`` short (a crash mid-write / disk-full artifact).
    Keeps ``keep_bytes`` bytes when given, else ``frac`` of the file.
    Returns the new size."""
    size = os.path.getsize(path)
    keep = keep_bytes if keep_bytes is not None else int(size * frac)
    keep = max(0, min(size, keep))
    with open(path, "rb+") as f:
        f.truncate(keep)
    return keep


def flip_bits(path: str, offset: Optional[int] = None, nbits: int = 8,
              seed: int = 0) -> int:
    """XOR-corrupt ``nbits`` bits at ``offset`` (random position when None)
    — silent corruption a checksum must catch. Returns the offset hit."""
    size = os.path.getsize(path)
    if size == 0:
        raise ValueError(f"cannot bit-flip empty file {path!r}")
    rng = random.Random(seed)
    if offset is None:
        offset = rng.randrange(size)
    offset = min(offset, size - 1)
    with open(path, "rb+") as f:
        f.seek(offset)
        b = f.read(1)[0]
        mask = 0
        for _ in range(max(1, nbits)):
            mask |= 1 << rng.randrange(8)
        f.seek(offset)
        f.write(bytes([b ^ (mask or 0x01)]))
    return offset


@contextlib.contextmanager
def fail_nth(module, name: str, n: int = 1, exc: Optional[BaseException] = None):
    """Monkeypatched syscall shim: the Nth call (1-based) of
    ``module.name`` inside the region raises (default ``OSError``). Models
    quota/EIO failures at exact protocol positions, e.g.::

        with chaos.fail_nth(os, "replace", n=2):
            save_state_dict(state, path)   # 2nd atomic rename dies
    """
    real = getattr(module, name)
    err = exc if exc is not None else OSError(
        f"chaos: injected failure on call #{n} of {module.__name__}.{name}")
    state = {"calls": 0}

    def shim(*args, **kwargs):
        state["calls"] += 1
        if state["calls"] == n:
            raise err
        return real(*args, **kwargs)

    setattr(module, name, shim)
    try:
        yield state
    finally:
        setattr(module, name, real)


@contextlib.contextmanager
def async_writer_fault(exc: Optional[BaseException] = None):
    """Every job the background checkpoint writer picks up inside the
    region fails with ``exc`` (stored on the job, surfaced by
    ``wait()``/the next save — the error-propagation contract under
    test)."""
    from ..framework import async_writer
    err = exc if exc is not None else RuntimeError(
        "chaos: injected async-writer fault")
    async_writer.set_fault(err)
    try:
        yield err
    finally:
        async_writer.set_fault(None)


class stall_heartbeat:
    """Freeze the worker's liveness stamping (the thread keeps running but
    stops SETting) — to the launcher's monitor this process is
    alive-but-hung, and past ``--elastic_timeout`` it gets killed and the
    round restarts. Models a native deadlock / swap storm.

    A plain class (NOT a generator contextmanager) on purpose: a stall is
    often armed fire-and-forget right before the process freezes, and a
    GC'd generator CM would run its ``finally`` and silently un-pause."""

    def __enter__(self):
        from ..distributed import elastic
        self._ev = elastic._pause_event()
        if self._ev is not None:
            self._ev.set()
        return self

    def __exit__(self, *exc):
        if self._ev is not None:
            self._ev.clear()
        return False


def kill_self(sig: int = signal.SIGKILL) -> None:
    """Die mid-step with no cleanup (default SIGKILL: no atexit, no flush
    — exactly what a preemption without grace or an OOM kill looks like)."""
    os.kill(os.getpid(), sig)


# name -> injector; docs/FAULT_TOLERANCE.md's generated injector count
# (tools/refresh_docs.py) reads this registry
INJECTORS = {
    "truncate_file": truncate_file,
    "flip_bits": flip_bits,
    "fail_nth": fail_nth,
    "async_writer_fault": async_writer_fault,
    "stall_heartbeat": stall_heartbeat,
    "kill_self": kill_self,
}
