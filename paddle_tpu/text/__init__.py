"""``paddle.text`` parity.

Reference surface: ``python/paddle/text/`` — dataset downloaders (Imdb,
Conll05, ...) plus ``viterbi_decode``/``ViterbiDecoder``. This environment is
hermetic (zero egress), so the dataset downloaders raise with a clear
message; the decoding ops are real implementations.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..ops._helpers import ensure_tensor, forward_op

__all__ = ["viterbi_decode", "ViterbiDecoder", "Imdb", "Conll05st", "Movielens",
           "UCIHousing", "WMT14", "WMT16"]


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag: bool = True, name=None):
    """CRF Viterbi decoding (ref: paddle.text.viterbi_decode).

    potentials [B, T, N]; transition_params [N, N] (or [N+2, N+2] with
    BOS/EOS rows when include_bos_eos_tag); lengths [B].
    Returns (scores [B], paths [B, T]).
    """
    pot = ensure_tensor(potentials)
    trans = ensure_tensor(transition_params)
    lens = ensure_tensor(lengths)

    def decode(p, tr, ln):
        B, T, N = p.shape
        if include_bos_eos_tag:
            # rows/cols N..N+1 of tr are BOS/EOS (reference convention)
            start = tr[N, :N] if tr.shape[0] > N else jnp.zeros((N,))
            stop = tr[:N, N + 1] if tr.shape[0] > N + 1 else jnp.zeros((N,))
            tr_core = tr[:N, :N]
        else:
            start = jnp.zeros((N,), p.dtype)
            stop = jnp.zeros((N,), p.dtype)
            tr_core = tr

        alpha0 = p[:, 0] + start[None]

        def step(carry, t):
            alpha, _ = carry
            # [B, from, to]
            scores = alpha[:, :, None] + tr_core[None]
            best_prev = jnp.argmax(scores, axis=1)               # [B, N]
            alpha_t = jnp.max(scores, axis=1) + p[:, t]
            # frozen past length: keep alpha
            active = (t < ln)[:, None]
            alpha_new = jnp.where(active, alpha_t, alpha)
            return (alpha_new, None), jnp.where(active, best_prev, -1)

        (alpha, _), backptrs = jax.lax.scan(
            step, (alpha0, None), jnp.arange(1, T))
        final = alpha + stop[None]
        scores = jnp.max(final, axis=-1)
        last_tag = jnp.argmax(final, axis=-1)                      # [B]

        def backtrack(carry, bp_t):
            tag = carry
            prev = jnp.take_along_axis(bp_t, tag[:, None], axis=1)[:, 0]
            tag_new = jnp.where(prev >= 0, prev, tag)
            return tag_new, tag

        _, path_rev = jax.lax.scan(backtrack, last_tag, backptrs[::-1])
        paths = jnp.concatenate(
            [path_rev[::-1].T, last_tag[:, None]], axis=1)         # [B, T]
        return scores, paths.astype(jnp.int32)

    return forward_op("viterbi_decode", decode, [pot, trans, lens],
                      differentiable=False)


class ViterbiDecoder:
    """Layer-style wrapper (ref: paddle.text.ViterbiDecoder)."""

    def __init__(self, transitions, include_bos_eos_tag: bool = True, name=None):
        self.transitions = ensure_tensor(transitions)
        self.include_bos_eos_tag = include_bos_eos_tag

    def __call__(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)


def _no_download(name):
    class _D:
        def __init__(self, *a, **k):
            raise RuntimeError(
                f"paddle.text.{name}: dataset download needs network access; "
                f"this environment is hermetic — construct a paddle.io.Dataset "
                f"over local files instead")
    _D.__name__ = name
    return _D


Imdb = _no_download("Imdb")
Conll05st = _no_download("Conll05st")
Movielens = _no_download("Movielens")
UCIHousing = _no_download("UCIHousing")
WMT14 = _no_download("WMT14")
WMT16 = _no_download("WMT16")


def _register_text_ops():
    from ..core.dispatch import OP_REGISTRY, register_op
    if "viterbi_decode" not in OP_REGISTRY:
        register_op("viterbi_decode", viterbi_decode,
                    (viterbi_decode.__doc__ or "").strip().split("\n")[0],
                    differentiable=False, category="text",
                    public=viterbi_decode)


_register_text_ops()
