"""Maintenance tools (docs regeneration, artifact checks)."""
