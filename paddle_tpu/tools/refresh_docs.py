"""Regenerate every narrated number from its artifact (r4 VERDICT weak #2 /
next #9: the builder's README/BASELINE counts drifted from the registry and
the driver's bench output — so the counts are now GENERATED, and
tests/test_docs_fresh.py fails CI-style when they drift).

    python -m paddle_tpu.tools.refresh_docs          # rewrite docs
    python -m paddle_tpu.tools.refresh_docs --check  # exit 1 on drift
"""

from __future__ import annotations

import glob
import json
import os
import re
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def measured_counts() -> dict:
    """Ground truth from the live registry/namespaces."""
    import paddle_tpu  # noqa: F401
    from paddle_tpu.ops.gen_docs import generate  # imports every domain
    # reuse gen_docs' import set without writing the file
    import paddle_tpu.ops, paddle_tpu.nn.functional  # noqa: E401,F401
    import paddle_tpu.sparse, paddle_tpu.signal  # noqa: E401,F401
    import paddle_tpu.geometric, paddle_tpu.vision.ops  # noqa: E401,F401
    import paddle_tpu.fft, paddle_tpu.audio  # noqa: E401,F401
    import paddle_tpu.incubate.nn.functional  # noqa: F401
    import paddle_tpu.distributed.moe_utils  # noqa: F401
    import paddle_tpu.distributed.ps  # noqa: F401
    import paddle_tpu.vision.transforms  # noqa: F401
    import paddle_tpu.text, paddle_tpu.metric  # noqa: E401,F401
    import paddle_tpu.optimizer  # noqa: F401
    from paddle_tpu.core.dispatch import OP_REGISTRY
    from paddle_tpu.ops.sweep_specs import attach_specs, sweep_coverage
    attach_specs()
    covered, total = sweep_coverage()

    import paddle_tpu.nn as nn
    from paddle_tpu.nn.layer import Layer
    layers = sorted(n for n in dir(nn)
                    if isinstance(getattr(nn, n, None), type)
                    and issubclass(getattr(nn, n), Layer)
                    and n != "Layer")
    import paddle_tpu.nn.functional as F
    fnames = [n for n in dir(F) if not n.startswith("_")
              and callable(getattr(F, n))]
    import paddle_tpu.optimizer as opt
    from paddle_tpu.optimizer.optimizer import Optimizer
    optimizers = [n for n in dir(opt)
                  if isinstance(getattr(opt, n, None), type)
                  and issubclass(getattr(opt, n), Optimizer)
                  and n != "Optimizer"]
    from paddle_tpu.optimizer import lr as lrmod
    base = getattr(lrmod, "LRScheduler")
    lrs = [n for n in dir(lrmod)
           if isinstance(getattr(lrmod, n, None), type)
           and issubclass(getattr(lrmod, n), base)
           and n != "LRScheduler"]
    from paddle_tpu.testing.chaos import INJECTORS
    from paddle_tpu.flags import get_flags
    health_flags = sorted(n for n in get_flags()
                          if n.startswith("FLAGS_health_"))
    serving_flags = sorted(n for n in get_flags()
                           if n.startswith("FLAGS_serving_"))
    return {
        "ops": total,
        "swept": covered,
        "swept_pct": 100 * covered // total,
        "layers": len(layers),
        "functional": len(fnames),
        "optimizers": len(optimizers),
        "lr_schedulers": len(lrs),
        "chaos_injectors": len(INJECTORS),
        "health_flags": len(health_flags),
        "serving_flags": len(serving_flags),
        "_health_flag_rows": health_flags,   # consumed by health_flags_table
        "_serving_flag_rows": serving_flags,  # ... serving_flags_table
    }


def latest_bench() -> dict:
    """Newest BENCH_r*.json -> {metric: value}."""
    def round_no(path):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        return int(m.group(1)) if m else -1

    files = sorted(glob.glob(os.path.join(ROOT, "BENCH_r*.json")),
                   key=round_no)
    if not files:
        return {}
    rows = {}
    raw = open(files[-1]).read()
    for line in raw.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            d = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(d, dict) and "metric" in d:
            rows[d["metric"]] = d
    if not rows:   # maybe one JSON array/object
        try:
            data = json.loads(raw)
            if isinstance(data, list):
                for d in data:
                    if isinstance(d, dict) and "metric" in d:
                        rows[d["metric"]] = d
            elif isinstance(data, dict) and isinstance(data.get("tail"), str):
                # driver format: one object whose "tail" holds the bench
                # stdout (JSON lines) — parse the embedded metric lines
                for line in data["tail"].splitlines():
                    line = line.strip()
                    if not line.startswith("{"):
                        continue
                    try:
                        d = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    if isinstance(d, dict) and "metric" in d:
                        rows[d["metric"]] = d
        except json.JSONDecodeError:
            pass
    return rows


# every generated span sits between these markers in the docs
# (digits allowed: bench metric keys like resnet50_throughput / h2d carry them)
_GEN = re.compile(r"<!--gen:(?P<key>[a-z0-9_]+)-->(?P<body>.*?)"
                  r"<!--/gen-->", re.S)


def render(key: str, counts: dict, bench: dict) -> str:
    if key in ("health_flags_table", "serving_flags_table"):
        # generated flags table straight from the live registry (ONE
        # shared renderer with ops/gen_docs.py) so the docs cannot drift
        # from flags.py or from each other
        from paddle_tpu.flags import flags_table
        rows = flags_table(counts["_" + key.replace("_table", "_rows")
                                  .replace("flags", "flag")])
        return "\n" + "\n".join(rows) + "\n"
    if key in counts:
        return str(counts[key])
    if key == "sweep_line":
        return (f"{counts['swept']}/{counts['ops']} ops "
                f"({counts['swept_pct']}%) oracle-swept")
    if key.startswith("bench_"):
        m = bench.get(key[len("bench_"):])
        return "unmeasured" if m is None else f"{m['value']} {m['unit']}"
    raise KeyError(key)


def refresh(check: bool = False) -> int:
    counts = measured_counts()
    bench = latest_bench()
    drift = []
    for rel in ("README.md", "docs/FAULT_TOLERANCE.md",
                "docs/PERFORMANCE.md", "docs/SERVING.md"):
        path = os.path.join(ROOT, rel)
        src = open(path).read()

        def sub(m):
            want = render(m.group("key"), counts, bench)
            have = m.group("body")
            if have != want:
                drift.append(f"{rel}: {m.group('key')}: "
                             f"{have!r} -> {want!r}")
            return f"<!--gen:{m.group('key')}-->{want}<!--/gen-->"

        out = _GEN.sub(sub, src)
        if not check and out != src:
            open(path, "w").write(out)
    if check and drift:
        print("DRIFT:\n  " + "\n  ".join(drift))
        return 1
    if drift and not check:
        print("refreshed:\n  " + "\n  ".join(drift))
    else:
        print("docs match artifacts")
    return 0


def main():
    check = "--check" in sys.argv
    sys.exit(refresh(check=check))


if __name__ == "__main__":
    main()
