"""``paddle.utils`` parity: unique_name, deprecated, try_import, dlpack."""

from . import dlpack, unique_name  # noqa: F401
from .deprecated import deprecated


def try_import(module_name: str, err_msg: str = None):
    """ref: paddle.utils.try_import — import or raise a friendly error."""
    import importlib
    try:
        return importlib.import_module(module_name)
    except ImportError:
        raise ImportError(
            err_msg or f"{module_name} is required but not installed; this "
            f"environment is hermetic (no pip) — gate the feature instead")


def run_check():
    """ref: paddle.utils.run_check — sanity-check the device stack."""
    import jax
    import numpy as np
    from ..core.tensor import to_tensor
    x = to_tensor(np.ones((2, 2), np.float32))
    y = (x @ x).numpy()
    assert np.allclose(y, 2.0), y
    print(f"paddle_tpu is installed successfully! backend="
          f"{jax.default_backend()}, devices={jax.device_count()}")


__all__ = ["unique_name", "deprecated", "dlpack", "try_import", "run_check"]
