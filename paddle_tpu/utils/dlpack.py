"""DLPack interop (ref: ``python/paddle/utils/dlpack.py``) — zero-copy
exchange with torch/numpy via jax's dlpack support."""

from __future__ import annotations

from ..core.tensor import Tensor, _wrap_value

__all__ = ["to_dlpack", "from_dlpack"]


def to_dlpack(x: Tensor):
    import jax.dlpack
    return jax.dlpack.to_dlpack(x._value)


def from_dlpack(capsule) -> Tensor:
    import jax.dlpack
    # jax accepts either a raw capsule or any __dlpack__-capable object
    return _wrap_value(jax.dlpack.from_dlpack(capsule))
