"""Unique name generator (ref: ``python/paddle/utils/unique_name.py`` →
``base/unique_name.py``): per-prefix counters, ``guard`` for scoped resets."""

from __future__ import annotations

import contextlib
from collections import defaultdict

__all__ = ["generate", "guard", "switch"]

_counters = defaultdict(int)


def generate(key: str) -> str:
    n = _counters[key]
    _counters[key] += 1
    return f"{key}_{n}"


def switch(new_generator=None):
    global _counters
    old = _counters
    _counters = new_generator if new_generator is not None else defaultdict(int)
    return old


@contextlib.contextmanager
def guard(new_generator=None):
    old = switch(new_generator)
    try:
        yield
    finally:
        switch(old)
