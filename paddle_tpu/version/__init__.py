"""Version info (ref: generated ``python/paddle/version.py``)."""

full_version = "0.1.0"
major = "0"
minor = "1"
patch = "0"
rc = "0"
commit = "unknown"
istaged = False
with_pip = False

cuda_version = "False"
cudnn_version = "False"
xpu_version = "False"
tpu = True


def show():
    print(f"paddle_tpu {full_version} (tpu-native; jax/XLA/PJRT backend)")


def cuda():
    return False


def cudnn():
    return False


def xpu():
    return False
