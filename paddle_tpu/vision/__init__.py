"""``paddle.vision`` parity: transforms, models, datasets.

Reference surface: ``python/paddle/vision/`` (transforms on HWC images,
model zoo incl. ResNet family, dataset downloaders). Downloaders raise (zero
egress); transforms are pure-numpy so they run inside DataLoader worker
subprocesses (which must never touch the PJRT client); models build on the
framework's nn layers.
"""

from . import datasets, models, ops, transforms  # noqa: F401
from .models import (LeNet, ResNet, resnet18, resnet34, resnet50, resnet101,
                     resnet152, vgg11, vgg13, vgg16, vgg19, VGG)  # noqa: F401

__all__ = ["transforms", "models", "datasets", "ResNet", "LeNet", "VGG",
           "resnet18", "resnet34", "resnet50", "resnet101", "resnet152",
           "vgg11", "vgg13", "vgg16", "vgg19", "set_image_backend",
           "get_image_backend", "image_load"]


def set_image_backend(backend: str):
    if backend not in ("cv2", "pil", "numpy", "tensor"):
        raise ValueError(f"unsupported image backend {backend!r}")
    transforms._IMAGE_BACKEND = backend


def get_image_backend() -> str:
    return transforms._IMAGE_BACKEND


def image_load(path: str, backend=None):
    raise NotImplementedError(
        "vision.image_load: no image decoder (PIL/cv2) in this hermetic "
        "environment — load arrays with numpy and feed HWC ndarrays to the "
        "transforms")
