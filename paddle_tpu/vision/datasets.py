"""Vision datasets (ref: ``python/paddle/vision/datasets/``).

Downloaders need network access (hermetic environment -> raise with
guidance); ``FakeData``-style synthetic dataset provided for pipelines and
benchmarks.
"""

from __future__ import annotations

import numpy as np

from ..io import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "Flowers",
           "VOC2012", "DatasetFolder", "ImageFolder", "FakeImageDataset"]


class FakeImageDataset(Dataset):
    """Synthetic image/label pairs (deterministic per index)."""

    def __init__(self, num_samples: int = 1024, image_shape=(3, 224, 224),
                 num_classes: int = 1000, transform=None, dtype="float32"):
        self.num_samples = int(num_samples)
        self.image_shape = tuple(image_shape)
        self.num_classes = int(num_classes)
        self.transform = transform
        self.dtype = dtype

    def __getitem__(self, idx):
        rng = np.random.default_rng(idx)
        img = rng.standard_normal(self.image_shape).astype(self.dtype)
        label = np.int64(rng.integers(0, self.num_classes))
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return self.num_samples


def _needs_download(name):
    class _D(Dataset):
        def __init__(self, *a, download=True, **k):
            raise RuntimeError(
                f"vision.datasets.{name}: dataset download needs network "
                f"access; this environment is hermetic — point a "
                f"DatasetFolder-style paddle.io.Dataset at local files, or "
                f"use FakeImageDataset for pipeline tests")
    _D.__name__ = name
    return _D


MNIST = _needs_download("MNIST")
FashionMNIST = _needs_download("FashionMNIST")
Cifar10 = _needs_download("Cifar10")
Cifar100 = _needs_download("Cifar100")
Flowers = _needs_download("Flowers")
VOC2012 = _needs_download("VOC2012")


class DatasetFolder(Dataset):
    """Filesystem class-per-directory dataset (numpy ``.npy`` loader by
    default — no image decoder in this environment)."""

    def __init__(self, root: str, loader=None, extensions=(".npy",),
                 transform=None, is_valid_file=None):
        import os
        self.root = root
        self.transform = transform
        self.loader = loader or (lambda p: np.load(p))
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for fname in sorted(os.listdir(cdir)):
                path = os.path.join(cdir, fname)
                ok = (is_valid_file(path) if is_valid_file
                      else fname.lower().endswith(tuple(extensions)))
                if ok:
                    self.samples.append((path, self.class_to_idx[c]))

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, np.int64(target)

    def __len__(self):
        return len(self.samples)


class ImageFolder(DatasetFolder):
    pass
