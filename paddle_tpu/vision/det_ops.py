"""Legacy detection op family (SSD / Faster-RCNN / YOLO / SOLO era).

Parity targets: ``paddle/fluid/operators/detection/*`` and
``python/paddle/vision/ops.py`` in the reference — prior/anchor generation,
box decoding, proposal generation, ROI distribution, matching/assignment,
and the NMS variants (multiclass greedy, matrix soft-suppression).

TPU redesign (not a translation): the reference's CUDA kernels lean on
dynamic result counts (LoD outputs) and per-box serial loops. Here every
in-graph op is STATIC-shape — suppression/selection produce fixed-capacity
outputs plus validity masks or counts (the formulation `detection.static_nms`
established), so the whole post-processing chain compiles into one XLA
program. Matrix NMS is the naturally-parallel variant (a dense [N,N]
min-reduction — MXU/VPU friendly, no sequential dependency at all).
Anchor/prior generation is pure arithmetic on meshgrids. Ops whose upstream
contract IS a ragged host structure (distribute_fpn_proposals' per-level
lists, bipartite_match's greedy argmax chain) run eagerly like `nms`,
documented per-op.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..core.dispatch import register_op
from ..ops._helpers import Tensor, ensure_tensor, forward_op

__all__ = [
    "deform_conv2d", "psroi_pool", "prior_box", "density_prior_box",
    "anchor_generator", "yolo_box", "yolo_loss", "matrix_nms",
    "multiclass_nms", "generate_proposals", "distribute_fpn_proposals",
    "collect_fpn_proposals", "box_clip", "bipartite_match",
    "polygon_box_transform", "iou_similarity", "target_assign",
    "mine_hard_examples", "ssd_loss", "detection_output",
]


# ---------------------------------------------------------------------------
# deformable convolution
# ---------------------------------------------------------------------------

def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups: int = 1, groups: int = 1,
                  mask=None, name=None):
    """Deformable convolution v1/v2 (ref: paddle.vision.ops.deform_conv2d,
    deformable_conv_op). TPU formulation: the learned offsets shift a
    bilinear sampling grid; sampling is ONE big gather over [B, C, H, W]
    and the conv collapses to a single [B*OH*OW, C*kh*kw] x [C*kh*kw, M]
    matmul — MXU shaped, no per-location kernels. ``mask`` (v2 modulation)
    multiplies the sampled taps.

    Shapes: x [B, Cin, H, W]; offset [B, 2*dg*kh*kw, OH, OW];
    mask [B, dg*kh*kw, OH, OW]; weight [Cout, Cin//groups, kh, kw].
    """
    xt = ensure_tensor(x)
    ot = ensure_tensor(offset)
    wt = ensure_tensor(weight)
    sh, sw = (stride, stride) if isinstance(stride, int) else tuple(stride)
    ph, pw = (padding, padding) if isinstance(padding, int) else tuple(padding)
    dh, dw = (dilation, dilation) if isinstance(dilation, int) \
        else tuple(dilation)
    args = [xt, ot, wt]
    if mask is not None:
        args.append(ensure_tensor(mask))
    if bias is not None:
        args.append(ensure_tensor(bias))

    def impl(xv, ov, wv, *rest):
        mv = rest[0] if mask is not None else None
        bv = rest[-1] if bias is not None else None
        B, C, H, W = xv.shape
        Cout, Cg, kh, kw = wv.shape
        OH, OW = ov.shape[2], ov.shape[3]
        dg = deformable_groups
        K = kh * kw

        # base sampling locations per output position and tap
        oy = jnp.arange(OH) * sh - ph
        ox = jnp.arange(OW) * sw - pw
        ky = jnp.arange(kh) * dh
        kx = jnp.arange(kw) * dw
        base_y = oy[:, None, None, None] + ky[None, None, :, None]  # [OH,1,kh,1]
        base_x = ox[None, :, None, None] + kx[None, None, None, :]  # [1,OW,1,kw]
        base_y = jnp.broadcast_to(base_y, (OH, OW, kh, kw)).astype(jnp.float32)
        base_x = jnp.broadcast_to(base_x, (OH, OW, kh, kw)).astype(jnp.float32)

        # offsets: [B, dg, K, 2, OH, OW] with (dy, dx) interleaved upstream
        off = ov.reshape(B, dg, K, 2, OH, OW)
        dy = off[:, :, :, 0].transpose(0, 3, 4, 1, 2)     # [B, OH, OW, dg, K]
        dx = off[:, :, :, 1].transpose(0, 3, 4, 1, 2)
        sy = base_y.reshape(1, OH, OW, 1, K) + dy          # [B, OH, OW, dg, K]
        sx = base_x.reshape(1, OH, OW, 1, K) + dx

        # bilinear sample x at (sy, sx) for every channel of the dg's group
        y0 = jnp.floor(sy)
        x0 = jnp.floor(sx)
        wy = sy - y0
        wx = sx - x0
        inside = (sy > -1) & (sy < H) & (sx > -1) & (sx < W)

        def tap(yi, xi):
            ok = (yi >= 0) & (yi < H) & (xi >= 0) & (xi < W)
            yc = jnp.clip(yi, 0, H - 1).astype(jnp.int32)
            xc = jnp.clip(xi, 0, W - 1).astype(jnp.int32)
            flat = xv.reshape(B, C, H * W)                 # gather once
            lin = (yc * W + xc).reshape(B, -1)             # [B, OH*OW*dg*K]
            g = jnp.take_along_axis(flat, lin[:, None, :], axis=2)
            g = g.reshape(B, C, OH, OW, dg, K)
            return jnp.where(ok.reshape(B, 1, OH, OW, dg, K), g, 0.0)

        v = (tap(y0, x0) * ((1 - wy) * (1 - wx))[:, None]
             + tap(y0, x0 + 1) * ((1 - wy) * wx)[:, None]
             + tap(y0 + 1, x0) * (wy * (1 - wx))[:, None]
             + tap(y0 + 1, x0 + 1) * (wy * wx)[:, None])
        v = jnp.where(inside[:, None], v, 0.0)             # [B,C,OH,OW,dg,K]
        if mv is not None:
            mm = mv.reshape(B, dg, K, OH, OW).transpose(0, 3, 4, 1, 2)
            v = v * mm[:, None]

        # channels are partitioned across deformable groups: pick each
        # channel's group slice
        cg = C // dg
        v = v.reshape(B, dg, cg, OH, OW, dg, K)
        v = jnp.stack([v[:, g_, :, :, :, g_] for g_ in range(dg)], 1)
        v = v.reshape(B, C, OH, OW, K)

        # grouped conv as matmul
        og = Cout // groups
        icg = C // groups
        v = v.reshape(B, groups, icg, OH, OW, K)
        wg = wv.reshape(groups, og, Cg, kh * kw)
        out = jnp.einsum("bgcHWk,gock->bgoHW", v, wg)
        out = out.reshape(B, Cout, OH, OW)
        if bv is not None:
            out = out + bv.reshape(1, -1, 1, 1)
        return out

    return forward_op("deform_conv2d", impl, args)


# ---------------------------------------------------------------------------
# position-sensitive ROI pooling
# ---------------------------------------------------------------------------

def psroi_pool(x, boxes, boxes_num=None, output_size=7,
               spatial_scale: float = 1.0, name=None):
    """Position-sensitive ROI average pooling (ref:
    paddle.vision.ops.psroi_pool / psroi_pool_op, R-FCN). Input channels
    ``C = out_c * ph * pw``; output bin (i, j) pools its OWN channel group.
    Static formulation: bin membership is a mask over the full feature map
    (no dynamic slicing), one masked mean per bin via einsum."""
    xt = ensure_tensor(x)
    bt = ensure_tensor(boxes)
    ph_, pw_ = ((output_size, output_size) if isinstance(output_size, int)
                else tuple(output_size))

    def impl(xv, bv):
        B, C, H, W = xv.shape
        n = bv.shape[0]
        oc = C // (ph_ * pw_)
        x1 = bv[:, 0] * spatial_scale
        y1 = bv[:, 1] * spatial_scale
        x2 = bv[:, 2] * spatial_scale
        y2 = bv[:, 3] * spatial_scale
        bw = jnp.maximum(x2 - x1, 0.1)
        bh = jnp.maximum(y2 - y1, 0.1)
        # bin edges per roi: [n, ph+1] / [n, pw+1]
        ys = y1[:, None] + bh[:, None] * jnp.arange(ph_ + 1) / ph_
        xs = x1[:, None] + bw[:, None] * jnp.arange(pw_ + 1) / pw_
        gy = jnp.arange(H)[None, None, :] + 0.0
        gx = jnp.arange(W)[None, None, :] + 0.0
        # in-bin masks: [n, ph, H], [n, pw, W]
        my = ((gy >= jnp.floor(ys[:, :-1, None])) &
              (gy < jnp.ceil(ys[:, 1:, None])))
        mx = ((gx >= jnp.floor(xs[:, :-1, None])) &
              (gx < jnp.ceil(xs[:, 1:, None])))
        cnt = (my.sum(-1)[:, :, None] * mx.sum(-1)[:, None, :])  # [n,ph,pw]
        # batch of each roi: single image (B==1) or boxes_num split
        feat = xv[0] if B == 1 else xv[0]
        feat = feat.reshape(oc, ph_, pw_, H, W)
        pooled = jnp.einsum("cijHW,niH,njW->ncij",
                            feat[None][0], my.astype(xv.dtype),
                            mx.astype(xv.dtype))
        return pooled / jnp.maximum(cnt[:, None], 1)

    return forward_op("psroi_pool", impl, [xt, bt])


# ---------------------------------------------------------------------------
# prior / anchor generation (pure arithmetic)
# ---------------------------------------------------------------------------

def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip: bool = False,
              clip: bool = False, steps=(0.0, 0.0), offset: float = 0.5,
              min_max_aspect_ratios_order: bool = False, name=None):
    """SSD prior boxes for one feature map (ref: prior_box_op). Returns
    ``(boxes [H, W, P, 4] normalized xyxy, variances [H, W, P, 4])`` —
    pure meshgrid arithmetic, one fused XLA program."""
    ft = ensure_tensor(input)
    it = ensure_tensor(image)
    H, W = int(ft.shape[2]), int(ft.shape[3])
    IH, IW = int(it.shape[2]), int(it.shape[3])
    sh = steps[1] or IH / H
    sw = steps[0] or IW / W

    ars = [1.0]
    for ar in aspect_ratios:
        if not any(abs(ar - a) < 1e-6 for a in ars):
            ars.append(float(ar))
            if flip:
                ars.append(1.0 / float(ar))

    whs = []
    for ms in min_sizes:
        if min_max_aspect_ratios_order:
            whs.append((ms, ms))
            if max_sizes:
                mx = max_sizes[min_sizes.index(ms)]
                whs.append((math.sqrt(ms * mx), math.sqrt(ms * mx)))
            for ar in ars:
                if abs(ar - 1.0) < 1e-6:
                    continue
                whs.append((ms * math.sqrt(ar), ms / math.sqrt(ar)))
        else:
            for ar in ars:
                whs.append((ms * math.sqrt(ar), ms / math.sqrt(ar)))
            if max_sizes:
                mx = max_sizes[min_sizes.index(ms)]
                whs.append((math.sqrt(ms * mx), math.sqrt(ms * mx)))
    P = len(whs)

    def impl():
        cy = (jnp.arange(H) + offset) * sh
        cx = (jnp.arange(W) + offset) * sw
        wh = jnp.asarray(whs, jnp.float32)                 # [P, 2]
        planes = [
            (cx[None, :, None] - wh[None, None, :, 0] / 2) / IW,
            (cy[:, None, None] - wh[None, None, :, 1] / 2) / IH,
            (cx[None, :, None] + wh[None, None, :, 0] / 2) / IW,
            (cy[:, None, None] + wh[None, None, :, 1] / 2) / IH,
        ]
        bx = jnp.stack([jnp.broadcast_to(pl, (H, W, P)) for pl in planes],
                       -1)
        if clip:
            bx = jnp.clip(bx, 0.0, 1.0)
        var = jnp.broadcast_to(jnp.asarray(variance, jnp.float32),
                               (H, W, P, 4))
        return bx, var

    return forward_op("prior_box", impl, [], differentiable=False)


def density_prior_box(input, image, densities, fixed_sizes, fixed_ratios,
                      variance=(0.1, 0.1, 0.2, 0.2), clip: bool = False,
                      steps=(0.0, 0.0), offset: float = 0.5, name=None):
    """Density prior boxes (ref: density_prior_box_op): each fixed size is
    laid out on a density x density sub-grid inside the step cell."""
    ft = ensure_tensor(input)
    it = ensure_tensor(image)
    H, W = int(ft.shape[2]), int(ft.shape[3])
    IH, IW = int(it.shape[2]), int(it.shape[3])
    sh = steps[1] or IH / H
    sw = steps[0] or IW / W

    # enumerate (shift_x, shift_y, w, h) per prior
    priors = []
    for size, dens in zip(fixed_sizes, densities):
        for ratio in fixed_ratios:
            bw = size * math.sqrt(ratio)
            bh = size / math.sqrt(ratio)
            step = 1.0 / dens
            for di in range(dens):
                for dj in range(dens):
                    cxs = (dj + 0.5) * step - 0.5
                    cys = (di + 0.5) * step - 0.5
                    priors.append((cxs, cys, bw, bh))
    P = len(priors)
    pr = np.asarray(priors, np.float32)

    def impl():
        cy = (jnp.arange(H) + offset) * sh
        cx = (jnp.arange(W) + offset) * sw
        pcx = cx[None, :, None] + pr[None, None, :, 0] * sw
        pcy = cy[:, None, None] + pr[None, None, :, 1] * sh
        bw = pr[None, None, :, 2]
        bh = pr[None, None, :, 3]
        planes = [(pcx - bw / 2) / IW, (pcy - bh / 2) / IH,
                  (pcx + bw / 2) / IW, (pcy + bh / 2) / IH]
        bx = jnp.stack([jnp.broadcast_to(pl, (H, W, P)) for pl in planes],
                       -1)
        if clip:
            bx = jnp.clip(bx, 0.0, 1.0)
        var = jnp.broadcast_to(jnp.asarray(variance, jnp.float32),
                               (H, W, P, 4))
        return bx, var

    return forward_op("density_prior_box", impl, [], differentiable=False)


def anchor_generator(input, anchor_sizes=(64.0,), aspect_ratios=(1.0,),
                     variance=(0.1, 0.1, 0.2, 0.2), stride=(16.0, 16.0),
                     offset: float = 0.5, name=None):
    """Faster-RCNN anchors for one level (ref: anchor_generator_op).
    Returns ``(anchors [H, W, A, 4] xyxy in input pixels, variances)``."""
    ft = ensure_tensor(input)
    H, W = int(ft.shape[2]), int(ft.shape[3])
    sw, sh = float(stride[0]), float(stride[1])

    whs = []
    for s in anchor_sizes:
        for ar in aspect_ratios:
            whs.append((s * math.sqrt(ar), s / math.sqrt(ar)))
    A = len(whs)

    def impl():
        cy = (jnp.arange(H) + offset) * sh
        cx = (jnp.arange(W) + offset) * sw
        wh = jnp.asarray(whs, jnp.float32)
        planes = [
            cx[None, :, None] - wh[None, None, :, 0] / 2,
            cy[:, None, None] - wh[None, None, :, 1] / 2,
            cx[None, :, None] + wh[None, None, :, 0] / 2,
            cy[:, None, None] + wh[None, None, :, 1] / 2,
        ]
        bx = jnp.stack([jnp.broadcast_to(pl, (H, W, A)) for pl in planes],
                       -1)
        var = jnp.broadcast_to(jnp.asarray(variance, jnp.float32),
                               (H, W, A, 4))
        return bx, var

    return forward_op("anchor_generator", impl, [], differentiable=False)


# ---------------------------------------------------------------------------
# YOLO decode + loss
# ---------------------------------------------------------------------------

def yolo_box(x, img_size, anchors, class_num: int, conf_thresh: float = 0.01,
             downsample_ratio: int = 32, clip_bbox: bool = True,
             scale_x_y: float = 1.0, name=None):
    """Decode one YOLOv3 head (ref: yolo_box_op). x [B, A*(5+C), H, W] ->
    ``(boxes [B, H*W*A, 4] xyxy image pixels, scores [B, H*W*A, C])``.
    Sub-threshold predictions get zero boxes/scores (the reference zeroes
    them rather than dropping — already static-shape-friendly)."""
    xt = ensure_tensor(x)
    st = ensure_tensor(img_size)
    A = len(anchors) // 2
    an = np.asarray(anchors, np.float32).reshape(A, 2)

    def impl(xv, sz):
        B, _, H, W = xv.shape
        v = xv.reshape(B, A, 5 + class_num, H, W)
        tx, ty = v[:, :, 0], v[:, :, 1]
        tw, th = v[:, :, 2], v[:, :, 3]
        obj = jax.nn.sigmoid(v[:, :, 4])
        cls = jax.nn.sigmoid(v[:, :, 5:])
        gx = jnp.arange(W)[None, None, None, :]
        gy = jnp.arange(H)[None, None, :, None]
        alpha = scale_x_y
        bxc = (jax.nn.sigmoid(tx) * alpha - 0.5 * (alpha - 1) + gx) / W
        byc = (jax.nn.sigmoid(ty) * alpha - 0.5 * (alpha - 1) + gy) / H
        in_w = W * downsample_ratio
        in_h = H * downsample_ratio
        bw = jnp.exp(tw) * an[None, :, 0, None, None] / in_w
        bh = jnp.exp(th) * an[None, :, 1, None, None] / in_h
        imh = sz[:, 0].astype(jnp.float32)[:, None, None, None]
        imw = sz[:, 1].astype(jnp.float32)[:, None, None, None]
        x1 = (bxc - bw / 2) * imw
        y1 = (byc - bh / 2) * imh
        x2 = (bxc + bw / 2) * imw
        y2 = (byc + bh / 2) * imh
        if clip_bbox:
            x1 = jnp.clip(x1, 0, imw - 1)
            y1 = jnp.clip(y1, 0, imh - 1)
            x2 = jnp.clip(x2, 0, imw - 1)
            y2 = jnp.clip(y2, 0, imh - 1)
        keep = obj > conf_thresh
        boxes = jnp.stack([x1, y1, x2, y2], -1) * keep[..., None]
        scores = cls * (obj * keep)[:, :, None]
        boxes = boxes.transpose(0, 3, 4, 1, 2).reshape(B, -1, 4)
        scores = scores.transpose(0, 3, 4, 1, 2).reshape(B, -1, class_num)
        return boxes, scores

    return forward_op("yolo_box", impl, [xt, st], differentiable=False)


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num: int,
              ignore_thresh: float = 0.7, downsample_ratio: int = 32,
              use_label_smooth: bool = False, name=None):
    """YOLOv3 loss for one head (ref: yolov3_loss_op). Responsibility
    assignment (best-IoU anchor per gt) and the objectness ignore mask are
    computed in-graph with static [B, G] gt capacity (zero-area gts are
    padding). Returns the summed scalar loss per batch element [B]."""
    xt = ensure_tensor(x)
    gb = ensure_tensor(gt_box)      # [B, G, 4] cx cy w h, normalized
    gl = ensure_tensor(gt_label)    # [B, G] int
    A_all = np.asarray(anchors, np.float32).reshape(-1, 2)
    amask = list(anchor_mask)
    A = len(amask)
    an = A_all[amask]

    def impl(xv, gbv, glv):
        B, _, H, W = xv.shape
        in_w = W * downsample_ratio
        in_h = H * downsample_ratio
        v = xv.reshape(B, A, 5 + class_num, H, W)
        tx, ty = v[:, :, 0], v[:, :, 1]
        tw, th = v[:, :, 2], v[:, :, 3]
        tobj = v[:, :, 4]
        tcls = v[:, :, 5:].transpose(0, 1, 3, 4, 2)        # [B, A, H, W, C]
        G = gbv.shape[1]
        gt_valid = (gbv[..., 2] > 0) & (gbv[..., 3] > 0)   # [B, G]

        # which anchor (over the FULL anchor set) best matches each gt
        gw = gbv[..., 2] * in_w
        gh = gbv[..., 3] * in_h
        aw = A_all[None, None, :, 0]
        ah = A_all[None, None, :, 1]
        inter = (jnp.minimum(gw[..., None], aw) *
                 jnp.minimum(gh[..., None], ah))
        iou_wh = inter / (gw[..., None] * gh[..., None] +
                          aw * ah - inter + 1e-9)
        best = jnp.argmax(iou_wh, -1)                      # [B, G]
        mask_arr = jnp.asarray(amask)
        local = jnp.argmax(best[..., None] == mask_arr[None, None], -1)
        responsible = (best[..., None] == mask_arr[None, None]).any(-1)
        resp = gt_valid & responsible                      # [B, G]

        gi = jnp.clip((gbv[..., 0] * W).astype(jnp.int32), 0, W - 1)
        gj = jnp.clip((gbv[..., 1] * H).astype(jnp.int32), 0, H - 1)

        # scatter gt targets onto the [B, A, H, W] lattice
        def scat(val, fill=0.0):
            out = jnp.full((B, A, H, W), fill, jnp.float32)
            b = jnp.broadcast_to(jnp.arange(B)[:, None], (B, G))
            return out.at[b, local, gj, gi].set(
                jnp.where(resp, val, fill), mode="drop")

        obj_tgt = scat(jnp.ones((B, G)))
        txt = scat(gbv[..., 0] * W - gi)
        tyt = scat(gbv[..., 1] * H - gj)
        # per-anchor w/h targets need the matched anchor's size
        awm = jnp.asarray(an)[local][..., 0]
        ahm = jnp.asarray(an)[local][..., 1]
        twt = scat(jnp.log(jnp.maximum(gw, 1e-9) / jnp.maximum(awm, 1e-9)))
        tht = scat(jnp.log(jnp.maximum(gh, 1e-9) / jnp.maximum(ahm, 1e-9)))
        box_scale = scat(2.0 - gbv[..., 2] * gbv[..., 3])
        cls_t = jnp.zeros((B, A, H, W, class_num))
        b = jnp.broadcast_to(jnp.arange(B)[:, None], (B, G))
        cls_t = cls_t.at[b, local, gj, gi,
                         jnp.clip(glv, 0, class_num - 1)].set(
            jnp.where(resp, 1.0, 0.0), mode="drop")
        if use_label_smooth:
            delta = 1.0 / class_num
            cls_t = cls_t * (1 - delta) + delta / class_num

        # ignore mask: predictions whose best IoU with any gt > thresh
        gx_ = jnp.arange(W)[None, None, None, :]
        gy_ = jnp.arange(H)[None, None, :, None]
        pxc = (jax.nn.sigmoid(tx) + gx_) / W
        pyc = (jax.nn.sigmoid(ty) + gy_) / H
        pw = jnp.exp(jnp.clip(tw, -10, 10)) * an[None, :, 0, None, None] / in_w
        ph_ = jnp.exp(jnp.clip(th, -10, 10)) * an[None, :, 1, None, None] / in_h
        px1, py1 = pxc - pw / 2, pyc - ph_ / 2
        px2, py2 = pxc + pw / 2, pyc + ph_ / 2
        gx1 = (gbv[..., 0] - gbv[..., 2] / 2)
        gy1 = (gbv[..., 1] - gbv[..., 3] / 2)
        gx2 = (gbv[..., 0] + gbv[..., 2] / 2)
        gy2 = (gbv[..., 1] + gbv[..., 3] / 2)
        ix1 = jnp.maximum(px1[..., None], gx1[:, None, None, None, :])
        iy1 = jnp.maximum(py1[..., None], gy1[:, None, None, None, :])
        ix2 = jnp.minimum(px2[..., None], gx2[:, None, None, None, :])
        iy2 = jnp.minimum(py2[..., None], gy2[:, None, None, None, :])
        inter2 = jnp.clip(ix2 - ix1, 0) * jnp.clip(iy2 - iy1, 0)
        pa = pw * ph_
        ga = (gbv[..., 2] * gbv[..., 3])[:, None, None, None, :]
        iou = inter2 / jnp.maximum(pa[..., None] + ga - inter2, 1e-9)
        iou = jnp.where(gt_valid[:, None, None, None, :], iou, 0.0)
        best_iou = iou.max(-1)
        ignore = (best_iou > ignore_thresh) & (obj_tgt < 0.5)

        def bce(logit, tgt):
            return jnp.maximum(logit, 0) - logit * tgt + \
                jnp.log1p(jnp.exp(-jnp.abs(logit)))

        pos = obj_tgt > 0.5
        loss_xy = (bce(tx, txt) + bce(ty, tyt)) * box_scale * pos
        loss_wh = (jnp.abs(tw - twt) + jnp.abs(th - tht)) * box_scale * pos
        loss_obj = bce(tobj, obj_tgt) * jnp.where(ignore, 0.0, 1.0)
        loss_cls = (bce(tcls, cls_t) * pos[..., None]).sum(-1)
        total = (loss_xy + loss_wh + loss_obj + loss_cls).sum((1, 2, 3))
        return total

    return forward_op("yolo_loss", impl, [xt, gb, gl])


# ---------------------------------------------------------------------------
# NMS variants
# ---------------------------------------------------------------------------

def matrix_nms(bboxes, scores, score_threshold: float = 0.05,
               post_threshold: float = 0.0, nms_top_k: int = 100,
               keep_top_k: int = 100, use_gaussian: bool = False,
               gaussian_sigma: float = 2.0, normalized: bool = True,
               name=None):
    """Matrix NMS (ref: matrix_nms_op, SOLOv2): scores decay by the worst
    overlap with any higher-scored box of the same class — a dense [K, K]
    min-reduction with NO sequential dependency, which makes it the most
    TPU-friendly suppression of the family (fully parallel, one program).

    ``bboxes [B, M, 4]``, ``scores [B, C, M]`` ->
    ``(out [B, keep_top_k, 6] (label, score, x1, y1, x2, y2),
    index [B, keep_top_k], count [B])`` — static shapes, invalid slots have
    label -1 (the reference's padding convention)."""
    bt = ensure_tensor(bboxes)
    st = ensure_tensor(scores)
    off = 0.0 if normalized else 1.0

    def impl(bv, sv):
        B, C, M = sv.shape
        K = min(nms_top_k, M)

        def one_class(boxes, s):                  # [M,4], [M] -> decayed [K]
            top_s, idx = lax.top_k(s, K)
            tb = boxes[idx]
            x1, y1, x2, y2 = (tb[:, i] for i in range(4))
            area = jnp.clip(x2 - x1 + off, 0) * jnp.clip(y2 - y1 + off, 0)
            ix1 = jnp.maximum(x1[:, None], x1[None, :])
            iy1 = jnp.maximum(y1[:, None], y1[None, :])
            ix2 = jnp.minimum(x2[:, None], x2[None, :])
            iy2 = jnp.minimum(y2[:, None], y2[None, :])
            inter = jnp.clip(ix2 - ix1 + off, 0) * jnp.clip(iy2 - iy1 + off, 0)
            iou = inter / jnp.maximum(area[:, None] + area[None, :] - inter,
                                      1e-9)
            upper = jnp.tril(iou, -1)             # iou[i, j] for j < i
            comp = upper.max(1)                   # worst overlap of each
            if use_gaussian:
                dec = jnp.exp(-(upper ** 2 - comp[None, :] ** 2)
                              * gaussian_sigma)
            else:
                dec = (1 - upper) / jnp.maximum(1 - comp[None, :], 1e-9)
            decay = jnp.where(
                jnp.tril(jnp.ones((K, K), bool), -1), dec, jnp.inf
            ).min(1)
            decay = jnp.where(jnp.arange(K) == 0, 1.0, decay)
            ds = top_s * decay * (top_s > score_threshold)
            if post_threshold > 0:
                ds = ds * (ds > post_threshold)
            return ds, idx

        def one_image(boxes, sc):                 # [M,4], [C,M]
            ds, idx = jax.vmap(lambda s: one_class(boxes, s))(sc)  # [C,K]
            flat = ds.reshape(-1)
            kk = min(keep_top_k, flat.shape[0])
            top, fi = lax.top_k(flat, kk)
            cls = (fi // K).astype(jnp.float32)
            mi = idx.reshape(-1)[fi]
            bsel = boxes[mi]
            valid = top > 0
            out = jnp.concatenate(
                [jnp.where(valid, cls, -1.0)[:, None], top[:, None], bsel],
                -1)
            return out, jnp.where(valid, mi, -1), valid.sum()

        return jax.vmap(one_image)(bv, sv)

    return forward_op("matrix_nms", impl, [bt, st], differentiable=False)


def multiclass_nms(bboxes, scores, score_threshold: float = 0.05,
                   nms_top_k: int = 100, keep_top_k: int = 100,
                   nms_threshold: float = 0.3, normalized: bool = True,
                   background_label: int = -1, name=None):
    """Multiclass greedy NMS (ref: multiclass_nms_op): per-class greedy
    suppression then a global keep_top_k merge. TPU formulation: the
    per-class pass is ``vmap`` over classes of the static greedy kernel
    (fori_loop over K candidates), the merge one global top-k — everything
    static-shape ([B, keep_top_k, 6] + counts, label -1 padding).

    ``bboxes [B, M, 4]``, ``scores [B, C, M]`` ->
    ``(out [B, keep_top_k, 6], index [B, keep_top_k], count [B])``."""
    bt = ensure_tensor(bboxes)
    st = ensure_tensor(scores)
    off = 0.0 if normalized else 1.0

    def impl(bv, sv):
        B, C, M = sv.shape
        K = min(nms_top_k, M)

        def one_class(boxes, s):
            top_s, idx = lax.top_k(s, K)
            tb = boxes[idx]
            x1, y1, x2, y2 = (tb[:, i] for i in range(4))
            area = jnp.clip(x2 - x1 + off, 0) * jnp.clip(y2 - y1 + off, 0)
            ix1 = jnp.maximum(x1[:, None], x1[None, :])
            iy1 = jnp.maximum(y1[:, None], y1[None, :])
            ix2 = jnp.minimum(x2[:, None], x2[None, :])
            iy2 = jnp.minimum(y2[:, None], y2[None, :])
            inter = jnp.clip(ix2 - ix1 + off, 0) * jnp.clip(iy2 - iy1 + off, 0)
            iou = inter / jnp.maximum(area[:, None] + area[None, :] - inter,
                                      1e-9)

            def body(i, keep):
                sup = (iou[i] > nms_threshold) & (jnp.arange(K) > i)
                return jnp.where(keep[i], keep & ~sup, keep)

            keep = lax.fori_loop(0, K, body, top_s > score_threshold)
            return jnp.where(keep, top_s, 0.0), idx

        def one_image(boxes, sc):
            ds, idx = jax.vmap(lambda s: one_class(boxes, s))(sc)  # [C, K]
            if background_label >= 0:
                ds = ds.at[background_label].set(0.0)
            flat = ds.reshape(-1)
            kk = min(keep_top_k, flat.shape[0])
            top, fi = lax.top_k(flat, kk)
            cls = (fi // K).astype(jnp.float32)
            mi = idx.reshape(-1)[fi]
            bsel = boxes[mi]
            valid = top > 0
            out = jnp.concatenate(
                [jnp.where(valid, cls, -1.0)[:, None], top[:, None], bsel],
                -1)
            return out, jnp.where(valid, mi, -1), valid.sum()

        return jax.vmap(one_image)(bv, sv)

    return forward_op("multiclass_nms", impl, [bt, st],
                      differentiable=False)


# ---------------------------------------------------------------------------
# proposals
# ---------------------------------------------------------------------------

def _decode_rcnn(anchors, deltas, variances=None):
    aw = anchors[:, 2] - anchors[:, 0] + 1.0
    ah = anchors[:, 3] - anchors[:, 1] + 1.0
    acx = anchors[:, 0] + aw / 2
    acy = anchors[:, 1] + ah / 2
    if variances is not None:
        deltas = deltas * variances
    dcx = acx + deltas[:, 0] * aw
    dcy = acy + deltas[:, 1] * ah
    dw = aw * jnp.exp(jnp.clip(deltas[:, 2], -10, 4.135))
    dh = ah * jnp.exp(jnp.clip(deltas[:, 3], -10, 4.135))
    return jnp.stack([dcx - dw / 2, dcy - dh / 2,
                      dcx + dw / 2 - 1, dcy + dh / 2 - 1], -1)


def generate_proposals(scores, bbox_deltas, im_shape, anchors, variances=None,
                       pre_nms_top_n: int = 6000, post_nms_top_n: int = 1000,
                       nms_thresh: float = 0.5, min_size: float = 0.1,
                       eta: float = 1.0, name=None):
    """RPN proposal generation (ref: generate_proposals_v2_op): decode
    anchors with deltas, clip to image, drop tiny boxes, pre-NMS top-k,
    greedy NMS, post-NMS top-k. All stages are static-shape (drops become
    score zeroing); returns ``(rois [B, post_nms_top_n, 4],
    roi_scores [B, post_nms_top_n], count [B])``."""
    st = ensure_tensor(scores)        # [B, A, H, W]
    dt = ensure_tensor(bbox_deltas)   # [B, A*4, H, W]
    it = ensure_tensor(im_shape)      # [B, 2] (h, w)
    at = ensure_tensor(anchors)       # [H, W, A, 4] or [N, 4]
    args = [st, dt, it, at]
    if variances is not None:
        args.append(ensure_tensor(variances))

    def impl(sv, dv, iv, av, *var):
        B, A, H, W = sv.shape
        N = A * H * W
        anc = av.reshape(-1, 4)
        if anc.shape[0] != N:          # [H, W, A, 4] layout
            anc = av.reshape(N, 4)
        vv = var[0].reshape(-1, 4) if var else None

        def one(s, d, im):
            s = s.transpose(1, 2, 0).reshape(-1)            # HWA order
            d = d.reshape(A, 4, H, W).transpose(2, 3, 0, 1).reshape(-1, 4)
            a2 = anc
            K = min(pre_nms_top_n, N)
            top_s, idx = lax.top_k(s, K)
            boxes = _decode_rcnn(a2[idx], d[idx],
                                 None if vv is None else vv[idx])
            h, w = im[0], im[1]
            boxes = jnp.stack([jnp.clip(boxes[:, 0], 0, w - 1),
                               jnp.clip(boxes[:, 1], 0, h - 1),
                               jnp.clip(boxes[:, 2], 0, w - 1),
                               jnp.clip(boxes[:, 3], 0, h - 1)], -1)
            bw = boxes[:, 2] - boxes[:, 0] + 1
            bh = boxes[:, 3] - boxes[:, 1] + 1
            ok = (bw >= min_size) & (bh >= min_size)
            top_s = jnp.where(ok, top_s, 0.0)
            area = bw * bh
            ix1 = jnp.maximum(boxes[:, None, 0], boxes[None, :, 0])
            iy1 = jnp.maximum(boxes[:, None, 1], boxes[None, :, 1])
            ix2 = jnp.minimum(boxes[:, None, 2], boxes[None, :, 2])
            iy2 = jnp.minimum(boxes[:, None, 3], boxes[None, :, 3])
            inter = jnp.clip(ix2 - ix1 + 1, 0) * jnp.clip(iy2 - iy1 + 1, 0)
            iou = inter / jnp.maximum(area[:, None] + area[None, :] - inter,
                                      1e-9)

            def body(i, keep):
                sup = (iou[i] > nms_thresh) & (jnp.arange(K) > i)
                return jnp.where(keep[i], keep & ~sup, keep)

            keep = lax.fori_loop(0, K, body, top_s > 0)
            kept_s = jnp.where(keep, top_s, 0.0)
            P = min(post_nms_top_n, K)
            fs, fi = lax.top_k(kept_s, P)
            return boxes[fi], fs, (fs > 0).sum()

        return jax.vmap(one)(sv, dv, iv)

    return forward_op("generate_proposals", impl, args,
                      differentiable=False)


def distribute_fpn_proposals(fpn_rois, min_level: int, max_level: int,
                             refer_level: int, refer_scale: int,
                             rois_num=None, name=None):
    """Assign RoIs to FPN levels by scale (ref:
    distribute_fpn_proposals_op): level = refer + floor(log2(sqrt(area)/
    refer_scale)). The upstream output is a ragged per-level list, so this
    op is EAGER-ONLY (like ``nms``); returns (list of per-level roi
    Tensors, restore_index)."""
    rt = ensure_tensor(fpn_rois)
    rv = np.asarray(rt._value)
    w = rv[:, 2] - rv[:, 0]
    h = rv[:, 3] - rv[:, 1]
    scale = np.sqrt(np.clip(w * h, 0, None))
    lvl = np.floor(np.log2(scale / refer_scale + 1e-8)) + refer_level
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)
    from ..core.tensor import to_tensor
    outs, order = [], []
    for L in range(min_level, max_level + 1):
        idx = np.nonzero(lvl == L)[0]
        outs.append(to_tensor(rv[idx]))
        order.append(idx)
    order = np.concatenate(order) if order else np.zeros((0,), np.int64)
    restore = np.empty_like(order)
    restore[order] = np.arange(order.shape[0])
    return outs, to_tensor(restore.astype(np.int64))


def collect_fpn_proposals(multi_rois, multi_scores, post_nms_top_n: int,
                          name=None):
    """Merge per-level RPN outputs and keep the global top-n by score (ref:
    collect_fpn_proposals_op). Static: inputs are fixed-capacity per level;
    one concat + top_k."""
    rts = [ensure_tensor(r) for r in multi_rois]
    sts = [ensure_tensor(s) for s in multi_scores]

    def impl(*vals):
        k = len(rts)
        rois = jnp.concatenate(vals[:k], 0)
        scores = jnp.concatenate(vals[k:], 0)
        P = min(post_nms_top_n, scores.shape[0])
        top, idx = lax.top_k(scores, P)
        return rois[idx], top

    return forward_op("collect_fpn_proposals", impl, rts + sts,
                      differentiable=False)


# ---------------------------------------------------------------------------
# matching / assignment / misc
# ---------------------------------------------------------------------------

def box_clip(input, im_info, name=None):
    """Clip boxes to image bounds (ref: box_clip_op). ``im_info`` rows are
    (h, w, scale); boxes clip to [0, dim/scale - 1]."""
    bt = ensure_tensor(input)
    it = ensure_tensor(im_info)

    def impl(bv, iv):
        h = iv[..., 0] / iv[..., 2] - 1
        w = iv[..., 1] / iv[..., 2] - 1
        if bv.ndim == 2:
            hh, ww = h[0] if h.ndim else h, w[0] if w.ndim else w
            return jnp.stack([jnp.clip(bv[:, 0], 0, ww),
                              jnp.clip(bv[:, 1], 0, hh),
                              jnp.clip(bv[:, 2], 0, ww),
                              jnp.clip(bv[:, 3], 0, hh)], -1)
        return jnp.stack([jnp.clip(bv[..., 0], 0, w[:, None]),
                          jnp.clip(bv[..., 1], 0, h[:, None]),
                          jnp.clip(bv[..., 2], 0, w[:, None]),
                          jnp.clip(bv[..., 3], 0, h[:, None])], -1)

    return forward_op("box_clip", impl, [bt, it])


def iou_similarity(x, y, box_normalized: bool = True, name=None):
    """Pairwise IoU matrix [N, M] (ref: iou_similarity_op; the SSD matching
    metric). Same math as ``vision.ops.box_iou`` with the reference's +1
    convention for unnormalized boxes."""
    xt = ensure_tensor(x)
    yt = ensure_tensor(y)
    off = 0.0 if box_normalized else 1.0

    def impl(a, b):
        area1 = jnp.clip(a[:, 2] - a[:, 0] + off, 0) * \
            jnp.clip(a[:, 3] - a[:, 1] + off, 0)
        area2 = jnp.clip(b[:, 2] - b[:, 0] + off, 0) * \
            jnp.clip(b[:, 3] - b[:, 1] + off, 0)
        lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
        rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
        wh = jnp.clip(rb - lt + off, 0)
        inter = wh[..., 0] * wh[..., 1]
        return inter / jnp.maximum(area1[:, None] + area2[None] - inter,
                                   1e-10)

    return forward_op("iou_similarity", impl, [xt, yt])


def bipartite_match(dist_matrix, match_type: str = "bipartite",
                    dist_threshold: float = 0.5, name=None):
    """Greedy bipartite matching (ref: bipartite_match_op, the SSD
    matcher): repeatedly take the globally largest entry, retire its row
    and column. The argmax chain is inherently serial and the output
    semantics are index tables, so this runs EAGERLY on host (like
    ``nms``); ``per_prediction`` additionally matches every column whose
    best row-distance exceeds ``dist_threshold``. Returns
    ``(match_indices [N] row->col, match_dist [N])`` for a single [R, C]
    matrix (columns = priors in the reference's layout are rows here:
    we match rows of the matrix)."""
    dt = ensure_tensor(dist_matrix)
    d = np.asarray(dt._value, np.float64).copy()
    R, C = d.shape
    match = -np.ones(C, np.int64)
    dist = np.zeros(C, np.float64)
    work = d.copy()
    for _ in range(min(R, C)):
        i, j = np.unravel_index(np.argmax(work), work.shape)
        if work[i, j] <= 0:
            break
        match[j] = i
        dist[j] = work[i, j]
        work[i, :] = -1
        work[:, j] = -1
    if match_type == "per_prediction":
        for j in range(C):
            if match[j] < 0:
                i = int(np.argmax(d[:, j]))
                if d[i, j] >= dist_threshold:
                    match[j] = i
                    dist[j] = d[i, j]
    from ..core.tensor import to_tensor
    return to_tensor(match), to_tensor(dist.astype(np.float32))


def target_assign(input, matched_indices, negative_indices=None,
                  mismatch_value: float = 0.0, name=None):
    """Gather per-prior targets by match index (ref: target_assign_op):
    out[j] = input[matched_indices[j]], mismatch slots get
    ``mismatch_value`` and weight 0. Returns (out, out_weight)."""
    it = ensure_tensor(input)
    mt = ensure_tensor(matched_indices)

    def impl(iv, mv):
        safe = jnp.clip(mv, 0, iv.shape[0] - 1)
        out = iv[safe]
        ok = (mv >= 0).reshape((-1,) + (1,) * (out.ndim - 1))
        out = jnp.where(ok, out, mismatch_value)
        return out, ok.astype(jnp.float32)

    return forward_op("target_assign", impl, [it, mt],
                      differentiable=False)


def mine_hard_examples(cls_loss, match_indices, neg_pos_ratio: float = 3.0,
                       neg_dist_threshold: float = 0.5,
                       sample_size: Optional[int] = None,
                       mining_type: str = "max_negative", name=None):
    """SSD hard-negative mining (ref: mine_hard_examples_op): keep the
    highest-loss unmatched priors up to ``neg_pos_ratio x`` the positive
    count. Static formulation: a sort + rank threshold produces a [N] bool
    mask (fixed shape) instead of the reference's ragged index list."""
    lt = ensure_tensor(cls_loss)
    mt = ensure_tensor(match_indices)

    def impl(lv, mv):
        pos = mv >= 0
        n_pos = pos.sum()
        cap = (neg_pos_ratio * n_pos).astype(jnp.int32) if sample_size is None \
            else jnp.asarray(sample_size, jnp.int32)
        neg_loss = jnp.where(pos, -jnp.inf, lv)
        order = jnp.argsort(-neg_loss)
        rank = jnp.empty_like(order).at[order].set(jnp.arange(lv.shape[0]))
        return (~pos) & (rank < cap) & jnp.isfinite(neg_loss)

    return forward_op("mine_hard_examples", impl, [lt, mt],
                      differentiable=False)


def ssd_loss(location, confidence, gt_box, gt_label, prior_box_t,
             prior_box_var=None, neg_pos_ratio: float = 3.0,
             background_label: int = 0, loc_loss_weight: float = 1.0,
             conf_loss_weight: float = 1.0, name=None):
    """SSD multibox loss (ref: ssd_loss_op), composed from the family's own
    pieces: iou match (eager bipartite) -> target assign -> smooth-L1 loc
    loss + softmax conf loss with mined hard negatives. One scalar out."""
    loc = ensure_tensor(location)      # [P, 4]
    conf = ensure_tensor(confidence)   # [P, C]
    gb = ensure_tensor(gt_box)         # [G, 4]
    gl = ensure_tensor(gt_label)       # [G]
    pb = ensure_tensor(prior_box_t)    # [P, 4]

    iou = iou_similarity(gb, pb)
    match, _ = bipartite_match(iou, "per_prediction", 0.5)

    def impl(locv, confv, gbv, glv, pbv, mv):
        P = pbv.shape[0]
        pos = mv >= 0
        safe = jnp.clip(mv, 0, gbv.shape[0] - 1)
        tgt = gbv[safe]
        # encode gt against priors (the SSD box coder)
        pw = pbv[:, 2] - pbv[:, 0]
        ph_ = pbv[:, 3] - pbv[:, 1]
        pcx = (pbv[:, 0] + pbv[:, 2]) / 2
        pcy = (pbv[:, 1] + pbv[:, 3]) / 2
        gw = jnp.maximum(tgt[:, 2] - tgt[:, 0], 1e-6)
        gh = jnp.maximum(tgt[:, 3] - tgt[:, 1], 1e-6)
        gcx = (tgt[:, 0] + tgt[:, 2]) / 2
        gcy = (tgt[:, 1] + tgt[:, 3]) / 2
        enc = jnp.stack([(gcx - pcx) / pw / 0.1, (gcy - pcy) / ph_ / 0.1,
                         jnp.log(gw / pw) / 0.2, jnp.log(gh / ph_) / 0.2],
                        -1)
        diff = locv - enc
        l1 = jnp.where(jnp.abs(diff) < 1, 0.5 * diff ** 2,
                       jnp.abs(diff) - 0.5).sum(-1)
        n_pos = jnp.maximum(pos.sum(), 1)
        loc_loss = (l1 * pos).sum() / n_pos

        labels = jnp.where(pos, glv[safe], background_label)
        logp = jax.nn.log_softmax(confv, -1)
        ce = -jnp.take_along_axis(logp, labels[:, None], -1)[:, 0]
        neg_loss = jnp.where(pos, -jnp.inf, ce)
        order = jnp.argsort(-neg_loss)
        rank = jnp.empty_like(order).at[order].set(jnp.arange(P))
        hard_neg = (~pos) & (rank < (neg_pos_ratio * pos.sum()).astype(
            jnp.int32))
        conf_loss = (ce * (pos | hard_neg)).sum() / n_pos
        return loc_loss_weight * loc_loss + conf_loss_weight * conf_loss

    return forward_op("ssd_loss", impl, [loc, conf, gb, gl, pb, match])


def detection_output(loc, scores, prior_box_t, prior_box_var=None,
                     background_label: int = 0, nms_threshold: float = 0.3,
                     nms_top_k: int = 400, keep_top_k: int = 200,
                     score_threshold: float = 0.01, name=None):
    """SSD inference head (ref: detection_output_op): decode priors with
    the predicted deltas, then multiclass NMS. Composed entirely from this
    family's static ops. ``loc [B, P, 4]``, ``scores [B, P, C]``,
    priors [P, 4] (+var [P, 4]); returns the multiclass_nms triple."""
    lt = ensure_tensor(loc)
    st = ensure_tensor(scores)
    pt = ensure_tensor(prior_box_t)
    var = ensure_tensor(prior_box_var) if prior_box_var is not None else None

    def decode(lv, pv, vv):
        pw = pv[:, 2] - pv[:, 0]
        ph_ = pv[:, 3] - pv[:, 1]
        pcx = (pv[:, 0] + pv[:, 2]) / 2
        pcy = (pv[:, 1] + pv[:, 3]) / 2
        v = vv if vv is not None else jnp.asarray([0.1, 0.1, 0.2, 0.2])
        dcx = pcx + lv[..., 0] * v[..., 0] * pw
        dcy = pcy + lv[..., 1] * v[..., 1] * ph_
        dw = pw * jnp.exp(jnp.clip(lv[..., 2] * v[..., 2], -10, 10))
        dh = ph_ * jnp.exp(jnp.clip(lv[..., 3] * v[..., 3], -10, 10))
        return jnp.stack([dcx - dw / 2, dcy - dh / 2,
                          dcx + dw / 2, dcy + dh / 2], -1)

    args = [lt, st, pt] + ([var] if var is not None else [])

    def impl(lv, sv, pv, *vv):
        boxes = decode(lv, pv, vv[0] if vv else None)       # [B, P, 4]
        return boxes, sv.transpose(0, 2, 1)                 # [B, C, P]

    decoded = forward_op("detection_output", impl, args,
                         differentiable=False)
    boxes, sc = decoded
    return multiclass_nms(boxes, sc, score_threshold=score_threshold,
                          nms_top_k=nms_top_k, keep_top_k=keep_top_k,
                          nms_threshold=nms_threshold,
                          background_label=background_label)


def polygon_box_transform(input, name=None):
    """Quad-offset -> corner-coordinate transform for EAST-style OCR heads
    (ref: polygon_box_transform_op): channel 2k holds x offsets, 2k+1 y
    offsets; output adds the lattice coordinates to non-zero entries."""
    it = ensure_tensor(input)

    def impl(iv):
        B, C, H, W = iv.shape
        gx = jnp.arange(W)[None, None, None, :] * 4.0
        gy = jnp.arange(H)[None, None, :, None] * 4.0
        is_x = (jnp.arange(C) % 2 == 0)[None, :, None, None]
        base = jnp.where(is_x, gx, gy)
        return jnp.where(iv != 0, base - iv, iv)

    return forward_op("polygon_box_transform", impl, [it],
                      differentiable=False)


# register every public op in the schema registry (ops.yaml-equivalent)
for _n in __all__:
    _f = globals()[_n]
    register_op(_n, _f, (_f.__doc__ or "").strip().split("\n")[0].rstrip(","),
                public=_f)


# ---------------------------------------------------------------------------
# r5 follow-on: contrib vision singles (ref: fluid/operators —
# prroi_pool_op, bilateral_slice_op, correlation_op,
# retinanet_detection_output_op). Same static-shape design language.
# ---------------------------------------------------------------------------

def prroi_pool(x, boxes, output_size=7, spatial_scale: float = 1.0,
               name=None):
    """Precise ROI pooling (ref: prroi_pool_op): exact integral of the
    bilinear surface over each bin — here the integral is evaluated by
    dense per-pixel bin-overlap weights (one einsum; exact for the
    piecewise-constant surface, the standard TPU-friendly approximation)."""
    xt = ensure_tensor(x)
    bt = ensure_tensor(boxes)
    ph_, pw_ = ((output_size, output_size) if isinstance(output_size, int)
                else tuple(output_size))

    def impl(xv, bv):
        B, C, H, W = xv.shape
        n = bv.shape[0]
        x1 = bv[:, 0] * spatial_scale
        y1 = bv[:, 1] * spatial_scale
        x2 = bv[:, 2] * spatial_scale
        y2 = bv[:, 3] * spatial_scale
        bw = jnp.maximum(x2 - x1, 1e-4)
        bh = jnp.maximum(y2 - y1, 1e-4)
        ys = y1[:, None] + bh[:, None] * jnp.arange(ph_ + 1) / ph_
        xs = x1[:, None] + bw[:, None] * jnp.arange(pw_ + 1) / pw_
        gy = jnp.arange(H)[None, None, :]
        gx = jnp.arange(W)[None, None, :]
        # fractional overlap of each pixel cell [g, g+1) with each bin
        oy = jnp.clip(jnp.minimum(ys[:, 1:, None], gy + 1) -
                      jnp.maximum(ys[:, :-1, None], gy), 0)   # [n, ph, H]
        ox = jnp.clip(jnp.minimum(xs[:, 1:, None], gx + 1) -
                      jnp.maximum(xs[:, :-1, None], gx), 0)   # [n, pw, W]
        area = (bh[:, None] / ph_) * (bw[:, None] / pw_)
        pooled = jnp.einsum("cHW,niH,njW->ncij", xv[0], oy, ox)
        return pooled / jnp.maximum(area[:, :, None, None] * 0 +
                                    (oy.sum(-1)[:, :, None] *
                                     ox.sum(-1)[:, None, :])[:, None], 1e-6)

    return forward_op("prroi_pool", impl, [xt, bt])


def bilateral_slice(x, guide, grid, has_offset: bool = False, name=None):
    """HDRNet bilateral-grid slicing (ref: bilateral_slice_op): trilinear
    lookup of per-pixel affine coefficients from a low-res grid indexed by
    (x, y, guide)."""
    xt = ensure_tensor(x)
    gt = ensure_tensor(guide)
    rt = ensure_tensor(grid)

    def impl(xv, gv, rv):
        B, C, H, W = xv.shape
        _, GC, GD, GH, GW = rv.shape
        yy = (jnp.arange(H) + 0.5) / H * GH - 0.5
        xx = (jnp.arange(W) + 0.5) / W * GW - 0.5
        zz = gv * GD - 0.5                                   # [B, H, W]
        y0 = jnp.clip(jnp.floor(yy), 0, GH - 1).astype(jnp.int32)
        x0 = jnp.clip(jnp.floor(xx), 0, GW - 1).astype(jnp.int32)
        z0 = jnp.clip(jnp.floor(zz), 0, GD - 1).astype(jnp.int32)
        y1 = jnp.clip(y0 + 1, 0, GH - 1)
        x1 = jnp.clip(x0 + 1, 0, GW - 1)
        z1 = jnp.clip(z0 + 1, 0, GD - 1)
        wy = (yy - jnp.floor(yy))[None, :, None]
        wx = (xx - jnp.floor(xx))[None, None, :]
        wz = zz - jnp.floor(zz)
        out = 0
        for zi, wzf in ((z0, 1 - wz), (z1, wz)):
            for yi, wyf in ((y0, 1 - wy), (y1, wy)):
                for xi, wxf in ((x0, 1 - wx), (x1, wx)):
                    g = rv[jnp.arange(B)[:, None, None], :, zi,
                           yi[None, :, None], xi[None, None, :]]
                    out = out + g * (wzf * wyf * wxf)[..., None]
        coeff = jnp.moveaxis(out, -1, 1)                     # [B, GC, H, W]
        if not has_offset:
            return coeff
        # affine apply: GC = C*(C+1) -> out C channels
        nco = GC // (C + 1)
        mat = coeff.reshape(B, nco, C + 1, H, W)
        return (mat[:, :, :C] * xv[:, None]).sum(2) + mat[:, :, C]

    return forward_op("bilateral_slice", impl, [xt, gt, rt])


def correlation(x, y, pad_size: int = 4, kernel_size: int = 1,
                max_displacement: int = 4, stride1: int = 1,
                stride2: int = 1, corr_type_multiply: int = 1, name=None):
    """FlowNet correlation layer (ref: correlation_op): dot products of
    local patches across displacement offsets — a [D*D, B, H, W] stack of
    shifted elementwise products, one fused XLA program."""
    xt = ensure_tensor(x)
    yt = ensure_tensor(y)
    d = max_displacement

    def impl(xv, yv):
        B, C, H, W = xv.shape
        pads = [(0, 0), (0, 0), (d, d), (d, d)]
        yp = jnp.pad(yv, pads)
        outs = []
        for dy in range(0, 2 * d + 1, stride2):
            for dx in range(0, 2 * d + 1, stride2):
                shifted = yp[:, :, dy:dy + H, dx:dx + W]
                outs.append((xv * shifted).mean(1))
        return jnp.stack(outs, 1)                            # [B, D*D, H, W]

    return forward_op("correlation", impl, [xt, yt])


def retinanet_detection_output(bboxes_list, scores_list, anchors_list,
                               im_info, score_threshold: float = 0.05,
                               nms_top_k: int = 1000, keep_top_k: int = 100,
                               nms_threshold: float = 0.3, name=None):
    """RetinaNet head decode + multiclass NMS over FPN levels (ref:
    retinanet_detection_output_op): per-level decode vs anchors, concat,
    then the static multiclass_nms."""
    decoded = []
    scores_all = []
    for deltas, scores, anchors in zip(bboxes_list, scores_list,
                                       anchors_list):
        dt = ensure_tensor(deltas)      # [B, A, 4]
        st = ensure_tensor(scores)      # [B, A, C]
        at = ensure_tensor(anchors)     # [A, 4]

        def dec(dv, av):
            return jax.vmap(lambda d: _decode_rcnn(av, d))(dv)

        decoded.append(forward_op("retinanet_decode", dec, [dt, at],
                                  differentiable=False))
        scores_all.append(st)
    from ..ops.manipulation import concat, transpose
    boxes = concat(decoded, axis=1)
    scores = transpose(concat(scores_all, axis=1), [0, 2, 1])
    return multiclass_nms(boxes, scores, score_threshold=score_threshold,
                          nms_top_k=nms_top_k, keep_top_k=keep_top_k,
                          nms_threshold=nms_threshold)


__all__ += ["prroi_pool", "bilateral_slice", "correlation",
            "retinanet_detection_output"]
for _n in ["prroi_pool", "bilateral_slice", "correlation",
           "retinanet_detection_output"]:
    _f = globals()[_n]
    register_op(_n, _f, (_f.__doc__ or "").strip().split("\n")[0],
                public=_f)


# ---------------------------------------------------------------------------
# r5 third batch: R-CNN training-side target assignment (ref:
# rpn_target_assign_op, retinanet_target_assign_op,
# generate_proposal_labels_op, box_decoder_and_assign_op,
# roi_perspective_transform_op). Assignment is IoU thresholding — dense
# masked argmax here (no ragged sampling lists; sampling quotas become
# rank-threshold masks, the static formulation used throughout this file).
# ---------------------------------------------------------------------------

def rpn_target_assign(anchors, gt_boxes, is_crowd=None, im_info=None,
                      rpn_batch_size_per_im: int = 256,
                      rpn_straddle_thresh: float = 0.0,
                      rpn_fg_fraction: float = 0.5,
                      rpn_positive_overlap: float = 0.7,
                      rpn_negative_overlap: float = 0.3, name=None):
    """RPN anchor labeling (ref: rpn_target_assign_op): label 1 for
    anchors with IoU >= positive_overlap (plus each gt's argmax anchor),
    0 for IoU < negative_overlap, -1 ignore. Static [A] outputs:
    (labels [A], matched_gt [A], fg_mask [A], bg_mask [A]) with sampling
    quotas enforced by score-free rank masks."""
    at = ensure_tensor(anchors)
    gt = ensure_tensor(gt_boxes)

    def impl(av, gv):
        A = av.shape[0]
        area_ok = (gv[:, 2] > gv[:, 0]) & (gv[:, 3] > gv[:, 1])
        lt_ = jnp.maximum(av[:, None, :2], gv[None, :, :2])
        rb = jnp.minimum(av[:, None, 2:], gv[None, :, 2:])
        wh = jnp.clip(rb - lt_, 0)
        inter = wh[..., 0] * wh[..., 1]
        a1 = (av[:, 2] - av[:, 0]) * (av[:, 3] - av[:, 1])
        a2 = (gv[:, 2] - gv[:, 0]) * (gv[:, 3] - gv[:, 1])
        iou = inter / jnp.maximum(a1[:, None] + a2[None] - inter, 1e-9)
        iou = jnp.where(area_ok[None, :], iou, 0.0)
        best_iou = iou.max(1)
        best_gt = iou.argmax(1)
        pos = best_iou >= rpn_positive_overlap
        # each gt's best anchor is positive too
        gt_best_anchor = iou.argmax(0)
        pos = pos.at[gt_best_anchor].set(area_ok | pos[gt_best_anchor])
        neg = (best_iou < rpn_negative_overlap) & ~pos
        n_fg = int(rpn_batch_size_per_im * rpn_fg_fraction)
        # rank-based subsample to the quotas (deterministic: by IoU rank)
        fg_rank = jnp.argsort(
            jnp.argsort(jnp.where(pos, -best_iou, jnp.inf)))
        fg = pos & (fg_rank < n_fg)
        n_bg = rpn_batch_size_per_im - n_fg
        bg_rank = jnp.argsort(jnp.argsort(jnp.where(neg, best_iou,
                                                    jnp.inf)))
        bg = neg & (bg_rank < n_bg)
        labels = jnp.where(fg, 1, jnp.where(bg, 0, -1))
        return labels, best_gt, fg, bg

    return forward_op("rpn_target_assign", impl, [at, gt],
                      differentiable=False)


def retinanet_target_assign(anchors, gt_boxes, gt_labels, im_info=None,
                            positive_overlap: float = 0.5,
                            negative_overlap: float = 0.4, name=None):
    """RetinaNet anchor labeling (ref: retinanet_target_assign_op): like
    RPN but multi-class labels and no subsampling (focal loss handles the
    imbalance). Returns (cls_targets [A] (-1 ignore, 0 bg, c+1 fg),
    matched_gt [A], fg_mask [A])."""
    at = ensure_tensor(anchors)
    gt = ensure_tensor(gt_boxes)
    gl = ensure_tensor(gt_labels)

    def impl(av, gv, lv):
        area_ok = (gv[:, 2] > gv[:, 0]) & (gv[:, 3] > gv[:, 1])
        lt_ = jnp.maximum(av[:, None, :2], gv[None, :, :2])
        rb = jnp.minimum(av[:, None, 2:], gv[None, :, 2:])
        wh = jnp.clip(rb - lt_, 0)
        inter = wh[..., 0] * wh[..., 1]
        a1 = (av[:, 2] - av[:, 0]) * (av[:, 3] - av[:, 1])
        a2 = (gv[:, 2] - gv[:, 0]) * (gv[:, 3] - gv[:, 1])
        iou = inter / jnp.maximum(a1[:, None] + a2[None] - inter, 1e-9)
        iou = jnp.where(area_ok[None, :], iou, 0.0)
        best_iou = iou.max(1)
        best_gt = iou.argmax(1)
        fg = best_iou >= positive_overlap
        bg = best_iou < negative_overlap
        cls = jnp.where(fg, lv[best_gt] + 1, jnp.where(bg, 0, -1))
        return cls, best_gt, fg

    return forward_op("retinanet_target_assign", impl, [at, gt, gl],
                      differentiable=False)


def generate_proposal_labels(rois, gt_boxes, gt_classes,
                             batch_size_per_im: int = 512,
                             fg_fraction: float = 0.25,
                             fg_thresh: float = 0.5,
                             bg_thresh_hi: float = 0.5,
                             bg_thresh_lo: float = 0.0,
                             bbox_reg_weights=(0.1, 0.1, 0.2, 0.2),
                             name=None):
    """Fast R-CNN head training targets (ref: generate_proposal_labels_op):
    label each roi fg/bg by IoU, emit class targets + encoded box deltas
    + inside weights. Static [R] outputs with rank-quota sampling masks."""
    rt = ensure_tensor(rois)
    gt = ensure_tensor(gt_boxes)
    gc = ensure_tensor(gt_classes)
    w = np.asarray(bbox_reg_weights, np.float32)

    def impl(rv, gv, cv):
        R = rv.shape[0]
        lt_ = jnp.maximum(rv[:, None, :2], gv[None, :, :2])
        rb = jnp.minimum(rv[:, None, 2:], gv[None, :, 2:])
        whi = jnp.clip(rb - lt_, 0)
        inter = whi[..., 0] * whi[..., 1]
        a1 = (rv[:, 2] - rv[:, 0]) * (rv[:, 3] - rv[:, 1])
        a2 = (gv[:, 2] - gv[:, 0]) * (gv[:, 3] - gv[:, 1])
        iou = inter / jnp.maximum(a1[:, None] + a2[None] - inter, 1e-9)
        best = iou.max(1)
        bidx = iou.argmax(1)
        fg = best >= fg_thresh
        bg = (best < bg_thresh_hi) & (best >= bg_thresh_lo)
        n_fg = int(batch_size_per_im * fg_fraction)
        fg_rank = jnp.argsort(jnp.argsort(jnp.where(fg, -best, jnp.inf)))
        fg_keep = fg & (fg_rank < n_fg)
        n_bg = batch_size_per_im - n_fg
        bg_rank = jnp.argsort(jnp.argsort(jnp.where(bg, best, jnp.inf)))
        bg_keep = bg & (bg_rank < n_bg)
        labels = jnp.where(fg_keep, cv[bidx], 0) * fg_keep
        tgt = gv[bidx]
        rw = rv[:, 2] - rv[:, 0] + 1e-6
        rh = rv[:, 3] - rv[:, 1] + 1e-6
        rcx = (rv[:, 0] + rv[:, 2]) / 2
        rcy = (rv[:, 1] + rv[:, 3]) / 2
        gw = jnp.maximum(tgt[:, 2] - tgt[:, 0], 1e-6)
        gh = jnp.maximum(tgt[:, 3] - tgt[:, 1], 1e-6)
        gcx = (tgt[:, 0] + tgt[:, 2]) / 2
        gcy = (tgt[:, 1] + tgt[:, 3]) / 2
        deltas = jnp.stack([(gcx - rcx) / rw / w[0],
                            (gcy - rcy) / rh / w[1],
                            jnp.log(gw / rw) / w[2],
                            jnp.log(gh / rh) / w[3]], -1)
        inside_w = fg_keep[:, None].astype(rv.dtype) * jnp.ones((1, 4))
        return (labels.astype(jnp.int32), deltas * inside_w, inside_w,
                fg_keep, bg_keep)

    return forward_op("generate_proposal_labels", impl, [rt, gt, gc],
                      differentiable=False)


def box_decoder_and_assign(prior_box_t, prior_box_var, target_box,
                           box_score, box_clip_v: float = 4.135, name=None):
    """Decode per-class box deltas then pick each roi's best-class box
    (ref: box_decoder_and_assign_op). ``target_box [R, C*4]``,
    ``box_score [R, C]``; returns (decoded [R, C*4], assigned [R, 4])."""
    pt = ensure_tensor(prior_box_t)
    vt = ensure_tensor(prior_box_var)
    tt = ensure_tensor(target_box)
    st = ensure_tensor(box_score)

    def impl(pv, vv, tv, sv):
        R = pv.shape[0]
        C = sv.shape[1]
        pw = pv[:, 2] - pv[:, 0] + 1
        ph_ = pv[:, 3] - pv[:, 1] + 1
        pcx = pv[:, 0] + pw / 2
        pcy = pv[:, 1] + ph_ / 2
        d = tv.reshape(R, C, 4) * vv.reshape(R, 1, 4)
        dcx = pcx[:, None] + d[..., 0] * pw[:, None]
        dcy = pcy[:, None] + d[..., 1] * ph_[:, None]
        dw = pw[:, None] * jnp.exp(jnp.clip(d[..., 2], -box_clip_v,
                                            box_clip_v))
        dh = ph_[:, None] * jnp.exp(jnp.clip(d[..., 3], -box_clip_v,
                                             box_clip_v))
        dec = jnp.stack([dcx - dw / 2, dcy - dh / 2,
                         dcx + dw / 2 - 1, dcy + dh / 2 - 1], -1)
        best = sv.argmax(1)
        assigned = dec[jnp.arange(R), best]
        return dec.reshape(R, C * 4), assigned

    return forward_op("box_decoder_and_assign", impl, [pt, vt, tt, st],
                      differentiable=False)


def roi_perspective_transform(x, rois, transformed_height: int,
                              transformed_width: int,
                              spatial_scale: float = 1.0, name=None):
    """Perspective-warp quadrilateral ROIs to a fixed rectangle (ref:
    roi_perspective_transform_op, the OCR rectification kernel). rois
    [N, 8] are quad corners (x1..y4, clockwise from top-left); bilinear
    sampling on the homography inverse — all dense gathers."""
    xt = ensure_tensor(x)
    rt = ensure_tensor(rois)
    TH, TW = transformed_height, transformed_width

    def impl(xv, rv):
        B, C, H, W = xv.shape
        N = rv.shape[0]
        q = rv.reshape(N, 4, 2) * spatial_scale

        # homography mapping output rect corners -> quad corners, solved
        # in closed form per roi (vmapped 8x8 solve)
        def homography(quad):
            dst = jnp.asarray([[0, 0], [TW - 1, 0], [TW - 1, TH - 1],
                               [0, TH - 1]], jnp.float32)
            rows = []
            rhs = []
            for i in range(4):
                xd, yd = dst[i, 0], dst[i, 1]
                xs, ys = quad[i, 0], quad[i, 1]
                rows.append(jnp.stack([xd, yd, 1., 0., 0., 0.,
                                       -xs * xd, -xs * yd]))
                rhs.append(xs)
                rows.append(jnp.stack([0., 0., 0., xd, yd, 1.,
                                       -ys * xd, -ys * yd]))
                rhs.append(ys)
            A = jnp.stack(rows)
            b = jnp.stack(rhs)
            h8 = jnp.linalg.solve(A, b)
            return jnp.append(h8, 1.0).reshape(3, 3)

        Hs = jax.vmap(homography)(q)                       # [N, 3, 3]
        yy, xx = jnp.meshgrid(jnp.arange(TH), jnp.arange(TW),
                              indexing="ij")
        ones = jnp.ones_like(xx)
        pts = jnp.stack([xx, yy, ones], 0).reshape(3, -1).astype(
            jnp.float32)                                    # [3, TH*TW]
        src = jnp.einsum("nij,jp->nip", Hs, pts)
        sx = src[:, 0] / jnp.maximum(src[:, 2], 1e-8)
        sy = src[:, 1] / jnp.maximum(src[:, 2], 1e-8)
        x0 = jnp.floor(sx)
        y0 = jnp.floor(sy)
        wx = sx - x0
        wy = sy - y0

        def tap(yi, xi):
            ok = (yi >= 0) & (yi < H) & (xi >= 0) & (xi < W)
            yc = jnp.clip(yi, 0, H - 1).astype(jnp.int32)
            xc = jnp.clip(xi, 0, W - 1).astype(jnp.int32)
            g = xv[0][:, yc, xc]                            # [C, N, P]
            return jnp.where(ok[None], g, 0.0)

        out = (tap(y0, x0) * ((1 - wy) * (1 - wx))[None]
               + tap(y0, x0 + 1) * ((1 - wy) * wx)[None]
               + tap(y0 + 1, x0) * (wy * (1 - wx))[None]
               + tap(y0 + 1, x0 + 1) * (wy * wx)[None])
        return out.transpose(1, 0, 2).reshape(N, C, TH, TW)

    return forward_op("roi_perspective_transform", impl, [xt, rt])


__all__ += ["rpn_target_assign", "retinanet_target_assign",
            "generate_proposal_labels", "box_decoder_and_assign",
            "roi_perspective_transform"]
for _n in ["rpn_target_assign", "retinanet_target_assign",
           "generate_proposal_labels", "box_decoder_and_assign",
           "roi_perspective_transform"]:
    _f = globals()[_n]
    register_op(_n, _f, (_f.__doc__ or "").strip().split("\n")[0],
                public=_f)
