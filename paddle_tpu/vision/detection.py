"""Detection family: FPN neck + PP-YOLOE-style decoupled head + static NMS.

Capability target: the reference ecosystem's PP-YOLOE detector
(PaddleDetection ``ppdet/modeling``: CSPRepResNet/MobileNet backbones, a
top-down FPN neck, the ET-head with decoupled cls/reg branches, TAL-style
assignment, and multiclass NMS — BASELINE.json configs[2] names PP-OCRv4 /
PP-YOLOE as capability targets).

TPU redesign, not a translation:

* **Anchor-free point head.** Each FPN level predicts per-pixel class
  logits and (l, t, r, b) distances (the PP-YOLOE/FCOS formulation); all
  shapes are static — levels are concatenated to a fixed total anchor
  count decided by the input resolution.
* **Static-shape NMS** (the honest TPU formulation of the reference's
  dynamic multiclass_nms): top-K pre-selection with ``lax.top_k``, then
  greedy suppression as a sequential mask update over the K candidates
  (K fixed, outputs padded with validity flags — no data-dependent
  shapes anywhere, runs inside jit).
* **Center-based assignment** for training (FCOS-style center sampling —
  the static-shape-friendly simplification of TAL): positives are points
  whose location falls in a gt center region on the level whose scale
  range matches the box size; loss = varifocal-style BCE on cls + GIoU on
  boxes.
"""

from __future__ import annotations

import math
from typing import List, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..core.tensor import Tensor, _wrap_value
from ..core.dispatch import forward_op
from ..nn import BatchNorm2D, Conv2D, Identity, ReLU, Sequential, SiLU
from ..nn.layer import Layer
from .models import ConvBNLayer, mobilenet_v3_large, mobilenet_v3_small

__all__ = ["FPN", "PPYOLOEHead", "PPYOLOEDetector", "ppyoloe_mbv3",
           "static_nms", "detection_loss"]


# ---------------------------------------------------------------------------
# neck
# ---------------------------------------------------------------------------

class FPN(Layer):
    """Top-down feature pyramid (ref: ppdet necks — lateral 1x1 + output
    3x3, nearest-neighbor upsampling)."""

    def __init__(self, in_channels: Sequence[int], out_channel: int = 96):
        super().__init__()
        self.out_channel = out_channel
        self.laterals = Sequential(*[Conv2D(c, out_channel, 1)
                                     for c in in_channels])
        self.outputs = Sequential(*[
            ConvBNLayer(out_channel, out_channel, 3, act="relu")
            for _ in in_channels])

    def forward(self, feats: List):
        lats = [l(f) for l, f in zip(self.laterals, feats)]
        # top-down: upsample deeper level and add
        out = [lats[-1]]
        for i in range(len(lats) - 2, -1, -1):
            deeper = out[0]
            B, C, H, W = lats[i].shape

            def up(v, H=H, W=W):
                return jax.image.resize(v, v.shape[:2] + (H, W),
                                        method="nearest")
            upd = forward_op("fpn_upsample", up, [deeper])
            out.insert(0, lats[i] + upd)
        return [o_layer(o) for o_layer, o in zip(self.outputs, out)]


# ---------------------------------------------------------------------------
# head
# ---------------------------------------------------------------------------

class PPYOLOEHead(Layer):
    """Decoupled per-level head: a small cls branch and a reg branch
    (ref: ppdet PPYOLOEHead ET-head, simplified to direct ltrb)."""

    def __init__(self, in_channel: int, num_classes: int,
                 num_levels: int = 3, stacked: int = 2):
        super().__init__()
        self.num_classes = num_classes
        self.num_levels = num_levels

        def branch():
            layers = []
            for _ in range(stacked):
                layers.append(ConvBNLayer(in_channel, in_channel, 3,
                                          act="relu"))
            return Sequential(*layers)

        self.cls_branches = Sequential(*[branch() for _ in range(num_levels)])
        self.reg_branches = Sequential(*[branch() for _ in range(num_levels)])
        self.cls_preds = Sequential(*[Conv2D(in_channel, num_classes, 3,
                                             padding=1)
                                      for _ in range(num_levels)])
        self.reg_preds = Sequential(*[Conv2D(in_channel, 4, 3, padding=1)
                                      for _ in range(num_levels)])

    def forward(self, feats: List):
        """-> (cls_logits [B, A, C], ltrb [B, A, 4]) with A = sum of
        per-level H*W (static)."""
        from ..ops.manipulation import concat, reshape, transpose
        cls_all, reg_all = [], []
        for i, f in enumerate(feats):
            c = self.cls_preds[i](self.cls_branches[i](f))
            r = self.reg_preds[i](self.reg_branches[i](f))
            B, C, H, W = c.shape
            cls_all.append(reshape(transpose(c, [0, 2, 3, 1]),
                                   [B, H * W, C]))
            reg_all.append(reshape(transpose(r, [0, 2, 3, 1]),
                                   [B, H * W, 4]))
        return concat(cls_all, axis=1), concat(reg_all, axis=1)


def _level_points(hw_list, strides):
    """Anchor-point centers [(x, y)] per level, concatenated [A, 2], plus
    per-point stride [A]."""
    pts, sts = [], []
    for (h, w), s in zip(hw_list, strides):
        ys, xs = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
        p = np.stack([(xs + 0.5) * s, (ys + 0.5) * s], -1).reshape(-1, 2)
        pts.append(p)
        sts.append(np.full((h * w,), s, np.float32))
    return (jnp.asarray(np.concatenate(pts).astype(np.float32)),
            jnp.asarray(np.concatenate(sts)))


class PPYOLOEDetector(Layer):
    """backbone (MobileNetV3 features) -> FPN -> decoupled head.

    ``forward(images)`` -> (cls_logits [B, A, C], boxes_xyxy [B, A, 4]);
    training uses :func:`detection_loss`, inference decodes + static NMS.
    """

    STRIDES = (8, 16, 32)

    def __init__(self, num_classes: int = 80, backbone: str = "small",
                 neck_channel: int = 96, image_size: int = 320):
        super().__init__()
        self.num_classes = num_classes
        self.image_size = image_size
        mk = (mobilenet_v3_small if backbone == "small"
              else mobilenet_v3_large)
        self.backbone = mk(feature_only=True)
        # channels of C3/C4/C5 discovered from the config cuts
        cfg = self.backbone._config
        cuts = self.backbone._feature_cuts()
        from .models import _make_divisible
        chans = [_make_divisible(cfg[i][2] * self.backbone._scale)
                 for i in cuts]
        self.neck = FPN(chans, neck_channel)
        self.head = PPYOLOEHead(neck_channel, num_classes)
        self._hw = [(image_size // s, image_size // s) for s in self.STRIDES]

    def anchor_points(self):
        return _level_points(self._hw, self.STRIDES)

    def forward(self, images):
        feats = self.backbone(images)
        feats = self.neck(feats)
        cls_logits, ltrb = self.head(feats)
        pts, strides = self.anchor_points()

        def decode(lv, pv, sv):
            d = jax.nn.softplus(lv) * sv[None, :, None]   # positive dists
            x, y = pv[None, :, 0:1], pv[None, :, 1:2]
            return jnp.concatenate(
                [x - d[..., 0:1], y - d[..., 1:2],
                 x + d[..., 2:3], y + d[..., 3:4]], -1)
        boxes = forward_op("detect_decode", decode, [ltrb, pts, strides])
        return cls_logits, boxes


def ppyoloe_mbv3(num_classes: int = 80, image_size: int = 320,
                 backbone: str = "small"):
    return PPYOLOEDetector(num_classes=num_classes, image_size=image_size,
                           backbone=backbone)


# ---------------------------------------------------------------------------
# loss (functional; static-shape center assignment)
# ---------------------------------------------------------------------------

def detection_loss(cls_logits, boxes, gt_boxes, gt_labels, points, strides,
                   num_classes: int, center_radius: float = 1.5):
    """Center-sampled assignment + BCE cls + GIoU box loss.

    ``gt_boxes [B, G, 4]`` xyxy (padded with zeros), ``gt_labels [B, G]``
    (-1 = padding). A point is positive for the first gt whose center
    region (radius ``center_radius * stride``) contains it AND whose box
    contains it. All shapes static.
    """
    from ..core.tensor import to_tensor
    cl_t = cls_logits if isinstance(cls_logits, Tensor) else \
        to_tensor(cls_logits)
    bx_t = boxes if isinstance(boxes, Tensor) else to_tensor(boxes)
    gb_t = gt_boxes if isinstance(gt_boxes, Tensor) else to_tensor(gt_boxes)
    gl_t = gt_labels if isinstance(gt_labels, Tensor) else \
        to_tensor(gt_labels)

    def impl(cl, bx, gb, gl):
        B, A, C = cl.shape
        G = gb.shape[1]
        px, py = points[:, 0], points[:, 1]                      # [A]
        cx = (gb[..., 0] + gb[..., 2]) / 2                       # [B, G]
        cy = (gb[..., 1] + gb[..., 3]) / 2
        rad = center_radius * strides[None, :, None]             # [1, A, 1]
        in_center = ((jnp.abs(px[None, :, None] - cx[:, None, :]) < rad) &
                     (jnp.abs(py[None, :, None] - cy[:, None, :]) < rad))
        in_box = ((px[None, :, None] >= gb[:, None, :, 0]) &
                  (px[None, :, None] <= gb[:, None, :, 2]) &
                  (py[None, :, None] >= gb[:, None, :, 1]) &
                  (py[None, :, None] <= gb[:, None, :, 3]))
        valid_gt = (gl >= 0)[:, None, :]                         # [B, 1, G]
        pos_mat = in_center & in_box & valid_gt                  # [B, A, G]
        assigned = jnp.argmax(pos_mat, axis=-1)                  # first gt
        is_pos = pos_mat.any(-1)                                 # [B, A]

        # gather each point's assigned gt row: [B, A, 4]
        tgt_box = jnp.take_along_axis(
            gb[:, None].repeat(A, 1).reshape(B * A, G, 4),
            assigned.reshape(B * A, 1, 1).repeat(4, -1), 1
        ).reshape(B, A, 4)
        tgt_lab = jnp.take_along_axis(gl, assigned.reshape(B, A), 1)

        # cls target: one-hot at the assigned label for positives
        onehot = jax.nn.one_hot(jnp.clip(tgt_lab, 0), C) * \
            is_pos[..., None]
        clf = cl.astype(jnp.float32)
        bce = jnp.maximum(clf, 0) - clf * onehot + \
            jnp.log1p(jnp.exp(-jnp.abs(clf)))
        n_pos = jnp.maximum(is_pos.sum(), 1)
        cls_loss = bce.sum() / n_pos

        # GIoU on positives
        bxf = bx.astype(jnp.float32)
        ix1 = jnp.maximum(bxf[..., 0], tgt_box[..., 0])
        iy1 = jnp.maximum(bxf[..., 1], tgt_box[..., 1])
        ix2 = jnp.minimum(bxf[..., 2], tgt_box[..., 2])
        iy2 = jnp.minimum(bxf[..., 3], tgt_box[..., 3])
        inter = jnp.clip(ix2 - ix1, 0) * jnp.clip(iy2 - iy1, 0)
        area_p = jnp.clip(bxf[..., 2] - bxf[..., 0], 0) * \
            jnp.clip(bxf[..., 3] - bxf[..., 1], 0)
        area_g = jnp.clip(tgt_box[..., 2] - tgt_box[..., 0], 0) * \
            jnp.clip(tgt_box[..., 3] - tgt_box[..., 1], 0)
        union = area_p + area_g - inter
        iou = inter / jnp.maximum(union, 1e-9)
        ex1 = jnp.minimum(bxf[..., 0], tgt_box[..., 0])
        ey1 = jnp.minimum(bxf[..., 1], tgt_box[..., 1])
        ex2 = jnp.maximum(bxf[..., 2], tgt_box[..., 2])
        ey2 = jnp.maximum(bxf[..., 3], tgt_box[..., 3])
        enclose = jnp.maximum((ex2 - ex1) * (ey2 - ey1), 1e-9)
        giou = iou - (enclose - union) / enclose
        box_loss = (jnp.where(is_pos, 1.0 - giou, 0.0).sum() / n_pos)
        return cls_loss + 2.0 * box_loss

    return forward_op("detection_loss", impl,
                      [cl_t, bx_t, gb_t, gl_t])


# ---------------------------------------------------------------------------
# static NMS
# ---------------------------------------------------------------------------

def static_nms(boxes, scores, *, top_k: int = 100,
               score_threshold: float = 0.05, iou_threshold: float = 0.6):
    """Single-class static-shape NMS: top-K pre-select + greedy IoU
    suppression with fixed shapes (the TPU formulation of the reference's
    multiclass_nms; dynamic result counts become a validity mask).

    ``boxes [A, 4]``, ``scores [A]`` ->
    ``(boxes [K, 4], scores [K], keep [K] bool)`` — suppressed/sub-threshold
    slots have ``keep=False``.
    """
    b = boxes._value if isinstance(boxes, Tensor) else jnp.asarray(boxes)
    s = scores._value if isinstance(scores, Tensor) else jnp.asarray(scores)

    def impl(b, s):
        K = min(top_k, s.shape[0])
        top_s, idx = lax.top_k(s, K)
        top_b = b[idx]
        x1, y1, x2, y2 = (top_b[:, i] for i in range(4))
        area = jnp.clip(x2 - x1, 0) * jnp.clip(y2 - y1, 0)
        ix1 = jnp.maximum(x1[:, None], x1[None, :])
        iy1 = jnp.maximum(y1[:, None], y1[None, :])
        ix2 = jnp.minimum(x2[:, None], x2[None, :])
        iy2 = jnp.minimum(y2[:, None], y2[None, :])
        inter = jnp.clip(ix2 - ix1, 0) * jnp.clip(iy2 - iy1, 0)
        iou = inter / jnp.maximum(area[:, None] + area[None, :] - inter,
                                  1e-9)

        def body(i, keep):
            # if candidate i is alive, kill later candidates over threshold
            sup = (iou[i] > iou_threshold) & (jnp.arange(K) > i)
            return jnp.where(keep[i], keep & ~sup, keep)

        keep = lax.fori_loop(0, K, body,
                             top_s > score_threshold)
        return top_b, top_s, keep

    return forward_op("static_nms", impl, [b, s], differentiable=False)


def _register():
    from ..core.dispatch import register_op
    for n, f in (("static_nms", static_nms),
                 ("detection_loss", detection_loss)):
        register_op(n, f, (f.__doc__ or "").strip().split("\n")[0],
                    public=f)


_register()
