"""Vision model zoo (ref: ``python/paddle/vision/models/``).

ResNet family (BasicBlock/BottleneckBlock, the baseline-bench config),
VGG, LeNet. NCHW layout (reference default). ``pretrained=True`` raises —
zero-egress environment, no weight downloads.
"""

from __future__ import annotations

from typing import List, Optional, Type, Union

from ..nn import (AdaptiveAvgPool2D, BatchNorm2D, Conv2D, Dropout, Linear,
                  MaxPool2D, ReLU, Sequential)
from ..nn.layer import Layer

__all__ = ["ResNet", "BasicBlock", "BottleneckBlock", "resnet18", "resnet34",
           "resnet50", "resnet101", "resnet152", "VGG", "vgg11", "vgg13",
           "vgg16", "vgg19", "LeNet"]


def _no_pretrained(flag):
    if flag:
        raise RuntimeError(
            "pretrained=True needs weight downloads; this environment is "
            "hermetic (zero egress) — load local weights with "
            "model.set_state_dict(paddle.load(path)) instead")


class BasicBlock(Layer):
    expansion = 1

    def __init__(self, inplanes, planes, stride=1, downsample=None,
                 groups=1, base_width=64, dilation=1, norm_layer=None):
        super().__init__()
        norm_layer = norm_layer or BatchNorm2D
        self.conv1 = Conv2D(inplanes, planes, 3, stride=stride, padding=1,
                            bias_attr=False)
        self.bn1 = norm_layer(planes)
        self.relu = ReLU()
        self.conv2 = Conv2D(planes, planes, 3, padding=1, bias_attr=False)
        self.bn2 = norm_layer(planes)
        self.downsample = downsample
        self.stride = stride

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class BottleneckBlock(Layer):
    expansion = 4

    def __init__(self, inplanes, planes, stride=1, downsample=None,
                 groups=1, base_width=64, dilation=1, norm_layer=None):
        super().__init__()
        norm_layer = norm_layer or BatchNorm2D
        width = int(planes * (base_width / 64.0)) * groups
        self.conv1 = Conv2D(inplanes, width, 1, bias_attr=False)
        self.bn1 = norm_layer(width)
        self.conv2 = Conv2D(width, width, 3, stride=stride, padding=dilation,
                            groups=groups, dilation=dilation, bias_attr=False)
        self.bn2 = norm_layer(width)
        self.conv3 = Conv2D(width, planes * self.expansion, 1, bias_attr=False)
        self.bn3 = norm_layer(planes * self.expansion)
        self.relu = ReLU()
        self.downsample = downsample
        self.stride = stride

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class ResNet(Layer):
    """ref: vision.models.ResNet (depth via block/layers lists)."""

    def __init__(self, block: Type[Union[BasicBlock, BottleneckBlock]],
                 depth_or_layers, num_classes: int = 1000,
                 with_pool: bool = True, groups: int = 1,
                 width: int = 64):
        super().__init__()
        layer_cfg = {18: [2, 2, 2, 2], 34: [3, 4, 6, 3], 50: [3, 4, 6, 3],
                     101: [3, 4, 23, 3], 152: [3, 8, 36, 3]}
        layers = (layer_cfg[depth_or_layers]
                  if isinstance(depth_or_layers, int) else list(depth_or_layers))
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.groups = groups
        self.base_width = width
        self.inplanes = 64
        self.dilation = 1

        self.conv1 = Conv2D(3, self.inplanes, 7, stride=2, padding=3,
                            bias_attr=False)
        self.bn1 = BatchNorm2D(self.inplanes)
        self.relu = ReLU()
        self.maxpool = MaxPool2D(kernel_size=3, stride=2, padding=1)
        self.layer1 = self._make_layer(block, 64, layers[0])
        self.layer2 = self._make_layer(block, 128, layers[1], stride=2)
        self.layer3 = self._make_layer(block, 256, layers[2], stride=2)
        self.layer4 = self._make_layer(block, 512, layers[3], stride=2)
        if with_pool:
            self.avgpool = AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = Linear(512 * block.expansion, num_classes)

    def _make_layer(self, block, planes, blocks, stride=1):
        downsample = None
        if stride != 1 or self.inplanes != planes * block.expansion:
            downsample = Sequential(
                Conv2D(self.inplanes, planes * block.expansion, 1,
                       stride=stride, bias_attr=False),
                BatchNorm2D(planes * block.expansion))
        layers = [block(self.inplanes, planes, stride, downsample,
                        self.groups, self.base_width)]
        self.inplanes = planes * block.expansion
        for _ in range(1, blocks):
            layers.append(block(self.inplanes, planes, groups=self.groups,
                                base_width=self.base_width))
        return Sequential(*layers)

    def forward(self, x):
        x = self.maxpool(self.relu(self.bn1(self.conv1(x))))
        x = self.layer4(self.layer3(self.layer2(self.layer1(x))))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            from ..ops.manipulation import flatten
            x = self.fc(flatten(x, 1))
        return x


def _resnet(block, depth, pretrained, **kwargs):
    _no_pretrained(pretrained)
    return ResNet(block, depth, **kwargs)


def resnet18(pretrained=False, **kwargs):
    return _resnet(BasicBlock, 18, pretrained, **kwargs)


def resnet34(pretrained=False, **kwargs):
    return _resnet(BasicBlock, 34, pretrained, **kwargs)


def resnet50(pretrained=False, **kwargs):
    return _resnet(BottleneckBlock, 50, pretrained, **kwargs)


def resnet101(pretrained=False, **kwargs):
    return _resnet(BottleneckBlock, 101, pretrained, **kwargs)


def resnet152(pretrained=False, **kwargs):
    return _resnet(BottleneckBlock, 152, pretrained, **kwargs)


class VGG(Layer):
    """ref: vision.models.VGG (features + 4096-wide classifier head)."""

    def __init__(self, features: Layer, num_classes: int = 1000,
                 with_pool: bool = True):
        super().__init__()
        self.features = features
        self.num_classes = num_classes
        self.with_pool = with_pool
        if with_pool:
            self.avgpool = AdaptiveAvgPool2D((7, 7))
        if num_classes > 0:
            self.classifier = Sequential(
                Linear(512 * 7 * 7, 4096), ReLU(), Dropout(),
                Linear(4096, 4096), ReLU(), Dropout(),
                Linear(4096, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            from ..ops.manipulation import flatten
            x = self.classifier(flatten(x, 1))
        return x


_VGG_CFG = {
    11: [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    13: [64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M",
         512, 512, "M"],
    16: [64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512, "M",
         512, 512, 512, "M"],
    19: [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
         512, 512, 512, 512, "M", 512, 512, 512, 512, "M"],
}


def _vgg_features(cfg, batch_norm=False):
    layers: List[Layer] = []
    in_c = 3
    for v in cfg:
        if v == "M":
            layers.append(MaxPool2D(kernel_size=2, stride=2))
        else:
            layers.append(Conv2D(in_c, v, 3, padding=1))
            if batch_norm:
                layers.append(BatchNorm2D(v))
            layers.append(ReLU())
            in_c = v
    return Sequential(*layers)


def _vgg(depth, pretrained, batch_norm=False, **kwargs):
    _no_pretrained(pretrained)
    return VGG(_vgg_features(_VGG_CFG[depth], batch_norm), **kwargs)


def vgg11(pretrained=False, batch_norm=False, **kwargs):
    return _vgg(11, pretrained, batch_norm, **kwargs)


def vgg13(pretrained=False, batch_norm=False, **kwargs):
    return _vgg(13, pretrained, batch_norm, **kwargs)


def vgg16(pretrained=False, batch_norm=False, **kwargs):
    return _vgg(16, pretrained, batch_norm, **kwargs)


def vgg19(pretrained=False, batch_norm=False, **kwargs):
    return _vgg(19, pretrained, batch_norm, **kwargs)


class LeNet(Layer):
    """ref: vision.models.LeNet (MNIST-scale smoke model)."""

    def __init__(self, num_classes: int = 10):
        super().__init__()
        self.num_classes = num_classes
        self.features = Sequential(
            Conv2D(1, 6, 3, stride=1, padding=1), ReLU(),
            MaxPool2D(2, 2),
            Conv2D(6, 16, 5, stride=1, padding=0), ReLU(),
            MaxPool2D(2, 2))
        if num_classes > 0:
            self.fc = Sequential(
                Linear(400, 120), Linear(120, 84), Linear(84, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.num_classes > 0:
            from ..ops.manipulation import flatten
            x = self.fc(flatten(x, 1))
        return x


# ---------------------------------------------------------------------------
# MobileNetV3 (ref: vision.models.MobileNetV3Small/Large — the backbone the
# detection family rides on; PP-LCNet/PP-YOLOE ecosystem target)
# ---------------------------------------------------------------------------

from ..nn import Hardsigmoid, Hardswish, Identity, Sigmoid  # noqa: E402


def _make_divisible(v, divisor=8, min_value=None):
    min_value = min_value or divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class ConvBNLayer(Layer):
    def __init__(self, cin, cout, k, stride=1, groups=1, act=None):
        super().__init__()
        self.conv = Conv2D(cin, cout, k, stride=stride,
                           padding=(k - 1) // 2, groups=groups,
                           bias_attr=False)
        self.bn = BatchNorm2D(cout)
        self.act = ({"relu": ReLU, "hardswish": Hardswish}.get(act) or
                    Identity)()

    def forward(self, x):
        return self.act(self.bn(self.conv(x)))


class SEModule(Layer):
    def __init__(self, channel, reduction=4):
        super().__init__()
        self.avg_pool = AdaptiveAvgPool2D(1)
        self.conv1 = Conv2D(channel, channel // reduction, 1)
        self.relu = ReLU()
        self.conv2 = Conv2D(channel // reduction, channel, 1)
        self.hs = Hardsigmoid()

    def forward(self, x):
        s = self.hs(self.conv2(self.relu(self.conv1(self.avg_pool(x)))))
        return x * s


class InvertedResidual(Layer):
    def __init__(self, cin, mid, cout, k, stride, use_se, act):
        super().__init__()
        self.use_res = stride == 1 and cin == cout
        self.expand = (ConvBNLayer(cin, mid, 1, act=act)
                       if mid != cin else Identity())
        self.dw = ConvBNLayer(mid, mid, k, stride=stride, groups=mid,
                              act=act)
        self.se = SEModule(mid) if use_se else Identity()
        self.pw = ConvBNLayer(mid, cout, 1, act=None)

    def forward(self, x):
        y = self.pw(self.se(self.dw(self.expand(x))))
        return x + y if self.use_res else y


# (kernel, exp, out, se, act, stride) per block — the reference configs
_MBV3_SMALL = [
    (3, 16, 16, True, "relu", 2), (3, 72, 24, False, "relu", 2),
    (3, 88, 24, False, "relu", 1), (5, 96, 40, True, "hardswish", 2),
    (5, 240, 40, True, "hardswish", 1), (5, 240, 40, True, "hardswish", 1),
    (5, 120, 48, True, "hardswish", 1), (5, 144, 48, True, "hardswish", 1),
    (5, 288, 96, True, "hardswish", 2), (5, 576, 96, True, "hardswish", 1),
    (5, 576, 96, True, "hardswish", 1),
]
_MBV3_LARGE = [
    (3, 16, 16, False, "relu", 1), (3, 64, 24, False, "relu", 2),
    (3, 72, 24, False, "relu", 1), (5, 72, 40, True, "relu", 2),
    (5, 120, 40, True, "relu", 1), (5, 120, 40, True, "relu", 1),
    (3, 240, 80, False, "hardswish", 2), (3, 200, 80, False, "hardswish", 1),
    (3, 184, 80, False, "hardswish", 1), (3, 184, 80, False, "hardswish", 1),
    (3, 480, 112, True, "hardswish", 1), (3, 672, 112, True, "hardswish", 1),
    (5, 672, 160, True, "hardswish", 2), (5, 960, 160, True, "hardswish", 1),
    (5, 960, 160, True, "hardswish", 1),
]


class MobileNetV3(Layer):
    """ref: vision.models.MobileNetV3Small/Large. ``feature_only=True``
    returns the three detection-scale feature maps (stride 8/16/32) for
    FPN necks (vision/detection.py)."""

    def __init__(self, config, last_channels, scale=1.0,
                 num_classes=1000, feature_only=False):
        super().__init__()
        self.feature_only = feature_only
        self.num_classes = num_classes
        cin = _make_divisible(16 * scale)
        self.stem = ConvBNLayer(3, cin, 3, stride=2, act="hardswish")
        blocks = []
        self._feat_idx = []
        strides_seen = 2
        for i, (k, exp, cout, se, act, stride) in enumerate(config):
            mid = _make_divisible(exp * scale)
            co = _make_divisible(cout * scale)
            blocks.append(InvertedResidual(cin, mid, co, k, stride, se, act))
            cin = co
            strides_seen *= stride
            # record the LAST block of each stride level (C3/C4/C5)
        self.blocks = Sequential(*blocks)
        self._config = config
        self._scale = scale
        self.out_channels = cin
        if not feature_only:
            mid = _make_divisible(last_channels * scale)
            self.last_conv = ConvBNLayer(cin, mid, 1, act="hardswish")
            self.pool = AdaptiveAvgPool2D(1)
            self.fc = Linear(mid, num_classes)

    def _feature_cuts(self):
        """Indices after which stride increases (C3=stride8 ... C5=32)."""
        cuts = []
        stride = 2  # stem
        for i, (_, _, _, _, _, s) in enumerate(self._config):
            if s == 2:
                stride *= s
                if stride in (16, 32):  # the block BEFORE this one closes
                    cuts.append(i - 1)  # the previous level
        cuts.append(len(self._config) - 1)
        return cuts[-3:]

    def forward(self, x):
        x = self.stem(x)
        if not self.feature_only:
            x = self.blocks(x)
            x = self.last_conv(x)
            x = self.pool(x)
            from ..ops.manipulation import flatten
            return self.fc(flatten(x, 1))
        feats = []
        cuts = set(self._feature_cuts())
        for i, blk in enumerate(self.blocks):
            x = blk(x)
            if i in cuts:
                feats.append(x)
        return feats


def mobilenet_v3_small(pretrained=False, scale=1.0, **kwargs):
    _no_pretrained(pretrained)
    return MobileNetV3(_MBV3_SMALL, 1024, scale=scale, **kwargs)


def mobilenet_v3_large(pretrained=False, scale=1.0, **kwargs):
    _no_pretrained(pretrained)
    return MobileNetV3(_MBV3_LARGE, 1280, scale=scale, **kwargs)


class AlexNet(Layer):
    """ref: vision.models.AlexNet."""

    def __init__(self, num_classes=1000, dropout=0.5):
        super().__init__()
        self.features = Sequential(
            Conv2D(3, 64, 11, stride=4, padding=2), ReLU(), MaxPool2D(3, 2),
            Conv2D(64, 192, 5, padding=2), ReLU(), MaxPool2D(3, 2),
            Conv2D(192, 384, 3, padding=1), ReLU(),
            Conv2D(384, 256, 3, padding=1), ReLU(),
            Conv2D(256, 256, 3, padding=1), ReLU(), MaxPool2D(3, 2))
        self.pool = AdaptiveAvgPool2D(6)
        self.classifier = Sequential(
            Dropout(dropout), Linear(256 * 36, 4096), ReLU(),
            Dropout(dropout), Linear(4096, 4096), ReLU(),
            Linear(4096, num_classes))

    def forward(self, x):
        x = self.pool(self.features(x))
        from ..ops.manipulation import flatten
        return self.classifier(flatten(x, 1))


def alexnet(pretrained=False, **kwargs):
    _no_pretrained(pretrained)
    return AlexNet(**kwargs)


__all__ += ["MobileNetV3", "mobilenet_v3_small", "mobilenet_v3_large",
            "AlexNet", "alexnet"]
