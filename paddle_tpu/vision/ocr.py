"""OCR text recognition: CRNN backbone + BiLSTM neck + CTC head.

Capability target: the reference ecosystem's PP-OCR recognition stack
(PaddleOCR ``ppocr/modeling``: MobileNet/ResNet rec backbones, the
SequenceEncoder rnn neck, CTCHead; BASELINE.json configs[2] names PP-OCRv4
as a capability target). The detection side of PP-OCR is the
``vision/detection.py`` family; this module is the recognizer.

TPU notes: the conv stack pools height to 1 so the sequence axis is the
image WIDTH (static); the BiLSTM neck compiles as lax.scan per direction;
CTC loss is the in-graph alpha recursion (`nn.functional.ctc_loss`);
greedy CTC decode (collapse repeats, drop blanks) is a static-shape scan
emitting a fixed-width token buffer + validity count.
"""

from __future__ import annotations

from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.dispatch import forward_op
from ..nn import LSTM, BatchNorm2D, Conv2D, Linear, MaxPool2D, ReLU, Sequential
from ..nn.layer import Layer

__all__ = ["CRNN", "crnn_mobilenet", "ctc_greedy_decode"]


class _ConvBlock(Layer):
    def __init__(self, cin, cout, pool):
        super().__init__()
        self.conv = Conv2D(cin, cout, 3, padding=1, bias_attr=False)
        self.bn = BatchNorm2D(cout)
        self.act = ReLU()
        self.pool = MaxPool2D(pool, pool) if pool else None

    def forward(self, x):
        x = self.act(self.bn(self.conv(x)))
        return self.pool(x) if self.pool else x


class CRNN(Layer):
    """Conv stack (H -> 1) + BiLSTM neck + CTC projection head.

    ``forward(images [B, C, H, W])`` -> logits ``[T, B, num_classes]``
    (paddle CTC layout, T = W / 4); class 0 is the CTC blank.
    """

    def __init__(self, num_classes: int, in_channels: int = 3,
                 image_height: int = 32, hidden_size: int = 96):
        super().__init__()
        if image_height % 16:
            raise ValueError(f"image_height {image_height} must be a "
                             "multiple of 16 (four height-halvings)")
        self.num_classes = num_classes
        # pools: (2,2) (2,2) -> T = W/4; then height-only (2,1) pools
        self.features = Sequential(
            _ConvBlock(in_channels, 32, (2, 2)),
            _ConvBlock(32, 64, (2, 2)),
            _ConvBlock(64, 96, (2, 1)),
            _ConvBlock(96, 96, (2, 1)),
        )
        self._feat_h = image_height // 16
        self.neck = LSTM(96 * self._feat_h, hidden_size,
                         direction="bidirectional")
        self.head = Linear(2 * hidden_size, num_classes)

    def forward(self, x):
        from ..ops.manipulation import reshape, transpose
        f = self.features(x)                       # [B, C, H/16, W/4]
        B, C, H, W = f.shape
        seq = reshape(transpose(f, [0, 3, 1, 2]), [B, W, C * H])
        out, _ = self.neck(seq)                    # [B, T, 2*hidden]
        logits = self.head(out)                    # [B, T, num_classes]
        return transpose(logits, [1, 0, 2])        # [T, B, C] (CTC layout)


def crnn_mobilenet(num_classes: int, **kw) -> CRNN:
    """PP-OCR-rec-shaped factory (conv backbone scaled for mobile)."""
    return CRNN(num_classes, **kw)


def ctc_greedy_decode(logits, blank: int = 0, merge_repeats: bool = True):
    """Greedy CTC decoding with STATIC shapes: argmax per step, collapse
    repeats, drop blanks — emitted as a fixed-width ``[B, T]`` token buffer
    (left-aligned, padded with ``blank``) plus per-row valid counts.

    ``logits [T, B, C]`` -> ``(tokens [B, T], lengths [B])``; jit-safe (the
    scatter of kept tokens is a sort by emit-index, not a dynamic gather).
    """
    v = logits._value if isinstance(logits, Tensor) else jnp.asarray(logits)

    def impl(lp):
        T, B, C = lp.shape
        ids = jnp.argmax(lp, axis=-1).T               # [B, T]
        if merge_repeats:
            prev = jnp.concatenate(
                [jnp.full((B, 1), -1, ids.dtype), ids[:, :-1]], axis=1)
            keep = (ids != blank) & (ids != prev)
        else:
            keep = ids != blank
        # left-align kept tokens: emit position = cumsum(keep) - 1; a
        # stable argsort over (not kept, position) pulls kept tokens first
        order = jnp.argsort(jnp.where(keep, 0, 1), axis=1, stable=True)
        toks = jnp.take_along_axis(ids, order, axis=1)
        lengths = keep.sum(axis=1)
        mask = jnp.arange(T)[None, :] < lengths[:, None]
        return jnp.where(mask, toks, blank), lengths

    return forward_op("ctc_greedy_decode", impl, [v],
                      differentiable=False)
