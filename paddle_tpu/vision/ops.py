"""``paddle.vision.ops`` parity: detection primitives.

Parity target: ``python/paddle/vision/ops.py`` in the reference (nms,
roi_align, roi_pool, box_coder — the PaddleDetection post-processing
kernels). TPU lowering notes: roi_align/roi_pool are vectorized bilinear /
max gathers (one XLA program, static shapes given static output_size); nms
is greedy suppression over a precomputed IoU matrix — O(N^2) on device,
which beats serializing N kernel launches for the N found in practice.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..core.dispatch import register_op
from ..ops._helpers import Tensor, ensure_tensor, forward_op

__all__ = ["nms", "box_iou", "roi_align", "roi_pool", "box_coder"]


def box_iou(boxes1, boxes2, name=None):
    """Pairwise IoU of two [N,4]/[M,4] xyxy box sets -> [N, M]."""
    a = ensure_tensor(boxes1)
    b = ensure_tensor(boxes2)

    def impl(x, y):
        area1 = (x[:, 2] - x[:, 0]) * (x[:, 3] - x[:, 1])
        area2 = (y[:, 2] - y[:, 0]) * (y[:, 3] - y[:, 1])
        lt = jnp.maximum(x[:, None, :2], y[None, :, :2])
        rb = jnp.minimum(x[:, None, 2:], y[None, :, 2:])
        wh = jnp.clip(rb - lt, 0)
        inter = wh[..., 0] * wh[..., 1]
        return inter / jnp.maximum(area1[:, None] + area2[None, :] - inter,
                                   1e-10)

    return forward_op("box_iou", impl, [a, b])


def nms(boxes, iou_threshold: float = 0.3, scores=None,
        category_idxs=None, categories=None, top_k: Optional[int] = None,
        name=None):
    """Greedy non-maximum suppression; returns kept indices into ``boxes``
    ordered by descending score (ref: paddle.vision.ops.nms). With
    ``category_idxs``/``categories``, suppression is per-category
    (batched-nms offset trick). Eager-only output shape (data dependent)."""
    b = ensure_tensor(boxes)
    n = int(b.shape[0])
    if scores is None:
        sv = jnp.arange(n, 0, -1, dtype=jnp.float32)  # keep input order
    else:
        sv = ensure_tensor(scores)._value.astype(jnp.float32)
    bv = b._value.astype(jnp.float32)
    if category_idxs is not None:
        # shift each category into a disjoint coordinate range so cross-
        # category boxes never overlap (the standard batched-nms trick)
        cv = ensure_tensor(category_idxs)._value.astype(jnp.float32)
        span = jnp.max(bv) - jnp.min(bv) + 1.0
        bv = bv + (cv * span)[:, None]

    order = jnp.argsort(-sv)
    bs = bv[order]
    iou = np.asarray(box_iou(Tensor(bs), Tensor(bs))._value)

    keep_sorted = np.ones(n, bool)
    for i in range(n):          # greedy suppression (host; N is post-top-k)
        if not keep_sorted[i]:
            continue
        keep_sorted[i + 1:] &= ~(iou[i, i + 1:] > iou_threshold)
    kept = np.asarray(order)[keep_sorted]
    if top_k is not None:
        kept = kept[:top_k]
    from ..core.tensor import to_tensor
    return to_tensor(kept.astype(np.int64))


def roi_align(x, boxes, boxes_num, output_size, spatial_scale: float = 1.0,
              sampling_ratio: int = -1, aligned: bool = True, name=None):
    """RoIAlign: average of bilinear samples per output bin (ref:
    paddle.vision.ops.roi_align; boxes [R, 4] xyxy in input coords,
    ``boxes_num`` [B] rois per image)."""
    xt = ensure_tensor(x)
    bt = ensure_tensor(boxes)
    bn = np.asarray(ensure_tensor(boxes_num).numpy()).astype(np.int64)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    if sampling_ratio > 0:
        sr = sampling_ratio
    else:
        # adaptive (-1): the reference uses ceil(roi_size/output_size)
        # samples per bin PER RoI; static shapes need one count per call,
        # so use the ceil for the LARGEST RoI (over-sampling smaller RoIs
        # only refines their average)
        bnp = np.asarray(bt.numpy(), np.float32)
        max_h = float(np.max(bnp[:, 3] - bnp[:, 1])) * spatial_scale \
            if len(bnp) else 1.0
        max_w = float(np.max(bnp[:, 2] - bnp[:, 0])) * spatial_scale \
            if len(bnp) else 1.0
        sr = int(max(1, min(8, np.ceil(max(max_h / ph, max_w / pw)))))
    batch_idx = np.repeat(np.arange(len(bn)), bn).astype(np.int32)

    def impl(xv, bv):
        off = 0.5 if aligned else 0.0
        x1 = bv[:, 0] * spatial_scale - off
        y1 = bv[:, 1] * spatial_scale - off
        x2 = bv[:, 2] * spatial_scale - off
        y2 = bv[:, 3] * spatial_scale - off
        rw = jnp.maximum(x2 - x1, 1e-3 if aligned else 1.0)
        rh = jnp.maximum(y2 - y1, 1e-3 if aligned else 1.0)
        bin_w = rw / pw
        bin_h = rh / ph
        # sample grid: [R, ph*sr] x [R, pw*sr]
        gy = (y1[:, None] + (jnp.arange(ph * sr) + 0.5)[None, :] *
              (bin_h / sr)[:, None])
        gx = (x1[:, None] + (jnp.arange(pw * sr) + 0.5)[None, :] *
              (bin_w / sr)[:, None])
        H, W = xv.shape[2], xv.shape[3]

        def bilinear(img, yy, xx):
            # img [C, H, W]; yy [Py], xx [Px] -> [C, Py, Px]
            y0 = jnp.clip(jnp.floor(yy), 0, H - 1)
            x0 = jnp.clip(jnp.floor(xx), 0, W - 1)
            y1i = jnp.clip(y0 + 1, 0, H - 1)
            x1i = jnp.clip(x0 + 1, 0, W - 1)
            wy = jnp.clip(yy, 0, H - 1) - y0
            wx = jnp.clip(xx, 0, W - 1) - x0
            y0, x0, y1i, x1i = (a.astype(jnp.int32)
                                for a in (y0, x0, y1i, x1i))
            v00 = img[:, y0][:, :, x0]
            v01 = img[:, y0][:, :, x1i]
            v10 = img[:, y1i][:, :, x0]
            v11 = img[:, y1i][:, :, x1i]
            return (v00 * (1 - wy)[None, :, None] * (1 - wx)[None, None, :]
                    + v01 * (1 - wy)[None, :, None] * wx[None, None, :]
                    + v10 * wy[None, :, None] * (1 - wx)[None, None, :]
                    + v11 * wy[None, :, None] * wx[None, None, :])

        def one_roi(bi, yy, xx):
            samp = bilinear(xv[bi], yy, xx)         # [C, ph*sr, pw*sr]
            C = samp.shape[0]
            samp = samp.reshape(C, ph, sr, pw, sr)
            return samp.mean(axis=(2, 4))           # [C, ph, pw]

        return jax.vmap(one_roi)(jnp.asarray(batch_idx), gy, gx)

    return forward_op("roi_align", impl, [xt, bt])


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale: float = 1.0,
             name=None):
    """RoIPool: max over quantized bins (ref: paddle.vision.ops.roi_pool)."""
    xt = ensure_tensor(x)
    bt = ensure_tensor(boxes)
    bn = np.asarray(ensure_tensor(boxes_num).numpy()).astype(np.int64)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    batch_idx = np.repeat(np.arange(len(bn)), bn).astype(np.int32)

    def impl(xv, bv):
        H, W = xv.shape[2], xv.shape[3]
        x1 = jnp.round(bv[:, 0] * spatial_scale)
        y1 = jnp.round(bv[:, 1] * spatial_scale)
        x2 = jnp.round(bv[:, 2] * spatial_scale)
        y2 = jnp.round(bv[:, 3] * spatial_scale)
        rw = jnp.maximum(x2 - x1 + 1, 1.0)
        rh = jnp.maximum(y2 - y1 + 1, 1.0)

        # dense sampling grid (oversample then segment-max per bin keeps
        # shapes static; grid of H/W points covers every integer cell)
        def one_roi(bi, xx1, yy1, ww, hh):
            gy = jnp.clip(yy1 + (jnp.arange(ph * 4) + 0.0) * hh / (ph * 4),
                          0, H - 1).astype(jnp.int32)
            gx = jnp.clip(xx1 + (jnp.arange(pw * 4) + 0.0) * ww / (pw * 4),
                          0, W - 1).astype(jnp.int32)
            patch = xv[bi][:, gy][:, :, gx]          # [C, ph*4, pw*4]
            C = patch.shape[0]
            patch = patch.reshape(C, ph, 4, pw, 4)
            return patch.max(axis=(2, 4))

        return jax.vmap(one_roi)(jnp.asarray(batch_idx), x1, y1, rw, rh)

    return forward_op("roi_pool", impl, [xt, bt])


def box_coder(prior_box, prior_box_var, target_box,
              code_type: str = "encode_center_size", box_normalized: bool = True,
              axis: int = 0, name=None):
    """Encode/decode boxes against priors (ref: paddle.vision.ops.box_coder,
    SSD/R-CNN box regression transform)."""
    p = ensure_tensor(prior_box)
    v = None if prior_box_var is None else ensure_tensor(prior_box_var)
    t = ensure_tensor(target_box)
    norm = 0.0 if box_normalized else 1.0

    def centers(b):
        w = b[..., 2] - b[..., 0] + norm
        h = b[..., 3] - b[..., 1] + norm
        cx = b[..., 0] + 0.5 * w
        cy = b[..., 1] + 0.5 * h
        return cx, cy, w, h

    if code_type == "encode_center_size":
        def impl(pv, tv, *var):
            pcx, pcy, pw, ph_ = centers(pv)
            tcx, tcy, tw, th = centers(tv[:, None, :]
                                       if tv.ndim == 2 else tv)
            out = jnp.stack([(tcx - pcx) / pw, (tcy - pcy) / ph_,
                             jnp.log(tw / pw), jnp.log(th / ph_)], axis=-1)
            if var:
                out = out / var[0]
            return out
        args = [p, t] + ([v] if v is not None else [])
        return forward_op("box_coder", impl, args)

    def impl(pv, tv, *var):   # decode_center_size
        pcx, pcy, pw, ph_ = centers(pv)
        if tv.ndim == 3:
            # priors broadcast along `axis` of the [N, M, 4] deltas
            # (ref: box_coder's axis attr; axis=0 -> prior per column)
            expand = (lambda a: a[None, :]) if axis == 0 \
                else (lambda a: a[:, None])
            pcx, pcy, pw, ph_ = (expand(a) for a in (pcx, pcy, pw, ph_))
        d = tv * var[0] if var else tv
        ocx = d[..., 0] * pw + pcx
        ocy = d[..., 1] * ph_ + pcy
        ow = jnp.exp(d[..., 2]) * pw
        oh = jnp.exp(d[..., 3]) * ph_
        return jnp.stack([ocx - 0.5 * ow, ocy - 0.5 * oh,
                          ocx + 0.5 * ow - norm, ocy + 0.5 * oh - norm],
                         axis=-1)

    args = [p, t] + ([v] if v is not None else [])
    return forward_op("box_coder", impl, args)


for _n, _f, _d in [
    ("box_iou", lambda a, b: a, "pairwise IoU matrix"),
    ("nms", lambda b: b, "greedy non-maximum suppression"),
    ("roi_align", lambda x, b: x, "RoIAlign bilinear pooling"),
    ("roi_pool", lambda x, b: x, "RoIPool max pooling"),
    ("box_coder", lambda p, t: t, "SSD/R-CNN box regression transform"),
]:
    register_op(_n, _f, f"vision.ops.{_n}: {_d}")


# legacy detection family (deform conv, priors/anchors, proposals, NMS
# variants, SSD matching) — see det_ops.py for the TPU design notes
from .det_ops import *  # noqa: F401,E402,F403
from .det_ops import __all__ as _det_all  # noqa: E402
__all__ = list(__all__) + list(_det_all)


def read_file(filename, name=None):
    """Raw bytes of a file as a uint8 Tensor (ref:
    paddle.vision.ops.read_file)."""
    import numpy as _np
    from ..core.tensor import to_tensor
    with open(filename, "rb") as f:
        data = f.read()
    return to_tensor(_np.frombuffer(data, dtype=_np.uint8).copy())


def decode_jpeg(x, mode: str = "unchanged", name=None):
    """Decode a JPEG byte Tensor to [C, H, W] uint8 (ref:
    paddle.vision.ops.decode_jpeg). Host-side decode (data pipeline);
    gated on Pillow which this hermetic image may lack — the contract and
    error message follow the text-dataset stance."""
    import numpy as _np
    from ..core.tensor import to_tensor
    try:
        from PIL import Image  # noqa: WPS433
    except ImportError as e:  # pragma: no cover
        raise RuntimeError(
            "decode_jpeg needs Pillow, which is not available in this "
            "hermetic environment; feed decoded arrays to the DataLoader "
            "instead") from e
    import io
    img = Image.open(io.BytesIO(_np.asarray(x._value
                                            if hasattr(x, "_value")
                                            else x).tobytes()))
    if mode == "gray":
        img = img.convert("L")
    elif mode == "rgb":
        img = img.convert("RGB")
    arr = _np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = arr.transpose(2, 0, 1)
    return to_tensor(arr)


from ..core.dispatch import register_op as _reg5
for _n5 in ("read_file", "decode_jpeg"):
    _reg5(_n5, globals()[_n5],
          (globals()[_n5].__doc__ or "").strip().split("\n")[0],
          differentiable=False, public=globals()[_n5])
__all__ = list(__all__) + ["read_file", "decode_jpeg"]
