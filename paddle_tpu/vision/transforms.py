"""Image transforms (ref: ``python/paddle/vision/transforms/transforms.py``).

Pure numpy on HWC images (uint8 or float), so they are safe inside
DataLoader worker subprocesses. ``ToTensor`` produces CHW float32 numpy
(Tensor conversion happens in the DataLoader parent, reference data_format
semantics preserved).
"""

from __future__ import annotations

import numbers
import random as pyrandom
from typing import Sequence

import numpy as np

__all__ = ["Compose", "BaseTransform", "ToTensor", "Normalize", "Resize",
           "CenterCrop", "RandomCrop", "RandomHorizontalFlip",
           "RandomVerticalFlip", "RandomResizedCrop", "Transpose", "Pad",
           "BrightnessTransform", "ContrastTransform", "Grayscale",
           "to_tensor", "normalize", "resize", "center_crop", "hflip", "vflip",
           "crop", "pad"]

_IMAGE_BACKEND = "numpy"


def _hwc(img) -> np.ndarray:
    a = np.asarray(img)
    if a.ndim == 2:
        a = a[:, :, None]
    if a.ndim != 3:
        raise ValueError(f"expected HW or HWC image, got shape {a.shape}")
    return a


# -- functional --------------------------------------------------------------

def to_tensor(img, data_format: str = "CHW") -> np.ndarray:
    a = _hwc(img).astype(np.float32)
    if np.issubdtype(np.asarray(img).dtype, np.integer):
        a = a / 255.0
    if data_format.upper() == "CHW":
        a = np.transpose(a, (2, 0, 1))
    return a


def normalize(img, mean, std, data_format: str = "CHW", to_rgb: bool = False):
    a = np.asarray(img, np.float32)
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    if data_format.upper() == "CHW":
        return (a - mean[:, None, None]) / std[:, None, None]
    return (a - mean) / std


def _resize_bilinear(a: np.ndarray, out_h: int, out_w: int) -> np.ndarray:
    h, w, c = a.shape
    if (h, w) == (out_h, out_w):
        return a
    ys = (np.arange(out_h) + 0.5) * h / out_h - 0.5
    xs = (np.arange(out_w) + 0.5) * w / out_w - 0.5
    y0 = np.clip(np.floor(ys).astype(np.int64), 0, h - 1)
    x0 = np.clip(np.floor(xs).astype(np.int64), 0, w - 1)
    y1 = np.clip(y0 + 1, 0, h - 1)
    x1 = np.clip(x0 + 1, 0, w - 1)
    wy = np.clip(ys - y0, 0, 1)[:, None, None]
    wx = np.clip(xs - x0, 0, 1)[None, :, None]
    af = a.astype(np.float32)
    top = af[y0][:, x0] * (1 - wx) + af[y0][:, x1] * wx
    bot = af[y1][:, x0] * (1 - wx) + af[y1][:, x1] * wx
    out = top * (1 - wy) + bot * wy
    return out.astype(a.dtype) if np.issubdtype(a.dtype, np.floating) \
        else np.clip(np.round(out), 0, 255).astype(a.dtype)


def resize(img, size, interpolation: str = "bilinear"):
    a = _hwc(img)
    h, w = a.shape[:2]
    if isinstance(size, numbers.Number):
        # short side -> size, keep aspect (reference semantics)
        if h <= w:
            oh, ow = int(size), max(1, int(round(w * size / h)))
        else:
            oh, ow = max(1, int(round(h * size / w))), int(size)
    else:
        oh, ow = int(size[0]), int(size[1])
    if interpolation == "nearest":
        yi = np.clip((np.arange(oh) * h / oh).astype(np.int64), 0, h - 1)
        xi = np.clip((np.arange(ow) * w / ow).astype(np.int64), 0, w - 1)
        return a[yi][:, xi]
    return _resize_bilinear(a, oh, ow)


def crop(img, top: int, left: int, height: int, width: int):
    return _hwc(img)[top:top + height, left:left + width]


def center_crop(img, output_size):
    a = _hwc(img)
    if isinstance(output_size, numbers.Number):
        output_size = (int(output_size), int(output_size))
    th, tw = output_size
    h, w = a.shape[:2]
    return crop(a, max(0, (h - th) // 2), max(0, (w - tw) // 2), th, tw)


def hflip(img):
    return _hwc(img)[:, ::-1]


def vflip(img):
    return _hwc(img)[::-1]


def pad(img, padding, fill=0, padding_mode: str = "constant"):
    a = _hwc(img)
    if isinstance(padding, numbers.Number):
        pl = pt = pr = pb = int(padding)
    elif len(padding) == 2:
        pl = pr = int(padding[0])
        pt = pb = int(padding[1])
    else:
        pl, pt, pr, pb = (int(p) for p in padding)
    if padding_mode == "constant":
        return np.pad(a, ((pt, pb), (pl, pr), (0, 0)), constant_values=fill)
    return np.pad(a, ((pt, pb), (pl, pr), (0, 0)), mode=padding_mode)


# -- transform classes -------------------------------------------------------

class BaseTransform:
    def __call__(self, img):
        return self._apply_image(img)

    def _apply_image(self, img):
        raise NotImplementedError


class Compose:
    def __init__(self, transforms: Sequence):
        self.transforms = list(transforms)

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


class ToTensor(BaseTransform):
    def __init__(self, data_format: str = "CHW", keys=None):
        self.data_format = data_format

    def _apply_image(self, img):
        return to_tensor(img, self.data_format)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format: str = "CHW",
                 to_rgb: bool = False, keys=None):
        if isinstance(mean, numbers.Number):
            mean = [mean] * 3
        if isinstance(std, numbers.Number):
            std = [std] * 3
        self.mean = mean
        self.std = std
        self.data_format = data_format

    def _apply_image(self, img):
        return normalize(img, self.mean, self.std, self.data_format)


class Resize(BaseTransform):
    def __init__(self, size, interpolation: str = "bilinear", keys=None):
        self.size = size
        self.interpolation = interpolation

    def _apply_image(self, img):
        return resize(img, self.size, self.interpolation)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        self.size = size

    def _apply_image(self, img):
        return center_crop(img, self.size)


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed: bool = False,
                 fill=0, padding_mode: str = "constant", keys=None):
        if isinstance(size, numbers.Number):
            size = (int(size), int(size))
        self.size = size
        self.padding = padding
        self.pad_if_needed = pad_if_needed
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        a = _hwc(img)
        if self.padding is not None:
            a = pad(a, self.padding, self.fill, self.padding_mode)
        th, tw = self.size
        h, w = a.shape[:2]
        if self.pad_if_needed and (h < th or w < tw):
            a = pad(a, (0, 0, max(0, tw - w), max(0, th - h)), self.fill,
                    self.padding_mode)
            h, w = a.shape[:2]
        top = pyrandom.randint(0, max(0, h - th))
        left = pyrandom.randint(0, max(0, w - tw))
        return crop(a, top, left, th, tw)


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob: float = 0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        return hflip(img) if pyrandom.random() < self.prob else _hwc(img)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob: float = 0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        return vflip(img) if pyrandom.random() < self.prob else _hwc(img)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation: str = "bilinear", keys=None):
        if isinstance(size, numbers.Number):
            size = (int(size), int(size))
        self.size = size
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        import math
        a = _hwc(img)
        h, w = a.shape[:2]
        area = h * w
        for _ in range(10):
            target = area * pyrandom.uniform(*self.scale)
            ar = math.exp(pyrandom.uniform(math.log(self.ratio[0]),
                                           math.log(self.ratio[1])))
            cw = int(round(math.sqrt(target * ar)))
            ch = int(round(math.sqrt(target / ar)))
            if 0 < cw <= w and 0 < ch <= h:
                top = pyrandom.randint(0, h - ch)
                left = pyrandom.randint(0, w - cw)
                return resize(crop(a, top, left, ch, cw), self.size,
                              self.interpolation)
        return resize(center_crop(a, min(h, w)), self.size, self.interpolation)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = tuple(order)

    def _apply_image(self, img):
        return np.transpose(_hwc(img), self.order)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode: str = "constant",
                 keys=None):
        self.padding = padding
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        return pad(img, self.padding, self.fill, self.padding_mode)


class BrightnessTransform(BaseTransform):
    def __init__(self, value: float, keys=None):
        self.value = float(value)

    def _apply_image(self, img):
        a = _hwc(img).astype(np.float32)
        factor = 1.0 + pyrandom.uniform(-self.value, self.value)
        out = a * factor
        if np.issubdtype(np.asarray(img).dtype, np.integer):
            return np.clip(out, 0, 255).astype(np.uint8)
        return out


class ContrastTransform(BaseTransform):
    def __init__(self, value: float, keys=None):
        self.value = float(value)

    def _apply_image(self, img):
        a = _hwc(img).astype(np.float32)
        factor = 1.0 + pyrandom.uniform(-self.value, self.value)
        mean = a.mean()
        out = (a - mean) * factor + mean
        if np.issubdtype(np.asarray(img).dtype, np.integer):
            return np.clip(out, 0, 255).astype(np.uint8)
        return out


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels: int = 1, keys=None):
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        a = _hwc(img).astype(np.float32)
        gray = (0.299 * a[..., 0] + 0.587 * a[..., 1] + 0.114 * a[..., 2])
        out = np.repeat(gray[..., None], self.num_output_channels, axis=-1)
        if np.issubdtype(np.asarray(img).dtype, np.integer):
            return np.clip(out, 0, 255).astype(np.uint8)
        return out


# ---------------------------------------------------------------------------
# r5: the remaining functional transform surface (ref:
# python/paddle/vision/transforms/functional.py). Host-side numpy by design
# — augmentation runs in the DataLoader workers; the TPU sees the batched
# result (SURVEY §2.3 vision row). All take HWC or CHW arrays and preserve
# layout/dtype conventions of the existing functionals above.
# ---------------------------------------------------------------------------

def _apply_hwc(img, fn):
    a = np.asarray(img)
    chw = a.ndim == 3 and a.shape[0] in (1, 3) and a.shape[-1] not in (1, 3)
    h = a.transpose(1, 2, 0) if chw else a
    out = fn(h.astype(np.float32))
    if chw:
        out = out.transpose(2, 0, 1)
    if np.issubdtype(a.dtype, np.integer):
        return np.clip(out, 0, 255).astype(np.uint8)
    return out.astype(a.dtype)


def adjust_brightness(img, brightness_factor: float):
    """Scale pixel intensities (ref: F.adjust_brightness)."""
    return _apply_hwc(img, lambda a: a * brightness_factor)


def adjust_contrast(img, contrast_factor: float):
    """Interpolate toward the grayscale mean (ref: F.adjust_contrast)."""
    def f(a):
        gray = 0.299 * a[..., 0] + 0.587 * a[..., 1] + 0.114 * a[..., 2]
        mean = gray.mean()
        return (a - mean) * contrast_factor + mean
    return _apply_hwc(img, f)


def adjust_saturation(img, saturation_factor: float):
    """Interpolate toward the per-pixel grayscale (ref:
    F.adjust_saturation)."""
    def f(a):
        gray = (0.299 * a[..., 0] + 0.587 * a[..., 1]
                + 0.114 * a[..., 2])[..., None]
        return (a - gray) * saturation_factor + gray
    return _apply_hwc(img, f)


def adjust_hue(img, hue_factor: float):
    """Rotate hue by ``hue_factor`` (in [-0.5, 0.5] turns; ref:
    F.adjust_hue) via RGB->HSV->RGB."""
    if not -0.5 <= hue_factor <= 0.5:
        raise ValueError("hue_factor must be in [-0.5, 0.5]")

    def f(a):
        scale = 255.0 if a.max() > 1.5 else 1.0
        x = a / scale
        mx = x.max(-1)
        mn = x.min(-1)
        diff = mx - mn + 1e-12
        r, g, b = x[..., 0], x[..., 1], x[..., 2]
        h = np.where(mx == r, ((g - b) / diff) % 6,
                     np.where(mx == g, (b - r) / diff + 2,
                              (r - g) / diff + 4)) / 6.0
        s = np.where(mx > 0, diff / (mx + 1e-12), 0)
        v = mx
        h = (h + hue_factor) % 1.0
        i = np.floor(h * 6)
        fpart = h * 6 - i
        p = v * (1 - s)
        q = v * (1 - fpart * s)
        t = v * (1 - (1 - fpart) * s)
        i = i.astype(np.int32) % 6
        rgb = np.stack([
            np.choose(i, [v, q, p, p, t, v]),
            np.choose(i, [t, v, v, q, p, p]),
            np.choose(i, [p, p, t, v, v, q]),
        ], -1)
        return rgb * scale
    return _apply_hwc(img, f)


def to_grayscale(img, num_output_channels: int = 1):
    """Luma grayscale (ref: F.to_grayscale)."""
    def f(a):
        gray = 0.299 * a[..., 0] + 0.587 * a[..., 1] + 0.114 * a[..., 2]
        return np.repeat(gray[..., None], num_output_channels, -1)
    return _apply_hwc(img, f)


def rotate(img, angle: float, interpolation: str = "nearest",
           expand: bool = False, center=None, fill=0):
    """Rotate about the center (ref: F.rotate). Inverse-map + nearest or
    bilinear sampling, numpy only."""
    def f(a):
        H, W = a.shape[:2]
        cy, cx = ((H - 1) / 2.0, (W - 1) / 2.0) if center is None \
            else (center[1], center[0])
        th = np.deg2rad(angle)
        cos, sin = np.cos(th), np.sin(th)
        if expand:
            corners = np.array([[-cx, -cy], [W - 1 - cx, -cy],
                                [-cx, H - 1 - cy], [W - 1 - cx, H - 1 - cy]])
            rot = corners @ np.array([[cos, -sin], [sin, cos]]).T
            OW = int(np.ceil(rot[:, 0].max() - rot[:, 0].min() + 1))
            OH = int(np.ceil(rot[:, 1].max() - rot[:, 1].min() + 1))
            ocx, ocy = (OW - 1) / 2.0, (OH - 1) / 2.0
        else:
            OH, OW, ocx, ocy = H, W, cx, cy
        yy, xx = np.meshgrid(np.arange(OH), np.arange(OW), indexing="ij")
        dx = xx - ocx
        dy = yy - ocy
        sx = cos * dx + sin * dy + cx
        sy = -sin * dx + cos * dy + cy
        if interpolation == "bilinear":
            x0 = np.floor(sx).astype(int)
            y0 = np.floor(sy).astype(int)
            wx = sx - x0
            wy = sy - y0
            out = np.zeros((OH, OW, a.shape[2]), np.float32)
            for (yi, xi, w) in ((y0, x0, (1 - wy) * (1 - wx)),
                                (y0, x0 + 1, (1 - wy) * wx),
                                (y0 + 1, x0, wy * (1 - wx)),
                                (y0 + 1, x0 + 1, wy * wx)):
                ok = (yi >= 0) & (yi < H) & (xi >= 0) & (xi < W)
                yc = np.clip(yi, 0, H - 1)
                xc = np.clip(xi, 0, W - 1)
                out += np.where(ok[..., None],
                                a[yc, xc] * w[..., None], 0)
            ok_any = (sy >= -0.5) & (sy <= H - 0.5) & \
                (sx >= -0.5) & (sx <= W - 0.5)
            return np.where(ok_any[..., None], out, fill)
        xi = np.round(sx).astype(int)
        yi = np.round(sy).astype(int)
        ok = (yi >= 0) & (yi < H) & (xi >= 0) & (xi < W)
        return np.where(ok[..., None],
                        a[np.clip(yi, 0, H - 1), np.clip(xi, 0, W - 1)],
                        fill)
    return _apply_hwc(img, f)


def perspective(img, startpoints, endpoints, interpolation: str = "nearest",
                fill=0):
    """Perspective warp mapping ``startpoints`` -> ``endpoints`` (ref:
    F.perspective); solves the 8-dof homography then inverse-samples."""
    sp = np.asarray(startpoints, np.float64)
    ep = np.asarray(endpoints, np.float64)
    # solve homography from endpoints back to startpoints (inverse map)
    A, bvec = [], []
    for (xs, ys), (xd, yd) in zip(sp, ep):
        A.append([xd, yd, 1, 0, 0, 0, -xs * xd, -xs * yd])
        bvec.append(xs)
        A.append([0, 0, 0, xd, yd, 1, -ys * xd, -ys * yd])
        bvec.append(ys)
    hcoef = np.linalg.solve(np.asarray(A), np.asarray(bvec))
    Hm = np.append(hcoef, 1.0).reshape(3, 3)

    def f(a):
        H, W = a.shape[:2]
        yy, xx = np.meshgrid(np.arange(H), np.arange(W), indexing="ij")
        den = Hm[2, 0] * xx + Hm[2, 1] * yy + Hm[2, 2]
        sx = (Hm[0, 0] * xx + Hm[0, 1] * yy + Hm[0, 2]) / den
        sy = (Hm[1, 0] * xx + Hm[1, 1] * yy + Hm[1, 2]) / den
        xi = np.round(sx).astype(int)
        yi = np.round(sy).astype(int)
        ok = (yi >= 0) & (yi < H) & (xi >= 0) & (xi < W)
        return np.where(ok[..., None],
                        a[np.clip(yi, 0, H - 1), np.clip(xi, 0, W - 1)],
                        fill)
    return _apply_hwc(img, f)


def erase(img, i: int, j: int, h: int, w: int, v, inplace: bool = False):
    """Erase the rectangle [i:i+h, j:j+w] with value ``v`` (ref: F.erase).
    Follows the input's layout (CHW erases [:, i:i+h, j:j+w])."""
    a = np.asarray(img)
    out = a if inplace else a.copy()
    chw = a.ndim == 3 and a.shape[0] in (1, 3) and a.shape[-1] not in (1, 3)
    if chw:
        out[:, i:i + h, j:j + w] = v
    else:
        out[i:i + h, j:j + w] = v
    return out


def solarize(img, threshold: float = 128.0):
    """Invert pixels above ``threshold`` (ref: F.solarize)."""
    def f(a):
        top = 255.0 if a.max() > 1.5 else 1.0
        return np.where(a >= threshold, top - a, a)
    return _apply_hwc(img, f)


def posterize(img, bits: int = 4):
    """Keep the top ``bits`` bits of each (uint8-range) channel (ref:
    F.posterize)."""
    def f(a):
        mask = 256 - (1 << (8 - bits))
        return (a.astype(np.int32) & mask).astype(np.float32)
    return _apply_hwc(img, f)


def equalize(img):
    """Per-channel histogram equalization over uint8 range (ref:
    F.equalize)."""
    def f(a):
        out = np.empty_like(a)
        for c in range(a.shape[-1]):
            ch = a[..., c].astype(np.uint8)
            hist = np.bincount(ch.reshape(-1), minlength=256)
            nz = hist[hist > 0]
            if nz.size <= 1:
                out[..., c] = ch
                continue
            step = (hist.sum() - nz[-1]) // 255
            if step == 0:
                out[..., c] = ch
                continue
            lut = (np.cumsum(hist) - hist // 2) // step
            out[..., c] = np.clip(lut, 0, 255)[ch]
        return out.astype(np.float32)
    return _apply_hwc(img, f)


def autocontrast(img):
    """Stretch each channel to the full range (ref: F.autocontrast)."""
    def f(a):
        top = 255.0 if a.max() > 1.5 else 1.0
        mn = a.min((0, 1), keepdims=True)
        mx = a.max((0, 1), keepdims=True)
        scale = np.where(mx > mn, top / np.maximum(mx - mn, 1e-12), 1.0)
        return np.where(mx > mn, (a - mn) * scale, a)
    return _apply_hwc(img, f)


def gaussian_blur(img, kernel_size, sigma=None):
    """Separable gaussian blur (ref: F.gaussian_blur)."""
    kh, kw = ((kernel_size, kernel_size) if isinstance(kernel_size, int)
              else tuple(kernel_size))
    if sigma is None:
        sigma = 0.3 * ((kh - 1) * 0.5 - 1) + 0.8
    sy = sx = sigma if np.isscalar(sigma) else None
    if sy is None:
        sy, sx = sigma

    def kern(k, s):
        r = np.arange(k) - (k - 1) / 2.0
        w = np.exp(-(r ** 2) / (2 * s * s))
        return w / w.sum()

    ky = kern(kh, sy)
    kx = kern(kw, sx)

    def f(a):
        pad_y = kh // 2
        pad_x = kw // 2
        p = np.pad(a, ((pad_y, pad_y), (0, 0), (0, 0)), mode="edge")
        out = sum(p[i:i + a.shape[0]] * ky[i] for i in range(kh))
        p = np.pad(out, ((0, 0), (pad_x, pad_x), (0, 0)), mode="edge")
        return sum(p[:, i:i + a.shape[1]] * kx[i] for i in range(kw))
    return _apply_hwc(img, f)


__all__ += ["adjust_brightness", "adjust_contrast", "adjust_saturation",
            "adjust_hue", "to_grayscale", "rotate", "perspective", "erase",
            "solarize", "posterize", "equalize", "autocontrast",
            "gaussian_blur"]


def _register_transforms():
    """The functional transforms join the schema registry (they are ops in
    the reference's ops.yaml sense — host-side preprocessing kernels)."""
    from ..core.dispatch import OP_REGISTRY, register_op
    for _n in ["to_tensor", "normalize", "resize", "center_crop", "hflip",
               "vflip", "crop", "pad", "adjust_brightness", "adjust_contrast",
               "adjust_saturation", "adjust_hue", "to_grayscale", "rotate",
               "perspective", "erase", "solarize", "posterize", "equalize",
               "autocontrast", "gaussian_blur"]:
        _f = globals()[_n]
        key = _n if _n not in OP_REGISTRY else "img_" + _n
        if key not in OP_REGISTRY:
            register_op(key, _f, (_f.__doc__ or "").strip().split("\n")[0],
                        differentiable=False, category="vision_transform",
                        public=_f)


_register_transforms()
