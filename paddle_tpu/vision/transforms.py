"""Image transforms (ref: ``python/paddle/vision/transforms/transforms.py``).

Pure numpy on HWC images (uint8 or float), so they are safe inside
DataLoader worker subprocesses. ``ToTensor`` produces CHW float32 numpy
(Tensor conversion happens in the DataLoader parent, reference data_format
semantics preserved).
"""

from __future__ import annotations

import numbers
import random as pyrandom
from typing import Sequence

import numpy as np

__all__ = ["Compose", "BaseTransform", "ToTensor", "Normalize", "Resize",
           "CenterCrop", "RandomCrop", "RandomHorizontalFlip",
           "RandomVerticalFlip", "RandomResizedCrop", "Transpose", "Pad",
           "BrightnessTransform", "ContrastTransform", "Grayscale",
           "to_tensor", "normalize", "resize", "center_crop", "hflip", "vflip",
           "crop", "pad"]

_IMAGE_BACKEND = "numpy"


def _hwc(img) -> np.ndarray:
    a = np.asarray(img)
    if a.ndim == 2:
        a = a[:, :, None]
    if a.ndim != 3:
        raise ValueError(f"expected HW or HWC image, got shape {a.shape}")
    return a


# -- functional --------------------------------------------------------------

def to_tensor(img, data_format: str = "CHW") -> np.ndarray:
    a = _hwc(img).astype(np.float32)
    if np.issubdtype(np.asarray(img).dtype, np.integer):
        a = a / 255.0
    if data_format.upper() == "CHW":
        a = np.transpose(a, (2, 0, 1))
    return a


def normalize(img, mean, std, data_format: str = "CHW", to_rgb: bool = False):
    a = np.asarray(img, np.float32)
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    if data_format.upper() == "CHW":
        return (a - mean[:, None, None]) / std[:, None, None]
    return (a - mean) / std


def _resize_bilinear(a: np.ndarray, out_h: int, out_w: int) -> np.ndarray:
    h, w, c = a.shape
    if (h, w) == (out_h, out_w):
        return a
    ys = (np.arange(out_h) + 0.5) * h / out_h - 0.5
    xs = (np.arange(out_w) + 0.5) * w / out_w - 0.5
    y0 = np.clip(np.floor(ys).astype(np.int64), 0, h - 1)
    x0 = np.clip(np.floor(xs).astype(np.int64), 0, w - 1)
    y1 = np.clip(y0 + 1, 0, h - 1)
    x1 = np.clip(x0 + 1, 0, w - 1)
    wy = np.clip(ys - y0, 0, 1)[:, None, None]
    wx = np.clip(xs - x0, 0, 1)[None, :, None]
    af = a.astype(np.float32)
    top = af[y0][:, x0] * (1 - wx) + af[y0][:, x1] * wx
    bot = af[y1][:, x0] * (1 - wx) + af[y1][:, x1] * wx
    out = top * (1 - wy) + bot * wy
    return out.astype(a.dtype) if np.issubdtype(a.dtype, np.floating) \
        else np.clip(np.round(out), 0, 255).astype(a.dtype)


def resize(img, size, interpolation: str = "bilinear"):
    a = _hwc(img)
    h, w = a.shape[:2]
    if isinstance(size, numbers.Number):
        # short side -> size, keep aspect (reference semantics)
        if h <= w:
            oh, ow = int(size), max(1, int(round(w * size / h)))
        else:
            oh, ow = max(1, int(round(h * size / w))), int(size)
    else:
        oh, ow = int(size[0]), int(size[1])
    if interpolation == "nearest":
        yi = np.clip((np.arange(oh) * h / oh).astype(np.int64), 0, h - 1)
        xi = np.clip((np.arange(ow) * w / ow).astype(np.int64), 0, w - 1)
        return a[yi][:, xi]
    return _resize_bilinear(a, oh, ow)


def crop(img, top: int, left: int, height: int, width: int):
    return _hwc(img)[top:top + height, left:left + width]


def center_crop(img, output_size):
    a = _hwc(img)
    if isinstance(output_size, numbers.Number):
        output_size = (int(output_size), int(output_size))
    th, tw = output_size
    h, w = a.shape[:2]
    return crop(a, max(0, (h - th) // 2), max(0, (w - tw) // 2), th, tw)


def hflip(img):
    return _hwc(img)[:, ::-1]


def vflip(img):
    return _hwc(img)[::-1]


def pad(img, padding, fill=0, padding_mode: str = "constant"):
    a = _hwc(img)
    if isinstance(padding, numbers.Number):
        pl = pt = pr = pb = int(padding)
    elif len(padding) == 2:
        pl = pr = int(padding[0])
        pt = pb = int(padding[1])
    else:
        pl, pt, pr, pb = (int(p) for p in padding)
    if padding_mode == "constant":
        return np.pad(a, ((pt, pb), (pl, pr), (0, 0)), constant_values=fill)
    return np.pad(a, ((pt, pb), (pl, pr), (0, 0)), mode=padding_mode)


# -- transform classes -------------------------------------------------------

class BaseTransform:
    def __call__(self, img):
        return self._apply_image(img)

    def _apply_image(self, img):
        raise NotImplementedError


class Compose:
    def __init__(self, transforms: Sequence):
        self.transforms = list(transforms)

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


class ToTensor(BaseTransform):
    def __init__(self, data_format: str = "CHW", keys=None):
        self.data_format = data_format

    def _apply_image(self, img):
        return to_tensor(img, self.data_format)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format: str = "CHW",
                 to_rgb: bool = False, keys=None):
        if isinstance(mean, numbers.Number):
            mean = [mean] * 3
        if isinstance(std, numbers.Number):
            std = [std] * 3
        self.mean = mean
        self.std = std
        self.data_format = data_format

    def _apply_image(self, img):
        return normalize(img, self.mean, self.std, self.data_format)


class Resize(BaseTransform):
    def __init__(self, size, interpolation: str = "bilinear", keys=None):
        self.size = size
        self.interpolation = interpolation

    def _apply_image(self, img):
        return resize(img, self.size, self.interpolation)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        self.size = size

    def _apply_image(self, img):
        return center_crop(img, self.size)


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed: bool = False,
                 fill=0, padding_mode: str = "constant", keys=None):
        if isinstance(size, numbers.Number):
            size = (int(size), int(size))
        self.size = size
        self.padding = padding
        self.pad_if_needed = pad_if_needed
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        a = _hwc(img)
        if self.padding is not None:
            a = pad(a, self.padding, self.fill, self.padding_mode)
        th, tw = self.size
        h, w = a.shape[:2]
        if self.pad_if_needed and (h < th or w < tw):
            a = pad(a, (0, 0, max(0, tw - w), max(0, th - h)), self.fill,
                    self.padding_mode)
            h, w = a.shape[:2]
        top = pyrandom.randint(0, max(0, h - th))
        left = pyrandom.randint(0, max(0, w - tw))
        return crop(a, top, left, th, tw)


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob: float = 0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        return hflip(img) if pyrandom.random() < self.prob else _hwc(img)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob: float = 0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        return vflip(img) if pyrandom.random() < self.prob else _hwc(img)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation: str = "bilinear", keys=None):
        if isinstance(size, numbers.Number):
            size = (int(size), int(size))
        self.size = size
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        import math
        a = _hwc(img)
        h, w = a.shape[:2]
        area = h * w
        for _ in range(10):
            target = area * pyrandom.uniform(*self.scale)
            ar = math.exp(pyrandom.uniform(math.log(self.ratio[0]),
                                           math.log(self.ratio[1])))
            cw = int(round(math.sqrt(target * ar)))
            ch = int(round(math.sqrt(target / ar)))
            if 0 < cw <= w and 0 < ch <= h:
                top = pyrandom.randint(0, h - ch)
                left = pyrandom.randint(0, w - cw)
                return resize(crop(a, top, left, ch, cw), self.size,
                              self.interpolation)
        return resize(center_crop(a, min(h, w)), self.size, self.interpolation)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = tuple(order)

    def _apply_image(self, img):
        return np.transpose(_hwc(img), self.order)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode: str = "constant",
                 keys=None):
        self.padding = padding
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        return pad(img, self.padding, self.fill, self.padding_mode)


class BrightnessTransform(BaseTransform):
    def __init__(self, value: float, keys=None):
        self.value = float(value)

    def _apply_image(self, img):
        a = _hwc(img).astype(np.float32)
        factor = 1.0 + pyrandom.uniform(-self.value, self.value)
        out = a * factor
        if np.issubdtype(np.asarray(img).dtype, np.integer):
            return np.clip(out, 0, 255).astype(np.uint8)
        return out


class ContrastTransform(BaseTransform):
    def __init__(self, value: float, keys=None):
        self.value = float(value)

    def _apply_image(self, img):
        a = _hwc(img).astype(np.float32)
        factor = 1.0 + pyrandom.uniform(-self.value, self.value)
        mean = a.mean()
        out = (a - mean) * factor + mean
        if np.issubdtype(np.asarray(img).dtype, np.integer):
            return np.clip(out, 0, 255).astype(np.uint8)
        return out


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels: int = 1, keys=None):
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        a = _hwc(img).astype(np.float32)
        gray = (0.299 * a[..., 0] + 0.587 * a[..., 1] + 0.114 * a[..., 2])
        out = np.repeat(gray[..., None], self.num_output_channels, axis=-1)
        if np.issubdtype(np.asarray(img).dtype, np.integer):
            return np.clip(out, 0, 255).astype(np.uint8)
        return out
