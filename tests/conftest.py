"""Test configuration.

Tests run on a virtual 8-device CPU mesh (the reference's Gloo-on-localhost trick for
testing collective logic without accelerators — see SURVEY.md §4): env must be set
before jax initializes any backend, hence at conftest import time.
"""

import os

# Force-assign (not setdefault): the parent env carries JAX_PLATFORMS=axon (real TPU
# tunnel); tests must run on the virtual CPU mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
prev = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in prev:
    os.environ["XLA_FLAGS"] = (prev + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

# The /root/.axon_site sitecustomize may have claimed the real TPU at interpreter
# start (before this conftest ran). Tear that backend down and re-resolve on CPU so
# the env vars above take effect regardless of how pytest was invoked.
jax.config.update("jax_platforms", "cpu")
if jax.default_backend() != "cpu" or jax.device_count() < 8:
    from jax._src import xla_bridge
    xla_bridge._clear_backends()
assert jax.default_backend() == "cpu" and jax.device_count() >= 8

jax.config.update("jax_default_matmul_precision", "highest")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seeded():
    import paddle_tpu as paddle
    paddle.seed(1234)
    np.random.seed(1234)
    yield
