"""Test configuration.

Tests run on a virtual 8-device CPU mesh (the reference's Gloo-on-localhost trick for
testing collective logic without accelerators — see SURVEY.md §4): env must be set
before jax initializes any backend, hence at conftest import time.
"""

import os

# Force-assign (not setdefault): the parent env carries JAX_PLATFORMS=axon (real TPU
# tunnel); tests must run on the virtual CPU mesh. NOTE: run pytest with PYTHONPATH=
# (empty) — the /root/.axon_site sitecustomize claims the TPU at interpreter start,
# before conftest can do anything.
os.environ["JAX_PLATFORMS"] = "cpu"
prev = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in prev:
    os.environ["XLA_FLAGS"] = (prev + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

jax.config.update("jax_default_matmul_precision", "highest")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seeded():
    import paddle_tpu as paddle
    paddle.seed(1234)
    np.random.seed(1234)
    yield
