"""Test configuration.

Tests run on a virtual 8-device CPU mesh (the reference's Gloo-on-localhost trick for
testing collective logic without accelerators — see SURVEY.md §4): env must be set
before jax initializes any backend, hence at conftest import time.
"""

import os

# Force-assign (not setdefault): the parent env carries JAX_PLATFORMS=axon (real TPU
# tunnel); tests must run on the virtual CPU mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
prev = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in prev:
    os.environ["XLA_FLAGS"] = (prev + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

# The /root/.axon_site sitecustomize may have claimed the real TPU at interpreter
# start (before this conftest ran). Tear that backend down and re-resolve on CPU so
# the env vars above take effect regardless of how pytest was invoked.
jax.config.update("jax_platforms", "cpu")
if jax.default_backend() != "cpu" or jax.device_count() < 8:
    from jax._src import xla_bridge
    xla_bridge._clear_backends()
assert jax.default_backend() == "cpu" and jax.device_count() >= 8

jax.config.update("jax_default_matmul_precision", "highest")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def _load_slowlist():
    path = os.path.join(os.path.dirname(__file__), "slowlist.txt")
    try:
        with open(path) as f:
            return {ln.strip() for ln in f
                    if ln.strip() and not ln.startswith("#")}
    except OSError:
        return set()


def pytest_collection_modifyitems(config, items):
    """Auto-mark measured-slow tests (tests/slowlist.txt) so the default run
    (pytest.ini addopts = -m "not slow") is a fast green signal; explicit
    @pytest.mark.slow still works for new tests (SURVEY §4 CI discipline)."""
    slow = _load_slowlist()
    for item in items:
        if item.nodeid in slow:
            item.add_marker(pytest.mark.slow)


@pytest.fixture(autouse=True)
def _seeded():
    import paddle_tpu as paddle
    paddle.seed(1234)
    np.random.seed(1234)
    yield


@pytest.fixture(scope="session")
def tp_platform():
    """The multi-device host platform the serving tensor-parallel tests
    (@pytest.mark.tp) shard over. This conftest provisions (and asserts,
    above) the 8-way virtual CPU mesh for the whole suite — XLA_FLAGS is
    set before jax initializes, so it cannot be toggled per test. This
    fixture is the TP tests' explicit CONTRACT with that mesh: it names
    the dependency, returns the device count so tests size their meshes,
    and — belt and braces for a harness that bootstraps the platform
    differently (e.g. tests invoked without this conftest's env control)
    — skips rather than erroring deep inside device_put when fewer than
    2 devices resolved. Session-scoped so MODULE-scoped engine fixtures
    can depend on it (a skip must fire before an engine fixture builds a
    mesh, which would ERROR instead)."""
    n = jax.device_count()
    if n < 2:
        pytest.skip("serving TP tests need >= 2 devices "
                    "(XLA_FLAGS=--xla_force_host_platform_device_count)")
    return n
