"""paddle.amp parity tests: auto_cast op-list semantics, GradScaler dynamics,
decorate O2 master weights, end-to-end mixed-precision training."""

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu import amp
from paddle_tpu.optimizer import SGD, AdamW


class TestAutoCast:
    def test_white_op_casts_to_bf16(self):
        x = paddle.to_tensor(np.random.randn(4, 8).astype("float32"))
        w = paddle.to_tensor(np.random.randn(8, 8).astype("float32"))
        with amp.auto_cast():
            y = paddle.matmul(x, w)
        assert y.dtype == jnp.bfloat16
        y2 = paddle.matmul(x, w)
        assert y2.dtype == jnp.float32  # state restored

    def test_black_op_stays_fp32(self):
        x = paddle.to_tensor(np.random.randn(4, 8).astype("float32"))
        with amp.auto_cast():
            h = F.relu(x)          # neither list: input dtype preserved
            s = F.softmax(x)       # black: fp32
        assert h.dtype == jnp.float32
        assert s.dtype == jnp.float32

    def test_black_op_upcasts_bf16_input(self):
        x = paddle.to_tensor(
            np.random.randn(4, 8).astype("float32")).astype("bfloat16")
        with amp.auto_cast():
            s = F.softmax(x)
        assert s.dtype == jnp.float32

    def test_o2_casts_everything_but_black(self):
        x = paddle.to_tensor(np.random.randn(4, 8).astype("float32"))
        with amp.auto_cast(level="O2"):
            h = F.relu(x)
            s = F.softmax(x)
        assert h.dtype == jnp.bfloat16
        assert s.dtype == jnp.float32

    def test_custom_lists(self):
        x = paddle.to_tensor(np.random.randn(4, 8).astype("float32"))
        with amp.auto_cast(custom_white_list={"relu"}):
            h = F.relu(x)
        assert h.dtype == jnp.bfloat16
        with amp.auto_cast(custom_black_list={"matmul"}):
            y = paddle.matmul(x, paddle.transpose(x, [1, 0]))
        assert y.dtype == jnp.float32

    def test_disable(self):
        x = paddle.to_tensor(np.random.randn(4, 8).astype("float32"))
        with amp.auto_cast(enable=False):
            y = paddle.matmul(x, paddle.transpose(x, [1, 0]))
        assert y.dtype == jnp.float32

    def test_level_validation(self):
        with pytest.raises(ValueError):
            with amp.auto_cast(level="O9"):
                pass

    def test_backward_through_autocast(self):
        lin = nn.Linear(8, 8)
        x = paddle.to_tensor(np.random.randn(4, 8).astype("float32"))
        with amp.auto_cast():
            loss = (lin(x) ** 2).mean()
        loss.backward()
        assert lin.weight.grad is not None
        assert np.isfinite(lin.weight.grad.numpy()).all()


class TestGradScaler:
    def _mini(self):
        lin = nn.Linear(4, 4)
        opt = SGD(learning_rate=0.1, parameters=lin.parameters())
        x = paddle.to_tensor(np.random.randn(2, 4).astype("float32"))
        return lin, opt, x

    def test_scale_and_step(self):
        lin, opt, x = self._mini()
        scaler = amp.GradScaler(init_loss_scaling=128.0)
        w0 = lin.weight.numpy().copy()
        loss = (lin(x) ** 2).mean()
        scaler.scale(loss).backward()
        scaler.step(opt)
        scaler.update()
        opt.clear_grad()
        assert not np.allclose(lin.weight.numpy(), w0)

    def test_unscale_restores_grad_magnitude(self):
        lin, opt, x = self._mini()
        scaler = amp.GradScaler(init_loss_scaling=1024.0)
        loss = (lin(x) ** 2).mean()
        scaler.scale(loss).backward()
        g_scaled = lin.weight.grad.numpy().copy()
        scaler.unscale_(opt)
        np.testing.assert_allclose(lin.weight.grad.numpy(),
                                   g_scaled / 1024.0, rtol=1e-6)

    def test_inf_skips_step_and_shrinks_scale(self):
        lin, opt, x = self._mini()
        scaler = amp.GradScaler(init_loss_scaling=256.0)
        w0 = lin.weight.numpy().copy()
        loss = (lin(x) ** 2).mean()
        loss.backward()
        lin.weight.grad.set_value(
            np.full_like(lin.weight.grad.numpy(), np.inf))
        scaler.step(opt)
        scaler.update()
        np.testing.assert_array_equal(lin.weight.numpy(), w0)  # skipped
        assert scaler.get_init_loss_scaling() == 128.0  # 256 * 0.5

    def test_scale_grows_after_n_good_steps(self):
        lin, opt, x = self._mini()
        scaler = amp.GradScaler(init_loss_scaling=2.0, incr_every_n_steps=2)
        for _ in range(2):
            loss = (lin(x) ** 2).mean()
            scaler.scale(loss).backward()
            scaler.step(opt)
            scaler.update()
            opt.clear_grad()
        assert scaler.get_init_loss_scaling() == 4.0

    def test_disabled_passthrough(self):
        lin, opt, x = self._mini()
        scaler = amp.GradScaler(enable=False)
        loss = (lin(x) ** 2).mean()
        assert scaler.scale(loss) is loss
        loss.backward()
        scaler.step(opt)  # plain step
        scaler.update()

    def test_state_dict_roundtrip(self):
        s1 = amp.GradScaler(init_loss_scaling=99.0)
        s2 = amp.GradScaler()
        s2.load_state_dict(s1.state_dict())
        assert s2.get_init_loss_scaling() == 99.0


class TestDecorate:
    def test_o2_casts_params_and_master_weights(self):
        model = nn.Sequential(nn.Linear(8, 16), nn.GELU(), nn.Linear(16, 4))
        opt = AdamW(learning_rate=1e-2, parameters=model.parameters())
        model, opt = amp.decorate(model, opt, level="O2")
        assert model[0].weight.dtype == jnp.bfloat16
        assert opt._multi_precision
        x = paddle.to_tensor(np.random.randn(4, 8).astype("float32"))
        with amp.auto_cast(level="O2"):
            loss = (model(x).astype("float32") ** 2).mean()
        loss.backward()
        opt.step()
        # master weights exist in fp32
        assert opt._master_weights
        for mw in opt._master_weights.values():
            assert mw.dtype == jnp.float32

    def test_norm_layers_stay_fp32(self):
        model = nn.Sequential(nn.Linear(8, 8), nn.LayerNorm(8))
        amp.decorate(model, level="O2")
        assert model[0].weight.dtype == jnp.bfloat16
        assert model[1].weight.dtype == jnp.float32


class TestEndToEnd:
    def test_amp_training_matches_fp32_direction(self):
        """bf16-autocast training tracks the fp32 loss curve (tolerance)."""
        def build():
            paddle.seed(7)
            m = nn.Sequential(nn.Linear(16, 32), nn.GELU(), nn.Linear(32, 1))
            o = SGD(learning_rate=0.05, parameters=m.parameters())
            return m, o

        rng = np.random.default_rng(0)
        xs = rng.standard_normal((5, 8, 16)).astype("float32")
        ys = rng.standard_normal((5, 8, 1)).astype("float32")

        def run(use_amp):
            m, o = build()
            losses = []
            scaler = amp.GradScaler(enable=use_amp)
            for i in range(5):
                x, y = paddle.to_tensor(xs[i]), paddle.to_tensor(ys[i])
                if use_amp:
                    with amp.auto_cast():
                        loss = ((m(x) - y) ** 2).mean()
                else:
                    loss = ((m(x) - y) ** 2).mean()
                scaler.scale(loss).backward()
                scaler.step(o)
                scaler.update()
                o.clear_grad()
                losses.append(float(loss))
            return losses

        fp32 = run(False)
        mixed = run(True)
        assert mixed[-1] < mixed[0]  # converging
        np.testing.assert_allclose(mixed, fp32, rtol=0.1, atol=0.05)
