"""Tape autograd: backward, accumulation, hooks, stop_gradient, paddle.grad,
numeric gradient checks (the reference OpTest check_grad pattern via finite
differences)."""

import numpy as np
import pytest

import paddle_tpu as paddle


def numeric_grad(f, x, eps=1e-3):
    """Central finite differences of scalar f at numpy array x."""
    g = np.zeros_like(x, dtype=np.float64)
    flat = x.reshape(-1)
    gf = g.reshape(-1)
    for i in range(flat.size):
        orig = flat[i]
        flat[i] = orig + eps
        f1 = f(x.copy().reshape(x.shape))
        flat[i] = orig - eps
        f0 = f(x.copy().reshape(x.shape))
        flat[i] = orig
        gf[i] = (f1 - f0) / (2 * eps)
    return g


def test_simple_backward():
    x = paddle.to_tensor([1.0, 2.0, 3.0], stop_gradient=False)
    y = (x * x).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0, 4.0, 6.0], rtol=1e-6)


def test_chain_and_broadcast():
    a = np.random.rand(3, 4).astype(np.float32)
    b = np.random.rand(4).astype(np.float32)
    x = paddle.to_tensor(a, stop_gradient=False)
    y = paddle.to_tensor(b, stop_gradient=False)
    out = ((x + y) * 2.0).mean()
    out.backward()
    np.testing.assert_allclose(x.grad.numpy(), np.full((3, 4), 2.0 / 12), rtol=1e-6)
    np.testing.assert_allclose(y.grad.numpy(), np.full((4,), 3 * 2.0 / 12), rtol=1e-6)


def test_grad_accumulation_across_backwards():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    (x * 2).backward()
    (x * 3).backward()
    np.testing.assert_allclose(x.grad.numpy(), [5.0])
    x.clear_grad()
    assert x.grad is None


def test_multi_use_fanout():
    x = paddle.to_tensor([2.0], stop_gradient=False)
    y = x * x + x * 3
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [2 * 2 + 3])


def test_stop_gradient_blocks():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    y = paddle.to_tensor([1.0], stop_gradient=True)
    (x * y).backward()
    assert x.grad is not None
    assert y.grad is None


def test_detach_cuts_graph():
    x = paddle.to_tensor([3.0], stop_gradient=False)
    y = x * 2
    z = y.detach() * x
    z.backward()
    np.testing.assert_allclose(x.grad.numpy(), [6.0])  # only through the second factor


def test_matmul_grad_vs_numeric():
    a = np.random.rand(3, 4).astype(np.float64)
    b = np.random.rand(4, 2).astype(np.float64)
    x = paddle.to_tensor(a.astype(np.float32), stop_gradient=False)
    w = paddle.to_tensor(b.astype(np.float32), stop_gradient=False)
    loss = paddle.matmul(x, w).sum()
    loss.backward()
    ng = numeric_grad(lambda aa: (aa @ b).sum(), a.copy())
    np.testing.assert_allclose(x.grad.numpy(), ng, rtol=1e-3, atol=1e-3)


def test_unary_grads_vs_numeric():
    fns = [
        (paddle.tanh, np.tanh),
        (paddle.exp, np.exp),
        (paddle.log, np.log),
        (paddle.sqrt, np.sqrt),
        (paddle.sigmoid, lambda v: 1 / (1 + np.exp(-v))),
    ]
    a = np.random.rand(5).astype(np.float64) + 0.5
    for pf, nf in fns:
        x = paddle.to_tensor(a.astype(np.float32), stop_gradient=False)
        pf(x).sum().backward()
        ng = numeric_grad(lambda v: nf(v).sum(), a.copy())
        np.testing.assert_allclose(x.grad.numpy(), ng, rtol=1e-2, atol=1e-3,
                                   err_msg=pf.__name__)


def test_backward_non_scalar_requires_grad_tensor():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    y = x * 2
    with pytest.raises(RuntimeError):
        y.backward()
    y = x * 2
    y.backward(paddle.to_tensor([1.0, 0.5]))
    np.testing.assert_allclose(x.grad.numpy(), [2.0, 1.0])


def test_retain_graph():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    y = x * x
    y.backward(retain_graph=True)
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [4.0])
    z = x * x
    z.backward()
    with pytest.raises(RuntimeError):
        z.backward()


def test_hooks():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    seen = []

    def hook(g):
        seen.append(g.numpy().copy())
        return g * 2

    y = x * 3
    y.register_hook(hook)
    (y * 5).backward()
    assert len(seen) == 1
    np.testing.assert_allclose(seen[0], [5.0])
    np.testing.assert_allclose(x.grad.numpy(), [30.0])  # 5 * 2 (hook) * 3


def test_leaf_hook():
    x = paddle.to_tensor([2.0], stop_gradient=False)
    x.register_hook(lambda g: g * 10)
    (x * 1.0).backward()
    np.testing.assert_allclose(x.grad.numpy(), [10.0])


def test_paddle_grad_api():
    x = paddle.to_tensor([2.0], stop_gradient=False)
    y = x * x
    (g,) = paddle.grad([y], [x])
    np.testing.assert_allclose(g.numpy(), [4.0])
    assert x.grad is None  # paddle.grad must not pollute .grad


def test_double_grad():
    x = paddle.to_tensor([3.0], stop_gradient=False)
    y = x * x * x  # y = x^3, dy/dx = 3x^2, d2y/dx2 = 6x
    (g,) = paddle.grad([y], [x], create_graph=True)
    np.testing.assert_allclose(g.numpy(), [27.0])
    assert not g.stop_gradient
    (gg,) = paddle.grad([g], [x])
    np.testing.assert_allclose(gg.numpy(), [18.0])


def test_no_grad_context():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    with paddle.no_grad():
        y = x * 2
    assert y.stop_gradient

    @paddle.no_grad()
    def f(t):
        return t * 3

    assert f(x).stop_gradient


def test_multi_output_op_grad():
    x = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3),
                         stop_gradient=False)
    parts = paddle.split(x, 3, axis=1)
    loss = parts[0].sum() * 1 + parts[2].sum() * 3
    loss.backward()
    np.testing.assert_allclose(x.grad.numpy(),
                               [[1, 0, 3], [1, 0, 3]], rtol=1e-6)


def test_setitem_grad_flows():
    x = paddle.to_tensor([1.0, 2.0, 3.0], stop_gradient=False)
    v = paddle.to_tensor([10.0], stop_gradient=False)
    y = x * 1.0
    y[1] = v  # functional scatter under the hood
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [1.0, 0.0, 1.0])
    np.testing.assert_allclose(v.grad.numpy(), [1.0])


def test_inplace_add_keeps_tape():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    y = x * 2
    y.add_(paddle.to_tensor([5.0]))
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0])


def test_nan_check_flag():
    paddle.set_flags({"FLAGS_check_nan_inf": True})
    try:
        x = paddle.to_tensor([-1.0], stop_gradient=False)
        # jax_debug_nans raises FloatingPointError; the dispatcher wraps it
        # in the typed FatalError carrying the op name + nan-hunt hint
        # (r3 enforce layer), chaining the original as __cause__
        with pytest.raises(
                (FloatingPointError, paddle.enforce.FatalError)) as ei:
            paddle.log(x)
        assert "log" in str(ei.value)
    finally:
        paddle.set_flags({"FLAGS_check_nan_inf": False})
