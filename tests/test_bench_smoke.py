"""Bench entry-point smoke (ISSUE 2 satellite): `python bench.py --<sec>`
must import and run one tiny step under JAX_PLATFORMS=cpu, so bench bit-rot
is caught by tier-1 instead of burning a driver round. Sections chosen for
CPU cost: llama (the headline path, smoke config compiles in seconds) and
input (the new pipeline section, sub-second). The heavy conv sections
(resnet/detect) compile for minutes on CPU and stay driver-only."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_bench(*flags, timeout=420):
    env = {"JAX_PLATFORMS": "cpu",
           "PATH": os.environ.get("PATH", "/usr/bin:/bin:/usr/local/bin"),
           "PYTHONPATH": REPO,
           "HOME": os.environ.get("HOME", "/tmp"),
           "BENCH_BUDGET_S": "3600",   # never self-skip in the smoke run
           "BENCH_CACHE_DIR": os.path.join(REPO, ".jax_cache")}
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), *flags],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=timeout)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    metrics = {}
    for line in proc.stdout.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            d = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(d, dict) and "metric" in d:
            metrics[d["metric"]] = d
    return metrics, proc


def test_bench_llama_entry_point():
    """The headline section: one tiny fused+donated train step end to end,
    final stdout line is the llama_train_mfu re-emit the driver parses."""
    metrics, proc = _run_bench("--llama", "--steps", "1")
    assert "llama_train_mfu" in metrics, proc.stdout + proc.stderr
    last = [l for l in proc.stdout.splitlines() if l.strip()][-1]
    assert json.loads(last)["metric"] == "llama_train_mfu"


def test_bench_input_entry_point():
    """The input-pipeline section: H2D cost + prefetch overlap rows."""
    metrics, proc = _run_bench("--input", "--steps", "2")
    assert "input_h2d_ms_per_batch" in metrics, proc.stdout + proc.stderr
    assert "input_overlap_pct" in metrics
    assert metrics["input_h2d_ms_per_batch"]["value"] > 0
    assert 0.0 <= metrics["input_overlap_pct"]["value"] <= 100.0


def test_bench_serve_entry_point():
    """The serving section (ISSUE 4 + 5): continuous batching over the
    paged KV cache vs the static-batch baseline on one mixed-length trace,
    plus the shared-prefix trace (prefix cache on vs off) and the
    preemption-pressure trace (on-demand paging under a deliberately
    undersized pool). The section itself asserts the acceptance proofs
    (paged greedy bit-equal to the dense path, constant decode-executable
    count, pressure-row parity) before emitting, so a green run here pins
    them in tier-1; the smoke additionally checks the detail record and
    that the throughput rows landed."""
    metrics, proc = _run_bench("--serve")
    assert "serving_agg_tok_s" in metrics, proc.stdout + proc.stderr
    assert "serving_throughput_speedup" in metrics
    assert "serving_prefix_speedup" in metrics
    assert metrics["serving_agg_tok_s"]["value"] > 0
    detail = None
    for line in proc.stderr.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                d = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(d, dict) and "serve" in d:
                detail = d["serve"]
    assert detail is not None, proc.stderr
    assert detail["outputs_match"] is True
    assert detail["recompiles_constant"] is True
    assert detail["decode_traces"] == 1
    # shared-prefix row: hits actually happened and parity held
    assert detail["prefix_outputs_match"] is True
    assert detail["prefix_hit_tokens"] > 0
    # preemption-pressure row: the machinery fired and stayed bit-exact
    assert detail["preempt_outputs_match"] is True
    assert detail["preemptions"] >= 1
    assert detail["oom_truncated"] == 0
    # long-context row (ISSUE 10): the Pallas flash-decoding paged-
    # attention kernel (interpret mode on CPU — the REAL kernel path)
    # must emit token streams bit-equal to the gather fallback at every
    # context length with one decode executable per engine; the parity/
    # no-recompile asserts also live in-section
    assert detail["longctx_outputs_match"] is True
    assert detail["longctx_recompiles_constant"] is True
    assert any(k.startswith("longctx_kernel_tok_s") for k in detail)
    # KV capacity row (ISSUE 10 acceptance): at one fixed byte budget the
    # int8 pool admits >= 2x the fp pool's concurrent sequences, serves
    # the trace with exact length/EOS parity, and its pool actually fits
    # the budget
    assert detail["kv_capacity_ratio"] >= 2.0
    assert detail["kv_int8_peak_live"] >= 2 * detail["kv_fp_peak_live"]
    assert detail["kv_length_parity"] is True
    # True on the deterministic CPU trace (a fully-agreeing request
    # exists); None would mean the exactness check went vacuous
    assert detail["kv_eos_parity"] is not False
    assert detail["kv_token_agreement"] >= 0.6
    assert detail["kv_int8_pool_bytes"] <= detail["kv_budget_bytes"]
    # tensor-parallel row (ISSUE 12): at one fixed PER-DEVICE byte budget
    # the TP=2 engine (pool sharded on its kv-heads axis over the tp
    # mesh) must hold >= 2x the TP=1 engine's concurrent sequences,
    # serve the trace bit-identically (greedy + seeded sampling), compile
    # decode once per mesh shape, leak nothing, and its per-device pool
    # bytes must actually fit the budget. The parity/compile-once asserts
    # also live in-section; the smoke pins the detail record and the
    # serving_tp_capacity_ratio metric. bench provisions the 8-way host
    # platform itself (XLA_FLAGS before jax init), so tp_supported must
    # be True here.
    assert detail["tp_supported"] is True
    assert detail["tp_outputs_match"] is True
    assert detail["tp_capacity_ratio"] >= 2.0
    assert detail["tp2_concurrent"] >= 2 * detail["tp1_concurrent"]
    # measured, not just arithmetic: the live peak actually doubled
    assert detail["tp2_peak_live"] >= 2 * detail["tp1_peak_live"]
    assert detail["tp_decode_traces"] == 1
    assert detail["tp_leaked_blocks"] == 0
    assert detail["tp2_shard_bytes"] <= detail["tp_per_device_budget_bytes"]
    assert detail["tp_tok_s"] > 0
    assert "serving_tp_capacity_ratio" in metrics
    # spec-decode row (ISSUE 11): n-gram drafting + multi-query verify
    # across the acceptance sweep — bit-parity on BOTH traces, real
    # acceptance on the high trace, one verify executable, zero leaked
    # blocks after rollback, and the low-acceptance fall-through bound
    # are asserted in-section; the smoke pins the detail record
    assert detail["spec_outputs_match"] is True
    assert detail["spec_accepted"] > 0
    assert detail["spec_traces"] == 1
    assert detail["spec_leaked_blocks"] == 0
    assert detail["spec_low_accept_ratio"] >= 0.9
    assert "serving_spec_speedup" in metrics
    # overload row (ISSUE 6): 2x-capacity arrivals through FIFO vs EDF +
    # TTFT-SLO shedding — load was genuinely shed and every NON-shed
    # output stayed bit-identical to the dense oracle (timed-out partials
    # prefix-match). The EDF-beats-FIFO p99 comparison is asserted inside
    # the bench section itself (a regression fails this entry point via
    # the bench's nonzero exit).
    assert detail["overload_outputs_match"] is True
    assert detail["overload_shed"] > 0
    assert detail["overload_served"] > 0
    assert detail["overload_edf_decode_traces"] == 1
    # front-line row (ISSUE 7): a mini trace through the asyncio server
    # (in-process transport — port-free) with an engine crash injected
    # mid-trace, then a graceful drain. The bit-parity / restart /
    # zero-leak / scale-up proofs are asserted inside the section; the
    # smoke pins the detail record so the row can't silently vanish.
    assert detail["frontline_outputs_match"] is True
    assert detail["frontline_restarts"] >= 1
    assert detail["frontline_resubmitted"] >= 1
    assert detail["frontline_leaked_blocks"] == 0
    assert detail["frontline_tok_s"] > 0
    assert detail["autoscale_action"] == "scale_up"
    # fleet row (ISSUE 9): replica_kill mid-trace through the 2-replica
    # router — failover bit-parity, zero router-failed requests, zero
    # leaked blocks on EVERY replica, a rolling restart that rebuilds the
    # whole fleet under live traffic, and no recompile anywhere (shared
    # EnginePrograms). The asserts also live in-section; the smoke pins
    # the detail record so the row can't silently vanish.
    assert detail["router_outputs_match"] is True
    assert detail["router_failovers"] >= 1
    assert detail["router_failed"] == 0
    assert detail["router_leaked_blocks"] == 0
    assert detail["router_roll_outputs_match"] is True
    assert detail["router_roll_restarts"] >= detail["router_replicas"]
    assert detail["router_recompiles_constant"] is True
    assert detail["router_tok_s"] > 0
    # KV tiering row (ISSUE 16): device-pool churn with the host offload
    # tier on vs off — re-visit parity, real swap traffic, verified (zero
    # corrupt-drop) restores, zero recompute, and strictly more prefix
    # hits than the tier-off run whose chains died with the device pool.
    # The asserts also live in-section; the smoke pins the record + the
    # emitted metric.
    assert detail["tier_outputs_match"] is True
    assert detail["tier_swap_outs"] > 0
    assert detail["tier_swap_ins"] > 0
    assert detail["tier_hits"] > 0
    assert detail["tier_corrupt_drops"] == 0
    assert detail["tier_recomputed_tokens"] == 0
    assert detail["tier_prefix_hit_tokens"] > \
        detail["tier_off_prefix_hit_tokens"]
    assert detail["tier_hit_ttft_ratio"] > 0
    assert "serving_tier_hit_ttft_ratio" in metrics
    # migration row (ISSUE 16): scale-in drain with live KV migration —
    # every in-flight request moved (block chains + resolved state) to
    # the survivor and finished bit-identically with zero recompute,
    # zero failures and zero leaked blocks anywhere in the fleet
    assert detail["migration_outputs_match"] is True
    assert detail["migrations"] >= 1
    assert detail["migration_failed"] == 0
    assert detail["migration_recomputed_tokens"] == 0
    assert detail["migration_leaked_blocks"] == 0
    assert detail["migration_recompute_saved"] > 0
    assert "serving_migration_recompute_saved" in metrics
    # fleet-cache row (ISSUE 17): prefix families re-visited from the
    # NON-holder replica — the fleet directory pulls the chain's blocks
    # cross-replica (CRC-checked at both ends) where island caches
    # re-prefill. Parity / pulls / zero fallbacks / zero leaks are
    # asserted in-section; the smoke pins the record + the metric.
    assert detail["fleet_outputs_match"] is True
    assert detail["fleet_cache_pulls"] >= 1
    assert detail["fleet_pulled_blocks"] >= 3
    assert detail["fleet_pull_fallbacks"] == 0
    assert detail["fleet_prefix_hit_tokens"] > \
        detail["fleet_island_hit_tokens"]
    assert detail["fleet_leaked_blocks"] == 0
    assert detail["fleet_hit_ttft_ratio"] > 0
    assert "serving_fleet_cache_hit_ttft_ratio" in metrics
    # disaggregation row (ISSUE 17): long prompts prefill on a dedicated
    # replica and hand their finished chain to a decode replica via the
    # adopt path — parity, handoffs >= 1, recomputed_tokens == 0, zero
    # failed/leaks asserted in-section; the smoke pins the record + the
    # metric.
    assert detail["disagg_outputs_match"] is True
    assert detail["disagg_prefill_routed"] >= 1
    assert detail["disagg_prefill_handoffs"] >= 1
    assert detail["disagg_recomputed_tokens"] == 0
    assert detail["disagg_failed"] == 0
    assert detail["disagg_leaked_blocks"] == 0
    assert detail["disagg_tpot_ratio"] > 0
    assert "serving_disagg_tpot_ratio" in metrics
    # durability row (ISSUE 18): journal overhead < 5% on the mixed
    # trace, then kill -9 mid-flight + timed cold-restart recovery —
    # bit parity across the kill, ZERO lost requests and ZERO
    # re-delivered tokens are asserted in-section; the smoke pins the
    # detail record + the serving_recovery_ms metric so the row (and
    # its exactly-once proof) cannot silently vanish.
    assert detail["durable_outputs_match"] is True
    assert detail["durable_lost_requests"] == 0
    assert detail["durable_duplicated_tokens"] == 0
    assert detail["durable_journal_overhead_pct"] < 5.0
    assert detail["durable_recovery_ms"] > 0
    assert detail["durable_resubmitted"] >= 1
    assert detail["durable_recovered_records"] >= 1
    assert detail["durable_wal_bytes"] > 0
    assert detail["durable_leaked_blocks"] == 0
    assert "serving_recovery_ms" in metrics
    # multi-adapter LoRA row (ISSUE 19): 8 adapters served round-robin
    # from ONE paged pool vs the base-only engine — zero-adapter traffic
    # bit-identical, the mix adds zero decode executables, overhead
    # < 10%, zero leaked blocks; the smoke pins the detail record + both
    # metrics so the row cannot silently vanish.
    assert detail["lora_outputs_match"] is True
    assert detail["lora_adapter_overhead_pct"] < 10.0
    assert detail["lora_adapters"] == 8
    assert detail["lora_adapter_loads"] >= 8
    assert detail["lora_leaked_blocks"] == 0
    assert "serving_lora_adapter_overhead_pct" in metrics
    assert "serving_lora_adapters_per_replica" in metrics
    # mixed-batching row (ISSUE 20): chunked prefill fused into the
    # decode dispatch — mixed streams bit-equal to the two-phase AND
    # dense oracles, chat TPOT p99 under long-prompt admission strictly
    # better than two-phase, fewer dispatches per step, ONE mixed
    # executable across role churn, zero leaked blocks; the asserts also
    # live in-section, the smoke pins the record + both metrics so the
    # row cannot silently vanish.
    assert detail["mixed_outputs_match"] is True
    assert detail["mixed_tpot_p99_ratio"] > 1.0
    assert detail["mixed_dispatches_per_step"] < \
        detail["unmixed_dispatches_per_step"]
    assert detail["mixed_traces"] == 1
    assert detail["mixed_recompiles_constant"] is True
    assert detail["mixed_leaked_blocks"] == 0
    assert "serving_mixed_tpot_p99_ratio" in metrics
    assert "serving_mixed_dispatches_per_step" in metrics


def test_bench_health_entry_point():
    """The run-health section (ISSUE 3): sentinel overhead row on the
    tuned llama path plus the in-bench containment proof (a NaN-poisoned
    step must be flagged bad by the fused detector)."""
    metrics, proc = _run_bench("--health", "--steps", "1")
    assert "health_sentinel_overhead_pct" in metrics, \
        proc.stdout + proc.stderr
    detail = None
    for line in proc.stderr.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                d = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(d, dict) and "health" in d:
                detail = d["health"]
    assert detail is not None, proc.stderr
    assert detail["nan_step_flagged"] is True
    assert detail["nan_step_contained"] is True
