"""BERT-family model tests (fine-tune + pretrain heads, masking, jit)."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.bert import (BertConfig, BertForPretraining,
                                    BertForSequenceClassification, BertModel)


def tiny_cfg():
    return BertConfig(vocab_size=100, hidden_size=32, num_hidden_layers=2,
                      num_attention_heads=4, intermediate_size=64,
                      max_position_embeddings=64, type_vocab_size=2)


def _ids(B=2, S=12, V=100, seed=0):
    rng = np.random.default_rng(seed)
    return paddle.to_tensor(rng.integers(0, V, (B, S)).astype("int64"))


class TestBertModel:
    def test_forward_shapes(self):
        m = BertModel(tiny_cfg())
        m.eval()
        seq, pooled = m(_ids())
        assert list(seq.shape) == [2, 12, 32]
        assert list(pooled.shape) == [2, 32]

    def test_attention_mask_blocks_padding(self):
        m = BertModel(tiny_cfg())
        m.eval()
        ids = _ids()
        mask = np.ones((2, 12), np.int64)
        mask[:, 8:] = 0
        # changing PADDED positions must not change unpadded outputs
        ids2_np = ids.numpy().copy()
        ids2_np[:, 8:] = 5
        seq1, _ = m(ids, attention_mask=paddle.to_tensor(mask))
        seq2, _ = m(paddle.to_tensor(ids2_np),
                    attention_mask=paddle.to_tensor(mask))
        np.testing.assert_allclose(seq1.numpy()[:, :8], seq2.numpy()[:, :8],
                                   atol=1e-5)

    def test_finetune_trains(self):
        model = BertForSequenceClassification(tiny_cfg(), num_classes=3)
        from paddle_tpu.optimizer import AdamW
        opt = AdamW(learning_rate=1e-3, parameters=model.parameters())
        ids = _ids()
        labels = paddle.to_tensor(np.asarray([0, 2]))
        losses = []
        for _ in range(5):
            loss, logits = model(ids, labels=labels)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < losses[0]
        assert list(logits.shape) == [2, 3]

    def test_pretraining_heads(self):
        model = BertForPretraining(tiny_cfg())
        ids = _ids()
        mlm_labels = ids.numpy().copy()
        mlm_labels[:, ::2] = -100  # only odd positions contribute
        loss = model(ids, masked_lm_labels=paddle.to_tensor(mlm_labels),
                     next_sentence_labels=paddle.to_tensor(
                         np.asarray([0, 1])))
        assert np.isfinite(float(loss))
        loss.backward()
        grads = [p.grad for p in model.parameters() if p.grad is not None]
        assert grads

    def test_to_static_parity(self):
        from paddle_tpu.jit import to_static
        model = BertForSequenceClassification(tiny_cfg(), num_classes=2)
        model.eval()
        ids = _ids()
        eager = model(ids).numpy()
        fn = to_static(lambda x: model(x))
        fn(ids)  # warmup (eager)
        compiled = fn(ids).numpy()
        np.testing.assert_allclose(eager, compiled, atol=1e-5)
