"""Chaos suite: drive the checkpoint/launch/elastic stack through injected
faults (paddle_tpu.testing.chaos) and assert the job converges to the same
loss as an unfaulted run — robustness EXERCISED, not just written.

Fast tier (plain ``chaos`` marker): single-process truncate/bit-flip/
writer-fault/syscall-shim recovery, runs in tier-1. Launcher-driven tests
(rank kill, heartbeat stall, SIGTERM preemption) are additionally ``slow``.
"""

import os
import signal
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed.checkpoint import (AsyncCheckpointer,
                                               CheckpointCorruptionError,
                                               load_state_dict,
                                               prune_uncommitted,
                                               save_state_dict)
from paddle_tpu.distributed.checkpoint import manifest
from paddle_tpu.distributed.launch.main import (PREEMPT_RC, _parse,
                                                launch_procs)
from paddle_tpu.testing import chaos

pytestmark = pytest.mark.chaos


def _state(val: float, n: int = 4):
    return {"w": paddle.to_tensor(np.full((n,), val, np.float32))}


def _series(root, steps=3, keep=3):
    ck = AsyncCheckpointer(str(root), keep_last_k=keep)
    for s in range(steps):
        ck.save(_state(float(s)), s)
    ck.wait()
    return ck


def _newest_shard(root):
    step, path = manifest.latest_committed(str(root))
    return step, os.path.join(path, "data_0.pkl")


class TestFastChaos:
    """Tier-1 smoke chaos: single-process fault -> detect -> recover."""

    def test_truncated_shard_falls_back_to_last_good(self, tmp_path):
        ck = _series(tmp_path / "ckpt")
        step, shard = _newest_shard(tmp_path / "ckpt")
        chaos.truncate_file(shard, frac=0.4)
        dst = _state(-1.0)
        assert ck.restore(dst) == step - 1     # walked back to last-good
        np.testing.assert_array_equal(dst["w"].numpy(),
                                      np.full((4,), float(step - 1)))

    def test_bit_flipped_shard_detected_and_falls_back(self, tmp_path):
        ck = _series(tmp_path / "ckpt")
        step, shard = _newest_shard(tmp_path / "ckpt")
        chaos.flip_bits(shard, offset=os.path.getsize(shard) // 2)
        dst = _state(-1.0)
        assert ck.restore(dst) == step - 1
        np.testing.assert_array_equal(dst["w"].numpy(),
                                      np.full((4,), float(step - 1)))

    def test_corrupt_committed_checkpoint_raises_not_garbage(self, tmp_path):
        """Direct load of a corrupted COMMITTED dir raises — never silently
        unpickles garbage bytes into tensors."""
        save_state_dict(_state(7.0), str(tmp_path / "ck"))
        chaos.flip_bits(str(tmp_path / "ck" / "data_0.pkl"))
        with pytest.raises(CheckpointCorruptionError, match="SHA-256|bytes"):
            load_state_dict(_state(0.0), str(tmp_path / "ck"))

    def test_uncommitted_newest_ignored_by_restore(self, tmp_path):
        """A save that never dropped its COMMITTED marker (kill mid-save)
        is invisible to restore and removed by the launcher's prune."""
        ck = _series(tmp_path / "ckpt", steps=3)
        _, path = manifest.latest_committed(str(tmp_path / "ckpt"))
        os.remove(os.path.join(path, manifest.COMMITTED_MARKER))
        dst = _state(-1.0)
        assert ck.restore(dst) == 1            # newest (2) is now torn
        removed = prune_uncommitted(str(tmp_path / "ckpt"))
        assert removed == [path]
        assert ck.restore(_state(-1.0)) == 1   # still last-good after prune

    def test_async_writer_fault_surfaces_and_next_save_recovers(self,
                                                                tmp_path):
        ck = AsyncCheckpointer(str(tmp_path / "ckpt"))
        ck.save(_state(0.0), 0)
        ck.wait()
        with chaos.async_writer_fault(RuntimeError("chaos boom")):
            ck.save(_state(1.0), 1)
            with pytest.raises(RuntimeError, match="chaos boom"):
                ck.wait()                      # the error is never silent
        # the failed step never committed; the series is still on step 0
        assert ck.latest_step() == 0
        ck.save(_state(2.0), 2)                # writer recovered
        ck.wait()
        dst = _state(-1.0)
        assert ck.restore(dst) == 2
        np.testing.assert_array_equal(dst["w"].numpy(), np.full((4,), 2.0))

    def test_async_writer_fault_surfaces_on_next_submit(self, tmp_path):
        """Fire-and-forget loops that never call wait() still see the
        error: the next submit re-raises it."""
        from paddle_tpu.framework.async_writer import default_writer
        default_writer().wait_all()            # drain unrelated jobs
        with chaos.async_writer_fault(RuntimeError("lost write")):
            j = save_state_dict(_state(1.0), str(tmp_path / "ck"),
                                async_save=True)
            while not j.done:
                time.sleep(0.01)
        with pytest.raises(RuntimeError, match="lost write"):
            save_state_dict(_state(2.0), str(tmp_path / "ck"),
                            async_save=True)

    def test_fail_nth_rename_keeps_series_on_last_good(self, tmp_path):
        """Syscall shim: an os.replace dying mid-protocol leaves the new
        dir uncommitted and the series resumable from the previous step."""
        ck = _series(tmp_path / "ckpt", steps=2)
        with chaos.fail_nth(os, "replace", n=2):
            with pytest.raises(OSError, match="chaos"):
                save_state_dict(_state(9.0),
                                str(tmp_path / "ckpt" /
                                    manifest.step_dir_name(2)))
        assert ck.latest_step() == 1           # torn dir carries no marker
        dst = _state(-1.0)
        assert ck.restore(dst) == 1

    def test_tier1_save_atomic_under_rename_failure(self, tmp_path):
        """paddle.save: a crash mid-save never clobbers the previous
        checkpoint (the load-bearing satellite fix)."""
        p = str(tmp_path / "m.pdparams")
        paddle.save(_state(1.0), p)
        with chaos.fail_nth(os, "replace", n=1):
            with pytest.raises(OSError, match="chaos"):
                paddle.save(_state(2.0), p)
        got = paddle.load(p)                   # old file intact + verified
        np.testing.assert_array_equal(got["w"].numpy(), np.full((4,), 1.0))

    def test_tier1_truncation_detected(self, tmp_path):
        p = str(tmp_path / "m.pdparams")
        paddle.save(_state(3.0), p)
        chaos.truncate_file(p, frac=0.7)
        with pytest.raises(CheckpointCorruptionError):
            paddle.load(p)

    def test_tier1_bit_flip_detected(self, tmp_path):
        p = str(tmp_path / "m.pdparams")
        paddle.save(_state(3.0), p)
        chaos.flip_bits(p, offset=os.path.getsize(p) // 3)
        with pytest.raises(CheckpointCorruptionError):
            paddle.load(p)

    def test_tier1_async_save_overlaps_and_lands(self, tmp_path):
        p = str(tmp_path / "m.pdparams")
        from paddle_tpu.framework import io as fio
        fio.async_save(_state(5.0), p)
        fio.wait_save()
        assert not fio.is_saving()
        np.testing.assert_array_equal(paddle.load(p)["w"].numpy(),
                                      np.full((4,), 5.0))


# ---------------------------------------------------------------------------
# launcher-driven chaos: inject the fault into a real elastic job and
# require convergence parity with the unfaulted run
# ---------------------------------------------------------------------------

_TRAIN = """
    import os, sys, time
    sys.path.insert(0, {repo!r})
    os.environ["JAX_PLATFORMS"] = "cpu"
    import numpy as np
    rank = int(os.environ["PADDLE_TRAINER_ID"])
    rnd = int(os.environ["PADDLE_RESTART_ROUND"])
    fault = os.environ.get("CHAOS_FAULT", "")
    import paddle_tpu as paddle
    from paddle_tpu.distributed.checkpoint import AsyncCheckpointer
    from paddle_tpu.distributed import elastic
    from paddle_tpu.testing import chaos
    elastic.start_heartbeat(interval=0.25)
    out = {out!r}
    ck = AsyncCheckpointer(keep_last_k=3)   # root: PADDLE_CHECKPOINT_DIR
    state = {{"w": paddle.to_tensor(np.zeros((3, 1), np.float32)),
              "step": paddle.to_tensor(np.zeros((), np.float32))}}
    restored = ck.restore(state)
    start = int(float(state["step"])) if restored is not None else 0
    if restored is not None and rank == 0:
        open(os.path.join(out, "resumed.%d" % rnd), "w").write(str(start))
    rng = np.random.RandomState(0)
    X = paddle.to_tensor(rng.randn(32, 3).astype("float32"))
    y = X.matmul(paddle.to_tensor(
        np.array([[1.5], [-2.0], [0.5]], np.float32)))
    wt = paddle.Parameter(state["w"].numpy())
    holder = {{"w": wt.numpy(), "step": start}}
    if fault.startswith("preempt"):
        elastic.install_preemption_handler(save_fn=lambda: ck.save_sync(
            {{"w": paddle.to_tensor(holder["w"]),
              "step": paddle.to_tensor(np.float32(holder["step"]))}},
            holder["step"]))
    nsteps = int(os.environ.get("CHAOS_STEPS", "8"))
    open(os.path.join(out, "started.%d.%d" % (rnd, rank)), "w").write("1")
    for step in range(start, nsteps):
        loss = ((X.matmul(wt) - y) ** 2).mean()
        loss.backward()
        wt.set_value(wt.numpy() - 0.1 * wt.grad.numpy())
        wt.clear_grad()
        holder["w"], holder["step"] = wt.numpy(), step + 1
        if fault == "preempt_worker" and rnd == 0 and step == 3:
            import signal as _sig
            os.kill(os.getpid(), _sig.SIGTERM)   # infra preempts the WORKER
            time.sleep(30)   # handler exits the process; never reached
        if rank == 0 and not fault.startswith("preempt"):
            ck.save({{"w": paddle.to_tensor(wt.numpy()),
                      "step": paddle.to_tensor(np.float32(step + 1))}},
                    step + 1)
        if rnd == 0 and step >= 3:
            if fault == "kill" and rank == int(os.environ.get(
                    "CHAOS_KILL_RANK", "1")):
                # die mid-step — but only once a commit exists, so the
                # restart provably resumes from it (startup skew between
                # ranks would otherwise race the first commit)
                from paddle_tpu.distributed.checkpoint import manifest
                while manifest.latest_committed(
                        os.environ["PADDLE_CHECKPOINT_DIR"]) is None:
                    time.sleep(0.05)
                chaos.kill_self()               # SIGKILL mid-step
            if fault == "stall" and rank == 0 and step == 3:
                _stall = chaos.stall_heartbeat()
                _stall.__enter__()              # freeze liveness stamping
                time.sleep(60)                  # alive-but-hung forever
        if fault == "preempt":
            time.sleep(0.25)   # slow steps: SIGTERM lands mid-training
        else:
            time.sleep(0.05)
    ck.wait()
    final = float(((X.matmul(wt) - y) ** 2).mean())
    open(os.path.join(out, "final.%d" % rank), "w").write(str(final))
"""


def _write_script(tmp_path, repo="/root/repo"):
    p = tmp_path / "train.py"
    p.write_text(textwrap.dedent(_TRAIN.format(repo=repo,
                                               out=str(tmp_path))))
    return str(p)


def _run_launcher(tmp_path, script, fault, *extra, env_extra=None):
    env_bak = dict(os.environ)
    os.environ.pop("PYTHONPATH", None)
    os.environ["CHAOS_FAULT"] = fault
    os.environ["PADDLE_HEARTBEAT_INTERVAL"] = "0.25"
    os.environ.update(env_extra or {})
    try:
        args = _parse([*extra, "--log_dir", str(tmp_path / f"log_{fault}"),
                       "--ckpt_dir", str(tmp_path / f"ckpt_{fault}"),
                       script])
        return launch_procs(args)
    finally:
        os.environ.clear()
        os.environ.update(env_bak)


def _final_loss(tmp_path, rank=0):
    return float((tmp_path / f"final.{rank}").read_text())


@pytest.mark.slow
class TestLauncherChaos:
    def test_rank_kill_mid_step_resumes_and_converges(self, tmp_path):
        """Rank 1 is SIGKILLed mid-step; the launcher restarts the round,
        the job resumes from the last committed checkpoint and reaches the
        unfaulted run's loss."""
        ref_dir = tmp_path / "ref"
        ref_dir.mkdir()
        rc = _run_launcher(ref_dir, _write_script(ref_dir), "",
                           "--nproc_per_node", "2")
        assert rc == 0
        ref = _final_loss(ref_dir)

        rc = _run_launcher(tmp_path, _write_script(tmp_path), "kill",
                           "--nproc_per_node", "2", "--max_restart", "2")
        assert rc == 0, (tmp_path / "log_kill" / "workerlog.1").read_text()
        assert (tmp_path / "resumed.1").exists()   # round 1 resumed
        assert int((tmp_path / "resumed.1").read_text()) >= 1
        np.testing.assert_allclose(_final_loss(tmp_path), ref,
                                   rtol=1e-5, atol=1e-6)

    def test_stalled_heartbeat_detected_restarts_and_converges(self,
                                                               tmp_path):
        """chaos.stall_heartbeat freezes liveness stamping mid-training:
        the watchdog declares the rank hung, restarts, and the resumed run
        converges to the unfaulted loss."""
        ref_dir = tmp_path / "ref"
        ref_dir.mkdir()
        rc = _run_launcher(ref_dir, _write_script(ref_dir), "")
        assert rc == 0
        ref = _final_loss(ref_dir)

        rc = _run_launcher(tmp_path, _write_script(tmp_path), "stall",
                           "--max_restart", "2", "--elastic_timeout", "2.5")
        assert rc == 0, (tmp_path / "log_stall" / "workerlog.0").read_text()
        assert (tmp_path / "resumed.1").exists()
        np.testing.assert_allclose(_final_loss(tmp_path), ref,
                                   rtol=1e-5, atol=1e-6)

    def test_worker_sigterm_emergency_exit_is_preemption_not_crash(
            self, tmp_path):
        """The infrastructure SIGTERMs the WORKERS directly (bypassing the
        launcher): the worker commits an emergency checkpoint and exits
        EMERGENCY_EXIT_RC; the launcher must treat that as a preemption
        (PREEMPT_RC, no restart round burned), not a crash loop."""
        rc = _run_launcher(tmp_path, _write_script(tmp_path),
                           "preempt_worker", "--max_restart", "2")
        assert rc == PREEMPT_RC, rc
        got = manifest.latest_committed(str(tmp_path / "ckpt_preempt_worker"))
        assert got is not None and got[0] >= 1   # emergency commit exists
        # no restart round ran (resumed.* is written on restore in round 1+)
        assert not list(tmp_path.glob("resumed.*"))

    def test_sigterm_preemption_emergency_save_then_resume_converges(
            self, tmp_path):
        """SIGTERM to the LAUNCHER: workers get the bounded grace window,
        the preemption handler commits an emergency checkpoint, the job
        exits PREEMPT_RC; the rescheduled job resumes from that commit and
        converges to the unfaulted loss."""
        ref_dir = tmp_path / "ref"
        ref_dir.mkdir()
        rc = _run_launcher(ref_dir, _write_script(ref_dir), "",
                           env_extra={"CHAOS_STEPS": "40"})
        assert rc == 0
        ref = _final_loss(ref_dir)

        script = _write_script(tmp_path)
        env = dict(os.environ)
        env.pop("PYTHONPATH", None)
        env.update({"PYTHONPATH": "/root/repo", "CHAOS_FAULT": "preempt",
                    "CHAOS_STEPS": "40"})
        ckpt = str(tmp_path / "ckpt_preempt")
        proc = subprocess.Popen(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--log_dir", str(tmp_path / "log_p0"), "--ckpt_dir", ckpt,
             "--preempt_grace", "10", script],
            cwd="/root/repo", env=env)
        # preempt only once training has verifiably begun (the handler is
        # installed before the loop): a fixed sleep races slow imports
        deadline = time.time() + 90
        while not (tmp_path / "started.0.0").exists():
            assert time.time() < deadline, "worker never started training"
            assert proc.poll() is None, "job died before being preempted"
            time.sleep(0.2)
        time.sleep(2.0)                  # a few 0.25s steps into the run
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=60)
        assert rc == PREEMPT_RC, rc
        got = manifest.latest_committed(ckpt)
        assert got is not None, "emergency save never committed"
        step = got[0]
        assert 1 <= step < 40            # mid-training commit

        # "rescheduled" job: resume to completion, loss parity
        rc = _run_launcher(tmp_path, script, "preempt",
                           env_extra={"CHAOS_FAULT": "preempt",
                                      "CHAOS_STEPS": "40"})
        # _run_launcher uses ckpt_preempt via the fault name — same root
        assert rc == 0, (tmp_path / "log_preempt" /
                         "workerlog.0").read_text()
        assert (tmp_path / "resumed.0").exists()
        assert int((tmp_path / "resumed.0").read_text()) == step
        np.testing.assert_allclose(_final_loss(tmp_path), ref,
                                   rtol=1e-5, atol=1e-6)
